"""PageRank parity tests (SURVEY.md §4): networkx oracle for the textbook
semantics, the pure-python RDD-semantics oracle for Spark parity, both at
the L1 ≤ 1e-6 bar BASELINE.json:5 sets (float64 on CPU backend)."""

import numpy as np
import networkx as nx
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import PageRankConfig, pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.io import from_edges, synthetic_powerlaw

from tests.spark_oracle import spark_pagerank

EDGES_SMALL = [(0, 1), (0, 2), (1, 2), (2, 0), (2, 4), (5, 5), (0, 4), (3, 2)]


def _graph(edges):
    a = np.array(edges)
    return from_edges(a[:, 0], a[:, 1])


def _nx_ranks(edges, n, **kw):
    G = nx.DiGraph()
    G.add_nodes_from(range(n))
    G.add_edges_from(edges)
    d = nx.pagerank(G, alpha=0.85, max_iter=500, tol=1e-14, **kw)
    return np.array([d[i] for i in range(n)])


@pytest.mark.parametrize("edges", [EDGES_SMALL])
def test_parity_networkx_redistribute(edges):
    g = _graph(edges)
    res = pagerank(
        g, iterations=200, dangling="redistribute", init="uniform", dtype="float64"
    )
    expect = _nx_ranks([(int(a), int(b)) for a, b in zip(g.src, g.dst)], g.n_nodes)
    # graph node order == compacted ids here (ids are 0..5 contiguous)
    assert np.abs(res.ranks - expect).sum() <= 1e-6
    assert abs(res.ranks.sum() - 1.0) < 1e-9


def test_parity_networkx_synthetic():
    g = synthetic_powerlaw(300, 1500, seed=3)
    res = pagerank(
        g, iterations=300, dangling="redistribute", init="uniform", dtype="float64"
    )
    edges = list(zip(g.src.tolist(), g.dst.tolist()))
    expect = _nx_ranks(edges, g.n_nodes)
    assert np.abs(res.ranks - expect).sum() <= 1e-6


def test_spark_exact_matches_rdd_oracle():
    g = _graph(EDGES_SMALL)
    res = pagerank(g, PageRankConfig(iterations=7, spark_exact=True, dtype="float64"))
    oracle = spark_pagerank(EDGES_SMALL, 7)
    for i in range(g.n_nodes):
        nid = int(g.node_ids[i])
        if nid in oracle:
            assert res.ranks[i] == pytest.approx(oracle[nid], abs=1e-9), nid
        else:
            assert res.ranks[i] == 0.0, nid


def test_spark_exact_matches_rdd_oracle_synthetic():
    g = synthetic_powerlaw(200, 600, seed=5)
    edges = [(int(g.node_ids[a]), int(g.node_ids[b])) for a, b in zip(g.src, g.dst)]
    res = pagerank(g, PageRankConfig(iterations=10, spark_exact=True, dtype="float64"))
    oracle = spark_pagerank(edges, 10)
    got = {int(g.node_ids[i]): res.ranks[i] for i in range(g.n_nodes) if res.ranks[i] != 0.0}
    assert set(got) == set(oracle)
    l1 = sum(abs(got[k] - oracle[k]) for k in oracle)
    assert l1 <= 1e-6


def test_drop_mode_loses_mass():
    g = _graph(EDGES_SMALL)  # node 4 dangling
    res = pagerank(g, iterations=50, dangling="drop", init="uniform", dtype="float64")
    assert res.ranks.sum() < 1.0  # dangling mass vanished, by design


def test_personalized_matches_networkx():
    g = _graph(EDGES_SMALL)
    src_node = 0
    res = pagerank(
        g,
        iterations=300,
        dangling="redistribute",
        init="uniform",
        personalize=(src_node,),
        dtype="float64",
    )
    edges = [(int(a), int(b)) for a, b in zip(g.src, g.dst)]
    expect = _nx_ranks(
        edges, g.n_nodes, personalization={i: float(i == src_node) for i in range(g.n_nodes)}
    )
    assert np.abs(res.ranks - expect).sum() <= 1e-6


def test_tolerance_early_stop():
    g = _graph(EDGES_SMALL)
    res = pagerank(
        g, iterations=500, tol=1e-10, dangling="redistribute", init="uniform", dtype="float64"
    )
    assert res.iterations < 500
    assert res.l1_delta <= 1e-10


@pytest.mark.parametrize("impl", ["bcoo", "cumsum", "cumsum_mxu", "pallas"])
def test_spmv_impls_match_segment(impl):
    g = synthetic_powerlaw(100, 400, seed=7)
    r1 = pagerank(g, iterations=20, dangling="redistribute", init="uniform",
                  spmv_impl="segment", dtype="float64")
    r2 = pagerank(g, iterations=20, dangling="redistribute", init="uniform",
                  spmv_impl=impl, dtype="float64")
    assert np.abs(r1.ranks - r2.ranks).max() < 1e-12


@pytest.mark.parametrize("impl", ["cumsum", "cumsum_mxu"])
def test_cumsum_impl_f32_accuracy(impl):
    """The fast prefix-sum SpMVs must stay rank-accurate in float32 at a
    scale where their accumulated error could plausibly bite."""
    g = synthetic_powerlaw(20_000, 100_000, seed=9)
    exact = pagerank(g, iterations=20, dangling="redistribute", init="uniform",
                     spmv_impl="segment", dtype="float64")
    fast = pagerank(g, iterations=20, dangling="redistribute", init="uniform",
                    spmv_impl=impl, dtype="float32")
    assert np.abs(fast.ranks - exact.ranks).sum() < 1e-3


@pytest.mark.parametrize("n", [0, 1, 5, 512, 513, 128 * 9, 40_001])
def test_cumsum_blocked_matches_jnp(n):
    """The MXU-blocked prefix sum must agree with jnp.cumsum for every
    length class: empty, below the recursion base, exact multiples of the
    block, stragglers, and multi-level recursion."""
    import jax.numpy as jnp

    from page_rank_and_tfidf_using_apache_spark_tpu.ops.pagerank import cumsum_blocked

    rng = np.random.default_rng(n)
    x = jnp.asarray(rng.standard_normal(n).astype(np.float64))
    np.testing.assert_allclose(
        np.asarray(cumsum_blocked(x)), np.cumsum(np.asarray(x)),
        rtol=1e-12, atol=1e-12,
    )


def test_spark_default_config_shape():
    """Reference defaults: 20 iters, d=0.85, init ONE, drop (BASELINE.json:7)."""
    cfg = PageRankConfig()
    assert cfg.iterations == 20 and cfg.damping == 0.85
    g = _graph(EDGES_SMALL)
    res = pagerank(g, cfg)
    assert res.iterations == 20
    assert res.ranks.shape == (g.n_nodes,)


def test_personalize_duplicate_ids_mass():
    """Duplicate restart ids must accumulate, not overwrite: e sums to 1."""
    from page_rank_and_tfidf_using_apache_spark_tpu.ops.pagerank import restart_vector

    cfg = PageRankConfig(personalize=(3, 3, 5), dtype="float64")
    e = restart_vector(10, cfg)
    assert e.sum() == 1.0
    assert e[3] == 2 / 3 and e[5] == 1 / 3


def test_from_edges_large_noncompact_ids():
    """Dedup must be overflow-safe for big raw ids under compact_ids=False."""
    big = 2**30
    g = from_edges(np.array([big - 2, big - 2]), np.array([big - 1, big - 1]),
                   compact_ids=True)
    assert g.n_edges == 1  # duplicate removed


def test_zero_iterations():
    g = _graph(EDGES_SMALL)
    res = pagerank(g, iterations=0)
    np.testing.assert_allclose(res.ranks, 1.0)


def test_personalize_uses_original_node_ids():
    """SNAP inputs have id gaps; --personalize takes ORIGINAL ids and must
    hit exactly those nodes after compaction."""
    # ids 10, 20, 30, 40 — compacted to rows 0..3
    edges = [(10, 20), (20, 30), (30, 10), (40, 10)]
    g = _graph(edges)
    res = pagerank(g, iterations=200, tol=1e-12, dangling="redistribute",
                   init="uniform", personalize=(30,), dtype="float64")
    G = nx.DiGraph(edges)
    want = nx.pagerank(G, alpha=0.85, personalization={30: 1.0}, tol=1e-12,
                       max_iter=500)
    got = {int(g.node_ids[i]): res.ranks[i] for i in range(g.n_nodes)}
    for node, w in want.items():
        assert abs(got[node] - w) < 1e-9

    with pytest.raises(ValueError, match="not present"):
        pagerank(g, iterations=5, personalize=(15,))


@pytest.mark.parametrize("impl", ["cumsum", "pallas"])
def test_spark_exact_rejects_prefix_sum_impls(impl):
    with pytest.raises(ValueError, match="spark_exact requires"):
        PageRankConfig(spark_exact=True, dangling="drop", spmv_impl=impl)


def test_pallas_cumsum_multi_chunk_carry(monkeypatch):
    """The Pallas kernel's scalar carry must thread the prefix sum across
    grid steps; shrink the chunk so a modest graph spans several chunks."""
    import jax.numpy as jnp

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "_CHUNK", 1024)
    pk.cumsum_pallas.clear_cache()
    try:
        g = synthetic_powerlaw(800, 5000, seed=11)
        dg = ops.put_graph(g, "float64")
        w = jnp.asarray(np.random.default_rng(2).random(g.n_nodes))
        ref = ops.spmv_segment(dg, w, g.n_nodes)
        got = pk.spmv_pallas(dg.src, dg.indptr, w, n=g.n_nodes, interpret=True)
        assert int(np.ceil(g.n_edges / 1024)) > 3  # really multi-chunk
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-9)
    finally:
        pk.cumsum_pallas.clear_cache()


# TPU lowering pins (incl. the Mosaic pipeline for the Pallas kernel) live
# in tests/test_tpu_lowering.py.
