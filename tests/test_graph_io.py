"""Golden-file + unit tests for SNAP ingest (SURVEY.md A2/A3, §4)."""

import os

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.io import (
    from_edges,
    load_snap,
    parse_snap_text,
    save_ranks,
    synthetic_powerlaw,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "tiny.txt")


def test_parse_snap_fixture():
    g = load_snap(FIXTURE)
    # ids 0,1,2,4,5 → compacted to 0..4 (id 3 absent in input)
    assert g.n_nodes == 5
    assert list(g.node_ids) == [0, 1, 2, 4, 5]
    # duplicate edge 1→2 deduped; self-loop 5→5 kept
    assert g.n_edges == 7
    # destination-sorted invariant
    assert (np.diff(g.dst) >= 0).all()
    # out-degrees on original ids: 0→{1,2,4}, 1→{2}, 2→{0,4}, 4 dangling, 5→{5}
    assert list(g.out_degree) == [3, 1, 2, 0, 1]
    assert list(g.dangling_mask) == [False, False, False, True, False]


def test_parse_equivalence_text_vs_file():
    with open(FIXTURE, "rb") as f:
        g2 = parse_snap_text(f.read())
    g1 = load_snap(FIXTURE)
    np.testing.assert_array_equal(g1.src, g2.src)
    np.testing.assert_array_equal(g1.dst, g2.dst)


def test_dedup_and_self_loops():
    g = from_edges(np.array([1, 1, 2, 2]), np.array([2, 2, 2, 1]))
    assert g.n_edges == 3  # (1,2) deduped, (2,2) self-loop kept
    g2 = from_edges(np.array([1, 1, 2, 2]), np.array([2, 2, 2, 1]), drop_self_loops=True)
    assert g2.n_edges == 2


def test_empty_graph():
    g = parse_snap_text("# only comments\n")
    assert g.n_nodes == 0 and g.n_edges == 0


def test_odd_token_count_raises():
    with pytest.raises(ValueError, match="odd token count"):
        parse_snap_text("1 2 3\n")


def test_compact_ids_roundtrip():
    g = from_edges(np.array([100, 7]), np.array([7, 2000]))
    assert g.n_nodes == 3
    assert list(g.node_ids) == [7, 100, 2000]


def test_save_ranks(tmp_path):
    g = load_snap(FIXTURE)
    ranks = np.arange(g.n_nodes, dtype=np.float32)
    out = tmp_path / "ranks.txt"
    save_ranks(str(out), g, ranks, top_k=2)
    lines = out.read_text().splitlines()
    assert len(lines) == 2
    # highest rank first, mapped back to original node ids
    nid, r = lines[0].split("\t")
    assert int(nid) == g.node_ids[g.n_nodes - 1]


def test_synthetic_powerlaw_shape():
    g = synthetic_powerlaw(1000, 5000, seed=1)
    assert g.n_nodes <= 1000
    assert g.n_edges <= 5000  # dedup may shrink
    # power-law: max in-degree far above mean
    indeg = np.bincount(g.dst, minlength=g.n_nodes)
    assert indeg.max() > 10 * indeg.mean()
