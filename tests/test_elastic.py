"""Elastic mesh degradation tests (ISSUE 5): chaos-killed devices are
survived by shrink-and-resume.

The acceptance bar: with ``GRAFT_CHAOS="*:device_lost@dev:1"`` on a
2-device mesh, BOTH sharded runners complete via the mesh-shrink rung (no
``ResilienceExhausted``), match uninterrupted outputs to atol 1e-6 f32
with zero recomputed committed iterations/chunks, and the trace artifact
shows exactly one ``mesh.shrink`` span with devices 2->1.  (The conftest
backend simulates 8 CPU devices; a 2-device mesh over devices [0, 1] is
the same code path as ``--xla_force_host_platform_device_count=2``, which
``tools/chaos.sh``'s device_lost scenario exercises end to end.)
"""

from __future__ import annotations

import importlib.util
import json
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.io import synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
    run_pagerank_sharded,
    run_tfidf_sharded,
)
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import mesh as pmesh
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import (
    chaos,
    elastic,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.resilience.executor import (
    ResilienceExhausted,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    DEGRADE_LADDER,
    GRAFT_ENV_KNOBS,
    PageRankConfig,
    TfidfConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder

REPO = Path(__file__).resolve().parents[1]

GRAPH_KW = dict(dangling="redistribute", init="uniform", dtype="float32")


def _trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", REPO / "tools" / "trace_report.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_health():
    """Device health is process-global (a real dead chip stays dead); every
    test starts and ends with a clean slate."""
    elastic.reset_health()
    yield
    elastic.reset_health()


# ------------------------------------------------------------ chaos grammar


def test_parse_device_lost_plan():
    (inj,) = chaos.parse_plan("*:device_lost@dev:1")
    assert inj.kind == "device_lost" and inj.when == "dev"
    assert inj.param == 1.0
    assert inj.matches("any_site", 1) and inj.matches("any_site", 99)
    (inj2,) = chaos.parse_plan("pagerank_step:device_lost@dev:0")
    assert not inj2.matches("other_site", 1)


@pytest.mark.parametrize(
    "bad",
    ["a:device_lost@1", "a:device_lost@dev", "a:device_lost@dev:x",
     "a:device_lost@dev:1:2", "a:device_lost@%2:1"],
)
def test_parse_device_lost_rejects(bad):
    with pytest.raises(ValueError):
        chaos.parse_plan(bad)


def test_device_lost_fires_until_acknowledged():
    """The injection behaves like a real dead chip: every guarded call
    fails until the elastic runtime marks the device dead, then the
    survivors work again."""
    with chaos.inject("s:device_lost@dev:3"):
        for _ in range(2):
            with pytest.raises(chaos.DeviceLostError) as ei:
                chaos.on_call("s")
            assert ei.value.device == 3
        elastic.health().mark_lost(3)
        chaos.on_call("s")  # acknowledged: no further injection


# --------------------------------------------------- planner + health units


def test_largest_pow2_and_shrink_devices():
    assert [pmesh.largest_pow2(n) for n in (0, 1, 2, 3, 5, 8)] == [0, 1, 2, 2, 4, 8]

    class Dev:
        def __init__(self, i):
            self.id = i

    survivors = pmesh.shrink_devices([Dev(0), Dev(2), Dev(5)])
    assert [d.id for d in survivors] == [0, 2]
    assert pmesh.shrink_devices([]) == []


def test_device_health_registry():
    h = elastic.DeviceHealth()
    assert h.mark_lost(4) and not h.mark_lost(4)
    assert h.is_lost(4) and not h.is_lost(0)
    assert h.lost() == frozenset({4})
    h.reset()
    assert h.lost() == frozenset()


def test_plan_shrink_rungs():
    import jax

    devs = jax.devices()[:4]
    elastic.health().mark_lost(devs[3].id)
    plan = elastic.plan_shrink(devs)
    assert (plan.old_count, plan.new_count) == (4, 2)
    assert plan.rung == "mesh_shrink"
    assert all(not elastic.health().is_lost(d.id) for d in plan.devices)

    elastic.health().mark_lost(devs[1].id)
    plan2 = elastic.plan_shrink(list(plan.devices))
    assert (plan2.old_count, plan2.new_count) == (2, 1)
    assert plan2.rung == "single_device"


def test_plan_shrink_halves_on_unattributed_loss():
    """A persistent device-loss error that names no device still makes
    progress: the plan halves rather than rebuilding the same mesh."""
    import jax

    plan = elastic.plan_shrink(jax.devices()[:4])
    assert (plan.old_count, plan.new_count) == (4, 2)


def test_ladder_rungs_are_declared():
    """Every rung the elastic planner can take is a declared ladder name,
    and the elastic knob is a declared env knob."""
    assert {"mesh_shrink", "single_device", "cpu"} <= set(DEGRADE_LADDER)
    assert "GRAFT_ELASTIC" in GRAFT_ENV_KNOBS


# ------------------------------------------------------- executor fallbacks


def test_run_guarded_walks_fallback_rungs_in_order():
    calls = []
    pol = rx.RetryPolicy(max_retries=0, backoff_base_s=0.001)
    m = MetricsRecorder()

    def rung_a(exc):
        calls.append(("a", type(exc).__name__))
        raise RuntimeError("rung a cannot help")

    def rung_b(exc):
        calls.append(("b", type(exc).__name__))
        return "recovered"

    with chaos.inject("fb:lost@1+"):
        out = rx.run_guarded(
            lambda: 1, site="fb", policy=pol, metrics=m,
            fallbacks=[(None, rung_a), ("cpu", rung_b)],
        )
    assert out == "recovered"
    assert calls == [("a", "DeviceLostError"), ("b", "RuntimeError")]
    # only the NAMED rung publishes degraded (the unnamed one owns its own
    # emission, and here it declined)
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert len(degraded) == 1 and degraded[0]["ladder"] == "cpu"


def test_run_guarded_all_rungs_fail_exhausts():
    pol = rx.RetryPolicy(max_retries=0, backoff_base_s=0.001)

    def declines(exc):
        raise exc

    with chaos.inject("fb2:lost@1+"):
        with pytest.raises(ResilienceExhausted):
            rx.run_guarded(lambda: 1, site="fb2", policy=pol,
                           fallbacks=[(None, declines)])


# ----------------------------------------- end-to-end: sharded PageRank


def test_pagerank_sharded_survives_device_loss_2to1(tmp_path):
    """Acceptance: 2-device mesh, chaos kills logical device 1 -> the run
    completes via mesh shrink (no ResilienceExhausted), matches the
    uninterrupted ranks to atol 1e-6, recomputes zero committed
    iterations, and the trace shows exactly one mesh.shrink span 2->1."""
    g = synthetic_powerlaw(900, 3600, seed=21)
    cfg = PageRankConfig(iterations=9, checkpoint_every=3,
                         checkpoint_dir=str(tmp_path / "ck"), **GRAPH_KW)
    base = run_pagerank(g, PageRankConfig(iterations=9, **GRAPH_KW))

    m = MetricsRecorder()
    obs.start_run("elastic_pr", str(tmp_path / "tr"))
    try:
        with chaos.inject("*:device_lost@dev:1"):
            res = run_pagerank_sharded(g, cfg, n_devices=2, metrics=m)
    finally:
        obs.end_run()
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)
    assert res.iterations == 9

    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert len(degraded) == 1
    assert degraded[0]["ladder"] == "single_device"
    assert (degraded[0]["devices_old"], degraded[0]["devices_new"]) == (2, 1)
    # zero recomputed committed iterations: every segment commit advanced
    # the iteration counter; nothing was resumed or replayed
    iters = [r["iter"] for r in m.records if "iter" in r and "l1_delta" in r]
    assert iters == sorted(set(iters))
    assert not [r for r in m.records if r.get("event") == "resume"]

    trace = next((tmp_path / "tr").glob("elastic_pr.*.trace.jsonl"))
    rep = _trace_report().report(str(trace))
    assert len(rep["mesh_shrinks"]) == 1
    s = rep["mesh_shrinks"][0]
    assert (s["devices_old"], s["devices_new"]) == (2, 1)
    assert s["site"] == "pagerank_step"
    assert not rep["exhausted"]


def test_pagerank_sharded_shrinks_4to2_nodes_balanced(tmp_path):
    """A 4-device nodes_balanced mesh losing one device lands on the
    mesh_shrink rung at 2 devices — the partition planner re-balances its
    edge splits for the surviving count."""
    g = synthetic_powerlaw(800, 3200, seed=13)
    base = run_pagerank(g, PageRankConfig(iterations=8, **GRAPH_KW))
    m = MetricsRecorder()
    with chaos.inject("*:device_lost@dev:3"):
        res = run_pagerank_sharded(
            g, PageRankConfig(iterations=8, **GRAPH_KW),
            n_devices=4, strategy="nodes_balanced", metrics=m,
        )
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert [d["ladder"] for d in degraded] == ["mesh_shrink"]
    assert (degraded[0]["devices_old"], degraded[0]["devices_new"]) == (4, 2)
    parts = [r for r in m.records if r.get("event") == "partition"]
    assert [p["devices"] for p in parts] == [4, 2]  # repartitioned once


@pytest.mark.parametrize("strategy", ["edges", "hybrid"])
def test_pagerank_sharded_device_loss_at_result_pull(tmp_path, strategy):
    """Carried-forward hardening (a), ISSUE 7: a device loss FIRST
    surfacing at the sharded result-pull site (every segment already
    committed, nothing left to dispatch) used to exhaust the ladder — it
    must now walk the elastic rung: salvage the newest checkpoint,
    rebuild the mesh over the survivor, re-run only the uncommitted
    iterations there, and pull from the rebuilt mesh."""
    g = synthetic_powerlaw(700, 2800, seed=11)
    cfg = PageRankConfig(iterations=8, checkpoint_every=4,
                         checkpoint_dir=str(tmp_path / "ck"), **GRAPH_KW)
    base = run_pagerank(g, PageRankConfig(iterations=8, **GRAPH_KW))
    m = MetricsRecorder()
    with chaos.inject("pagerank_result_pull:device_lost@dev:1"):
        res = run_pagerank_sharded(g, cfg, n_devices=2, metrics=m,
                                   strategy=strategy)
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)
    assert res.iterations == 8
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert [d["ladder"] for d in degraded] == ["single_device"]
    assert degraded[0]["site"] == "pagerank_result_pull"
    assert (degraded[0]["devices_old"], degraded[0]["devices_new"]) == (2, 1)
    # the salvage repartitioned once onto the survivor
    parts = [r for r in m.records if r.get("event") == "partition"]
    assert [p["devices"] for p in parts] == [2, 1]


def test_pagerank_result_pull_loss_without_checkpoint_reruns(tmp_path):
    """No checkpoint_dir: the pull rung restarts the fixpoint from init
    on the shrunk mesh — slower, but still converging to the
    uninterrupted answer instead of exhausting."""
    g = synthetic_powerlaw(300, 1200, seed=6)
    base = run_pagerank(g, PageRankConfig(iterations=6, **GRAPH_KW))
    m = MetricsRecorder()
    with chaos.inject("pagerank_result_pull:device_lost@dev:1"):
        res = run_pagerank_sharded(
            g, PageRankConfig(iterations=6, **GRAPH_KW), n_devices=2,
            metrics=m,
        )
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)
    assert [d["ladder"] for d in m.records
            if d.get("event") == "degraded"] == ["single_device"]


def test_pagerank_sharded_elastic_disabled_exhausts(tmp_path, monkeypatch):
    monkeypatch.setenv("GRAFT_ELASTIC", "0")
    g = synthetic_powerlaw(400, 1600, seed=3)
    cfg = PageRankConfig(iterations=6, checkpoint_every=3,
                         checkpoint_dir=str(tmp_path / "ck"), **GRAPH_KW)
    with chaos.inject("*:device_lost@dev:1"):
        with pytest.raises(ResilienceExhausted):
            run_pagerank_sharded(g, cfg, n_devices=2)


def test_shrink_checkpoint_is_mesh_tagged_and_cross_readable(tmp_path):
    """The checkpoint the shrink writes carries the mesh shape that wrote
    it, and resumes under a different device count (here: single-chip)."""
    from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt

    g = synthetic_powerlaw(500, 2000, seed=9)
    cfg = PageRankConfig(iterations=6, checkpoint_every=2,
                         checkpoint_dir=str(tmp_path / "ck"), **GRAPH_KW)
    with chaos.inject("*:device_lost@dev:1"):
        run_pagerank_sharded(g, cfg, n_devices=2)
    metas = [
        ckpt.peek_meta(str(p))
        for p in sorted((tmp_path / "ck").glob("ckpt_*.npz"))
    ]
    assert any(m["extra"].get("devices") for m in metas)
    base = run_pagerank(g, PageRankConfig(iterations=6, **GRAPH_KW))
    res = run_pagerank(g, cfg, resume=True)  # single-chip reads it fine
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)


# ------------------------------------------- end-to-end: sharded TF-IDF


def _chunks(n_chunks: int, docs_per_chunk: int = 2) -> list[list[str]]:
    docs = [f"tok{i} tok{i % 5} shared word extra{i % 3}"
            for i in range(n_chunks * docs_per_chunk)]
    return [docs[i:i + docs_per_chunk]
            for i in range(0, len(docs), docs_per_chunk)]


def test_tfidf_sharded_survives_device_loss_2to1(tmp_path):
    """Acceptance: sharded TF-IDF on a 2-device mesh survives losing
    device 1 — scores match the uninterrupted run to atol 1e-6, zero
    chunks are reprocessed, and the trace shows one mesh.shrink 2->1."""
    chunks = _chunks(12)
    base = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                             n_devices=2)
    elastic.reset_health()

    cfg = TfidfConfig(vocab_bits=10, checkpoint_every=4,
                      checkpoint_dir=str(tmp_path / "ck"))
    m = MetricsRecorder()
    obs.start_run("elastic_tf", str(tmp_path / "tr"))
    try:
        with chaos.inject("*:device_lost@dev:1"):
            res = run_tfidf_sharded(iter(chunks), cfg, n_devices=2,
                                    metrics=m)
    finally:
        obs.end_run()
    assert res.n_docs == base.n_docs
    np.testing.assert_allclose(res.to_dense(), base.to_dense(), atol=1e-6)

    # zero reprocessed chunks: the committed super-chunks cover each of
    # the 12 input chunks exactly once (the in-flight group the loss
    # interrupted was re-sliced, never committed twice)
    sc = [r for r in m.records if r.get("event") == "super_chunk"]
    assert sum(r["devices"] for r in sc) == 12
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert len(degraded) == 1
    assert (degraded[0]["devices_old"], degraded[0]["devices_new"]) == (2, 1)

    trace = next((tmp_path / "tr").glob("elastic_tf.*.trace.jsonl"))
    rep = _trace_report().report(str(trace))
    assert len(rep["mesh_shrinks"]) == 1
    s = rep["mesh_shrinks"][0]
    assert (s["devices_old"], s["devices_new"]) == (2, 1)
    # the staged pipeline attributes the shrink to the site the loss
    # surfaced at: one of the ISSUE 10 H2D staging sites, or the guarded
    # drain pull for a loss first seen there
    assert s["site"] in ("ingest_h2d_put", "ingest_h2d_wait",
                         "tfidf_shard_sync")
    assert not rep["exhausted"]


def test_tfidf_sharded_custom_axis_mesh_survives():
    """The shrink rung must preserve a caller-provided mesh's axis name —
    rebuilding under the default DATA_AXIS used to crash the rung (and so
    the run) for any custom-named mesh."""
    chunks = _chunks(8)
    base = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                             n_devices=2)
    elastic.reset_health()
    custom = pmesh.make_mesh(2, "batch")
    with chaos.inject("*:device_lost@dev:1"):
        res = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                                mesh=custom)
    np.testing.assert_allclose(res.to_dense(), base.to_dense(), atol=1e-6)


# -------------------------------- stacked losses inside the shrink-rerun


def test_pagerank_second_loss_inside_shrink_rerun_reenters(tmp_path):
    """Elastic gap (ISSUE 8): the FIRST loss enters the shrink rung; the
    SECOND fires at the rerun site (``pagerank_elastic_rerun``) while the
    rebuilt mesh is re-running the failed segment — it must re-enter the
    ladder (second shrink) instead of exhausting.  Two stacked
    ``device_lost`` injections, two ``mesh.shrink`` spans, exact ranks."""
    g = synthetic_powerlaw(800, 3200, seed=17)
    cfg = PageRankConfig(iterations=8, checkpoint_every=4,
                         checkpoint_dir=str(tmp_path / "ck"), **GRAPH_KW)
    base = run_pagerank(g, PageRankConfig(iterations=8, **GRAPH_KW))
    m = MetricsRecorder()
    obs.start_run("elastic_stack", str(tmp_path / "tr"))
    try:
        with chaos.inject(
            "pagerank_step:device_lost@dev:1;"
            "pagerank_elastic_rerun:device_lost@dev:2"
        ):
            res = run_pagerank_sharded(g, cfg, n_devices=4, metrics=m)
    finally:
        obs.end_run()
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)
    assert res.iterations == 8
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert [(d["devices_old"], d["devices_new"]) for d in degraded] == \
        [(4, 2), (2, 1)]
    assert [d["ladder"] for d in degraded] == ["mesh_shrink", "single_device"]
    trace = next((tmp_path / "tr").glob("elastic_stack.*.trace.jsonl"))
    rep = _trace_report().report(str(trace))
    assert len(rep["mesh_shrinks"]) == 2
    assert not rep["exhausted"]


def test_pagerank_second_loss_during_salvage_is_absorbed(tmp_path):
    """A wildcard double injection: the second loss fires during the
    salvage pull (pagerank_ckpt_pull) — the rung acknowledges it, retries
    the salvage against the health registry, and ONE shrink absorbs both
    dead devices.  The run completes either way; exhausting is the only
    wrong answer."""
    g = synthetic_powerlaw(700, 2800, seed=23)
    cfg = PageRankConfig(iterations=8, checkpoint_every=4,
                         checkpoint_dir=str(tmp_path / "ck"), **GRAPH_KW)
    base = run_pagerank(g, PageRankConfig(iterations=8, **GRAPH_KW))
    m = MetricsRecorder()
    with chaos.inject("*:device_lost@dev:1;*:device_lost@dev:2"):
        res = run_pagerank_sharded(g, cfg, n_devices=4, metrics=m)
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert degraded  # shrank at least once, exhausted never
    assert elastic.health().lost() == frozenset({1, 2})


def test_tfidf_second_loss_inside_reslice_reenters(tmp_path):
    """The sharded-ingest counterpart: a second device dying while the
    re-sliced in-flight super-chunk drains re-enters the shrink ladder
    (4 -> 2 -> 1), commits every chunk exactly once, and matches the
    uninterrupted output."""
    chunks = _chunks(12)
    base = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                             n_devices=4)
    elastic.reset_health()
    m = MetricsRecorder()
    with chaos.inject("*:device_lost@dev:1;*:device_lost@dev:2"):
        res = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                                n_devices=4, metrics=m)
    np.testing.assert_allclose(res.to_dense(), base.to_dense(), atol=1e-6)
    sc = [r for r in m.records if r.get("event") == "super_chunk"]
    assert sum(r["devices"] for r in sc) == 12  # zero reprocessed chunks
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert [(d["devices_old"], d["devices_new"]) for d in degraded] == \
        [(4, 2), (2, 1)]


# --------------------------------------- adaptive sync deadline satellites


def test_sync_p99_from_trace(tmp_path):
    tr = tmp_path / "x.trace.jsonl"
    events = [{"kind": "run_start", "t": 0.0, "thread": "m"}]
    for i in range(100):
        events.append({
            "kind": "span_end", "t": float(i), "name": "tfidf.chunk",
            "secs": 0.01 * (i + 1),
        })
    events.append({"kind": "span_end", "t": 200.0, "name": "bench.warm",
                   "secs": 99.0})  # not a sync span: must not count
    tr.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    mod = _trace_report()
    p99 = mod.sync_p99(str(tr))
    assert p99 == pytest.approx(0.99)
    empty = tmp_path / "y.trace.jsonl"
    empty.write_text(json.dumps({"kind": "run_start", "t": 0.0}) + "\n")
    assert mod.sync_p99(str(empty)) is None


def test_effective_sync_deadline_math():
    import importlib.util as ilu

    spec = ilu.spec_from_file_location("bench_mod", REPO / "bench.py")
    bench = ilu.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench._effective_sync_deadline(120.0, None) == 120.0
    assert bench._effective_sync_deadline(120.0, 10.0) == 120.0  # knob wins
    assert bench._effective_sync_deadline(120.0, 90.0) == 270.0  # 3 x p99
    assert bench._effective_sync_deadline(0.0, 90.0) == 0.0  # 0 = disabled
