"""Test env: 8 simulated devices on the CPU backend (SURVEY.md §4).

Only one physical TPU chip exists in this environment, so every distributed
test runs the real psum/shard_map code paths over XLA's fake host devices.
Must run before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin in this image overrides JAX_PLATFORMS from the
# environment; the config API wins over the plugin.
jax.config.update("jax_platforms", "cpu")

# SURVEY.md §5.2: NaN debugging on in tests (functional model has no data
# races; NaN poisoning is the failure class that remains).
jax.config.update("jax_debug_nans", True)
# float64 available on the CPU test backend so parity bars of 1e-6..1e-9
# are meaningful; production TPU runs use float32 (configs' dtype field).
jax.config.update("jax_enable_x64", True)
