"""TF-IDF oracle anchor (ISSUE 1 satellite): pin ``models/tfidf.py``
against an independently-computed sklearn-style reference on the
``tests/fixtures/tiny.txt`` corpus (each line of the fixture is one
document).

Smoothing convention documented and pinned here — the sklearn
``TfidfVectorizer(smooth_idf=True, sublinear_tf=False, norm="l2")``
formula, which this framework spells ``idf_mode="smooth"``:

    idf(t)  = ln((1 + N) / (1 + df(t))) + 1
    tf(t,d) = raw count of t in d
    w(t,d)  = tf(t,d) * idf(t), then each document L2-normalized.

The reference below is hand-rolled numpy over the package's own tokenizer
and hashed vocabulary (collisions must fold identically on both sides),
so it anchors the *numeric pipeline* — sort+RLE counting, segment-sum DF,
the IDF join, the per-doc L2 reduction — not the tokenizer.
"""

from __future__ import annotations

import math
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
    fnv1a_64,
    hash_to_vocab,
    tokenize,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig

FIXTURE = Path(__file__).parent / "fixtures" / "tiny.txt"
VOCAB_BITS = 10


def _corpus() -> list[str]:
    return FIXTURE.read_text().splitlines()


def _hashed(tok: str) -> int:
    return int(hash_to_vocab(fnv1a_64([tok]), VOCAB_BITS)[0])


def _reference_dense(docs: list[str]) -> np.ndarray:
    """sklearn-convention TF-IDF matrix, computed with dicts + math.log."""
    n = len(docs)
    vocab = 1 << VOCAB_BITS
    tok_docs = [[_hashed(t) for t in tokenize(d)] for d in docs]

    df = np.zeros(vocab)
    for toks in tok_docs:
        for h in set(toks):
            df[h] += 1
    idf = np.zeros(vocab)
    for h in range(vocab):
        if df[h] > 0:
            idf[h] = math.log((1.0 + n) / (1.0 + df[h])) + 1.0

    dense = np.zeros((n, vocab))
    for d, toks in enumerate(tok_docs):
        for h in toks:
            dense[d, h] += 1.0  # raw tf
        dense[d] *= idf
        norm = math.sqrt((dense[d] ** 2).sum())
        if norm > 0:
            dense[d] /= norm
    return dense


def test_tiny_corpus_matches_sklearn_formula():
    docs = _corpus()
    assert len(docs) >= 8, "fixture should exercise several documents"

    out = run_tfidf(
        docs,
        TfidfConfig(
            vocab_bits=VOCAB_BITS,
            tf_mode="raw",
            idf_mode="smooth",
            l2_normalize=True,
        ),
    )
    got = out.to_dense()
    want = _reference_dense(docs)

    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_tiny_corpus_df_and_idf_match_reference():
    docs = _corpus()
    out = run_tfidf(
        docs,
        TfidfConfig(
            vocab_bits=VOCAB_BITS,
            tf_mode="raw",
            idf_mode="smooth",
            l2_normalize=True,
        ),
    )
    n = len(docs)
    tok_docs = [{_hashed(t) for t in tokenize(d)} for d in docs]
    for h in range(1 << VOCAB_BITS):
        df = sum(1 for toks in tok_docs if h in toks)
        assert out.df[h] == pytest.approx(df)
        want_idf = math.log((1.0 + n) / (1.0 + df)) + 1.0 if df else 0.0
        assert out.idf[h] == pytest.approx(want_idf, rel=1e-6)
