"""Native C++ ingest kernels == numpy fallbacks, bit for bit.

SURVEY.md §7 flags the host-side parse/tokenize loops as the scale
bottleneck; utils/native.py binds the C++ kernels and io/{graph,text}.py
fall back to numpy when they're unavailable.  These tests pin the two
implementations equal on the same inputs — the graceful-degradation
contract only holds if the fast path is indistinguishable.
"""

from __future__ import annotations

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.io import graph as gio
from page_rank_and_tfidf_using_apache_spark_tpu.io import text as tio
from page_rank_and_tfidf_using_apache_spark_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)

SNAP_TEXT = (
    "# Directed graph (each unordered pair of nodes is saved once)\n"
    "# FromNodeId\tToNodeId\n"
    "0\t1\n"
    "1\t2\n"
    "  \n"
    "2\t0\n"
    "2\t1\r\n"
    "   # indented comment\n"
    "3 3\n"
    "0\t1\n"  # duplicate edge — dedup happens downstream in from_edges
    "10    7\n"  # multi-space separator, dangling node 7
)


def _numpy_pairs(text: str) -> np.ndarray:
    lines = [ln for ln in text.splitlines() if ln and not ln.lstrip().startswith("#")]
    flat = " ".join(lines).split()
    return np.array(flat, dtype=np.int64).reshape(-1, 2)


def test_edge_parser_matches_numpy(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text(SNAP_TEXT)
    got = native.parse_edge_file(str(p))
    assert got is not None
    np.testing.assert_array_equal(got, _numpy_pairs(SNAP_TEXT))


def test_edge_parser_no_trailing_newline(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("0 1\n2 3")
    got = native.parse_edge_file(str(p))
    np.testing.assert_array_equal(got, [[0, 1], [2, 3]])


def test_edge_parser_empty_and_comment_only(tmp_path):
    p = tmp_path / "empty.txt"
    p.write_text("")
    assert native.parse_edge_file(str(p)).shape == (0, 2)
    p.write_text("# nothing here\n#\n")
    assert native.parse_edge_file(str(p)).shape == (0, 2)


def test_edge_parser_rejects_garbage(tmp_path):
    # Inputs the numpy path raises on must make the native path bail (None)
    # so load_snap falls through and surfaces the numpy error.
    p = tmp_path / "bad.txt"
    # int64-overflowing ids also bail (numpy raises OverflowError there).
    p.write_text("99999999999999999999 3\n")
    assert native.parse_edge_file(str(p)) is None
    for bad in ["0 1\n2 x\n", "0 1 2\n", "12abc 3\n"]:
        p.write_text(bad)
        assert native.parse_edge_file(str(p)) is None
        with pytest.raises(ValueError):
            gio.load_snap(str(p))


def test_load_snap_uses_native(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text(SNAP_TEXT)
    g_native = gio.load_snap(str(p))
    g_numpy = gio.parse_snap_text(SNAP_TEXT)
    assert g_native.n_nodes == g_numpy.n_nodes
    np.testing.assert_array_equal(g_native.src, g_numpy.src)
    np.testing.assert_array_equal(g_native.dst, g_numpy.dst)
    np.testing.assert_array_equal(g_native.out_degree, g_numpy.out_degree)
    np.testing.assert_array_equal(g_native.node_ids, g_numpy.node_ids)


DOCS = [
    "The quick brown fox jumps over the lazy dog",
    "to be or not to be, that is the question!",
    "",
    "   punctuation-only:  ...!!!   ",
    "MiXeD CaSe 123 abc123def 42",
    "café naïve résumé",  # multi-byte UTF-8 acts as separator
    "İstanbul is large",  # U+0130: lower() -> 'i' + combining dot (token break)
    "300K is hot, AKB too",  # U+212A KELVIN: lower() -> ASCII 'k'
    "İİ double dotted-İ edge İ",
    "a bb ccc dddd",
    "single",
]


def _numpy_tokenize(docs, *, vocab_bits, ngram, lowercase, min_token_len):
    per_doc = [
        tio.add_ngrams(tio.tokenize(d, lowercase=lowercase, min_token_len=min_token_len), ngram)
        for d in docs
    ]
    doc_lengths = np.fromiter((len(p) for p in per_doc), dtype=np.int32, count=len(per_doc))
    flat = [t for p in per_doc for t in p]
    term_ids = tio.hash_to_vocab(tio.fnv1a_64(flat), vocab_bits)
    doc_ids = np.repeat(np.arange(len(docs), dtype=np.int32), doc_lengths)
    return doc_ids, term_ids, doc_lengths


@pytest.mark.parametrize("ngram", [1, 2, 3])
@pytest.mark.parametrize("lowercase", [True, False])
@pytest.mark.parametrize("min_token_len", [1, 2])
def test_tokenizer_matches_numpy(ngram, lowercase, min_token_len):
    kw = dict(vocab_bits=18, ngram=ngram, lowercase=lowercase, min_token_len=min_token_len)
    got = native.tokenize_and_hash(DOCS, **kw)
    assert got is not None
    want = _numpy_tokenize(DOCS, **kw)
    for g, w, name in zip(got, want, ["doc_ids", "term_ids", "doc_lengths"]):
        np.testing.assert_array_equal(g, w, err_msg=name)


def test_tokenizer_empty_batch():
    got = native.tokenize_and_hash([], vocab_bits=18, ngram=1, lowercase=True, min_token_len=1)
    doc_ids, term_ids, doc_lengths = got
    assert doc_ids.size == 0 and term_ids.size == 0 and doc_lengths.size == 0


def test_tokenizer_small_vocab_bits():
    got = native.tokenize_and_hash(DOCS, vocab_bits=4, ngram=2, lowercase=True, min_token_len=1)
    want = _numpy_tokenize(DOCS, vocab_bits=4, ngram=2, lowercase=True, min_token_len=1)
    np.testing.assert_array_equal(got[1], want[1])
    assert got[1].size == 0 or got[1].max() < 16


def test_tokenize_corpus_native_equals_fallback(monkeypatch):
    """tokenize_corpus must give identical TokenizedCorpus either way."""
    kw = dict(vocab_bits=12, ngram=2, lowercase=True, min_token_len=1)
    tc_native = tio.tokenize_corpus(DOCS, **kw)
    monkeypatch.setattr(native, "tokenize_and_hash", lambda *a, **k: None)
    tc_numpy = tio.tokenize_corpus(DOCS, **kw)
    np.testing.assert_array_equal(tc_native.doc_ids, tc_numpy.doc_ids)
    np.testing.assert_array_equal(tc_native.term_ids, tc_numpy.term_ids)
    np.testing.assert_array_equal(tc_native.doc_lengths, tc_numpy.doc_lengths)


@pytest.mark.parametrize("dedup", [True, False])
def test_sort_dedup_edges_matches_lexsort(dedup):
    """The C++ radix sort must reproduce numpy's (dst, src) lexsort layout
    bit-for-bit, including duplicate handling and self-loops."""
    rng = np.random.default_rng(3)
    src = rng.integers(0, 500, 20_000).astype(np.int64)
    dst = rng.integers(0, 500, 20_000).astype(np.int64)
    src[::97] = dst[::97]  # self-loops
    src[1000:1100] = src[:100]  # guaranteed duplicates
    dst[1000:1100] = dst[:100]

    # the native call mutates its inputs in place — compare against copies
    got = native.sort_dedup_edges(src.copy(), dst.copy(), dedup=dedup)
    assert got is not None
    order = np.lexsort((src, dst))
    s, d = src[order], dst[order]
    if dedup:
        keep = np.empty(s.shape, bool)
        keep[0] = True
        keep[1:] = (s[1:] != s[:-1]) | (d[1:] != d[:-1])
        s, d = s[keep], d[keep]
    np.testing.assert_array_equal(got[0], s)
    np.testing.assert_array_equal(got[1], d)


def test_from_edges_native_equals_fallback(monkeypatch):
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import from_edges

    rng = np.random.default_rng(9)
    src = rng.integers(0, 2000, 50_000)
    dst = rng.integers(0, 2000, 50_000)
    g_native = from_edges(src, dst)
    monkeypatch.setattr(native, "sort_dedup_edges", lambda *a, **k: None)
    g_numpy = from_edges(src, dst)
    np.testing.assert_array_equal(g_native.src, g_numpy.src)
    np.testing.assert_array_equal(g_native.dst, g_numpy.dst)
    np.testing.assert_array_equal(g_native.out_degree, g_numpy.out_degree)
