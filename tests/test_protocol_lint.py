"""graftlint tier-6 tests (ISSUE 18): distributed wire-protocol
analysis, its derived conformance harness, and the seeded-mutation
acceptance gate.

Four layers, mirroring tests/test_persistence_lint.py:

1. **Fixture snippets** — per tier-6 check (endpoint-contract-drift,
   status-class-drift, retry-unsafe-effect, floor-monotonicity): a true
   positive, a true negative, and a suppressed positive.  Snippets are
   parsed, never executed.
2. **The declared contract** — ``WIRE_SCHEMAS`` drift is validated in
   both directions against fixture registries, and the real registry's
   rows must resolve (handlers, readers, the query row's 503-retryable
   class the floor protocol depends on).
3. **The whole-repo gate** — the tier-6 analyzer runs over the real
   wire surface and must report nothing beyond ``analysis/baseline.json``
   (currently empty: the first sweep's true positive — ``handle_query``
   crashing into an undeclared 500 on shape-malformed JSON — was fixed,
   not frozen), under the declared ``GRAFT_PROTO_BUDGET_S`` budget.
4. **The derived message space + seeded mutation** — the probe
   enumeration is pinned against the real contract, and one seeded
   contract mutation (deleting the query row's declared 503) must be
   caught BOTH statically (``endpoint-contract-drift``: the code emits
   an undeclared code) and on the wire (``tools/protocol_harness.py``:
   the observed floor refusal falls outside the declared set).
"""

from __future__ import annotations

import dataclasses
import importlib.util
import json
import os
import re
import textwrap
import time
from pathlib import Path

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
    baseline_path,
    load_baseline,
    repo_root,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis import __main__ as lint_cli
from page_rank_and_tfidf_using_apache_spark_tpu.analysis import protocol
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.protocol import (
    PROTO_RULES,
    SCAN_MODULES,
    enumerate_message_space,
    run_protocol,
    wire_contract,
    wire_fingerprint,
)

REPO = repo_root()

_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"


def wire(tmp_path: Path, files: dict[str, str], extra: tuple = ()):
    """Write a tiny repo tree and run the tier-6 analyzer over it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    models = protocol.build_models(tmp_path, extra=tuple(extra) or None)
    return run_protocol(root=tmp_path, models=models)


def rules_hit(findings) -> set[str]:
    return {f.rule for f in findings}


def _tool(name: str):
    spec = importlib.util.spec_from_file_location(
        f"protocol_test_{name}", REPO / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------- fixture builder


def _wire_fixture(
    status='((200, "success"), (400, "terminal"), (503, "retryable"))',
    request_keys='("rid", "text")',
    response_keys='("rid", "text")',
    aux="()",
    resp_doc='{"rid": rid, "text": text}',
    pre_guard="pass",
    post_guard="pass",
    reader_extra="pass",
    reg_disable="",
    srv_extra="",
):
    """One declared POST endpoint with a dedup-guarded handler and a
    retrying reader (the router seat) — clean by construction; every
    parameter seeds exactly one drift."""
    registry = f"""
    WIRE_SCHEMAS = (  {reg_disable}
        ("echo",
         "POST",
         "/echo",
         "srv.py::Echo.handle_echo::req",
         ("srv.py::ask_echo::reply",),
         {request_keys},
         {response_keys},
         {aux},
         {status}),
    )
    """
    srv = f"""
    import json

    from urllib.error import HTTPError


    class Echo:
        def __init__(self):
            self._rid_cache = {{}}
            self.served = 0
            self.latencies = []

        def handle_echo(self, body):
            try:
                req = json.loads(body)
                rid = req["rid"]
                text = req["text"]
            except (ValueError, KeyError, TypeError):
                return (400, "text/plain", "bad request")
            if not self.ready():
                return (503, "text/plain", "below floor")
            {pre_guard}
            hit = self._rid_cache.get(rid)
            if hit is not None:
                return hit
            {post_guard}
            resp = (200, "application/json", json.dumps({resp_doc}))
            self._rid_cache[rid] = resp
            self.served += 1
            return resp

        def ready(self):
            return True


    def ask_echo(session, rid, text):
        doc = {{"rid": rid, "text": text}}
        for _attempt in range(3):
            try:
                reply = session.post("/echo", doc)
            except HTTPError as exc:
                if exc.code == 400:
                    raise
                continue
            {reader_extra}
            return reply["rid"], reply["text"]
        return None


    def serve(exporter, echo):
        return exporter(routes={{("POST", "/echo"): echo.handle_echo}})
    {srv_extra}
    """
    return {"analysis/registry.py": registry, "srv.py": srv}


def test_wire_fixture_clean(tmp_path):
    res = wire(tmp_path, _wire_fixture())
    assert not res.findings, "\n".join(f.render() for f in res.findings)


# ------------------------------------------------- endpoint-contract-drift


def test_undeclared_emitted_code_tp(tmp_path):
    """The seeded-mutation shape at fixture scale: drop the declared 503
    and the handler's floor refusal becomes an unclassified code."""
    res = wire(tmp_path, _wire_fixture(
        status='((200, "success"), (400, "terminal"))'))
    hits = [f for f in res.findings if f.rule == "endpoint-contract-drift"]
    assert hits and any("503" in f.message and "dropped-request" in f.message
                        for f in hits)


def test_declared_code_never_emitted(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        status='((200, "success"), (400, "terminal"), (410, "terminal"), '
               '(503, "retryable"))'))
    hits = [f for f in res.findings if f.rule == "endpoint-contract-drift"]
    assert hits and any("410" in f.message and "never emits" in f.message
                        for f in hits)


def test_undeclared_response_key_write(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        resp_doc='{"rid": rid, "text": text, "stowaway": 1}'))
    hits = [f for f in res.findings if f.rule == "endpoint-contract-drift"]
    assert hits and any("'stowaway'" in f.message for f in hits)
    assert any(f.path == "srv.py" for f in hits)  # anchored at the write


def test_reader_reads_undeclared_key(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        reader_extra='_ = reply["mystery"]'))
    hits = [f for f in res.findings if f.rule == "endpoint-contract-drift"]
    assert hits and any("'mystery'" in f.message for f in hits)


def test_declared_response_key_never_written(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        response_keys='("rid", "text", "ghost")'))
    hits = [f for f in res.findings if f.rule == "endpoint-contract-drift"]
    assert hits and any("'ghost'" in f.message and "no handler" in f.message
                        for f in hits)


def test_aux_exempts_write_only_response_key(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        response_keys='("rid", "text", "forensic")',
        aux='("forensic",)',
        resp_doc='{"rid": rid, "text": text, "forensic": 1}'))
    assert "endpoint-contract-drift" not in rules_hit(res.findings)


def test_registered_route_not_declared(tmp_path):
    res = wire(tmp_path, _wire_fixture(srv_extra="""

    def serve_extra(exporter, echo):
        return exporter(routes={("GET", "/extra"): echo.handle_echo})
    """))
    hits = [f for f in res.findings if f.rule == "endpoint-contract-drift"]
    assert hits and any("/extra" in f.message and "does not declare"
                        in f.message for f in hits)


def test_stale_handler_row(tmp_path):
    files = _wire_fixture()
    files["analysis/registry.py"] = """
    WIRE_SCHEMAS = (
        ("echo",
         "POST",
         "/echo",
         "srv.py::no_such_handler::req",
         (),
         ("rid",),
         (),
         (),
         ((200, "success"),)),
    )
    """
    res = wire(tmp_path, files)
    hits = [f for f in res.findings if f.rule == "endpoint-contract-drift"]
    assert hits and any("does not resolve" in f.message for f in hits)


def test_endpoint_drift_suppressed(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        post_guard='resp418 = (418, "text/plain", "teapot")  '
                   "# graftlint: disable=endpoint-contract-drift "
                   "(easter egg, never routed)"))
    assert "endpoint-contract-drift" not in rules_hit(res.findings)


# ----------------------------------------------------- status-class-drift


def test_status_class_503_must_be_retryable(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        status='((200, "success"), (400, "terminal"), (503, "terminal"))'))
    hits = [f for f in res.findings if f.rule == "status-class-drift"]
    assert hits and any("503" in f.message and "retryable" in f.message
                        for f in hits)


def test_status_class_retryable_but_router_raises(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        status='((200, "success"), (400, "retryable"), '
               '(503, "retryable"))'))
    hits = [f for f in res.findings if f.rule == "status-class-drift"]
    assert hits and any("the router raises on it" in f.message
                        for f in hits)


def test_status_class_unknown_class(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        status='((200, "success"), (400, "weird"), (503, "retryable"))'))
    hits = [f for f in res.findings if f.rule == "status-class-drift"]
    assert hits and any("unknown class 'weird'" in f.message for f in hits)


def test_status_class_suppressed(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        status='((200, "success"), (400, "terminal"), (503, "terminal"))',
        reg_disable="# graftlint: disable=status-class-drift "
                    "(fixture: split-brain contract under test)"))
    assert "status-class-drift" not in rules_hit(res.findings)


# ----------------------------------------------------- retry-unsafe-effect


def test_retry_unsafe_counter_before_guard(tmp_path):
    res = wire(tmp_path, _wire_fixture(pre_guard="self.served += 1"))
    hits = [f for f in res.findings if f.rule == "retry-unsafe-effect"]
    assert hits and any("BEFORE" in f.message for f in hits)


def test_retry_unsafe_mutator_call_before_guard(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        pre_guard="self.latencies.append(1.0)"))
    hits = [f for f in res.findings if f.rule == "retry-unsafe-effect"]
    assert hits and any("latencies.append()" in f.message for f in hits)


def test_retry_unsafe_commit_leaf_before_guard(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        pre_guard="commit_append(body, rid, text)"))
    hits = [f for f in res.findings if f.rule == "retry-unsafe-effect"]
    assert hits and any("commit_append() commit" in f.message for f in hits)


def test_retry_unsafe_interprocedural(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        pre_guard="self._bump()",
        srv_extra="""

    def _bump(self):
        self.served += 1
    """))
    hits = [f for f in res.findings if f.rule == "retry-unsafe-effect"]
    assert hits and any("via _bump()" in f.message for f in hits)


def test_retry_unsafe_tn_effects_behind_guard(tmp_path):
    res = wire(tmp_path, _wire_fixture())
    assert "retry-unsafe-effect" not in rules_hit(res.findings)


def test_retry_unsafe_no_guard_at_all(tmp_path):
    files = {
        "analysis/registry.py": """
    WIRE_SCHEMAS = (
        ("echo",
         "POST",
         "/echo",
         "srv.py::Echo.handle_echo::req",
         (),
         ("rid",),
         (),
         (),
         ((200, "success"), (400, "terminal"))),
    )
    """,
        "srv.py": """
    import json


    class Echo:
        def __init__(self):
            self.served = 0

        def handle_echo(self, body):
            try:
                req = json.loads(body)
                rid = req["rid"]
            except (ValueError, KeyError, TypeError):
                return (400, "text/plain", "bad request")
            self.served += 1
            return (200, "text/plain", rid)


    def serve(exporter, echo):
        return exporter(routes={("POST", "/echo"): echo.handle_echo})
    """,
    }
    res = wire(tmp_path, files)
    hits = [f for f in res.findings if f.rule == "retry-unsafe-effect"]
    assert hits and any("never consults" in f.message for f in hits)


def test_retry_unsafe_suppressed(tmp_path):
    res = wire(tmp_path, _wire_fixture(
        pre_guard="self.served += 1  "
                  "# graftlint: disable=retry-unsafe-effect "
                  "(monotonic attempt counter, replay-safe by design)"))
    assert "retry-unsafe-effect" not in rules_hit(res.findings)


# ----------------------------------------------------- floor-monotonicity


_FLOOR_REGISTRY = {"analysis/registry.py": "WIRE_SCHEMAS = ()\n"}

FLOOR_TN = """
import os


def durable_replace(src, dst):
    os.replace(src, dst)


def commit_floor(d, gen):
    tmp = os.path.join(d, ".floor.tmp")
    with open(tmp, "w") as f:
        f.write(str(gen))
    durable_replace(tmp, os.path.join(d, "FLOOR"))


class Replica:
    def __init__(self):
        self.floor = 0

    def observe(self, gen):
        if gen > self.floor:
            self.floor = gen

    def adopt(self, gen):
        self.floor = max(self.floor, gen)
"""

FLOOR_RAW_REPLACE_TP = """
import os


def commit_floor(d, gen):
    tmp = os.path.join(d, ".floor.tmp")
    with open(tmp, "w") as f:
        f.write(str(gen))
    os.replace(tmp, os.path.join(d, "FLOOR"))
"""

FLOOR_UNGUARDED_STORE_TP = """
class Replica:
    def __init__(self):
        self.floor = 0

    def rollback(self, gen):
        self.floor = gen
"""

FLOOR_SUPPRESSED = """
class Replica:
    def __init__(self):
        self.floor = 0

    def reset_for_test(self, gen):
        self.floor = gen  # graftlint: disable=floor-monotonicity (test-only fixture reset)
"""


def _floor(tmp_path, src):
    return wire(tmp_path, {**_FLOOR_REGISTRY, "floor.py": src},
                extra=("floor.py",))


def test_floor_tn(tmp_path):
    res = _floor(tmp_path, FLOOR_TN)
    assert "floor-monotonicity" not in rules_hit(res.findings)


def test_floor_raw_replace_tp(tmp_path):
    res = _floor(tmp_path, FLOOR_RAW_REPLACE_TP)
    hits = [f for f in res.findings if f.rule == "floor-monotonicity"]
    assert hits and any("durable_replace" in f.message for f in hits)


def test_floor_unguarded_store_tp(tmp_path):
    res = _floor(tmp_path, FLOOR_UNGUARDED_STORE_TP)
    hits = [f for f in res.findings if f.rule == "floor-monotonicity"]
    assert hits and any("ratchets up" in f.message for f in hits)


def test_floor_suppressed(tmp_path):
    res = _floor(tmp_path, FLOOR_SUPPRESSED)
    assert "floor-monotonicity" not in rules_hit(res.findings)


# ------------------------------------------------------- the real contract


def test_real_contract_resolves():
    contract = wire_contract(REPO)
    assert contract is not None and contract.rows
    endpoints = {r.endpoint for r in contract.rows}
    assert {"query", "status", "healthz", "metrics",
            "snapshot"} <= endpoints
    models = protocol.build_models(REPO)
    for row in contract.rows:
        assert protocol._resolve_spec(models, row.handler) is not None, \
            f"stale handler {row.handler!r}"
        assert row.status_classes, f"{row.endpoint}: no status classes"
    query = next(r for r in contract.rows if r.endpoint == "query")
    assert set(query.request_keys) == {"rid", "terms", "ranker"}
    assert (503, "retryable") in query.status_classes


def test_wire_fingerprint_is_stable_hex():
    fp = wire_fingerprint(REPO)
    assert fp is not None and re.fullmatch(r"[0-9a-f]{16}", fp)
    assert wire_fingerprint(REPO) == fp  # cached + deterministic


# ------------------------------------------------------ whole-repo ratchet


def test_whole_repo_protocol_clean_under_budget():
    """The acceptance gate: zero unratcheted tier-6 findings over the
    real wire surface, inside the declared GRAFT_PROTO_BUDGET_S budget
    (the first sweep's true positive — the malformed-shape 500 in
    handle_query — was fixed, not frozen)."""
    budget = float(os.environ.get("GRAFT_PROTO_BUDGET_S", 10))
    t0 = time.monotonic()
    res = run_protocol(root=REPO)
    elapsed = time.monotonic() - t0
    baseline = load_baseline(baseline_path(REPO))
    new = [f for f in res.findings if f.fingerprint not in baseline]
    assert not new, "\n".join(f.render() for f in new)
    assert elapsed < budget, f"tier-6 sweep took {elapsed:.1f}s"
    monitored = set(res.monitored)
    for mod in SCAN_MODULES:
        assert mod in monitored, mod


# ------------------------------------------------ derived message space


def test_message_space_derived_from_contract():
    probes = enumerate_message_space(REPO)
    assert probes
    q_kinds = {p["kind"] for p in probes if p.get("endpoint") == "query"}
    assert {"malformed-syntax", "malformed-shape", "missing-rid",
            "missing-terms", "wrong-method", "undeclared-key",
            "duplicate-rid", "stale-floor", "declared-codes"} <= q_kinds
    # ranker is parsed with .get -> optional, so dropping it must succeed
    assert "optional-ranker" in q_kinds
    stale = next(p for p in probes if p.get("endpoint") == "query"
                 and p["kind"] == "stale-floor")
    assert stale["expect"] == [503]
    assert any(p["kind"] == "unknown-path" for p in probes)


# ------------------------------------------------------------------- CLI


def test_cli_tier6_clean(capsys):
    rc = lint_cli.main(["--tier", "6"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "clean" in out


def test_cli_list_rules_has_tier6(capsys):
    rc = lint_cli.main(["--list-rules"])
    assert rc == 0
    out = capsys.readouterr().out
    for rule in PROTO_RULES:
        assert rule in out
    assert "[tier 6]" in out


def test_cli_wire_probes_json(capsys):
    rc = lint_cli.main(["--tier", "6", "--wire-probes", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True
    kinds = {p["kind"] for p in doc["wire_probes"]}
    assert {"duplicate-rid", "stale-floor", "unknown-path"} <= kinds


# ------------------------------------- seeded mutation + the live harness


def _mutated_contract():
    """The acceptance mutation: delete the query row's declared 503 —
    one undeclared status code."""
    real = wire_contract(REPO)
    rows = tuple(
        dataclasses.replace(row, status_classes=tuple(
            (c, cls) for c, cls in row.status_classes if c != 503))
        if row.endpoint == "query" else row
        for row in real.rows
    )
    return dataclasses.replace(real, rows=rows)


def test_seeded_mutation_caught_statically(monkeypatch):
    monkeypatch.setitem(protocol._contract_cache, str(REPO),
                        _mutated_contract())
    res = run_protocol(root=REPO)
    hits = [f for f in res.findings
            if f.rule == "endpoint-contract-drift" and "503" in f.message]
    assert hits, ("deleting the declared 503 must surface as an "
                  "emitted-but-undeclared code")


def _load_harness(monkeypatch):
    # the harness pins a deterministic fixture env at import; route that
    # through monkeypatch so an ambient chaos plan is restored afterwards
    for knob in ("GRAFT_CHAOS", "GRAFT_TRACE_DIR", "PALLAS_AXON_POOL_IPS"):
        monkeypatch.delenv(knob, raising=False)
    return _tool("protocol_harness")


def test_harness_conformant_against_real_contract(monkeypatch):
    harness = _load_harness(monkeypatch)
    report = harness.run_harness(timeout_s=10.0)
    assert "fatal" not in report, report
    assert report["ok"] is True, report["violations"]
    assert report["probes"] >= 10
    assert report["replica_checks"] >= 2  # duplicate-rid + stale-floor
    assert report["router_checks"] >= 1
    assert report["fingerprint"] == wire_fingerprint(REPO)


def test_seeded_mutation_caught_on_the_wire(monkeypatch):
    """The other half of the acceptance gate: the SAME mutation fails
    the dynamic harness — the replica's floor refusal (503) is observed
    on the wire but no longer declared."""
    harness = _load_harness(monkeypatch)
    monkeypatch.setitem(protocol._contract_cache, str(REPO),
                        _mutated_contract())
    report = harness.run_harness(timeout_s=10.0)
    assert "fatal" not in report, report
    assert report["ok"] is False
    assert any("contract drift caught on the wire" in v["detail"]
               for v in report["violations"]), report["violations"]
