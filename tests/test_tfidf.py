"""TF-IDF parity tests (SURVEY.md §4): sklearn TfidfVectorizer oracle for
the smooth/l2 variant, the RDD-semantics oracle for the raw count passes,
manual formula checks for the classic/mllib variants."""

import math

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import TfidfConfig, tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
    add_ngrams,
    fnv1a_64,
    hash_to_vocab,
    tokenize,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf_streaming
from page_rank_and_tfidf_using_apache_spark_tpu.ops.tfidf import score_query, tfidf_pipeline

from tests.spark_oracle import spark_tfidf_counts

DOCS = [
    "the cat sat on the mat",
    "the dog sat",
    "cats and dogs are friends",
    "mat mat mat dog",
    "",  # empty doc must not break anything
]


def _dense(out):
    return out.to_dense()


def test_counts_match_rdd_oracle():
    out = tfidf(DOCS, vocab_bits=12, idf_mode="classic", tf_mode="raw")
    toks = [tokenize(d) for d in DOCS]
    tf_oracle, df_oracle = spark_tfidf_counts(toks)
    # recover our per-(term, doc) raw counts through the token hash
    got = {}
    for d, t, w in zip(out.doc, out.term, out.weight):
        got[(int(t), int(d))] = w
    n = len(DOCS)
    for (term, d), cnt in tf_oracle.items():
        h = int(hash_to_vocab(fnv1a_64([term]), 12)[0])
        idf = math.log(n / df_oracle[term])
        assert got[(h, d)] == pytest.approx(cnt * idf, rel=1e-6), (term, d)
    # df parity
    for term, df in df_oracle.items():
        h = int(hash_to_vocab(fnv1a_64([term]), 12)[0])
        assert out.df[h] == df


def test_parity_sklearn():
    from sklearn.feature_extraction.text import TfidfVectorizer

    out = tfidf(DOCS, vocab_bits=12, idf_mode="smooth", l2_normalize=True)
    vec = TfidfVectorizer(token_pattern=r"[A-Za-z0-9]+", norm="l2", smooth_idf=True)
    X = vec.fit_transform([d for d in DOCS]).toarray()
    terms = list(vec.get_feature_names_out())
    hids = hash_to_vocab(fnv1a_64(terms), 12)
    assert len(set(hids.tolist())) == len(terms), "fixture must be collision-free"
    ours = _dense(out)[: X.shape[0], hids]
    np.testing.assert_allclose(ours, X, atol=1e-5)


def test_idf_variants():
    out_c = tfidf(DOCS, vocab_bits=12, idf_mode="classic")
    out_m = tfidf(DOCS, vocab_bits=12, idf_mode="mllib")
    n = len(DOCS)
    h = int(hash_to_vocab(fnv1a_64(["dog"]), 12)[0])
    df = out_c.df[h]
    assert df == 2  # "dog" in docs 1 and 3
    assert out_c.idf[h] == pytest.approx(math.log(n / df), rel=1e-6)
    assert out_m.idf[h] == pytest.approx(math.log((n + 1) / (df + 1)), rel=1e-6)


def test_tf_modes():
    out_raw = tfidf(["a a a b"], vocab_bits=12, tf_mode="raw", idf_mode="mllib")
    out_freq = tfidf(["a a a b"], vocab_bits=12, tf_mode="freq", idf_mode="mllib")
    out_log = tfidf(["a a a b"], vocab_bits=12, tf_mode="lognorm", idf_mode="mllib")
    ha = int(hash_to_vocab(fnv1a_64(["a"]), 12)[0])
    idf = math.log(2 / 2)  # mllib with N=1, df=1
    d_raw, d_freq, d_log = _dense(out_raw), _dense(out_freq), _dense(out_log)
    # idf == 0 here makes weights 0; check via df-independent ratios instead
    assert out_raw.df[ha] == 1
    cfgs = dict(vocab_bits=12, tf_mode="raw", idf_mode="smooth")
    d_raw = _dense(tfidf(["a a a b"], **cfgs))
    d_freq = _dense(tfidf(["a a a b"], **{**cfgs, "tf_mode": "freq"}))
    d_log = _dense(tfidf(["a a a b"], **{**cfgs, "tf_mode": "lognorm"}))
    assert d_freq[0, ha] == pytest.approx(d_raw[0, ha] / 4)  # count/doclen
    assert d_log[0, ha] == pytest.approx(d_raw[0, ha] / 3 * (1 + math.log(3)))


def test_bigrams():
    out = tfidf(["red fox jumps"], vocab_bits=14, ngram=2)
    toks = add_ngrams(tokenize("red fox jumps"), 2)
    assert "red fox" in toks and "fox jumps" in toks
    hb = int(hash_to_vocab(fnv1a_64(["red fox"]), 14)[0])
    assert out.df[hb] == 1


def test_streaming_equals_batch():
    cfg = TfidfConfig(vocab_bits=12, idf_mode="smooth", l2_normalize=True)
    batch = tfidf(DOCS, cfg)
    stream = run_tfidf_streaming([DOCS[:2], DOCS[2:4], DOCS[4:]], cfg)
    np.testing.assert_allclose(_dense(stream), _dense(batch), atol=1e-6)
    np.testing.assert_array_equal(stream.df, batch.df)


def test_streaming_pipeline_depths_bit_identical():
    """The double-buffered pipeline (prefetch>0) must produce bit-identical
    output to the fully serial order — only scheduling changes."""
    docs = [f"w{i % 13} w{i % 5} common x{i} y{i // 3}" for i in range(60)]
    chunks = [docs[i : i + 7] for i in range(0, 60, 7)]
    outs = []
    for depth in (0, 1, 3):
        cfg = TfidfConfig(vocab_bits=12, idf_mode="smooth", l2_normalize=True,
                          prefetch=depth)
        outs.append(run_tfidf_streaming(iter(chunks), cfg))
    for out in outs[1:]:
        np.testing.assert_array_equal(out.weight, outs[0].weight)
        np.testing.assert_array_equal(out.doc, outs[0].doc)
        np.testing.assert_array_equal(out.df, outs[0].df)


def test_streaming_producer_exception_propagates():
    def bad_chunks():
        yield ["fine doc"]
        raise RuntimeError("corpus source died")

    with pytest.raises(RuntimeError, match="corpus source died"):
        run_tfidf_streaming(bad_chunks(), TfidfConfig(vocab_bits=10))


def test_device_finalize_matches_host(monkeypatch):
    """ops.finalize_weights (the at-scale device second pass) must agree
    with the numpy finalize on every tf/l2 variant."""
    from page_rank_and_tfidf_using_apache_spark_tpu.models import tfidf as mtfidf

    docs = [f"a{i % 4} b{i % 7} c shared t{i}" for i in range(30)]
    chunks = [docs[i : i + 6] for i in range(0, 30, 6)]
    for tf_mode in ("raw", "freq", "lognorm"):
        for l2 in (False, True):
            cfg = TfidfConfig(vocab_bits=12, tf_mode=tf_mode, l2_normalize=l2)
            host = run_tfidf_streaming(iter(chunks), cfg)
            monkeypatch.setattr(mtfidf, "DEVICE_FINALIZE_MIN_NNZ", 0)
            dev = run_tfidf_streaming(iter(chunks), cfg)
            monkeypatch.undo()
            np.testing.assert_allclose(dev.weight, host.weight, rtol=2e-6)
            np.testing.assert_array_equal(dev.doc, host.doc)


def test_streaming_chunk_cap_bump():
    cfg = TfidfConfig(vocab_bits=12, chunk_tokens=4)
    stream = run_tfidf_streaming([["a b c d e f g h i j"]], cfg)
    assert stream.n_docs == 1
    batch = tfidf(["a b c d e f g h i j"], vocab_bits=12)
    np.testing.assert_allclose(_dense(stream), _dense(batch), atol=1e-6)


def test_score_query_topk():
    import jax.numpy as jnp

    docs = ["apple banana", "apple apple apple", "cherry", "banana cherry"]
    cfg = TfidfConfig(vocab_bits=12, idf_mode="smooth", l2_normalize=True)
    corpus_toks = None
    from page_rank_and_tfidf_using_apache_spark_tpu.io.text import tokenize_corpus

    corpus = tokenize_corpus(docs, vocab_bits=12)
    res = tfidf_pipeline(
        jnp.asarray(corpus.doc_ids), jnp.asarray(corpus.term_ids),
        jnp.asarray(corpus.doc_lengths),
        n_docs=4, vocab=1 << 12, idf_mode=cfg.idf_mode, l2_normalize=True,
    )
    q = np.zeros(1 << 12, np.float32)
    q[int(hash_to_vocab(fnv1a_64(["apple"]), 12)[0])] = 1.0
    scores, idx = score_query(res, jnp.asarray(q), n_docs=4, k=2)
    assert int(idx[0]) == 1  # "apple apple apple" wins
    assert scores[0] > scores[1] > 0


def test_empty_corpus():
    out = tfidf([], vocab_bits=10)
    assert out.n_docs == 0 and out.nnz == 0
