"""graftlint tier-4 tests (ISSUE 12): interprocedural concurrency &
buffer-lifetime analysis.

Three layers:

1. **Fixture snippets** — for each tier-4 check (lock-order-cycle,
   blocking-under-lock, use-after-donate, chaos-coverage-drift,
   thread-lock-drift) plus the tier-1 ``thread-registry-drift`` rule: a
   true positive, a true negative, and a suppressed positive.  Snippets
   are parsed, never executed.
2. **The whole-repo gate** — the tier-4 analyzer runs over the real
   surface and must report nothing beyond ``analysis/baseline.json``
   (currently empty: the first sweep's true positives were fixed or
   justified inline), under the declared ``GRAFT_CONC_BUDGET_S`` budget.
3. **Chaos coverage** — the fault-injection tests the first tier-4 sweep
   demanded: every guarded site it found unexercised
   (``tfidf_batch_sync``, ``tfidf_finalize_sync``, ``tfidf_df_commit``,
   ``pagerank_ckpt_pull``, ``partitioned_pull``, ``bm25_weights_pull``,
   ``serve_warmup``, ``serve_pull``) now retries an injected transient
   invisibly, with outputs equal to an uninterrupted run.  These tests
   are simultaneously what makes the ``chaos-coverage-drift`` check pass:
   the analyzer cross-references the site names injected here.
"""

from __future__ import annotations

import textwrap
import time
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
    baseline_path,
    load_baseline,
    repo_root,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis import __main__ as lint_cli
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.concurrency import (
    CONC_RULES,
    run_concurrency,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import lint_file

REPO = repo_root()

_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"


def conc(tmp_path: Path, files: dict[str, str]):
    """Write a tiny repo tree and run the tier-4 analyzer over it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_concurrency(root=tmp_path, paths=[tmp_path])


def rules_hit(findings) -> set[str]:
    return {f.rule for f in findings}


# ------------------------------------------------------------ lock-order-cycle


CYCLE_TP = """
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def take_b_under_a():
    with LOCK_A:
        with LOCK_B:
            pass


def take_a_under_b():
    with LOCK_B:
        with LOCK_A:
            pass
"""

CYCLE_TN = """
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def consistent_one():
    with LOCK_A:
        with LOCK_B:
            pass


def consistent_two():
    with LOCK_A:
        with LOCK_B:
            pass
"""

CYCLE_SUPPRESSED = """
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def take_b_under_a():
    with LOCK_A:
        with LOCK_B:  # graftlint: disable=lock-order-cycle (shutdown-only path, never concurrent with take_a_under_b)
            pass


def take_a_under_b():
    with LOCK_B:
        with LOCK_A:  # graftlint: disable=lock-order-cycle (shutdown-only path)
            pass
"""

CYCLE_INTERPROCEDURAL_TP = """
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def helper_takes_b():
    with LOCK_B:
        pass


def forward():
    with LOCK_A:
        helper_takes_b()


def backward():
    with LOCK_B:
        with LOCK_A:
            pass
"""


def test_lock_cycle_true_positive(tmp_path):
    res = conc(tmp_path, {"snippet.py": CYCLE_TP})
    assert "lock-order-cycle" in rules_hit(res.findings)
    assert ("snippet.py::LOCK_A", "snippet.py::LOCK_B") in res.graph.edges
    assert ("snippet.py::LOCK_B", "snippet.py::LOCK_A") in res.graph.edges


def test_lock_cycle_true_negative(tmp_path):
    res = conc(tmp_path, {"snippet.py": CYCLE_TN})
    assert "lock-order-cycle" not in rules_hit(res.findings)
    # the consistent edge is still in the graph — just acyclic
    assert ("snippet.py::LOCK_A", "snippet.py::LOCK_B") in res.graph.edges


def test_lock_cycle_suppressed(tmp_path):
    res = conc(tmp_path, {"snippet.py": CYCLE_SUPPRESSED})
    assert "lock-order-cycle" not in rules_hit(res.findings)


def test_lock_cycle_through_same_file_call(tmp_path):
    res = conc(tmp_path, {"snippet.py": CYCLE_INTERPROCEDURAL_TP})
    assert "lock-order-cycle" in rules_hit(res.findings)


def test_self_deadlock_on_plain_lock(tmp_path):
    res = conc(tmp_path, {"snippet.py": """
import threading


class S:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""})
    hits = [f for f in res.findings if f.rule == "lock-order-cycle"]
    assert hits and "re-acquired" in hits[0].message


# --------------------------------------------------------- blocking-under-lock


BLOCKING_TP_RESULT = """
import threading


class Hub:
    def __init__(self):
        self._hub_lock = threading.Lock()

    def flush(self, fut):
        with self._hub_lock:
            fut.result()
"""

BLOCKING_TP_QUEUE = """
import queue
import threading


class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue(4)

    def push(self, item):
        with self._lock:
            self._q.put(item)
"""

BLOCKING_TN = """
import queue
import threading


class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue(4)

    def push(self, item):
        with self._lock:
            depth = self._q.qsize()
        self._q.put(item)
        return depth
"""

BLOCKING_SUPPRESSED = """
import threading


class Hub:
    def __init__(self):
        self._hub_lock = threading.Lock()

    def flush(self, fut):
        with self._hub_lock:
            fut.result()  # graftlint: disable=blocking-under-lock (single-threaded test harness)
"""

BLOCKING_INTERPROCEDURAL_TP = """
import threading
import time

LOCK_M = threading.Lock()


def helper_sleeps():
    time.sleep(1.0)


def hot():
    with LOCK_M:
        helper_sleeps()
"""


def test_blocking_result_under_lock(tmp_path):
    res = conc(tmp_path, {"snippet.py": BLOCKING_TP_RESULT})
    hits = [f for f in res.findings if f.rule == "blocking-under-lock"]
    assert hits and "Future.result" in hits[0].message


def test_blocking_queue_put_under_lock(tmp_path):
    res = conc(tmp_path, {"snippet.py": BLOCKING_TP_QUEUE})
    hits = [f for f in res.findings if f.rule == "blocking-under-lock"]
    assert hits and "queue.put" in hits[0].message


def test_blocking_true_negative(tmp_path):
    res = conc(tmp_path, {"snippet.py": BLOCKING_TN})
    assert "blocking-under-lock" not in rules_hit(res.findings)


def test_blocking_suppressed(tmp_path):
    res = conc(tmp_path, {"snippet.py": BLOCKING_SUPPRESSED})
    assert "blocking-under-lock" not in rules_hit(res.findings)


def test_blocking_through_same_file_call(tmp_path):
    res = conc(tmp_path, {"snippet.py": BLOCKING_INTERPROCEDURAL_TP})
    hits = [f for f in res.findings if f.rule == "blocking-under-lock"]
    assert hits and "time.sleep" in hits[0].message
    assert "helper_sleeps()" in hits[0].message  # the call chain is named


# ------------------------------------------------------------ use-after-donate


DONATE_TP_READ = """
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops


def ingest(d_doc, d_term, d_valid, df_dev):
    counts, new_df = ops.chunk_counts_carry(d_doc, d_term, d_valid, df_dev, vocab=16)
    host_df = np.asarray(df_dev)
    return counts, new_df, host_df
"""

DONATE_TP_REDISPATCH = """
from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops


def ingest_twice(a1, b1, c1, a2, b2, c2, df_dev):
    counts1, fresh = ops.chunk_counts_carry(a1, b1, c1, df_dev, vocab=16)
    counts2, fresh2 = ops.chunk_counts_carry(a2, b2, c2, df_dev, vocab=16)
    return counts1, counts2, fresh2
"""

DONATE_TP_RETRY_CLOSURE = """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx


def hot(dg, ranks_dev, e, runner):
    return rx.run_guarded(lambda: runner(dg, ranks_dev, e), site="fix_step")
"""

DONATE_TN_REBIND = """
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops


def ingest(chunks, df_dev):
    for d_doc, d_term, d_valid in chunks:
        counts, df_dev = ops.chunk_counts_carry(d_doc, d_term, d_valid, df_dev, vocab=16)
    return np.asarray(df_dev)
"""

DONATE_SUPPRESSED = """
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops


def ingest(d_doc, d_term, d_valid, df_dev):
    counts, new_df = ops.chunk_counts_carry(d_doc, d_term, d_valid, df_dev, vocab=16)
    host_df = np.asarray(df_dev)  # graftlint: disable=use-after-donate (CPU-interpret test path: donation is a no-op there)
    return counts, new_df, host_df
"""


def test_use_after_donate_host_read(tmp_path):
    res = conc(tmp_path, {"snippet.py": DONATE_TP_READ})
    hits = [f for f in res.findings if f.rule == "use-after-donate"]
    assert hits and "host-side read" in hits[0].message


def test_use_after_donate_redispatch(tmp_path):
    res = conc(tmp_path, {"snippet.py": DONATE_TP_REDISPATCH})
    hits = [f for f in res.findings if f.rule == "use-after-donate"]
    assert hits and "re-dispatch" in hits[0].message


def test_use_after_donate_retry_closure(tmp_path):
    """The PR-6 ``pagerank_delta_sync`` hazard shape: a donating call
    inside a run_guarded closure re-dispatches the consumed carry on
    every retry."""
    res = conc(tmp_path, {"snippet.py": DONATE_TP_RETRY_CLOSURE})
    hits = [f for f in res.findings if f.rule == "use-after-donate"]
    assert hits and "pagerank_delta_sync hazard" in hits[0].message


def test_use_after_donate_read_in_rebinding_statement(tmp_path):
    """A statement that rebinds the consumed name while READING it on its
    own RHS (``df_dev = np.asarray(df_dev)``) still reads the dead
    buffer — the rebind must not mask the read (review regression)."""
    res = conc(tmp_path, {"snippet.py": """
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops


def ingest(d_doc, d_term, d_valid, df_dev):
    counts, new_df = ops.chunk_counts_carry(d_doc, d_term, d_valid, df_dev, vocab=16)
    df_dev = np.asarray(df_dev)
    return counts, new_df, df_dev
"""})
    hits = [f for f in res.findings if f.rule == "use-after-donate"]
    assert hits and "host-side read" in hits[0].message


def test_use_after_donate_rebind_is_quiet(tmp_path):
    res = conc(tmp_path, {"snippet.py": DONATE_TN_REBIND})
    assert "use-after-donate" not in rules_hit(res.findings)


def test_use_after_donate_suppressed(tmp_path):
    res = conc(tmp_path, {"snippet.py": DONATE_SUPPRESSED})
    assert "use-after-donate" not in rules_hit(res.findings)


def test_donation_contract_missing_row(tmp_path):
    """A registry entry declaring donate= with no DONATED_CALLEES row
    serving it is contract drift (and vice versa for stale rows)."""
    res = conc(tmp_path, {"analysis/registry.py": """
DONATED_CALLEES: tuple = (
    ("ghost_kernel", (0,), ("entry_that_does_not_exist",)),
)

ENTRY_POINTS = (
    EntryPoint(name="orphan_entry", donate=(1,)),
)
"""})
    msgs = [f.message for f in res.findings if f.rule == "use-after-donate"]
    assert any("no DONATED_CALLEES row serves it" in m for m in msgs)
    assert any("stale contract row" in m for m in msgs)


def test_donation_contract_validates_real_registry():
    """Every donating EntryPoint in the real registry is served by a
    DONATED_CALLEES row with matching argnums (the sweep keeps this
    green; drift re-opens a finding)."""
    res = run_concurrency(root=REPO)
    msgs = [f.message for f in res.findings if f.rule == "use-after-donate"]
    assert not any("DONATED_CALLEES" in m for m in msgs), msgs


# -------------------------------------------------------- chaos-coverage-drift


COVERAGE_SITE = """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx


def pull(fn):
    return rx.run_guarded(fn, site="frob_sync")
"""


def test_chaos_coverage_true_positive(tmp_path):
    res = conc(tmp_path, {"models/thing.py": COVERAGE_SITE})
    hits = [f for f in res.findings if f.rule == "chaos-coverage-drift"]
    assert hits and "'frob_sync'" in hits[0].message


def test_chaos_coverage_true_negative(tmp_path):
    res = conc(tmp_path, {
        "models/thing.py": COVERAGE_SITE,
        "tests/test_frob.py": """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos


def test_frob_retries():
    with chaos.inject("frob_sync:fail@1"):
        pass
""",
    })
    assert "chaos-coverage-drift" not in rules_hit(res.findings)


def test_chaos_coverage_fstring_suffix(tmp_path):
    """An f-string site is covered once any named chaos site ends with
    its literal suffix (the dataflow/fixpoint.py convention)."""
    res = conc(tmp_path, {
        "models/thing.py": """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx


def pull(fn, prefix):
    return rx.run_guarded(fn, site=f"{prefix}_frob_sync")
""",
        "tests/test_frob.py": 'SPEC = "ppr_frob_sync:fail@1"\n',
    })
    assert "chaos-coverage-drift" not in rules_hit(res.findings)


def test_chaos_coverage_outside_guarded_dirs_is_quiet(tmp_path):
    res = conc(tmp_path, {"utils/thing.py": COVERAGE_SITE})
    assert "chaos-coverage-drift" not in rules_hit(res.findings)


def test_chaos_coverage_suppressed(tmp_path):
    res = conc(tmp_path, {"models/thing.py": """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx


def pull(fn):
    return rx.run_guarded(fn, site="frob_sync")  # graftlint: disable=chaos-coverage-drift (exercised implicitly by every elastic test)
"""})
    assert "chaos-coverage-drift" not in rules_hit(res.findings)


def test_chaos_coverage_unresolvable_site(tmp_path):
    res = conc(tmp_path, {"models/thing.py": """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx


def pull(fn, site):
    return rx.run_guarded(fn, site=site)
"""})
    hits = [f for f in res.findings if f.rule == "chaos-coverage-drift"]
    assert hits and "statically-resolvable" in hits[0].message


# ----------------------------------------------- thread registry (tiers 1 + 4)


def lint_snippet(tmp_path: Path, code: str, name: str = "snippet.py"):
    f = tmp_path / name
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(code))
    return lint_file(f, tmp_path)


THREAD_TP = """
import threading


def spawn(fn):
    t = threading.Thread(target=fn, name="totally-novel-thread", daemon=True)
    t.start()
    return t
"""

_TMP_CONFIG = """
THREAD_REGISTRY: tuple = (
    ("totally-novel-thread", "snippet.py", ()),
)
"""


def test_thread_registry_undeclared_name(tmp_path):
    findings = lint_snippet(tmp_path, THREAD_TP)
    assert "thread-registry-drift" in rules_hit(findings)


def test_thread_registry_declared_name_quiet(tmp_path):
    (tmp_path / "utils").mkdir()
    (tmp_path / "utils" / "config.py").write_text(textwrap.dedent(_TMP_CONFIG))
    findings = lint_snippet(tmp_path, THREAD_TP)
    assert "thread-registry-drift" not in rules_hit(findings)


def test_thread_registry_suppressed(tmp_path):
    findings = lint_snippet(tmp_path, """
import threading


def spawn(fn):
    return threading.Thread(target=fn, name="totally-novel-thread")  # graftlint: disable=thread-registry-drift (test-only helper)
""")
    assert "thread-registry-drift" not in rules_hit(findings)


def test_thread_registry_unnamed_thread(tmp_path):
    findings = lint_snippet(tmp_path, """
import threading


def spawn(fn):
    return threading.Thread(target=fn)
""")
    hits = [f for f in findings if f.rule == "thread-registry-drift"]
    assert hits and "without a name=" in hits[0].message


def test_thread_registry_stale_declaration(tmp_path):
    (tmp_path / "utils").mkdir()
    (tmp_path / "utils" / "config.py").write_text(textwrap.dedent("""
THREAD_REGISTRY: tuple = (
    ("ghost-thread", "no/such/module.py", ()),
)
"""))
    findings = lint_file(tmp_path / "utils" / "config.py", tmp_path)
    hits = [f for f in findings if f.rule == "thread-registry-drift"]
    assert hits and "implemented nowhere" in hits[0].message


THREAD_LOCK_SVC = """
import threading


class S:
    def __init__(self):
        self._svc_lock = threading.Lock()
        self._t = threading.Thread(target=self._run, name="worker", daemon=True)

    def _run(self):
        with self._svc_lock:
            pass
"""


def test_thread_lock_drift_true_positive(tmp_path):
    res = conc(tmp_path, {
        "svc.py": THREAD_LOCK_SVC,
        "utils/config.py": """
THREAD_REGISTRY: tuple = (
    ("worker", "svc.py", ()),
)
""",
    })
    hits = [f for f in res.findings if f.rule == "thread-lock-drift"]
    assert hits and "svc.py::S._svc_lock" in hits[0].message


def test_thread_lock_drift_true_negative(tmp_path):
    res = conc(tmp_path, {
        "svc.py": THREAD_LOCK_SVC,
        "utils/config.py": """
THREAD_REGISTRY: tuple = (
    ("worker", "svc.py", ("S._svc_lock",)),
)
""",
    })
    assert "thread-lock-drift" not in rules_hit(res.findings)


def test_thread_lock_drift_suppressed(tmp_path):
    res = conc(tmp_path, {
        "svc.py": THREAD_LOCK_SVC.replace(
            'name="worker", daemon=True)',
            'name="worker", daemon=True)  # graftlint: disable=thread-lock-drift (migration in flight)',
        ),
        "utils/config.py": """
THREAD_REGISTRY: tuple = (
    ("worker", "svc.py", ()),
)
""",
    })
    assert "thread-lock-drift" not in rules_hit(res.findings)


# ------------------------------------------------------- whole-repo regression


def test_whole_repo_tier4_clean_under_budget():
    """The ratchet bar: a full tier-4 run over the real surface reports
    nothing beyond the baseline (currently nothing at all), and completes
    well inside the declared GRAFT_CONC_BUDGET_S default (10s)."""
    t0 = time.perf_counter()
    res = run_concurrency(root=REPO)
    elapsed = time.perf_counter() - t0
    baseline = load_baseline(baseline_path(REPO))
    new = [f for f in res.findings if f.fingerprint not in baseline]
    assert not new, "unratcheted tier-4 findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert elapsed < 10.0, f"tier-4 whole-repo run took {elapsed:.1f}s"


def test_repo_lock_graph_contents():
    res = run_concurrency(root=REPO)
    g = res.graph
    server_lock = f"{_PKG}/serving/server.py::TfidfServer._lock"
    assert server_lock in g.nodes
    assert g.nodes[server_lock]["kind"] == "Lock"
    # the declared thread inventory shows up with its observed locks
    drains = [t for t in g.threads if t["name"] == "tfidf-serve-drain"]
    assert drains and server_lock in drains[0]["locks"]
    dot = g.to_dot()
    assert dot.startswith("digraph lock_graph") and server_lock in dot
    js = g.to_json()
    assert set(js) == {"nodes", "edges", "threads"}


def test_cli_tier4_and_lock_graph(capsys):
    assert lint_cli.main(["--tier", "4"]) == 0
    out = capsys.readouterr().out
    assert "graftlint: clean" in out
    assert lint_cli.main(["--tier", "4", "--lock-graph"]) == 0
    out = capsys.readouterr().out
    assert "digraph lock_graph" in out


def test_list_rules_includes_tier4(capsys):
    assert lint_cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in CONC_RULES:
        assert rid in out
    assert "[tier 4]" in out


# ---------------------------------------------------------- chaos coverage
# The fault-injection tests the first tier-4 sweep demanded: each site it
# flagged as unexercised retries one injected transient invisibly and
# produces output equal to an uninterrupted run.


from page_rank_and_tfidf_using_apache_spark_tpu import serving  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.bm25 import (  # noqa: E402
    bm25_from_tfidf,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.partition import (  # noqa: E402
    PartitionedArray,
)
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (  # noqa: E402
    synthetic_powerlaw,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import (  # noqa: E402
    run_pagerank,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (  # noqa: E402
    run_tfidf,
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (  # noqa: E402
    ServeConfig,
    TfidfServer,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (  # noqa: E402
    PageRankConfig,
    TfidfConfig,
)

_DOCS = [
    "alpha beta gamma beta",
    "beta gamma delta",
    "epsilon zeta alpha zeta",
    "gamma gamma beta alpha",
]
_TCFG = TfidfConfig(vocab_bits=8)


def test_chaos_tfidf_batch_sync_retries():
    base = run_tfidf(_DOCS, _TCFG)
    with chaos.inject("tfidf_batch_sync:fail@1") as plan:
        out = run_tfidf(_DOCS, _TCFG)
    assert plan.call_count("tfidf_batch_sync") >= 2  # failed + retried
    np.testing.assert_array_equal(out.to_dense(), base.to_dense())


def test_chaos_tfidf_finalize_and_df_commit_retry():
    cfg = TfidfConfig(vocab_bits=8, chunk_tokens=16)
    chunks = [_DOCS[:2], _DOCS[2:]]
    base = run_tfidf_streaming(iter(chunks), cfg)
    with chaos.inject("tfidf_df_commit:fail@1;tfidf_finalize_sync:fail@1"):
        out = run_tfidf_streaming(iter(chunks), cfg)
    np.testing.assert_array_equal(out.to_dense(), base.to_dense())


def test_chaos_pagerank_ckpt_pull_retries(tmp_path):
    g = synthetic_powerlaw(64, 256, seed=3)
    kw = dict(dangling="redistribute", init="uniform", dtype="float32")
    base = run_pagerank(g, PageRankConfig(iterations=4, **kw))
    cfg = PageRankConfig(iterations=4, checkpoint_every=2,
                         checkpoint_dir=str(tmp_path), **kw)
    with chaos.inject("pagerank_ckpt_pull:fail@1") as plan:
        res = run_pagerank(g, cfg)
    assert plan.call_count("pagerank_ckpt_pull") >= 2
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-7)


def test_chaos_partitioned_pull_retries():
    host = np.arange(8, dtype=np.float32)
    pa = PartitionedArray.identity(8).put(host)
    with chaos.inject("partitioned_pull:fail@1") as plan:
        out = pa.pull()
    assert plan.call_count("partitioned_pull") >= 2
    np.testing.assert_array_equal(out, host)


def test_chaos_bm25_weights_pull_retries():
    out = run_tfidf(_DOCS, _TCFG)
    base = bm25_from_tfidf(out)
    with chaos.inject("bm25_weights_pull:fail@1") as plan:
        w = bm25_from_tfidf(out)
    assert plan.call_count("bm25_weights_pull") >= 2
    np.testing.assert_array_equal(w, base)


@pytest.fixture(scope="module")
def tiny_index(tmp_path_factory):
    out = run_tfidf(_DOCS, _TCFG)
    d = tmp_path_factory.mktemp("conc_idx")
    serving.save_index(str(d), out, _TCFG)
    return serving.load_index(str(d))


def test_chaos_serve_warmup_and_pull_retry(tiny_index):
    scfg = ServeConfig(top_k=3, max_batch=2)
    with TfidfServer(tiny_index, scfg) as ref_srv:
        ref_scores, ref_docs = ref_srv.query(["beta", "gamma"])
    with chaos.inject("serve_warmup:fail@1;serve_pull:fail@1") as plan:
        with TfidfServer(tiny_index, scfg) as srv:
            scores, docs = srv.query(["beta", "gamma"])
    assert plan.call_count("serve_warmup") >= 2  # injected fail + retry
    assert plan.call_count("serve_pull") >= 2
    np.testing.assert_array_equal(docs, ref_docs)
    np.testing.assert_allclose(scores, ref_scores, atol=1e-7)
