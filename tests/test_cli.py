"""CLI smoke tests — the reference's user surface is the command line
(SURVEY.md L5), so the drivers get end-to-end coverage."""

import json
import os

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.cli import pagerank as pr_cli
from page_rank_and_tfidf_using_apache_spark_tpu.cli import tfidf as tfidf_cli

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "tiny.txt")


def test_pagerank_cli_file_output(tmp_path, capsys):
    out = tmp_path / "ranks.txt"
    rc = pr_cli.main([FIXTURE, "10", "--output", str(out),
                      "--dangling", "redistribute", "--init", "uniform",
                      "--dtype", "float64",
                      "--metrics-json", str(tmp_path / "m.json")])
    assert rc == 0
    lines = out.read_text().splitlines()
    assert len(lines) == 5  # tiny.txt has 5 nodes
    ranks = [float(l.split("\t")[1]) for l in lines]
    assert ranks == sorted(ranks, reverse=True)
    m = json.loads((tmp_path / "m.json").read_text())
    assert any("l1_delta" in r for r in m["records"])


def test_pagerank_cli_synthetic_stdout(capsys):
    rc = pr_cli.main(["synthetic:50,200,1", "5", "--top-k", "3"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 3


def test_tfidf_cli_dir(tmp_path, capsys):
    d = tmp_path / "corpus"
    d.mkdir()
    (d / "a.txt").write_text("apple banana apple")
    (d / "b.txt").write_text("banana cherry")
    out = tmp_path / "w.tsv"
    rc = tfidf_cli.main([str(d), "--vocab-bits", "12", "--output", str(out),
                         "--query", "apple", "--top-k", "2"])
    assert rc == 0
    assert len(out.read_text().splitlines()) == 4  # 4 distinct (term,doc) pairs
    q = capsys.readouterr().out.strip().splitlines()
    assert q and q[0].startswith("a.txt")  # apple doc wins the query


def test_tfidf_cli_lines_streaming(tmp_path):
    f = tmp_path / "corpus.txt"
    f.write_text("dog cat\ncat fish\nfish dog dog\n")
    rc = tfidf_cli.main([str(f), "--lines", "--streaming", "--chunk-docs", "2",
                         "--vocab-bits", "12"])
    assert rc == 0


def test_tfidf_cli_mesh_streaming_matches_single(tmp_path):
    """--mesh N routes through the sharded ingest and must produce the same
    weights as the single-device streaming path."""
    f = tmp_path / "corpus.txt"
    f.write_text("\n".join(f"w{i % 5} w{i % 3} shared t{i}" for i in range(40)))
    single = tmp_path / "w1.tsv"
    meshed = tmp_path / "w8.tsv"
    assert tfidf_cli.main([str(f), "--lines", "--streaming", "--chunk-docs", "4",
                           "--vocab-bits", "12", "--l2-normalize",
                           "--output", str(single)]) == 0
    assert tfidf_cli.main([str(f), "--lines", "--streaming", "--chunk-docs", "4",
                           "--vocab-bits", "12", "--l2-normalize",
                           "--mesh", "8", "--output", str(meshed)]) == 0
    a = sorted(single.read_text().splitlines())
    b = sorted(meshed.read_text().splitlines())
    assert len(a) == len(b) > 0
    for la, lb in zip(a, b):
        assert la.split()[:2] == lb.split()[:2]
        assert abs(float(la.split()[2]) - float(lb.split()[2])) < 1e-6


def test_tfidf_cli_mesh_requires_streaming(tmp_path):
    d = tmp_path / "corpus"
    d.mkdir()
    (d / "a.txt").write_text("one doc")
    with pytest.raises(SystemExit):
        tfidf_cli.main([str(d), "--mesh", "4"])


def test_workloads_cli_ppr_hits_cc(tmp_path, capsys):
    from page_rank_and_tfidf_using_apache_spark_tpu.cli import (
        workloads as wl_cli,
    )

    rc = wl_cli.main(["ppr", "synthetic:60,240,1", "--queries", "0,1", "2",
                      "--iterations", "20", "--top-k", "2"])
    assert rc == 0
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(lines) == 4  # 2 queries x top-2
    assert {ln.split("\t")[0] for ln in lines} == {"0", "1"}

    rc = wl_cli.main(["hits", "synthetic:60,240,1", "--top-k", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("hub\t") == 3 and out.count("auth\t") == 3

    comp = tmp_path / "components.tsv"
    rc = wl_cli.main(["cc", "synthetic:60,120,1", "--output", str(comp)])
    assert rc == 0
    rows = [ln.split("\t") for ln in comp.read_text().splitlines()]
    assert rows and all(len(r) == 2 for r in rows)
    # labels are canonical smallest-member ids: every component label is
    # also a node mapped to itself
    labels = {r[1] for r in rows}
    selfmap = {r[0] for r in rows if r[0] == r[1]}
    assert labels == selfmap


def test_serve_cli_ranker_prefix(tmp_path, capsys, monkeypatch):
    """End-to-end A/B through the CLIs: build an index with bundled BM25
    weights via cli.tfidf --save-index, then serve one query under each
    ranker via the @ prefix."""
    from page_rank_and_tfidf_using_apache_spark_tpu.cli import (
        serve as serve_cli,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.cli import (
        tfidf as tfidf_cli,
    )

    f = tmp_path / "corpus.txt"
    f.write_text("apollo guidance computer\napollo program apollo\n"
                 "guidance law\ncomputer science computer\n")
    idx = tmp_path / "idx"
    rc = tfidf_cli.main([str(f), "--lines", "--vocab-bits", "10",
                         "--save-index", str(idx)])
    assert rc == 0
    q = tmp_path / "queries.txt"
    q.write_text("@tfidf apollo\n@bm25 apollo\n")
    rc = serve_cli.main([str(idx), "--queries", str(q), "--top-k", "2"])
    assert rc == 0
    out = capsys.readouterr().out.splitlines()
    q0 = sorted(ln for ln in out if ln.startswith("0\t"))
    q1 = sorted(ln for ln in out if ln.startswith("1\t"))
    assert q0 and q1
    # same query, different ranker -> different scores
    assert [ln.split("\t")[2] for ln in q0] != [ln.split("\t")[2] for ln in q1]

    # a '@bm25' line against an index WITHOUT BM25 weights reports the
    # error and keeps serving the rest of the stream (no crash)
    idx2 = tmp_path / "idx2"
    rc = tfidf_cli.main([str(f), "--lines", "--vocab-bits", "10",
                         "--no-index-bm25", "--save-index", str(idx2)])
    assert rc == 0
    q2 = tmp_path / "queries2.txt"
    q2.write_text("@bm25 apollo\napollo\n")
    rc = serve_cli.main([str(idx2), "--queries", str(q2), "--top-k", "2"])
    assert rc == 0
    captured = capsys.readouterr()
    assert "no BM25 weights" in captured.err
    assert any(ln.startswith("1\t") for ln in captured.out.splitlines())
