"""CLI smoke tests — the reference's user surface is the command line
(SURVEY.md L5), so the drivers get end-to-end coverage."""

import json
import os

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.cli import pagerank as pr_cli
from page_rank_and_tfidf_using_apache_spark_tpu.cli import tfidf as tfidf_cli

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "tiny.txt")


def test_pagerank_cli_file_output(tmp_path, capsys):
    out = tmp_path / "ranks.txt"
    rc = pr_cli.main([FIXTURE, "10", "--output", str(out),
                      "--dangling", "redistribute", "--init", "uniform",
                      "--dtype", "float64",
                      "--metrics-json", str(tmp_path / "m.json")])
    assert rc == 0
    lines = out.read_text().splitlines()
    assert len(lines) == 5  # tiny.txt has 5 nodes
    ranks = [float(l.split("\t")[1]) for l in lines]
    assert ranks == sorted(ranks, reverse=True)
    m = json.loads((tmp_path / "m.json").read_text())
    assert any("l1_delta" in r for r in m["records"])


def test_pagerank_cli_synthetic_stdout(capsys):
    rc = pr_cli.main(["synthetic:50,200,1", "5", "--top-k", "3"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 3


def test_tfidf_cli_dir(tmp_path, capsys):
    d = tmp_path / "corpus"
    d.mkdir()
    (d / "a.txt").write_text("apple banana apple")
    (d / "b.txt").write_text("banana cherry")
    out = tmp_path / "w.tsv"
    rc = tfidf_cli.main([str(d), "--vocab-bits", "12", "--output", str(out),
                         "--query", "apple", "--top-k", "2"])
    assert rc == 0
    assert len(out.read_text().splitlines()) == 4  # 4 distinct (term,doc) pairs
    q = capsys.readouterr().out.strip().splitlines()
    assert q and q[0].startswith("a.txt")  # apple doc wins the query


def test_tfidf_cli_lines_streaming(tmp_path):
    f = tmp_path / "corpus.txt"
    f.write_text("dog cat\ncat fish\nfish dog dog\n")
    rc = tfidf_cli.main([str(f), "--lines", "--streaming", "--chunk-docs", "2",
                         "--vocab-bits", "12"])
    assert rc == 0


def test_tfidf_cli_mesh_streaming_matches_single(tmp_path):
    """--mesh N routes through the sharded ingest and must produce the same
    weights as the single-device streaming path."""
    f = tmp_path / "corpus.txt"
    f.write_text("\n".join(f"w{i % 5} w{i % 3} shared t{i}" for i in range(40)))
    single = tmp_path / "w1.tsv"
    meshed = tmp_path / "w8.tsv"
    assert tfidf_cli.main([str(f), "--lines", "--streaming", "--chunk-docs", "4",
                           "--vocab-bits", "12", "--l2-normalize",
                           "--output", str(single)]) == 0
    assert tfidf_cli.main([str(f), "--lines", "--streaming", "--chunk-docs", "4",
                           "--vocab-bits", "12", "--l2-normalize",
                           "--mesh", "8", "--output", str(meshed)]) == 0
    a = sorted(single.read_text().splitlines())
    b = sorted(meshed.read_text().splitlines())
    assert len(a) == len(b) > 0
    for la, lb in zip(a, b):
        assert la.split()[:2] == lb.split()[:2]
        assert abs(float(la.split()[2]) - float(lb.split()[2])) < 1e-6


def test_tfidf_cli_mesh_requires_streaming(tmp_path):
    d = tmp_path / "corpus"
    d.mkdir()
    (d / "a.txt").write_text("one doc")
    with pytest.raises(SystemExit):
        tfidf_cli.main([str(d), "--mesh", "4"])
