"""Run-telemetry tests (ISSUE 4): the obs/ span tracer + event bus +
crash-safe JSONL sinks + run manifests, and the trace-driven accounting
pipeline (tools/trace_report.py, bench.py ``extra.breakdown``).

Acceptance bars exercised here:

- a chaos-injected (``GRAFT_CHAOS=*:fail@%5``) streaming TF-IDF run
  SIGKILLed mid-stream leaves a parseable trace from which trace_report
  recovers per-chunk wall time, retry counts per site, and the last
  incomplete span;
- ``python bench.py`` on the CPU backend emits a BENCH record whose
  ``extra.breakdown`` phases sum to within 10% of the measured wall time,
  with the accounting read from the trace artifact (not stderr).
"""

from __future__ import annotations

import importlib.util
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
from pathlib import Path

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    GRAFT_ENV_KNOBS,
    TfidfConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
    MetricsRecorder,
    resolve_log_level,
)

REPO = Path(__file__).resolve().parents[1]


def _trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", REPO / "tools" / "trace_report.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def sink():
    s = obs.MemorySink()
    obs.bus().attach(s)
    yield s
    obs.bus().detach(s)


# ---------------------------------------------------------------- tracer


def test_span_nesting_and_status(sink):
    with obs.span("outer", k=1) as outer_id:
        with obs.span("inner") as inner_id:
            pass
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("x")
    ends = {e["name"]: e for e in sink.of_kind("span_end")}
    assert ends["inner"]["parent"] == outer_id
    assert ends["outer"]["parent"] is None
    assert ends["outer"]["attrs"] == {"k": 1}
    assert inner_id != outer_id
    assert ends["inner"]["secs"] >= 0
    assert ends["boom"]["status"] == "error:ValueError"
    # begin published before the body ran (crash evidence by construction)
    kinds = [e["kind"] for e in sink.events if e.get("name") == "inner"]
    assert kinds == ["span_begin", "span_end"]


def test_span_nesting_across_threads(sink):
    """Each thread keeps its own span stack: concurrent nests never steal
    each other's parent, and a fresh thread starts at top level even while
    the spawning thread holds an open span."""
    barrier = threading.Barrier(2)

    def work(tag: str):
        with obs.span(f"{tag}.root"):
            barrier.wait()  # both threads inside their roots at once
            with obs.span(f"{tag}.child"):
                barrier.wait()

    with obs.span("main.open"):  # must NOT become any thread's parent
        threads = [
            threading.Thread(target=work, args=(t,), name=t) for t in ("a", "b")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    ends = {e["name"]: e for e in sink.of_kind("span_end")}
    for tag in ("a", "b"):
        assert ends[f"{tag}.root"]["parent"] is None  # fresh thread = top level
        assert ends[f"{tag}.child"]["parent"] == ends[f"{tag}.root"]["span"]
        assert ends[f"{tag}.child"]["thread"] == tag


def test_explicit_cross_thread_parent(sink):
    """Cross-thread parentage is available by passing parent= explicitly
    (the prefetch pattern: worker spans attributed to the coordinator)."""
    with obs.span("coordinator") as cid:
        pass

    def worker():
        with obs.span("worker", parent=cid):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    end = [e for e in sink.of_kind("span_end") if e["name"] == "worker"][0]
    assert end["parent"] == cid


# ------------------------------------------------------------- event bus


def test_broken_sink_is_detached_not_fatal(sink):
    class Broken:
        def emit(self, event):
            raise RuntimeError("sink died")

    broken = Broken()
    obs.bus().attach(broken)
    obs.emit("ping")  # must not raise
    assert obs.bus().sink_count() >= 1
    obs.emit("pong")
    kinds = sink.kinds()
    assert "ping" in kinds and "pong" in kinds


def test_metrics_recorder_thread_safe_and_forwards(sink):
    m = MetricsRecorder()
    n_threads, per = 8, 200

    def pump(k):
        for i in range(per):
            m.record(event="x", thread=k, i=i)

    threads = [threading.Thread(target=pump, args=(k,)) for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(m.records) == n_threads * per
    assert len(sink.of_kind("metric")) >= n_threads * per


def test_resolve_log_level():
    import logging

    assert resolve_log_level(None) == logging.INFO
    assert resolve_log_level("debug") == logging.DEBUG
    assert resolve_log_level("WARNING") == logging.WARNING
    assert resolve_log_level("15") == 15
    assert resolve_log_level("bogus") == logging.INFO


def test_graft_log_level_knob_declared():
    assert "GRAFT_LOG_LEVEL" in GRAFT_ENV_KNOBS
    assert "GRAFT_TRACE_DIR" in GRAFT_ENV_KNOBS


# ----------------------------------------------------- chaos/retry events


def test_chaos_injected_retry_publishes_events(sink):
    pol = rx.RetryPolicy(max_retries=3, backoff_base_s=0.001)
    with chaos.inject("obs_t1:fail@1;obs_t1:fail@2"):
        out = rx.run_guarded(lambda: 42, site="obs_t1", policy=pol)
    assert out == 42
    chaos_evts = [e for e in sink.of_kind("chaos") if e["site"] == "obs_t1"]
    retry_evts = [e for e in sink.of_kind("retry") if e["site"] == "obs_t1"]
    backoffs = [e for e in sink.of_kind("backoff") if e["site"] == "obs_t1"]
    assert len(chaos_evts) == 2 and chaos_evts[0]["fault"] == "fail"
    assert len(retry_evts) == 2
    assert retry_evts[0]["attempt"] == 1 and "ChaosError" in retry_evts[0]["error"]
    assert len(backoffs) == 2 and all(b["secs"] > 0 for b in backoffs)


def test_exhausted_and_degraded_events(sink):
    pol = rx.RetryPolicy(max_retries=1, backoff_base_s=0.001)
    with chaos.inject("obs_t2:lost@1+"):
        out = rx.run_guarded(lambda: 1, site="obs_t2", policy=pol,
                             fallback=lambda: "cpu")
    assert out == "cpu"
    assert [e["site"] for e in sink.of_kind("degraded")] == ["obs_t2"]
    with chaos.inject("obs_t3:fail@1+"):
        with pytest.raises(Exception):
            rx.run_guarded(lambda: 1, site="obs_t3", policy=pol)
    exh = sink.of_kind("exhausted")
    assert exh and exh[-1]["site"] == "obs_t3" and exh[-1]["attempts"] == 2


def test_watchdog_event_on_deadline(sink):
    pol = rx.RetryPolicy(max_retries=1, backoff_base_s=0.001, deadline_s=0.1)
    with chaos.inject("obs_t4:hang@1:5"):
        out = rx.run_guarded(lambda: "ok", site="obs_t4", policy=pol)
    assert out == "ok"
    wd = [e for e in sink.of_kind("watchdog") if e["site"] == "obs_t4"]
    assert len(wd) == 1 and wd[0]["deadline_s"] == 0.1


# ------------------------------------------------------- run + manifest


def test_manifest_knob_snapshot(tmp_path, monkeypatch):
    monkeypatch.setenv("GRAFT_RETRY_MAX", "7")
    monkeypatch.setenv("GRAFT_CHAOS", "s:fail@1")
    monkeypatch.delenv("GRAFT_CKPT_KEEP", raising=False)
    run = obs.start_run("knobtest", trace_dir=str(tmp_path))
    try:
        with open(run.manifest_path) as f:
            man = json.load(f)
        assert set(man["knobs"]) == set(GRAFT_ENV_KNOBS)
        assert man["knobs"]["GRAFT_RETRY_MAX"] == "7"
        assert man["knobs"]["GRAFT_CHAOS"] == "s:fail@1"
        assert man["knobs"]["GRAFT_CKPT_KEEP"] is None
        assert man["status"] == "running" and man["pid"] == os.getpid()
        assert man["backend"] == "cpu"  # jax is imported in the test session
        assert man["device_count"] == 8  # the simulated test mesh
        assert "lint_clean" in man
    finally:
        obs.end_run()
    with open(run.manifest_path) as f:
        man = json.load(f)
    assert man["status"] == "ok"
    assert man["wall_secs"] > 0 and man["events"] >= 2
    assert "summary" in man


def test_run_counters_and_summary(tmp_path):
    with obs.run("aggtest", trace_dir=str(tmp_path)) as r:
        obs.counter("widgets")
        obs.counter("widgets", 2)
        obs.gauge("level", 0.5)
        for v in (1.0, 2.0, 3.0, 4.0):
            obs.histogram("lat", v)
    rep = _trace_report().report(r.trace_path)
    s = rep["summary"]
    assert s["counters"]["widgets"] == 3
    assert s["gauges"]["level"] == 0.5
    h = s["histograms"]["lat"]
    assert h["count"] == 4 and h["min"] == 1.0 and h["max"] == 4.0
    assert abs(h["mean"] - 2.5) < 1e-9
    assert rep["complete"] and rep["status"] == "ok"


def test_run_supersede_and_error_status(tmp_path):
    r1 = obs.start_run("first", trace_dir=str(tmp_path))
    r2 = obs.start_run("second", trace_dir=str(tmp_path))  # supersedes r1
    obs.end_run()
    with open(r1.manifest_path) as f:
        assert json.load(f)["status"] == "superseded"
    with open(r2.manifest_path) as f:
        assert json.load(f)["status"] == "ok"
    with pytest.raises(RuntimeError):
        with obs.run("third", trace_dir=str(tmp_path)) as r3:
            raise RuntimeError("boom")
    with open(r3.manifest_path) as f:
        assert json.load(f)["status"] == "error:RuntimeError"


# ------------------------------------------- trace-driven accounting


def test_traced_streaming_run_report(tmp_path):
    """A healthy traced streaming run: breakdown covers the stream +
    finalize phases, the chunk timeline is complete, nothing dangling."""
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf_streaming,
    )

    docs = [f"tok{i} tok{i % 5} shared word" for i in range(24)]
    chunks = [docs[i:i + 4] for i in range(0, len(docs), 4)]
    with obs.run("streamtest", trace_dir=str(tmp_path)) as r:
        run_tfidf_streaming(chunks, TfidfConfig(vocab_bits=8, prefetch=0))
    rep = _trace_report().report(r.trace_path)
    assert rep["complete"] and not rep["last_incomplete"]
    assert set(rep["breakdown"]) >= {"tfidf.stream", "tfidf.finalize"}
    assert [c["chunk"] for c in rep["chunks"]] == list(range(6))
    assert all(c["complete"] and c["secs"] >= 0 for c in rep["chunks"])
    assert rep["summary"]["counters"]["tfidf.chunks"] == 6
    # phases nest under the main thread's top level only — no double count
    assert sum(rep["breakdown"].values()) <= rep["wall_secs"] * 1.02 + 0.02


KILL_CHILD = """
import os, signal, sys

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig


def chunks():
    for i in range(40):
        if i == 12:
            os.kill(os.getpid(), signal.SIGKILL)  # die mid-stream, no cleanup
        yield [f"tok{j} tok{j % 5} shared word c{i}" for j in range(4)]


obs.start_run("killtest")
# fully serial (no tokenize or H2D run-ahead): the kill at chunk 12 must
# land with exactly chunks 0..11 drained, so the accounting pin is exact
run_tfidf_streaming(chunks(), TfidfConfig(vocab_bits=8, prefetch=0,
                                          pipeline_depth=0))
"""


def test_sigkilled_chaos_run_leaves_full_accounting(tmp_path):
    """ISSUE 4 acceptance: a chaos-injected (*:fail@%5) streaming TF-IDF
    run SIGKILLed mid-stream leaves a parseable JSONL trace from which
    trace_report recovers (a) per-chunk wall time for every completed
    chunk, (b) retry counts per site, (c) the last incomplete span — plus
    a manifest frozen at status "running" with the chaos knob on record."""
    script = tmp_path / "kill_child.py"
    script.write_text(textwrap.dedent(KILL_CHILD))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=str(REPO),  # the script runs from tmp_path
        GRAFT_TRACE_DIR=str(tmp_path),
        GRAFT_CHAOS="*:fail@%5",
        GRAFT_BACKOFF_BASE_S="0.001",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=240, env=env, cwd=REPO,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    traces = sorted(tmp_path.glob("killtest.*.trace.jsonl"))
    assert len(traces) == 1
    tr = _trace_report()
    events, bad = tr.load_events(str(traces[0]))
    assert events and bad <= 1  # at most the single SIGKILL-truncated line

    rep = tr.report(str(traces[0]))
    assert rep["complete"] is False and rep["status"] == "killed"
    # (a) per-chunk wall time for chunks 0..11 (the kill lands fetching #12)
    done = [c for c in rep["chunks"] if c["complete"]]
    assert [c["chunk"] for c in done] == list(range(12))
    assert all(c["secs"] > 0 for c in done)
    # (b) retry count per site: %5 chaos fired at guarded calls 5 and 10
    assert rep["chaos"].get("tfidf_chunk_sync", 0) >= 2
    assert rep["retries"].get("tfidf_chunk_sync", 0) >= 2
    # (c) the last incomplete span names the phase the process died inside
    # — since the staged pipeline (ISSUE 10) that is the ingest *stage*
    # the kill landed in (the source dies mid-tokenize), with the
    # enclosing tfidf.stream phase still on record as incomplete
    assert rep["last_incomplete"] is not None
    assert rep["last_incomplete"]["name"] == "ingest.tokenize"
    assert "tfidf.stream" in rep["incomplete_phases"]

    manifests = sorted(tmp_path.glob("killtest.*.manifest.json"))
    assert len(manifests) == 1
    man = json.loads(manifests[0].read_text())
    assert man["status"] == "running"  # SIGKILL: never finalized — evidence
    assert man["knobs"]["GRAFT_CHAOS"] == "*:fail@%5"


def test_trace_report_cli(tmp_path):
    with obs.run("clitest", trace_dir=str(tmp_path)) as r:
        with obs.span("phase.a"):
            pass
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         r.trace_path, "--json"],
        capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["complete"] and "phase.a" in rep["breakdown"]
    human = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"), r.trace_path],
        capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert human.returncode == 0 and "phase.a" in human.stdout


def test_sharded_per_device_timings_in_chunk_timeline(tmp_path):
    """ROADMAP hardening (d): the sharded ingest publishes one
    ``device_timing`` event per super-chunk; trace_report joins it into
    the chunk timeline, so a straggling device is attributable from the
    artifact alone."""
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        run_tfidf_sharded,
    )

    docs = [f"tok{i} tok{i % 5} shared word" for i in range(16)]
    chunks = [docs[i:i + 2] for i in range(0, len(docs), 2)]
    obs.start_run("shardtime", str(tmp_path))
    try:
        run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                          n_devices=4)
    finally:
        obs.end_run()
    trace = next(tmp_path.glob("shardtime.*.trace.jsonl"))
    rep = _trace_report().report(str(trace))
    timed = [c for c in rep["chunks"] if c.get("per_device_secs")]
    assert timed, rep["chunks"]
    for c in timed:
        assert c["devices"] == len(c["per_device_secs"]) == 4
        # waited in device order: the recorded times are non-decreasing
        assert c["per_device_secs"] == sorted(c["per_device_secs"])
        assert c["per_device_secs"][-1] >= 0


def test_stitch_groups_children_by_trace_parent(tmp_path, monkeypatch):
    """ROADMAP hardening (c): two child runs exporting the same
    GRAFT_TRACE_PARENT stitch into one tree; an unparented run stays
    outside it."""
    monkeypatch.setenv("GRAFT_TRACE_PARENT", "round-7")
    for name in ("child_a", "child_b"):
        with obs.run(name, trace_dir=str(tmp_path)):
            with obs.span("work"):
                pass
    monkeypatch.delenv("GRAFT_TRACE_PARENT")
    with obs.run("loner", trace_dir=str(tmp_path)):
        pass
    mod = _trace_report()
    doc = mod.stitch(str(tmp_path))
    by_parent = {t["trace_parent"]: t for t in doc["trees"]}
    assert {c["name"] for c in by_parent["round-7"]["children"]} == \
        {"child_a", "child_b"}
    assert {c["name"] for c in by_parent["(unparented)"]["children"]} == \
        {"loner"}
    # the stitched view is also reachable from the CLI (directory arg)
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "trace_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert proc.returncode == 0 and "round-7" in proc.stdout


# ---------------------------------------------------- bench integration


def test_bench_breakdown_sums_to_wall():
    """ISSUE 4 acceptance: bench.py on the CPU backend emits a BENCH
    record whose extra.breakdown phases sum to within 10% of the measured
    wall time (the tfidf child's run span), read from the trace artifact —
    no stderr scraping on the accounting path."""
    import tempfile

    trace_dir = tempfile.mkdtemp(prefix="obs_bench_trace_")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BENCH_NODES="400", BENCH_EDGES="1600", BENCH_ITERS="2",
        BENCH_IMPLS="segment", BENCH_IMPL_TIMEOUT_S="180",
        BENCH_PROBE_TIMEOUT_S="90",
        BENCH_TFIDF_DOCS="256", BENCH_TFIDF_TOKENS_PER_DOC="30",
        BENCH_TFIDF_CHUNK_DOCS="64",
        BENCH_TFIDF_TIMEOUT_S="300",
        BENCH_TRACE_DIR=trace_dir,
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    extra = record["extra"]
    # pid-scoped subdir: a persistent BENCH_TRACE_DIR never lets a previous
    # round's trace masquerade as this record's accounting
    assert Path(extra["trace_path"]).parent == Path(trace_dir)
    breakdown = extra["breakdown"]
    wall = extra["breakdown_wall_secs"]
    assert breakdown and wall > 0
    assert {"bench.batch_cold", "bench.stream_serial"} <= set(breakdown)
    total = sum(breakdown.values())
    assert abs(total - wall) / wall <= 0.10, (breakdown, wall)
    assert extra["tfidf"]["partial"] is False
    # the artifacts themselves survive for post-mortems
    run_dir = Path(extra["trace_path"])
    assert list(run_dir.glob("tfidf.*.trace.jsonl"))
    assert list(run_dir.glob("impl_segment.*.manifest.json"))
