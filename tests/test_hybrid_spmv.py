"""Degree-aware hybrid + sort-based static-shuffle SpMV tests (ISSUE 7).

The acceptance bars:

- property-based equivalence of ``spmv_hybrid`` and ``spmv_sort_shuffle``
  against ``spmv_segment`` on random power-law (Zipf) graphs — dangling
  nodes included by construction — plus the empty-head / empty-tail /
  empty-graph edge cases;
- the static layouts account for every edge exactly once (the layout IS
  the graph, re-blocked);
- ``plan_partition(strategy="hybrid")`` reports ``pad_frac <= 0.25`` on
  the web-Google-scale graph at 8 devices, where the r05-measured
  ``nodes_balanced`` padding was 0.61 — and the optimal min-max
  ``nodes_balanced`` planner itself now beats that measured value;
- chip-count invariance of the sharded ``hybrid`` strategy lives in
  tests/test_parallel.py next to the other strategies.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from page_rank_and_tfidf_using_apache_spark_tpu.io import (
    from_edges,
    synthetic_powerlaw,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pallas_kernels as pk
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
    auto_select_strategy,
    plan_partition,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

F64 = dict(dangling="redistribute", init="uniform", dtype="float64")


def _spmv(graph, impl: str, w: np.ndarray) -> np.ndarray:
    dg = ops.put_graph(graph, "float64", layout=ops.layout_for_impl(impl))
    return np.asarray(ops.spmv(dg, jnp.asarray(w), graph.n_nodes, impl))


def _assert_impls_match_segment(graph, w=None):
    rng = np.random.default_rng(0)
    if w is None:
        w = rng.random(graph.n_nodes)
    want = _spmv(graph, "segment", w)
    # sort_shuffle is in segment's exact accuracy class (blocked per-node
    # sums); hybrid's tail rides the prefix-sum path, whose f64 error is
    # ~E*eps — far under 1e-9 at test scale, bounded at 1e-12 exactly only
    # for the shuffle layout
    got = _spmv(graph, "sort_shuffle", w)
    np.testing.assert_allclose(got, want, atol=1e-12, rtol=1e-12)
    got = _spmv(graph, "hybrid", w)
    np.testing.assert_allclose(got, want, atol=1e-9, rtol=1e-9)


# ------------------------------------------------------- direct equivalence


def test_equivalence_on_powerlaw_fixture():
    _assert_impls_match_segment(synthetic_powerlaw(300, 2400, seed=5))


def test_equivalence_empty_head():
    """A ring has uniform in-degree 1 — no node qualifies for the dense
    head (nor fills a bucket), so hybrid degenerates to the pure tail."""
    n = 40
    g = from_edges(np.arange(n), (np.arange(n) + 1) % n)
    hl = ops.build_hybrid_layout(g)
    assert hl.head_ids.size == 0 and hl.tail_src.size == g.n_edges
    _assert_impls_match_segment(g)


def test_equivalence_empty_tail():
    """A star pushes every edge into one hub: the whole graph is head,
    the tail is empty (and the leaves are dangling)."""
    g = from_edges(np.arange(1, 64), np.zeros(63, int))
    hl = ops.build_hybrid_layout(g)
    assert hl.tail_src.size == 0 and hl.head_ids.tolist() == [0]
    assert (g.out_degree == 0).sum() == 1  # the hub itself dangles
    _assert_impls_match_segment(g)


def test_layout_builders_handle_empty_graph():
    g = from_edges(np.empty(0, np.int64), np.empty(0, np.int64))
    hl = ops.build_hybrid_layout(g)
    assert hl.head_ids.size == 0 and hl.tail_src.size == 0
    bucket_src, bucket_node, _ = ops.build_shuffle_layout(g)
    assert bucket_src.shape[0] == 0 and bucket_node.size == 0


def test_hybrid_layout_accounts_every_edge_once():
    g = synthetic_powerlaw(200, 1600, seed=9)
    hl = ops.build_hybrid_layout(g)
    n = g.n_nodes
    pairs = []
    for row, slot in zip(hl.head_src, hl.head_row_node):
        dst = int(hl.head_ids[slot])
        for s in row[row != n]:
            pairs.append((int(s), dst))
    assert int((hl.head_src == n).sum()) == hl.pad_slots
    pairs += list(zip(hl.tail_src.tolist(), hl.tail_dst.tolist()))
    want = sorted(zip(g.src.tolist(), g.dst.tolist()))
    assert sorted(pairs) == want
    # the head really is the high-in-degree end: every member's in-degree
    # >= the adaptive row width (no mostly-padding dense rows)
    indeg = np.diff(g.csr_indptr())
    if hl.head_ids.size:
        assert indeg[hl.head_ids].min() >= hl.head_src.shape[1]


def test_shuffle_layout_accounts_every_edge_once():
    g = synthetic_powerlaw(150, 900, seed=4)
    bucket_src, bucket_node, _ = ops.build_shuffle_layout(g, bucket_width=8)
    assert (np.diff(bucket_node) >= 0).all()
    pairs = []
    for row, dst in zip(bucket_src, bucket_node):
        for s in row[row != g.n_nodes]:
            pairs.append((int(s), int(dst)))
    assert sorted(pairs) == sorted(zip(g.src.tolist(), g.dst.tolist()))


def test_rowsum_pallas_interpret_matches_dense():
    rng = np.random.default_rng(1)
    for r, w in ((1, 8), (7, 128), (2048, 128), (2049, 128)):
        mat = rng.random((r, w)).astype(np.float32)
        got = np.asarray(pk.rowsum_pallas(jnp.asarray(mat), interpret=True))
        np.testing.assert_allclose(got, mat.sum(axis=1), rtol=1e-6)


# -------------------------------------------------- property-based (Zipf)
# hypothesis drives the example search when available; without it the same
# properties run over a fixed deterministic seed sweep (only the search
# strategy degrades — this file must never skip wholesale).


def _check_zipf_equivalence(seed: int, zipf_a: float) -> None:
    """Random power-law graphs (Zipf destinations, uniform sources —
    dangling nodes and duplicate edges arise naturally) — both new impls
    must agree with segment_sum to f64 round-off."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(2, 120))
    e = int(rng.integers(1, 600))
    g = synthetic_powerlaw(n, e, seed=seed % (2**31), zipf_a=zipf_a)
    _assert_impls_match_segment(g, w=rng.random(g.n_nodes))


def _check_full_run_equivalence(seed: int) -> None:
    """End-to-end fixpoint runs (donated carry, scan loop, dangling
    redistribution) agree across impls in f64."""
    g = synthetic_powerlaw(80, 500, seed=seed % (2**31))
    base = run_pagerank(g, PageRankConfig(iterations=20, **F64)).ranks
    for impl in ("hybrid", "sort_shuffle"):
        got = run_pagerank(
            g, PageRankConfig(iterations=20, spmv_impl=impl, **F64)
        ).ranks
        np.testing.assert_allclose(got, base, atol=1e-9)


try:
    from hypothesis import given, settings, strategies as st
except ImportError:
    _SWEEP = [7, 193, 4040, 91823, 777_777, 2**30 + 3]

    @pytest.mark.parametrize("seed", _SWEEP)
    def test_property_equivalence_on_zipf_graphs(seed):
        _check_zipf_equivalence(seed, zipf_a=1.2 + (seed % 19) / 10.0)

    @pytest.mark.parametrize("seed", _SWEEP[:3])
    def test_property_full_run_equivalence(seed):
        _check_full_run_equivalence(seed)
else:

    @settings(max_examples=25, deadline=None)
    @given(st.integers(0, 2**31 - 1), st.floats(1.2, 3.0))
    def test_property_equivalence_on_zipf_graphs(seed, zipf_a):
        _check_zipf_equivalence(seed, zipf_a)

    @settings(max_examples=10, deadline=None)
    @given(st.integers(0, 2**31 - 1))
    def test_property_full_run_equivalence(seed):
        _check_full_run_equivalence(seed)


# ----------------------------------------------- plan-level padding pins


def test_preprocess_time_is_recorded():
    g = synthetic_powerlaw(100, 600, seed=2)
    res = run_pagerank(g, PageRankConfig(iterations=2, spmv_impl="hybrid", **F64))
    (rec,) = [r for r in res.metrics.records if r.get("event") == "put_graph"]
    assert rec["spmv_impl"] == "hybrid" and rec["preprocess_secs"] >= 0


def test_hybrid_plan_beats_pad_ceiling_at_webgoogle_scale():
    """The ISSUE 7 acceptance pin, statically checkable on CPU: at the
    bench's web-Google scale (875K nodes / 5.1M edges, 8 devices) the
    hybrid plan's padding waste is ~1e-4 — far under the 0.25 ceiling the
    registry now enforces — while the r05 dryrun measured 0.61 for
    nodes_balanced (whose optimal planner now plans 0.43: its remaining
    padding is the node-granularity floor a 780K-in-degree hub forces on
    any layout that cannot split one node's run across devices)."""
    g = synthetic_powerlaw(875_000, 5_100_000, seed=7)
    plan = plan_partition(g, 8, strategy="hybrid")
    assert plan.pad_frac <= 0.25, plan
    head_k, w, rows, rows_dev = plan.head
    assert head_k >= 1 and rows_dev * 8 * w >= plan.head[2] * w
    # the improved nodes_balanced planner beats the r05-measured 0.6123
    nb = plan_partition(g, 8, strategy="nodes_balanced")
    assert nb.pad_frac < 0.5
    # ... but cannot beat its own node-granularity lower bound, which the
    # hub's in-degree sets; hybrid goes below it by splitting dense rows
    indeg_max = int(np.diff(g.csr_indptr()).max())
    floor = (8 * indeg_max - g.n_edges) / (8 * indeg_max)
    assert nb.pad_frac == pytest.approx(floor, abs=0.01)
    assert plan.pad_frac < floor


def test_auto_select_prefers_hybrid_for_powerlaw_heads():
    g = synthetic_powerlaw(500, 3000, seed=42)
    # hub-heavy graph, generous budget -> the degree-aware hybrid layout
    assert auto_select_strategy(g, 8) == "hybrid"
    # no dense-worthy head (uniform ring) -> replicated 'edges'
    n = 400
    ring = from_edges(np.arange(n), (np.arange(n) + 1) % n)
    assert auto_select_strategy(ring, 8) == "edges"
    # starved budget picks the owned-slices layout (ISSUE 15 trigger:
    # replicated state doesn't fit)
    assert auto_select_strategy(g, 8, hbm_bytes=10_000) == "owned"
