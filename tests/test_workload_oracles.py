"""External-oracle tests for the four ISSUE 9 dataflow workloads:
networkx ``pagerank(personalization=)`` / ``hits`` /
``connected_components`` on small Zipf graphs, plus a hand-computed BM25
fixture beside the existing sklearn TF-IDF oracle — value-level pins,
not just orderings.
"""

from __future__ import annotations

import numpy as np
import pytest

nx = pytest.importorskip("networkx")

from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.bm25 import (  # noqa: E402
    bm25_from_tfidf,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.components import (  # noqa: E402
    run_components,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.hits import run_hits  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.ppr import (  # noqa: E402
    run_ppr_batch,
)
from page_rank_and_tfidf_using_apache_spark_tpu.io import synthetic_powerlaw  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.io.text import tokenize  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf  # noqa: E402
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (  # noqa: E402
    Bm25Config,
    ComponentsConfig,
    HitsConfig,
    PageRankConfig,
    TfidfConfig,
)


def _nx_digraph(graph):
    G = nx.DiGraph()
    G.add_nodes_from(range(graph.n_nodes))
    G.add_edges_from(zip(graph.src.tolist(), graph.dst.tolist()))
    return G


def _compact(graph, original_id: int) -> int:
    return int(np.searchsorted(graph.node_ids, original_id))


# --------------------------------------------- personalized PageRank


def test_ppr_batch_matches_networkx_personalization():
    """Each query of a batched personalized PageRank run matches
    networkx.pagerank(personalization=) on the same Zipf graph — the
    vmap axis changes the schedule, never a value."""
    g = synthetic_powerlaw(150, 700, seed=11)
    G = _nx_digraph(g)
    queries = [
        [int(g.node_ids[0])],
        [int(g.node_ids[3]), int(g.node_ids[9])],
        [int(g.node_ids[7]), int(g.node_ids[7]), int(g.node_ids[2])],
    ]
    cfg = PageRankConfig(iterations=500, tol=1e-12, dangling="redistribute",
                         init="uniform", dtype="float64")
    res = run_ppr_batch(g, cfg, queries)
    assert res.ranks.shape == (len(queries), g.n_nodes)
    for qi, q in enumerate(queries):
        pers = {i: 0.0 for i in range(g.n_nodes)}
        for oid in q:  # duplicates accumulate, matching restart_vector
            pers[_compact(g, oid)] += 1.0 / len(q)
        want = nx.pagerank(G, alpha=0.85, personalization=pers,
                           tol=1e-12, max_iter=1000)
        got = res.ranks[qi] / res.ranks[qi].sum()
        np.testing.assert_allclose(
            got, np.array([want[i] for i in range(g.n_nodes)]), atol=1e-8
        )


def test_ppr_batch_queries_differ_and_concentrate():
    """Sanity on the personalization semantics: a query's restart nodes
    hold more mass under their own query than under a different one."""
    g = synthetic_powerlaw(200, 900, seed=4)
    q0, q1 = [int(g.node_ids[0])], [int(g.node_ids[50])]
    res = run_ppr_batch(
        g, PageRankConfig(iterations=100, tol=1e-10,
                          dangling="redistribute", init="uniform"),
        [q0, q1],
    )
    i0, i1 = _compact(g, q0[0]), _compact(g, q1[0])
    assert res.ranks[0][i0] > res.ranks[1][i0]
    assert res.ranks[1][i1] > res.ranks[0][i1]


# ----------------------------------------------------------------- HITS


def test_hits_matches_networkx():
    g = synthetic_powerlaw(150, 700, seed=13)
    res = run_hits(g, HitsConfig(iterations=1000, tol=1e-13, dtype="float64"))
    nh, na = nx.hits(_nx_digraph(g), max_iter=2000, tol=1e-13)
    np.testing.assert_allclose(
        res.hubs, np.array([nh[i] for i in range(g.n_nodes)]), atol=1e-6
    )
    np.testing.assert_allclose(
        res.authorities, np.array([na[i] for i in range(g.n_nodes)]),
        atol=1e-6,
    )
    assert abs(res.hubs.sum() - 1.0) < 1e-9
    assert abs(res.authorities.sum() - 1.0) < 1e-9


# ----------------------------------------------------- connected components


@pytest.mark.parametrize("seed", [1, 9, 42])
def test_components_match_networkx(seed):
    g = synthetic_powerlaw(300, 600, seed=seed)
    res = run_components(g, ComponentsConfig())
    want = sorted(
        sorted(c) for c in nx.connected_components(
            _nx_digraph(g).to_undirected()
        )
    )
    got = sorted(sorted(c) for c in res.groups())
    assert got == want
    assert res.n_components == len(want)
    # labels are canonical: the smallest member id of the component
    for comp in got:
        assert all(res.labels[i] == comp[0] for i in comp)


def test_components_isolated_nodes_and_empty():
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import from_edges

    g = from_edges(np.array([0, 1, 5]), np.array([1, 0, 6]))
    res = run_components(g, ComponentsConfig())
    assert res.n_components == 2
    assert res.converged


def test_components_iteration_cap_flags_non_convergence():
    """A chain longer than the round cap cannot reach the fixpoint: the
    result must say so instead of silently over-segmenting."""
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import from_edges

    n = 40
    g = from_edges(np.arange(n - 1), np.arange(1, n))  # a path graph
    res = run_components(g, ComponentsConfig(iterations=3))
    assert not res.converged
    assert res.n_components > 1  # the over-segmentation the flag warns of
    full = run_components(g, ComponentsConfig())
    assert full.converged and full.n_components == 1


# ----------------------------------------------------------------- BM25


def test_bm25_matches_hand_computed_fixture():
    """Hand-computed Okapi BM25 (Lucene idf) on a tiny corpus — the
    formula re-derived in numpy from first principles next to the sklearn
    TF-IDF oracle (tests/test_tfidf_oracle.py)."""
    docs = [
        "apollo guidance computer",
        "apollo program",
        "guidance law control systems",
        "computer science computer architecture computer",
        "the moon landing apollo apollo",
    ]
    cfg = TfidfConfig(vocab_bits=12)
    out = run_tfidf(docs, cfg)
    k1, b = 1.7, 0.6
    got = bm25_from_tfidf(out, Bm25Config(k1=k1, b=b))
    assert got.shape == out.weight.shape

    n = len(docs)
    dls = np.array([len(tokenize(d)) for d in docs], float)
    avgdl = dls.mean()
    # independent hand computation per (doc, term) COO row
    for row in range(out.nnz):
        d, t, c = int(out.doc[row]), int(out.term[row]), float(out.count[row])
        df = float(out.df[t])
        idf = np.log(1.0 + (n - df + 0.5) / (df + 0.5))
        want = idf * c * (k1 + 1) / (c + k1 * (1 - b + b * dls[d] / avgdl))
        assert abs(got[row] - want) < 1e-5, (row, got[row], want)
    # saturation: a count-3 pair must weigh LESS than 3x the weight the
    # same (term, doc-length) pair would get at count 1 — the k1 term-
    # frequency damping, checked against the hand formula
    crow = [r for r in range(out.nnz) if int(out.doc[r]) == 3
            and float(out.count[r]) == 3.0]
    assert crow, "fixture expects a count-3 pair"
    r = crow[0]
    df = float(out.df[int(out.term[r])])
    idf = np.log(1.0 + (n - df + 0.5) / (df + 0.5))
    w1 = idf * (k1 + 1) / (1 + k1 * (1 - b + b * dls[3] / avgdl))
    assert got[r] < 3 * w1 * 0.75  # well below linear growth


def test_bm25_requires_counts():
    import dataclasses

    docs = ["a b", "b c"]
    out = run_tfidf(docs, TfidfConfig(vocab_bits=8))
    stripped = dataclasses.replace(out, count=None)
    with pytest.raises(ValueError, match="raw counts"):
        bm25_from_tfidf(stripped)


def test_bm25_serving_ab_ranker_byte_stable(tmp_path):
    """The served BM25 path: index bundles BM25 weights, per-request
    ranker selection returns BM25-ordered results byte-equal to scoring
    the BM25 weight table directly through score_query."""
    import jax.numpy as jnp

    from page_rank_and_tfidf_using_apache_spark_tpu import serving
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as tops

    docs = ["apollo guidance computer", "apollo program apollo",
            "guidance law", "computer science computer"]
    cfg = TfidfConfig(vocab_bits=10)
    out = run_tfidf(docs, cfg)
    serving.save_index(str(tmp_path), out, cfg, bm25=Bm25Config())
    idx = serving.load_index(str(tmp_path))
    assert idx.bm25_weight is not None
    assert idx.extra["has_bm25"] and idx.extra["bm25_config"]["k1"] == 1.5

    with serving.TfidfServer(idx, serving.ServeConfig(top_k=4)) as srv:
        scores, docs_idx = srv.query(["apollo"], ranker="bm25")
        qt, qw = srv.make_query(["apollo"])
        qvec = np.zeros(idx.vocab_size, idx.weight.dtype)
        np.add.at(qvec, qt, qw)
        res = tops.TfidfResult(
            doc=jnp.asarray(idx.doc), term=jnp.asarray(idx.term),
            weight=jnp.asarray(idx.bm25_weight),
            n_pairs=jnp.asarray(idx.nnz),
            valid=jnp.ones(idx.nnz, idx.weight.dtype),
            idf=jnp.asarray(idx.idf), df=jnp.asarray(idx.df),
        )
        want_s, want_i = tops.score_query(
            res, jnp.asarray(qvec), n_docs=idx.n_docs, k=4
        )
        assert scores.tobytes() == np.asarray(want_s).tobytes()
        assert docs_idx.tobytes() == np.asarray(want_i).tobytes()
        # and the two rankers genuinely differ on this corpus
        t_scores, _ = srv.query(["apollo"], ranker="tfidf")
        assert t_scores.tobytes() != scores.tobytes()


def test_bm25_ranker_refused_without_weights(tmp_path):
    from page_rank_and_tfidf_using_apache_spark_tpu import serving

    docs = ["a b c", "b c d"]
    cfg = TfidfConfig(vocab_bits=8)
    out = run_tfidf(docs, cfg)
    serving.save_index(str(tmp_path), out, cfg)  # no bm25=
    idx = serving.load_index(str(tmp_path))
    assert idx.bm25_weight is None
    with serving.TfidfServer(idx, serving.ServeConfig(top_k=2)) as srv:
        with pytest.raises(ValueError, match="no BM25 weights"):
            srv.submit(["a"], ranker="bm25")
        with pytest.raises(ValueError, match="unknown ranker"):
            srv.submit(["a"], ranker="pagerank")
