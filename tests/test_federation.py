"""Fleet observability plane (ISSUE 19): exact cross-process metrics
federation, scrape staleness, and burn-rate replica autoscaling.

The tentpole guarantee pinned here is **federation exactness**: merging
every replica's exported mergeable into a fresh hub produces the SAME
numbers as one hub that observed the union of all their events — counts,
sums, totals and budget tallies exactly; quantiles identically (both
sides bucket into the same geometric bins).  A property-based version
runs when ``hypothesis`` is installed; a seeded random sweep covers the
same invariant unconditionally.

The staleness/chaos units pin the scrape contract: a partitioned replica
is *labeled* stale and keeps its last-known contribution — never dropped
from the aggregate, never able to block a board read — and recovery
clears the label.  The autoscaler units pin the control-loop decision
table (burn up, idle down, cooldown, hysteresis bounds) against injected
fleet snapshots, and the slow stepped-load soak drives the whole loop
end-to-end: 1 -> N -> 1 with a dropped=0 / double_served=0 audit across
the scale events.
"""

from __future__ import annotations

import importlib.util
import time
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.obs.federation import (
    FleetHub,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import (
    MetricsHub,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos

REPO = Path(__file__).resolve().parents[1]

try:  # the property version needs hypothesis; the sweep below does not
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False


def _tool(name: str):
    spec = importlib.util.spec_from_file_location(
        f"fed_test_{name}", REPO / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------- federation exactness


_HUB_ARGS = dict(window_s=60.0, slots=30, latency_slo_s=0.05,
                 availability_target=0.99)


def _assert_close(a, b, path=""):
    """Recursive numeric equality: ints/bools exact via approx-with-0-rel
    anyway; floats to within summation-order + snapshot-rounding noise."""
    if isinstance(a, dict):
        assert set(a) == set(b), f"{path}: keys {set(a)} vs {set(b)}"
        for k in a:
            _assert_close(a[k], b[k], f"{path}.{k}")
    elif isinstance(a, (int, float)) and not isinstance(a, bool):
        assert b == pytest.approx(a, rel=1e-6, abs=2e-4), (
            f"{path}: {a} vs {b}"
        )
    else:
        assert a == b, f"{path}: {a!r} vs {b!r}"


def _assert_union_equals_merge(hubs, union, clk):
    merged = MetricsHub(clock=clk, **_HUB_ARGS)
    for h in hubs:
        merged.merge_mergeable(h.to_mergeable())
    ms, us = merged.snapshot(), union.snapshot()
    for section in ("latency_s", "queue_wait_s", "counters", "budgets",
                    "gauges"):
        _assert_close(us.get(section), ms.get(section), section)


def _drive_random(rng, hubs, union, clk):
    names = ("serve.cache_hits", "ingest.chunks", "retry")
    for _ in range(int(rng.integers(100, 300))):
        clk.t += float(rng.uniform(0.0, 0.05))
        k = int(rng.integers(0, len(hubs)))
        roll = rng.random()
        if roll < 0.6:
            total_s = float(rng.lognormal(-4.0, 1.2))
            ok = bool(rng.random() > 0.1)
            q = (float(rng.uniform(0.0, 0.01))
                 if rng.random() > 0.5 else None)
            hubs[k].observe_request(total_s, ok=ok, queue_wait_s=q)
            union.observe_request(total_s, ok=ok, queue_wait_s=q)
        elif roll < 0.9:
            name = names[int(rng.integers(0, len(names)))]
            n = float(rng.integers(1, 5))
            hubs[k].count(name, n)
            union.count(name, n)
        else:
            # per-replica gauge names: last-write-wins has no cross-
            # replica ordering to disagree on
            v = float(rng.uniform(0.0, 1.0))
            hubs[k].gauge(f"g{k}", v)
            union.gauge(f"g{k}", v)


def test_federation_exactness_random_sweep():
    """Merged replicas == one union-fed hub, across seeded random mixes
    of requests/errors/counters/gauges on 2-4 replica hubs."""
    for seed in range(8):
        rng = np.random.default_rng(seed)
        clk = FakeClock(100.0)
        hubs = [MetricsHub(clock=clk, **_HUB_ARGS)
                for _ in range(int(rng.integers(2, 5)))]
        union = MetricsHub(clock=clk, **_HUB_ARGS)
        _drive_random(rng, hubs, union, clk)
        _assert_union_equals_merge(hubs, union, clk)


if HAVE_HYPOTHESIS:

    @settings(max_examples=30, deadline=None)
    @given(st.lists(
        st.tuples(
            st.integers(min_value=0, max_value=2),        # replica
            st.floats(min_value=1e-4, max_value=2.0,
                      allow_nan=False, allow_infinity=False),  # latency s
            st.booleans(),                                 # ok
            st.floats(min_value=0.0, max_value=0.05,
                      allow_nan=False, allow_infinity=False),  # dt
        ),
        min_size=1, max_size=200,
    ))
    def test_federation_exactness_property(events):
        clk = FakeClock(100.0)
        hubs = [MetricsHub(clock=clk, **_HUB_ARGS) for _ in range(3)]
        union = MetricsHub(clock=clk, **_HUB_ARGS)
        for k, total_s, ok, dt in events:
            clk.t += dt
            hubs[k].observe_request(total_s, ok=ok)
            union.observe_request(total_s, ok=ok)
        _assert_union_equals_merge(hubs, union, clk)

else:

    @pytest.mark.skip(reason="hypothesis not installed; "
                             "random-sweep fallback covers exactness")
    def test_federation_exactness_property():
        pass


def test_merge_rejects_mismatched_window():
    clk = FakeClock()
    a = MetricsHub(window_s=60.0, clock=clk)
    b = MetricsHub(window_s=30.0, clock=clk)
    b.observe_request(0.01, ok=True)
    with pytest.raises(ValueError, match="window_s mismatch"):
        a.merge_mergeable(b.to_mergeable())


# ------------------------------------------------- scrape-and-merge hub


class _StubFleet:
    """N replica hubs behind an injectable fetch: no HTTP, a FakeClock,
    and a per-replica kill switch for partition scenarios."""

    def __init__(self, n: int = 2, *, scrape_s: float = 1.0):
        self.clk = FakeClock(100.0)
        self.hubs = {str(i): MetricsHub(clock=self.clk, **_HUB_ARGS)
                     for i in range(n)}
        self.alive = {r: True for r in self.hubs}
        self.fleet = FleetHub(scrape_s=scrape_s, clock=self.clk,
                              fetch=self._fetch, **_HUB_ARGS)
        for r in self.hubs:
            self.fleet.register(r, f"http://stub/{r}")

    def _fetch(self, url: str) -> dict:
        r = url.rsplit("/", 1)[-1]
        if not self.alive[r]:
            raise OSError(f"replica {r} unreachable")
        return self.hubs[r].snapshot()


def test_scrape_staleness_labels_never_drops():
    sf = _StubFleet(2, scrape_s=1.0)  # stale after 3.0s
    sf.hubs["0"].observe_request(0.01, ok=True)
    sf.hubs["1"].observe_request(0.02, ok=True)
    assert sf.fleet.scrape_once() == {"0": True, "1": True}
    snap = sf.fleet.snapshot()
    assert snap["fleet"]["stale"] == []
    assert snap["counters"]["serve.requests"]["total"] == 2

    # replica 1 partitions: scrapes fail, age grows past 3 periods
    sf.alive["1"] = False
    for _ in range(4):
        sf.clk.t += 1.0
        sf.fleet.scrape_once()
    snap = sf.fleet.snapshot()
    assert snap["fleet"]["replicas"] == ["0", "1"]  # labeled, NOT dropped
    assert snap["fleet"]["stale"] == ["1"]
    assert snap["fleet"]["per_replica"]["1"]["stale"] is True
    assert snap["fleet"]["scrape_errors"] >= 4
    # the aggregate keeps replica 1's last-known contribution
    assert snap["counters"]["serve.requests"]["total"] == 2
    assert snap["gauges"]["fed_stale_replicas"] == 1.0
    assert snap["gauges"]["fed_staleness_s_max"] >= 3.0

    # recovery: one good scrape clears the label
    sf.alive["1"] = True
    sf.fleet.scrape_once()
    assert sf.fleet.snapshot()["fleet"]["stale"] == []


def test_merge_under_churn():
    """Replicas joining and draining between scrapes: a deregistered
    replica's contribution leaves with it, a layout-drifted replica is a
    recorded per-replica merge error, and the board never raises.

    Runs under its own (empty) chaos plan: the exact counter arithmetic
    below is the point of the test, and an ambient ``fed_scrape`` fault
    (the tools/chaos.sh gate) would CORRECTLY drop a first-scrape
    contribution — that containment behavior has its own test right
    below and a full-fabric scenario in the gate itself."""
    with chaos.inject(""):
        sf = _StubFleet(3)
        for i, r in enumerate(sf.hubs):
            for _ in range(i + 1):
                sf.hubs[r].observe_request(0.01, ok=True)
        sf.fleet.scrape_once()
        assert (sf.fleet.snapshot()["counters"]["serve.requests"]["total"]
                == 1 + 2 + 3)

        # drain replica 0: its 1 request leaves the aggregate
        sf.fleet.deregister("0")
        snap = sf.fleet.snapshot()
        assert snap["fleet"]["replicas"] == ["1", "2"]
        assert snap["counters"]["serve.requests"]["total"] == 2 + 3

        # a mixed-version replica whose mergeable has a different window
        # is a per-replica merge error, not a dead board
        sf.hubs["3"] = MetricsHub(window_s=30.0, clock=sf.clk)
        sf.hubs["3"].observe_request(0.01, ok=True)
        sf.alive["3"] = True
        sf.fleet.register("3", "http://stub/3")
        sf.fleet.scrape_once()
        snap = sf.fleet.snapshot()
        assert "3" in snap["fleet"]["merge_errors"]
        assert snap["counters"]["serve.requests"]["total"] == 2 + 3

        # churn race: a replica deregistered mid-scrape must not resurrect
        sf.fleet.deregister("3")
        assert "3" not in sf.fleet.snapshot()["fleet"]["replicas"]


def test_scrape_chaos_never_blocks_the_board():
    """``fed_scrape`` faults are contained: a partition marks scrapes
    failed (stale labeling, last-known aggregate), a hang costs at most
    the watchdog budget, and ``snapshot()`` stays served throughout —
    the routing-path half of this contract runs full-fabric in
    tools/chaos.sh and the slow soak below."""
    sf = _StubFleet(2)
    sf.hubs["0"].observe_request(0.01, ok=True)
    sf.fleet.scrape_once()
    base_errors = sf.fleet.snapshot()["fleet"]["scrape_errors"]
    assert base_errors == 0

    with chaos.inject("fed_scrape:net_partition@1+"):
        ok = sf.fleet.scrape_once()
        assert ok == {"0": False, "1": False}
        snap = sf.fleet.snapshot()  # board still serves, last-known kept
        assert snap["counters"]["serve.requests"]["total"] == 1
        assert snap["fleet"]["scrape_errors"] == base_errors + 2
    sf.clk.t += 10.0
    assert sf.fleet.snapshot()["fleet"]["stale"] == ["0", "1"]

    with chaos.inject("fed_scrape:net_hang@1+:100"):
        t0 = time.perf_counter()
        sf.fleet.scrape_once()
        # each hung scrape returns within the watchdog deadline, never
        # wedges the calling thread indefinitely
        assert time.perf_counter() - t0 < 2.0 * (sf.fleet.timeout_s + 1.0)

    sf.fleet.scrape_once()  # chaos lifted: clean recovery
    assert sf.fleet.snapshot()["fleet"]["stale"] == []


# ------------------------------------------------------ autoscaler units


class _StubFabric:
    """replica_ids/scale_up/scale_down surface driven by injected
    snapshots — the Autoscaler never touches real processes here."""

    def __init__(self, n: int = 1):
        self.fleet = object()  # federation present; tick() gets snaps
        self._n = n

    def replica_ids(self):
        return list(range(self._n))

    def scale_up(self, k: int = 1) -> int:
        self._n += k
        return k

    def scale_down(self, k: int = 1) -> int:
        self._n -= k
        return k


def _scaler(n=1, **cfg_over):
    from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
    clk = FakeClock(100.0)
    cfg = fabric.AutoscaleConfig(**{
        "min_replicas": 1, "max_replicas": 3, "cooldown_s": 10.0,
        "idle_hold_s": 5.0, **cfg_over})
    return fabric.Autoscaler(_StubFabric(n), cfg, clock=clk), clk


_BURN = {"budgets": {"availability": {"burn_rate": 10.0}}}
_IDLE: dict = {}


def test_autoscaler_scales_up_on_burn_and_respects_cooldown():
    sc, clk = _scaler(1)
    assert sc.tick(_BURN) == "up"
    assert len(sc.fabric.replica_ids()) == 2
    assert sc.tick(_BURN) == "hold"  # cooling
    clk.t += 11.0
    assert sc.tick(_BURN) == "up"
    assert sc.tick(dict(_BURN)) == "hold"  # at max after cooldown too
    clk.t += 11.0
    assert sc.tick(_BURN) == "hold"  # at_max
    assert len(sc.fabric.replica_ids()) == 3
    assert sc.stats()["ups"] == 2 and sc.stats()["flaps"] == 0


def test_autoscaler_scales_down_only_after_idle_hold():
    sc, clk = _scaler(2, cooldown_s=0.0)
    assert sc.tick(_IDLE) == "hold"  # idle starts, hold not yet served
    clk.t += 4.9
    assert sc.tick(_IDLE) == "hold"
    clk.t += 0.2
    assert sc.tick(_IDLE) == "down"
    assert len(sc.fabric.replica_ids()) == 1
    clk.t += 6.0
    assert sc.tick(_IDLE) == "hold"  # at_min, never below
    assert sc.stats()["downs"] == 1


def test_autoscaler_pressure_interrupts_idle_and_counts_flaps():
    sc, clk = _scaler(1, cooldown_s=1.0, idle_hold_s=2.0)
    assert sc.tick(_BURN) == "up"
    clk.t += 1.5
    assert sc.tick(_IDLE) == "hold"  # idle clock starts
    clk.t += 2.5
    assert sc.tick(_IDLE) == "down"
    assert sc.stats()["flaps"] == 1  # up -> down reversal
    clk.t += 1.5
    # fresh pressure re-arms the idle hold: burn then idle again
    assert sc.tick(_BURN) == "up"
    assert sc.stats()["flaps"] == 2
    clk.t += 1.1
    assert sc.tick(_IDLE) == "hold"  # must re-serve the full idle hold
    clk.t += 1.0
    assert sc.tick(_IDLE) == "hold"


# ------------------------------------- stepped-load autoscale fleet soak


@pytest.mark.slow
def test_fleet_soak_autoscale_scenario(tmp_path):
    """The ISSUE 19 acceptance scenario end-to-end: stepped load against
    a real replica fleet scales 1 -> 2 on measured burn and back to 1 on
    sustained idle, with a dropped=0 / double_served=0 router audit
    across both scale events, and the autoscale timeline + fleet SLO
    rendered by trace_report from the run's trace."""
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.soak import (
        FleetSoakConfig,
        run_fleet_soak,
    )

    trace_dir = tmp_path / "trace"
    with obs.run("fedsoak", trace_dir=str(trace_dir)) as r:
        rec = run_fleet_soak(FleetSoakConfig(
            duration_s=32.0, qps=10.0, clients=2, replicas=2,
            rebuild_every_s=8.0, autoscale=True,
            step_at_s=5.0, idle_at_s=14.0, cooldown_s=3.0,
            fleet_window_s=7.0,
        ))
    a = rec["autoscale"]
    assert a is not None
    assert a["ups"] >= 1 and a["scale_ups"] >= 1
    assert a["downs"] >= 1 and a["scale_downs"] >= 1
    assert a["flaps"] <= a["ups"] + a["downs"] - 1
    assert a["federation"]["replicas"] == 1  # back at min after idle
    assert a["federation"]["scrapes"] > 0
    assert rec["dropped"] == 0 and rec["double_served"] == 0
    assert rec["requests"] > 10

    rep = _tool("trace_report").report(r.trace_path)
    assert rep["autoscale"] is not None
    assert rep["autoscale"]["ups"] >= 1 and rep["autoscale"]["downs"] >= 1
    assert rep["slo"]["autoscale"]["scale_ups"] >= 1
    assert rep["slo"]["dropped"] == 0
