"""Live SLO telemetry (ISSUE 11): bounded-memory streaming histograms,
rolling windows, error budgets, the bus-fed hub, and the HTTP exporter.

The two satellite guarantees pinned here:

- **O(bins), not O(events)**: a 10^6-event synthetic feed leaves the
  histogram state exactly as large as after the first event — the
  unbounded-memory risk of the old sample-retaining ``Aggregates`` is a
  regression test now.
- **Online-quantile accuracy**: streaming p50/p99 agree with exact numpy
  quantiles to within one geometric bin (relative error <= growth - 1)
  on adversarial distributions — heavy tails, bimodal spikes, constants,
  out-of-range values.
"""

from __future__ import annotations

import importlib.util
import json
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.obs.export import (
    MetricsExporter,
    metrics_port_from_env,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import (
    ErrorBudget,
    MetricsHub,
    RollingHistogram,
    StreamingHistogram,
    TelemetrySink,
    WindowedCounter,
)

REPO = Path(__file__).resolve().parents[1]


def _tool(name: str):
    spec = importlib.util.spec_from_file_location(
        f"slo_test_{name}", REPO / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ------------------------------------------------- online quantile accuracy


GROWTH = 1.1


def _check_quantiles(values: np.ndarray, rel_tol: float = GROWTH - 1 + 0.03):
    """Streaming p50/p99 within one bin (plus nearest-rank slack) of the
    exact numpy quantiles."""
    h = StreamingHistogram(growth=GROWTH)
    h.observe_many(values)
    for p in (0.50, 0.95, 0.99):
        exact = float(np.quantile(values, p))
        approx = h.quantile(p)
        assert approx is not None
        assert abs(approx - exact) <= rel_tol * exact + 1e-12, (
            f"p{int(p * 100)}: {approx} vs exact {exact}"
        )


def test_quantile_accuracy_lognormal():
    rng = np.random.default_rng(0)
    _check_quantiles(rng.lognormal(-3.0, 1.2, 50_000))


def test_quantile_accuracy_heavy_tail():
    rng = np.random.default_rng(1)
    _check_quantiles(1e-3 * (1.0 + rng.pareto(1.5, 50_000)))


def test_quantile_accuracy_bimodal():
    rng = np.random.default_rng(2)
    fast = rng.normal(2e-3, 1e-4, 45_000)
    slow = rng.normal(1.0, 5e-2, 5_000)  # the retry-spike mode
    _check_quantiles(np.abs(np.concatenate([fast, slow])))


def test_quantile_constant_distribution_is_exact():
    h = StreamingHistogram(growth=GROWTH)
    h.observe_many(np.full(10_000, 0.0421))
    # every quantile of a constant stream is the constant, exactly
    # (bin midpoint clamps into the exact [min, max] observed range)
    for p in (0.01, 0.5, 0.99):
        assert h.quantile(p) == pytest.approx(0.0421, abs=0.0)


def test_quantile_out_of_range_values_clamp():
    h = StreamingHistogram(lo=1e-4, hi=1e2, growth=GROWTH)
    h.observe_many(np.array([1e-9] * 50 + [1e9] * 50))
    assert h.quantile(0.25) == pytest.approx(1e-9)  # underflow -> exact min
    assert h.quantile(0.99) == pytest.approx(1e9)  # overflow -> exact max
    snap = h.snapshot()
    assert snap["min"] == pytest.approx(1e-9)
    assert snap["max"] == pytest.approx(1e9)


def test_quantile_order_independence():
    rng = np.random.default_rng(3)
    vals = rng.lognormal(-4, 1.0, 20_000)
    a = StreamingHistogram(growth=GROWTH)
    b = StreamingHistogram(growth=GROWTH)
    a.observe_many(vals)
    b.observe_many(np.sort(vals)[::-1])  # adversarial arrival order
    assert a.quantile(0.99) == b.quantile(0.99)
    assert a.snapshot() == b.snapshot()


# ------------------------------------------------------- bounded memory


def test_histogram_memory_is_o_bins_over_1e6_events():
    """The soak-length regression: 10^6 observations leave the histogram
    state byte-identical in size to after the first one."""
    rng = np.random.default_rng(4)
    h = StreamingHistogram()
    h.observe(0.01)
    bytes_at_1 = h.approx_bytes()
    h.observe_many(rng.lognormal(-3, 1.5, 1_000_000))
    assert h.count == 1_000_001
    assert h.approx_bytes() == bytes_at_1  # no per-event storage, ever
    # and the state really is just the fixed bin array
    assert h._counts.shape == (h.bins.n_slots,)
    assert h.bins.n_slots < 1024


def test_aggregates_histogram_bounded_and_exact_over_1e6_events():
    """The run-end Aggregates ride the same instrument: feed 10^6 events
    through the public histogram() path; count/sum/min/max/mean stay
    exact, quantiles are bin-accurate, and memory does not grow."""
    agg = obs.Aggregates()
    rng = np.random.default_rng(5)
    vals = rng.lognormal(-5, 1.0, 1_000_000)
    for v in vals[:1000]:
        agg.histogram("lat", float(v))
    bytes_early = agg._hists["lat"].approx_bytes()
    # the remaining ~10^6 go through the same observe() path, vectorized
    # per-article of the instrument's own API to keep the test fast
    agg._hists["lat"].observe_many(vals[1000:])
    assert agg._hists["lat"].approx_bytes() == bytes_early
    s = agg.summary()["histograms"]["lat"]
    assert s["count"] == 1_000_000
    assert s["min"] == pytest.approx(float(vals.min()))
    assert s["max"] == pytest.approx(float(vals.max()))
    assert s["mean"] == pytest.approx(float(vals.mean()), rel=1e-9)
    assert s["p50"] == pytest.approx(float(np.quantile(vals, 0.5)), rel=0.13)
    assert s["p99"] == pytest.approx(float(np.quantile(vals, 0.99)), rel=0.13)
    # the legacy summary keys trace_report renders are all still there
    assert {"count", "sum", "min", "max", "mean", "p50", "p90"} <= set(s)


# ---------------------------------------------------- rolling windows


def test_rolling_histogram_expires_old_slots():
    clk = FakeClock()
    r = RollingHistogram(window_s=10.0, slots=10, clock=clk)
    for i in range(50):
        clk.t = i * 0.1  # first 5 seconds: fast requests
        r.observe(0.001)
    clk.t = 8.0
    for _ in range(10):  # a late slow burst
        r.observe(1.0)
    assert r.window_count() == 60
    p99 = r.quantile(0.99)
    assert p99 == pytest.approx(1.0, rel=0.15)
    # advance past the window: the early fast mode expires, p50 is now slow
    clk.t = 16.0
    assert r.window_count() == 10
    assert r.quantile(0.50) == pytest.approx(1.0, rel=0.15)
    clk.t = 40.0
    assert r.window_count() == 0
    assert r.quantile(0.99) is None


def test_windowed_counter_rate():
    clk = FakeClock()
    c = WindowedCounter(window_s=10.0, slots=10, clock=clk)
    for i in range(100):
        clk.t = i * 0.1  # 10 adds/sec for 10s
        c.add()
    assert c.total() == 100
    assert c.rate() == pytest.approx(10.0, rel=0.15)
    clk.t = 25.0  # everything expired
    assert c.window_sum() == 0.0
    assert c.total() == 100  # cumulative survives


def test_error_budget_burn():
    clk = FakeClock()
    b = ErrorBudget(0.99, window_s=10.0, slots=10, clock=clk)
    for i in range(1000):
        clk.t = i * 0.01
        b.observe(good=(i % 100) != 0)  # exactly the allowed 1% bad
    s = b.snapshot()
    assert s["total"] == 1000 and s["bad"] == 10
    assert s["allowed"] == pytest.approx(10.0)
    assert s["consumed_frac"] == pytest.approx(1.0)
    assert s["burn_rate"] == pytest.approx(1.0, rel=0.2)
    # a hard outage: 50 straight failures => burn explodes
    for i in range(50):
        clk.t = 10.0 + i * 0.01
        b.observe(good=False)
    assert b.snapshot()["burn_rate"] > 5.0


# ------------------------------------------------- hub fed from the bus


def test_bus_feeds_hub_with_zero_call_site_wiring():
    """Attach a TelemetrySink and publish the events the serving/ingest
    paths already emit: the hub's window quantiles, counters and budgets
    light up with no publisher changes."""
    hub = MetricsHub(window_s=30.0, latency_slo_s=0.25,
                     availability_target=0.999)
    sink = TelemetrySink(hub)
    obs.bus().attach(sink)
    try:
        for i in range(40):
            obs.emit("serve_request", cache="miss", queue_wait_s=0.002,
                     total_s=0.010 + 0.0005 * i, batch=4)
        obs.emit("serve_request", cache="miss", queue_wait_s=0.0,
                 total_s=0.4, batch=1, error="ChaosError: boom")
        obs.emit("chaos", site="serve_dispatch", fault="lost", call=7)
        obs.emit("retry", site="serve_dispatch", attempt=1, error="x")
        obs.emit("metric", event="chunk", chunk=0, tokens=512, secs=0.01)
    finally:
        obs.bus().detach(sink)
    snap = hub.snapshot()
    win = snap["latency_s"]["window"]
    assert win["count"] == 40  # the error's latency is not service time
    assert 0.01 <= win["p99"] <= 0.05
    ctr = {k: v["total"] for k, v in snap["counters"].items()}
    assert ctr["serve.requests"] == 41 and ctr["serve.errors"] == 1
    assert ctr["chaos.injections"] == 1 and ctr["chaos.losses"] == 1
    assert ctr["retry"] == 1
    assert ctr["ingest.chunks"] == 1 and ctr["ingest.tokens"] == 512
    avail = snap["budgets"]["availability"]
    assert avail["bad"] == 1 and avail["total"] == 41
    lat_budget = snap["budgets"]["latency"]
    assert lat_budget["bad"] == 1  # the failed request also missed latency


# ----------------------------------------------------- HTTP exporter


def test_exporter_serves_snapshot_and_prometheus():
    hub = MetricsHub(window_s=30.0)
    hub.observe_request(0.017, ok=True, queue_wait_s=0.001)
    hub.count("serve.requests")
    hub.gauge("h2d_overlap_frac", 0.9)
    with MetricsExporter(hub, port=0) as ex:
        assert ex.port > 0
        with urllib.request.urlopen(ex.url + "/snapshot.json",
                                    timeout=5) as resp:
            snap = json.loads(resp.read())
        assert snap["latency_s"]["window"]["count"] == 1
        assert snap["gauges"]["h2d_overlap_frac"] == 0.9
        with urllib.request.urlopen(ex.url + "/metrics", timeout=5) as resp:
            text = resp.read().decode()
        assert "graft_serve_latency_seconds" in text
        assert "graft_h2d_overlap_frac 0.9" in text
        with urllib.request.urlopen(ex.url + "/healthz", timeout=5) as resp:
            assert resp.read() == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(ex.url + "/nope", timeout=5)


def test_metrics_port_knob(monkeypatch):
    monkeypatch.delenv("GRAFT_METRICS_PORT", raising=False)
    assert metrics_port_from_env() is None
    monkeypatch.setenv("GRAFT_METRICS_PORT", "0")
    assert metrics_port_from_env() == 0
    monkeypatch.setenv("GRAFT_METRICS_PORT", "9109")
    assert metrics_port_from_env() == 9109


# ----------------------------------------------------- slo_watch renderer


def test_slo_watch_renders_live_endpoint():
    """The terminal watcher end-to-end: fetch a live exporter's snapshot
    and render the board (stdlib-only module, loaded from tools/)."""
    watch = _tool("slo_watch")
    hub = MetricsHub(window_s=30.0, latency_slo_s=0.25,
                     availability_target=0.999)
    for i in range(20):
        hub.observe_request(0.004 + 0.0001 * i, ok=True)
    hub.observe_request(0.4, ok=False)
    with MetricsExporter(hub, port=0) as ex:
        snap = watch.fetch(ex.url)
    board = watch.render(snap)
    assert "serve latency ms" in board
    assert "p99" in board
    assert "budget[availability]" in board
    assert "serve.errors" in board
    # and the CLI --once path over the same endpoint
    with MetricsExporter(hub, port=0) as ex:
        assert watch.main(["--url", ex.url, "--once"]) == 0
