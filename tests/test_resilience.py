"""Resilience runtime tests (ISSUE 2): deterministic fault injection
(resilience/chaos.py) driving the retry/deadline executor
(resilience/executor.py) and the resumable execution paths end to end.

The acceptance bar: with GRAFT_CHAOS-style injection mid-run, PageRank
resumes from checkpoint and converges to the same ranks as an
uninterrupted run; streaming TF-IDF resume reprocesses ZERO completed
chunks (asserted via chunk-event counts); bench.py under a forced tfidf
timeout emits a ``"partial": true`` record with nonzero chunks completed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import (
    PageRankConfig,
    ResilienceExhausted,
    TfidfConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.io import synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.io.text import iter_corpus_chunks
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    resume_point,
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder

REPO = Path(__file__).resolve().parents[1]


# ------------------------------------------------------------- chaos layer


def test_parse_plan_schedules():
    plan = chaos.parse_plan("a:fail@3; b:lost@2+ ; c:hang@%4:0.5")
    assert [i.kind for i in plan] == ["fail", "lost", "hang"]
    a, b, c = plan
    assert [a.matches("a", n) for n in (1, 2, 3, 4)] == [False, False, True, False]
    assert [b.matches("b", n) for n in (1, 2, 3)] == [False, True, True]
    assert [c.matches("c", n) for n in (3, 4, 8, 9)] == [False, True, True, False]
    assert c.param == 0.5
    assert not a.matches("other_site", 3)


def test_parse_plan_wildcard_site():
    (inj,) = chaos.parse_plan("*:fail@%2")
    assert inj.matches("anything", 2) and not inj.matches("anything", 3)


@pytest.mark.parametrize(
    "bad", ["nosep", "a:frob@1", "a:fail", "a:fail@0", "a:fail@x",
            "a:fail@%0", "a:fail@5++", "a:fail@%5+", "a:fail@+5"]
)
def test_parse_plan_rejects(bad):
    with pytest.raises(ValueError):
        chaos.parse_plan(bad)


def test_inject_overrides_env_and_counts(monkeypatch):
    monkeypatch.setenv("GRAFT_CHAOS", "s:lost@1")  # would fail immediately
    with chaos.inject("s:fail@2") as plan:
        chaos.on_call("s")  # call 1: no injection under the override
        with pytest.raises(chaos.ChaosError):
            chaos.on_call("s")  # call 2: injected transient
        assert plan.call_count("s") == 2
    # env plan active again after the context exits
    with pytest.raises(chaos.DeviceLostError):
        chaos.on_call("s")


# ---------------------------------------------------------------- executor


def test_backoff_deterministic_and_bounded():
    pol = rx.RetryPolicy(backoff_base_s=0.05, backoff_max_s=0.2)
    d1 = rx.backoff_delay("site", 1, pol)
    assert d1 == rx.backoff_delay("site", 1, pol)  # deterministic
    assert 0.05 <= d1 < 0.075
    assert rx.backoff_delay("site", 10, pol) == 0.2  # capped


def test_transient_classification():
    assert rx.is_transient(chaos.ChaosError("x"))
    assert rx.is_transient(rx.SyncDeadlineExceeded("x"))
    assert rx.is_transient(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert not rx.is_transient(chaos.DeviceLostError("x"))
    assert not rx.is_transient(ValueError("shape mismatch"))


def test_run_guarded_retries_transients():
    calls = []
    pol = rx.RetryPolicy(max_retries=3, backoff_base_s=0.001)
    m = MetricsRecorder()
    with chaos.inject("t1:fail@1;t1:fail@2"):
        out = rx.run_guarded(lambda: calls.append(1) or 42, site="t1",
                             policy=pol, metrics=m)
    assert out == 42
    assert len(calls) == 1  # two injections happened BEFORE fn ran
    assert sum(r.get("event") == "retry" for r in m.records) == 2


def test_run_guarded_persistent_skips_retries_and_uses_fallback():
    pol = rx.RetryPolicy(max_retries=5, backoff_base_s=0.001)
    m = MetricsRecorder()
    with chaos.inject("t2:lost@1+") as plan:
        out = rx.run_guarded(lambda: 1, site="t2", policy=pol, metrics=m,
                             fallback=lambda: "degraded")
    assert out == "degraded"
    assert plan.call_count("t2") == 1  # no retry spent on a lost device
    assert any(r.get("event") == "degraded" for r in m.records)


def test_run_guarded_exhausted_carries_checkpoint(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 7, {"x": np.arange(3)}, "h")
    pol = rx.RetryPolicy(max_retries=1, backoff_base_s=0.001)
    with chaos.inject("t3:fail@1+"):
        with pytest.raises(ResilienceExhausted) as ei:
            rx.run_guarded(lambda: 1, site="t3", policy=pol, checkpoint_dir=d)
    err = ei.value
    assert err.site == "t3" and err.attempts == 2
    assert err.last_checkpoint and err.last_checkpoint.endswith("ckpt_00000007.npz")
    assert isinstance(err.last_error, chaos.ChaosError)


def test_sync_deadline_watchdog_abandons_hung_call():
    pol = rx.RetryPolicy(max_retries=1, backoff_base_s=0.001, deadline_s=0.15)
    t0 = time.perf_counter()
    # call 1 hangs 5s inside the watched thread; the watchdog abandons it
    # and the retry (call 2, uninjected) succeeds.
    with chaos.inject("t4:hang@1:5"):
        out = rx.run_guarded(lambda: "ok", site="t4", policy=pol)
    assert out == "ok"
    assert time.perf_counter() - t0 < 2.0  # nowhere near the 5s hang


# -------------------------------------------------- checkpoint satellites


def test_latest_pointer_write_failure_leaks_no_tmp(tmp_path, monkeypatch):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 1, {"x": np.arange(2)}, "h")
    real_replace = os.replace

    def failing_replace(src, dst):
        if dst.endswith("LATEST"):
            raise OSError("disk full")
        return real_replace(src, dst)

    monkeypatch.setattr(ckpt.os, "replace", failing_replace)
    with pytest.raises(OSError):
        ckpt.save_checkpoint(d, 2, {"x": np.arange(2)}, "h")
    monkeypatch.setattr(ckpt.os, "replace", real_replace)
    assert not [n for n in os.listdir(d) if n.endswith(".tmp")]
    # the previous LATEST still resolves (old pointer, old payload intact)
    step, arrays, _ = ckpt.load_checkpoint(ckpt.latest_checkpoint(d), "h")
    assert step == 1


def test_gc_checkpoints_retention_keeps_latest(tmp_path):
    d = str(tmp_path)
    for s in range(6):
        ckpt.save_checkpoint(d, s, {"x": np.arange(2)}, "h", keep=0)
    deleted = ckpt.gc_checkpoints(d, keep=2)
    kept = sorted(n for n in os.listdir(d) if n.endswith(".npz"))
    assert kept == ["ckpt_00000004.npz", "ckpt_00000005.npz"]
    assert len(deleted) == 4
    assert ckpt.latest_checkpoint(d).endswith("ckpt_00000005.npz")
    with pytest.raises(ValueError):
        ckpt.gc_checkpoints(d, keep=0)


def test_save_checkpoint_default_retention(tmp_path, monkeypatch):
    monkeypatch.setenv("GRAFT_CKPT_KEEP", "3")
    d = str(tmp_path)
    for s in range(10):
        ckpt.save_checkpoint(d, s, {"x": np.arange(2)}, "h")
    assert sum(n.endswith(".npz") for n in os.listdir(d)) == 3


def test_peek_meta_reads_without_arrays(tmp_path):
    d = str(tmp_path)
    path = ckpt.save_checkpoint(d, 5, {"x": np.arange(4)}, "hash5",
                                extra={"n_docs": 9})
    meta = ckpt.peek_meta(path)
    assert meta["step"] == 5 and meta["config_hash"] == "hash5"
    assert meta["extra"] == {"n_docs": 9}


# -------------------------------------------------------- io chunk skipping


def test_iter_corpus_chunks_skip_prefix_keeps_indices():
    docs = [f"d{i}" for i in range(10)]
    plain = list(iter_corpus_chunks(iter(docs), 3))
    skipped = list(iter_corpus_chunks(iter(docs), 3, skip_chunks=2))
    assert len(skipped) == len(plain) == 4
    assert skipped[0] == [] and skipped[1] == []  # placeholders, no strings
    assert skipped[2:] == plain[2:]


def test_iter_corpus_chunks_rejects_rechunked_resume():
    """Resume bookkeeping is in chunk indices: skipping 2 chunks of 3 docs
    when the checkpoint ingested 8 means the chunking changed — refuse."""
    docs = [f"d{i}" for i in range(10)]
    ok = list(iter_corpus_chunks(iter(docs), 3, skip_chunks=2,
                                 expect_skipped_docs=6))
    assert ok[0] == [] and ok[2:] == [["d6", "d7", "d8"], ["d9"]]
    with pytest.raises(ValueError, match="chunking mismatch"):
        list(iter_corpus_chunks(iter(docs), 3, skip_chunks=2,
                                expect_skipped_docs=8))
    with pytest.raises(ValueError, match="corpus ended"):
        list(iter_corpus_chunks(iter(docs[:4]), 3, skip_chunks=4,
                                expect_skipped_docs=12))
    # A checkpoint covering a partial FINAL chunk is legitimate (crash after
    # ingest, during finalize): matching doc counts must not raise.
    tail = list(iter_corpus_chunks(iter(docs), 3, skip_chunks=4,
                                   expect_skipped_docs=10))
    assert tail == [[], [], [], []]


def test_streaming_resume_rejects_rechunked_corpus(tmp_path):
    """Model-side guard: feeding a resume run differently-sized real
    chunks (doc counts that cannot match the checkpoint) fails loudly
    instead of silently re-ingesting documents."""
    chunks = _chunks(6, docs_per_chunk=2)
    cfg = TfidfConfig(vocab_bits=10, prefetch=0, checkpoint_every=1,
                      checkpoint_dir=str(tmp_path / "ck"))
    run_tfidf_streaming(chunks[:4], cfg)  # "crash" after 4 chunks / 8 docs
    docs = [d for c in chunks for d in c]
    rechunked = [docs[i:i + 3] for i in range(0, len(docs), 3)]  # chunks of 3
    with pytest.raises(ValueError, match="chunking mismatch"):
        run_tfidf_streaming(rechunked, cfg, resume=True)


# ------------------------------------------- end-to-end recovery: PageRank


GRAPH_KW = dict(dangling="redistribute", init="uniform", dtype="float32")


def test_pagerank_transient_failure_recovers_identically():
    """(a) A transient dispatch failure mid-PageRank: the executor retries
    and the final ranks match an uninterrupted run to f32 tolerance."""
    g = synthetic_powerlaw(2000, 8000, seed=13)
    cfg = PageRankConfig(iterations=12, **GRAPH_KW)
    base = run_pagerank(g, cfg)
    m = MetricsRecorder()
    with chaos.inject("pagerank_step:fail@1"):
        res = run_pagerank(g, cfg, metrics=m)
    assert any(r.get("event") == "retry" for r in m.records)
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)


def test_pagerank_device_loss_degrades_to_cpu():
    g = synthetic_powerlaw(500, 2000, seed=3)
    cfg = PageRankConfig(iterations=8, **GRAPH_KW)
    base = run_pagerank(g, cfg)
    m = MetricsRecorder()
    with chaos.inject("pagerank_step:lost@1+"):
        res = run_pagerank(g, cfg, metrics=m)
    assert any(r.get("event") == "degraded" for r in m.records)
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)


@pytest.fixture
def fresh_health():
    from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic

    elastic.reset_health()
    yield
    elastic.reset_health()


@pytest.mark.parametrize(
    "site", ["pagerank_delta_sync", "pagerank_ckpt_pull",
             "pagerank_result_pull"],
)
def test_pagerank_single_chip_device_lost_at_pull_sites(tmp_path, site,
                                                        fresh_health):
    """ISSUE 9 carried-forward satellite: a single-chip device loss first
    surfacing at a checkpoint-pull-class site (the delta fetch, the
    checkpoint pull, the final result pull) used to dead-end — the CPU
    rung re-pulled the carry that died with the device.  Now those sites
    walk the same elastic salvage the sharded pull uses: acknowledge the
    loss, reload the newest snapshot, re-run only the uncommitted span on
    the CPU backend, and finish with ranks matching an uninterrupted run."""
    g = synthetic_powerlaw(800, 3200, seed=7)
    base = run_pagerank(g, PageRankConfig(iterations=12, **GRAPH_KW))
    cfg = PageRankConfig(iterations=12, checkpoint_every=4,
                         checkpoint_dir=str(tmp_path / "ck"), **GRAPH_KW)
    m = MetricsRecorder()
    with chaos.inject(f"{site}:device_lost@dev:0"):
        res = run_pagerank(g, cfg, metrics=m)
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert degraded and degraded[0]["ladder"] == "cpu"
    assert "salvage_iter" in degraded[0]  # the elastic salvage, not the
    # legacy pull-the-dead-carry rung
    assert res.iterations == 12
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)


def test_pagerank_single_chip_device_lost_without_checkpoint(fresh_health):
    """The salvage rung without any checkpoint dir: falls back to the
    init vector and re-runs the whole span on CPU — still converging to
    the uninterrupted ranks (nothing to salvage means recompute, not
    fail)."""
    g = synthetic_powerlaw(500, 2000, seed=3)
    cfg = PageRankConfig(iterations=8, **GRAPH_KW)
    base = run_pagerank(g, cfg)
    m = MetricsRecorder()
    with chaos.inject("pagerank_delta_sync:device_lost@dev:0"):
        res = run_pagerank(g, cfg, metrics=m)
    assert any(r.get("event") == "degraded" for r in m.records)
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)


def test_pagerank_exhausted_resumes_from_checkpoint(tmp_path):
    """The full ladder: mid-run device loss with the CPU rung also failing
    -> ResilienceExhausted carrying the checkpoint -> a resume run (no
    chaos) converges to the uninterrupted ranks."""
    g = synthetic_powerlaw(800, 3200, seed=7)
    base = run_pagerank(g, PageRankConfig(iterations=12, **GRAPH_KW))

    ckdir = str(tmp_path / "ck")
    cfg = PageRankConfig(iterations=12, checkpoint_every=4,
                         checkpoint_dir=ckdir, **GRAPH_KW)
    m = MetricsRecorder()
    with chaos.inject("pagerank_step:lost@3+;pagerank_cpu_pull:lost@1+"):
        with pytest.raises(ResilienceExhausted) as ei:
            run_pagerank(g, cfg, metrics=m)
    # segments 1 and 2 completed -> checkpoint at iteration 8 survives
    assert ei.value.last_checkpoint is not None
    assert ckpt.peek_meta(ei.value.last_checkpoint)["step"] == 8

    m2 = MetricsRecorder()
    res = run_pagerank(g, cfg, metrics=m2, resume=True)
    resumed = [r for r in m2.records if r.get("event") == "resume"]
    assert resumed and resumed[0]["start_iter"] == 8
    assert res.iterations == 12
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)


def test_pagerank_sharded_exhausted_then_resume(tmp_path, monkeypatch):
    """With the elastic mesh-shrink rung disabled (GRAFT_ELASTIC=0 — the
    operator off-switch), the sharded path keeps its pre-elastic
    contract: exhaustion surfaces the checkpoint, and a single-chip
    resume finishes to the same ranks.  (With elastic on, device loss is
    survived in-run instead — tests/test_elastic.py.)"""
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        run_pagerank_sharded,
    )

    monkeypatch.setenv("GRAFT_ELASTIC", "0")
    g = synthetic_powerlaw(600, 2400, seed=11)
    base = run_pagerank(g, PageRankConfig(iterations=9, **GRAPH_KW))
    ckdir = str(tmp_path / "ck")
    cfg = PageRankConfig(iterations=9, checkpoint_every=3,
                         checkpoint_dir=ckdir, **GRAPH_KW)
    with chaos.inject("pagerank_step:lost@2+"):
        with pytest.raises(ResilienceExhausted) as ei:
            run_pagerank_sharded(g, cfg, n_devices=4)
    assert ei.value.last_checkpoint is not None
    res = run_pagerank(g, cfg, resume=True)  # degrade: finish single-chip
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)


# ------------------------------------------- end-to-end recovery: TF-IDF


def _chunks(n_chunks: int, docs_per_chunk: int = 2) -> list[list[str]]:
    docs = [f"tok{i} tok{i % 5} shared word extra{i % 3}"
            for i in range(n_chunks * docs_per_chunk)]
    return [docs[i:i + docs_per_chunk]
            for i in range(0, len(docs), docs_per_chunk)]


def test_tfidf_chunk25_failure_resumes_with_zero_reprocessing(tmp_path):
    """(b) A chunk-25 failure in streaming TF-IDF: chunks 0-24 are not
    reprocessed (chunk-event counts prove it) and the resumed output
    matches the uninterrupted run."""
    chunks = _chunks(26)
    base_cfg = TfidfConfig(vocab_bits=10, prefetch=0)
    full = run_tfidf_streaming(chunks, base_cfg)

    cfg = TfidfConfig(vocab_bits=10, prefetch=0, checkpoint_every=1,
                      checkpoint_dir=str(tmp_path / "ck"))
    m1 = MetricsRecorder()
    with chaos.inject("tfidf_chunk_sync:lost@26"):  # the 26th drain = chunk 25
        with pytest.raises(ResilienceExhausted) as ei:
            run_tfidf_streaming(chunks, cfg, metrics=m1)
    done_before = [r["chunk"] for r in m1.records if r.get("event") == "chunk"]
    assert done_before == list(range(25))  # chunks 0-24 landed, then the kill
    assert ei.value.last_checkpoint is not None
    assert ckpt.peek_meta(ei.value.last_checkpoint)["step"] == 25
    assert resume_point(cfg) == 25

    m2 = MetricsRecorder()
    res = run_tfidf_streaming(chunks, cfg, metrics=m2, resume=True)
    done_after = [r["chunk"] for r in m2.records if r.get("event") == "chunk"]
    assert done_after == [25]  # ZERO completed chunks reprocessed
    assert res.n_docs == full.n_docs
    np.testing.assert_allclose(res.to_dense(), full.to_dense(), atol=1e-6)


def test_tfidf_transient_chunk_failures_are_invisible(tmp_path):
    chunks = _chunks(8)
    full = run_tfidf_streaming(chunks, TfidfConfig(vocab_bits=10, prefetch=0))
    m = MetricsRecorder()
    with chaos.inject("tfidf_chunk_sync:fail@%3"):
        res = run_tfidf_streaming(chunks, TfidfConfig(vocab_bits=10, prefetch=0),
                                  metrics=m)
    assert sum(r.get("event") == "retry" for r in m.records) >= 2
    np.testing.assert_allclose(res.to_dense(), full.to_dense(), atol=1e-6)


def test_tfidf_sharded_loss_then_resume(tmp_path, monkeypatch):
    """Same off-switch contract for sharded TF-IDF: no shrink rung, so a
    persistent loss exhausts with a resumable chunk checkpoint."""
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        run_tfidf_sharded,
    )

    monkeypatch.setenv("GRAFT_ELASTIC", "0")
    chunks = _chunks(12)
    base = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                             n_devices=4)
    cfg = TfidfConfig(vocab_bits=10, checkpoint_every=4,
                      checkpoint_dir=str(tmp_path / "ck"))
    with chaos.inject("tfidf_shard_sync:lost@2+"):
        with pytest.raises(ResilienceExhausted) as ei:
            run_tfidf_sharded(iter(chunks), cfg, n_devices=4)
    assert ei.value.last_checkpoint is not None
    res = run_tfidf_sharded(iter(chunks), cfg, n_devices=4, resume=True)
    assert res.n_docs == base.n_docs
    np.testing.assert_allclose(res.to_dense(), base.to_dense(), atol=1e-6)


def test_tfidf_checkpoint_carries_throughput_accounting(tmp_path):
    cfg = TfidfConfig(vocab_bits=10, prefetch=0, checkpoint_every=2,
                      checkpoint_dir=str(tmp_path / "ck"))
    run_tfidf_streaming(_chunks(6), cfg)
    meta = ckpt.peek_meta(ckpt.latest_checkpoint(cfg.checkpoint_dir))
    assert meta["extra"]["n_docs"] == 12
    assert meta["extra"]["n_tokens"] > 0
    assert meta["extra"]["ingest_secs"] > 0


# ----------------------------------------------- bench.py partial record


def test_bench_forced_tfidf_timeout_emits_partial_record():
    """Acceptance: bench.py under a forced tfidf timeout (chaos hangs every
    chunk drain from the 8th on; the child can never finish) emits a
    ``"partial": true`` record with nonzero chunks completed — instead of
    BENCH_r05's bare TIMEOUT log line and a discarded run."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BENCH_NODES="400", BENCH_EDGES="1600", BENCH_ITERS="2",
        BENCH_IMPLS="segment", BENCH_IMPL_TIMEOUT_S="180",
        BENCH_PROBE_TIMEOUT_S="90",
        BENCH_TFIDF_DOCS="256", BENCH_TFIDF_TOKENS_PER_DOC="30",
        BENCH_TFIDF_CHUNK_DOCS="16",  # -> 16 streaming chunks
        BENCH_TFIDF_PACK_TOKENS="0",  # keep them 16: the cap-filling
        # re-pack would fold this tiny corpus into ONE chunk and the
        # hang below could never fire mid-stream
        BENCH_TFIDF_CKPT_EVERY="1",   # chunk-granular resume for this test
        BENCH_TFIDF_TIMEOUT_S="30", BENCH_TFIDF_RETRIES="1",
        # every chunk drain from the 8th on hangs "forever": the child
        # checkpoints 7 chunks then wedges; the resume retry checkpoints 7
        # more from chunk 7 and wedges again
        GRAFT_CHAOS="tfidf_chunk_sync:hang@8+:600",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True, text=True, timeout=560, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    tfidf = record["extra"].get("tfidf")
    assert tfidf, record
    assert tfidf["partial"] is True
    assert tfidf["chunks_completed"] > 0
    assert tfidf["tokens_completed"] > 0
    assert tfidf["stream_tokens_per_sec_so_far"] > 0
    # the resume retry made it strictly past the first child's wedge point
    assert tfidf["chunks_completed"] >= 8
