"""Pure-python oracle reproducing the canonical Spark example's semantics.

The reference's PageRank is fingerprinted by BASELINE.json:5 as the
``links.join(ranks).flatMap(computeContribs).reduceByKey(add)`` chain — the
Spark distribution's own example program.  pyspark is not installed here
(SURVEY.md §6), so this module simulates those exact RDD semantics with
dicts: ``distinct().groupByKey()`` adjacency, inner-join contribution
emission, and the shrinking rank key-set (nodes that receive no
contribution drop out of the rank table — SURVEY.md §3.1).
"""

from __future__ import annotations

from collections import defaultdict


def spark_pagerank(
    edges: list[tuple[int, int]], iterations: int, damping: float = 0.85
) -> dict[int, float]:
    """Ranks keyed exactly like the canonical example's final RDD: only
    nodes present after the last ``reduceByKey`` appear."""
    links: dict[int, list[int]] = defaultdict(list)
    for a, b in sorted(set(edges)):  # .distinct().groupByKey()
        links[a].append(b)
    ranks = {k: 1.0 for k in links}  # links.mapValues(lambda _: 1.0)
    for _ in range(iterations):
        contribs: dict[int, float] = defaultdict(float)
        for src, nbrs in links.items():
            if src in ranks:  # inner join
                c = ranks[src] / len(nbrs)
                for d in nbrs:  # flatMap(computeContribs)
                    contribs[d] += c  # reduceByKey(add)
        ranks = {k: (1.0 - damping) + damping * v for k, v in contribs.items()}
    return dict(ranks)


def spark_tfidf_counts(
    docs: list[list[str]],
) -> tuple[dict[tuple[str, int], int], dict[str, int]]:
    """The reference's two reduceByKey passes over ((term, doc), 1) records:
    returns (term-frequency counts, document frequencies)."""
    tf: dict[tuple[str, int], int] = defaultdict(int)
    for d, tokens in enumerate(docs):
        for t in tokens:
            tf[(t, d)] += 1
    df: dict[str, int] = defaultdict(int)
    for (t, _d) in tf:  # distinct (term, doc) → (term, 1) → reduceByKey
        df[t] += 1
    return dict(tf), dict(df)
