"""dataflow/ core tests (ISSUE 9): primitive units + the port-equivalence
pins that make the PageRank/TF-IDF move onto the dataflow primitives
provably a refactor, not a rewrite.

Pins:
- PageRank ranks through the ported runners match an independent numpy
  power iteration (the pre-port semantics) to 1e-6;
- a PageRank program composed *directly* from the dataflow primitives
  (broadcast_join → graph_combine → iterate) matches ``run_pagerank``;
- streaming TF-IDF (now a thin program over ``chunked_ingest``) is
  byte-equal to the batch pipeline;
- the ``chunked_ingest`` pipeline preserves the drain-before-commit /
  commit-before-checkpoint ordering the donated-carry design requires,
  and chaos through the shared wiring stays invisible.
"""

from __future__ import annotations

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import dataflow
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.partition import (
    PartitionedArray,
)
from page_rank_and_tfidf_using_apache_spark_tpu.io import synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    run_tfidf,
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    PageRankConfig,
    TfidfConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


# ------------------------------------------------------------- primitives


def test_iterate_scan_matches_manual_loop():
    import jax
    import jax.numpy as jnp

    def step(x):
        return 0.5 * x + 1.0

    x0 = jnp.arange(4.0)
    out, iters, delta = jax.jit(
        lambda x: dataflow.iterate(step, x, iterations=5)
    )(x0)
    want = np.arange(4.0)
    for _ in range(5):
        want = 0.5 * want + 1.0
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)
    assert int(iters) == 5
    prev = want * 2 - 2  # state before the last step: want = 0.5*prev + 1
    np.testing.assert_allclose(float(delta), np.abs(want - prev).sum(),
                               rtol=1e-5)


def test_iterate_tol_stops_early_and_zero_iterations():
    import jax

    def step(x):
        return x * 0.0  # one step reaches the fixpoint exactly

    x0 = np.ones(8, np.float32)
    out, iters, delta = jax.jit(
        lambda x: dataflow.iterate(step, x, iterations=50, tol=1e-9)
    )(x0)
    assert int(iters) == 2  # step 1 zeroes, step 2 measures delta 0
    assert float(delta) == 0.0
    _, iters0, delta0 = jax.jit(
        lambda x: dataflow.iterate(step, x, iterations=0)
    )(x0)
    assert int(iters0) == 0 and np.isinf(float(delta0))


def test_segment_combine_ops():
    import jax.numpy as jnp

    vals = jnp.asarray(np.array([5.0, 1.0, 3.0, 2.0, 9.0], np.float32))
    keys = jnp.asarray(np.array([0, 0, 1, 1, 1], np.int32))
    add = dataflow.segment_combine(vals, keys, 3, op="add",
                                   indices_are_sorted=True)
    np.testing.assert_allclose(np.asarray(add), [6.0, 14.0, 0.0])
    mn = dataflow.segment_combine(vals, keys, 3, op="min",
                                  indices_are_sorted=True)
    assert np.asarray(mn)[:2].tolist() == [1.0, 2.0]
    mx = dataflow.segment_combine(vals, keys, 3, op="max")
    assert np.asarray(mx)[:2].tolist() == [5.0, 9.0]
    with pytest.raises(ValueError, match="unknown combine op"):
        dataflow.segment_combine(vals, keys, 3, op="mean")


def test_broadcast_join_is_the_gather():
    import jax.numpy as jnp

    table = jnp.asarray(np.array([10.0, 20.0, 30.0], np.float32))
    keys = jnp.asarray(np.array([2, 0, 2], np.int32))
    np.testing.assert_allclose(
        np.asarray(dataflow.broadcast_join(table, keys)), [30.0, 10.0, 30.0]
    )


def test_partitioned_array_roundtrip_identity_and_relabeled():
    n = 7
    ident = PartitionedArray.identity(n)
    x = np.arange(n, dtype=np.float32)
    put = ident.put(x)
    np.testing.assert_array_equal(put.pull(site="t"), x)

    # relabeled + padded layout (a 'nodes_balanced'-style node_map)
    node_map = np.array([3, 0, 5, 1, 8, 2, 7], np.int64)
    pa = PartitionedArray.from_plan(n, 10, node_map)
    put2 = pa.put(x)
    padded = np.asarray(put2.value)
    assert padded.shape == (10,)
    np.testing.assert_array_equal(padded[node_map], x)
    np.testing.assert_array_equal(put2.pull(site="t"), x)


# ------------------------------------------------- port-equivalence pins


GRAPH_KW = dict(dangling="redistribute", init="uniform", dtype="float32")


def _numpy_pagerank(graph, iters: int, damping: float = 0.85) -> np.ndarray:
    """The pre-port reference semantics as a plain numpy loop."""
    n = graph.n_nodes
    inv = np.where(graph.out_degree > 0,
                   1.0 / np.maximum(graph.out_degree, 1), 0.0)
    dang = (graph.out_degree == 0).astype(np.float64)
    e = np.full(n, 1.0 / n)
    r = np.full(n, 1.0 / n)
    for _ in range(iters):
        w = r * inv
        contribs = np.zeros(n)
        np.add.at(contribs, graph.dst, w[graph.src])
        contribs += float(r @ dang) * e
        r = (1 - damping) * e + damping * contribs
    return r


def test_ported_pagerank_matches_pre_port_reference():
    """ISSUE 9 acceptance pin: the runners are now thin programs over
    dataflow.iterate — ranks must still match the uninterrupted reference
    to 1e-6 (f32) for both the scan and the while-loop fixpoints."""
    g = synthetic_powerlaw(1500, 6000, seed=21)
    want = _numpy_pagerank(g, 15)
    res = run_pagerank(g, PageRankConfig(iterations=15, **GRAPH_KW))
    np.testing.assert_allclose(res.ranks, want, atol=1e-6)
    res_tol = run_pagerank(
        g, PageRankConfig(iterations=500, tol=1e-10, **GRAPH_KW)
    )
    assert 0 < res_tol.iterations <= 500
    np.testing.assert_allclose(
        res_tol.ranks, _numpy_pagerank(g, res_tol.iterations), atol=1e-5
    )


def test_pagerank_composed_from_primitives_matches_runner():
    """The marginal-cost claim in one test: PageRank expressed DIRECTLY
    as broadcast_join → graph_combine → iterate (no ops.make_* runner)
    equals the production path."""
    import functools

    import jax
    import jax.numpy as jnp

    g = synthetic_powerlaw(600, 2400, seed=5)
    n = g.n_nodes
    cfg = PageRankConfig(iterations=12, **GRAPH_KW)
    dg = ops.put_graph(g, cfg.dtype)
    e = jnp.asarray(ops.restart_vector(n, cfg))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def program(ranks0):
        def step(r):
            weighted = r * dg.inv_outdeg  # mapValues
            contribs = dataflow.graph_combine(dg, weighted, n)  # the shuffle
            dmass = jnp.sum(r * dg.dangling)
            contribs = contribs + dmass * e
            return (1.0 - cfg.damping) * e + cfg.damping * contribs

        return dataflow.iterate(step, ranks0, iterations=cfg.iterations)

    ranks, iters, _ = program(jnp.asarray(ops.init_ranks(n, cfg)))
    base = run_pagerank(g, cfg)
    assert int(iters) == cfg.iterations
    np.testing.assert_allclose(np.asarray(ranks), base.ranks, atol=1e-6)


def test_streaming_over_chunked_ingest_byte_equal_to_batch(monkeypatch):
    """ISSUE 9 acceptance pin: the streaming path (now a thin program
    over dataflow.chunked_ingest) produces byte-identical weights to the
    batch pipeline, at every prefetch depth."""
    from page_rank_and_tfidf_using_apache_spark_tpu.models import tfidf as mt

    monkeypatch.setattr(mt, "DEVICE_FINALIZE_MIN_NNZ", 0)
    docs = [f"alpha beta{i % 7} gamma{i % 3} shared token{i}"
            for i in range(40)]
    cfg = TfidfConfig(vocab_bits=10)
    batch = run_tfidf(docs, cfg)

    def key(out):
        order = np.lexsort((out.doc, out.term))
        return (out.doc[order], out.term[order], out.weight[order])

    bd, bt, bw = key(batch)
    chunks = [docs[i:i + 8] for i in range(0, len(docs), 8)]
    for prefetch in (0, 2):
        scfg = TfidfConfig(vocab_bits=10, chunk_tokens=64, prefetch=prefetch)
        stream = run_tfidf_streaming(iter(chunks), scfg)
        sd, st, sw = key(stream)
        np.testing.assert_array_equal(sd, bd)
        np.testing.assert_array_equal(st, bt)
        assert sw.tobytes() == bw.tobytes()  # BYTE-equal, not allclose
        # the raw counts ride along for the BM25 ranker
        assert stream.count is not None and stream.doc_lengths is not None


def test_chunked_ingest_ordering_contract():
    """The pipeline's discipline, pinned: commit only ever runs with
    nothing in flight, checkpoints drain-then-commit-then-save, and depth
    bounds the in-flight window."""
    log: list[str] = []
    inflight = [0]
    due = {"flag": False}

    def launch(i):
        inflight[0] += 1
        assert inflight[0] <= 3  # depth 2 -> at most depth+1 briefly
        log.append(f"launch{i}")
        if i == 3:
            due["flag"] = True
        return i

    def drain(i):
        inflight[0] -= 1
        log.append(f"drain{i}")

    def commit():
        assert inflight[0] == 0, "commit with launches in flight"
        log.append("commit")

    def save():
        log.append("ckpt")
        due["flag"] = False

    dataflow.chunked_ingest(
        range(6), launch=launch, drain=drain, commit=commit, depth=2,
        checkpoint_due=lambda: due["flag"], save_checkpoint=save,
        prefetch_source=False,
    )
    assert log[-1] == "commit"
    assert "ckpt" in log
    assert log.index("ckpt") == log.index("commit") + 1  # commit before save
    assert [x for x in log if x.startswith("launch")] == [
        f"launch{i}" for i in range(6)
    ]
    assert sorted(x for x in log if x.startswith("drain")) == [
        f"drain{i}" for i in range(6)
    ]


def test_workload_fixpoints_survive_device_loss_via_shared_salvage():
    """The marginal-cost resilience claim: the NEW workloads inherit the
    single-chip device-loss salvage (dataflow.fixpoint.make_cpu_salvage)
    without wiring of their own — a device-targeted loss at each
    workload's delta-sync site recovers to the uninterrupted result."""
    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.components import (
        run_components,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.hits import run_hits
    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.ppr import (
        run_ppr_batch,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        ComponentsConfig,
        HitsConfig,
    )

    g = synthetic_powerlaw(300, 1200, seed=17)
    pcfg = PageRankConfig(iterations=20, **GRAPH_KW)
    queries = [[int(g.node_ids[0])], [int(g.node_ids[5])]]
    base_ppr = run_ppr_batch(g, pcfg, queries)
    base_hits = run_hits(g, HitsConfig(iterations=30, tol=0.0))
    base_cc = run_components(g, ComponentsConfig())

    elastic.reset_health()
    try:
        with chaos.inject("ppr_delta_sync:device_lost@dev:0"):
            m = MetricsRecorder()
            ppr = run_ppr_batch(g, pcfg, queries, metrics=m)
        assert any(r.get("event") == "degraded" and r.get("ladder") == "cpu"
                   for r in m.records)
        np.testing.assert_allclose(ppr.ranks, base_ppr.ranks, atol=1e-6)

        elastic.reset_health()
        with chaos.inject("hits_delta_sync:device_lost@dev:0"):
            h = run_hits(g, HitsConfig(iterations=30, tol=0.0))
        np.testing.assert_allclose(h.hubs, base_hits.hubs, atol=1e-6)

        elastic.reset_health()
        with chaos.inject("cc_delta_sync:device_lost@dev:0"):
            c = run_components(g, ComponentsConfig())
        np.testing.assert_array_equal(c.labels, base_cc.labels)

        # a loss first surfacing at the RESULT pull (no segment dispatch
        # left to catch it) walks the shared pull-salvage rung
        elastic.reset_health()
        with chaos.inject("ppr_result_pull:device_lost@dev:0"):
            ppr2 = run_ppr_batch(g, pcfg, queries)
        np.testing.assert_allclose(ppr2.ranks, base_ppr.ranks, atol=1e-6)
    finally:
        elastic.reset_health()


def test_chunked_ingest_chaos_stays_invisible():
    """Transient chunk-drain faults through the shared ingest wiring are
    absorbed by the executor exactly as before the port."""
    docs = [f"doc{i} token{i % 4} word" for i in range(24)]
    chunks = [docs[i:i + 4] for i in range(0, len(docs), 4)]
    cfg = TfidfConfig(vocab_bits=9, chunk_tokens=32, prefetch=1)
    base = run_tfidf_streaming(iter(chunks), cfg)
    m = MetricsRecorder()
    with chaos.inject("tfidf_chunk_sync:fail@2"):
        out = run_tfidf_streaming(iter(chunks), cfg, metrics=m)
    assert any(r.get("event") == "retry" for r in m.records)
    assert out.weight.tobytes() == base.weight.tobytes()
