"""Staged double-buffered ingest pipeline tests (ISSUE 10).

Covers the dataflow core's new pieces — :class:`Prefetched`'s poison/close
protocol, :func:`pack_doc_chunks`, :func:`overlap_fraction`, the staged
``chunked_ingest`` — and the acceptance bars: streaming TF-IDF byte-equal
to batch at every ``pipeline_depth``, chunk-kill resume with a
staged-but-uncommitted chunk in flight reprocessing zero committed
chunks, and chaos ``device_lost`` at ``ingest_h2d_put`` walking the
elastic rung on both the single-chip and 2-device sharded paths.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import ingest as dflow
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    resume_point,
    run_tfidf,
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import run_tfidf_sharded
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos, elastic
from page_rank_and_tfidf_using_apache_spark_tpu.resilience.executor import (
    ResilienceExhausted,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    IngestConfig,
    TfidfConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


@pytest.fixture
def fresh_health():
    elastic.reset_health()
    yield
    elastic.reset_health()


def _chunks(n_chunks: int, docs_per_chunk: int = 2) -> list[list[str]]:
    docs = [f"tok{i} tok{i % 5} shared word extra{i % 3}"
            for i in range(n_chunks * docs_per_chunk)]
    return [docs[i:i + docs_per_chunk]
            for i in range(0, len(docs), docs_per_chunk)]


# ------------------------------------------------ Prefetched protocol


def test_prefetched_producer_exception_keeps_traceback():
    """A producer exception re-raises on the consumer side WITH the
    original traceback — the producer frame must be visible (the ISSUE 10
    satellite: no more 'exception came from a queue' dead ends)."""

    def bad_source():
        yield 1
        raise ValueError("boom at item 2")

    it = dflow.prefetched(bad_source(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="boom at item 2") as ei:
        list(it)
    frames = []
    tb = ei.value.__traceback__
    while tb is not None:
        frames.append(tb.tb_frame.f_code.co_name)
        tb = tb.tb_next
    assert "bad_source" in frames  # the producer frame survived the queue


def test_prefetched_close_unblocks_full_queue_and_keeps_items():
    """close() must shut down a producer BLOCKED on a full queue promptly,
    and every produced-but-unconsumed item (including the one the producer
    had in hand) must survive into leftover() — zero loss."""
    produced: list[int] = []

    def source():
        for i in range(100):
            produced.append(i)
            yield i

    pf = dflow.Prefetched(source(), depth=2)
    assert next(pf) == 0
    time.sleep(0.1)  # let the producer fill the queue and block
    t0 = time.perf_counter()
    pf.close()
    assert time.perf_counter() - t0 < 2.0  # prompt, not a timeout crawl
    assert not pf.thread.is_alive()  # no leaked thread
    left = pf.leftover()
    # consumed [0]; everything else the producer pulled from the source
    # must be in leftover, in order
    assert left == produced[1:]
    assert len(left) >= 2  # queue depth + possibly the in-hand orphan


def test_prefetched_generator_abandonment_stops_producer():
    """Abandoning the legacy generator wrapper early (the chunk-kill
    resume path) must terminate the producer thread instead of leaking it
    blocked on a full queue."""
    before = threading.active_count()
    gen = dflow.prefetched(iter(range(1000)), depth=1)
    assert next(gen) == 0
    gen.close()  # abandon early
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_prefetched_end_to_end_order():
    assert list(dflow.prefetched(iter(range(50)), depth=3)) == list(range(50))


# ------------------------------------------------ pack_doc_chunks


def test_pack_doc_chunks_fills_target_and_preserves_order():
    docs = [f"w{i} " * (i % 7 + 1) for i in range(40)]  # 1..7 tokens each
    chunks = [docs[i:i + 3] for i in range(0, len(docs), 3)]
    packed = list(dflow.pack_doc_chunks(iter(chunks), target_tokens=20))
    # order preserved, nothing lost, documents never split
    assert [d for c in packed for d in c] == docs
    # every chunk except the last carries <= target but the NEXT doc
    # would have overflowed it (fills to within one document)
    for c in packed[:-1]:
        assert sum(dflow.estimate_tokens(d) for d in c) <= 20


def test_pack_doc_chunks_deterministic():
    docs = [f"a{i} b c" for i in range(30)]
    chunks = [docs[i:i + 4] for i in range(0, len(docs), 4)]
    p1 = list(dflow.pack_doc_chunks(iter(chunks), 10))
    p2 = list(dflow.pack_doc_chunks(iter(chunks), 10))
    assert p1 == p2


def test_pack_doc_chunks_oversized_doc_gets_own_chunk():
    docs = ["small doc", "x " * 200, "tiny"]
    packed = list(dflow.pack_doc_chunks(iter([docs]), 10))
    assert ["x " * 200] in [c for c in packed if len(c) == 1]
    assert [d for c in packed for d in c] == docs


# ------------------------------------------------ overlap_fraction


def test_overlap_fraction_math():
    # h2d [0,2] fully under compute [0,4] -> 1.0
    assert dflow.overlap_fraction([(0, 2)], [(0, 4)]) == pytest.approx(1.0)
    # h2d [3,5] half under compute [0,4] -> 0.5
    assert dflow.overlap_fraction([(3, 5)], [(0, 4)]) == pytest.approx(0.5)
    # disjoint -> 0.0; empty h2d -> 0.0
    assert dflow.overlap_fraction([(10, 12)], [(0, 4)]) == 0.0
    assert dflow.overlap_fraction([], [(0, 4)]) == 0.0
    # overlapping compute intervals must not double-count
    assert dflow.overlap_fraction(
        [(0, 4)], [(0, 2), (1, 3)]
    ) == pytest.approx(0.75)


def test_ingest_config_validation():
    assert IngestConfig().pipeline_depth == 2
    with pytest.raises(ValueError):
        IngestConfig(prefetch=-1)
    with pytest.raises(ValueError):
        IngestConfig(pipeline_depth=-1)
    assert TfidfConfig(prefetch=1, pipeline_depth=3).ingest() == IngestConfig(
        prefetch=1, pipeline_depth=3
    )


# ------------------------------------ byte-equality across pipeline depths


def test_streaming_byte_equal_to_batch_at_all_pipeline_depths():
    """ISSUE 10 acceptance: streaming output byte-equal to batch pinned at
    pipeline_depth in {0, 1, 2, 4} — only scheduling may change."""
    chunks = _chunks(10, docs_per_chunk=3)
    docs = [d for c in chunks for d in c]
    batch = run_tfidf(docs, TfidfConfig(vocab_bits=10)).to_dense()
    for depth in (0, 1, 2, 4):
        scfg = TfidfConfig(vocab_bits=10, chunk_tokens=64, prefetch=2,
                           pipeline_depth=depth)
        sw = run_tfidf_streaming(iter(chunks), scfg).to_dense()
        assert sw.tobytes() == batch.tobytes(), f"depth {depth}"


def test_streaming_byte_equal_with_packing():
    """Re-packing the source chunking (pack_target_tokens) changes chunk
    boundaries only — the output must stay byte-equal to batch."""
    chunks = _chunks(12, docs_per_chunk=1)
    docs = [d for c in chunks for d in c]
    batch = run_tfidf(docs, TfidfConfig(vocab_bits=10)).to_dense()
    m = MetricsRecorder()
    scfg = TfidfConfig(vocab_bits=10, chunk_tokens=64,
                       pack_target_tokens=30)
    out = run_tfidf_streaming(iter(chunks), scfg, metrics=m)
    assert out.to_dense().tobytes() == batch.tobytes()
    # packing really regrouped: fewer packed chunks than input chunks
    chunk_events = [r for r in m.records if r.get("event") == "chunk"]
    assert 0 < len(chunk_events) < 12


def test_ingest_overlap_record_published():
    m = MetricsRecorder()
    run_tfidf_streaming(iter(_chunks(6)), TfidfConfig(vocab_bits=10),
                        metrics=m)
    ov = [r for r in m.records if r.get("event") == "ingest_overlap"]
    assert len(ov) == 1
    rec = ov[0]
    assert set(rec) >= {"h2d_overlap_frac", "tokenize_secs", "h2d_secs",
                        "compute_secs", "chunks", "depth", "pipeline_depth"}
    assert rec["chunks"] == 6
    assert 0.0 <= rec["h2d_overlap_frac"] <= 1.0


# ------------------------------------------- resume with staged chunks


def test_chunk_kill_with_staged_inflight_resumes_zero_reprocessing(tmp_path):
    """A drain kill while later chunks are already STAGED (device_put
    issued, compute not committed) must leave a checkpoint at the last
    committed chunk; resume reprocesses zero committed chunks and matches
    the uninterrupted output."""
    chunks = _chunks(16)
    full = run_tfidf_streaming(iter(chunks), TfidfConfig(vocab_bits=10))

    cfg = TfidfConfig(vocab_bits=10, prefetch=2, pipeline_depth=2,
                      checkpoint_every=1,
                      checkpoint_dir=str(tmp_path / "ck"))
    m1 = MetricsRecorder()
    with chaos.inject("tfidf_chunk_sync:lost@9"):  # the 9th drain fails
        with pytest.raises(ResilienceExhausted) as ei:
            run_tfidf_streaming(iter(chunks), cfg, metrics=m1)
    assert ei.value.last_checkpoint is not None
    committed = resume_point(cfg)
    done_before = [r["chunk"] for r in m1.records if r.get("event") == "chunk"]
    # drained != committed: the failing drain happened INSIDE a commit
    # barrier, so some chunks drained after the last successful commit
    # (their DF lives only in the dead carry) — the checkpoint must hold
    # strictly committed state, never those
    assert committed == 6
    assert done_before == list(range(8))  # drains 0-7 landed, 8 was killed

    m2 = MetricsRecorder()
    res = run_tfidf_streaming(iter(chunks), cfg, metrics=m2, resume=True)
    done_after = [r["chunk"] for r in m2.records if r.get("event") == "chunk"]
    # resume replays exactly the uncommitted span: ZERO committed chunks
    # reprocessed (6 and 7 were drained but never committed, so their
    # replay is what keeps DF consistent)
    assert done_after == list(range(committed, 16))
    np.testing.assert_allclose(res.to_dense(), full.to_dense(), atol=1e-6)


# ----------------------------------- chaos at the H2D staging sites


def test_h2d_put_transient_faults_invisible():
    """Transient faults at ingest_h2d_put retry on the transfer thread
    and stay invisible to the caller."""
    chunks = _chunks(9)
    base = run_tfidf_streaming(iter(chunks), TfidfConfig(vocab_bits=10))
    m = MetricsRecorder()
    with chaos.inject("ingest_h2d_put:fail@%3"):
        res = run_tfidf_streaming(iter(chunks), TfidfConfig(vocab_bits=10),
                                  metrics=m)
    retries = [r for r in m.records if r.get("event") == "retry"
               and r.get("site") == dflow.H2D_PUT_SITE]
    assert len(retries) >= 2
    assert res.to_dense().tobytes() == base.to_dense().tobytes()


def test_single_chip_device_lost_at_h2d_put_walks_elastic_rung(
        fresh_health, tmp_path):
    """ISSUE 10 acceptance: chaos device_lost at ingest_h2d_put on the
    single-chip path walks the elastic rung (acknowledge + rollback to
    the last commit + CPU replay of retained host chunks) and matches the
    uninterrupted output — no ResilienceExhausted."""
    chunks = _chunks(12)
    base = run_tfidf_streaming(iter(chunks), TfidfConfig(vocab_bits=10))
    m = MetricsRecorder()
    cfg = TfidfConfig(vocab_bits=10, prefetch=2, pipeline_depth=2,
                      checkpoint_every=4,
                      checkpoint_dir=str(tmp_path / "ck"))
    with chaos.inject("ingest_h2d_put:device_lost@dev:0"):
        res = run_tfidf_streaming(iter(chunks), cfg, metrics=m)
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert len(degraded) == 1
    assert degraded[0]["ladder"] == "cpu"
    assert degraded[0]["site"] == dflow.H2D_PUT_SITE
    np.testing.assert_allclose(res.to_dense(), base.to_dense(), atol=1e-6)


def test_single_chip_device_lost_mid_stream_rolls_back_to_commit(
        fresh_health, tmp_path):
    """The loss fires mid-stream with committed chunks behind it: the
    rollback must keep every committed chunk exactly once (no drops, no
    double counts) — byte-level equality of the dense matrix proves it."""
    chunks = _chunks(14)
    base = run_tfidf_streaming(iter(chunks), TfidfConfig(vocab_bits=10))
    m = MetricsRecorder()
    cfg = TfidfConfig(vocab_bits=10, prefetch=2, pipeline_depth=2,
                      checkpoint_every=3,
                      checkpoint_dir=str(tmp_path / "ck"))
    # dev schedule: fires on every ingest_h2d_put call until acknowledged;
    # delay the first injection past several commits by targeting a later
    # call — chunk 8's put is well past the chunk-6 checkpoint
    with chaos.inject("ingest_h2d_wait:device_lost@dev:0"):
        res = run_tfidf_streaming(iter(chunks), cfg, metrics=m)
    assert [r["ladder"] for r in m.records if r.get("event") == "degraded"] \
        == ["cpu"]
    np.testing.assert_allclose(res.to_dense(), base.to_dense(), atol=1e-6)
    assert res.n_docs == base.n_docs


def test_sharded_device_lost_at_h2d_put_shrinks_mesh(fresh_health, tmp_path):
    """ISSUE 10 acceptance: chaos device_lost at ingest_h2d_put on a
    2-device sharded mesh walks the elastic mesh-shrink rung — the
    in-flight staged groups re-slice over the shrunk mesh from retained
    host corpora — and the output matches the uninterrupted run."""
    chunks = _chunks(12)
    base = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                             n_devices=2)
    elastic.reset_health()
    m = MetricsRecorder()
    obs.start_run("ingest_h2d_loss", str(tmp_path / "tr"))
    try:
        with chaos.inject("ingest_h2d_put:device_lost@dev:1"):
            res = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                                    n_devices=2, metrics=m)
    finally:
        obs.end_run()
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert len(degraded) == 1
    assert (degraded[0]["devices_old"], degraded[0]["devices_new"]) == (2, 1)
    sc = [r for r in m.records if r.get("event") == "super_chunk"]
    assert sum(r["devices"] for r in sc) == 12  # every chunk exactly once
    np.testing.assert_allclose(res.to_dense(), base.to_dense(), atol=1e-6)


# --------------------------------------------- trace artifact rendering


def test_trace_report_renders_ingest_section(tmp_path):
    import importlib.util
    from pathlib import Path

    with obs.run("ingesttrace", trace_dir=str(tmp_path)):
        run_tfidf_streaming(iter(_chunks(4)), TfidfConfig(vocab_bits=10))
    trace = next(tmp_path.glob("ingesttrace.*.trace.jsonl"))
    spec = importlib.util.spec_from_file_location(
        "trace_report",
        Path(__file__).resolve().parents[1] / "tools" / "trace_report.py",
    )
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    rep = tr.report(str(trace))
    assert rep["ingest"] and len(rep["ingest"]) == 1
    assert rep["ingest"][0]["chunks"] == 4
    assert "h2d_overlap_frac" in rep["ingest"][0]
    human = tr.render_human(rep)
    assert "ingest pipeline" in human and "h2d_overlap" in human


def test_trace_diff_folds_overlapped_ingest_phases():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "trace_diff",
        Path(__file__).resolve().parents[1] / "tools" / "trace_diff.py",
    )
    td = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(td)
    # wall time moved from compute into overlapped h2d: NOT a regression
    old = {"ingest.compute": 10.0, "bench.warm": 1.0}
    new = {"ingest.compute": 6.0, "ingest.h2d": 4.0, "bench.warm": 1.0}
    rows = td.diff_breakdowns(old, new)
    combined = [r for r in rows if r["phase"] == "ingest.h2d+compute"]
    assert len(combined) == 1
    assert combined[0]["delta_secs"] == pytest.approx(0.0)
    assert not any(r["phase"] in ("ingest.h2d", "ingest.compute")
                   for r in rows)


# ---------------------------------------- review regressions (PR 10)


def test_wait_site_does_not_retry_iterator_failures():
    """A persistent stage failure whose message carries a transient
    marker (e.g. XLA 'RESOURCE_EXHAUSTED: out of memory') must NOT be
    retried at the ingest_h2d_wait site: the staged iterator is stateful,
    so a re-invoked next() would read _END off the finished Prefetched
    and silently truncate the stream (or skip the failed item inline).
    The cause must propagate to the caller/recovery point instead."""
    for depth in (0, 2):
        drained: list = []

        def stage(item):
            if item == 4:
                raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
            return item

        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            dflow.chunked_ingest(
                range(8), stage=stage, launch=lambda s: s,
                drain=drained.append, commit=lambda: None,
                depth=2, pipeline_depth=depth,
            )
        # the run did NOT complete as if successful, and what drained is
        # a contiguous prefix stopping before the casualty — nothing was
        # skipped past it (undrained items stay accounted for recovery)
        assert drained == list(range(len(drained))), (depth, drained)
        assert len(drained) <= 4, (depth, drained)


def test_wait_site_recovery_redelivers_after_marker_failure():
    """Same failure, with a recover hook: every unprocessed item
    (including the casualty) is re-delivered exactly once — no
    truncation, no double-processing."""
    fail = {"armed": True}
    drained: list = []
    seen: list = []

    def stage(item):
        if item == 4 and fail["armed"]:
            fail["armed"] = False
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return item

    def recover(exc, remaining, where):
        assert where == "stage"
        assert "RESOURCE_EXHAUSTED" in str(exc)
        seen.append(sorted(remaining))
        return seen[-1]

    dflow.chunked_ingest(
        iter(range(8)), stage=stage, launch=lambda s: s,
        drain=drained.append, commit=lambda: None,
        depth=2, pipeline_depth=2, recover=recover,
    )
    # the casualty was re-delivered (not skipped), and every item was
    # processed exactly once overall — no truncation, no double-drain
    assert len(seen) == 1 and 4 in seen[0]
    assert sorted(drained) == list(range(8))
    assert drained[:len(drained) - len(seen[0])] == \
        list(range(8 - len(seen[0])))


def test_wait_site_watchdog_never_drops_consumed_items(monkeypatch):
    """With GRAFT_SYNC_DEADLINE_S armed and a staging stage slower than
    the deadline, the wait site must NOT run under the watchdog: an
    abandoned attempt would still be blocked inside next() on the
    stateful staged iterator, and whatever item that zombie thread
    eventually consumed would vanish from the committed output (silently
    — the run 'succeeds' minus chunks).  The pull is a local thread
    handoff, so it runs inline; the device-facing put keeps its own
    deadline at ingest_h2d_put."""
    monkeypatch.setenv("GRAFT_SYNC_DEADLINE_S", "0.2")
    for depth in (0, 2):
        drained: list = []

        def stage(item):
            time.sleep(0.3)  # slower than the armed deadline
            return item

        dflow.chunked_ingest(
            range(6), stage=stage, launch=lambda s: s,
            drain=drained.append, commit=lambda: None,
            depth=2, pipeline_depth=depth,
        )
        assert drained == list(range(6)), (depth, drained)


def test_swept_source_exception_fails_recovery_replay():
    """A source exception the consumer never saw (it died on a drain
    fault first, and the teardown swept the parked exception out of the
    prefetch thread) must re-surface during the recovery replay at its
    stream position: the replayed run must NOT complete 'successfully'
    with a silently truncated corpus and the source error unread."""
    for pdepth in (0, 2):
        drained: list = []
        recovered: list = []

        def source():
            yield from range(4)
            raise ValueError("corrupt input past doc 3")

        armed = {"on": True}

        def drain(rec):
            if rec == 1 and armed["on"]:
                armed["on"] = False
                # let the producer run past the source fault so the
                # teardown sweeps it unread (the regression path); the
                # live-raise path is equivalent and also covered
                time.sleep(0.2)
                raise RuntimeError("persistent drain fault")
            drained.append(rec)

        def recover(exc, remaining, where):
            # mirrors production: recover handles the device-class
            # fault, anything else re-raises into the ladder
            recovered.append(type(exc).__name__)
            if isinstance(exc, ValueError):
                raise exc
            return remaining

        with pytest.raises(ValueError, match="corrupt input"):
            dflow.chunked_ingest(
                source(), stage=lambda it: it, launch=lambda s: s,
                drain=drain, commit=lambda: None,
                depth=2, pipeline_depth=pdepth, recover=recover,
            )
        # the drain fault recovered, then the swept source error failed
        # the replay (the run did NOT complete as if successful); what
        # drained is each real doc at most once, in stream order —
        # in-flight items at the moment the source error surfaced are
        # uncommitted work on a FAILED run, not silent drops
        assert recovered == ["RuntimeError", "ValueError"], (pdepth,
                                                             recovered)
        assert drained == sorted(set(drained)), (pdepth, drained)
        assert set(drained) <= {0, 1, 2, 3}, (pdepth, drained)


def test_estimate_tokens_matches_tokenizer_split_rule():
    """estimate_tokens must upper-bound the real tokenizer on
    punctuation/newline-heavy text (it splits on ALL non-alphanumerics,
    not whitespace), or pack_doc_chunks overfills chunks past the
    compiled cap and forces mid-stream recompiles."""
    from page_rank_and_tfidf_using_apache_spark_tpu.io import text as tio

    for doc in ("a,b,c,d", "x\ny\nz", "one two", "a--b__c", ""):
        assert dflow.estimate_tokens(doc) >= len(tio.tokenize(doc)), doc
    assert dflow.estimate_tokens("a,b,c,d") == 4
    # ngram=2 ~doubles the token count: the estimator must track it
    est2 = dflow.ngram_estimator(2)
    toks = tio.add_ngrams(tio.tokenize("a,b c;d"), 2)
    assert est2("a,b c;d") >= len(toks)
    assert dflow.ngram_estimator(1) is dflow.estimate_tokens


def test_packed_streaming_never_bumps_cap_on_punctuated_corpus():
    """End-to-end guard for the estimator: packing a punctuation-heavy
    corpus to a target at the chunk cap must not overflow it (no
    chunk_cap_bump recompiles mid-stream) and stays byte-equal."""
    docs = [",".join(f"tok{i}w{j}" for j in range(7)) for i in range(40)]
    chunks = [docs[i:i + 2] for i in range(0, len(docs), 2)]
    batch = run_tfidf(docs, TfidfConfig(vocab_bits=10)).to_dense()
    m = MetricsRecorder()
    scfg = TfidfConfig(vocab_bits=10, chunk_tokens=64,
                       pack_target_tokens=64)
    out = run_tfidf_streaming(iter(chunks), scfg, metrics=m)
    assert out.to_dense().tobytes() == batch.tobytes()
    assert not [r for r in m.records if r.get("event") == "chunk_cap_bump"]


def test_no_checkpoint_streaming_bounds_retained_chunks(monkeypatch):
    """With checkpointing off, retain_until_commit must not hold the
    whole corpus: a commit-only barrier every _RETAIN_COMMIT_EVERY chunks
    releases the retained host copies (and byte-equality holds across
    the extra barriers)."""
    from page_rank_and_tfidf_using_apache_spark_tpu.models import tfidf as mt

    monkeypatch.setattr(mt, "_RETAIN_COMMIT_EVERY", 4)
    chunks = _chunks(12)
    docs = [d for c in chunks for d in c]
    batch = run_tfidf(docs, TfidfConfig(vocab_bits=10)).to_dense()
    peak = {"n": 0}
    orig = dflow.chunked_ingest

    def spying(source, **kw):
        orig_drain = kw["drain"]
        retained = kw.get("retain_until_commit")
        assert retained is True
        # wrap commit to observe how many chunks were retained between
        # barriers via the drain counter
        count = {"n": 0}

        def drain(rec):
            count["n"] += 1
            orig_drain(rec)

        orig_commit = kw["commit"]

        def commit():
            peak["n"] = max(peak["n"], count["n"])
            count["n"] = 0
            orig_commit()

        kw["drain"], kw["commit"] = drain, commit
        return orig(source, **kw)

    monkeypatch.setattr(mt.dflow, "chunked_ingest", spying)
    out = run_tfidf_streaming(iter(chunks),
                              TfidfConfig(vocab_bits=10, prefetch=2,
                                          pipeline_depth=2))
    assert out.to_dense().tobytes() == batch.tobytes()
    # barriers fired mid-stream: no commit interval saw all 12 chunks
    assert 0 < peak["n"] <= 6
