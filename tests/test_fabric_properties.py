"""Hypothesis properties for the serving fabric's consistent-hash ring
(ISSUE 18 satellite).

``test_fabric.test_ring_remap_bound_on_replica_loss`` pins the stability
contract for ONE fleet shape (4 replicas, kill replica 0).  These
properties hold it universally: under arbitrary fleet add/kill
sequences, a key whose owning replica survives the step NEVER remaps —
removal only reshuffles the dead replica's keys, and an addition only
moves keys onto the newcomer.  That is the invariant the router's
affinity cache and the replica result caches ride: fleet churn must not
invalidate survivors' working sets.

Skips cleanly when hypothesis is not installed (it is optional in this
environment), like tests/test_properties.py.
"""

from __future__ import annotations

import pytest

pytest.importorskip(
    "hypothesis",
    reason="property-based ring tests need hypothesis",
)

from hypothesis import given, settings, strategies as st  # noqa: E402

from page_rank_and_tfidf_using_apache_spark_tpu.serving import (  # noqa: E402
    fabric,
)

_KEYS = [f"doc-{i:03d}" for i in range(48)]
_SLOTS = 32


def _owners(ring: "fabric._Ring") -> dict:
    return {k: ring.route(k)[0] for k in _KEYS}


@settings(max_examples=25, deadline=None)
@given(st.sets(st.integers(0, 31), min_size=2, max_size=8), st.data())
def test_kill_never_remaps_survivor_keys(fleet, data):
    """For ANY fleet and ANY strict subset of kills: every key whose
    primary owner survives keeps that owner on the shrunk ring."""
    kill = data.draw(
        st.sets(st.sampled_from(sorted(fleet)), max_size=len(fleet) - 1),
        label="killed replicas",
    )
    survivors = fleet - kill
    full = _owners(fabric._Ring(sorted(fleet), slots=_SLOTS))
    shrunk = _owners(fabric._Ring(sorted(survivors), slots=_SLOTS))
    for k in _KEYS:
        if full[k] in survivors:
            assert shrunk[k] == full[k], (
                f"key {k!r} owned by surviving replica {full[k]} remapped "
                f"to {shrunk[k]} when {sorted(kill)} died"
            )


@settings(max_examples=15, deadline=None)
@given(st.lists(st.tuples(st.sampled_from(["add", "kill"]),
                          st.integers(0, 15)),
                max_size=12))
def test_fleet_churn_moves_keys_only_to_the_newcomer(ops):
    """Walk an arbitrary add/kill sequence one step at a time: after a
    kill, every key owned by a still-present replica stays put; after an
    add, a key either keeps its owner or moves to the replica that just
    joined — never to an unrelated survivor."""
    fleet = {0, 1}
    owners = _owners(fabric._Ring(sorted(fleet), slots=_SLOTS))
    for op, rid in ops:
        if op == "add":
            fleet = fleet | {rid}
        elif len(fleet) > 1:
            fleet = fleet - {rid} or fleet
        new_owners = _owners(fabric._Ring(sorted(fleet), slots=_SLOTS))
        for k in _KEYS:
            if op == "kill":
                if owners[k] in fleet:
                    assert new_owners[k] == owners[k], (
                        f"kill of {rid} remapped survivor-owned {k!r}: "
                        f"{owners[k]} -> {new_owners[k]}"
                    )
            else:
                assert new_owners[k] in (owners[k], rid), (
                    f"add of {rid} moved {k!r} to unrelated replica "
                    f"{new_owners[k]} (was {owners[k]})"
                )
        owners = new_owners
