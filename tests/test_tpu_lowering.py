"""Cross-platform TPU lowering pins (no chip needed).

``jax.export`` with ``platforms=["tpu"]`` runs the full StableHLO (and, for
Pallas kernels, Mosaic) lowering pipeline, so ops that cannot compile on a
real TPU fail HERE instead of on the benchmark chip.  This caught a previous
kernel design that used 1-D vector gathers (no Mosaic lowering) and
``jnp.cumsum`` inside a kernel (no Pallas TPU lowering).
"""

import jax
import jax.numpy as jnp
import pytest
from jax import export

from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as tf_ops
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    IdfMode,
    PageRankConfig,
    TfMode,
)


@pytest.fixture(scope="module")
def device_graph():
    g = synthetic_powerlaw(5000, 40000, seed=1)
    return g, ops.put_graph(g, "float32")


@pytest.mark.parametrize("impl", ["segment", "bcoo", "cumsum", "cumsum_mxu", "pallas"])
def test_pagerank_runner_lowers_for_tpu(device_graph, impl, monkeypatch):
    g, dg = device_graph
    # _spmv picks interpret mode from the trace-time default backend; force
    # the real Mosaic path so this pin actually covers the TPU kernel.
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    cfg = PageRankConfig(iterations=5, dangling="redistribute", init="uniform",
                         dtype="float32", spmv_impl=impl)
    runner = ops.make_pagerank_runner(g.n_nodes, cfg)
    e = jnp.asarray(ops.restart_vector(g.n_nodes, cfg))
    r0 = jnp.asarray(ops.init_ranks(g.n_nodes, cfg))
    exp = export.export(runner, platforms=["tpu"])(dg, r0, e)
    module = exp.mlir_module()
    assert module
    if impl == "pallas":
        # the kernel really went through Mosaic, not an interpret fallback
        assert "tpu_custom_call" in module


def test_pagerank_tolerance_runner_lowers_for_tpu(device_graph):
    g, dg = device_graph
    cfg = PageRankConfig(iterations=50, tol=1e-8, dangling="redistribute",
                         init="uniform", dtype="float32", spmv_impl="cumsum")
    runner = ops.make_pagerank_runner(g.n_nodes, cfg)
    e = jnp.asarray(ops.restart_vector(g.n_nodes, cfg))
    r0 = jnp.asarray(ops.init_ranks(g.n_nodes, cfg))
    assert export.export(runner, platforms=["tpu"])(dg, r0, e).mlir_module()


@pytest.mark.parametrize("impl", ["segment", "cumsum", "cumsum_mxu"])
@pytest.mark.parametrize("strategy", ["edges", "nodes", "nodes_balanced", "src", "src_ring"])
def test_sharded_runner_lowers_for_tpu(strategy, impl):
    """The multi-chip shard_map program (collectives included) must lower
    for the TPU platform — the CPU dryrun alone cannot prove that."""
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import make_mesh
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        pagerank_sharded as ps,
    )

    g = synthetic_powerlaw(2000, 10000, seed=1)
    mesh = make_mesh(8)
    cfg = PageRankConfig(iterations=3, dangling="redistribute", init="uniform",
                         dtype="float32", spmv_impl=impl)
    sg = ps.partition_graph(g, 8, strategy=strategy, dtype="float32")
    runner = ps.make_sharded_runner(sg, cfg, mesh)
    dev = ps.device_put_sharded_graph(sg, mesh)
    e_vec = jnp.asarray(ps._restart_padded(sg, cfg))
    r0 = jnp.asarray(ps._to_padded(sg, np.full(sg.n, 1.0 / sg.n, np.float32),
                                   "float32"))
    exp = export.export(runner, platforms=["tpu"])(r0, *dev, e_vec)
    assert exp.mlir_module()


def test_tfidf_sharded_kernel_lowers_for_tpu():
    """The vocab-sharded TF-IDF ingest kernel (psum'd DF) must lower for
    the TPU platform."""
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import make_mesh
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.tfidf_sharded import (
        make_sharded_counts_kernel,
    )

    mesh = make_mesh(8)
    kernel = make_sharded_counts_kernel(mesh, vocab=4096)
    docs = jnp.zeros((8, 256), jnp.int32)
    terms = jnp.zeros((8, 256), jnp.int32)
    valid = jnp.ones((8, 256), bool)
    assert export.export(kernel, platforms=["tpu"])(docs, terms, valid).mlir_module()


def test_tfidf_passes_lower_for_tpu():
    ids = jnp.zeros(1024, jnp.int32)
    docs = jnp.zeros(1024, jnp.int32)
    valid = jnp.ones(1024, bool)

    def full(doc_ids, term_ids, token_valid):
        counts = tf_ops.count_pairs(doc_ids, term_ids, token_valid=token_valid)
        df = tf_ops.document_frequency(counts, 4096)
        idf = tf_ops.idf_vector(df, 64.0, IdfMode.SMOOTH)
        dl = jax.ops.segment_sum(
            token_valid.astype(jnp.float32), doc_ids, num_segments=64
        )
        vals = tf_ops.tf_values(counts, dl, TfMode.LOGNORM)
        return counts, df, idf, vals

    exp = export.export(jax.jit(full), platforms=["tpu"])(docs, ids, valid)
    assert exp.mlir_module()
