"""Impacted-list scoring + incremental index segments (ISSUE 13):
byte-equality of the latency-shaped path against the full-COO scorer on
the sklearn-oracle corpus (all three rankers), the CSC-by-term artifact
layout, the segment lifecycle (seal → commit → serve → merge → hot-swap)
including a query served from a segment committed AFTER server start,
and the zero-dropped / zero-double-served future audit across hot swaps
under ``fail@%5`` + ``device_lost`` chaos.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import serving
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
    ENTRY_POINTS,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.serving.artifact import (
    build_term_offsets,
)
from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
    IMPACT_MIN_BUCKET_BITS,
    impacted_pad_plan,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

FIXTURE = Path(__file__).parent / "fixtures" / "tiny.txt"
CFG = TfidfConfig(vocab_bits=10, idf_mode="smooth", l2_normalize=True)

QUERIES = [
    ["directed", "graph"],
    ["node"],
    ["0", "1"],
    ["dangling", "node", "4"],
    ["zebra", "unseen"],
]


@pytest.fixture(scope="module")
def oracle_index(tmp_path_factory):
    """The sklearn-oracle corpus built into one servable artifact with
    BM25 weights and a PageRank prior — the byte-equality substrate."""
    docs = FIXTURE.read_text().splitlines()
    out = run_tfidf(docs, CFG)
    d = tmp_path_factory.mktemp("idx")
    ranks = np.linspace(0.5, 1.5, out.n_docs).astype(np.float32)
    serving.save_index(str(d), out, CFG, ranks=ranks, bm25=Bm25Config())
    return serving.load_index(str(d))


def _docs() -> list[str]:
    return FIXTURE.read_text().splitlines()


# ------------------------------------------------ impacted-list equality


def test_impacted_byte_equal_to_coo_all_rankers(oracle_index):
    """Acceptance: impacted-list results byte-equal to score_query_batch
    for tfidf, bm25 AND the per-request prior blend — same corpus, same
    queries, only ServeConfig.scoring differs."""
    expect: dict = {}
    for scoring in ("coo", "impacted"):
        cfg = serving.ServeConfig(top_k=4, max_batch=4, scoring=scoring,
                                  prior_alpha=0.25)
        with serving.TfidfServer(oracle_index, cfg) as srv:
            for ranker in serving.RANKERS:
                for q in QUERIES:
                    scores, idx = srv.query(q, ranker=ranker)
                    key = (ranker, tuple(q))
                    got = (scores.tobytes(), idx.tobytes())
                    if scoring == "coo":
                        expect[key] = got
                    else:
                        assert got == expect[key], (ranker, q)


def test_impacted_bucket_planner_matches_naive(oracle_index):
    """The vectorized host planner produces exactly the buckets a naive
    per-term walk of the CSC offsets would."""
    cfg = serving.ServeConfig(top_k=4, scoring="impacted",
                              impact_bucket_width=4)
    srv = serving.TfidfServer(oracle_index, cfg)
    srv._use_prior = False
    seg = srv._build_segs(srv._segset, srv.k)[0]
    uniq = []
    for q in QUERIES:
        qt, qw = srv.make_query(q)
        from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
            _Pending,
        )

        uniq.append(_Pending(b"k", qt, qw))
    dtype = np.float32
    bs, bl, br, bqw, total = srv._plan_impacted([seg], uniq, dtype)[0]
    # naive reference
    W = 4
    off = seg.offsets
    exp = []
    for row, p in enumerate(uniq):
        for t, w in zip(p.q_term, p.q_weight):
            s, e = int(off[t]), int(off[t + 1])
            run = e - s
            for j in range((run + W - 1) // W):
                exp.append((s + j * W, min(W, run - j * W), row, float(w)))
    assert total == len(exp)
    for i, (s, ln, row, w) in enumerate(exp):
        assert (bs[i], bl[i], br[i]) == (s, ln, row)
        assert bqw[i] == pytest.approx(w)
    # pad tail is inert
    assert (bl[total:] == 0).all() and (bqw[total:] == 0).all()


def test_artifact_term_offsets_describe_runs(oracle_index):
    off = oracle_index.term_offsets
    term = np.asarray(oracle_index.term)
    assert off is not None and off.shape[0] == oracle_index.vocab_size + 1
    assert off[0] == 0 and off[-1] == oracle_index.nnz
    np.testing.assert_array_equal(
        off, build_term_offsets(term, oracle_index.vocab_size))
    # runs really are term-homogeneous
    for t in np.unique(term)[:20]:
        s, e = int(off[t]), int(off[t + 1])
        assert (term[s:e] == t).all()


def test_streaming_built_artifact_is_term_sorted(tmp_path):
    """save_index re-sorts a chunk-major streaming build ONCE at build
    time so the CSC offsets (and the impacted path) always hold."""
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf_streaming,
    )

    docs = _docs()
    chunks = [docs[i:i + 3] for i in range(0, len(docs), 3)]
    scfg = TfidfConfig(vocab_bits=10, prefetch=0, pipeline_depth=0)
    out = run_tfidf_streaming(iter(chunks), scfg)
    serving.save_index(str(tmp_path), out, scfg)
    idx = serving.load_index(str(tmp_path))
    term = np.asarray(idx.term)
    doc = np.asarray(idx.doc)
    assert ((term[1:] > term[:-1])
            | ((term[1:] == term[:-1]) & (doc[1:] >= doc[:-1]))).all()
    assert idx.term_offsets is not None


def test_impacted_pad_plan_policy():
    plan = impacted_pad_plan([10, 60, 64, 100])
    assert plan[0][0] == "impacted"
    assert 0.0 <= plan[0][1] < 0.7
    # floor: tiny batches pad to the 2**min_bits floor
    floor = impacted_pad_plan([1])
    assert floor[0][1] == 1 - 1 / (1 << IMPACT_MIN_BUCKET_BITS)


def test_registry_covers_impacted_entries():
    eps = {ep.name: ep for ep in ENTRY_POINTS}
    imp = eps["tfidf_score_impacted_batch"]
    assert imp.donate == ()  # must-alias-nothing contract
    assert imp.pad_plan is not None and imp.pad_frac_ceiling is not None
    worst = max(frac for _, frac in imp.pad_plan())
    assert worst <= imp.pad_frac_ceiling
    assert "tfidf_topk_merge" in eps


# ------------------------------------------------------ segment lifecycle


def _seal(d, docs, scfg, base):
    out = run_tfidf(docs, scfg)
    ref = sgm.seal_segment(str(d), out, scfg, doc_base=base,
                           ranks=np.ones(out.n_docs, np.float32),
                           bm25=Bm25Config())
    sgm.commit_append(str(d), ref, scfg.config_hash())
    return out, ref


def test_segment_seal_commit_and_global_stats(tmp_path):
    scfg = TfidfConfig(vocab_bits=10)
    docs = _docs()
    half = len(docs) // 2
    o1, r1 = _seal(tmp_path, docs[:half], scfg, 0)
    o2, r2 = _seal(tmp_path, docs[half:], scfg, o1.n_docs)
    m = sgm.latest_manifest(str(tmp_path))
    assert m.version == 2 and len(m.segments) == 2
    assert m.n_docs == o1.n_docs + o2.n_docs
    segset = sgm.load_segment_set(str(tmp_path))
    # global DF is the SUM of segment-local DFs == a full rebuild's DF
    full = run_tfidf(docs, scfg)
    np.testing.assert_allclose(segset.df_global, full.df, atol=1e-6)
    # config-hash guard both ways
    with pytest.raises(ValueError, match="refusing"):
        sgm.load_segment_set(str(tmp_path), expect_config_hash="nope")
    other = TfidfConfig(vocab_bits=10, idf_mode="smooth")
    bad = run_tfidf(docs[:2], other)
    ref = sgm.seal_segment(str(tmp_path), bad, other, doc_base=m.n_docs)
    with pytest.raises(ValueError, match="refusing"):
        sgm.commit_append(str(tmp_path), ref, other.config_hash())


def test_segmented_scoring_matches_full_rebuild(tmp_path):
    """Cross-segment scoring under summed global stats == a monolithic
    rebuild of the same corpus (global IDF drift included)."""
    scfg = TfidfConfig(vocab_bits=10)
    docs = _docs()
    half = len(docs) // 2
    o1, _ = _seal(tmp_path, docs[:half], scfg, 0)
    _seal(tmp_path, docs[half:], scfg, o1.n_docs)
    segset = sgm.load_segment_set(str(tmp_path))
    full = run_tfidf(docs, scfg)
    ref_dir = tmp_path / "ref"
    serving.save_index(str(ref_dir), full, scfg)
    with serving.TfidfServer(
        segset, serving.ServeConfig(top_k=5, scoring="impacted")
    ) as seg_srv, serving.TfidfServer(
        serving.load_index(str(ref_dir)), serving.ServeConfig(top_k=5)
    ) as ref_srv:
        for q in QUERIES:
            ss, si = seg_srv.query(q)
            rs, ri = ref_srv.query(q)
            np.testing.assert_allclose(ss, rs, atol=1e-5)
            # ids agree wherever scores are distinct
            if rs.shape[0] > 1 and np.all(np.abs(np.diff(rs)) > 1e-6):
                np.testing.assert_array_equal(si, ri)


def test_query_served_from_segment_committed_after_start(tmp_path):
    """THE acceptance bar: a segment committed after server start is
    servable via refresh_segments — no restart — and returns GLOBAL doc
    ids from the new segment's range."""
    scfg = TfidfConfig(vocab_bits=10)
    docs = _docs()
    o1, _ = _seal(tmp_path, docs, scfg, 0)
    srv = serving.TfidfServer(
        sgm.load_segment_set(str(tmp_path)),
        serving.ServeConfig(top_k=3, scoring="impacted"),
    ).start()
    try:
        s0, _ = srv.query(["zzzfresh"])
        assert float(s0[0]) == 0.0  # unknown term before the commit
        o2, _ = _seal(tmp_path, ["zzzfresh newdoc content"], scfg, o1.n_docs)
        srv.refresh_segments(sgm.load_segment_set(str(tmp_path)))
        s1, i1 = srv.query(["zzzfresh"])
        assert float(s1[0]) > 0.0
        assert int(i1[0]) == o1.n_docs  # the new segment's global base
        assert srv.stats()["refreshes"] == 1
        assert srv.index.n_docs == o1.n_docs + 1
    finally:
        srv.stop()


def test_merge_preserves_scores_and_merger_chaos_retry(tmp_path):
    """Merging segments must not change served results (same global
    stats, one fewer segment); a transient fault at the ``segment_merge``
    site retries invisibly (the chaos-coverage contract for the merge
    thread's guarded work)."""
    scfg = TfidfConfig(vocab_bits=10)
    docs = _docs()
    third = max(len(docs) // 3, 1)
    o1, _ = _seal(tmp_path, docs[:third], scfg, 0)
    o2, _ = _seal(tmp_path, docs[third:2 * third], scfg, o1.n_docs)
    _seal(tmp_path, docs[2 * third:], scfg, o1.n_docs + o2.n_docs)
    segset = sgm.load_segment_set(str(tmp_path))
    assert len(segset.segments) == 3
    with serving.TfidfServer(
        segset, serving.ServeConfig(top_k=5, scoring="impacted")
    ) as srv:
        before = {tuple(q): srv.query(q) for q in QUERIES}
        merger = sgm.SegmentMerger(str(tmp_path), scfg, max_segments=1)
        with chaos.inject("segment_merge:fail@1") as plan:
            assert merger.merge_once()  # injected fail retried inside
        assert plan.call_count("segment_merge") >= 2
        while merger.merge_once():
            pass
        m = sgm.latest_manifest(str(tmp_path))
        assert len(m.segments) == 1
        assert merger.merges >= 2
        srv.refresh_segments(sgm.load_segment_set(str(tmp_path)))
        for q in QUERIES:
            s, i = srv.query(q)
            bs, bi = before[tuple(q)]
            np.testing.assert_allclose(s, bs, atol=1e-5)
    # replaced segment dirs are gone; the merged one serves
    live = {s.name for s in m.segments}
    on_disk = {p.name for p in (tmp_path / "segments").iterdir()
               if p.is_dir()}
    assert live <= on_disk


def test_merge_refuses_non_contiguous(tmp_path):
    scfg = TfidfConfig(vocab_bits=10)
    docs = _docs()
    o1, r1 = _seal(tmp_path, docs[:4], scfg, 0)
    o2, r2 = _seal(tmp_path, docs[4:8], scfg, o1.n_docs)
    o3, r3 = _seal(tmp_path, docs[8:], scfg, o1.n_docs + o2.n_docs)
    with pytest.raises(ValueError, match="contiguous"):
        sgm.merge_segments(str(tmp_path), (r1, r3), scfg)


def test_hot_swap_future_audit_under_chaos(tmp_path):
    """Zero dropped / zero double-served across seal→commit→refresh and
    merge hot-swaps under transient chaos plus one device loss: every
    logical request is served exactly once (the soak's abandoned-future
    audit, run at test scale against a single server object)."""
    scfg = TfidfConfig(vocab_bits=10)
    docs = _docs()
    o1, _ = _seal(tmp_path, docs, scfg, 0)
    srv = serving.TfidfServer(
        sgm.load_segment_set(str(tmp_path)),
        serving.ServeConfig(top_k=3, max_batch=4, scoring="impacted"),
    ).start()
    stop = threading.Event()
    records: list[dict] = []

    def client(idx: int) -> None:
        rng = np.random.default_rng(idx)
        while not stop.is_set():
            terms = [f"w{int(rng.integers(0, 40))}", "node"]
            rec = {"ok": False, "abandoned": [], "attempts": 0}
            records.append(rec)
            for _ in range(50):
                rec["attempts"] += 1
                fut = None
                try:
                    fut = srv.submit(terms)
                    fut.result(5.0)
                    rec["ok"] = True
                    break
                except Exception:  # noqa: BLE001 — retry every class
                    if fut is not None and not fut.done:
                        rec["abandoned"].append(fut)
                    time.sleep(0.01)
            time.sleep(0.005)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(2)]
    base = o1.n_docs
    with chaos.inject("serve_dispatch:fail@%5;serve_dispatch:lost@9"):
        for t in threads:
            t.start()
        for i in range(3):  # three post-start commits + refreshes
            out = run_tfidf([f"swapdoc{i} content node"], scfg)
            ref = sgm.seal_segment(str(tmp_path), out, scfg, doc_base=base,
                                   bm25=Bm25Config())
            sgm.commit_append(str(tmp_path), ref, scfg.config_hash())
            base += out.n_docs
            srv.refresh_segments(sgm.load_segment_set(str(tmp_path)))
            time.sleep(0.1)
        merger = sgm.SegmentMerger(str(tmp_path), scfg, max_segments=2)
        while merger.merge_once():
            pass
        srv.refresh_segments(sgm.load_segment_set(str(tmp_path)))
        time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
    time.sleep(0.2)  # let abandoned futures settle before the audit
    srv.stop()
    finished = [r for r in records if r["ok"] or r["attempts"] >= 50]
    assert len(finished) > 10
    dropped = 0
    double = 0
    for r in finished:
        served = int(r["ok"]) + sum(
            1 for f in r["abandoned"] if f.done and f.error is None)
        dropped += served == 0
        double += max(served - 1, 0)
    assert dropped == 0
    assert double == 0
    assert srv.stats()["refreshes"] == 4
