"""Multi-process serving fabric (ISSUE 17): consistent-hash ring
stability, the enforced generation floor, the idempotent request-id
replay, router retry under chaos (``fabric_route:net_partition`` /
``fabric_route:net_hang``), process-level chaos grammar (``proc_kill``),
replica and ``cli.serve`` graceful SIGTERM, the end-to-end fleet
(SIGKILL → respawn → rolling restart, dropped=0 / double_served=0), the
fleet soak scenario, and the trace_report / trace_diff fabric surfaces.
"""

from __future__ import annotations

import importlib.util
import json
import signal
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.obs.export import (
    MetricsExporter,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import MetricsHub
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
    segments as sgm,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

FIXTURE = Path(__file__).parent / "fixtures" / "tiny.txt"
SCFG = TfidfConfig(vocab_bits=10)
REPO = Path(__file__).resolve().parents[1]


def _tool(name: str):
    spec = importlib.util.spec_from_file_location(
        f"fabric_test_{name}", REPO / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _seal(d, docs, base=0):
    out = run_tfidf(docs, SCFG)
    ref = sgm.seal_segment(str(d), out, SCFG, doc_base=base,
                           ranks=np.ones(out.n_docs, np.float32),
                           bm25=Bm25Config())
    return sgm.commit_append(str(d), ref, SCFG.config_hash()), out.n_docs


def _docs():
    return FIXTURE.read_text().splitlines()


# ------------------------------------------------------------------ ring


def test_ring_remap_bound_on_replica_loss():
    """The consistent-hash property the sharded cache rides: removing a
    replica remaps ONLY the keys it owned — every key owned by a
    survivor keeps its owner, and the remapped fraction stays near 1/N
    instead of the ~(N-1)/N a modulo router would reshuffle."""
    n = 4
    full = fabric._Ring(range(n), slots=64)
    survivors = fabric._Ring([1, 2, 3], slots=64)
    keys = [f"key-{i}" for i in range(600)]
    owner_full = {k: full.route(k)[0] for k in keys}
    owner_after = {k: survivors.route(k)[0] for k in keys}
    remapped = 0
    for k in keys:
        if owner_full[k] == 0:
            remapped += 1
        else:
            # survivor-owned keys NEVER move
            assert owner_after[k] == owner_full[k]
    # expected ~1/N; allow generous vnode variance, still far from 1/2
    assert remapped / len(keys) < 0.45


def test_ring_preference_order_and_exclude():
    ring = fabric._Ring(range(3), slots=32)
    order = ring.route("some-key")
    assert sorted(order) == [0, 1, 2]  # every replica appears once
    primary = order[0]
    excluded = ring.route("some-key", exclude={primary})
    # the suspect moves to the BACK, it does not vanish
    assert sorted(excluded) == [0, 1, 2]
    assert excluded[-1] == primary
    assert excluded[0] == order[1]
    # with everyone suspect the caller still gets candidates
    assert sorted(ring.route("some-key", exclude={0, 1, 2})) == [0, 1, 2]


def test_affinity_key_canonicalization():
    a = fabric.affinity_key(["graph", "directed", "graph"], "tfidf")
    b = fabric.affinity_key(["directed", "graph"], "tfidf")
    assert a == b  # order- and duplicate-insensitive, like the LRU key
    assert a != fabric.affinity_key(["directed", "graph"], "bm25")


# ----------------------------------------------------------------- floor


def test_floor_round_trip_and_corruption(tmp_path):
    d = str(tmp_path)
    assert fabric.read_floor(d) == 0  # never committed: everything servable
    fabric.commit_floor(d, 3)
    assert fabric.read_floor(d) == 3
    fabric.commit_floor(d, 5)
    assert fabric.read_floor(d) == 5
    # a torn/garbage floor file reads as 0, never raises into serving
    (tmp_path / fabric.FLOOR_FILE).write_text("{not json")
    assert fabric.read_floor(d) == 0


def test_replica_refuses_pre_floor_artifact_then_catches_up(tmp_path):
    """The floor is ENFORCED: a replica restarted mid-rolling-swap that
    can only see a pre-floor manifest comes up UNREADY and 503s queries;
    once the fleet's generation lands on disk its poll loop catches up
    and it starts serving."""
    docs = _docs()
    v1, n1 = _seal(tmp_path, docs[:5])
    assert v1 == 1
    fabric.commit_floor(str(tmp_path), 2)  # the fleet committed gen 2
    rep = fabric._Replica(str(tmp_path), replica_id=0, top_k=5,
                          max_batch=None, scoring="coo", poll_s=0.05)
    rep.start()
    try:
        assert not rep.ready()
        code, _, body = rep.handle_query(json.dumps(
            {"rid": "r1", "terms": ["node"], "ranker": "tfidf"}
        ).encode())
        assert code == 503
        assert json.loads(body)["floor"] == 2
        # generation 2 commits; the poll loop picks it up
        _seal(tmp_path, docs[5:], base=n1)
        deadline = time.monotonic() + 10.0
        while not rep.ready() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert rep.ready()
        code, _, body = rep.handle_query(json.dumps(
            {"rid": "r2", "terms": ["node"], "ranker": "tfidf"}
        ).encode())
        assert code == 200
        assert json.loads(body)["generation"] == 2
    finally:
        rep.stop()


def test_replica_rid_replay_is_idempotent(tmp_path):
    """A re-dispatched request id REPLAYS the cached bytes instead of
    re-executing — the cross-process double-serve guard."""
    _seal(tmp_path, _docs())
    rep = fabric._Replica(str(tmp_path), replica_id=0, top_k=5,
                          max_batch=None, scoring="coo", poll_s=5.0)
    rep.start()
    try:
        body = json.dumps({"rid": "dup-1", "terms": ["node"],
                           "ranker": "tfidf"}).encode()
        first = rep.handle_query(body)
        again = rep.handle_query(body)
        assert first == again  # byte-identical replay
        assert rep._executions == 1 and rep._replays == 1
        rep.handle_query(json.dumps({"rid": "dup-2", "terms": ["node"],
                                     "ranker": "tfidf"}).encode())
        assert rep._executions == 2
    finally:
        rep.stop()


def test_crash_harness_covers_floor_commit():
    """The tier-5 kill-point harness sweeps the floor-commit boundary
    (the 'floor' scenario) and the static enumeration declares it."""
    ch = _tool("crash_harness")
    assert "floor" in ch._SCENARIOS
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis.persistence import (
        CRASH_ENTRIES,
    )
    assert any(e.endswith("serving/fabric.py::commit_floor")
               for e in CRASH_ENTRIES)


# --------------------------------------------------- chaos grammar (proc)


def test_chaos_proc_kill_schedule(monkeypatch):
    """``proc_kill`` SIGKILLs the CURRENT process at the scheduled call
    — observed here by monkeypatching os.kill (the documented test
    seam): ``replica_query:proc_kill@2`` fires on call 2 only."""
    kills: list[tuple] = []
    monkeypatch.setattr("os.kill", lambda pid, sig: kills.append((pid, sig)))
    with chaos.inject("replica_query:proc_kill@2"):
        chaos.on_call("replica_query")
        assert kills == []
        chaos.on_call("replica_query")
    assert len(kills) == 1
    assert kills[0][1] == signal.SIGKILL


def test_chaos_proc_kill_mid_swap(monkeypatch):
    """``replica_swap:proc_kill@1`` — the kill-during-hot-swap scenario:
    the kill lands inside the guarded swap attempt, before the new
    generation is published."""
    kills: list[tuple] = []
    monkeypatch.setattr("os.kill", lambda pid, sig: kills.append((pid, sig)))
    with chaos.inject("replica_swap:proc_kill@1"):
        chaos.on_call("replica_swap")
    assert len(kills) == 1


def test_chaos_net_hang_param_is_milliseconds():
    plan = chaos.parse_plan("fabric_route:net_hang@1:80")
    assert plan[0].kind == "net_hang" and plan[0].param == 80.0
    # default: a 500 ms stall a request timeout should absorb
    assert chaos.parse_plan("fabric_route:net_hang@1")[0].param == 500.0
    t0 = time.perf_counter()
    with chaos.inject("fabric_route:net_hang@1:80"):
        chaos.on_call("fabric_route")  # sleeps 80 ms, then proceeds
    assert time.perf_counter() - t0 >= 0.07


def test_chaos_net_partition_is_transient_chaos_error():
    with chaos.inject("fabric_route:net_partition@1"):
        with pytest.raises(chaos.PartitionError):
            chaos.on_call("fabric_route")
    assert issubclass(chaos.PartitionError, chaos.ChaosError)


# ------------------------------------------------- router (stub replicas)


class _StubFleet:
    """In-process stand-ins for replica processes: each 'replica' is a
    MetricsExporter serving the SAME (method, path) route contract the
    real replica registers, so the router code under test is exercised
    byte-for-byte — minus the fork."""

    def __init__(self, handlers):
        self.exporters = [
            MetricsExporter(MetricsHub(), port=0,
                            routes={("POST", "/query"): h}).start()
            for h in handlers
        ]

    def ports(self):
        return [e.port for e in self.exporters]

    def stop(self):
        for e in self.exporters:
            e.stop()


def _stub_router(tmp_path, ports, **cfg_overrides):
    cfg = fabric.FabricConfig(replicas=len(ports), retry_pause_s=0.01,
                              request_timeout_s=5.0, **cfg_overrides)
    fab = fabric.ServingFabric(str(tmp_path), cfg)
    # routed without start(): no child processes (id-keyed since ISSUE 19)
    fab._ports = dict(enumerate(ports))
    return fab


def _ok_handler(replica_id, seen=None):
    def handle(body: bytes):
        req = json.loads(body.decode())
        if seen is not None:
            seen.append(req["rid"])
        return (200, "application/json", json.dumps({
            "rid": req["rid"], "replica": replica_id, "generation": 1,
            "scores": [1.0], "docs": [0],
        }))
    return handle


def _unready_handler(body: bytes):
    return (503, "application/json",
            json.dumps({"error": "replica below generation floor"}))


def test_router_retries_sibling_on_unready_replica(tmp_path):
    """One replica 503s (below floor / shutting down): the router tries
    the sibling under the SAME rid — served, not dropped, not suspect."""
    seen: list[str] = []
    stubs = _StubFleet([_unready_handler, _ok_handler(1, seen)])
    try:
        fab = _stub_router(tmp_path, stubs.ports(), retry_limit=8)
        for _ in range(4):
            scores, docs = fab.query(["alpha", "beta"])
            assert scores.dtype == np.float32 and docs.dtype == np.int32
        audit = fab.audit()
        assert audit["delivered"] == 4 and audit["dropped"] == 0
        assert audit["double_served"] == 0
        assert len(seen) == len(set(seen)) == 4  # fresh rid per query
    finally:
        stubs.stop()


def test_router_partition_reroutes_to_sibling(tmp_path):
    """``fabric_route:net_partition@1``: the first router→replica hop
    partitions; the target is marked suspect and the query re-dispatches
    to the sibling under the same rid."""
    seen: list[str] = []
    stubs = _StubFleet([_ok_handler(0, seen), _ok_handler(1, seen)])
    try:
        fab = _stub_router(tmp_path, stubs.ports(), retry_limit=8)
        with chaos.inject("fabric_route:net_partition@1"):
            fab.query(["gamma"])
        audit = fab.audit()
        assert audit["delivered"] == 1 and audit["dropped"] == 0
        assert len(fab._suspect) == 1  # the partitioned hop's target
        assert len(seen) == 1  # exactly one replica executed it
    finally:
        stubs.stop()


def test_router_survives_net_hang(tmp_path):
    """``fabric_route:net_hang@1:80``: the hop stalls 80 ms inside the
    guarded attempt, then completes — absorbed, not failed."""
    stubs = _StubFleet([_ok_handler(0), _ok_handler(1)])
    try:
        fab = _stub_router(tmp_path, stubs.ports(), retry_limit=8)
        t0 = time.perf_counter()
        with chaos.inject("fabric_route:net_hang@1:80"):
            fab.query(["delta"])
        assert time.perf_counter() - t0 >= 0.07
        assert fab.audit()["dropped"] == 0
    finally:
        stubs.stop()


def test_router_exhaustion_is_typed(tmp_path):
    """Every replica unready for the whole retry window: the caller gets
    a typed FabricExhausted — never a silent drop — and the audit counts
    the request as dropped."""
    stubs = _StubFleet([_unready_handler, _unready_handler])
    try:
        fab = _stub_router(tmp_path, stubs.ports(), retry_limit=4)
        with pytest.raises(fabric.FabricExhausted):
            fab.query(["epsilon"])
        audit = fab.audit()
        assert audit["dropped"] == 1 and audit["delivered"] == 0
    finally:
        stubs.stop()


def test_router_bad_request_raises_value_error(tmp_path):
    def bad_handler(body: bytes):
        return (400, "application/json",
                json.dumps({"error": "unknown ranker 'nope'"}))

    stubs = _StubFleet([bad_handler, bad_handler])
    try:
        fab = _stub_router(tmp_path, stubs.ports(), retry_limit=4)
        with pytest.raises(ValueError, match="unknown ranker"):
            fab.query(["zeta"], ranker="nope")
    finally:
        stubs.stop()


def test_router_affinity_routes_same_key_to_same_replica(tmp_path):
    """The sharded-cache property end to end: the same logical query
    (same affinity key) always lands on the same healthy replica.

    Runs under its own (empty) chaos plan: a ``fabric_route`` fault from
    the ambient tools/chaos.sh gate makes the router CORRECTLY reroute
    one hop to the sibling, which is exactly what the strict (6,0)/(0,6)
    stickiness assertion exists to rule out in the fault-free case —
    retry-under-chaos has its own tests above."""
    seen0: list[str] = []
    seen1: list[str] = []
    stubs = _StubFleet([_ok_handler(0, seen0), _ok_handler(1, seen1)])
    try:
        fab = _stub_router(tmp_path, stubs.ports(), retry_limit=4)
        with chaos.inject(""):
            for _ in range(6):
                fab.query(["stable", "key"])
        assert (len(seen0), len(seen1)) in ((6, 0), (0, 6))
    finally:
        stubs.stop()


# ------------------------------------------------ subprocess: one replica


def test_replica_process_handshake_query_and_sigterm(tmp_path):
    """One REAL replica process: ready handshake on stdout, a /query
    round-trip over HTTP, graceful SIGTERM exit (rc 0)."""
    from page_rank_and_tfidf_using_apache_spark_tpu.resilience import (
        process as procs,
    )

    _seal(tmp_path, _docs())
    handle = procs.ProcessHandle([
        sys.executable, "-m",
        "page_rank_and_tfidf_using_apache_spark_tpu.serving.fabric",
        "--replica", str(tmp_path), "--replica-id", "0", "--port", "0",
        "--top-k", "3",
    ], ready_timeout_s=120.0).spawn()
    try:
        assert handle.ready["ready"] is True
        port = int(handle.ready["port"])
        assert handle.ready["generation"] == 1
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/query",
            data=json.dumps({"rid": "t-1", "terms": ["node"],
                             "ranker": "tfidf"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            resp = json.loads(r.read())
        assert resp["rid"] == "t-1" and resp["generation"] == 1
        # /healthz is the same surface the router health-checks
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as r:
            assert r.status == 200
        rc = handle.terminate(grace_s=20.0)  # SIGTERM, graceful path
        assert rc == 0
    finally:
        handle.kill()


def test_cli_serve_sigterm_graceful(tmp_path):
    """``cli.serve`` under a supervisor's SIGTERM: answers the in-flight
    request, exits rc 0, and stamps ``"shutdown": "sigterm"`` into its
    stats line — the typed-drain satellite of ISSUE 17."""
    from page_rank_and_tfidf_using_apache_spark_tpu import serving
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf as _run,
    )

    out = _run(_docs(), SCFG)
    idx = tmp_path / "idx"
    serving.save_index(str(idx), out, SCFG)
    proc = subprocess.Popen([
        sys.executable, "-m",
        "page_rank_and_tfidf_using_apache_spark_tpu.cli.serve",
        str(idx), "--top-k", "3",
    ], stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        proc.stdin.write("directed graph\n")
        proc.stdin.flush()
        line = proc.stdout.readline()  # interactive mode: answer now
        assert line and "\t" in line
        proc.send_signal(signal.SIGTERM)
        _, err = proc.communicate(timeout=60)
        assert proc.returncode == 0
        stats = json.loads(err.strip().splitlines()[-1])
        assert stats["shutdown"] == "sigterm"
        assert stats["requests"] >= 1
    finally:
        proc.kill()


# -------------------------------------------------- subprocess: the fleet


@pytest.mark.slow
def test_fabric_end_to_end_kill_respawn_and_rolling_restart(tmp_path):
    """The tentpole acceptance scenario at test scale: a 2-replica fleet
    serves under per-replica chaos (``replica_query:proc_kill@3`` kills
    replica 1 mid-query), a SIGKILL on replica 0 recovers through
    sibling retry + supervisor respawn with dropped=0/double_served=0,
    and a rolling restart under a committed generation floor leaves the
    whole fleet at the new generation.  The run is traced and the
    trace_report fabric section must parse out of it."""
    docs = _docs()
    v1, n1 = _seal(tmp_path, docs[:5])
    trace_dir = tmp_path / "trace"
    with obs.run("fabrictest", trace_dir=str(trace_dir)) as r:
        fab = fabric.ServingFabric(str(tmp_path), fabric.FabricConfig(
            replicas=2, poll_s=0.1, health_period_s=0.2,
            retry_limit=100, retry_pause_s=0.1, request_timeout_s=10.0,
            grace_s=10.0,
            # deterministic process-level chaos INSIDE a real replica:
            # replica 1 SIGKILLs itself on its 3rd executed query
            replica_chaos=((1, "replica_query:proc_kill@3"),),
        ))
        with fab:
            for _ in range(8):
                scores, docs_out = fab.query(["node"])
                assert len(scores) > 0
            # hard SIGKILL on replica 0 mid-traffic
            fab.kill_replica(0)
            for _ in range(20):
                fab.query(["directed", "graph"])
            # the supervisor respawned at least one dead replica by now
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if (fab.audit()["respawns"] >= 1
                        and all(s is not None and s.get("ready")
                                for s in fab.statuses())):
                    break
                time.sleep(0.2)
            audit = fab.audit()
            assert audit["respawns"] >= 1
            assert audit["dropped"] == 0 and audit["double_served"] == 0

            # rolling restart under a committed floor at generation 2
            _seal(tmp_path, docs[5:], base=n1)
            assert fab.await_fleet_generation(2, timeout=60.0)
            fab.rolling_restart(timeout=60.0)
            assert fabric.read_floor(str(tmp_path)) == 2
            statuses = fab.statuses()
            assert all(s is not None and s.get("ready")
                       and s.get("generation") >= 2 for s in statuses)
            assert all(s.get("floor") == 2 for s in statuses)
            fab.query(["node"])  # still serving after the roll
            audit = fab.audit()
            assert audit["rolled"] == 2
            assert audit["dropped"] == 0 and audit["double_served"] == 0
    rep = _tool("trace_report").report(r.trace_path)
    fb = rep["fabric"]
    assert fb is not None
    assert fb["replicas"] == 2
    assert fb["kills"] >= 1 and len(fb["respawns"]) >= 1
    assert fb["rolls"] == 2
    assert fb["floor_timeline"] and fb["floor_timeline"][-1]["floor"] == 2
    assert fb["totals"]["dropped"] == 0
    assert fb["totals"]["double_served"] == 0


@pytest.mark.slow
def test_fleet_soak_scenario(tmp_path):
    """The soak harness's fleet scenario: N=2 replicas under continuous
    ingest + closed-loop clients, one SIGKILL and one rolling restart
    mid-run, scored on the SAME slo record shape the single-process soak
    publishes (trace_report/trace_diff work unchanged)."""
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.soak import (
        FleetSoakConfig,
        run_fleet_soak,
    )

    trace_dir = tmp_path / "trace"
    with obs.run("fleettest", trace_dir=str(trace_dir)) as r:
        rec = run_fleet_soak(FleetSoakConfig(
            duration_s=18.0, qps=6.0, clients=2, replicas=2,
            rebuild_every_s=6.0, kill_at_s=5.0, roll_at_s=11.0,
        ))
    assert rec["requests"] > 10
    assert rec["dropped"] == 0 and rec["double_served"] == 0
    assert rec["recovery"]["losses_injected"] == 1
    assert rec["recovery"]["time_to_recover_s"] is not None
    assert rec["fleet"]["respawns"] >= 1
    assert rec["fleet"]["rolled"] == 2 and rec["fleet"]["roll"]["ok"]
    assert rec["fleet"]["floor"] >= 1
    assert rec["served_p99_ms"] is not None
    assert rec["error_budget"]["availability"]["total"] > 0
    # the slo event landed in the trace where trace_report renders it
    # and trace_diff regresses it — SAME record shape as run_soak
    rep = _tool("trace_report").report(r.trace_path)
    assert rep["slo"] is not None
    assert rep["slo"]["dropped"] == 0
    assert rep["slo"]["fleet"]["rolled"] == 2


# ------------------------------------------------- trace_diff fabric gate


def _bench(tmp_path, name, extra):
    p = tmp_path / name
    p.write_text(json.dumps({"extra": extra}))
    return str(p)


def test_trace_diff_fabric_regressions(tmp_path):
    td = _tool("trace_diff")
    old = td.load_fabric(_bench(tmp_path, "old.json", {
        "fabric_qps": {"n1": 100.0, "n4": 180.0},
        "fabric_recovery_s": 2.0, "fabric_dropped": 0,
        "fabric_double_served": 0,
    }))
    # QPS collapse at one fleet size regresses
    new = td.load_fabric(_bench(tmp_path, "new.json", {
        "fabric_qps": {"n1": 98.0, "n4": 90.0},
        "fabric_recovery_s": 2.1, "fabric_dropped": 0,
        "fabric_double_served": 0,
    }))
    rows = td.diff_fabric(old, new, threshold=0.25)
    assert [r["key"] for r in rows] == ["fabric.qps.n4"]
    # dropped/double-served are invariants: ANY increase regresses
    worse = td.load_fabric(_bench(tmp_path, "worse.json", {
        "fabric_qps": {"n1": 100.0, "n4": 180.0},
        "fabric_recovery_s": 2.0, "fabric_dropped": 1,
        "fabric_double_served": 0,
    }))
    keys = {r["key"] for r in td.diff_fabric(old, worse, threshold=0.25)}
    assert keys == {"fabric.dropped"}
    # recovery growth must clear BOTH the relative threshold and the
    # absolute jitter floor
    slow = td.load_fabric(_bench(tmp_path, "slow.json", {
        "fabric_qps": {"n1": 100.0, "n4": 180.0},
        "fabric_recovery_s": 7.5, "fabric_dropped": 0,
        "fabric_double_served": 0,
    }))
    keys = {r["key"] for r in td.diff_fabric(old, slow, threshold=0.25)}
    assert keys == {"fabric.recovery_s"}


def test_trace_diff_fabric_nulls_and_absence(tmp_path):
    td = _tool("trace_diff")
    # a failed fabric child records nulls: comparisons skip, no crash
    old = td.load_fabric(_bench(tmp_path, "o.json", {
        "fabric_qps": {"n1": None, "n4": 180.0},
        "fabric_recovery_s": None, "fabric_dropped": None,
        "fabric_double_served": None,
    }))
    new = td.load_fabric(_bench(tmp_path, "n.json", {
        "fabric_qps": {"n1": 50.0, "n4": 170.0},
        "fabric_recovery_s": 3.0, "fabric_dropped": 0,
        "fabric_double_served": 0,
    }))
    assert td.diff_fabric(old, new, threshold=0.25) == []
    # pre-fabric rounds: no gate until the first new round
    assert td.load_fabric(_bench(tmp_path, "pre.json", {"qps": 1})) is None
    assert td.diff_fabric(None, new, threshold=0.25) == []
    # a round LOSING its fabric numbers is itself a finding
    rows = td.diff_fabric(new, None, threshold=0.25)
    assert rows and rows[0]["key"] == "fabric.missing"
