"""Distributed correctness on 8 simulated devices (SURVEY.md §4): the key
test is chip-count invariance — same ranks/weights on 1, 2, 4, 8 devices —
over the real psum/all_gather/shard_map code paths."""

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import PageRankConfig, TfidfConfig
from page_rank_and_tfidf_using_apache_spark_tpu.io import from_edges, synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf_streaming
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
    make_mesh,
    partition_graph,
    run_pagerank_sharded,
    run_tfidf_sharded,
)

CFG = PageRankConfig(
    iterations=30, dangling="redistribute", init="uniform", dtype="float64"
)


@pytest.fixture(scope="module")
def graph():
    return synthetic_powerlaw(500, 3000, seed=42)


@pytest.fixture(scope="module")
def single_chip_ranks(graph):
    return run_pagerank(graph, CFG).ranks


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
@pytest.mark.parametrize(
    "strategy",
    ["edges", "nodes", "nodes_balanced", "src", "src_ring", "hybrid",
     "owned"])
def test_chip_count_invariance(graph, single_chip_ranks, n_devices, strategy):
    res = run_pagerank_sharded(graph, CFG, n_devices=n_devices, strategy=strategy)
    assert np.abs(res.ranks - single_chip_ranks).sum() <= 1e-9


@pytest.mark.parametrize("impl", ["cumsum", "cumsum_mxu"])
@pytest.mark.parametrize(
    "strategy", ["edges", "nodes", "nodes_balanced", "src", "src_ring"])
def test_sharded_cumsum_impl_matches_single_chip(
        graph, single_chip_ranks, strategy, impl):
    """The scatter-free monotone-diff SpMVs must agree with segment_sum in
    every sharded layout (local_indptr correctness incl. padding slots —
    and the indptr must actually be BUILT for every prefix-sum impl)."""
    cfg = PageRankConfig(iterations=30, dangling="redistribute", init="uniform",
                         dtype="float64", spmv_impl=impl)
    res = run_pagerank_sharded(graph, cfg, n_devices=8, strategy=strategy)
    assert np.abs(res.ranks - single_chip_ranks).sum() <= 1e-9


def test_sharded_drop_and_one_init(graph):
    """Spark-convention flags work sharded too (init ONE, dangling drop)."""
    cfg = PageRankConfig(iterations=10, dtype="float64")
    base = run_pagerank(graph, cfg).ranks
    res = run_pagerank_sharded(graph, cfg, n_devices=4)
    assert np.abs(res.ranks - base).sum() <= 1e-9


def test_sharded_personalized(graph):
    cfg = PageRankConfig(
        iterations=40, dangling="redistribute", init="uniform",
        personalize=(3, 17), dtype="float64",
    )
    base = run_pagerank(graph, cfg).ranks
    res = run_pagerank_sharded(graph, cfg, n_devices=8, strategy="nodes")
    assert np.abs(res.ranks - base).sum() <= 1e-9


def test_sharded_tolerance(graph):
    cfg = PageRankConfig(
        iterations=500, tol=1e-10, dangling="redistribute", init="uniform",
        dtype="float64",
    )
    res = run_pagerank_sharded(graph, cfg, n_devices=4)
    assert res.iterations < 500
    assert res.l1_delta <= 1e-10


def test_sharded_checkpoint_resume(graph, tmp_path):
    ckdir = str(tmp_path / "ck")
    full = run_pagerank_sharded(graph, CFG, n_devices=4)
    partial = PageRankConfig(
        iterations=10, dangling="redistribute", init="uniform", dtype="float64",
        checkpoint_every=5, checkpoint_dir=ckdir,
    )
    run_pagerank_sharded(graph, partial, n_devices=4)
    resume_cfg = PageRankConfig(
        iterations=30, dangling="redistribute", init="uniform", dtype="float64",
        checkpoint_every=5, checkpoint_dir=ckdir,
    )
    res = run_pagerank_sharded(graph, resume_cfg, n_devices=4, resume=True)
    np.testing.assert_allclose(res.ranks, full.ranks, atol=1e-12)


def test_partition_edges_balanced(graph):
    sg = partition_graph(graph, 8, strategy="edges")
    # perfect balance: every device's slice is full except the last tail
    assert sg.pad_frac < 8 / max(graph.n_edges, 1) + 0.01
    assert (np.diff(sg.dst.ravel()[sg.valid.ravel() > 0]) >= 0).all()


@pytest.mark.parametrize("strategy", ["nodes", "nodes_balanced"])
def test_partition_nodes_covers_all_edges(graph, strategy):
    sg = partition_graph(graph, 8, strategy=strategy)
    assert int(sg.valid.sum()) == graph.n_edges
    # dst_local within block bounds
    assert (sg.dst >= 0).all() and (sg.dst < sg.block).all()
    # node_map is a bijection into per-device slots
    assert len(np.unique(sg.node_map)) == graph.n_nodes


def test_partition_nodes_balanced_evens_powerlaw_edges():
    """A hub-heavy graph: equal-node blocks concentrate in-edges on one
    device; equal-edge boundaries must spread them to near-parity."""
    rng = np.random.default_rng(0)
    # 2000 nodes; node 0..3 receive ~90% of all edges (celebrities)
    hubs = rng.integers(0, 4, 9000)
    tail = rng.integers(4, 2000, 1000)
    dst = np.concatenate([hubs, tail])
    src = rng.integers(0, 2000, dst.size)
    g = from_edges(src, dst)
    plain = partition_graph(g, 8, strategy="nodes")
    balanced = partition_graph(g, 8, strategy="nodes_balanced")

    def max_real_edges(sg):
        return int(sg.valid.sum(axis=1).max())

    # plain 'nodes' puts ~all hub edges on device 0; balanced caps a device
    # at roughly the largest single node's in-degree
    assert max_real_edges(balanced) <= max_real_edges(plain) / 2
    res_b = run_pagerank_sharded(
        g, PageRankConfig(iterations=15, dangling="redistribute",
                          init="uniform", dtype="float64"),
        n_devices=8, strategy="nodes_balanced",
    )
    res_1 = run_pagerank(
        g, PageRankConfig(iterations=15, dangling="redistribute",
                          init="uniform", dtype="float64"),
    )
    assert np.abs(res_b.ranks - res_1.ranks).sum() <= 1e-9


def test_partition_src_covers_all_edges(graph):
    sg = partition_graph(graph, 8, strategy="src")
    assert int(sg.valid.sum()) == graph.n_edges
    # sources are block-local; destinations are global padded ids, sorted
    # per device row (pads at n_pad-1 keep the tail sorted)
    assert (sg.src >= 0).all() and (sg.src < sg.block).all()
    assert all((np.diff(row) >= 0).all() for row in sg.dst)


def test_ring_reduce_scatter_matches_psum_scatter():
    """The explicit ppermute-ring exchange must agree with XLA's
    psum_scatter bit-for-bit in f64 on every mesh size."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.compat import shard_map

    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import collectives as coll

    rng = np.random.default_rng(3)
    for d in (1, 2, 4, 8):
        mesh = make_mesh(d)
        axis = mesh.axis_names[0]
        x = rng.random((d, d * 16))  # one [D*B] partial per device
        ring = shard_map(
            lambda v: coll.ring_reduce_scatter(v[0], axis)[None, :],
            mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None),
            check_vma=False,
        )
        ref = shard_map(
            lambda v: coll.reduce_scatter(v[0], axis)[None, :],
            mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None),
            check_vma=False,
        )
        got = np.asarray(jax.jit(ring)(x))
        want = np.asarray(jax.jit(ref)(x))
        np.testing.assert_allclose(got, want, atol=1e-12)
        # and both equal the plain sum-then-shard
        np.testing.assert_allclose(
            got.ravel(), x.sum(axis=0), atol=1e-12)


def test_auto_select_strategy(graph, single_chip_ranks):
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        auto_select_strategy,
    )

    # hub-heavy powerlaw graph, generous budget -> degree-aware 'hybrid'
    # (the no-head and starved-budget pins live in test_hybrid_spmv.py)
    assert auto_select_strategy(graph, 8) == "hybrid"
    # starved budget -> the owned-slices layout (ISSUE 15: replicated-
    # state-doesn't-fit is the owned trigger)
    assert auto_select_strategy(graph, 8, hbm_bytes=10_000) == "owned"
    res = run_pagerank_sharded(graph, CFG, n_devices=4, strategy="auto")
    assert any(r.get("event") == "auto_strategy" for r in res.metrics.records)
    assert np.abs(res.ranks - single_chip_ranks).sum() <= 1e-9


def test_spark_exact_sharded_raises(graph):
    cfg = PageRankConfig(iterations=2, spark_exact=True)
    with pytest.raises(NotImplementedError):
        run_pagerank_sharded(graph, cfg, n_devices=2)


def test_tfidf_sharded_matches_streaming():
    docs = [f"w{i % 7} w{i % 3} common tail{i}" for i in range(40)]
    chunks = [docs[i : i + 5] for i in range(0, 40, 5)]
    cfg = TfidfConfig(vocab_bits=12, idf_mode="smooth", l2_normalize=True)
    base = run_tfidf_streaming(iter(chunks), cfg)
    for d in (2, 8):
        out = run_tfidf_sharded(iter(chunks), cfg, n_devices=d)
        assert out.n_docs == base.n_docs
        np.testing.assert_array_equal(out.df, base.df)
        np.testing.assert_allclose(out.to_dense(), base.to_dense(), atol=1e-6)


def test_tfidf_sharded_uneven_tail():
    """Last super-chunk smaller than the device count must still work."""
    docs = [f"a b c d{i}" for i in range(11)]
    chunks = [docs[i : i + 2] for i in range(0, 11, 2)]  # 6 chunks, d=4
    cfg = TfidfConfig(vocab_bits=10)
    base = run_tfidf_streaming(iter(chunks), cfg)
    out = run_tfidf_sharded(iter(chunks), cfg, n_devices=4)
    np.testing.assert_allclose(out.to_dense(), base.to_dense(), atol=1e-6)


def test_make_mesh_too_many_devices():
    with pytest.raises(ValueError, match="available"):
        make_mesh(99)
