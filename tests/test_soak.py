"""Production soak harness (ISSUE 11): SLO record, recovery after an
injected device loss, the zero-dropped / zero-double-served invariants
across mid-soak server rebuilds, the live endpoint agreement, and the
trace_report / trace_diff SLO surfaces.

The two short soak runs here are the acceptance scenario at test scale
(seconds, not minutes): tools/ci.sh runs the ~20 s smoke gate and the
bench round runs the full >= 60 s one.
"""

from __future__ import annotations

import importlib.util
import json
from pathlib import Path

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.serving.soak import (
    SoakConfig,
    run_soak,
)

REPO = Path(__file__).resolve().parents[1]


def _tool(name: str):
    spec = importlib.util.spec_from_file_location(
        f"soak_test_{name}", REPO / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def loss_soak(tmp_path_factory):
    """ONE traced soak with an injected device loss, shared by the
    record/trace assertions below (a soak costs wall-clock by design)."""
    trace_dir = tmp_path_factory.mktemp("soak_trace")
    with obs.run("soaktest", trace_dir=str(trace_dir)) as r:
        record = run_soak(SoakConfig(
            duration_s=6.0, qps=20.0, clients=2,
            rebuild_every_s=2.5, chunk_interval_s=0.3,
            prior_refresh_every_s=2.0,
            losses=1, loss_at_s=2.0, grace_s=20.0,
        ))
    return record, r.trace_path


def test_soak_slo_record_acceptance(loss_soak):
    """The acceptance record: served p50/p99 under ingest load, error
    budget, a measured time-to-recover for the injected loss, and
    dropped/double-served == 0."""
    rec, _ = loss_soak
    assert rec["requests"] > 40
    assert rec["served_p50_ms"] is not None
    assert rec["served_p99_ms"] is not None
    assert rec["served_p99_ms"] >= rec["served_p50_ms"]
    # the loss fired and the supervisor measurably recovered
    assert rec["chaos_losses"] >= 1
    recov = rec["recovery"]
    assert recov["losses_injected"] == 1
    assert recov["time_to_recover_s"] is not None
    assert 0.0 < recov["time_to_recover_s"] < 20.0
    assert recov["recoveries"][0]["reason"] == "device_loss"
    # the invariants: every logical request served exactly once
    assert rec["dropped"] == 0
    assert rec["double_served"] == 0
    # ingest ran CONCURRENTLY: chunks streamed and versions committed
    assert rec["ingest"]["chunks"] > 0
    assert rec["ingest"]["rebuilds"] >= 1
    assert rec["ingest"]["index_version"] >= 2
    # mixed traffic actually mixed
    mixed = rec["mixed_traffic"]
    assert sum(mixed.values()) == rec["requests"]
    assert mixed["tfidf"] > 0 and mixed["bm25"] > 0 and mixed["prior"] > 0
    # error budgets present with the configured targets
    avail = rec["error_budget"]["availability"]
    assert avail["target"] == 0.999
    assert avail["total"] >= rec["requests"]
    assert "burn_rate" in avail and "consumed_frac" in avail


def test_soak_endpoint_serves_live_window(loss_soak):
    """The live metrics endpoint answered mid-run and its p99 agrees
    with the hub window the final record was scored from (the HTTP view
    IS the instrument, not a parallel bookkeeping path)."""
    rec, _ = loss_soak
    ep = rec["endpoint"]
    assert ep["port"] > 0
    mid = ep["mid"]
    assert mid is not None and "error" not in mid
    assert mid["http_p99_ms"] is not None
    # same instrument, same moment: the HTTP read equals the direct read
    assert mid["http_p99_ms"] == pytest.approx(mid["direct_p99_ms"],
                                               rel=0.25)
    # and the mid-run window agrees with the final record's window to
    # within run-phase drift (both read the same rolling histogram)
    assert rec["served_p99_ms"] == pytest.approx(mid["http_p99_ms"], rel=5.0)


def test_soak_slo_record_lands_in_trace(loss_soak):
    """The soak publishes its record as an ``slo`` event: trace_report
    picks it up as a first-class section and renders it."""
    rec, trace_path = loss_soak
    tr = _tool("trace_report")
    rep = tr.report(trace_path)
    assert rep["slo"] is not None
    assert rep["slo"]["served_p99_ms"] == rec["served_p99_ms"]
    assert rep["slo"]["dropped"] == 0
    human = tr.render_human(rep)
    assert "slo:" in human and "error budget" in human
    assert "time-to-recover" in human


def test_soak_rebuild_hot_swap_no_drop_no_double(tmp_path):
    """The mid-soak server-rebuild invariant in isolation: aggressive
    rebuild cadence, NO injected loss — several hot swaps under live
    traffic must drop nothing and double-serve nothing."""
    rec = run_soak(SoakConfig(
        duration_s=5.0, qps=24.0, clients=2,
        rebuild_every_s=1.5, chunk_interval_s=0.25,
        prior_refresh_every_s=30.0,  # prior path exercised elsewhere
        losses=0, grace_s=20.0,
    ), index_dir=str(tmp_path))
    assert rec["ingest"]["rebuilds"] >= 2
    assert rec["ingest"]["index_version"] >= 3  # bootstrap + rebuilds
    assert rec["requests"] > 40
    assert rec["dropped"] == 0
    assert rec["double_served"] == 0
    assert rec["recovery"]["losses_injected"] == 0
    assert rec["recovery"]["time_to_recover_s"] is None
    assert rec["served_p99_ms"] is not None
    # the record is exactly one JSON line's worth of plain data
    json.dumps(rec)


def test_soak_config_from_env(monkeypatch):
    monkeypatch.setenv("GRAFT_SOAK_DURATION_S", "17")
    monkeypatch.setenv("GRAFT_SOAK_QPS", "9")
    monkeypatch.setenv("GRAFT_SOAK_SLO_P99_MS", "123")
    monkeypatch.setenv("GRAFT_SOAK_SLO_AVAILABILITY", "0.99")
    cfg = SoakConfig.from_env(clients=2)
    assert cfg.duration_s == 17.0
    assert cfg.qps == 9.0
    assert cfg.slo_p99_ms == 123.0
    assert cfg.availability_target == 0.99
    assert cfg.clients == 2
    monkeypatch.delenv("GRAFT_SOAK_DURATION_S")
    assert SoakConfig.from_env().duration_s == 60.0


# ------------------------------------------------ trace_diff SLO gate


def _bench_record(path: Path, slo: dict | None,
                  breakdown: dict | None = None) -> str:
    extra: dict = {"breakdown": breakdown or {"tfidf.stream": 10.0},
                   "breakdown_wall_secs": 12.0}
    extra["slo"] = slo
    path.write_text(json.dumps({
        "metric": "pagerank_iters_per_sec_webgoogle_scale",
        "value": 100.0, "unit": "iters/sec", "vs_baseline": 1.5,
        "extra": extra,
    }))
    return str(path)


def _slo(p99: float, consumed: float = 0.1, dropped: int = 0) -> dict:
    return {
        "served_p99_ms": p99,
        "error_budget": {
            "availability": {"target": 0.999, "consumed_frac": consumed},
            "latency": {"target": 0.99, "consumed_frac": 0.0},
        },
        "dropped": dropped,
        "double_served": 0,
    }


def test_trace_diff_slo_p99_regression_fails(tmp_path, capsys):
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json", _slo(p99=50.0))
    new = _bench_record(tmp_path / "BENCH_r02.json", _slo(p99=120.0))
    rc = td.main([old, new, "--threshold", "0.35"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "slo.served_p99_ms" in out and "REGRESSED" in out


def test_trace_diff_slo_budget_regression_fails(tmp_path):
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json",
                        _slo(p99=50.0, consumed=0.10))
    new = _bench_record(tmp_path / "BENCH_r02.json",
                        _slo(p99=50.0, consumed=0.80))
    assert td.main([old, new, "--threshold", "0.35", "--json"]) == 1


def test_trace_diff_slo_invariant_regression_fails(tmp_path):
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json", _slo(p99=50.0))
    new = _bench_record(tmp_path / "BENCH_r02.json",
                        _slo(p99=50.0, dropped=2))
    assert td.main([old, new, "--threshold", "0.35"]) == 1


def test_trace_diff_slo_within_threshold_passes(tmp_path):
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json", _slo(p99=50.0))
    new = _bench_record(tmp_path / "BENCH_r02.json",
                        _slo(p99=55.0, consumed=0.2))
    assert td.main([old, new, "--threshold", "0.35"]) == 0


def test_trace_diff_slo_jitter_floor(tmp_path):
    """Single-digit-ms p99 noise on a fast CPU soak must not fail CI even
    when it is large RELATIVELY (1ms -> 2.5ms is 2.5x but 1.5ms)."""
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json", _slo(p99=1.0))
    new = _bench_record(tmp_path / "BENCH_r02.json", _slo(p99=2.5))
    assert td.main([old, new, "--threshold", "0.35"]) == 0


def test_trace_diff_served_p99_regression_fails(tmp_path, capsys):
    """The ISSUE 13 served-latency gate: a batch size's served p99
    regressing past the threshold fails the diff like an SLO breach."""
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json", None)
    new = _bench_record(tmp_path / "BENCH_r02.json", None)
    for path, p99 in ((old, 20.0), (new, 90.0)):
        rec = json.loads(Path(path).read_text())
        rec["extra"]["served_p99_ms"] = {"b8": p99, "b16": 10.0}
        Path(path).write_text(json.dumps(rec))
    rc = td.main([old, new, "--threshold", "0.35"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "served.b8.p99_ms" in out and "REGRESSED" in out


def test_trace_diff_served_jitter_and_fallback(tmp_path):
    """Jitter-floor deltas pass, and the old-round fallback reads the
    per-batch p99 out of extra.served_qps (r07/r08 rounds), so the gate
    arms on the FIRST round that writes the flat maps."""
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json", None)
    new = _bench_record(tmp_path / "BENCH_r02.json", None)
    rec = json.loads(Path(old).read_text())
    rec["extra"]["served_qps"] = {"b8": {"qps": 50.0, "p99_ms": 1.0}}
    Path(old).write_text(json.dumps(rec))
    rec = json.loads(Path(new).read_text())
    rec["extra"]["served_p99_ms"] = {"b8": 2.5}  # 2.5x but 1.5ms: jitter
    Path(new).write_text(json.dumps(rec))
    assert td.main([old, new, "--threshold", "0.35"]) == 0
    rec["extra"]["served_p99_ms"] = {"b8": 40.0}  # real regression
    Path(new).write_text(json.dumps(rec))
    assert td.main([old, new, "--threshold", "0.35"]) == 1


def test_trace_diff_served_numbers_vanishing_fails(tmp_path):
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json", None)
    new = _bench_record(tmp_path / "BENCH_r02.json", None)
    rec = json.loads(Path(old).read_text())
    rec["extra"]["served_p99_ms"] = {"b8": 20.0}
    Path(old).write_text(json.dumps(rec))
    assert td.main([old, new, "--threshold", "0.35"]) == 1


def test_trace_diff_slo_absent_on_old_round_is_not_a_regression(tmp_path):
    """r08 and earlier carry no SLO record: the first SLO-carrying round
    must not fail the gate against them — but LOSING the record once the
    trajectory has one is itself a regression."""
    td = _tool("trace_diff")
    old = _bench_record(tmp_path / "BENCH_r01.json", None)
    new = _bench_record(tmp_path / "BENCH_r02.json", _slo(p99=50.0))
    assert td.main([old, new, "--threshold", "0.35"]) == 0
    old2 = _bench_record(tmp_path / "BENCH_r03.json", _slo(p99=50.0))
    new2 = _bench_record(tmp_path / "BENCH_r04.json", None)
    assert td.main([old2, new2, "--threshold", "0.35"]) == 1
