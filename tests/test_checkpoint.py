"""Checkpoint/resume + fault injection (SURVEY.md §5.3/§5.4): recovery on
TPU is restart-from-snapshot; these tests kill runs mid-flight and assert
bit-equal results after resume."""

import glob
import os

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import PageRankConfig, TfidfConfig, pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.io import synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf_streaming
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt


def test_pagerank_checkpoint_resume_identical(tmp_path):
    g = synthetic_powerlaw(100, 400, seed=11)
    base_cfg = dict(iterations=12, dangling="redistribute", init="uniform", dtype="float64")
    full = pagerank(g, PageRankConfig(**base_cfg))

    # run with checkpoints, "crash" by only running the first 8 iterations
    ckdir = str(tmp_path / "ck")
    partial_cfg = PageRankConfig(**{**base_cfg, "iterations": 8},
                                 checkpoint_every=4, checkpoint_dir=ckdir)
    run_pagerank(g, partial_cfg)
    assert ckpt.latest_checkpoint(ckdir) is not None

    # resume under the full config and finish
    resume_cfg = PageRankConfig(**base_cfg, checkpoint_every=4, checkpoint_dir=ckdir)
    res = run_pagerank(g, resume_cfg, resume=True)
    np.testing.assert_array_equal(res.ranks, full.ranks)


def test_checkpoint_config_hash_guard(tmp_path):
    g = synthetic_powerlaw(50, 150, seed=2)
    ckdir = str(tmp_path / "ck")
    cfg = PageRankConfig(iterations=8, checkpoint_every=2, checkpoint_dir=ckdir,
                         dangling="redistribute", init="uniform")
    run_pagerank(g, cfg)
    other = PageRankConfig(iterations=8, damping=0.5, checkpoint_every=2,
                           checkpoint_dir=ckdir, dangling="redistribute", init="uniform")
    with pytest.raises(ValueError, match="refusing to resume"):
        run_pagerank(g, other, resume=True)


def test_atomic_write_survives_partial_tmp(tmp_path):
    """A leftover .tmp file (simulated kill mid-write) must not corrupt the
    LATEST pointer or the resumable state."""
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 3, {"x": np.arange(4)}, "h")
    with open(os.path.join(d, "junk.tmp"), "wb") as f:
        f.write(b"\x00garbage")  # simulated torn write
    latest = ckpt.latest_checkpoint(d)
    step, arrays, _ = ckpt.load_checkpoint(latest, "h")
    assert step == 3
    np.testing.assert_array_equal(arrays["x"], np.arange(4))


def test_pagerank_resume_rejects_different_graph(tmp_path):
    """The config hash excludes the input graph; a checkpoint from a
    different-sized graph must fail loudly, not partially initialize."""
    ckdir = str(tmp_path / "ck")
    base = dict(iterations=6, checkpoint_every=2, checkpoint_dir=ckdir,
                dangling="redistribute", init="uniform")
    run_pagerank(synthetic_powerlaw(40, 120, seed=3), PageRankConfig(**base))
    with pytest.raises(ValueError, match="different graph"):
        run_pagerank(synthetic_powerlaw(80, 240, seed=3), PageRankConfig(**base),
                     resume=True)


def test_tfidf_sharded_checkpoint_resume(tmp_path):
    """Sharded ingest checkpoints at the same chunk cadence as streaming and
    resumes mid-corpus to the same result."""
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import run_tfidf_sharded

    docs = [f"tok{i} tok{i % 5} shared word extra{i % 2}" for i in range(32)]
    chunks = [docs[i : i + 2] for i in range(0, 32, 2)]
    base = dict(vocab_bits=12, l2_normalize=True, idf_mode="smooth")
    full = run_tfidf_sharded(iter(chunks), TfidfConfig(**base), n_devices=4)

    ckdir = str(tmp_path / "ck")
    cfg = TfidfConfig(**base, checkpoint_every=4, checkpoint_dir=ckdir)
    run_tfidf_sharded(iter(chunks[:8]), cfg, n_devices=4)  # "crash" mid-corpus
    assert ckpt.latest_checkpoint(ckdir) is not None
    res = run_tfidf_sharded(iter(chunks), cfg, n_devices=4, resume=True)
    assert res.n_docs == full.n_docs
    np.testing.assert_allclose(res.to_dense(), full.to_dense(), atol=1e-6)


def test_tfidf_streaming_resume(tmp_path):
    docs = [f"tok{i} tok{i % 3} shared word" for i in range(12)]
    chunks = [docs[i : i + 3] for i in range(0, 12, 3)]
    cfg = TfidfConfig(vocab_bits=12, checkpoint_every=1,
                      checkpoint_dir=str(tmp_path / "ck"), l2_normalize=True,
                      idf_mode="smooth")
    full = run_tfidf_streaming(chunks, cfg)

    # crash after 2 chunks: feed only the first two, then resume with all
    cfg2 = TfidfConfig(vocab_bits=12, checkpoint_every=1,
                       checkpoint_dir=str(tmp_path / "ck2"), l2_normalize=True,
                       idf_mode="smooth")
    run_tfidf_streaming(chunks[:2], cfg2)
    res = run_tfidf_streaming(chunks, cfg2, resume=True)
    assert res.n_docs == full.n_docs
    np.testing.assert_allclose(res.to_dense(), full.to_dense(), atol=1e-6)
