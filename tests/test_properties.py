"""Property-based tests (SURVEY.md §4): rank-mass conservation, node-relabel
invariance, hashed-vocab ≈ exact-vocab convergence."""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from page_rank_and_tfidf_using_apache_spark_tpu import pagerank, tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.io import from_edges
from page_rank_and_tfidf_using_apache_spark_tpu.io.text import fnv1a_64, hash_to_vocab


edges_strategy = st.lists(
    st.tuples(st.integers(0, 15), st.integers(0, 15)), min_size=1, max_size=60
)


@settings(max_examples=20, deadline=None)
@given(edges_strategy)
def test_rank_mass_conserved(edges):
    a = np.array(edges)
    g = from_edges(a[:, 0], a[:, 1])
    res = pagerank(g, iterations=25, dangling="redistribute", init="uniform",
                   dtype="float64")
    assert abs(res.ranks.sum() - 1.0) < 1e-9
    assert (res.ranks >= 0).all()


@settings(max_examples=15, deadline=None)
@given(edges_strategy, st.integers(0, 1000))
def test_relabel_invariance(edges, offset):
    """Adding a constant to every node id must not change the ranks (ids are
    opaque keys in the reference's RDDs)."""
    a = np.array(edges)
    g1 = from_edges(a[:, 0], a[:, 1])
    g2 = from_edges(a[:, 0] + offset, a[:, 1] + offset)
    r1 = pagerank(g1, iterations=20, dangling="redistribute", init="uniform",
                  dtype="float64")
    r2 = pagerank(g2, iterations=20, dangling="redistribute", init="uniform",
                  dtype="float64")
    np.testing.assert_allclose(r1.ranks, r2.ranks, atol=1e-12)


@settings(max_examples=10, deadline=None)
@given(st.lists(st.text(alphabet="abcdefgh", min_size=1, max_size=6),
                min_size=1, max_size=30))
def test_hashed_vocab_converges_to_exact(tokens):
    """With a wide enough hash, hashed TF-IDF == exact-vocab TF-IDF: weights
    keyed by token hash must match a collision-free computation
    (SURVEY.md §4 'hashed-vocab ≈ exact-vocab as hash width → large')."""
    doc = " ".join(tokens)
    out = tfidf([doc], vocab_bits=22, idf_mode="smooth")
    uniq = sorted(set(tokens))
    hids = hash_to_vocab(fnv1a_64(uniq), 22)
    if len(set(hids.tolist())) != len(uniq):
        return  # collision at 2^22 is astronomically unlikely; skip if so
    # every unique token appears with weight idf*(count); smooth idf with
    # N=1, df=1 gives idf=1, so weight == count
    counts = {t: tokens.count(t) for t in uniq}
    dense = out.to_dense()
    for t, h in zip(uniq, hids):
        assert dense[0, int(h)] == counts[t]
