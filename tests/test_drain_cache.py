"""Zero-downtime drain handoff + partition-tolerant sharded cache
(serving/fabric.py, ISSUE 20).

Two layers under test:

- **Drain by handoff, not retry**: ``rolling_restart`` with
  ``FabricConfig.handoff`` spawns the successor into the predecessor's
  SO_REUSEPORT listener group first, waits for its deferred ready
  handshake, then TERMs the predecessor which drains in-flight requests
  to completion — a roll under closed-loop load finishes with ZERO
  roll-attributed retries and the 0/0 dropped/double-served audit
  intact.  A successor spawn killed by chaos (``drain_handoff:fail@1``)
  aborts the roll with the predecessor untouched and still serving.

- **Sharded result cache**: the ring owner of an affinity key is its
  cache authority — a non-owner replica peeks the owner under a bounded
  deadline (``cache_peek`` site) before computing and fills it back
  asynchronously (``cache_fill`` site), every peer hop behind a per-peer
  circuit breaker.  Peer partition (``cache_peek:net_partition@``) and
  hang (``cache_peek:net_hang@``) chaos degrade gracefully to local
  compute: served bytes identical on every path, latency bounded by the
  peek deadline, breaker trips within the configured count and recovers
  through its half-open probe.
"""

from __future__ import annotations

import importlib.util
import json
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.obs.export import (
    MetricsExporter,
    reuse_port_supported,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import MetricsHub
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
    segments as sgm,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

FIXTURE = Path(__file__).parent / "fixtures" / "tiny.txt"
REPO = Path(__file__).parent.parent
SCFG = TfidfConfig(vocab_bits=10)


@pytest.fixture(autouse=True)
def _hermetic_chaos(monkeypatch):
    """The chaos gate (tools/chaos.sh) reruns tier-1 under an ambient
    ``*:fail@%5`` plan; these tests pin EXACT peer/breaker/roll ledgers
    (roll_retries == 0, breaker trip counts, byte-equality across
    specific serve paths), so an ambient transient would land in the
    very numbers under test.  Per the gate's contract, tests install
    their own plan: ``inject("")`` shadows the env plan in-process
    WITHOUT touching its per-site counters (downstream files keep their
    phase), and the env override hands child replicas a clean plan too.
    Tests that want chaos nest their own ``chaos.inject(...)``."""
    monkeypatch.setenv("GRAFT_CHAOS", "")
    with chaos.inject(""):
        yield


def _tool(name: str):
    spec = importlib.util.spec_from_file_location(
        f"drain_test_{name}", REPO / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _seal(d, docs, base=0):
    out = run_tfidf(docs, SCFG)
    ref = sgm.seal_segment(str(d), out, SCFG, doc_base=base,
                           ranks=np.ones(out.n_docs, np.float32),
                           bm25=Bm25Config())
    return sgm.commit_append(str(d), ref, SCFG.config_hash())


def _docs():
    return FIXTURE.read_text().splitlines()


def _mk_replica(d, rid, **kw):
    rep = fabric._Replica(str(d), replica_id=rid, top_k=5, max_batch=None,
                          scoring="coo", poll_s=5.0, **kw)
    rep.start()
    deadline = time.monotonic() + 15.0
    while not rep.ready() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert rep.ready()
    return rep


def _query_body(rid, terms, ranker="tfidf"):
    return json.dumps({"rid": rid, "terms": terms,
                       "ranker": ranker}).encode()


def _owned_terms(owner_id, ids, slots=64, ranker="tfidf"):
    """A single-word query routed to ``owner_id`` by the cache ring."""
    ring = fabric._Ring(sorted(ids), slots)
    for w in _docs()[0].split() + ["alpha", "beta", "gamma", "delta"]:
        if ring.route(fabric.affinity_key([w], ranker))[0] == owner_id:
            return [w]
    raise AssertionError("no fixture word routed to the wanted owner")


# ------------------------------------------------------------- breaker


def test_breaker_trips_half_opens_and_recloses():
    br = fabric._Breaker(trip=3, probe_s=2.0)
    assert br.allow(now=0.0) and br.state == "closed"
    br.record_failure(now=0.0)
    br.record_failure(now=0.1)
    assert br.state == "closed"  # under the trip count
    br.record_failure(now=0.2)
    assert br.state == "open"
    assert not br.allow(now=0.3)  # open: fail fast, no peer I/O
    assert not br.allow(now=2.1)
    assert br.allow(now=2.3)  # probe period elapsed -> ONE half-open probe
    assert br.state == "half_open"
    br.record_failure(now=2.4)  # failed probe re-opens immediately
    assert br.state == "open"
    assert br.allow(now=4.5)
    br.record_success()
    assert br.state == "closed" and br.failures == 0


def test_breaker_success_resets_consecutive_count():
    br = fabric._Breaker(trip=2, probe_s=1.0)
    br.record_failure(now=0.0)
    br.record_success()  # trip counts CONSECUTIVE timeouts only
    br.record_failure(now=0.1)
    assert br.state == "closed"
    br.record_failure(now=0.2)
    assert br.state == "open"


# ----------------------------------------------------- peek/fill handlers


def test_cache_peek_miss_hit_and_malformed(tmp_path):
    _seal(tmp_path, _docs())
    rep = _mk_replica(tmp_path, 0)
    try:
        code, _, body = rep.handle_cache_peek(
            json.dumps({"terms": ["node"]}).encode())
        assert code == 200 and json.loads(body)["hit"] is False
        # prime the local LRU through the serve path, then peek again
        _, _, qbody = rep.handle_query(_query_body("pk-1", ["node"]))
        served = json.loads(qbody)
        code, _, body = rep.handle_cache_peek(
            json.dumps({"terms": ["node"], "ranker": "tfidf"}).encode())
        peek = json.loads(body)
        assert code == 200 and peek["hit"] is True
        assert peek["generation"] == served["generation"]
        # byte-equal: the peeked values re-serialize to the served ones
        assert peek["scores"] == served["scores"]
        assert peek["docs"] == served["docs"]
        code, _, _ = rep.handle_cache_peek(b"{not json")
        assert code == 400
        code, _, _ = rep.handle_cache_peek(b"[]")
        assert code == 400
    finally:
        rep.stop()


def test_cache_fill_is_idempotent_by_rid_and_generation_gated(tmp_path):
    gen = _seal(tmp_path, _docs())
    rep = _mk_replica(tmp_path, 0)
    try:
        doc = {"rid": "fl-1", "terms": ["node"], "ranker": "tfidf",
               "scores": [0.5, 0.25], "docs": [1, 0], "generation": gen}
        first = rep.handle_cache_fill(json.dumps(doc).encode())
        assert first[0] == 200 and json.loads(first[2])["stored"] is True
        stats = rep.srv.stats()
        assert stats["peer_stores"] == 1
        # replay: same bytes, no second store, counted as a replay
        again = rep.handle_cache_fill(json.dumps(doc).encode())
        assert again == first
        assert rep.srv.stats()["peer_stores"] == 1
        assert rep._replays == 1
        # the filled entry serves through the peek path
        code, _, body = rep.handle_cache_peek(
            json.dumps({"terms": ["node"]}).encode())
        peek = json.loads(body)
        assert code == 200 and peek["hit"] is True
        assert peek["scores"] == [0.5, 0.25] and peek["docs"] == [1, 0]
        # a stale-generation fill is refused (200, stored=false): a
        # straggler from before a hot-swap must not resurrect old scores
        stale = dict(doc, rid="fl-2", generation=gen + 7)
        code, _, body = rep.handle_cache_fill(json.dumps(stale).encode())
        assert code == 200 and json.loads(body)["stored"] is False
        # missing required key -> typed 400
        code, _, _ = rep.handle_cache_fill(
            json.dumps({"rid": "fl-3", "terms": ["node"]}).encode())
        assert code == 400
    finally:
        rep.stop()


def test_cache_fill_below_floor_is_typed_503_with_floor(tmp_path):
    gen = _seal(tmp_path, _docs())
    rep = fabric._Replica(str(tmp_path), replica_id=0, top_k=5,
                          max_batch=None, scoring="coo", poll_s=0.05)
    rep.start()
    try:
        deadline = time.monotonic() + 15.0
        while not rep.ready() and time.monotonic() < deadline:
            time.sleep(0.02)
        fabric.commit_floor(str(tmp_path), gen + 1)
        deadline = time.monotonic() + 10.0
        while rep.ready() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not rep.ready()
        doc = {"rid": "fl-floor", "terms": ["node"], "scores": [1.0],
               "docs": [0], "generation": gen}
        code, _, body = rep.handle_cache_fill(json.dumps(doc).encode())
        reply = json.loads(body)
        assert code == 503 and reply["floor"] == gen + 1
    finally:
        rep.stop()


def test_peers_push_installs_ring_and_single_member_disables(tmp_path):
    _seal(tmp_path, _docs())
    rep = _mk_replica(tmp_path, 0)
    try:
        code, _, body = rep.handle_peers(
            json.dumps({"peers": {"0": 1111, "1": 2222}}).encode())
        assert code == 200 and json.loads(body)["ok"] is True
        assert rep._peers == {1: 2222}  # self excluded from the dial map
        assert rep._peer_ring is not None
        # every member must agree on the owner: the ring is built over
        # ALL ids (self included)
        owner = rep._cache_owner(["node"], "tfidf")
        ring = fabric._Ring([0, 1], 64)
        assert owner == ring.route(fabric.affinity_key(["node"], "tfidf"))[0]
        # a solo fleet has no authority to consult
        code, _, _ = rep.handle_peers(
            json.dumps({"peers": {"0": 1111}}).encode())
        assert code == 200
        assert rep._cache_owner(["node"], "tfidf") is None
        code, _, _ = rep.handle_peers(json.dumps({"peers": "x"}).encode())
        assert code == 400
    finally:
        rep.stop()


# ------------------------------------------------- two-replica peer fleet


class _PeerPair:
    """Two in-process replicas served over real exporters with the full
    route table, wired as each other's peers — the sharded-cache fabric
    minus the forks."""

    def __init__(self, d, cache_size=None):
        self.reps = [_mk_replica(d, i, cache_size=cache_size)
                     for i in (0, 1)]
        self.exporters = [
            MetricsExporter(MetricsHub(), port=0, routes={
                ("POST", "/query"): r.handle_query,
                ("GET", "/status"): r.handle_status,
                ("POST", "/cache/peek"): r.handle_cache_peek,
                ("POST", "/cache/fill"): r.handle_cache_fill,
                ("POST", "/peers"): r.handle_peers,
            }, ready=r.ready).start()
            for r in self.reps
        ]
        peers = {i: e.port for i, e in enumerate(self.exporters)}
        for r in self.reps:
            r.configure_peers(peers)

    def ports(self):
        return [e.port for e in self.exporters]

    def stop(self):
        for e in self.exporters:
            e.stop()
        for r in self.reps:
            r.stop()


def _drain_fills(rep, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not rep._fill_q.empty() and time.monotonic() < deadline:
        time.sleep(0.02)
    time.sleep(0.05)  # let the in-flight fill POST land


def test_peer_hit_serves_byte_equal_and_fill_warms_owner(tmp_path):
    _seal(tmp_path, _docs())
    pair = _PeerPair(tmp_path)
    a, b = pair.reps
    try:
        terms = _owned_terms(0, [0, 1])
        # owner computes (and caches) first; the non-owner's miss then
        # peeks the owner instead of computing
        _, _, abody = a.handle_query(_query_body("ph-1", terms))
        _, _, bbody = b.handle_query(_query_body("ph-2", terms))
        served_a, served_b = json.loads(abody), json.loads(bbody)
        assert served_b["scores"] == served_a["scores"]
        assert served_b["docs"] == served_a["docs"]
        assert b._peer_stats["peer_hits"] == 1
        assert b._executions == 1 and a._executions == 1
        # a DIFFERENT owned key misses on the owner too: the non-owner
        # computes locally and fills the owner back asynchronously
        terms2 = None
        ring = fabric._Ring([0, 1], 64)
        for w in ("graph", "edge", "walk", "rank", "sparse", "matrix"):
            if w not in terms and \
                    ring.route(fabric.affinity_key([w], "tfidf"))[0] == 0:
                terms2 = [w]
                break
        assert terms2 is not None
        _, _, body2 = b.handle_query(_query_body("ph-3", terms2))
        _drain_fills(b)
        assert b._peer_stats["fills"] == 1
        assert a.srv.stats()["peer_stores"] == 1
        code, _, peek = a.handle_cache_peek(
            json.dumps({"terms": terms2}).encode())
        peeked = json.loads(peek)
        assert code == 200 and peeked["hit"] is True
        assert peeked["scores"] == json.loads(body2)["scores"]
    finally:
        pair.stop()


def test_cache_peek_partition_degrades_trips_and_recovers(
        tmp_path, monkeypatch):
    """Peer partition on the peek hop: every query still serves the
    correct bytes (local-compute fallback), the owner's breaker opens
    within the configured consecutive-timeout count, and the half-open
    probe recloses it once the partition heals — with the real router
    on top, the audit stays 0/0 throughout.

    The router affinity-routes a key to its ring owner, so the
    non-owner peek path is the FAILOVER surface — exercised here by
    driving the non-owner's /query directly, the shape a suspect-owner
    re-dispatch produces."""
    monkeypatch.setenv("GRAFT_CACHE_BREAKER_TRIP", "2")
    monkeypatch.setenv("GRAFT_CACHE_BREAKER_PROBE_S", "0.3")
    monkeypatch.setenv("GRAFT_CACHE_PEEK_DEADLINE_S", "0.5")
    _seal(tmp_path, _docs())
    pair = _PeerPair(tmp_path)
    a, b = pair.reps
    cfg = fabric.FabricConfig(replicas=2, retry_pause_s=0.01,
                              request_timeout_s=5.0)
    fab = fabric.ServingFabric(str(tmp_path), cfg)
    fab._ports = dict(enumerate(pair.ports()))
    try:
        terms = _owned_terms(0, [0, 1])
        _, _, abody = a.handle_query(_query_body("pt-ref", terms))
        ref = json.loads(abody)
        # distinct owner-routed keys: the non-owner's local LRU must MISS
        # on each so every iteration reaches the (partitioned) peek hop
        ring = fabric._Ring([0, 1], 64)
        owned = [[w] for w in (f"w{i}" for i in range(200))
                 if ring.route(fabric.affinity_key([w], "tfidf"))[0] == 0]
        assert len(owned) >= 4
        with chaos.inject("cache_peek:net_partition@1+;"
                          "cache_fill:net_partition@1+"):
            for n in range(4):
                # routed traffic keeps serving correct bytes mid-partition
                scores, docs = fab.query(terms)
                assert list(map(float, scores)) == ref["scores"]
                assert list(map(int, docs)) == ref["docs"]
                # non-owner traffic: peek partitioned -> local compute
                code, _, _ = b.handle_query(
                    _query_body(f"pt-b{n}", owned[n]))
                assert code == 200
            _drain_fills(b)
        # the non-owner's peek/fill failures tripped the breaker within
        # the configured consecutive count; later queries skipped peer
        # I/O entirely (fail-fast, no deadline burned per request)
        assert b._breakers[0].state == "open"
        assert b._peer_stats["peek_timeouts"] >= 1
        assert b._peer_stats["peeks_skipped_open"] >= 1
        assert b._peer_stats["breaker_trips"] >= 1
        # partition healed: after the probe period one half-open peek
        # goes through, succeeds, and the breaker recloses
        time.sleep(0.35)
        _, _, bbody = b.handle_query(_query_body("pt-heal", terms))
        assert json.loads(bbody)["scores"] == ref["scores"]
        assert b._breakers[0].state == "closed"
        assert b._peer_stats["peer_hits"] >= 1
        audit = fab.audit()
        assert audit["dropped"] == 0 and audit["double_served"] == 0
        assert audit["failed"] == 0
    finally:
        pair.stop()


def test_cache_peek_hang_is_bounded_by_deadline(tmp_path, monkeypatch):
    """A hung owner (chaos ``net_hang``) can cost a request at most the
    peek deadline + one local compute — never the hang duration."""
    monkeypatch.setenv("GRAFT_CACHE_PEEK_DEADLINE_S", "0.15")
    _seal(tmp_path, _docs())
    pair = _PeerPair(tmp_path)
    a, b = pair.reps
    try:
        terms = _owned_terms(0, [0, 1])
        _, _, abody = a.handle_query(_query_body("hg-ref", terms))
        ref = json.loads(abody)
        t0 = time.perf_counter()
        with chaos.inject("cache_peek:net_hang@1:2000"):
            _, _, bbody = b.handle_query(_query_body("hg-1", terms))
        elapsed = time.perf_counter() - t0
        assert json.loads(bbody)["scores"] == ref["scores"]
        assert elapsed < 1.5  # deadline + compute + slack, NOT the 2 s hang
        assert b._peer_stats["peek_timeouts"] == 1
    finally:
        pair.stop()


def test_cache_fill_partition_is_best_effort(tmp_path):
    """A partitioned owner on the write-back path costs nothing: the
    fill is dropped, tallied, and the serve path never notices."""
    _seal(tmp_path, _docs())
    pair = _PeerPair(tmp_path)
    a, b = pair.reps
    try:
        terms = _owned_terms(0, [0, 1])
        with chaos.inject("cache_fill:net_partition@1+"):
            code, _, body = b.handle_query(_query_body("fp-1", terms))
            assert code == 200
            _drain_fills(b)
        assert b._peer_stats["fill_errors"] == 1
        assert a.srv.stats()["peer_stores"] == 0
        # the owner is still healthy for the read path afterwards
        _, _, abody = a.handle_query(_query_body("fp-2", terms))
        assert json.loads(abody)["scores"] == json.loads(body)["scores"]
    finally:
        pair.stop()


# --------------------------------------------------- reuse-port exporter


@pytest.mark.skipif(not reuse_port_supported(),
                    reason="platform lacks SO_REUSEPORT")
def test_reuse_port_listener_group_and_drain_joins_inflight():
    """The handoff transport: two exporters share one port (kernel
    steering), and a draining exporter's stop() blocks until in-flight
    handlers have answered."""
    gate = threading.Event()

    def slow(body):
        gate.wait(5.0)
        return (200, "application/json", json.dumps({"ok": True}))

    first = MetricsExporter(MetricsHub(), port=0, reuse_port=True,
                            drain=True,
                            routes={("POST", "/slow"): slow}).start()
    second = MetricsExporter(MetricsHub(), port=first.port, reuse_port=True,
                             routes={}).start()
    assert second.port == first.port  # joined the group, no EADDRINUSE
    second.stop()

    results = []

    def call():
        req = urllib.request.Request(
            f"http://127.0.0.1:{first.port}/slow", data=b"{}",
            method="POST")
        with urllib.request.urlopen(req, timeout=10.0) as r:
            results.append(r.status)

    t = threading.Thread(target=call, daemon=True)
    t.start()
    time.sleep(0.2)  # request in flight, parked on the gate
    stopper = threading.Thread(target=first.stop, daemon=True)
    stopper.start()
    time.sleep(0.2)
    assert stopper.is_alive()  # stop() is draining, not dropping
    gate.set()
    stopper.join(10.0)
    t.join(10.0)
    assert results == [200]  # the in-flight request completed through stop


# --------------------------------------------------------- drain handoff


def _fab(tmp_path, **overrides):
    overrides.setdefault("replicas", 2)
    cfg = fabric.FabricConfig(poll_s=0.1, health_period_s=0.2,
                              grace_s=10.0, retry_pause_s=0.05,
                              federation=False, **overrides)
    return fabric.ServingFabric(str(tmp_path), cfg)


@pytest.mark.skipif(not reuse_port_supported(),
                    reason="platform lacks SO_REUSEPORT")
def test_handoff_spawn_failure_leaves_predecessor_serving(tmp_path):
    """Chaos on the guarded successor spawn (``drain_handoff:fail@1``):
    the roll aborts typed, the predecessor never stopped serving, and
    exactly one process per replica id remains."""
    _seal(tmp_path, _docs())
    fab = _fab(tmp_path, replicas=1)
    fab.start()
    try:
        pid_before = fab._handles[0].pid
        with chaos.inject("drain_handoff:fail@1"):
            with pytest.raises(chaos.ChaosError):
                fab.rolling_restart(timeout=30.0)
        assert fab._handles[0].pid == pid_before
        scores, _docs_ = fab.query(["node"])
        assert len(scores) > 0
        audit = fab.audit()
        assert audit["dropped"] == 0 and audit["double_served"] == 0
        assert audit["rolled"] == 0
    finally:
        fab.stop()


@pytest.mark.skipif(not reuse_port_supported(),
                    reason="platform lacks SO_REUSEPORT")
def test_rolling_restart_handoff_zero_roll_retries_under_load(tmp_path):
    """The tentpole acceptance: a roll under closed-loop load needs ZERO
    roll-attributed retries — the socket handoff, not the sibling-retry
    ladder, carries the roll.  Ports stay pinned across the roll and
    every replica ends on a fresh pid."""
    _seal(tmp_path, _docs())
    fab = _fab(tmp_path)
    fab.start()
    try:
        pids_before = {i: h.pid for i, h in fab._handles.items()}
        ports_before = dict(fab._ports)
        stop = threading.Event()
        failures: list = []

        def closed_loop():
            n = 0
            while not stop.is_set():
                try:
                    fab.query(["node", "graph"])
                except Exception as exc:  # noqa: BLE001 — recorded
                    failures.append(exc)
                n += 1

        t = threading.Thread(target=closed_loop, daemon=True)
        t.start()
        try:
            fab.rolling_restart(timeout=60.0)
        finally:
            stop.set()
            t.join(10.0)
        assert not failures
        audit = fab.audit()
        assert audit["roll_retries"] == 0
        assert audit["dropped"] == 0 and audit["double_served"] == 0
        assert audit["rolled"] == 2
        assert dict(fab._ports) == ports_before  # anchors pinned them
        pids_after = {i: h.pid for i, h in fab._handles.items()}
        assert all(pids_after[i] != pids_before[i] for i in pids_before)
    finally:
        fab.stop()


def test_trace_diff_gates_roll_retries_and_peer_hit_rate(tmp_path):
    """The trace_diff fleet gate (ISSUE 20): roll-attributed retries are
    an invariant (the handoff claim), the cross-replica cache hit rate a
    thresholded regression; both None-tolerant for older rounds."""
    td = _tool("trace_diff")

    def bench(name, extra):
        base = {"fabric_qps": {"n1": 100.0}, "fabric_recovery_s": 2.0,
                "fabric_dropped": 0, "fabric_double_served": 0}
        p = tmp_path / name
        p.write_text(json.dumps({"extra": dict(base, **extra)}))
        return td.load_fabric(str(p))

    old = bench("old.json", {"fabric_roll_retries": 0,
                             "cache_peer_hit_rate": 0.5,
                             "cache_speedup_skewed": 1.4})
    clean = bench("clean.json", {"fabric_roll_retries": 0,
                                 "cache_peer_hit_rate": 0.52,
                                 "cache_speedup_skewed": 1.5})
    assert td.diff_fabric(old, clean, threshold=0.25) == []
    # ANY roll-attributed retry regresses — the handoff stopped carrying
    retried = bench("retried.json", {"fabric_roll_retries": 2,
                                     "cache_peer_hit_rate": 0.5})
    keys = {r["key"] for r in td.diff_fabric(old, retried, threshold=0.25)}
    assert keys == {"fabric.roll_retries"}
    # the invariant arms at 0 even against a pre-handoff round
    pre = bench("pre.json", {})
    assert {r["key"] for r in td.diff_fabric(pre, retried, threshold=0.25)
            } == {"fabric.roll_retries"}
    # hit-rate collapse past the threshold regresses; a wiggle does not
    cold = bench("cold.json", {"fabric_roll_retries": 0,
                               "cache_peer_hit_rate": 0.1})
    keys = {r["key"] for r in td.diff_fabric(old, cold, threshold=0.25)}
    assert keys == {"fabric.cache_peer_hit_rate"}
    # None on either side (failed child / pre-cache round) skips cleanly
    nulls = bench("nulls.json", {"fabric_roll_retries": None,
                                 "cache_peer_hit_rate": None})
    assert td.diff_fabric(old, nulls, threshold=0.25) == []
    assert td.diff_fabric(nulls, clean, threshold=0.25) == []


def test_trace_report_cache_section_and_drain_timeline(tmp_path):
    """trace_report folds the router's replica-stats scrape into a cache
    section (hit rates, breaker timeline) and renders the handoff drain
    timeline inside the fabric section."""
    tr = _tool("trace_report")
    t0 = 1000.0
    events = [
        {"kind": "run_start", "name": "x", "t": t0, "seq": 0},
        {"kind": "fabric_start", "replicas": 2, "t": t0 + 0.1},
        {"kind": "fabric_handoff", "replica": 0, "phase": "spawn",
         "t": t0 + 1.0},
        {"kind": "fabric_handoff", "replica": 0,
         "phase": "successor_ready", "pid": 42, "t": t0 + 1.5},
        {"kind": "fabric_handoff", "replica": 0, "phase": "drain",
         "pid": 41, "t": t0 + 1.6},
        {"kind": "fabric_rolled", "replica": 0, "handoff": True,
         "restart_s": 0.7, "t": t0 + 1.7},
        {"kind": "cache_breaker", "replica": 1, "peer": 0,
         "old": "closed", "new": "open", "t": t0 + 2.0},
        {"kind": "fabric_replica_stats", "replica": 1, "requests": 40,
         "cache_hits": 10, "peer_hits": 6, "peer_misses": 2,
         "peek_timeouts": 2, "fills": 3, "breaker_open": 1,
         "peer_stores": 0, "t": t0 + 2.5},
        {"kind": "fabric_stop", "requests": 40, "delivered": 40,
         "retries": 0, "roll_retries": 0, "failed": 0,
         "double_served": 0, "dropped": 0, "rolled": 1, "t": t0 + 3.0},
        {"kind": "run_end", "name": "x", "status": "ok",
         "summary": {"histograms": {"cache_peek_s": {"count": 10}}},
         "t": t0 + 3.1},
    ]
    trace = tmp_path / "roll.trace.jsonl"
    trace.write_text("".join(json.dumps(e) + "\n" for e in events))
    rep = tr.report(str(trace))
    fb = rep["fabric"]
    assert fb["handoff_rolls"] == 1 and fb["retry_rolls"] == 0
    phases = [d["phase"] for d in fb["drain_timeline"]]
    assert phases == ["spawn", "successor_ready", "drain"]
    assert fb["totals"]["roll_retries"] == 0
    ca = rep["cache"]
    st = ca["replica_stats"][1]
    assert st["local_hit_rate"] == 0.25
    assert st["peer_hit_rate"] == 0.6  # 6 / (6 + 2 + 2)
    assert ca["peek_latency"] == {"count": 10}
    assert ca["breaker_transitions"][0]["new"] == "open"
    text = tr.render_human(rep)
    assert "drain:" in text and "handoff roll(s)" in text
    assert "peer hit rate" in text and "breaker" in text


def test_rolling_restart_without_handoff_still_rolls(tmp_path):
    """cfg.handoff=False keeps the PR-17 retry-carried roll working —
    the fallback for platforms without SO_REUSEPORT."""
    _seal(tmp_path, _docs())
    fab = _fab(tmp_path, handoff=False, peer_cache=False)
    fab.start()
    try:
        fab.rolling_restart(timeout=60.0)
        audit = fab.audit()
        assert audit["rolled"] == 2
        assert audit["dropped"] == 0 and audit["double_served"] == 0
        scores, _docs_ = fab.query(["node"])
        assert len(scores) > 0
    finally:
        fab.stop()
