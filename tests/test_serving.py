"""Serving-layer tests (ISSUE 8): artifact round-trip, served-vs-one-shot
byte equality on the sklearn-oracle corpus, LRU hit identity, padded
micro-batch policy, and chaos-degraded dispatch (errors isolated per
batch, the queue keeps draining).
"""

from __future__ import annotations

import importlib.util
import json
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs, serving
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
    batch_cap,
    batch_shape_matrix,
    serve_pad_plan,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder

REPO = Path(__file__).resolve().parents[1]
FIXTURE = Path(__file__).parent / "fixtures" / "tiny.txt"

CFG = TfidfConfig(vocab_bits=10, idf_mode="smooth", l2_normalize=True)


def _trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", REPO / "tools" / "trace_report.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def oracle_index(tmp_path_factory):
    """The sklearn-oracle corpus (tests/fixtures/tiny.txt) built into a
    servable index — the corpus test_tfidf_oracle.py pins numerically."""
    docs = FIXTURE.read_text().splitlines()
    out = run_tfidf(docs, CFG)
    d = tmp_path_factory.mktemp("idx")
    ranks = np.linspace(0.5, 1.5, out.n_docs).astype(np.float32)
    serving.save_index(str(d), out, CFG, ranks=ranks)
    return serving.load_index(str(d))


def _one_shot(index, q_term, q_weight, k, n_docs=None):
    """The pre-serving query path: a dense vocab vector through the
    one-shot ops.score_query — the equality oracle."""
    import jax.numpy as jnp

    res = ops.TfidfResult(
        doc=jnp.asarray(np.ascontiguousarray(index.doc)),
        term=jnp.asarray(np.ascontiguousarray(index.term)),
        weight=jnp.asarray(np.ascontiguousarray(index.weight)),
        n_pairs=jnp.asarray(index.nnz),
        valid=jnp.ones(index.nnz, index.weight.dtype),
        idf=jnp.asarray(np.ascontiguousarray(index.idf)),
        df=jnp.asarray(np.ascontiguousarray(index.df)),
    )
    q = np.zeros(index.vocab_size, index.weight.dtype)
    np.add.at(q, q_term, q_weight)
    scores, idx = ops.score_query(
        res, jnp.asarray(q), n_docs=n_docs or index.n_docs, k=k
    )
    return np.asarray(scores), np.asarray(idx)


# ----------------------------------------------------------------- artifact


def test_artifact_roundtrip_and_versioning(tmp_path, oracle_index):
    docs = FIXTURE.read_text().splitlines()
    out = run_tfidf(docs, CFG)
    p1 = serving.save_index(str(tmp_path), out, CFG)
    assert p1.endswith("v0001")
    p2 = serving.save_index(str(tmp_path), out, CFG)
    assert p2.endswith("v0002")
    idx = serving.load_index(str(tmp_path))  # LATEST -> v0002
    assert idx.version == 2
    old = serving.load_index(str(tmp_path), version=1)
    assert old.version == 1
    np.testing.assert_array_equal(idx.weight, old.weight)
    assert idx.n_docs == out.n_docs and idx.nnz == out.nnz
    assert idx.cfg.config_hash() == CFG.config_hash()
    assert idx.ranks is None  # built without a prior here


def test_artifact_is_mmap_loadable(tmp_path):
    docs = FIXTURE.read_text().splitlines()
    out = run_tfidf(docs, CFG)
    serving.save_index(str(tmp_path), out, CFG)
    idx = serving.load_index(str(tmp_path), mmap=True)
    assert isinstance(idx.weight, np.memmap)  # mapped, not copied
    ram = serving.load_index(str(tmp_path), mmap=False)
    assert not isinstance(ram.weight, np.memmap)
    np.testing.assert_array_equal(np.asarray(idx.weight), ram.weight)


def test_artifact_config_hash_guard(tmp_path):
    docs = FIXTURE.read_text().splitlines()
    out = run_tfidf(docs, CFG)
    serving.save_index(str(tmp_path), out, CFG)
    other = TfidfConfig(vocab_bits=10)  # different semantics
    with pytest.raises(ValueError, match="refusing to serve"):
        serving.load_index(
            str(tmp_path), expect_config_hash=other.config_hash()
        )


def test_artifact_ranks_shape_guard(tmp_path):
    docs = FIXTURE.read_text().splitlines()
    out = run_tfidf(docs, CFG)
    with pytest.raises(ValueError, match="ranks prior"):
        serving.save_index(
            str(tmp_path), out, CFG, ranks=np.ones(out.n_docs + 3, np.float32)
        )


def test_array_dir_atomicity_and_pointer(tmp_path):
    """The underlying checkpoint-machinery format: LATEST flips only after
    the version directory is fully in place, and versions are immutable."""
    d = str(tmp_path)
    ckpt.save_array_dir(d, 1, {"a": np.arange(4)}, "h")
    assert ckpt.latest_array_dir(d).endswith("v0001")
    assert ckpt.next_version(d) == 2
    with pytest.raises(FileExistsError):
        ckpt.save_array_dir(d, 1, {"a": np.arange(4)}, "h")
    step, arrays, extra = ckpt.load_array_dir(ckpt.latest_array_dir(d))
    assert step == 1 and list(arrays) == ["a"]
    with pytest.raises(ValueError, match="refusing"):
        ckpt.load_array_dir(ckpt.latest_array_dir(d), "other-hash")


# ---------------------------------------------------- served == one-shot


def test_served_topk_byte_equal_to_one_shot(oracle_index):
    """Acceptance: the warm batched path returns byte-identical top-k to
    the one-shot ops.score_query on the sklearn-oracle corpus.  (The
    fixture is the SNAP-format tiny graph read as text lines, so its
    vocabulary is the SNAP header words and node ids.)"""
    queries = [
        ["directed", "graph"],
        ["node"],
        ["0", "1"],
        ["dangling", "node", "4"],
        ["zebra", "unseen"],  # all-zero scores still well-defined
    ]
    with serving.TfidfServer(
        oracle_index, serving.ServeConfig(top_k=4, max_batch=4)
    ) as srv:
        futs = [srv.submit(q) for q in queries]
        for q, fut in zip(queries, futs):
            scores, idx = fut.result(30.0)
            qt, qw = srv.make_query(q)
            e_scores, e_idx = _one_shot(oracle_index, qt, qw, srv.k)
            assert scores.tobytes() == e_scores.tobytes()
            assert idx.tobytes() == e_idx.tobytes()


def test_served_rank_prior_blend(oracle_index):
    """rank_alpha fuses the artifact's PageRank prior on device:
    score + alpha * rank, before top-k."""
    alpha = 0.25
    with serving.TfidfServer(
        oracle_index,
        serving.ServeConfig(top_k=oracle_index.n_docs, rank_alpha=alpha),
    ) as srv:
        scores, idx = srv.query(["directed", "graph"])
        qt, qw = srv.make_query(["directed", "graph"])
    base_scores, _ = _one_shot(oracle_index, qt, qw, oracle_index.n_docs)
    # undo top-k ordering: scatter both back to doc order
    served = np.zeros(oracle_index.n_docs, np.float32)
    served[idx] = scores
    expect = base_scores.copy()
    order = np.argsort(-base_scores, kind="stable")
    dense = np.zeros_like(served)
    dense[_one_shot(oracle_index, qt, qw, oracle_index.n_docs)[1]] = base_scores
    expect_dense = dense + alpha * np.asarray(oracle_index.ranks)
    np.testing.assert_allclose(served, expect_dense, atol=1e-6)
    del expect, order


def test_lru_hit_returns_identical_results(oracle_index):
    with serving.TfidfServer(
        oracle_index, serving.ServeConfig(top_k=3)
    ) as srv:
        s1, i1 = srv.query(["node", "graph"])
        s2, i2 = srv.query(["graph", "node"])  # canonicalized: same key
        s3, i3 = srv.query(["node", "graph"])
        stats = srv.stats()
    assert s1.tobytes() == s2.tobytes() == s3.tobytes()
    assert np.array_equal(i1, i2) and np.array_equal(i2, i3)
    assert stats["cache_hits"] == 2 and stats["cache_misses"] == 1


def test_lru_eviction_bound(oracle_index):
    with serving.TfidfServer(
        oracle_index, serving.ServeConfig(top_k=2, cache_size=2)
    ) as srv:
        srv.query(["node"])
        srv.query(["graph"])
        srv.query(["edge"])  # evicts "node"
        srv.query(["node"])  # miss again
        stats = srv.stats()
    assert stats["cache_misses"] == 4 and stats["cache_hits"] == 0
    assert len(srv._cache) == 2


def test_cache_disabled(oracle_index):
    with serving.TfidfServer(
        oracle_index, serving.ServeConfig(top_k=2, cache_size=0)
    ) as srv:
        a = srv.query(["node"])
        b = srv.query(["node"])
        stats = srv.stats()
    assert stats["cache_hits"] == 0 and stats["cache_misses"] == 2
    assert a[0].tobytes() == b[0].tobytes()


# ------------------------------------------------------- batching policy


def test_batch_cap_is_grow_chunk_cap_at_min_bits_zero():
    m = MetricsRecorder()
    assert [batch_cap(b, 8, m) for b in (1, 2, 3, 4, 5, 7, 8)] == \
        [1, 2, 4, 4, 8, 8, 8]
    assert batch_cap(100, 8, m) == 8  # clipped at max_batch


def test_batch_shape_matrix_finite():
    assert batch_shape_matrix(8) == [1, 2, 4, 8]
    assert batch_shape_matrix(1) == [1]
    assert batch_shape_matrix(6) == [1, 2, 4, 6]  # clip keeps it bounded


def test_serve_pad_plan_matches_policy():
    (label, frac), = serve_pad_plan((1, 2, 3, 5, 7, 8), 8)
    assert label == "serve"
    # raw 26 slots over caps 1+2+4+8+8+8=31
    assert frac == pytest.approx((31 - 26) / 31)


def test_registry_covers_batched_serve_entry():
    """The batched entry's declared shape matrix must collapse to the
    warm set — tier-2's zero-per-request-recompile proof rides on it."""
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis import registry

    ep = {e.name: e for e in registry.ENTRY_POINTS}["tfidf_score_query_batch"]
    assert ep.max_compiles == len(batch_shape_matrix(registry.SERVE_MAX_BATCH))
    t = registry.build_traceable(ep)
    import jax

    sigs = {
        tuple((tuple(l.shape), str(l.dtype))
              for l in jax.tree_util.tree_leaves(args))
        for _, args in t.variants
    }
    assert len(sigs) <= ep.max_compiles


def test_make_query_applies_index_tokenizer(oracle_index):
    """Query terms run through the INDEX's real tokenizer: punctuation
    splits exactly like the corpus did, so 'from-node' scores like
    'from node' instead of hashing to a term no document produced."""
    srv = serving.TfidfServer(oracle_index, serving.ServeConfig(top_k=2))
    qt1, qw1 = srv.make_query(["from-node"])
    qt2, qw2 = srv.make_query(["from", "node"])
    np.testing.assert_array_equal(qt1, qt2)
    np.testing.assert_array_equal(qw1, qw2)


def test_make_query_builds_ngrams(tmp_path):
    """An ngram=2 index's server generates the same space-joined bigram
    ids the build side hashed — bigram queries are servable."""
    from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
        fnv1a_64,
        hash_to_vocab,
    )

    cfg2 = TfidfConfig(vocab_bits=10, ngram=2)
    out = run_tfidf(["alpha beta gamma", "beta gamma delta"], cfg2)
    serving.save_index(str(tmp_path), out, cfg2)
    idx = serving.load_index(str(tmp_path))
    srv = serving.TfidfServer(idx, serving.ServeConfig(top_k=2))
    qt, _ = srv.make_query(["alpha", "beta"])
    bigram_id = int(hash_to_vocab(fnv1a_64(["alpha beta"]), 10)[0])
    assert bigram_id in qt.tolist()


def test_stop_fails_raced_submit_instead_of_hanging(oracle_index):
    """A request slipping into the queue around shutdown is failed by
    stop()'s leftover drain, and post-stop submits refuse — no future can
    hang forever on a dead drain thread."""
    srv = serving.TfidfServer(oracle_index, serving.ServeConfig(top_k=2))
    srv.start()
    qt, qw = srv.make_query(["node"])
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
        _Pending,
    )

    srv._thread.join(0)  # still alive; now simulate the race:
    leftover = _Pending(srv.query_key(qt, qw), qt, qw)
    srv._queue.put(leftover)  # may land after the _STOP sentinel
    srv.stop()
    assert leftover.done  # resolved OR failed by the leftover drain
    # post-stop submits refuse with the TYPED shutdown error (still a
    # RuntimeError) so fabric/soak callers can tell an orderly stop from
    # a server that never started
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
        ServerShutdown,
    )

    with pytest.raises(ServerShutdown, match="server stopped"):
        srv.submit(["node"])
    with pytest.raises(RuntimeError, match="not started"):
        serving.TfidfServer(
            oracle_index, serving.ServeConfig(top_k=2)
        ).submit(["node"])


def test_query_truncation_and_empty(oracle_index):
    with serving.TfidfServer(
        oracle_index,
        serving.ServeConfig(top_k=2, max_query_terms=4),
    ) as srv:
        qt, qw = srv.make_query([f"t{i}" for i in range(20)])
        assert qt.shape[0] == 4
        qe, we = srv.make_query([])
        assert qe.shape[0] == 0
        scores, idx = srv.query([])  # empty query: all-prior/zero scores
        assert scores.shape == (2,)


def test_warmup_compiles_full_shape_matrix(oracle_index):
    srv = serving.TfidfServer(
        oracle_index, serving.ServeConfig(top_k=2, max_batch=8)
    )
    try:
        srv.start(warm=False)
        assert srv.warmup() == [1, 2, 4, 8]
    finally:
        srv.stop()


# ------------------------------------------------------- chaos resilience


def test_chaos_transient_faults_are_invisible(oracle_index):
    """fail@%5 on the serve dispatch site: the executor retries; every
    request still succeeds and matches the clean run."""
    queries = [["node", f"w{i}"] for i in range(16)] + [["directed"]] * 2
    with serving.TfidfServer(
        oracle_index, serving.ServeConfig(top_k=3, cache_size=0, max_batch=2)
    ) as srv:
        clean = [srv.query(q) for q in queries[:4]]
    m = MetricsRecorder()
    with chaos.inject("serve_dispatch:fail@%5") as plan:
        with serving.TfidfServer(
            oracle_index,
            serving.ServeConfig(top_k=3, cache_size=0, max_batch=2),
            metrics=m,
        ) as srv:
            # sequential queries: every request is its own micro-batch, so
            # the %5 schedule deterministically hits the 5th, 10th, ...
            # dispatch regardless of drain timing
            results = [srv.query(q, timeout=60.0) for q in queries]
            stats = srv.stats()
        assert plan.call_count("serve_dispatch") >= len(queries)
    assert stats["batch_errors"] == 0
    for (s, i), (cs, ci) in zip(results[:4], clean):
        assert s.tobytes() == cs.tobytes() and np.array_equal(i, ci)
    retries = [r for r in m.records if r.get("event") == "retry"
               and r.get("site") == "serve_dispatch"]
    assert retries  # the injection really fired and was absorbed


def test_chaos_hard_fault_degrades_per_request(oracle_index):
    """A persistent loss at the dispatch site fails exactly the batch that
    hit it; the queue keeps draining and later requests succeed."""
    with chaos.inject("serve_dispatch:lost@1"):
        with serving.TfidfServer(
            oracle_index,
            serving.ServeConfig(top_k=3, cache_size=0, max_batch=2,
                                flush_ms=50.0),
        ) as srv:
            first = srv.submit(["node"])
            second = srv.submit(["graph"])
            with pytest.raises(Exception):
                first.result(60.0)
            with pytest.raises(Exception):
                second.result(60.0)  # same micro-batch: same fault
            # the drain loop survived — fresh requests serve fine
            scores, idx = srv.query(["directed", "graph"], timeout=60.0)
            stats = srv.stats()
    assert stats["batch_errors"] == 1
    assert scores.shape == (3,)
    qt, qw = srv.make_query(["directed", "graph"])
    es, ei = _one_shot(oracle_index, qt, qw, 3)
    assert scores.tobytes() == es.tobytes()


# ----------------------------------------------------- telemetry + stitch


def test_serve_trace_accounting(oracle_index, tmp_path, monkeypatch):
    """A traced serve run leaves queue-wait/pad/dispatch/pull accounting
    and per-request latency percentiles readable by trace_report; with
    GRAFT_TRACE_PARENT set, the artifact joins the parent's stitched
    tree (ROADMAP hardening (c))."""
    monkeypatch.setenv("GRAFT_TRACE_PARENT", "round-42")
    obs.start_run("serve", str(tmp_path))
    try:
        with serving.TfidfServer(
            oracle_index, serving.ServeConfig(top_k=3, max_batch=4)
        ) as srv:
            srv.query(["directed", "graph"])  # populate the cache
            futs = [srv.submit(["directed", "graph"]) for _ in range(6)]
            futs += [srv.submit([f"w{i}"]) for i in range(5)]
            for f in futs:
                f.result(60.0)
    finally:
        obs.end_run()
    mod = _trace_report()
    trace = next(tmp_path.glob("serve.*.trace.jsonl"))
    rep = mod.report(str(trace))
    assert rep["trace_parent"] == "round-42"
    sv = rep["serving"]
    assert sv["requests"] == 12
    assert sv["cache_hits"] >= 6  # the 6 resubmits of the cached query
    assert sv["errors"] == 0
    assert sv["latency_p99_s"] >= sv["latency_p50_s"] >= 0
    assert {"dispatch", "pull"} <= set(sv["phases"])
    man = json.loads(next(tmp_path.glob("serve.*.manifest.json")).read_text())
    assert man["trace_parent"] == "round-42"
    stitched = mod.stitch(str(tmp_path))
    (tree,) = stitched["trees"]
    assert tree["trace_parent"] == "round-42"
    assert tree["children"][0]["serving"]["requests"] == 12


# -------------------------------------------------------------------- CLI


def test_cli_build_and_serve(tmp_path, capsys):
    from page_rank_and_tfidf_using_apache_spark_tpu.cli import serve as cli_serve
    from page_rank_and_tfidf_using_apache_spark_tpu.cli import tfidf as cli_tfidf

    corpus = tmp_path / "corpus.txt"
    corpus.write_text(FIXTURE.read_text())
    idx_dir = tmp_path / "index"
    rc = cli_tfidf.main([
        str(corpus), "--lines", "--vocab-bits", "10", "--idf-mode", "smooth",
        "--l2-normalize", "--save-index", str(idx_dir),
    ])
    assert rc == 0
    assert (idx_dir / "LATEST").exists()

    queries = tmp_path / "q.txt"
    queries.write_text("directed graph\nnode\n\n0 1\n")
    rc = cli_serve.main([
        str(idx_dir), "--queries", str(queries), "--top-k", "3",
        "--max-batch", "2",
    ])
    assert rc == 0
    out, err = capsys.readouterr()
    rows = [l.split("\t") for l in out.strip().splitlines() if l]
    assert rows and all(len(r) == 3 for r in rows)
    qids = {int(r[0]) for r in rows}
    assert 0 in qids  # "directed graph" matched something
    stats = json.loads(err.strip().splitlines()[-1])
    assert stats["requests"] == 3 and stats["p50_ms"] is not None


# --------------------------------------- per-request prior ranker (ISSUE 11)


def test_prior_ranker_per_request_blend(oracle_index):
    """ranker='prior' blends prior_alpha * ranks for exactly the requests
    that opt in; plain tfidf requests on the SAME server stay byte-equal
    to the one-shot path (the zero-prior operand adds exactly nothing)."""
    alpha = 0.5
    n = oracle_index.n_docs
    with serving.TfidfServer(
        oracle_index,
        serving.ServeConfig(top_k=n, prior_alpha=alpha, cache_size=0),
    ) as srv:
        qt, qw = srv.make_query(["directed", "graph"])
        s_plain, i_plain = srv.query(["directed", "graph"])
        s_prior, i_prior = srv.query(["directed", "graph"], ranker="prior")
    e_scores, e_idx = _one_shot(oracle_index, qt, qw, n)
    assert s_plain.tobytes() == e_scores.tobytes()
    assert i_plain.tobytes() == e_idx.tobytes()
    dense_plain = np.zeros(n, np.float32)
    dense_plain[i_plain] = s_plain
    dense_prior = np.zeros(n, np.float32)
    dense_prior[i_prior] = s_prior
    expect = dense_plain + alpha * np.asarray(oracle_index.ranks)
    np.testing.assert_allclose(dense_prior, expect, atol=1e-6)


def test_prior_ranker_refusal_paths(oracle_index, tmp_path):
    # prior_alpha unset on the server: the per-request ranker refuses
    with serving.TfidfServer(oracle_index, serving.ServeConfig()) as srv:
        with pytest.raises(ValueError, match="prior_alpha"):
            srv.submit(["node"], ranker="prior")
    # an index without a ranks prior cannot host a prior-capable server
    docs = FIXTURE.read_text().splitlines()
    out = run_tfidf(docs, CFG)
    serving.save_index(str(tmp_path), out, CFG)  # no ranks
    bare = serving.load_index(str(tmp_path))
    with pytest.raises(ValueError, match="prior"):
        serving.TfidfServer(bare, serving.ServeConfig(prior_alpha=0.5))


def test_set_prior_hot_swap_and_cache_invalidation(oracle_index):
    """set_prior on a RUNNING server re-blends subsequent prior queries
    (no recompile — operand swap) and invalidates cached results."""
    alpha = 1.0
    n = oracle_index.n_docs
    with serving.TfidfServer(
        oracle_index, serving.ServeConfig(top_k=n, prior_alpha=alpha)
    ) as srv:
        s1, i1 = srv.query(["node"], ranker="prior")
        # a cache hit would return the identical object contents
        s1b, _ = srv.query(["node"], ranker="prior")
        assert s1.tobytes() == s1b.tobytes()
        assert srv.stats()["cache_hits"] == 1
        new_ranks = np.linspace(5.0, 1.0, n).astype(np.float32)
        srv.set_prior(new_ranks)
        s2, i2 = srv.query(["node"], ranker="prior")
        qt, qw = srv.make_query(["node"])
        # shape guard + not-started guard
        with pytest.raises(ValueError, match="shape"):
            srv.set_prior(np.ones(n + 1, np.float32))
    base_scores, base_idx = _one_shot(oracle_index, qt, qw, n)
    dense_base = np.zeros(n, np.float32)
    dense_base[base_idx] = base_scores
    dense2 = np.zeros(n, np.float32)
    dense2[i2] = s2
    np.testing.assert_allclose(dense2, dense_base + alpha * new_ranks,
                               atol=1e-6)
    # the old blend really was different (cache cleared, not replayed)
    dense1 = np.zeros(n, np.float32)
    dense1[i1] = s1
    assert not np.allclose(dense1, dense2)


def test_set_prior_requires_prior_capable_server(oracle_index):
    with serving.TfidfServer(oracle_index, serving.ServeConfig()) as srv:
        with pytest.raises(RuntimeError, match="prior operand"):
            srv.set_prior(np.ones(oracle_index.n_docs, np.float32))


def test_cache_put_rejects_stale_prior_generation(oracle_index):
    """A batch dispatched against a pre-set_prior operand must not land
    its result in the cache after the invalidation: _cache_put drops
    writes whose generation predates the current prior swap."""
    n = oracle_index.n_docs
    with serving.TfidfServer(
        oracle_index, serving.ServeConfig(top_k=n, prior_alpha=1.0)
    ) as srv:
        stale_gen = srv._prior_gen
        srv.set_prior(np.ones(n, np.float32))  # bumps the generation
        srv._cache_put(b"stale-key", ("x", "y"), stale_gen)
        assert b"stale-key" not in srv._cache
        srv._cache_put(b"fresh-key", ("x", "y"), srv._prior_gen)
        assert b"fresh-key" in srv._cache
