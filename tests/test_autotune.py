"""Cost-model-driven autotuning tests (ISSUE 16).

Five layers, mirroring the other lint-tier test files:

1. **Pruning correctness** — the tuner's grid must cover the declared
   ``TUNED_KNOBS`` space exactly (group-partition drift is a loud
   error), every statically pruned point must carry a genuine budget
   violation against the SAME registry budgets tier 3 gates on, and a
   synthetic budget table drives the prune both ways (no budgets → no
   pruning; impossible budgets → everything pruned).
2. **Profile resolution** — write/load round-trip, the full ladder
   (explicit path > ``GRAFT_TUNED_PROFILE`` env, with ``"off"`` as the
   kill switch > committed per-backend artifact > TUNABLE_DEFAULTS) and
   ``tuned_config`` override precedence, including the int-coercion of
   JSON numbers.
3. **Backend provenance** — a profile stamped for one backend refuses to
   load for another, in BOTH directions, and the ``check_overwrite``
   guard keeps a CPU sweep from clobbering a TPU-stamped profile.
4. **Crash consistency** — a SIGKILL at every mutation boundary of the
   ``write_tuned_profile`` commit leaves the old profile or the new one,
   never a torn JSON (tools/crash_harness.py ``_arm_kill`` idiom).
5. **The tier-3 profile checks** — TP/TN/suppressed fixtures for
   ``profile-drift`` and ``untuned-knob-read`` via ``run_profile``'s
   contract/profiles injection, then the whole-repo zero-unratcheted
   gate over the real surface and the committed artifact.
"""

from __future__ import annotations

import importlib.util
import json
import os
import signal
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
    baseline_path,
    load_baseline,
    repo_root,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.profile import (
    ProfileArtifact,
    _contract_cache,
    run_profile,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
    TUNED_KNOBS,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.artifacts import (
    ProvenanceError,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    TUNABLE_DEFAULTS,
    TfidfConfig,
    TunedProfile,
    TunedProfileError,
    load_tuned_profile,
    profile_path,
    tuned_config,
    write_tuned_profile,
)

REPO = repo_root()
_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"


@pytest.fixture(scope="module")
def autotune():
    """tools/autotune.py, loaded the way trace_diff loads trace_report —
    the tools/ scripts are not package modules."""
    path = REPO / "tools" / "autotune.py"
    spec = importlib.util.spec_from_file_location("autotune_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def probes(autotune):
    return autotune.build_probes()


# ------------------------------------------------------- pruning correctness


def test_grid_covers_declared_space(autotune):
    """Every TUNED_KNOBS name in exactly one group; the grid's point
    count is the product of the domain sizes within each group."""
    domains = autotune._knob_domains()
    assert set(domains) == set(TUNABLE_DEFAULTS)
    grid = autotune.enumerate_grid(domains)
    grouped = [k for _, knobs in autotune.GROUPS for k in knobs]
    assert sorted(grouped) == sorted(domains), "a knob is grouped twice"
    for group, knobs in autotune.GROUPS:
        expect = 1
        for k in knobs:
            expect *= len(domains[k])
        assert len(grid[group]) == expect
        # every point binds exactly this group's knobs
        assert all(set(p) == set(knobs) for p in grid[group])


def test_grid_drift_guard_both_directions(autotune):
    domains = autotune._knob_domains()
    with pytest.raises(ValueError, match="drift"):
        autotune.enumerate_grid({**domains, "bogus_knob": (1, 2)})
    short = dict(domains)
    short.pop("prefetch")
    with pytest.raises(ValueError, match="drift"):
        autotune.enumerate_grid(short)


def test_pruned_points_actually_violate(autotune, probes):
    """The acceptance bar: >=30% of the raw grid discarded unmeasured,
    every discard justified by a named registry budget the point really
    violates, and every group keeps at least one survivor so the
    measured sweep stays runnable."""
    budgets = autotune._entry_budgets()
    plan = autotune.prune(autotune.enumerate_grid(autotune._knob_domains()),
                          probes, budgets)
    assert plan["prune_frac"] >= 0.30
    assert plan["raw_points"] == plan["pruned_points"] + plan["survivor_points"]
    for group, gp in plan["groups"].items():
        assert gp["survivors"], f"group {group!r} pruned to zero survivors"
        for entry in gp["pruned"]:
            assert entry["violations"], entry
            for v in entry["violations"]:
                budget = budgets[v["entry"]]
                if v["metric"] == "pad_frac":
                    assert v["value"] > budget["pad_frac_ceiling"], v
                    assert v["budget"] == budget["pad_frac_ceiling"]
                else:
                    assert v["metric"] == "intensity"
                    assert v["value"] < budget["intensity_floor"], v
                    assert v["budget"] == budget["intensity_floor"]
        # survivors re-evaluate clean against the same static model
        for point in gp["survivors"]:
            assert autotune.static_violations(group, point, probes,
                                              budgets) == []


def test_prune_synthetic_budgets_both_extremes(autotune, probes):
    """Synthetic budget tables drive the prune deterministically: no
    declared budgets prune nothing; impossible budgets prune every
    point, each discard naming the violated entry."""
    grid = autotune.enumerate_grid(autotune._knob_domains())
    none_budgets = {
        name: {"pad_frac_ceiling": None, "intensity_floor": None}
        for name in autotune._entry_budgets()
    }
    plan = autotune.prune(grid, probes, none_budgets)
    assert plan["pruned_points"] == 0 and plan["prune_frac"] == 0.0

    impossible = {
        name: {"pad_frac_ceiling": -1.0, "intensity_floor": 1e9}
        for name in autotune._entry_budgets()
    }
    plan = autotune.prune(grid, probes, impossible)
    assert plan["survivor_points"] == 0 and plan["prune_frac"] == 1.0
    for gp in plan["groups"].values():
        for entry in gp["pruned"]:
            assert all(v["entry"] for v in entry["violations"])


def test_static_pad_helpers_are_exact(autotune):
    """The tuner's stdlib mirrors of the padding policies, pinned on
    hand-computable inputs."""
    # greedy whole-doc packing: 10+10 fills a 20-token pack, 15 spills
    assert autotune.pack_counts([10, 10, 15], target=20, chunk_docs=8) \
        == [20, 15]
    # target 0 disables packing: token sums per fixed chunk_docs window
    assert autotune.pack_counts([5, 5, 5], target=0, chunk_docs=2) == [10, 5]
    # width-4 buckets over in-degrees 1..5 -> slots 4,4,4,4,8
    assert autotune.shuffle_padded_slots([1, 2, 3, 4, 5], width=4) == 24
    # constant 20-run rows, width 8, pow2 cap with the 2**6 floor:
    # cap=max(64, pow2(ceil(20*16/8)=40)=64) -> 64*8=512 slots for 320
    pad = autotune.impacted_static_pad([[20] * 16], width=8, min_bits=6)
    assert pad == pytest.approx(1 - 320 / 512)


# ---------------------------------------------------- resolution ladder


KNOBS_A = {"prefetch": 4, "pipeline_depth": 2, "pack_target_tokens": 131072}


def test_profile_write_load_roundtrip(tmp_path):
    p = tmp_path / "tuned_profile_cpu.json"
    record = write_tuned_profile(p, "cpu", KNOBS_A,
                                 measured={"sweep_secs": 1.0})
    assert set(record) == {"backend", "knobs", "git_sha", "created_wall",
                          "measured"}
    prof = load_tuned_profile(path=p)
    assert prof.backend == "cpu" and prof.source == "explicit"
    assert prof.knobs == KNOBS_A
    assert prof.measured == {"sweep_secs": 1.0}
    # the artifact is one JSON line (bench parent greps artifacts raw)
    assert len(p.read_text().strip().splitlines()) == 1


def test_resolution_ladder(tmp_path, monkeypatch):
    """explicit path > GRAFT_TUNED_PROFILE env ('off' disables) >
    committed tuned_profile_<backend>.json > absent -> None."""
    committed = Path(profile_path("cpu", root=tmp_path))
    write_tuned_profile(committed, "cpu", dict(KNOBS_A, prefetch=0))
    env_p = tmp_path / "env_profile.json"
    write_tuned_profile(env_p, "cpu", dict(KNOBS_A, prefetch=2))
    exp_p = tmp_path / "explicit.json"
    write_tuned_profile(exp_p, "cpu", dict(KNOBS_A, prefetch=4))

    monkeypatch.delenv("GRAFT_TUNED_PROFILE", raising=False)
    prof = load_tuned_profile(backend="cpu", root=tmp_path)
    assert prof.source == "committed" and prof.knob("prefetch") == 0

    monkeypatch.setenv("GRAFT_TUNED_PROFILE", str(env_p))
    prof = load_tuned_profile(backend="cpu", root=tmp_path)
    assert prof.source == "env" and prof.knob("prefetch") == 2

    # the explicit path outranks the env rung
    prof = load_tuned_profile(backend="cpu", path=exp_p, root=tmp_path)
    assert prof.source == "explicit" and prof.knob("prefetch") == 4

    # "off" (and empty) disable profile loading entirely
    for off in ("off", "", "0", "none", " OFF "):
        monkeypatch.setenv("GRAFT_TUNED_PROFILE", off)
        assert load_tuned_profile(backend="cpu", root=tmp_path) is None

    monkeypatch.delenv("GRAFT_TUNED_PROFILE", raising=False)
    assert load_tuned_profile(backend="cpu", root=tmp_path / "empty") is None


def test_tuned_config_precedence(tmp_path):
    """explicit non-None override > profile knob > field default; None
    means 'unset' (what argparse hands over); JSON floats coerce back to
    the TUNABLE_DEFAULTS kind for int knobs."""
    prof = TunedProfile(backend="cpu",
                        knobs={"prefetch": 4.0, "pipeline_depth": 0})
    cfg = tuned_config(TfidfConfig, prof, prefetch=None, vocab_bits=8)
    assert cfg.prefetch == 4 and isinstance(cfg.prefetch, int)
    assert cfg.pipeline_depth == 0
    assert cfg.vocab_bits == 8
    # explicit override wins over the profile
    cfg = tuned_config(TfidfConfig, prof, prefetch=1)
    assert cfg.prefetch == 1
    # no profile: the dataclass default (TUNABLE_DEFAULTS) stands
    cfg = tuned_config(TfidfConfig, None)
    assert cfg.prefetch == TUNABLE_DEFAULTS["prefetch"]
    # a knob absent from the profile falls through to the default
    assert tuned_config(
        TfidfConfig, TunedProfile(backend="cpu", knobs={})
    ).prefetch == TUNABLE_DEFAULTS["prefetch"]
    with pytest.raises(TypeError, match="no fields"):
        tuned_config(TfidfConfig, prof, not_a_field=3)


def test_profile_structure_errors(tmp_path):
    bad_json = tmp_path / "a.json"
    bad_json.write_text("{not json")
    with pytest.raises(TunedProfileError, match="not valid JSON"):
        load_tuned_profile(path=bad_json)
    no_keys = tmp_path / "b.json"
    no_keys.write_text(json.dumps({"knobs": {}}))
    with pytest.raises(TunedProfileError, match="required keys"):
        load_tuned_profile(path=no_keys)
    bool_knob = tmp_path / "c.json"
    bool_knob.write_text(json.dumps(
        {"backend": "cpu", "knobs": {"prefetch": True}}))
    with pytest.raises(TunedProfileError, match="numbers"):
        load_tuned_profile(path=bool_knob)
    with pytest.raises(TunedProfileError, match="unreadable"):
        load_tuned_profile(path=tmp_path / "missing.json")


# ------------------------------------------------------ backend provenance


def test_provenance_refusal_both_directions(tmp_path):
    """A CPU-tuned optimum must never steer a TPU run, nor vice versa —
    the same guard class as the measured cost artifacts."""
    cpu_p = tmp_path / "tuned_profile_cpu.json"
    write_tuned_profile(cpu_p, "cpu", KNOBS_A)
    with pytest.raises(ProvenanceError, match="cross-backend"):
        load_tuned_profile(backend="tpu", path=cpu_p)
    tpu_p = tmp_path / "tuned_profile_tpu.json"
    write_tuned_profile(tpu_p, "tpu", KNOBS_A)
    with pytest.raises(ProvenanceError, match="cross-backend"):
        load_tuned_profile(backend="cpu", path=tpu_p)
    # and each loads fine for its own backend
    assert load_tuned_profile(backend="tpu", path=tpu_p).backend == "tpu"
    assert load_tuned_profile(backend="cpu", path=cpu_p).backend == "cpu"


def test_overwrite_guard_protects_tpu_profile(tmp_path):
    p = tmp_path / "tuned_profile_tpu.json"
    write_tuned_profile(p, "tpu", KNOBS_A)
    with pytest.raises(ProvenanceError, match="refusing to overwrite"):
        write_tuned_profile(p, "cpu", KNOBS_A)
    # force downgrades deliberately; same-backend rewrites never need it
    write_tuned_profile(p, "cpu", dict(KNOBS_A, prefetch=0), force=True)
    assert load_tuned_profile(path=p, backend="cpu").knob("prefetch") == 0
    write_tuned_profile(p, "cpu", dict(KNOBS_A, prefetch=2))
    assert load_tuned_profile(path=p, backend="cpu").knob("prefetch") == 2


# -------------------------------------------------- crash consistency


_KILL_CHILD = textwrap.dedent("""
    import json, os, shutil, signal, sys

    sys.path.insert(0, sys.argv[1])
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        write_tuned_profile,
    )

    target, kill_at = sys.argv[2], int(sys.argv[3])
    counter = {"n": 0}

    def wrap(orig):
        def inner(*args, **kwargs):
            if counter["n"] == kill_at:
                os.kill(os.getpid(), signal.SIGKILL)
            counter["n"] += 1
            return orig(*args, **kwargs)
        return inner

    os.replace = wrap(os.replace)
    os.rename = wrap(os.rename)
    os.unlink = wrap(os.unlink)
    os.fsync = wrap(os.fsync)

    write_tuned_profile(target, "cpu", {"prefetch": 4}, measured={"v": 2})
    print(json.dumps({"boundaries": counter["n"]}))
""")


def test_profile_commit_kill_matrix(tmp_path):
    """SIGKILL right before EVERY reader-visible mutation syscall of the
    profile commit (crash_harness ``_arm_kill`` schedule): the committed
    path must afterwards parse and equal exactly the old record or the
    new one — pre XOR post, never torn, never missing."""
    target = tmp_path / "tuned_profile_cpu.json"
    write_tuned_profile(target, "cpu", {"prefetch": 2}, measured={"v": 1})
    old_text = target.read_text()

    def run_child(kill_at: int):
        return subprocess.run(
            [sys.executable, "-c", _KILL_CHILD, str(REPO), str(target),
             str(kill_at)],
            capture_output=True, text=True, timeout=60,
        )

    count = run_child(-1)  # arm nothing: count the boundaries
    assert count.returncode == 0, count.stderr
    boundaries = json.loads(count.stdout)["boundaries"]
    assert boundaries >= 2, "the commit lost its staged-rename protocol"
    new_record = json.loads(target.read_text())
    assert new_record["knobs"] == {"prefetch": 4}

    def stamp_free(record: dict) -> dict:
        # created_wall legitimately differs per attempt; everything else
        # must be byte-identical to one committed generation
        return {k: v for k, v in record.items() if k != "created_wall"}

    old_record = json.loads(old_text)
    for kill_at in range(boundaries):
        target.write_text(old_text)  # reset to the pre-commit state
        proc = run_child(kill_at)
        assert proc.returncode == -signal.SIGKILL, (kill_at, proc.stderr)
        surviving = json.loads(target.read_text())  # parses: never torn
        assert stamp_free(surviving) in (stamp_free(old_record),
                                         stamp_free(new_record)), (
            f"kill at boundary {kill_at} left a mixed-generation profile: "
            f"{surviving!r}"
        )


# --------------------------------------- tier-3 profile-check fixtures


REGISTRY_OK = """
class EntryPoint:
    def __init__(self, name=None):
        self.name = name


ENTRY_POINTS = (
    EntryPoint(name="tfidf_chunk_ingest_carry"),
)

TUNED_KNOBS = (
    ("prefetch", (0, 2, 4), ("tfidf_chunk_ingest_carry",)),
)
"""

CONFIG_OK = """
TUNABLE_DEFAULTS = {"prefetch": 2}
"""


def profile_lint(tmp_path: Path, registry_src: str, config_src: str,
                 scan_files: dict | None = None, profiles=None):
    """Write a synthetic contract tree and run the tier-3 profile checks
    over it (run_profile's injection point for fixture tests)."""
    files = {
        f"{_PKG}/analysis/registry.py": registry_src,
        f"{_PKG}/utils/config.py": config_src,
        **(scan_files or {}),
    }
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    _contract_cache.clear()
    try:
        return run_profile(root=tmp_path, paths=[tmp_path],
                           profiles=list(profiles or []))
    finally:
        _contract_cache.clear()


def _artifact(record, backend="cpu"):
    return ProfileArtifact(relpath=f"tuned_profile_{backend}.json",
                           backend=backend, record=record, error=None)


def test_profile_drift_tn(tmp_path):
    res = profile_lint(
        tmp_path, REGISTRY_OK, CONFIG_OK,
        profiles=[_artifact({"backend": "cpu", "knobs": {"prefetch": 4}})],
    )
    assert res.findings == []
    assert res.report["knobs"]["prefetch"]["tuned"]["cpu"] == 4


def test_profile_drift_artifact_tp(tmp_path):
    """Stale knob, out-of-domain value, declared-but-untuned knob, and a
    backend stamp disagreeing with the filename — each its own finding."""
    res = profile_lint(
        tmp_path, REGISTRY_OK, CONFIG_OK,
        profiles=[_artifact({"backend": "tpu",
                             "knobs": {"prefetch": 3, "bogus": 1}})],
    )
    msgs = [f.message for f in res.findings]
    assert all(f.rule == "profile-drift" for f in res.findings)
    assert any("stale knob 'bogus'" in m for m in msgs), msgs
    assert any("outside" in m and "'prefetch'" in m for m in msgs), msgs
    assert any("does not match the filename" in m for m in msgs), msgs
    # a profile missing a declared knob is a drift the other way
    res = profile_lint(
        tmp_path, REGISTRY_OK, CONFIG_OK,
        profiles=[_artifact({"backend": "cpu", "knobs": {}})],
    )
    assert any("untuned" in f.message for f in res.findings)
    # the TUNABLE_DEFAULTS value itself is always in-domain (a profile
    # may legitimately conclude the hand-picked default already wins)
    res = profile_lint(
        tmp_path,
        REGISTRY_OK.replace("(0, 2, 4)", "(0, 4)"),
        CONFIG_OK,
        profiles=[_artifact({"backend": "cpu", "knobs": {"prefetch": 2}})],
    )
    assert res.findings == []


def test_profile_drift_contract_tp(tmp_path):
    """The declaration itself drifts: a searchable knob with no default,
    a default with no search space, an affected entry that does not
    exist."""
    res = profile_lint(
        tmp_path,
        REGISTRY_OK.replace('"prefetch", (0, 2, 4)',
                            '"undeclared", (0, 2, 4)'),
        CONFIG_OK,
    )
    msgs = [f.message for f in res.findings]
    assert any("no such default" in m for m in msgs), msgs
    assert any("no TUNED_KNOBS row" in m for m in msgs), msgs
    res = profile_lint(
        tmp_path,
        REGISTRY_OK.replace('("tfidf_chunk_ingest_carry",)),',
                            '("no_such_entry",)),'),
        CONFIG_OK,
    )
    assert any("ENTRY_POINTS does not define" in f.message
               for f in res.findings)


def test_profile_drift_suppressed(tmp_path):
    res = profile_lint(
        tmp_path,
        REGISTRY_OK.replace(
            "TUNED_KNOBS = (",
            "TUNED_KNOBS = (  # graftlint: disable=profile-drift "
            "(migration window: default lands next PR)",
        ).replace('"prefetch", (0, 2, 4)', '"undeclared", (0, 2, 4)'),
        CONFIG_OK.replace('{"prefetch": 2}', "{}"),
    )
    assert [f for f in res.findings if f.rule == "profile-drift"] == []


def test_untuned_knob_read_tp(tmp_path):
    """A bare-literal signature default, a dataclass-field default, and a
    call-site keyword duplicating the TUNABLE_DEFAULTS value — each a
    site the resolution ladder cannot reach."""
    res = profile_lint(
        tmp_path, REGISTRY_OK, CONFIG_OK,
        scan_files={f"{_PKG}/models/thing.py": """
            import dataclasses


            def run(corpus, prefetch=2):
                return corpus


            @dataclasses.dataclass
            class Cfg:
                prefetch: int = 2


            def caller(corpus):
                return run(corpus, prefetch=2)
        """},
    )
    hits = [f for f in res.findings if f.rule == "untuned-knob-read"]
    assert len(hits) == 3, [f.render() for f in res.findings]
    assert all(f.path.endswith("models/thing.py") for f in hits)


def test_untuned_knob_read_tn(tmp_path):
    """Reading the table, None-defaults, and a deliberate non-default
    literal at a call site all stay quiet — only default-duplication is
    the hazard; outside the scanned runtime dirs nothing fires."""
    clean = """
        from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
            TUNABLE_DEFAULTS,
        )


        def run(corpus, prefetch=None):
            if prefetch is None:
                prefetch = TUNABLE_DEFAULTS["prefetch"]
            return corpus


        def caller(corpus):
            return run(corpus, prefetch=4)
    """
    res = profile_lint(
        tmp_path, REGISTRY_OK, CONFIG_OK,
        scan_files={f"{_PKG}/models/clean.py": clean,
                    # same literal default OUTSIDE the scan prefixes:
                    # tools and tests may pin values freely
                    f"{_PKG}/utils/helper.py": "def f(prefetch=2): pass\n"},
    )
    assert [f for f in res.findings if f.rule == "untuned-knob-read"] == []


def test_untuned_knob_read_suppressed(tmp_path):
    res = profile_lint(
        tmp_path, REGISTRY_OK, CONFIG_OK,
        scan_files={f"{_PKG}/models/thing.py": """
            def run(corpus, prefetch=2):  # graftlint: disable=untuned-knob-read (CLI compat shim, removed next PR)
                return corpus
        """},
    )
    assert [f for f in res.findings if f.rule == "untuned-knob-read"] == []


# ----------------------------------------------------- whole-repo gates


def test_whole_repo_profile_clean():
    """Zero unratcheted tier-3 profile findings over the real surface —
    the committed contract, the committed artifacts, and every knob read
    in models//parallel//serving//dataflow/."""
    res = run_profile(root=REPO)
    baseline = load_baseline(baseline_path(REPO))
    new = [f for f in res.findings if f.fingerprint not in baseline]
    assert not new, "\n".join(f.render() for f in new)
    # the report covers the whole declared space
    assert set(res.report["knobs"]) == set(TUNABLE_DEFAULTS)
    assert "cpu" in res.report["profiles"]


def test_committed_cpu_profile_is_live():
    """The committed artifact the acceptance gate measured: loads through
    the real ladder, carries provenance, and tunes every declared knob
    to an in-domain (or default) value."""
    prof = load_tuned_profile(backend="cpu", root=REPO)
    assert prof is not None and prof.source == "committed"
    assert prof.git_sha, "committed profile lost its git provenance"
    assert prof.measured, "committed profile lost its sweep evidence"
    assert prof.measured["prune"]["prune_frac"] >= 0.30
    domains = {name: tuple(domain) for name, domain, _ in TUNED_KNOBS}
    assert set(prof.knobs) == set(domains)
    for name, value in prof.knobs.items():
        assert value in domains[name] or value == TUNABLE_DEFAULTS[name]


def test_cli_profile_report():
    proc = subprocess.run(
        [sys.executable, "-m", f"{_PKG}.analysis",
         "--tier", "3", "--profile-report", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)["profile_report"]
    assert set(report["knobs"]) == set(TUNABLE_DEFAULTS)
    row = report["knobs"]["shuffle_bucket_width"]
    assert row["default"] == TUNABLE_DEFAULTS["shuffle_bucket_width"]
    assert row["tuned"]["cpu"] in row["domain"]
    assert report["profiles"]["cpu"]["path"] == "tuned_profile_cpu.json"
