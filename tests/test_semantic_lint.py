"""graftlint tier-2 (semantic / jaxpr-level) tests — ISSUE 3.

Mirrors the tier-1 test structure: for each semantic check a true positive
(a seeded EntryPoint that must fire), a true negative (the fixed shape must
stay quiet), and a suppressed positive (registry-level ``suppress`` must
silence it).  Fixture entry points are tiny synthetic programs traced the
same way the real registry entries are.

The regression layer at the bottom is the CI gate: every registered entry
point must build, trace on the CPU backend, and produce ZERO findings —
the tier-2 ratchet stays empty, matching ISSUE 3's acceptance bar.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import repo_root
from page_rank_and_tfidf_using_apache_spark_tpu.analysis import semantic
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import (
    changed_python_files,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
    ENTRY_POINTS,
    EntryPoint,
    Traceable,
)

REPO = repo_root()


def run_entries(*entries: EntryPoint):
    return semantic.run_semantic(root=REPO, entries=list(entries))


def rules_hit(findings) -> set[str]:
    return {f.rule for f in findings}


def _sds(shape, dtype=None):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(shape, dtype or np.float32)


# ------------------------------------------------------ recompile-per-shape


def _build_unpadded():
    """Raw workload sizes straight into jit: one compile per shape."""

    def f(x):
        return x * 2.0

    return Traceable(f, [(f"n{n}", (_sds((n,)),)) for n in (100, 177, 256)])


def _build_padded():
    """The same sizes through a pow2 padding policy: one compile."""

    def f(x):
        return x * 2.0

    return Traceable(f, [(f"n{n}", (_sds((256,)),)) for n in (100, 177, 256)])


def test_recompile_true_positive():
    ep = EntryPoint(name="unpadded", module="x.py", build=_build_unpadded)
    findings = run_entries(ep)
    assert "recompile-per-shape" in rules_hit(findings)
    assert any("3 distinct jit signatures" in f.message for f in findings)


def test_recompile_true_negative():
    ep = EntryPoint(name="padded", module="x.py", build=_build_padded)
    assert "recompile-per-shape" not in rules_hit(run_entries(ep))


def test_recompile_suppressed():
    ep = EntryPoint(
        name="unpadded",
        module="x.py",
        build=_build_unpadded,
        suppress=frozenset({"recompile-per-shape"}),
    )
    assert "recompile-per-shape" not in rules_hit(run_entries(ep))


# ------------------------------------------------------- implicit-promotion


def _build_promoting():
    """Unpinned iota: int64 under x64 — the count_pairs bug class this PR
    fixed (jnp.lexsort / bare jnp.arange inside the TF sort kernel)."""

    def f(x):
        import jax.numpy as jnp

        return x * jnp.arange(x.shape[0])

    return Traceable(f, [("v", (_sds((16,)),))])


def _build_pinned():
    def f(x):
        import jax.numpy as jnp

        return x * jnp.arange(x.shape[0], dtype=jnp.int32)

    return Traceable(f, [("v", (_sds((16,)),))])


def test_promotion_true_positive():
    ep = EntryPoint(name="promo", module="x.py", build=_build_promoting)
    findings = [f for f in run_entries(ep) if f.rule == "implicit-promotion"]
    assert findings and "int64" in findings[0].message


def test_promotion_true_negative():
    ep = EntryPoint(name="pinned", module="x.py", build=_build_pinned)
    assert "implicit-promotion" not in rules_hit(run_entries(ep))


def test_promotion_suppressed_by_allow_64bit():
    ep = EntryPoint(
        name="promo", module="x.py", build=_build_promoting, allow_64bit=True
    )
    assert "implicit-promotion" not in rules_hit(run_entries(ep))


def test_promotion_suppress_set():
    ep = EntryPoint(
        name="promo",
        module="x.py",
        build=_build_promoting,
        suppress=frozenset({"implicit-promotion"}),
    )
    assert "implicit-promotion" not in rules_hit(run_entries(ep))


# --------------------------------------------------------- transfer-census


def _build_callbacking():
    def f(x):
        import jax

        jax.debug.print("x = {x}", x=x)
        return x + 1.0

    return Traceable(f, [("v", (_sds((8,)),))])


def _build_pure():
    def f(x):
        return x + 1.0

    return Traceable(f, [("v", (_sds((8,)),))])


def test_transfer_true_positive():
    ep = EntryPoint(name="xfer", module="x.py", build=_build_callbacking)
    findings = [f for f in run_entries(ep) if f.rule == "transfer-census"]
    assert findings and "budget 0" in findings[0].message


def test_transfer_true_negative():
    ep = EntryPoint(name="clean", module="x.py", build=_build_pure)
    assert "transfer-census" not in rules_hit(run_entries(ep))


def test_transfer_within_budget():
    ep = EntryPoint(
        name="xfer", module="x.py", build=_build_callbacking, transfer_budget=1
    )
    assert "transfer-census" not in rules_hit(run_entries(ep))


def test_transfer_suppressed():
    ep = EntryPoint(
        name="xfer",
        module="x.py",
        build=_build_callbacking,
        suppress=frozenset({"transfer-census"}),
    )
    assert "transfer-census" not in rules_hit(run_entries(ep))


# ----------------------------------------------------------- sharding-axis


def _shard_mapped_psum(axis_in_mesh: str, axis_in_code: str):
    def build():
        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from page_rank_and_tfidf_using_apache_spark_tpu.parallel.compat import (
            shard_map,
        )

        mesh = Mesh(np.array(jax.devices("cpu")[:1]), (axis_in_mesh,))

        def kernel(x):
            return jax.lax.psum(x, axis_in_code)

        mapped = shard_map(
            kernel, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False
        )
        return Traceable(mapped, [("v", (_sds((8,)),))])

    return build


def test_sharding_axis_true_positive():
    ep = EntryPoint(
        name="ax",
        module="x.py",
        build=_shard_mapped_psum("data", "data"),
        axes=("nodes",),  # registry contract says nodes; program says data
    )
    findings = [f for f in run_entries(ep) if f.rule == "sharding-axis"]
    assert findings and "'data'" in findings[0].message


def test_sharding_axis_true_negative():
    ep = EntryPoint(
        name="ax",
        module="x.py",
        build=_shard_mapped_psum("nodes", "nodes"),
        axes=("nodes",),
        collective_budget=1,
    )
    assert "sharding-axis" not in rules_hit(run_entries(ep))


def test_collective_budget_true_positive():
    ep = EntryPoint(
        name="ax",
        module="x.py",
        build=_shard_mapped_psum("nodes", "nodes"),
        axes=("nodes",),
        collective_budget=0,
    )
    findings = [f for f in run_entries(ep) if f.rule == "sharding-axis"]
    assert findings and "communication eqn" in findings[0].message


def test_sharding_axis_suppressed():
    ep = EntryPoint(
        name="ax",
        module="x.py",
        build=_shard_mapped_psum("data", "data"),
        axes=("nodes",),
        collective_budget=0,
        suppress=frozenset({"sharding-axis"}),
    )
    assert "sharding-axis" not in rules_hit(run_entries(ep))


# ----------------------------------------------- collective-uniformity


def _shard_divergent(ctrl: str, uniform: bool):
    """A shard_mapped program whose ``ctrl`` (cond/while) wraps a psum.
    ``uniform=True`` reduces the predicate with a psum first (the owned
    fixpoint idiom) — globally identical by construction; False leaves
    it shard-varying: some shards would enter the collective, the rest
    never arrive."""

    def build():
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from page_rank_and_tfidf_using_apache_spark_tpu.parallel.compat import (
            shard_map,
        )

        mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("nodes",))

        def kernel(x):
            if ctrl == "cond":
                resid = jnp.sum(jnp.abs(x))
                if uniform:
                    resid = jax.lax.psum(resid, "nodes")
                return jax.lax.cond(
                    resid > 0.5,
                    lambda v: jax.lax.psum(v, "nodes"),
                    lambda v: v * 2.0,
                    x,
                )

            def cond_fn(c):
                resid = jnp.sum(jnp.abs(c))
                if uniform:
                    resid = jax.lax.psum(resid, "nodes")
                return resid > 0.5

            def body_fn(c):
                return jax.lax.psum(c, "nodes") * 0.25

            return jax.lax.while_loop(cond_fn, body_fn, x)

        mapped = shard_map(
            kernel, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False
        )
        return Traceable(mapped, [("v", (_sds((8,)),))])

    return build


def test_collective_uniformity_tp_cond():
    ep = EntryPoint(
        name="div_cond",
        module="x.py",
        build=_shard_divergent("cond", uniform=False),
        axes=("nodes",),
        collective_budget=8,
    )
    findings = [f for f in run_entries(ep)
                if f.rule == "collective-uniformity"]
    assert findings and "psum under cond" in findings[0].message
    assert "Hoist" in findings[0].message


def test_collective_uniformity_tp_while():
    ep = EntryPoint(
        name="div_while",
        module="x.py",
        build=_shard_divergent("while", uniform=False),
        axes=("nodes",),
        collective_budget=8,
    )
    findings = [f for f in run_entries(ep)
                if f.rule == "collective-uniformity"]
    assert findings and "psum under while" in findings[0].message


def test_collective_uniformity_tn_reduced_cond_predicate():
    """A psum-reduced predicate is uniform by construction — the branch
    is taken identically on every shard, so the nested collective is
    safe.  This is the owned strategies' fixpoint idiom: they pass by
    analysis, not by exemption."""
    ep = EntryPoint(
        name="uni_cond",
        module="x.py",
        build=_shard_divergent("cond", uniform=True),
        axes=("nodes",),
        collective_budget=8,
    )
    assert "collective-uniformity" not in rules_hit(run_entries(ep))


def test_collective_uniformity_tn_reduced_while_predicate():
    ep = EntryPoint(
        name="uni_while",
        module="x.py",
        build=_shard_divergent("while", uniform=True),
        axes=("nodes",),
        collective_budget=8,
    )
    assert "collective-uniformity" not in rules_hit(run_entries(ep))


def test_collective_uniformity_suppressed():
    ep = EntryPoint(
        name="div_cond_ok",
        module="x.py",
        build=_shard_divergent("cond", uniform=False),
        axes=("nodes",),
        collective_budget=8,
        suppress=frozenset({"collective-uniformity"}),
    )
    assert "collective-uniformity" not in rules_hit(run_entries(ep))


def test_collective_uniformity_needs_declared_axes():
    """Unsharded entries (no ``axes`` contract) never run the uniformity
    walk — there is no mesh to diverge over."""
    ep = EntryPoint(
        name="unsharded",
        module="x.py",
        build=_shard_divergent("cond", uniform=False),
    )
    findings = run_entries(ep)
    assert "collective-uniformity" not in rules_hit(findings)


# ------------------------------------------------------- entry-point-broken


def test_broken_entry_is_a_finding():
    def build():
        raise ImportError("entry point moved")

    ep = EntryPoint(name="gone", module="x.py", build=build)
    findings = [f for f in run_entries(ep) if f.rule == "entry-point-broken"]
    assert findings and "ImportError" in findings[0].message


def test_untraceable_entry_is_a_finding():
    def build():
        def f(x):
            return x.nonexistent_attribute

        return Traceable(f, [("v", (_sds((4,)),))])

    ep = EntryPoint(name="sick", module="x.py", build=build)
    assert "entry-point-broken" in rules_hit(run_entries(ep))


# ------------------------------------------------------ the tier-2 CI gate


def test_registry_covers_every_jit_surface():
    """Each production jit surface keeps at least one registered contract."""
    modules = {ep.module for ep in ENTRY_POINTS}
    pkg = "page_rank_and_tfidf_using_apache_spark_tpu"
    assert f"{pkg}/ops/pagerank.py" in modules
    assert f"{pkg}/ops/tfidf.py" in modules
    assert f"{pkg}/parallel/pagerank_sharded.py" in modules
    assert f"{pkg}/parallel/tfidf_sharded.py" in modules
    assert f"{pkg}/dataflow/ppr.py" in modules
    assert f"{pkg}/dataflow/hits.py" in modules
    assert f"{pkg}/dataflow/components.py" in modules
    assert f"{pkg}/dataflow/bm25.py" in modules


def test_every_dataflow_jit_surface_is_registered():
    """ISSUE 9 CI gate: a module under dataflow/ that creates a jit entry
    point (lexically: any ``jax.jit`` use) without a registry entry — or
    at least a ``watch`` hook from one — fails tier-1.  A new workload
    cannot ship outside the tier-2 recompile/promotion/transfer gates and
    the tier-3 intensity/pad/donation budgets."""
    pkg = "page_rank_and_tfidf_using_apache_spark_tpu"
    covered = {ep.module for ep in ENTRY_POINTS}
    covered |= {w for ep in ENTRY_POINTS for w in ep.watch}
    missing = []
    for p in sorted((REPO / pkg / "dataflow").glob("*.py")):
        if "jax.jit" not in p.read_text(encoding="utf-8"):
            continue
        rel = f"{pkg}/dataflow/{p.name}"
        if rel not in covered:
            missing.append(rel)
    assert not missing, (
        f"dataflow modules with jit entry points but no analysis/registry.py "
        f"coverage: {missing} — declare an EntryPoint (see README 'Static "
        "analysis') before shipping the workload"
    )


def test_sharded_entries_trace_the_shrink_chain():
    """Every sharded entry declares one variant per device count on the
    elastic shrink chain (d, d/2, ..., 1) — the semantic gates must hold
    for the shrunk meshes a degraded run executes on, down to 1 device."""
    sharded = [
        ep for ep in ENTRY_POINTS
        if ep.name.startswith("pagerank_sharded")
        or ep.name == "tfidf_sharded_ingest"
    ]
    # edges/nodes_balanced/src/hybrid/owned + tfidf
    assert len(sharded) == 6
    for ep in sharded:
        t = ep.build()
        labels = [label for label, _ in t.variants]
        assert len(labels) >= 2, (ep.name, labels)
        assert any(label.endswith("-d1") or "d1-" in label for label in labels), (
            ep.name, labels,
        )
        assert len(labels) <= ep.max_compiles, (ep.name, labels)


def test_repo_semantic_clean():
    """Every registered entry point traces with ZERO findings — the tier-2
    ratchet stays empty (ISSUE 3 acceptance bar)."""
    findings = semantic.run_semantic(root=REPO)
    msg = "\n".join(f.render() + " :: " + f.message for f in findings)
    assert not findings, f"tier-2 findings (fix the code, not the gate):\n{msg}"


def test_semantic_findings_carry_real_anchors():
    """Findings must point at the entry's public function so the ratchet
    fingerprints survive registry refactors."""
    def build():
        import functools

        from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops

        fn = functools.partial(ops.chunk_counts, vocab=64)
        return Traceable(
            fn,
            [(f"n{n}", (_sds((n,), "int32"), _sds((n,), "int32"),
                        _sds((n,), "bool"))) for n in (64, 96)],
            anchor=ops.chunk_counts,
        )

    ep = EntryPoint(
        name="unpadded",
        module="page_rank_and_tfidf_using_apache_spark_tpu/ops/tfidf.py",
        build=build,
        max_compiles=1,
    )
    findings = [f for f in run_entries(ep) if f.rule == "recompile-per-shape"]
    assert findings
    f = findings[0]
    assert f.path == "page_rank_and_tfidf_using_apache_spark_tpu/ops/tfidf.py"
    assert f.line > 1 and f.snippet


def test_only_modules_respects_watch_list():
    """--changed-only must re-trace an entry when a watched dependency
    (shape policy, mesh constants) changed, not just its own module."""
    ep = EntryPoint(
        name="unpadded",
        module="x.py",
        watch=("policy.py",),
        build=_build_unpadded,
    )
    hit = semantic.run_semantic(
        root=REPO, entries=[ep], only_modules={"policy.py"}
    )
    assert "recompile-per-shape" in rules_hit(hit)
    skipped = semantic.run_semantic(
        root=REPO, entries=[ep], only_modules={"unrelated.py"}
    )
    assert skipped == []


# ------------------------------------------------------------ CLI plumbing


def test_cli_tier2_clean():
    proc = subprocess.run(
        [sys.executable, "-m",
         "page_rank_and_tfidf_using_apache_spark_tpu.analysis", "--tier", "2"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_list_entry_points():
    proc = subprocess.run(
        [sys.executable, "-m",
         "page_rank_and_tfidf_using_apache_spark_tpu.analysis",
         "--list-entry-points"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    for ep in ENTRY_POINTS:
        assert ep.name in proc.stdout


def test_changed_only_mode(tmp_path):
    """--changed-only lints exactly the files changed vs the base ref."""
    repo = tmp_path / "r"
    repo.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
    subprocess.run(["git", "-C", str(repo), "config", "user.email", "t@t"],
                   check=True)
    subprocess.run(["git", "-C", str(repo), "config", "user.name", "t"],
                   check=True)
    (repo / "clean.py").write_text("x = 1\n")
    subprocess.run(["git", "-C", str(repo), "add", "."], check=True)
    subprocess.run(["git", "-C", str(repo), "commit", "-qm", "seed"],
                   check=True)
    assert changed_python_files(repo, "HEAD") == []

    (repo / "clean.py").write_text("x = 2\n")
    (repo / "new.py").write_text("y = 3\n")
    (repo / "notes.txt").write_text("not python\n")
    changed = changed_python_files(repo, "HEAD")
    assert [p.name for p in changed] == ["clean.py", "new.py"]


def test_cli_changed_only_runs_clean():
    """On the real repo the changed-only gate must run end to end (rc 0/1,
    never a crash), and rc must be 0 when the full gate is 0."""
    proc = subprocess.run(
        [sys.executable, "-m",
         "page_rank_and_tfidf_using_apache_spark_tpu.analysis",
         "--changed-only", "HEAD", "--tier", "1"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
