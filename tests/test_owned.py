"""Owned rank slices + sparse boundary exchange (ISSUE 15).

Covers the tentpole and its satellites: boundary-planner properties
(every cut edge covered exactly once, pad gauges pinned at web-Google
scale), chip-count invariance and semantics flags under ``strategy=
'owned'``, weighted-edge PageRank (networkx-oracle-pinned, owned
included), the elastic shrink ladder 4->2->1 with re-owned slices and
rebuilt boundary sets, the exact-count Zipf generator, sharded HITS /
connected components / query-sharded PPR equivalence pins, the per-step
comm-bytes gauge with its sublinear scaling, and the trace_diff comm
regression gate.
"""

from __future__ import annotations

import importlib.util
import json
from pathlib import Path

import numpy as np
import pytest

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.components import (
    run_components,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.hits import run_hits
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.partition import (
    OwnedArray,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.ppr import (
    run_ppr_batch,
)
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
    from_edges,
    synthetic_powerlaw,
    synthetic_zipf,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import (
    run_pagerank,
)
from page_rank_and_tfidf_using_apache_spark_tpu.ops import boundary as ob
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
    auto_select_strategy,
    partition_graph,
    plan_partition,
    run_pagerank_sharded,
)
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.workloads_sharded import (
    build_owned_pair,
    run_components_sharded,
    run_hits_sharded,
    run_ppr_sharded,
    transpose_graph,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos, elastic
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    ComponentsConfig,
    GRAFT_ENV_KNOBS,
    HitsConfig,
    PageRankConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
    MetricsRecorder,
)

REPO = Path(__file__).resolve().parents[1]

F64 = dict(dangling="redistribute", init="uniform", dtype="float64")
F32 = dict(dangling="redistribute", init="uniform", dtype="float32")


@pytest.fixture(autouse=True)
def _fresh_health():
    elastic.reset_health()
    yield
    elastic.reset_health()


@pytest.fixture(scope="module")
def graph():
    return synthetic_powerlaw(600, 3600, seed=33)


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- boundary planner props


def _numpy_owned_step(graph, shard, ranks_g):
    """One owned iteration simulated in PURE numpy from the materialized
    shard arrays — exchange, lookup, both segment combines, psum — so the
    per-edge index construction is verified against a dense reference,
    not against itself."""
    d, block, h_pad = shard.d, shard.block, shard.h_pad
    strength = graph.out_strength()
    inv = np.where(strength > 0, 1.0 / np.where(strength > 0, strength, 1), 0)
    tail, head = ob.split_global(shard, ranks_g * inv, "float64")
    # the exchange: every owner's packed boundary buffer, all-gathered
    btable = np.concatenate([
        tail[j * block:(j + 1) * block][shard.out_idx[j]] for j in range(d)
    ])
    contribs = np.zeros(graph.n_nodes)
    hbuf = np.zeros(h_pad + 2)
    for i in range(d):
        local = tail[i * block:(i + 1) * block]
        lk = np.concatenate([local, btable, head, [0.0]])
        per = lk[shard.tail_src_idx[i]] * shard.tail_w[i]
        # tail combine into this device's owned rows
        tgt = np.zeros(block)
        np.add.at(tgt, shard.tail_dst[i], per)
        mask = shard.tail_map >= 0
        slots = shard.tail_map[mask]
        sel = (slots >= i * block) & (slots < (i + 1) * block)
        ids = np.flatnonzero(mask)[sel]
        contribs[ids] += tgt[slots[sel] - i * block]
        # head partial (summed across devices = the psum)
        perh = lk[shard.head_src_idx[i]] * shard.head_w[i]
        np.add.at(hbuf, shard.head_slot[i], perh)
    contribs[shard.head_ids] += hbuf[: shard.h]
    # dense reference: contribs[v] = sum_u w(u,v) * ranks[u] / s(u)
    ref = np.zeros(graph.n_nodes)
    w = graph.weight if graph.weight is not None else np.ones(graph.n_edges)
    np.add.at(ref, graph.dst, (ranks_g * inv)[graph.src] * w)
    return contribs, ref


@pytest.mark.parametrize("d", [2, 4, 8])
def test_boundary_covers_every_cut_edge(graph, d):
    """The money property: the numpy-simulated owned step (exchange +
    host-precomputed lookup indices + both combines) reproduces the dense
    SpMV exactly — every cut edge is covered through the boundary table,
    none twice."""
    plan = plan_partition(graph, d, strategy="owned")
    shard = ob.build_owned_shard(graph, plan.owned, "float64")
    rng = np.random.default_rng(1)
    ranks = rng.random(graph.n_nodes)
    got, ref = _numpy_owned_step(graph, shard, ranks)
    np.testing.assert_allclose(got, ref, rtol=1e-12, atol=1e-12)


def test_boundary_sets_unique_and_remote_only(graph):
    """No double-count: each owner's boundary set is strictly sorted
    (unique), contains only nodes that owner owns, and every member IS
    read by some other device (no dead freight in the exchange)."""
    plan = plan_partition(graph, 4, strategy="owned")
    ow = plan.owned
    n = graph.n_nodes
    starts = np.concatenate([[0], np.cumsum(ow.boundary_counts)])
    shard = ob.build_owned_shard(graph, ow, "float32")
    for j in range(4):
        seg = ow.boundary_keys[starts[j]:starts[j + 1]]
        srcs = seg - j * np.int64(n)
        assert (np.diff(seg) > 0).all()  # unique within the owner
        # owned by j: padded slot falls inside j's block
        slots = shard.tail_map[srcs]
        assert ((slots >= j * shard.block)
                & (slots < (j + 1) * shard.block)).all()
    # every boundary member is actually referenced by a remote reader:
    # the lookup-index space region [block, block + d*b_pad) of OTHER
    # devices must name each packed position at least once
    referenced = set()
    for i in range(4):
        for idx in (shard.tail_src_idx[i].ravel(),
                    shard.head_src_idx[i].ravel()):
            inb = idx[(idx >= shard.block)
                      & (idx < shard.block + 4 * shard.b_pad)]
            referenced.update((inb - shard.block).tolist())
    expect = {
        int(j * shard.b_pad + p)
        for j in range(4) for p in range(int(ow.boundary_counts[j]))
    }
    assert expect <= referenced


def test_owned_plan_pinned_at_webgoogle_scale():
    """Plan-gauge pin at web-Google scale (875k nodes / 5.1M edges, the
    bench graph): edge-slot padding is the ceil remainder (~3e-6) and the
    boundary buffers stay under 20% padding — the numbers the tier-3
    ceiling budgets must keep honest."""
    g = synthetic_powerlaw(875_000, 5_100_000, seed=7)
    p = plan_partition(g, 8, strategy="owned")
    assert p.pad_frac < 1e-5
    assert p.owned.boundary_pad_frac == pytest.approx(0.1785, rel=0.02)
    assert p.owned.h == 4096  # the max_head cap binds on this graph
    assert p.comm_entries_per_step == pytest.approx(924_675, rel=0.02)


def test_owned_partition_covers_all_edges(graph):
    """Slot accounting: real (nonzero-coefficient) edge slots across both
    edge classes equal the edge count exactly."""
    sg = partition_graph(graph, 8, strategy="owned")
    sh = sg.owned
    real = int((sh.tail_w != 0).sum() + (sh.head_w != 0).sum())
    assert real == graph.n_edges


# ------------------------------------------- owned PageRank equivalence


def test_owned_chip_count_invariance(graph):
    cfg = PageRankConfig(iterations=30, **F64)
    base = run_pagerank(graph, cfg).ranks
    for d in (1, 2, 4, 8):
        res = run_pagerank_sharded(graph, cfg, n_devices=d, strategy="owned")
        assert np.abs(res.ranks - base).sum() <= 1e-9, d


def test_owned_tolerance_and_lagged_delta(graph):
    """The convergence gauge rides the head psum one step late: a tol
    run still stops (reported delta <= tol) at most one iteration after
    the replicated strategies would."""
    cfg = PageRankConfig(iterations=500, tol=1e-10, **F64)
    res = run_pagerank_sharded(graph, cfg, n_devices=4, strategy="owned")
    ref = run_pagerank_sharded(graph, cfg, n_devices=4, strategy="edges")
    assert res.l1_delta <= 1e-10
    assert res.iterations <= ref.iterations + 2


def test_owned_drop_and_one_init(graph):
    cfg = PageRankConfig(iterations=10, dtype="float64")
    base = run_pagerank(graph, cfg).ranks
    res = run_pagerank_sharded(graph, cfg, n_devices=4, strategy="owned")
    assert np.abs(res.ranks - base).sum() <= 1e-9


def test_owned_personalized(graph):
    cfg = PageRankConfig(iterations=40, personalize=(3, 17), **F64)
    base = run_pagerank(graph, cfg).ranks
    res = run_pagerank_sharded(graph, cfg, n_devices=8, strategy="owned")
    assert np.abs(res.ranks - base).sum() <= 1e-9


def test_owned_rejects_cumsum_impl(graph):
    cfg = PageRankConfig(iterations=2, spmv_impl="cumsum", **F64)
    with pytest.raises(NotImplementedError, match="segment"):
        run_pagerank_sharded(graph, cfg, n_devices=2, strategy="owned")


def test_owned_checkpoint_resume(graph, tmp_path):
    ckdir = str(tmp_path / "ck")
    full = run_pagerank_sharded(
        graph, PageRankConfig(iterations=12, **F64), n_devices=4,
        strategy="owned",
    )
    run_pagerank_sharded(
        graph,
        PageRankConfig(iterations=6, checkpoint_every=3,
                       checkpoint_dir=ckdir, **F64),
        n_devices=4, strategy="owned",
    )
    res = run_pagerank_sharded(
        graph,
        PageRankConfig(iterations=12, checkpoint_every=3,
                       checkpoint_dir=ckdir, **F64),
        n_devices=4, strategy="owned", resume=True,
    )
    np.testing.assert_allclose(res.ranks, full.ranks, atol=1e-12)


def test_owned_rejects_non_pow2_devices(graph):
    """The boundary butterfly is recursive doubling — a non-pow2 mesh
    must be rejected at plan time, not deep inside shard_map tracing."""
    with pytest.raises(ValueError, match="power-of-two"):
        plan_partition(graph, 3, strategy="owned")


def test_auto_select_weighted_and_non_pow2_fallbacks():
    """auto must never route a weighted graph into sharded 'hybrid' (it
    refuses weights), and a starved budget on a non-pow2 mesh falls back
    to nodes_balanced instead of handing the butterfly an odd count."""
    rng = np.random.default_rng(0)
    dst = np.concatenate([rng.integers(0, 4, 9000),
                          rng.integers(4, 2000, 1000)])
    src = rng.integers(0, 2000, dst.size)
    g = from_edges(src, dst)
    gw = from_edges(src, dst, weight=rng.uniform(0.5, 2.0, dst.size))
    assert auto_select_strategy(g, 8) == "hybrid"
    assert auto_select_strategy(gw, 8) == "edges"  # weighted: no hybrid
    assert auto_select_strategy(gw, 8, hbm_bytes=10_000) == "owned"
    assert auto_select_strategy(gw, 6, hbm_bytes=10_000) == "nodes_balanced"


def test_auto_select_picks_owned_when_replicated_does_not_fit(graph):
    assert auto_select_strategy(graph, 8, hbm_bytes=10_000) == "owned"
    res = run_pagerank_sharded(
        graph, PageRankConfig(iterations=10, **F64), n_devices=4,
        strategy="owned",
    )
    base = run_pagerank(graph, PageRankConfig(iterations=10, **F64))
    assert np.abs(res.ranks - base.ranks).sum() <= 1e-9


# --------------------------------------------------- weighted PageRank


def _weighted_graph(n=250, e=2000, seed=3):
    rng = np.random.default_rng(seed)
    return from_edges(
        rng.integers(0, n, e), rng.integers(0, n, e),
        weight=rng.uniform(0.2, 3.0, e),
    )


def test_weighted_oracle_networkx_all_impls():
    """Weighted-edge PageRank pinned against ``networkx.pagerank(
    weight=)`` for every single-chip SpMV impl — the last unopened
    workload from the original list."""
    nx = pytest.importorskip("networkx")
    g = _weighted_graph()
    G = nx.DiGraph()
    G.add_nodes_from(int(i) for i in g.node_ids)
    for s, d2, w in zip(g.src, g.dst, g.weight):
        G.add_edge(int(g.node_ids[s]), int(g.node_ids[d2]), weight=float(w))
    pr = nx.pagerank(G, alpha=0.85, tol=1e-12, max_iter=500, weight="weight")
    want = np.array([pr[int(i)] for i in g.node_ids])
    for impl in ("segment", "bcoo", "cumsum", "cumsum_mxu", "hybrid",
                 "sort_shuffle", "pallas"):
        cfg = PageRankConfig(iterations=200, spmv_impl=impl, **F64)
        res = run_pagerank(g, cfg)
        assert np.abs(res.ranks - want).max() < 1e-8, impl


@pytest.mark.parametrize(
    "strategy", ["owned", "edges", "nodes", "nodes_balanced", "src"])
def test_weighted_sharded_matches_single_chip(strategy):
    g = _weighted_graph()
    cfg = PageRankConfig(iterations=30, **F64)
    base = run_pagerank(g, cfg).ranks
    res = run_pagerank_sharded(g, cfg, n_devices=4, strategy=strategy)
    assert np.abs(res.ranks - base).sum() <= 1e-9


def test_weighted_sharded_hybrid_refuses():
    g = _weighted_graph()
    with pytest.raises(NotImplementedError, match="weighted"):
        partition_graph(g, 2, strategy="hybrid")


def test_weight_dedup_sums_duplicates():
    g = from_edges([0, 0, 1], [1, 1, 0], weight=[1.0, 2.5, 4.0])
    assert g.n_edges == 2
    assert g.weight[g.src == 0][0] == pytest.approx(3.5)
    assert g.out_strength()[0] == pytest.approx(3.5)


def test_weight_must_be_positive():
    with pytest.raises(ValueError, match="positive"):
        from_edges([0], [1], weight=[0.0])


# --------------------------------------------------- elastic shrink 4->2->1


def test_owned_elastic_shrink_ladder_4_2_1(tmp_path):
    """Acceptance: stacked device losses walk the owned strategy down
    4 -> 2 -> 1 — each lap re-owns the slices and rebuilds the boundary
    sets from host state — converging to the uninterrupted ranks at
    1e-6, with zero reprocessed committed iterations and one mesh.shrink
    span per loss."""
    g = synthetic_powerlaw(900, 3600, seed=21)
    cfg = PageRankConfig(iterations=8, checkpoint_every=4,
                         checkpoint_dir=str(tmp_path / "ck"), **F32)
    base = run_pagerank(g, PageRankConfig(iterations=8, **F32))
    m = MetricsRecorder()
    obs.start_run("owned_elastic", str(tmp_path / "tr"))
    try:
        with chaos.inject(
            "pagerank_step:device_lost@dev:1;"
            "pagerank_elastic_rerun:device_lost@dev:2"
        ):
            res = run_pagerank_sharded(g, cfg, n_devices=4,
                                       strategy="owned", metrics=m)
    finally:
        obs.end_run()
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)
    assert res.iterations == 8
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert [(d["devices_old"], d["devices_new"]) for d in degraded] == \
        [(4, 2), (2, 1)]
    assert [d["ladder"] for d in degraded] == ["mesh_shrink", "single_device"]
    # zero reprocessed committed iterations
    iters = [r["iter"] for r in m.records if "iter" in r and "l1_delta" in r]
    assert iters == sorted(set(iters))
    # re-owned slices: one partition per mesh shape, boundary sets rebuilt
    parts = [r for r in m.records if r.get("event") == "partition"]
    assert [p["devices"] for p in parts] == [4, 2, 1]
    assert all(p["comm_bytes_per_step"] is not None for p in parts)
    trace = next((tmp_path / "tr").glob("owned_elastic.*.trace.jsonl"))
    spec = importlib.util.spec_from_file_location(
        "trace_report", REPO / "tools" / "trace_report.py")
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    rep = tr.report(str(trace))
    assert len(rep["mesh_shrinks"]) == 2  # one span per loss
    assert not rep["exhausted"]


def test_owned_device_loss_at_result_pull(tmp_path):
    g = synthetic_powerlaw(700, 2800, seed=11)
    cfg = PageRankConfig(iterations=8, checkpoint_every=4,
                         checkpoint_dir=str(tmp_path / "ck"), **F32)
    base = run_pagerank(g, PageRankConfig(iterations=8, **F32))
    m = MetricsRecorder()
    with chaos.inject("pagerank_result_pull:device_lost@dev:1"):
        res = run_pagerank_sharded(g, cfg, n_devices=2, strategy="owned",
                                   metrics=m)
    np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert [d["ladder"] for d in degraded] == ["single_device"]
    assert degraded[0]["site"] == "pagerank_result_pull"


# ------------------------------------------------------- zipf generator


def test_synthetic_zipf_exact_counts_and_determinism():
    g1 = synthetic_zipf(1500, 9000, seed=4)
    g2 = synthetic_zipf(1500, 9000, seed=4)
    assert g1.n_nodes == 1500 and g1.n_edges == 9000
    np.testing.assert_array_equal(g1.src, g2.src)
    np.testing.assert_array_equal(g1.dst, g2.dst)
    g3 = synthetic_zipf(1500, 9000, seed=5)
    assert not np.array_equal(g1.src, g3.src)


def test_synthetic_zipf_exponent_knob_shapes_the_head():
    flat = synthetic_zipf(2000, 12000, seed=2, exponent=3.0)
    steep = synthetic_zipf(2000, 12000, seed=2, exponent=1.3)
    # a steeper (smaller) exponent spreads mass into the tail; 3.0
    # concentrates it — the hot head's in-degree must reflect the knob
    assert np.diff(flat.csr_indptr()).max() > np.diff(steep.csr_indptr()).max()


def test_synthetic_zipf_src_exponent_concentrates_sources():
    """Zipf sources are what make the owned boundary sublinear: distinct
    sources (and with them the cut) must be a small fraction of n."""
    uni = synthetic_zipf(4000, 24000, seed=2)
    zipf = synthetic_zipf(4000, 24000, seed=2, src_exponent=1.5)
    assert np.unique(zipf.src).size < np.unique(uni.src).size / 3
    p_u = plan_partition(uni, 4, strategy="owned")
    p_z = plan_partition(zipf, 4, strategy="owned")
    assert (p_z.owned.boundary_counts.sum()
            < p_u.owned.boundary_counts.sum() / 3)


def test_synthetic_zipf_rejects_impossible_targets():
    with pytest.raises(ValueError, match="capacity"):
        synthetic_zipf(10, 1000)


# ------------------------------------------------ comm gauge + trace_diff


def test_comm_bytes_gauge_published_and_sublinear():
    """The partition event carries the per-step comm footprint, and on
    Zipf-source graphs it scales sublinearly with node count (the small
    in-repo version of the MULTICHIP sweep)."""
    pts = []
    for n in (4000, 16000):
        g = synthetic_zipf(n, n * 6, seed=9, src_exponent=1.5)
        m = MetricsRecorder()
        res = run_pagerank_sharded(
            g, PageRankConfig(iterations=2, **F32), n_devices=8,
            strategy="owned", metrics=m,
        )
        assert np.isfinite(res.ranks).all()
        part = next(r for r in m.records if r.get("event") == "partition")
        assert part["comm_bytes_per_step"] > 0
        pts.append((n, part["comm_bytes_per_step"]))
    expo = (np.log(pts[1][1] / pts[0][1]) / np.log(pts[1][0] / pts[0][0]))
    assert expo < 1.0, pts


def test_owned_comm_beats_replicated_psum():
    """The point of the exchange: on a Zipf-source graph the owned comm
    footprint undercuts the replicated strategies' dense psum."""
    g = synthetic_zipf(16000, 96000, seed=9, src_exponent=1.5)
    owned = plan_partition(g, 8, strategy="owned")
    edges = plan_partition(g, 8, strategy="edges")
    assert owned.comm_entries_per_step < edges.comm_entries_per_step / 4


def _bench_round(tmp_path, name, comm):
    rec = {"metric": "x", "value": 1.0,
           "extra": {"breakdown": {"phase": 1.0},
                     "breakdown_wall_secs": 1.0,
                     "comm_bytes_per_step": comm}}
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


def test_trace_diff_comm_gate(tmp_path):
    td = _load_tool("trace_diff")
    old = _bench_round(tmp_path, "BENCH_r01.json",
                       {"owned-1x": 100_000, "owned-10x": 400_000})
    # within threshold + floor: clean
    ok = _bench_round(tmp_path, "BENCH_r02.json",
                      {"owned-1x": 101_000, "owned-10x": 401_000})
    assert td.main([old, ok, "--threshold", "0.10"]) == 0
    # a point regressing past threshold AND the absolute floor: rc 1
    bad = _bench_round(tmp_path, "BENCH_r03.json",
                       {"owned-1x": 100_000, "owned-10x": 800_000})
    assert td.main([old, bad, "--threshold", "0.10"]) == 1
    # old round without the map (pre-ISSUE-15): skips cleanly
    legacy = tmp_path / "BENCH_r00.json"
    legacy.write_text(json.dumps(
        {"metric": "x", "value": 1.0,
         "extra": {"breakdown": {"phase": 1.0}}}))
    assert td.main([str(legacy), bad, "--threshold", "0.10"]) == 0
    # new round LOSING the map while the old had it: flagged
    assert td.main([old, str(legacy), "--threshold", "0.10"]) == 1


def test_owned_budget_knob_declared():
    assert "GRAFT_OWNED_BUDGET_S" in GRAFT_ENV_KNOBS


# ------------------------------------------------- owned-slice workloads


def test_owned_array_roundtrip(graph):
    plan = plan_partition(graph, 4, strategy="owned")
    shard = ob.build_owned_shard(graph, plan.owned, "float64")
    arr = OwnedArray.from_shard(shard)
    rng = np.random.default_rng(0)
    v = rng.random(graph.n_nodes)
    put = arr.put(v, "float64")
    out = put.pull()
    np.testing.assert_array_equal(out, v)


def test_transpose_graph_invariants(graph):
    tg = transpose_graph(graph)
    assert tg.n_nodes == graph.n_nodes and tg.n_edges == graph.n_edges
    assert (np.diff(tg.dst) >= 0).all()
    fwd = set(zip(graph.src.tolist(), graph.dst.tolist()))
    rev = set(zip(tg.dst.tolist(), tg.src.tolist()))
    assert fwd == rev


def test_owned_pair_shares_ownership(graph):
    sf, sr = build_owned_pair(graph, 4, "float32")
    np.testing.assert_array_equal(sf.tail_map, sr.tail_map)
    assert sf.block == sr.block and sf.n_pad == sr.n_pad


@pytest.mark.parametrize("d", [2, 8])
def test_hits_sharded_matches_single_chip(graph, d):
    cfg = HitsConfig(iterations=50, tol=1e-10, dtype="float64")
    base = run_hits(graph, cfg)
    res = run_hits_sharded(graph, cfg, n_devices=d)
    np.testing.assert_allclose(res.hubs, base.hubs, atol=1e-6)
    np.testing.assert_allclose(res.authorities, base.authorities, atol=1e-6)


@pytest.mark.parametrize("d", [2, 8])
def test_components_sharded_matches_single_chip(d):
    # several disconnected clusters so labels are non-trivial
    rng = np.random.default_rng(5)
    parts = []
    for c in range(6):
        lo = c * 120
        parts.append((rng.integers(lo, lo + 120, 300),
                      rng.integers(lo, lo + 120, 300)))
    src = np.concatenate([p[0] for p in parts])
    dst = np.concatenate([p[1] for p in parts])
    g = from_edges(src, dst)
    base = run_components(g)
    res = run_components_sharded(g, ComponentsConfig(), n_devices=d)
    np.testing.assert_array_equal(res.labels, base.labels)
    assert res.n_components == base.n_components
    assert res.converged


def test_ppr_sharded_query_axis(graph):
    cfg = PageRankConfig(iterations=40, **F64)
    queries = [[1], [5, 9], [17], [3, 4, 5], [250]]
    base = run_ppr_batch(graph, cfg, queries)
    res = run_ppr_sharded(graph, cfg, queries, n_devices=4)
    assert np.abs(res.ranks - base.ranks).max() <= 1e-9
    assert res.ranks.shape == (5, graph.n_nodes)


def test_ppr_sharded_uneven_batch(graph):
    """B not a device multiple: the pad queries must not leak into the
    returned batch."""
    cfg = PageRankConfig(iterations=20, **F64)
    queries = [[2], [7], [11]]
    base = run_ppr_batch(graph, cfg, queries)
    res = run_ppr_sharded(graph, cfg, queries, n_devices=4)
    assert res.ranks.shape == (3, graph.n_nodes)
    assert np.abs(res.ranks - base.ranks).max() <= 1e-9


def test_hits_sharded_weighted(graph):
    """Weighted edges ride the owned exchange in HITS too (the tail_w
    coefficient arrays carry them)."""
    g = _weighted_graph(n=200, e=1600, seed=8)
    cfg = HitsConfig(iterations=30, tol=0.0, dtype="float64")
    base = run_hits(g, cfg)
    res = run_hits_sharded(g, cfg, n_devices=4)
    np.testing.assert_allclose(res.hubs, base.hubs, atol=1e-6)
