"""graftlint tier-5 tests (ISSUE 14): persistence & crash-consistency
analysis, plus the durability fixes its first sweep drove.

Four layers:

1. **Fixture snippets** — per tier-5 check (atomic-write-drift,
   pointer-flip-order, gc-before-flip, schema-pair-drift,
   commit-lock-drift): a true positive, a true negative, and a
   suppressed positive.  Snippets are parsed, never executed.
2. **The declared contracts** — ``ARTIFACT_SCHEMAS`` drift is validated
   in both directions against fixture registries, and the real
   registry's families must resolve.
3. **The whole-repo gate** — the tier-5 analyzer runs over the real
   surface and must report nothing beyond ``analysis/baseline.json``
   (currently empty: the first sweep's true positives — the missing
   fsyncs on every pointer-visible rename in ``utils/checkpoint.py`` /
   ``serving/segments.py`` and the in-place ``write_text`` in
   ``utils/artifacts.py`` — were fixed, not frozen), under the declared
   ``GRAFT_PERSIST_BUDGET_S`` budget.
4. **The derived crash surface** — the crash-point enumeration is pinned
   against the real ``commit_append`` / ``commit_replace`` /
   ``save_index`` bodies (the boundaries ``tools/crash_harness.py``
   SIGKILLs), and the runtime pieces the harness leans on
   (``durable_replace``, ``gc_orphans``) are unit-tested directly.
"""

from __future__ import annotations

import json
import os
import textwrap
import time
from pathlib import Path

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
    baseline_path,
    load_baseline,
    repo_root,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis import __main__ as lint_cli
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.persistence import (
    CRASH_ENTRIES,
    PERSIST_RULES,
    enumerate_crash_points,
    persist_contract,
    run_persistence,
)

REPO = repo_root()

_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"


def persist(tmp_path: Path, files: dict[str, str]):
    """Write a tiny repo tree and run the tier-5 analyzer over it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_persistence(root=tmp_path, paths=[tmp_path])


def rules_hit(findings) -> set[str]:
    return {f.rule for f in findings}


# -------------------------------------------------------- atomic-write-drift


ATOMIC_TP = """
import json
import os
import tempfile


def save_bad(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f)


def save_good(path, doc):
    fd, tmp = tempfile.mkstemp(dir=".")
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
"""

ATOMIC_TN = """
import json
import os
import tempfile


def save_good(path, doc):
    fd, tmp = tempfile.mkstemp(dir=".")
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def append_log(path, line):
    with open(path, "a") as f:
        f.write(line)
"""

ATOMIC_SUPPRESSED = """
import json
import os
import tempfile


def save_bad(path, doc):
    with open(path, "w") as f:  # graftlint: disable=atomic-write-drift (scratch file, never read back)
        json.dump(doc, f)


def save_good(path, doc):
    fd, tmp = tempfile.mkstemp(dir=".")
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
"""

POINTER_RAW_REPLACE_TP = """
import os
import tempfile


def _write_pointer(d, name):
    fd, tmp = tempfile.mkstemp(dir=d)
    with os.fdopen(fd, "w") as f:
        f.write(name)
    os.replace(tmp, os.path.join(d, "LATEST"))
"""

POINTER_DURABLE_TN = """
import os
import tempfile


def durable_replace(src, dst):
    fd = os.open(src, os.O_RDONLY)
    os.fsync(fd)
    os.close(fd)
    os.replace(src, dst)


def _write_pointer(d, name):
    fd, tmp = tempfile.mkstemp(dir=d)
    with os.fdopen(fd, "w") as f:
        f.write(name)
    durable_replace(tmp, os.path.join(d, "LATEST"))
"""


def test_atomic_write_drift_tp(tmp_path):
    res = persist(tmp_path, {"store.py": ATOMIC_TP})
    hits = [f for f in res.findings if f.rule == "atomic-write-drift"]
    assert hits and any("final name" in f.message for f in hits)


def test_atomic_write_drift_tn(tmp_path):
    res = persist(tmp_path, {"store.py": ATOMIC_TN})
    assert "atomic-write-drift" not in rules_hit(res.findings)


def test_atomic_write_drift_suppressed(tmp_path):
    res = persist(tmp_path, {"store.py": ATOMIC_SUPPRESSED})
    assert "atomic-write-drift" not in rules_hit(res.findings)


def test_raw_replace_on_pointer_path_tp(tmp_path):
    res = persist(tmp_path, {"ptr.py": POINTER_RAW_REPLACE_TP})
    hits = [f for f in res.findings if f.rule == "atomic-write-drift"]
    assert hits and any("durable_replace" in f.message for f in hits)


def test_durable_replace_is_blessed(tmp_path):
    res = persist(tmp_path, {"ptr.py": POINTER_DURABLE_TN})
    assert "atomic-write-drift" not in rules_hit(res.findings)


# -------------------------------------------------------- pointer-flip-order


FLIP_ORDER_TP = """
import os
import tempfile


def commit(d, tmp_payload):
    _write_pointer(d, "v0002")
    os.replace(tmp_payload, os.path.join(d, "v0002"))
"""

FLIP_ORDER_TN = """
import os
import tempfile


def commit(d, tmp_payload):
    os.replace(tmp_payload, os.path.join(d, "v0002"))
    _write_pointer(d, "v0002")
"""

FLIP_ORDER_SUPPRESSED = """
import os
import tempfile


def commit(d, tmp_payload):
    _write_pointer(d, "v0002")  # graftlint: disable=pointer-flip-order (the payload pre-exists; this re-points only)
    os.replace(tmp_payload, os.path.join(d, "v0002"))
"""


def test_pointer_flip_order_tp(tmp_path):
    res = persist(tmp_path, {"commit.py": FLIP_ORDER_TP})
    assert "pointer-flip-order" in rules_hit(res.findings)


def test_pointer_flip_order_tn(tmp_path):
    res = persist(tmp_path, {"commit.py": FLIP_ORDER_TN})
    assert "pointer-flip-order" not in rules_hit(res.findings)


def test_pointer_flip_order_suppressed(tmp_path):
    res = persist(tmp_path, {"commit.py": FLIP_ORDER_SUPPRESSED})
    assert "pointer-flip-order" not in rules_hit(res.findings)


# ----------------------------------------------------------- gc-before-flip


GC_TP = """
import os
import shutil


def commit(d, tmp_payload):
    shutil.rmtree(os.path.join(d, "v0001"))
    os.replace(tmp_payload, os.path.join(d, "v0002"))
    _write_pointer(d, "v0002")
"""

GC_TN = """
import os
import shutil


def commit(d, tmp_payload):
    os.replace(tmp_payload, os.path.join(d, "v0002"))
    _write_pointer(d, "v0002")
    shutil.rmtree(os.path.join(d, "v0001"))
"""

GC_INTERPROCEDURAL_TP = """
import os
import shutil


def _sweep(d):
    shutil.rmtree(os.path.join(d, "v0001"))


def commit(d, tmp_payload):
    _sweep(d)
    os.replace(tmp_payload, os.path.join(d, "v0002"))
    _write_pointer(d, "v0002")
"""

GC_SUPPRESSED = """
import os
import shutil


def commit(d, tmp_payload):
    shutil.rmtree(os.path.join(d, "scratch"))  # graftlint: disable=gc-before-flip (scratch dir, never pointer-named)
    os.replace(tmp_payload, os.path.join(d, "v0002"))
    _write_pointer(d, "v0002")
"""


def test_gc_before_flip_tp(tmp_path):
    res = persist(tmp_path, {"commit.py": GC_TP})
    assert "gc-before-flip" in rules_hit(res.findings)


def test_gc_before_flip_tn(tmp_path):
    res = persist(tmp_path, {"commit.py": GC_TN})
    assert "gc-before-flip" not in rules_hit(res.findings)


def test_gc_before_flip_interprocedural(tmp_path):
    res = persist(tmp_path, {"commit.py": GC_INTERPROCEDURAL_TP})
    hits = [f for f in res.findings if f.rule == "gc-before-flip"]
    assert hits and any("_sweep()" in f.message for f in hits)


def test_gc_before_flip_suppressed(tmp_path):
    res = persist(tmp_path, {"commit.py": GC_SUPPRESSED})
    assert "gc-before-flip" not in rules_hit(res.findings)


# -------------------------------------------------------- schema-pair-drift


def _schema_fixture(keys="('alpha', 'beta')", aux="()",
                    writer_extra="", reader_extra=""):
    registry = f"""
    ARTIFACT_SCHEMAS = (
        ("demo",
         ("store.py::save_demo",),
         ("store.py::load_demo",),
         {keys},
         {aux}),
    )
    COMMIT_LOCKS = ()
    """
    store = f"""
    import json
    import os
    import tempfile


    def save_demo(path, alpha, beta):
        doc = {{"alpha": alpha, "beta": beta}}
        {writer_extra}
        fd, tmp = tempfile.mkstemp(dir=".")
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)


    def load_demo(path):
        with open(path) as f:
            d = json.load(f)
        {reader_extra}
        return d["alpha"], d["beta"]
    """
    return {"analysis/registry.py": registry, "store.py": store}


def test_schema_pair_clean(tmp_path):
    res = persist(tmp_path, _schema_fixture())
    assert "schema-pair-drift" not in rules_hit(res.findings)


def test_schema_declared_never_saved(tmp_path):
    res = persist(tmp_path, _schema_fixture(
        keys="('alpha', 'beta', 'ghost')", aux="('ghost',)"))
    hits = [f for f in res.findings if f.rule == "schema-pair-drift"]
    assert hits and any("'ghost'" in f.message and "no declared writer"
                        in f.message for f in hits)


def test_schema_saved_never_loaded(tmp_path):
    res = persist(tmp_path, _schema_fixture(
        keys="('alpha', 'beta', 'orphan')",
        writer_extra='doc["orphan"] = 1'))
    hits = [f for f in res.findings if f.rule == "schema-pair-drift"]
    assert hits and any("'orphan'" in f.message and "never loaded"
                        in f.message for f in hits)


def test_schema_aux_exempts_write_only(tmp_path):
    res = persist(tmp_path, _schema_fixture(
        keys="('alpha', 'beta', 'forensic')", aux="('forensic',)",
        writer_extra='doc["forensic"] = 1'))
    assert "schema-pair-drift" not in rules_hit(res.findings)


def test_schema_undeclared_write(tmp_path):
    res = persist(tmp_path, _schema_fixture(
        writer_extra='doc["stowaway"] = 1'))
    hits = [f for f in res.findings if f.rule == "schema-pair-drift"]
    assert hits and any("'stowaway'" in f.message and "does not declare"
                        in f.message for f in hits)
    # anchored at the write site, not the registry
    assert any(f.path == "store.py" for f in hits)


def test_schema_undeclared_read(tmp_path):
    res = persist(tmp_path, _schema_fixture(
        reader_extra='_ = d.get("mystery")'))
    hits = [f for f in res.findings if f.rule == "schema-pair-drift"]
    assert hits and any("'mystery'" in f.message for f in hits)


def test_schema_stale_writer_spec(tmp_path):
    files = _schema_fixture()
    files["analysis/registry.py"] = """
    ARTIFACT_SCHEMAS = (
        ("demo",
         ("store.py::no_such_function",),
         ("store.py::load_demo",),
         ('alpha', 'beta'),
         ()),
    )
    COMMIT_LOCKS = ()
    """
    res = persist(tmp_path, files)
    hits = [f for f in res.findings if f.rule == "schema-pair-drift"]
    assert hits and any("does not resolve" in f.message for f in hits)


def test_real_registry_schemas_resolve():
    contract = persist_contract(REPO)
    assert contract is not None
    families = {row[0] for row in contract.schemas}
    assert {"index", "segment_manifest", "checkpoint_meta",
            "run_manifest", "cost_artifact"} <= families
    assert any(lock == "_COMMIT_LOCK" for _m, lock, _c in contract.locks)


# -------------------------------------------------------- commit-lock-drift


def _lock_fixture(call_site):
    registry = """
    ARTIFACT_SCHEMAS = ()
    COMMIT_LOCKS = (
        ("store.py", "_LOCK", ("_commit",)),
    )
    """
    store = f"""
    import os
    import tempfile
    import threading

    _LOCK = threading.Lock()


    def _commit(d, name):
        fd, tmp = tempfile.mkstemp(dir=d)
        with os.fdopen(fd, "w") as f:
            f.write(name)
        os.replace(tmp, os.path.join(d, name))


    {call_site}
    """
    return {"analysis/registry.py": registry, "store.py": store}


def test_commit_lock_tn(tmp_path):
    res = persist(tmp_path, _lock_fixture("""
    def append(d):
        with _LOCK:
            _commit(d, "m1")
    """))
    assert "commit-lock-drift" not in rules_hit(res.findings)


def test_commit_lock_tp(tmp_path):
    res = persist(tmp_path, _lock_fixture("""
    def append(d):
        _commit(d, "m1")
    """))
    hits = [f for f in res.findings if f.rule == "commit-lock-drift"]
    assert hits and any("without holding _LOCK" in f.message for f in hits)


def test_commit_lock_suppressed(tmp_path):
    res = persist(tmp_path, _lock_fixture("""
    def append(d):
        _commit(d, "m1")  # graftlint: disable=commit-lock-drift (single-threaded bootstrap path)
    """))
    assert "commit-lock-drift" not in rules_hit(res.findings)


def test_commit_lock_stale_declaration(tmp_path):
    files = _lock_fixture("""
    def append(d):
        with _LOCK:
            _commit(d, "m1")
    """)
    files["analysis/registry.py"] = """
    ARTIFACT_SCHEMAS = ()
    COMMIT_LOCKS = (
        ("store.py", "_GHOST_LOCK", ("_commit", "_no_such_mutator")),
    )
    """
    res = persist(tmp_path, files)
    msgs = [f.message for f in res.findings
            if f.rule == "commit-lock-drift"]
    assert any("_GHOST_LOCK" in m and "stale" in m for m in msgs)
    assert any("_no_such_mutator" in m for m in msgs)


# ------------------------------------------------------- whole-repo ratchet


def test_whole_repo_persistence_clean_under_budget():
    """The acceptance gate: zero unratcheted tier-5 findings over the real
    surface, inside the declared GRAFT_PERSIST_BUDGET_S budget."""
    budget = float(os.environ.get("GRAFT_PERSIST_BUDGET_S", 10))
    t0 = time.monotonic()
    res = run_persistence(root=REPO)
    elapsed = time.monotonic() - t0
    baseline = load_baseline(baseline_path(REPO))
    new = [f for f in res.findings if f.fingerprint not in baseline]
    assert not new, "\n".join(f.render() for f in new)
    assert elapsed < budget, f"tier-5 sweep took {elapsed:.1f}s"
    # the five protocol modules are all under the model
    monitored = set(res.monitored)
    for mod in (f"{_PKG}/utils/checkpoint.py", f"{_PKG}/utils/artifacts.py",
                f"{_PKG}/serving/artifact.py", f"{_PKG}/serving/segments.py",
                f"{_PKG}/obs/manifest.py"):
        assert mod in monitored, mod


# ------------------------------------------------- crash-point enumeration


def test_crash_points_commit_append_pinned():
    """The static enumeration against the REAL commit_append body: exactly
    two reader-visible boundaries — the manifest rename and the LATEST
    pointer rename — both via durable_replace, in that order."""
    pts = enumerate_crash_points(
        REPO, f"{_PKG}/serving/segments.py::commit_append")
    bounds = [p for p in pts if p["boundary"]]
    assert [b["op"] for b in bounds] == ["replace", "replace"]
    assert "_write_manifest()" in bounds[0]["via"]
    assert "durable_replace()" in bounds[0]["via"]
    assert "_write_pointer()" in bounds[1]["via"]
    # the non-boundary ops include the staged payload write and the
    # fsyncs the durable idiom requires
    ops = [p["op"] for p in pts]
    assert "write" in ops and "fsync" in ops
    # fsync-before-rename: at least one fsync precedes the first replace
    first_replace = ops.index("replace")
    assert "fsync" in ops[:first_replace]


def test_crash_points_commit_replace_has_deferred_delete():
    pts = enumerate_crash_points(
        REPO, f"{_PKG}/serving/segments.py::commit_replace")
    bounds = [p["op"] for p in pts if p["boundary"]]
    assert bounds == ["replace", "replace", "delete"]
    # the delete is the generation-DEFERRED gc, strictly after the flip
    assert pts[-1]["op"] == "delete" or bounds[-1] == "delete"


def test_crash_points_save_index_pinned():
    """seal/save_index bottoms out in save_array_dir: the staged version
    dir rename plus its LATEST flip — the dynamic append-scenario count
    (4 = seal 2 + commit 2) decomposes into exactly these enumerations."""
    pts = enumerate_crash_points(
        REPO, f"{_PKG}/serving/artifact.py::save_index")
    bounds = [p for p in pts if p["boundary"]]
    assert [b["op"] for b in bounds] == ["replace", "replace"]
    assert "save_array_dir()" in bounds[0]["via"]


def test_crash_point_report_covers_declared_entries(capsys):
    rc = lint_cli.main(["--tier", "5", "--crash-points", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True
    cps = doc["crash_points"]
    for entry in CRASH_ENTRIES:
        assert entry in cps and cps[entry], entry


# ----------------------------------------------------------------- CLI


def test_cli_tier5_clean(capsys):
    rc = lint_cli.main(["--tier", "5"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "clean" in out


def test_cli_list_rules_has_tier5(capsys):
    rc = lint_cli.main(["--list-rules"])
    assert rc == 0
    out = capsys.readouterr().out
    for rule in PERSIST_RULES:
        assert f"{rule}" in out
    assert "[tier 5]" in out


# ------------------------------------------------ durable_replace mechanics


def test_durable_replace_file(tmp_path):
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.checkpoint import (
        durable_replace,
    )

    src = tmp_path / "staged.tmp"
    dst = tmp_path / "final.json"
    dst.write_text("old")
    src.write_text("new")
    durable_replace(str(src), str(dst))
    assert dst.read_text() == "new"
    assert not src.exists()


def test_durable_replace_dir(tmp_path):
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.checkpoint import (
        durable_replace,
    )

    src = tmp_path / ".v0001.staging"
    src.mkdir()
    (src / "a.npy").write_bytes(b"abc")
    (src / "b.npy").write_bytes(b"def")
    dst = tmp_path / "v0001"
    durable_replace(str(src), str(dst))
    assert (dst / "a.npy").read_bytes() == b"abc"
    assert not src.exists()


# ------------------------------------------------------- gc_orphans (crash
# recovery: what tools/crash_harness.py asserts after every SIGKILL)


@pytest.fixture(scope="module")
def _segmented_builder():
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        segments as sgm,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        TfidfConfig,
    )

    cfg = TfidfConfig(vocab_bits=8)

    def build(directory, n_segments=1):
        refs = []
        base = 0
        for i in range(n_segments):
            out = run_tfidf([f"tok{i} shared word", f"tok{i} extra doc"],
                            cfg)
            ref = sgm.seal_segment(directory, out, cfg, doc_base=base)
            sgm.commit_append(directory, ref, cfg.config_hash())
            refs.append(ref)
            base += out.n_docs
        return cfg, refs

    return build, sgm


def test_gc_orphans_sweeps_crash_debris(tmp_path, _segmented_builder):
    build, sgm = _segmented_builder
    d = str(tmp_path / "idx")
    cfg, refs = build(d, n_segments=1)
    before = sgm.latest_manifest(d)

    # crash debris: a torn tmp file, a half-staged dir, a sealed-but-
    # never-committed segment, and a manifest written but never flipped to
    (tmp_path / "idx" / "writer.tmp").write_text("torn")
    staging = tmp_path / "idx" / "segments" / ".v0099.abc"
    staging.mkdir(parents=True)
    (staging / "doc.npy").write_bytes(b"x")
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf,
    )

    orphan_out = run_tfidf(["never committed"], cfg)
    sgm.seal_segment(d, orphan_out, cfg, doc_base=999)
    stale = tmp_path / "idx" / "manifest_000099.json"
    stale.write_text(json.dumps({"version": 99, "config_hash": "x",
                                 "segments": []}))

    # a live index would use the default mtime grace window; the debris
    # here is freshly planted, so sweep as the post-crash harness does
    deleted = sgm.gc_orphans(d, min_age_s=0)
    assert len(deleted) >= 4
    assert not (tmp_path / "idx" / "writer.tmp").exists()
    assert not staging.exists()
    assert not stale.exists()
    # the committed generation is untouched and still serves
    after = sgm.latest_manifest(d)
    assert after.version == before.version
    assert {s.name for s in after.segments} == {s.name for s in
                                               before.segments}
    segset = sgm.load_segment_set(d)
    assert segset.n_docs == before.n_docs
    # idempotent: a second sweep finds nothing
    assert sgm.gc_orphans(d, min_age_s=0) == []


def test_gc_orphans_keeps_deferred_gc_list(tmp_path, _segmented_builder):
    """Segments on the committed manifest's `replaced` list are still
    named (a reader of the just-superseded generation may hold them) —
    the orphan sweep must keep them; only commit_replace's own deferred
    pass may delete them one generation later."""
    build, sgm = _segmented_builder
    d = str(tmp_path / "idx")
    cfg, refs = build(d, n_segments=2)
    merged = sgm.merge_segments(d, tuple(refs), cfg)
    sgm.commit_replace(d, (refs[0].name, refs[1].name), merged)
    replaced_dirs = [os.path.join(d, sgm.SEGMENTS_SUBDIR, r.name)
                     for r in refs]
    assert all(os.path.isdir(p) for p in replaced_dirs)  # deferred GC
    sgm.gc_orphans(d, min_age_s=0)
    assert all(os.path.isdir(p) for p in replaced_dirs), \
        "gc_orphans deleted segments the replaced-list still names"
    # two appends (gen 1, 2) + the replace commit = generation 3
    assert sgm.load_segment_set(d).version == 3
