"""graftlint tier-3 (static cost model) tests — ISSUE 6.

Mirrors the tier-1/tier-2 test structure: for each tier-3 check a true
positive (a seeded EntryPoint that must fire), a true negative (the clean
shape must stay quiet), and a suppressed positive (registry-level
``suppress`` must silence it).  Then the regression layer the tentpole is
really about:

- the **static pad_frac analyzer** must reproduce the dryrun-measured
  ``pad_frac`` values recorded in MULTICHIP_r05.json within 2% — the plan
  the linter budgets is the plan ``partition_graph`` materializes;
- the **buffer-donation verifier** must hold on the fixed fixpoint and
  ingest-carry runners (declared donations really alias in the lowering);
- the whole registry must produce ZERO tier-3 findings (empty ratchet),
  and the backend-provenance guard must keep a CPU run from overwriting a
  TPU-measured cost artifact.
"""

from __future__ import annotations

import json
import re
import subprocess
import sys
import time
from pathlib import Path

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import repo_root
from page_rank_and_tfidf_using_apache_spark_tpu.analysis import cost
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
    ENTRY_POINTS,
    EntryPoint,
    Traceable,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils import artifacts

REPO = repo_root()


def _sds(shape, dtype=None):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(shape, dtype or np.float32)


def _tpu_baseline(tmp_path: Path) -> Path:
    p = tmp_path / "cost_tpu.json"
    p.write_text(json.dumps({"backend": "tpu", "ops": {}}))
    return p


def _cpu_baseline(tmp_path: Path) -> Path:
    p = tmp_path / "cost_cpu.json"
    p.write_text(json.dumps({"backend": "cpu", "ops": {}}))
    return p


def run_entries(*entries: EntryPoint, baseline: Path | None = None):
    return cost.run_cost(root=REPO, entries=list(entries),
                         baseline_path=baseline)


def rules_hit(findings) -> set[str]:
    return {f.rule for f in findings}


# ---------------------------------------------------------- intensity-floor


def _build_memory_bound():
    """x + 1: one flop per element over 8 read + 8 written bytes — static
    intensity ~0.125, far under a floor of 1.0."""

    def f(x):
        return x + 1.0

    return Traceable(f, [("v", (_sds((1024,)),))])


def test_intensity_true_positive_with_tpu_baseline(tmp_path):
    ep = EntryPoint(name="membound", module="x.py",
                    build=_build_memory_bound, intensity_floor=1.0)
    res = run_entries(ep, baseline=_tpu_baseline(tmp_path))
    findings = [f for f in res.findings if f.rule == "intensity-floor"]
    assert findings and "below the declared floor" in findings[0].message
    assert not res.advisories


def test_intensity_advisory_with_cpu_baseline(tmp_path):
    """The provenance downgrade: xla_cost_tpu.json stamped backend=cpu
    (the current tunnel-down reality) must not gate — the same regression
    surfaces as a non-gating advisory instead."""
    ep = EntryPoint(name="membound", module="x.py",
                    build=_build_memory_bound, intensity_floor=1.0)
    res = run_entries(ep, baseline=_cpu_baseline(tmp_path))
    assert "intensity-floor" not in rules_hit(res.findings)
    adv = [f for f in res.advisories if f.rule == "intensity-floor"]
    assert adv and "ADVISORY" in adv[0].message
    assert res.ok


def test_intensity_true_negative(tmp_path):
    ep = EntryPoint(name="membound", module="x.py",
                    build=_build_memory_bound, intensity_floor=0.01)
    res = run_entries(ep, baseline=_tpu_baseline(tmp_path))
    assert "intensity-floor" not in rules_hit(res.findings + res.advisories)


def test_intensity_suppressed(tmp_path):
    ep = EntryPoint(name="membound", module="x.py",
                    build=_build_memory_bound, intensity_floor=1.0,
                    suppress=frozenset({"intensity-floor"}))
    res = run_entries(ep, baseline=_tpu_baseline(tmp_path))
    assert "intensity-floor" not in rules_hit(res.findings + res.advisories)


# ---------------------------------------------------------- pad-frac-budget


def _build_trivial():
    def f(x):
        return x * 2.0

    return Traceable(f, [("v", (_sds((16,)),))])


def test_pad_frac_true_positive():
    ep = EntryPoint(name="padded", module="x.py", build=_build_trivial,
                    pad_plan=lambda: [("d4", 0.62), ("d2", 0.10)],
                    pad_frac_ceiling=0.25)
    res = run_entries(ep)
    findings = [f for f in res.findings if f.rule == "pad-frac-budget"]
    assert findings and "0.6200" in findings[0].message
    assert "'d4'" in findings[0].message  # attributes the worst plan point


def test_pad_frac_true_negative():
    ep = EntryPoint(name="padded", module="x.py", build=_build_trivial,
                    pad_plan=lambda: [("d4", 0.12)], pad_frac_ceiling=0.25)
    assert "pad-frac-budget" not in rules_hit(run_entries(ep).findings)


def test_pad_frac_suppressed():
    ep = EntryPoint(name="padded", module="x.py", build=_build_trivial,
                    pad_plan=lambda: [("d4", 0.62)], pad_frac_ceiling=0.25,
                    suppress=frozenset({"pad-frac-budget"}))
    assert "pad-frac-budget" not in rules_hit(run_entries(ep).findings)


# -------------------------------------------------------- donation-contract


def _build_undonated():
    """A carry-shaped program WITHOUT donate_argnums: the ingest-carry bug
    class this tier exists to catch."""

    def build():
        import jax

        f = jax.jit(lambda c, x: (c + x, x * 2.0))
        return Traceable(f, [("v", (_sds((8,)), _sds((8,))))])

    return build


def _build_donated():
    def build():
        import jax

        f = jax.jit(lambda c, x: (c + x, x * 2.0), donate_argnums=(0,))
        return Traceable(f, [("v", (_sds((8,)), _sds((8,))))])

    return build


def test_donation_declared_but_absent_is_a_finding():
    ep = EntryPoint(name="carry", module="x.py", build=_build_undonated(),
                    donate=(0,))
    findings = [f for f in run_entries(ep).findings
                if f.rule == "donation-contract"]
    assert findings and "does not happen" in findings[0].message


def test_donation_true_negative():
    ep = EntryPoint(name="carry", module="x.py", build=_build_donated(),
                    donate=(0,))
    res = run_entries(ep)
    assert "donation-contract" not in rules_hit(res.findings)


def test_undeclared_donation_is_a_finding():
    """The inverse direction: an aliased input the registry does not
    declare is a contract drift too (callers must know a buffer is
    consumed)."""
    ep = EntryPoint(name="carry", module="x.py", build=_build_donated(),
                    donate=())
    findings = [f for f in run_entries(ep).findings
                if f.rule == "donation-contract"]
    assert findings and "undeclared" in findings[0].message


def test_donation_unchecked_when_not_declared():
    ep = EntryPoint(name="carry", module="x.py", build=_build_donated())
    assert "donation-contract" not in rules_hit(run_entries(ep).findings)


def test_donation_suppressed():
    ep = EntryPoint(name="carry", module="x.py", build=_build_undonated(),
                    donate=(0,), suppress=frozenset({"donation-contract"}))
    assert "donation-contract" not in rules_hit(run_entries(ep).findings)


# --------------------------------------------------------- cost-entry-broken


def test_broken_entry_is_a_finding():
    def build():
        raise ImportError("entry point moved")

    ep = EntryPoint(name="gone", module="x.py", build=build)
    findings = [f for f in run_entries(ep).findings
                if f.rule == "cost-entry-broken"]
    assert findings and "ImportError" in findings[0].message


# ------------------------------------------- static pad_frac vs the dryrun


def _measured_dryrun_pad_fracs() -> dict[str, float]:
    """Strategy -> pad_frac as MEASURED by the 8-device dryrun, parsed out
    of MULTICHIP_r05.json's log tail (each partition event is followed by
    its 'dryrun pagerank[STRATEGY] ... ok' line)."""
    tail = json.loads((REPO / "MULTICHIP_r05.json").read_text())["tail"]
    pairs = re.findall(
        r'"pad_frac": ([0-9.]+).*?dryrun pagerank\[(\w+)\]', tail, re.S
    )
    return {strategy: float(frac) for frac, strategy in pairs}


def test_static_pad_frac_matches_multichip_dryrun_within_2pct():
    """The tentpole cross-check: the static plan analyzer, fed the dryrun
    graph (synthetic_powerlaw(64, 256, seed=0)) at the dryrun's 8 devices,
    must reproduce the run-measured pad_frac for src / nodes within 2% —
    no dispatch, no mesh, just the plan.  ``nodes_balanced``'s planner was
    deliberately IMPROVED by the hybrid PR (optimal min-max boundary
    search), so its static value must now PLAN STRICTLY LESS padding than
    the r05 dryrun measured (0.6058 -> 0.4661 on this graph; the
    remainder is the layout's node-granularity floor) — plan equality
    with what partition_graph materializes is pinned separately below."""
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        synthetic_powerlaw,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
        plan_partition,
    )

    measured = _measured_dryrun_pad_fracs()
    for strategy in ("src", "nodes", "nodes_balanced"):
        assert strategy in measured, (strategy, measured)
    d = json.loads((REPO / "MULTICHIP_r05.json").read_text())["n_devices"]
    graph = synthetic_powerlaw(64, 256, seed=0)  # the dryrun graph
    for strategy in ("src", "nodes"):
        static = plan_partition(graph, d, strategy=strategy).pad_frac
        assert static == pytest.approx(measured[strategy], rel=0.02), (
            strategy, static, measured[strategy],
        )
    improved = plan_partition(graph, d, strategy="nodes_balanced").pad_frac
    assert improved < measured["nodes_balanced"] - 0.10, (
        improved, measured["nodes_balanced"],
    )
    # the hybrid strategy plans still less on the registry's gated shrink
    # points (d=4 here; web-Google scale is pinned in test_hybrid_spmv)
    hybrid = plan_partition(graph, 4, strategy="hybrid").pad_frac
    assert hybrid <= 0.30


def test_plan_is_what_partition_graph_materializes():
    """plan_partition and partition_graph cannot diverge: the materialized
    ShardedGraph carries exactly the planned pad_frac / widths."""
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        synthetic_powerlaw,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        pagerank_sharded as ps,
    )

    graph = synthetic_powerlaw(300, 2400, seed=5)
    for strategy in ("edges", "nodes", "nodes_balanced", "src", "src_ring",
                     "hybrid"):
        for d in (1, 2, 4):
            plan = ps.plan_partition(graph, d, strategy=strategy)
            sg = ps.partition_graph(graph, d, strategy=strategy,
                                    need_local_indptr=False)
            assert sg.pad_frac == plan.pad_frac, (strategy, d)
            assert sg.n_pad == plan.n_pad and sg.block == plan.block
            assert sg.src.shape == (d, plan.e_dev)
            if strategy == "hybrid":
                head_k, w, rows, rows_dev = plan.head
                assert sg.head_src.shape == (d, max(rows_dev, 1), max(w, 1))
                # every real (non-sentinel) head slot is one head edge
                real = int((sg.head_src != sg.n_pad).sum())
                assert real == graph.n_edges - int(sg.valid.sum())


def test_stream_pad_plan_runs_the_real_cap_policy():
    """grow_chunk_cap doubling from a 2^14 start: caps 16384, 131072,
    131072, 131072 over the registry matrix — pad_frac ~0.127."""
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        stream_pad_plan,
    )

    [(label, frac)] = stream_pad_plan((9_000, 120_000, 97_531, 131_072))
    assert label == "stream"
    total_raw = 9_000 + 120_000 + 97_531 + 131_072
    total_cap = 16_384 + 3 * 131_072
    assert frac == pytest.approx(1 - total_raw / total_cap, abs=1e-6)


# -------------------------------------------------- backend-provenance guard


def test_provenance_guard_refuses_cpu_over_tpu(tmp_path):
    p = tmp_path / "cost.json"
    artifacts.write_artifact(p, {"ops": {"x": 1}}, backend="tpu")
    assert artifacts.read_backend(p) == "tpu"
    with pytest.raises(artifacts.ProvenanceError, match="refusing"):
        artifacts.write_artifact(p, {"ops": {"x": 2}}, backend="cpu")
    assert json.loads(p.read_text())["ops"] == {"x": 1}  # untouched


def test_provenance_guard_force_and_benign_paths(tmp_path):
    p = tmp_path / "cost.json"
    # cpu over cpu: fine (same-grade refresh)
    artifacts.write_artifact(p, {"v": 1}, backend="cpu")
    artifacts.write_artifact(p, {"v": 2}, backend="cpu")
    assert json.loads(p.read_text()) == {"backend": "cpu", "v": 2}
    # tpu over cpu: an upgrade, always allowed
    artifacts.write_artifact(p, {"v": 3}, backend="tpu")
    # cpu over tpu with --force: deliberate downgrade
    rec = artifacts.write_artifact(p, {"v": 4}, backend="cpu", force=True)
    assert rec["backend"] == "cpu"
    assert artifacts.read_backend(p) == "cpu"
    # path=None stamps without writing
    rec = artifacts.write_artifact(None, {"v": 5}, backend="cpu")
    assert rec == {"backend": "cpu", "v": 5}


def test_cost_tools_wire_the_guard():
    """All three cost tools expose --force and route writes through
    utils/artifacts.py (the uniform backend stamp)."""
    for tool in ("xla_cost_micro.py", "gather_micro.py", "spmv_breakdown.py"):
        src = (REPO / "tools" / tool).read_text()
        assert "artifacts.write_artifact" in src, tool
        assert "--force" in src, tool


# ------------------------------------------------------ the tier-3 CI gate


def test_repo_cost_clean():
    """Every registered entry point passes tier 3 with ZERO findings — the
    ratchet stays empty (ISSUE 6 acceptance bar).  This is also the
    donation-verifier regression: the fixpoint and ingest-carry runners
    declare donations and the lowering must alias them."""
    res = cost.run_cost(root=REPO)
    msg = "\n".join(f.render() + " :: " + f.message for f in res.findings)
    assert not res.findings, f"tier-3 findings (fix the code, not the gate):\n{msg}"
    # floors are currently met, so no advisories either
    assert not res.advisories, [f.message for f in res.advisories]


def test_donated_runners_verify_in_the_report():
    """The fixed runners: donation declared == donation lowered."""
    res = cost.run_cost(root=REPO)
    by_name = {e["entry"]: e for e in res.report["entries"]}
    for name in ("pagerank_step", "pagerank_step_tol_cumsum",
                 "pagerank_step_pallas", "pagerank_step_hybrid",
                 "pagerank_step_sort_shuffle", "tfidf_chunk_ingest_carry"):
        don = by_name[name].get("donation")
        assert don, (name, by_name[name])
        assert don["aliased_buffers"] == don["declared_buffers"] >= 1, (
            name, don,
        )


def test_pallas_entry_is_registered_and_covered():
    """The Pallas spmv path has a registry entry (interpret mode on CPU),
    so tiers 2 and 3 cover it without a chip."""
    names = {ep.name for ep in ENTRY_POINTS}
    assert "pagerank_step_pallas" in names
    res = cost.run_cost(
        root=REPO,
        entries=[ep for ep in ENTRY_POINTS
                 if ep.name == "pagerank_step_pallas"],
    )
    assert not res.findings
    [entry] = res.report["entries"]
    # the pallas_call really appears as a costed leaf class
    classes = next(iter(entry["variants"].values()))["classes"]
    assert "pallas" in classes, classes


def test_intensity_gate_is_advisory_while_baseline_is_cpu():
    """The real repo artifact currently records backend=cpu (tunnel was
    down) — the tier-3 report must say the intensity gate is advisory."""
    res = cost.run_cost(root=REPO)
    backend = cost.baseline_backend(REPO / cost.COST_BASELINE_ARTIFACT)
    expected = "enforcing" if backend == "tpu" else "advisory"
    assert res.report["intensity_gate"] == expected
    assert res.report["baseline_backend"] == backend == "cpu"


def test_all_tiers_fit_the_interactive_budget():
    """ISSUE 6 acceptance: tiers 2 + 3 (the jax-tracing tiers) complete in
    well under the 10s CPU budget in-process (tools/ci.sh enforces the
    same bound per tier on the CLI, interpreter startup included)."""
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis import semantic

    t0 = time.perf_counter()
    sem = semantic.run_semantic(root=REPO)
    res = cost.run_cost(root=REPO)
    dt = time.perf_counter() - t0
    assert not sem and not res.findings
    assert dt < 10.0, f"tiers 2+3 took {dt:.1f}s (budget 10s)"


# ------------------------------------------------------------ CLI plumbing


def test_cli_tier3_clean():
    proc = subprocess.run(
        [sys.executable, "-m",
         "page_rank_and_tfidf_using_apache_spark_tpu.analysis", "--tier", "3"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_tier_all_runs_three_tiers_clean():
    proc = subprocess.run(
        [sys.executable, "-m",
         "page_rank_and_tfidf_using_apache_spark_tpu.analysis",
         "--tier", "all"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_cost_report():
    proc = subprocess.run(
        [sys.executable, "-m",
         "page_rank_and_tfidf_using_apache_spark_tpu.analysis",
         "--tier", "3", "--cost-report", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    report = out["cost_report"]
    names = {e["entry"] for e in report["entries"]}
    assert {"pagerank_step", "tfidf_chunk_ingest_carry"} <= names
    sample = next(e for e in report["entries"] if e["entry"] == "pagerank_step")
    variant = next(iter(sample["variants"].values()))
    assert variant["flops"] > 0 and variant["hbm_bytes"] > 0
    assert 0 < variant["intensity"] < 10


def test_cli_list_rules_includes_tier3():
    proc = subprocess.run(
        [sys.executable, "-m",
         "page_rank_and_tfidf_using_apache_spark_tpu.analysis",
         "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    for rid in ("intensity-floor", "pad-frac-budget", "donation-contract"):
        assert rid in proc.stdout


# ------------------------------------------------------- tools/trace_diff.py


def _diff_mod():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_diff_under_test", REPO / "tools" / "trace_diff.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_diff_attributes_the_regressed_phase(tmp_path):
    td = _diff_mod()
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    # driver-wrapped round vs bare bench record: both shapes must load
    old.write_text(json.dumps({"parsed": {"extra": {
        "breakdown": {"tfidf.stream": 10.0, "tfidf.finalize": 1.0},
        "breakdown_wall_secs": 11.2}}}))
    new.write_text(json.dumps({"extra": {
        "breakdown": {"tfidf.stream": 14.0, "tfidf.finalize": 1.02},
        "breakdown_wall_secs": 15.3}}))
    rc = td.main([str(old), str(new), "--json"])
    assert rc == 1  # a regression past the threshold fails the diff
    rows = td.diff_breakdowns(*[td.load_breakdown(str(p))[0]
                                for p in (old, new)])
    assert rows[0]["phase"] == "tfidf.stream"
    assert rows[0]["delta_secs"] == pytest.approx(4.0)
    assert rows[0]["delta_frac"] == pytest.approx(0.4)


def test_trace_diff_clean_within_threshold(tmp_path, capsys):
    td = _diff_mod()
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps({"extra": {"breakdown": {"p": 5.0}}}))
    b.write_text(json.dumps({"extra": {"breakdown": {"p": 5.2}}}))
    assert td.main([str(a), str(b), "--threshold", "0.10"]) == 0
    assert "no phase regressed" in capsys.readouterr().out


def test_trace_diff_rejects_rounds_without_breakdowns(tmp_path):
    td = _diff_mod()
    a = tmp_path / "a.json"
    a.write_text(json.dumps({"parsed": {"extra": {}}}))
    assert td.main([str(a), str(a)]) == 2
