"""graftlint tests (ISSUE 1).

Two layers:

1. **Fixture snippets** — for each of the five rule classes, a true
   positive (must flag), a true negative (must stay quiet), and a
   suppressed positive (``# graftlint: disable=...`` must silence it).
   Snippets are parsed, never executed, so they stay minimal.
2. **The ratchet gate** — the analyzer runs over the real tier-1 surface
   (the package, ``tools/``, ``bench.py``) and must report nothing beyond
   ``analysis/baseline.json``; this is the CI gate every future PR rides
   through (``tools/lint.sh``).
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
    RULES,
    apply_ratchet,
    baseline_path,
    default_targets,
    load_baseline,
    repo_root,
    run_lint,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import lint_file

REPO = repo_root()


def lint_snippet(tmp_path: Path, code: str):
    f = tmp_path / "snippet.py"
    f.write_text(code)
    return lint_file(f, tmp_path)


def rules_hit(findings) -> set[str]:
    return {f.rule for f in findings}


# --------------------------------------------------------------- rule 1


HOST_SYNC_TP = """
import jax
import jax.numpy as jnp
import numpy as np

def drain(chunks):
    out = []
    for c in chunks:
        y = jnp.dot(c, c)          # device dispatch in the loop...
        out.append(np.asarray(y))  # ...and a host pull every iteration
    return out
"""

HOST_SYNC_TP_JIT = """
import jax

@jax.jit
def f(x):
    y = x + 1
    return float(y)  # concretizes a tracer
"""

HOST_SYNC_TN = """
import numpy as np

def host_only(chunks):
    out = []
    for c in chunks:
        out.append(np.asarray(c))  # pure host loop, no device work
    return out
"""

HOST_SYNC_SUPPRESSED = """
import jax
import jax.numpy as jnp
import numpy as np

def drain(chunks):
    out = []
    for c in chunks:
        y = jnp.dot(c, c)
        out.append(np.asarray(y))  # graftlint: disable=host-sync-in-loop (single batched drain)
    return out
"""


def test_host_sync_true_positive(tmp_path):
    assert "host-sync-in-loop" in rules_hit(lint_snippet(tmp_path, HOST_SYNC_TP))


def test_host_sync_in_jit_true_positive(tmp_path):
    findings = lint_snippet(tmp_path, HOST_SYNC_TP_JIT)
    assert "host-sync-in-loop" in rules_hit(findings)


def test_host_sync_true_negative(tmp_path):
    assert "host-sync-in-loop" not in rules_hit(lint_snippet(tmp_path, HOST_SYNC_TN))


def test_host_sync_suppressed(tmp_path):
    assert "host-sync-in-loop" not in rules_hit(
        lint_snippet(tmp_path, HOST_SYNC_SUPPRESSED)
    )


# --------------------------------------------------------------- rule 2


TRACER_BRANCH_TP = """
import jax

@jax.jit
def f(x):
    if x > 0:          # Python branch on a tracer
        return x
    return -x
"""

TRACER_BRANCH_TN = """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("flag",))
def g(x, flag):
    if flag:             # static argument: branch resolves at trace time
        return x * 2
    if x.shape[0] == 0:  # shapes are static under tracing
        return x
    return x
"""

TRACER_BRANCH_SUPPRESSED = """
import jax

@jax.jit
def f(x):
    if x > 0:  # graftlint: disable=tracer-branch
        return x
    return -x
"""


def test_tracer_branch_true_positive(tmp_path):
    assert "tracer-branch" in rules_hit(lint_snippet(tmp_path, TRACER_BRANCH_TP))


def test_tracer_branch_in_scan_body(tmp_path):
    code = """
import jax
from jax import lax

def outer(xs):
    def body(carry, x):
        while carry > 0:   # tracer-hostile loop inside the scan body
            carry = carry - x
        return carry, x
    return lax.scan(body, 0.0, xs)
"""
    assert "tracer-branch" in rules_hit(lint_snippet(tmp_path, code))


def test_tracer_branch_true_negative(tmp_path):
    assert "tracer-branch" not in rules_hit(lint_snippet(tmp_path, TRACER_BRANCH_TN))


def test_tracer_branch_suppressed(tmp_path):
    assert "tracer-branch" not in rules_hit(
        lint_snippet(tmp_path, TRACER_BRANCH_SUPPRESSED)
    )


# --------------------------------------------------------------- rule 3


DTYPE_TP = """
import jax.numpy as jnp
import numpy as np

def build(n):
    a = jnp.zeros(n)                 # float default drifts under x64
    b = jnp.asarray(np.ones(n))      # numpy float64 default flows to device
    c = np.float64(0.5)              # explicit float64
    return a, b, c
"""

DTYPE_TN = """
import jax.numpy as jnp
import numpy as np

def build(n):
    a = jnp.zeros(n, jnp.float32)
    b = jnp.asarray(np.ones(n, np.float32))
    c = jnp.full(n, 0.5, jnp.float32)
    d = np.zeros(n)  # host-only numpy never reaches the device here
    return a, b, c, d
"""

DTYPE_SUPPRESSED = """
import jax.numpy as jnp

def build(n):
    return jnp.zeros(n)  # graftlint: disable=dtype-drift
"""


def test_dtype_drift_true_positive(tmp_path):
    findings = [f for f in lint_snippet(tmp_path, DTYPE_TP) if f.rule == "dtype-drift"]
    assert len(findings) >= 3  # all three drift spellings


def test_dtype_drift_true_negative(tmp_path):
    assert "dtype-drift" not in rules_hit(lint_snippet(tmp_path, DTYPE_TN))


def test_dtype_drift_suppressed(tmp_path):
    assert "dtype-drift" not in rules_hit(lint_snippet(tmp_path, DTYPE_SUPPRESSED))


# --------------------------------------------------------------- rule 4


SHAPE_TP = """
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    pos = x[x > 0]          # boolean mask: data-dependent shape
    idx = jnp.nonzero(x)    # ditto, no size=
    return pos, idx
"""

SHAPE_TN = """
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    pos = jnp.where(x > 0, x, 0.0)          # fixed-shape masking
    idx = jnp.nonzero(x, size=8, fill_value=0)
    return pos, idx

def host_filter(a):
    return a[a > 0]  # outside jit: plain numpy filtering is fine
"""

SHAPE_SUPPRESSED = """
import jax

@jax.jit
def f(x):
    return x[x > 0]  # graftlint: disable=nonstatic-shape
"""


def test_nonstatic_shape_true_positive(tmp_path):
    findings = [
        f for f in lint_snippet(tmp_path, SHAPE_TP) if f.rule == "nonstatic-shape"
    ]
    assert len(findings) >= 2  # mask indexing + nonzero


def test_nonstatic_shape_traced_slice_bound(tmp_path):
    code = """
import jax

@jax.jit
def f(x, n):
    k = n + 1
    return x[:k]   # slice bound is traced -> data-dependent shape
"""
    assert "nonstatic-shape" in rules_hit(lint_snippet(tmp_path, code))


def test_nonstatic_shape_true_negative(tmp_path):
    assert "nonstatic-shape" not in rules_hit(lint_snippet(tmp_path, SHAPE_TN))


def test_nonstatic_shape_suppressed(tmp_path):
    assert "nonstatic-shape" not in rules_hit(lint_snippet(tmp_path, SHAPE_SUPPRESSED))


# --------------------------------------------------------------- rule 5


DCE_TP_REGION = """
import time
import jax.numpy as jnp

def bench(x):
    t0 = time.perf_counter()
    jnp.dot(x, x)   # result discarded, nothing fenced: times dispatch only
    return time.perf_counter() - t0
"""

DCE_TP_PARTIAL = """
import jax
import jax.numpy as jnp
from jax import lax

def measure(reps, x0):
    @jax.jit
    def f(x):
        def body(i, acc):
            out = jnp.sin(acc)
            return acc + out.ravel()[0]   # only element 0 is live
        return lax.fori_loop(0, reps, body, x)
    return f(x0)
"""

DCE_TN = """
import time
import jax
import jax.numpy as jnp

def bench(x):
    t0 = time.perf_counter()
    y = jnp.dot(x, x)
    jax.block_until_ready(y)   # fenced: the work is measured
    secs = time.perf_counter() - t0
    return secs, y
"""

DCE_SUPPRESSED = """
import time
import jax.numpy as jnp

def bench(x):
    t0 = time.perf_counter()
    jnp.dot(x, x)  # graftlint: disable=dce-timed-region
    return time.perf_counter() - t0
"""


def test_dce_timed_region_true_positive(tmp_path):
    assert "dce-timed-region" in rules_hit(lint_snippet(tmp_path, DCE_TP_REGION))


def test_dce_partial_consumption_true_positive(tmp_path):
    """The exact tools/xla_cost_micro round-5 bug shape."""
    assert "dce-timed-region" in rules_hit(lint_snippet(tmp_path, DCE_TP_PARTIAL))


def test_dce_timed_region_true_negative(tmp_path):
    assert "dce-timed-region" not in rules_hit(lint_snippet(tmp_path, DCE_TN))


def test_dce_timed_region_suppressed(tmp_path):
    assert "dce-timed-region" not in rules_hit(lint_snippet(tmp_path, DCE_SUPPRESSED))


# --------------------------------------------------------------- rule 6


UNGUARDED_SYNC_TP = """
import jax
import jax.numpy as jnp
import numpy as np

def drain(counts):
    jax.block_until_ready(counts)          # raw fence
    host = jax.device_get(counts)          # raw pull
    y = jnp.dot(host, host)
    arr = np.asarray(y)                    # hidden sync: y is device-bound
    return host, arr
"""

UNGUARDED_SYNC_TN = """
import numpy as np
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx

def drain(counts, cfg, metrics):
    host = rx.device_get(counts, site="tfidf_chunk_sync", metrics=metrics,
                         checkpoint_dir=cfg.checkpoint_dir)  # guarded
    lengths = np.asarray([1, 2, 3])        # host data: no sync
    return host, lengths
"""

UNGUARDED_SYNC_SUPPRESSED = """
import jax

def drain(counts):
    return jax.device_get(counts)  # graftlint: disable=unguarded-host-sync (bootstrap path, no executor yet)
"""


def lint_models_snippet(tmp_path: Path, code: str):
    """Write the snippet under a models/ subtree: unguarded-host-sync only
    patrols the models/, parallel/ and io/ directories."""
    d = tmp_path / "models"
    d.mkdir(exist_ok=True)
    f = d / "snippet.py"
    f.write_text(code)
    return lint_file(f, tmp_path)


def test_unguarded_sync_true_positive(tmp_path):
    findings = [f for f in lint_models_snippet(tmp_path, UNGUARDED_SYNC_TP)
                if f.rule == "unguarded-host-sync"]
    assert len(findings) >= 3  # fence + pull + device-bound asarray


def test_unguarded_sync_true_negative(tmp_path):
    assert "unguarded-host-sync" not in rules_hit(
        lint_models_snippet(tmp_path, UNGUARDED_SYNC_TN)
    )


def test_unguarded_sync_ignores_other_directories(tmp_path):
    """The same raw syncs are legal outside models//parallel//io/ (e.g.
    ops/ pipelines, tools/) — this rule is about the execution paths."""
    f = tmp_path / "snippet.py"
    f.write_text(UNGUARDED_SYNC_TP)
    assert "unguarded-host-sync" not in rules_hit(lint_file(f, tmp_path))


def test_unguarded_sync_suppressed(tmp_path):
    assert "unguarded-host-sync" not in rules_hit(
        lint_models_snippet(tmp_path, UNGUARDED_SYNC_SUPPRESSED)
    )


# ------------------------------------- rule 12: sync-put-in-ingest-loop


SYNC_PUT_LOOP_TP = """
import jax

def ingest(chunks, esh):
    out = []
    for chunk in chunks:
        out.append(jax.device_put(chunk, esh))  # raw per-chunk H2D
    return out
"""

SYNC_PUT_LOOP_TN = """
import jax
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.ingest import staged_put

def ingest(chunks, esh, metrics):
    out = []
    for chunk in chunks:
        out.append(staged_put(lambda: jax.device_put(chunk, esh),
                              metrics=metrics))  # the staging API
    graph = jax.device_put(chunks[0])  # one-time put outside any loop
    return out, graph
"""

SYNC_PUT_LOOP_SUPPRESSED = """
import jax

def ingest(chunks):
    for chunk in chunks:
        jax.device_put(chunk)  # graftlint: disable=sync-put-in-ingest-loop (rare recovery path, one put per shrink)
"""


def test_sync_put_in_ingest_loop_true_positive(tmp_path):
    findings = [f for f in lint_models_snippet(tmp_path, SYNC_PUT_LOOP_TP)
                if f.rule == "sync-put-in-ingest-loop"]
    assert len(findings) == 1


def test_sync_put_in_ingest_loop_true_negative(tmp_path):
    assert "sync-put-in-ingest-loop" not in rules_hit(
        lint_models_snippet(tmp_path, SYNC_PUT_LOOP_TN)
    )


def test_sync_put_in_ingest_loop_ignores_other_directories(tmp_path):
    """Raw in-loop puts are legal outside dataflow//models//parallel/
    (e.g. tools/ micro-benchmarks, the serving warmup loop)."""
    f = tmp_path / "snippet.py"
    f.write_text(SYNC_PUT_LOOP_TP)
    assert "sync-put-in-ingest-loop" not in rules_hit(lint_file(f, tmp_path))


def test_sync_put_in_ingest_loop_suppressed(tmp_path):
    assert "sync-put-in-ingest-loop" not in rules_hit(
        lint_models_snippet(tmp_path, SYNC_PUT_LOOP_SUPPRESSED)
    )


# ------------------------------------------------- rule 7: untraced spans


UNTRACED_GUARDED_TP = """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx

def drain(counts, cfg, metrics):
    host = rx.device_get(counts, site="tfidf_chunk_sync", metrics=metrics)
    out = rx.run_guarded(lambda: 1, site="tfidf_chunk_sync")
    return host, out
"""

UNTRACED_GUARDED_TN = """
from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils import profiling

def drain(counts, cfg, metrics, i):
    with obs.span("tfidf.chunk", chunk=i):
        host = rx.device_get(counts, site="tfidf_chunk_sync", metrics=metrics)
    with profiling.annotate("tfidf_chunk_sync"):  # the obs.span alias
        out = rx.run_guarded(lambda: 1, site="tfidf_chunk_sync")
    return host, out
"""

UNTRACED_GUARDED_SUPPRESSED = """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx

def drain(counts):
    return rx.device_get(counts, site="boot")  # graftlint: disable=untraced-guarded-site (pre-run bootstrap pull)
"""


def test_untraced_guarded_true_positive(tmp_path):
    findings = [f for f in lint_models_snippet(tmp_path, UNTRACED_GUARDED_TP)
                if f.rule == "untraced-guarded-site"]
    assert len(findings) == 2  # the guarded pull AND the run_guarded call


def test_untraced_guarded_true_negative(tmp_path):
    assert "untraced-guarded-site" not in rules_hit(
        lint_models_snippet(tmp_path, UNTRACED_GUARDED_TN)
    )


def test_untraced_guarded_ignores_other_directories(tmp_path):
    """resilience/ itself (and tools/, bench.py) legitimately hold bare
    guarded calls — the rule patrols the execution paths only."""
    f = tmp_path / "snippet.py"
    f.write_text(UNTRACED_GUARDED_TP)
    assert "untraced-guarded-site" not in rules_hit(lint_file(f, tmp_path))


def test_untraced_guarded_catches_bare_imports(tmp_path):
    """`from ...executor import device_get` must not evade the rule: the
    bare leaf is matched like the rx./executor. aliases (an explicit jax.
    prefix is the RAW call — unguarded-host-sync's beat, not this rule's)."""
    code = """
from page_rank_and_tfidf_using_apache_spark_tpu.resilience.executor import (
    device_get,
)

def drain(counts):
    return device_get(counts, site="s")
"""
    findings = [f for f in lint_models_snippet(tmp_path, code)
                if f.rule == "untraced-guarded-site"]
    assert len(findings) == 1


def test_untraced_guarded_callers_span_not_visible(tmp_path):
    """A span in the CALLER does not cover a guarded call in a helper —
    same lexical convention as the lock rule: the helper opens its own."""
    code = """
from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx

def helper(counts):
    return rx.device_get(counts, site="s")

def caller(counts):
    with obs.span("phase"):
        return helper(counts)
"""
    findings = [f for f in lint_models_snippet(tmp_path, code)
                if f.rule == "untraced-guarded-site"]
    assert len(findings) == 1


def test_untraced_guarded_suppressed(tmp_path):
    assert "untraced-guarded-site" not in rules_hit(
        lint_models_snippet(tmp_path, UNTRACED_GUARDED_SUPPRESSED)
    )


# --------------------------------------------------------------- rule 8


THREAD_STATE_TP = """
import threading

_STATS = {}

def worker():
    _STATS["done"] = _STATS.get("done", 0) + 1  # unlocked shared write

def spawn():
    t = threading.Thread(target=worker, daemon=True)
    t.start()
    return t
"""

THREAD_STATE_TP_SELF = """
import threading

class Pump:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()

    def _loop(self):
        self.count += 1  # instance state, lock exists but is not taken

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()
"""

THREAD_STATE_TN = """
import threading

_STATS = {}
_LOCK = threading.Lock()

def worker():
    with _LOCK:
        _STATS["done"] = _STATS.get("done", 0) + 1  # guarded

def spawn():
    box = {}

    def runner():
        box["result"] = 42  # closure state joined before reads: not shared

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    t.join()
    return box
"""

THREAD_STATE_SUPPRESSED = """
import threading

_STATS = {}

def worker():
    _STATS["done"] = 1  # graftlint: disable=unsynced-thread-state (joined before read)

def spawn():
    threading.Thread(target=worker).start()
"""


def test_thread_state_true_positive(tmp_path):
    assert "unsynced-thread-state" in rules_hit(
        lint_snippet(tmp_path, THREAD_STATE_TP)
    )


def test_thread_state_instance_attr_true_positive(tmp_path):
    assert "unsynced-thread-state" in rules_hit(
        lint_snippet(tmp_path, THREAD_STATE_TP_SELF)
    )


def test_thread_state_true_negative(tmp_path):
    assert "unsynced-thread-state" not in rules_hit(
        lint_snippet(tmp_path, THREAD_STATE_TN)
    )


def test_thread_state_suppressed(tmp_path):
    assert "unsynced-thread-state" not in rules_hit(
        lint_snippet(tmp_path, THREAD_STATE_SUPPRESSED)
    )


# --------------------------------------------------------------- rule 8


ENV_KNOB_TP = """
import os

def turbo():
    return os.environ.get("GRAFT_TURBO_MODE", "0") == "1"  # undeclared knob
"""

ENV_KNOB_TN = """
import os

def retries():
    # declared in utils/config.py GRAFT_ENV_KNOBS
    keep = os.environ["GRAFT_CKPT_KEEP"]
    return int(os.environ.get("GRAFT_RETRY_MAX", 3)), keep

def unrelated():
    return os.environ.get("BENCH_NODES", "0")  # non-GRAFT namespace: free
"""

ENV_KNOB_SUPPRESSED = """
import os

def turbo():
    return os.environ.get("GRAFT_TURBO_MODE")  # graftlint: disable=env-knob-drift (migration shim)
"""


def test_env_knob_true_positive(tmp_path):
    assert "env-knob-drift" in rules_hit(lint_snippet(tmp_path, ENV_KNOB_TP))


def test_env_knob_true_negative(tmp_path):
    assert "env-knob-drift" not in rules_hit(lint_snippet(tmp_path, ENV_KNOB_TN))


def test_env_knob_suppressed(tmp_path):
    assert "env-knob-drift" not in rules_hit(
        lint_snippet(tmp_path, ENV_KNOB_SUPPRESSED)
    )


LADDER_TP = """
from page_rank_and_tfidf_using_apache_spark_tpu import obs

def degrade():
    obs.emit("degraded", site="x", ladder="warp_drive", after_attempts=2)
"""

LADDER_TN = """
from page_rank_and_tfidf_using_apache_spark_tpu import obs

def degrade(rung):
    obs.emit("degraded", site="x", ladder="cpu", after_attempts=2)
    obs.emit("degraded", site="x", ladder=rung)  # computed: checked at the
    # declaration side, not here
    obs.emit("retry", site="x", ladder="warp_drive")  # not a degraded event
"""

LADDER_SUPPRESSED = """
from page_rank_and_tfidf_using_apache_spark_tpu import obs

def degrade():
    obs.emit("degraded", site="x", ladder="warp_drive")  # graftlint: disable=ladder-rung-drift (migration shim)
"""


def test_ladder_rung_true_positive(tmp_path):
    assert "ladder-rung-drift" in rules_hit(lint_snippet(tmp_path, LADDER_TP))


def test_ladder_rung_true_negative(tmp_path):
    assert "ladder-rung-drift" not in rules_hit(lint_snippet(tmp_path, LADDER_TN))


def test_ladder_rung_suppressed(tmp_path):
    assert "ladder-rung-drift" not in rules_hit(
        lint_snippet(tmp_path, LADDER_SUPPRESSED)
    )


def test_ladder_rung_declaration_coverage(tmp_path):
    """The declaration side: a DEGRADE_LADDER rung no resilience/ module
    references is drift, flagged at the declaration."""
    cfg_dir = tmp_path / "utils"
    cfg_dir.mkdir()
    cfg = cfg_dir / "config.py"
    cfg.write_text('DEGRADE_LADDER = ("zeta",)\n')
    res_dir = tmp_path / "resilience"
    res_dir.mkdir()
    (res_dir / "impl.py").write_text('LADDER = "other"\n')
    findings = lint_file(cfg, tmp_path)
    assert "ladder-rung-drift" in {f.rule for f in findings}

    (res_dir / "impl.py").write_text('LADDER = "zeta"\n')
    import page_rank_and_tfidf_using_apache_spark_tpu.analysis.rules as rules_mod

    rules_mod._ladder_cache.clear()  # per-root cache from the first pass
    findings = lint_file(cfg, tmp_path)
    assert "ladder-rung-drift" not in {f.rule for f in findings}


def test_env_knob_reads_local_declaration(tmp_path):
    """A scanned tree's own utils/config.py declaration wins over the
    package fallback."""
    cfg_dir = tmp_path / "utils"
    cfg_dir.mkdir()
    (cfg_dir / "config.py").write_text(
        'GRAFT_ENV_KNOBS = frozenset({"GRAFT_CUSTOM_KNOB"})\n'
    )
    ok = 'import os\nV = os.environ.get("GRAFT_CUSTOM_KNOB")\n'
    bad = 'import os\nV = os.environ.get("GRAFT_RETRY_MAX")\n'  # not declared HERE
    (tmp_path / "a.py").write_text(ok)
    (tmp_path / "b.py").write_text(bad)
    findings = run_lint([tmp_path / "a.py", tmp_path / "b.py"], tmp_path)
    knob_hits = {f.path for f in findings if f.rule == "env-knob-drift"}
    assert knob_hits == {"b.py"}


# ----------------------------------------------------- engine machinery


def test_fingerprints_stable_under_line_shift(tmp_path):
    a = lint_snippet(tmp_path, HOST_SYNC_TP)
    b = lint_snippet(tmp_path, "# a leading comment shifts every line\n" + HOST_SYNC_TP)
    assert {f.fingerprint for f in a} == {f.fingerprint for f in b}


def test_ratchet_blocks_new_but_allows_baselined(tmp_path):
    findings = lint_snippet(tmp_path, HOST_SYNC_TP)
    assert findings
    baseline = {
        f.fingerprint: {"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path}
        for f in findings
    }
    assert apply_ratchet(findings, baseline).ok
    assert not apply_ratchet(findings, {}).ok
    stale = apply_ratchet([], baseline).stale
    assert len(stale) == len(findings)


def test_file_level_suppression(tmp_path):
    code = "# graftlint: disable-file=host-sync-in-loop\n" + HOST_SYNC_TP
    assert "host-sync-in-loop" not in rules_hit(lint_snippet(tmp_path, code))


def test_every_rule_has_summary():
    assert set(RULES) == {
        "host-sync-in-loop",
        "tracer-branch",
        "dtype-drift",
        "nonstatic-shape",
        "dce-timed-region",
        "unguarded-host-sync",
        "untraced-guarded-site",
        "unsynced-thread-state",
        "thread-registry-drift",
        "env-knob-drift",
        "ladder-rung-drift",
        "metric-name-drift",
        "sync-put-in-ingest-loop",
    }
    for rule in RULES.values():
        assert rule.summary


# ----------------------------------------------------- the ratchet gate


def test_repo_clean_under_ratchet():
    """The tier-1 surface (package + tools/ + bench.py) must produce no
    findings beyond analysis/baseline.json — the per-PR CI gate."""
    findings = run_lint(default_targets(REPO), REPO)
    baseline = load_baseline(baseline_path(REPO))
    result = apply_ratchet(findings, baseline)
    msg = "\n".join(f.render() for f in result.new)
    assert result.ok, f"new graftlint findings (fix or ratchet them):\n{msg}"


def test_hot_path_inline_suppressions_are_justified():
    """ops/ and parallel/ may suppress inline only with named rules AND a
    parenthesized justification on the same line — no silent opt-outs."""
    import re

    pkg = REPO / "page_rank_and_tfidf_using_apache_spark_tpu"
    justified = re.compile(
        r"graftlint:\s*disable(?:-file)?=[A-Za-z0-9_,\- ]+?\s*\(.+\)"
    )
    for hot in ("ops", "parallel"):
        for f in sorted((pkg / hot).rglob("*.py")):
            for lineno, line in enumerate(f.read_text().splitlines(), 1):
                if "graftlint:" in line and "disable" in line:
                    assert justified.search(line), (
                        f"{f.relative_to(REPO)}:{lineno}: hot-path "
                        "suppression must name its rule(s) and carry a "
                        f"(justification): {line.strip()}"
                    )


def test_write_baseline_preserves_unscanned_entries(tmp_path):
    """A partial --write-baseline must not wipe ratchet entries for files
    outside the scanned set."""
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import (
        write_baseline,
    )

    bl = tmp_path / "baseline.json"
    (tmp_path / "a.py").write_text(HOST_SYNC_TP)
    (tmp_path / "b.py").write_text(TRACER_BRANCH_TP)
    both = run_lint([tmp_path / "a.py", tmp_path / "b.py"], tmp_path)
    write_baseline(bl, both, scanned_paths={"a.py", "b.py"})
    assert {e["path"] for e in load_baseline(bl).values()} == {"a.py", "b.py"}

    only_a = run_lint([tmp_path / "a.py"], tmp_path)
    write_baseline(bl, only_a, scanned_paths={"a.py"})
    kept = load_baseline(bl)
    assert {e["path"] for e in kept.values()} == {"a.py", "b.py"}


def test_baseline_entries_are_justified():
    """Every frozen finding needs a real one-line justification, and none
    may silently live in the hot-path modules."""
    baseline = load_baseline(baseline_path(REPO))
    for entry in baseline.values():
        just = entry.get("justification", "")
        assert just and "UNREVIEWED" not in just, entry
        assert not entry["path"].startswith(
            ("page_rank_and_tfidf_using_apache_spark_tpu/ops/",
             "page_rank_and_tfidf_using_apache_spark_tpu/parallel/")
        ), f"hot-path module may not carry baselined findings: {entry}"


def test_lint_cli_gate():
    """tools/lint.sh (the CI entry point) exits 0 on the current tree."""
    proc = subprocess.run(
        [str(REPO / "tools" / "lint.sh")],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lint_cli_json_output(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text(TRACER_BRANCH_TP)
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "page_rank_and_tfidf_using_apache_spark_tpu.analysis",
            str(f),
            "--json",
            "--no-baseline",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["ok"] is False
    assert any(x["rule"] == "tracer-branch" for x in payload["findings"])
