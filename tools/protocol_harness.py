#!/usr/bin/env python3
"""Wire-protocol conformance harness: replay the DECLARED message space
at a live replica and through the router (graftlint tier 6's derived
dynamic proof, ISSUE 18).

Tier 6's static half (``analysis/protocol.py``) proves the code and the
``WIRE_SCHEMAS`` contract agree lexically.  This harness proves the
contract *behaves*: it enumerates the declared message space with
``enumerate_message_space`` — malformed syntax/shape, each required key
dropped, out-of-contract paths and methods, a duplicate request id, a
stale generation floor — and replays every probe at a real ``_Replica``
served over HTTP by the real ``MetricsExporter`` route table, then
drives the real ``ServingFabric`` router at it.  The assertions are the
fabric's core audit invariants:

- **typed rejection, never a hang** — every probe answers within its
  timeout with a status code the contract declares for that endpoint
  (the dispatcher's 404/500 catch-alls are always admissible); a socket
  timeout is a failure, not a retry.
- **never a second execution** — a duplicate request id replays
  byte-identical cached bytes and the replica's ``executions`` counter
  does not move; the router audit ends with ``double_served == 0``.
- **floor refusal is retryable, then terminal** — with the committed
  floor ratcheted past the replica's generation the replica 503s with
  the floor attached, and the router surfaces a typed
  ``FabricExhausted`` within its bounded retry budget.

Because expected codes come from the contract, a seeded contract
mutation (e.g. deleting the query row's 503) fails the harness — the
observed refusal is no longer in the declared set — mirroring how the
static ``endpoint-contract-drift`` check fails on the code side.
Analogue of ``tools/crash_harness.py`` (tier 5's kill-point replayer);
wired into ci.sh as a bounded smoke under ``GRAFT_PROTO_BUDGET_S``.

Usage::

    python tools/protocol_harness.py [--json] [--timeout-s 5.0]

Exit codes: 0 = every probe conformed, 1 = violations (printed),
2 = could not bring the fixture fleet up.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

# Deterministic fixture environment: CPU tracing, no ambient chaos or
# trace capture leaking into the probe replies.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
for _knob in ("GRAFT_CHAOS", "GRAFT_TRACE_DIR", "PALLAS_AXON_POOL_IPS"):
    os.environ.pop(_knob, None)

import numpy as np  # noqa: E402

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (  # noqa: E402
    protocol,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (  # noqa: E402
    run_tfidf,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.export import (  # noqa: E402
    MetricsExporter,
)
from page_rank_and_tfidf_using_apache_spark_tpu.obs.metrics import (  # noqa: E402
    MetricsHub,
)
from page_rank_and_tfidf_using_apache_spark_tpu.serving import (  # noqa: E402
    fabric,
    segments as sgm,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (  # noqa: E402
    Bm25Config,
    TfidfConfig,
)

_SCFG = TfidfConfig(vocab_bits=10)
_DOCS = [
    "node edge graph rank walk",
    "graph node directed edge weight",
    "rank walk teleport damping node",
    "edge list sparse matrix graph",
]

# Template values for building a VALID request body from declared keys.
# (The harness bodies carry the UNION of every row's droppable keys, so
# every declared key needs a value the strictest handler parses: the
# /cache/fill coercions want numeric lists and an int generation, and
# /peers wants a str→int map — {} keeps the fixture topology peer-free.)
_REQUEST_VALUES = {"terms": ["node"], "ranker": "tfidf",
                   "scores": [1.0], "docs": [0], "generation": 1,
                   "peers": {}, "slots": 64}

# Dispatcher catch-alls: admissible on every endpoint without declaring
# them per row (unrouted path/method -> 404, handler crash -> 500).
_CATCH_ALLS = {404, 500}


def _seal(d: str, docs, base: int = 0) -> int:
    out = run_tfidf(docs, _SCFG)
    ref = sgm.seal_segment(d, out, _SCFG, doc_base=base,
                           ranks=np.ones(out.n_docs, np.float32),
                           bm25=Bm25Config())
    return sgm.commit_append(d, ref, _SCFG.config_hash())


def _http(method: str, url: str, body: "bytes | None",
          timeout_s: float) -> tuple[int, bytes]:
    """One bounded HTTP exchange.  Raises TimeoutError on a hang — the
    harness's cardinal failure."""
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


class _Violations:
    def __init__(self) -> None:
        self.rows: list[dict] = []

    def add(self, probe: dict, detail: str) -> None:
        self.rows.append({
            "endpoint": probe.get("endpoint"),
            "kind": probe.get("kind"),
            "detail": detail,
        })


def _valid_body(row_keys, rid: str) -> dict:
    doc = {}
    for k in row_keys:
        doc[k] = rid if k == "rid" else _REQUEST_VALUES.get(k, "x")
    return doc


def _declared_codes(probes: list, endpoint: "str | None") -> set:
    for p in probes:
        if p.get("kind") == "declared-codes" and p.get("endpoint") == endpoint:
            return set(p.get("codes", ()))
    return set()


def _replica_counters(port: int, timeout_s: float) -> dict:
    code, body = _http("GET", f"http://127.0.0.1:{port}/status", None,
                       timeout_s)
    if code != 200:
        raise RuntimeError(f"/status answered {code}")
    return json.loads(body.decode("utf-8"))


def run_harness(timeout_s: float = 5.0) -> dict:
    probes = protocol.enumerate_message_space(REPO)
    if not probes:
        return {"ok": False, "fatal": "no WIRE_SCHEMAS contract parsed"}

    viol = _Violations()
    rid_seq = [0]

    def fresh_rid() -> str:
        rid_seq[0] += 1
        return f"ph-{os.getpid()}-{rid_seq[0]}"

    request_keys = {"rid", "terms", "ranker"}
    for p in probes:
        if p.get("endpoint") == "query" and p.get("kind") == "declared-codes":
            pass  # declared codes resolved per probe below
    # the query row's declared request keys travel on the probes via
    # drop_key/extra_key; rebuild the full key set from them + defaults
    declared_req = {p["drop_key"] for p in probes if "drop_key" in p}
    if declared_req:
        request_keys = declared_req | {"rid"}

    tmp = tempfile.mkdtemp(prefix="protocol-harness-")
    gen = _seal(tmp, _DOCS)

    rep = fabric._Replica(tmp, replica_id=0, top_k=4, max_batch=None,
                          scoring="coo", poll_s=0.1)
    rep.start()
    exporter = MetricsExporter(MetricsHub(), port=0, routes={
        ("POST", "/query"): rep.handle_query,
        ("GET", "/status"): rep.handle_status,
        ("POST", "/cache/peek"): rep.handle_cache_peek,
        ("POST", "/cache/fill"): rep.handle_cache_fill,
        ("POST", "/peers"): rep.handle_peers,
    }).start()
    port = exporter.port

    stats = {"probes": 0, "replica_checks": 0, "router_checks": 0}
    t_start = time.monotonic()
    try:
        deadline = time.monotonic() + 15.0
        while not rep.ready() and time.monotonic() < deadline:
            time.sleep(0.05)
        if not rep.ready():
            return {"ok": False,
                    "fatal": "fixture replica never became ready"}

        # ---- phase 1: the enumerated probe matrix at the live replica.
        # stale-floor last: the floor only ratchets up, so it poisons
        # every probe after it.
        ordered = (
            [p for p in probes if p["kind"] not in
             ("stale-floor", "declared-codes")]
            + [p for p in probes if p["kind"] == "stale-floor"]
        )
        for probe in ordered:
            kind = probe["kind"]
            endpoint = probe.get("endpoint")
            allowed = _declared_codes(probes, endpoint) | _CATCH_ALLS
            url = f"http://127.0.0.1:{port}{probe['path']}"
            body: "bytes | None" = None
            if kind in ("malformed-syntax", "malformed-shape"):
                body = probe["body"].encode("utf-8")
            elif "drop_key" in probe:
                doc = _valid_body(request_keys, fresh_rid())
                doc.pop(probe["drop_key"], None)
                body = json.dumps(doc).encode("utf-8")
            elif "extra_key" in probe:
                doc = _valid_body(request_keys, fresh_rid())
                doc[probe["extra_key"]] = 1
                body = json.dumps(doc).encode("utf-8")
            elif probe["method"] == "POST":
                body = json.dumps(
                    _valid_body(request_keys, fresh_rid())).encode("utf-8")

            if kind == "duplicate-rid":
                before = _replica_counters(port, timeout_s)
                code1, bytes1 = _http(probe["method"], url, body, timeout_s)
                code2, bytes2 = _http(probe["method"], url, body, timeout_s)
                after = _replica_counters(port, timeout_s)
                stats["replica_checks"] += 1
                if (code1, bytes1) != (code2, bytes2):
                    viol.add(probe, "replayed rid did not return "
                                    "byte-identical response")
                if after["executions"] - before["executions"] > 1:
                    viol.add(probe, "duplicate rid executed twice "
                                    f"(executions {before['executions']} "
                                    f"-> {after['executions']})")
                if after["replays"] - before["replays"] < 1:
                    viol.add(probe, "duplicate rid was not counted as a "
                                    "replay")
                codes_seen = {code1, code2}
            elif kind == "stale-floor":
                fabric.commit_floor(tmp, gen + 1)  # strand the replica
                floor_deadline = time.monotonic() + 10.0
                while rep.ready() and time.monotonic() < floor_deadline:
                    time.sleep(0.05)
                if rep.ready():
                    viol.add(probe, "replica stayed ready past a floor "
                                    "above its generation")
                code, raw = _http(probe["method"], url, body, timeout_s)
                stats["replica_checks"] += 1
                codes_seen = {code}
                try:
                    reply = json.loads(raw.decode("utf-8"))
                except ValueError:
                    reply = {}
                if "floor" not in reply:
                    viol.add(probe, "floor refusal did not attach the "
                                    "committed floor")
            else:
                try:
                    code, _raw = _http(probe["method"], url, body, timeout_s)
                except (TimeoutError, OSError) as exc:
                    viol.add(probe, f"no bounded answer: "
                                    f"{type(exc).__name__}: {exc}")
                    continue
                codes_seen = {code}

            stats["probes"] += 1
            expect = set(probe.get("expect", ()))
            for code in sorted(codes_seen):
                if expect and code not in expect:
                    viol.add(probe, f"answered {code}, probe expects "
                                    f"one of {sorted(expect)}")
                if endpoint is not None and code not in allowed:
                    viol.add(probe, f"answered {code}, which the "
                                    "WIRE_SCHEMAS row does not declare "
                                    "— contract drift caught on the wire")

        # ---- phase 2: the real router at the (now stranded) replica:
        # typed exhaustion within the bounded retry budget, no hang.
        cfg = fabric.FabricConfig(replicas=1, retry_limit=3,
                                  retry_pause_s=0.05,
                                  request_timeout_s=timeout_s)
        fab = fabric.ServingFabric(tmp, cfg)
        fab._ports = {0: port}  # routed without start(): no child processes
        t0 = time.monotonic()
        try:
            fab.query(["node"], timeout=timeout_s)
            viol.add({"endpoint": "query", "kind": "router-stale-floor"},
                     "router served from a replica below the committed "
                     "floor")
        except fabric.FabricExhausted:
            pass  # the typed refusal the contract promises
        except Exception as exc:
            viol.add({"endpoint": "query", "kind": "router-stale-floor"},
                     f"untyped router failure {type(exc).__name__}: {exc}")
        stats["router_checks"] += 1
        elapsed = time.monotonic() - t0
        budget = timeout_s + cfg.retry_limit * (cfg.request_timeout_s
                                                + cfg.retry_pause_s) + 5.0
        if elapsed > budget:
            viol.add({"endpoint": "query", "kind": "router-stale-floor"},
                     f"router took {elapsed:.1f}s — unbounded retry")
        audit = fab.audit()
        if audit["double_served"] != 0:
            viol.add({"endpoint": "query", "kind": "router-audit"},
                     f"double_served == {audit['double_served']}")
    finally:
        exporter.stop()
        rep.stop()

    return {
        "ok": not viol.rows,
        "fingerprint": protocol.wire_fingerprint(REPO),
        "probes": stats["probes"],
        "replica_checks": stats["replica_checks"],
        "router_checks": stats["router_checks"],
        "elapsed_s": round(time.monotonic() - t_start, 2),
        "violations": viol.rows,
    }


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="protocol_harness",
        description="replay the declared wire message space at a live "
                    "replica and router; assert typed rejection, no "
                    "hangs, no double execution",
    )
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--timeout-s", type=float, default=5.0,
                    help="per-exchange HTTP timeout (a hit = a hang = "
                         "failure; default 5.0)")
    args = ap.parse_args(argv)

    report = run_harness(timeout_s=args.timeout_s)
    if "fatal" in report:
        print(f"protocol_harness: {report['fatal']}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(f"protocol_harness: {report['probes']} probe(s), "
              f"{report['replica_checks']} replica check(s), "
              f"{report['router_checks']} router check(s) against "
              f"contract {report['fingerprint']} in "
              f"{report['elapsed_s']}s")
        for v in report["violations"]:
            print(f"  VIOLATION [{v['endpoint']}/{v['kind']}] {v['detail']}")
        if report["ok"]:
            print("protocol_harness: conformant — typed rejection "
                  "everywhere, zero hangs, zero double executions")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
