"""Per-phase wall-time + SLO regression attribution between two bench
rounds.

Takes two BENCH artifacts — either round records (``BENCH_rNN.json``, whose
``extra.breakdown`` the bench parent derives from the tfidf child's trace)
or raw trace files (``*.trace.jsonl``, re-derived here via
tools/trace_report.py) — and answers the question a slower round always
raises: *which phase* paid for it.  This is the comparison layer over the
per-phase breakdowns the obs/ subsystem already records; nothing is
re-measured.

Since ISSUE 11 the diff also regresses the **SLO record** the soak
harness emits (``extra.slo`` on a BENCH round; the ``slo`` event on a raw
trace): a new round whose served p99 grew past ``--threshold`` relative
to the old one, or whose error-budget consumption worsened past the same
threshold (absolute fraction), fails the diff exactly like a phase
regression — production SLOs are part of the committed trajectory, not a
side channel.  Rounds are only compared when BOTH carry an SLO record,
except that a new round *losing* its record while the old one had one is
itself flagged (the bench lost its SLO accounting).

Since ISSUE 13 the same discipline covers the serve bench's **per-batch
served latency** (``extra.served_p99_ms``, falling back to the p99
blocks inside ``extra.served_qps`` for older rounds): a batch size whose
served p99 regressed past ``--threshold`` (over the same jitter floor)
fails the diff, and a round losing its served numbers is flagged.

Since ISSUE 15 it also covers the sharded strategies' **per-step comm
bytes** (``extra.comm_bytes_per_step``: the static exchange footprint
the partition event gauges, keyed per strategy/scale point): a point
whose bytes grew RELATIVELY past ``--threshold`` (over an absolute
floor — pow2 boundary-buffer widths legitimately jump in small steps)
fails the diff.  Rounds BEFORE the gauge existed carry no map, so the
old-round fallback skips cleanly; a new round losing the map while the
old one had it is flagged like the other gates.

Since ISSUE 17 the same discipline covers the serving fleet's numbers
(``extra.fabric_qps`` / ``extra.fabric_recovery_s`` and the cross-process
``extra.fabric_dropped`` / ``extra.fabric_double_served`` audit): fleet
QPS falling past ``--threshold``, respawn recovery growing past it, or
ANY dropped/double-served increase fails the diff; a round losing its
fabric numbers while the old one had them is flagged.

Since ISSUE 16 the new round's **tuned-profile provenance** is checked
on its own (``extra.tuned_profile.backend`` vs ``extra.backend``): a
round whose knobs came from a profile stamped for a different backend
than the one it measured on fails the diff — its numbers were shaped by
the wrong machine's sweep.  Rounds without a profile stamp skip cleanly.

Stdlib-only (importable from the jax-free bench parent, same rule as
trace_report.py).

Usage::

    python tools/trace_diff.py BENCH_r04.json BENCH_r05.json
    python tools/trace_diff.py old/tfidf.123.trace.jsonl new/tfidf.456.trace.jsonl
    python tools/trace_diff.py A B --json [--threshold 0.10]

Exit codes: 0 = no phase or SLO regressed past --threshold, 1 = at least
one did, 2 = artifacts unreadable/incomparable.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys


_trace_report_mod = None


def _trace_report():
    global _trace_report_mod
    if _trace_report_mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "trace_report.py")
        spec = importlib.util.spec_from_file_location(
            "trace_diff_report", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _trace_report_mod = mod
    return _trace_report_mod


def load_breakdown(path: str) -> tuple[dict[str, float], float | None, str]:
    """(phase -> secs, total wall secs or None, source kind) from either a
    BENCH round record or a raw JSONL trace artifact."""
    if path.endswith(".jsonl"):
        rep = _trace_report().report(path)
        if rep.get("empty"):
            raise ValueError(f"{path}: empty trace")
        return dict(rep["breakdown"]), float(rep["wall_secs"]), "trace"
    with open(path) as f:
        record = json.load(f)
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]  # driver-wrapped BENCH_rNN.json round
    extra = record.get("extra", {})
    breakdown = extra.get("breakdown")
    if not breakdown:
        raise ValueError(
            f"{path}: no extra.breakdown (pre-PR-4 round, or the tfidf "
            "child left no trace artifact)"
        )
    return (
        {k: float(v) for k, v in breakdown.items()},
        extra.get("breakdown_wall_secs"),
        "bench",
    )


# Phases of the staged ingest pipeline (ISSUE 10) that overlap BY DESIGN:
# the H2D staging stage runs under chunk compute, so wall time moving from
# ``ingest.compute`` into ``ingest.h2d`` is the optimization landing, not a
# regression.  They are folded into one combined phase before the per-phase
# comparison; the detailed split lives in trace_report's ingest section.
_OVERLAPPED_FOLD = {
    "ingest.h2d": "ingest.h2d+compute",
    "ingest.compute": "ingest.h2d+compute",
}


def _fold_overlapped(bd: dict[str, float]) -> dict[str, float]:
    out: dict[str, float] = {}
    for phase, secs in bd.items():
        key = _OVERLAPPED_FOLD.get(phase, phase)
        out[key] = out.get(key, 0.0) + secs
    return out


def load_slo(path: str) -> dict | None:
    """The SLO record riding an artifact: ``extra.slo`` for a BENCH round
    record, the trace's ``slo`` event for a raw JSONL trace; None when
    the artifact carries none (pre-ISSUE-11 rounds)."""
    if path.endswith(".jsonl"):
        return _trace_report().report(path).get("slo")
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    slo = record.get("extra", {}).get("slo")
    return slo if isinstance(slo, dict) else None


# Minimum absolute p99 delta (ms) an SLO regression must also clear — a
# CPU-backend soak's p99 jitters by single-digit milliseconds run to run.
SLO_MIN_DELTA_MS = 2.0


def load_served_p99(path: str) -> dict | None:
    """Per-batch served p99 map (``{"b8": ms, ...}``) riding a BENCH
    round: ``extra.served_p99_ms`` since ISSUE 13, with a fallback to the
    per-batch blocks inside ``extra.served_qps`` for older rounds (r07+),
    so the gate arms on the first new round.  None when the artifact
    carries no served numbers (raw traces, pre-serving rounds, failed
    serve child)."""
    if path.endswith(".jsonl"):
        return None
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    extra = record.get("extra", {})
    p99 = extra.get("served_p99_ms")
    if isinstance(p99, dict) and p99:
        return {k: float(v) for k, v in p99.items() if v is not None}
    served = extra.get("served_qps")
    if isinstance(served, dict):
        out = {
            b: float(v["p99_ms"]) for b, v in served.items()
            if isinstance(v, dict) and v.get("p99_ms") is not None
        }
        return out or None
    return None


def diff_served(
    old: dict | None, new: dict | None, threshold: float
) -> list[dict]:
    """Served-latency regression rows, mirroring the SLO p99 gate: a
    batch size's p99 regresses RELATIVELY past ``threshold`` (and past
    the jitter floor); a round LOSING its served numbers while the old
    one had them is itself flagged.  Batch sizes present on only one
    side (a changed matrix) are attribution, not regression."""
    if old is None:
        return []
    if new is None:
        return [{
            "key": "served.missing",
            "old": "present",
            "new": None,
            "why": "the old round carried served p50/p99 numbers and the "
                   "new one does not — the round lost its serve bench",
        }]
    rows: list[dict] = []
    for b in sorted(set(old) & set(new)):
        o, n = old[b], new[b]
        if n > o * (1.0 + threshold) and n - o > SLO_MIN_DELTA_MS:
            rows.append({
                "key": f"served.{b}.p99_ms",
                "old": o,
                "new": n,
                "why": f"served p99 at {b} grew {n / max(o, 1e-9):.2f}x",
            })
    return rows


# Minimum absolute growth (bytes/step) a comm regression must also clear:
# the pow2-padded boundary buffers legitimately step in small jumps when
# the cut drifts a little between rounds.
COMM_MIN_DELTA_BYTES = 4096


def load_comm_bytes(path: str) -> dict | None:
    """Per-point comm-bytes map (``{"owned-d8": bytes, ...}``) riding a
    BENCH round's ``extra.comm_bytes_per_step``; None when the artifact
    carries none (raw traces, pre-ISSUE-15 rounds) — the old-round
    fallback that lets the gate arm on the first new round."""
    if path.endswith(".jsonl"):
        return None
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    comm = record.get("extra", {}).get("comm_bytes_per_step")
    if isinstance(comm, dict) and comm:
        return {k: float(v) for k, v in comm.items() if v is not None}
    return None


def diff_comm(
    old: dict | None, new: dict | None, threshold: float
) -> list[dict]:
    """Comm-bytes regression rows, mirroring the served-latency gate: a
    strategy/scale point's per-step bytes grew relatively past
    ``threshold`` AND past the absolute floor; a round losing the map
    while the old one had it is flagged.  Points on one side only (a
    changed scale matrix) are attribution, not regression."""
    if old is None:
        return []
    if new is None:
        return [{
            "key": "comm.missing",
            "old": "present",
            "new": None,
            "why": "the old round carried per-step comm bytes and the new "
                   "one does not — the round lost its comm accounting",
        }]
    rows: list[dict] = []
    for k in sorted(set(old) & set(new)):
        o, n = old[k], new[k]
        if n > o * (1.0 + threshold) and n - o > COMM_MIN_DELTA_BYTES:
            rows.append({
                "key": f"comm.{k}.bytes_per_step",
                "old": o,
                "new": n,
                "why": f"per-step comm bytes at {k} grew "
                       f"{n / max(o, 1e-9):.2f}x",
            })
    return rows


# Minimum absolute growth (seconds) a fleet-recovery regression must also
# clear: respawn latency includes a fresh interpreter + index mmap, which
# jitters by a second or two on a loaded box.
FABRIC_MIN_RECOVERY_DELTA_S = 2.0


def load_fabric(path: str) -> dict | None:
    """Fleet numbers riding a BENCH round (ISSUE 17): the always-present
    ``extra.fabric_qps`` map (per-fleet-size saturated QPS), the measured
    SIGKILL→respawned ``extra.fabric_recovery_s``, and the cross-process
    delivery audit ``extra.fabric_dropped`` / ``extra.fabric_double_served``
    (all null on a failed fabric child).  None when the round predates the
    fabric bench — the old-round fallback that arms the gate on the first
    new round."""
    if path.endswith(".jsonl"):
        return None
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    extra = record.get("extra", {})
    if "fabric_qps" not in extra:
        return None
    return {
        "qps": extra.get("fabric_qps"),
        "recovery_s": extra.get("fabric_recovery_s"),
        "dropped": extra.get("fabric_dropped"),
        "double_served": extra.get("fabric_double_served"),
        # wire-protocol generation (ISSUE 18): the WIRE_SCHEMAS
        # fingerprint the round's fabric numbers were measured against
        # (absent on pre-tier-6 rounds)
        "proto_fp": extra.get("fabric_proto_fingerprint"),
        # host-context annotation (ISSUE 18): cpus < replicas means the
        # n4/n1 scaling ratio measured contention, not scaling
        "nongating": bool(extra.get("fabric_scaling_nongating")),
        # drain-handoff + sharded-cache numbers (ISSUE 20), all absent/
        # None on rounds predating them: retries attributed to the roll
        # window (0 when the socket handoff carries every roll), the
        # fleet's cross-replica cache hit rate under the skewed
        # workload, and the measured A/B speedup from peer caching
        "roll_retries": extra.get("fabric_roll_retries"),
        "peer_hit_rate": extra.get("cache_peer_hit_rate"),
        "cache_speedup": extra.get("cache_speedup_skewed"),
    }


def diff_fabric(
    old: dict | None, new: dict | None, threshold: float
) -> list[dict]:
    """Fleet regression rows, mirroring the SLO gate: per-fleet-size QPS
    falling relatively past ``threshold``, respawn recovery growing past
    ``threshold`` (over an absolute jitter floor), and the cross-process
    dropped / double-served audit as invariants (any increase regresses).
    A round losing its fabric numbers while the old one had them is
    itself flagged; null values (failed fabric child) on either side skip
    the comparison — the bench already recorded the failure.

    Protocol-generation gate (ISSUE 18): rounds measured against
    DIFFERENT ``WIRE_SCHEMAS`` fingerprints are not comparable — the
    wire contract changed between them (new endpoint, different retry
    classes), so the gate arms fresh instead of comparing.  A round
    whose ``fabric_scaling_nongating`` annotation is set measured
    replica contention (cpus < replicas), so scaled-fleet QPS keys skip
    the comparison on either side — only the n1 point stays gated."""
    if old is None:
        return []
    if new is None:
        return [{
            "key": "fabric.missing",
            "old": "present",
            "new": None,
            "why": "the old round carried fleet (fabric) numbers and the "
                   "new one does not — the round lost its fabric bench",
        }]
    o_fp, n_fp = old.get("proto_fp"), new.get("proto_fp")
    if o_fp is not None and n_fp is not None and o_fp != n_fp:
        return []  # wire contract changed between rounds: arm fresh
    rows: list[dict] = []
    o_qps = old.get("qps") if isinstance(old.get("qps"), dict) else {}
    n_qps = new.get("qps") if isinstance(new.get("qps"), dict) else {}
    nongating = bool(old.get("nongating")) or bool(new.get("nongating"))
    for k in sorted(set(o_qps) & set(n_qps)):
        o, n = o_qps[k], n_qps[k]
        if o is None or n is None:
            continue
        if nongating and k != "n1":
            continue  # scaled-fleet point measured contention, not scaling
        if n < o * (1.0 - threshold):
            rows.append({
                "key": f"fabric.qps.{k}",
                "old": o,
                "new": n,
                "why": f"fleet QPS at {k} fell to "
                       f"{n / max(o, 1e-9):.2f}x of the old round",
            })
    o_r, n_r = old.get("recovery_s"), new.get("recovery_s")
    if (o_r is not None and n_r is not None
            and n_r > o_r * (1.0 + threshold)
            and n_r - o_r > FABRIC_MIN_RECOVERY_DELTA_S):
        rows.append({
            "key": "fabric.recovery_s",
            "old": o_r,
            "new": n_r,
            "why": f"replica respawn recovery grew "
                   f"{n_r / max(o_r, 1e-9):.2f}x",
        })
    for key in ("dropped", "double_served"):
        o_v, n_v = old.get(key), new.get(key)
        if isinstance(o_v, int) and isinstance(n_v, int) and n_v > o_v:
            rows.append({
                "key": f"fabric.{key}",
                "old": o_v,
                "new": n_v,
                "why": f"cross-process {key} requests appeared — an "
                       "invariant, not a knob",
            })
    # Roll-attributed retries (ISSUE 20): the drain handoff's whole
    # claim is that a rolling restart needs NO sibling retries — any
    # appearance (or growth, for rounds that already paid some) means
    # the handoff stopped carrying the roll.  Old-round None arms the
    # invariant at 0: the first handoff round must come in clean.
    o_v, n_v = old.get("roll_retries"), new.get("roll_retries")
    if isinstance(n_v, int) and \
            n_v > (o_v if isinstance(o_v, int) else 0):
        rows.append({
            "key": "fabric.roll_retries",
            "old": o_v,
            "new": n_v,
            "why": "retries were attributed to the rolling-restart "
                   "window — the drain handoff stopped carrying the "
                   "roll (an invariant, not a knob)",
        })
    # Cross-replica cache hit rate (ISSUE 20): the sharded cache's
    # skewed-workload peer hit rate may not fall relatively past
    # ``threshold``.  None on either side (failed fabric child, or a
    # round predating the sharded cache) skips the comparison.
    o_h, n_h = old.get("peer_hit_rate"), new.get("peer_hit_rate")
    if (isinstance(o_h, (int, float)) and isinstance(n_h, (int, float))
            and o_h > 0 and n_h < o_h * (1.0 - threshold)):
        rows.append({
            "key": "fabric.cache_peer_hit_rate",
            "old": o_h,
            "new": n_h,
            "why": f"cross-replica cache hit rate fell to "
                   f"{n_h / max(o_h, 1e-9):.2f}x of the old round",
        })
    return rows


# One up->down reversal is inherent to a stepped-load round (scale up
# under load, back down when it recedes) — only MORE reversals than both
# the old round and this allowance indicate control-loop oscillation.
AUTOSCALE_FLAP_ALLOWANCE = 1


def load_autoscale(path: str) -> dict | None:
    """Federation/autoscaling numbers riding a BENCH round (ISSUE 19):
    the always-present ``extra.autoscale`` decision tallies (decisions/
    ups/downs/flaps) and ``extra.fleet_federation`` fleet board (replicas
    scraped, stale count, max staleness, fleet-aggregate p99) — both null
    on a failed fabric child.  None when the round predates the
    federation bench — the old-round fallback that arms the gate on the
    first new round."""
    if path.endswith(".jsonl"):
        return None
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    extra = record.get("extra", {})
    if "autoscale" not in extra:
        return None
    scale = extra.get("autoscale") or {}
    fed = extra.get("fleet_federation") or {}
    return {
        "flaps": scale.get("flaps"),
        "ups": scale.get("ups"),
        "downs": scale.get("downs"),
        "fleet_p99_ms": fed.get("p99_ms"),
        "stale": fed.get("stale"),
    }


def diff_autoscale(
    old: dict | None, new: dict | None, threshold: float
) -> list[dict]:
    """Autoscaling regression rows (ISSUE 19): flap count (direction
    reversals between consecutive scale actions) may not grow past both
    the old round and the one-reversal stepped-load allowance — a
    flapping control loop churns replicas without adding capacity — and
    the fleet-aggregate p99 (the exact federated merge, the number an
    operator alerts on) may not regress relatively past ``threshold``
    over the same absolute jitter floor as the per-replica SLO gate.
    Null values (failed fabric child) on either side skip the
    comparison; a round losing its numbers while the old one had them is
    itself flagged."""
    if old is None:
        return []
    if new is None:
        return [{
            "key": "autoscale.missing",
            "old": "present",
            "new": None,
            "why": "the old round carried federation/autoscale numbers "
                   "and the new one does not — the round lost its "
                   "federation bench",
        }]
    rows: list[dict] = []
    o_f, n_f = old.get("flaps"), new.get("flaps")
    if (isinstance(o_f, int) and isinstance(n_f, int) and n_f > o_f
            and n_f > AUTOSCALE_FLAP_ALLOWANCE):
        rows.append({
            "key": "autoscale.flaps",
            "old": o_f,
            "new": n_f,
            "why": "the autoscaler reversed direction more often — a "
                   "flapping control loop churns replicas without adding "
                   "capacity",
        })
    o_p, n_p = old.get("fleet_p99_ms"), new.get("fleet_p99_ms")
    if (o_p is not None and n_p is not None
            and n_p > o_p * (1.0 + threshold)
            and n_p - o_p > SLO_MIN_DELTA_MS):
        rows.append({
            "key": "autoscale.fleet_p99_ms",
            "old": o_p,
            "new": n_p,
            "why": f"fleet-aggregate served p99 grew "
                   f"{n_p / max(o_p, 1e-9):.2f}x — the federated board "
                   "an operator alerts on regressed",
        })
    return rows


def load_tuned_stamp(path: str) -> dict | None:
    """Tuned-profile provenance riding a BENCH round: the backend the
    committed profile was stamped with (``extra.tuned_profile.backend``,
    since ISSUE 16) next to the backend the round actually measured on
    (``extra.backend``).  None when the round predates autotuning, ran
    without a profile, or the snapshot recorded a read error — absence is
    attribution, only a present-and-wrong stamp is a finding."""
    if path.endswith(".jsonl"):
        return None
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    extra = record.get("extra", {})
    prof = extra.get("tuned_profile")
    if not isinstance(prof, dict) or prof.get("backend") is None:
        return None
    return {
        "profile_backend": prof.get("backend"),
        "measured_backend": extra.get("backend"),
        "path": prof.get("path"),
    }


def check_tuned_backend(stamp: dict | None) -> list[dict]:
    """Provenance gate on the NEW round alone (no old-round comparison):
    a round steered by a tuned profile stamped for a DIFFERENT backend
    than the one it measured on is reporting numbers shaped by the wrong
    machine's sweep — the runtime loader refuses that combination
    (``ProvenanceError``), so a mismatched stamp in a finished record
    means the run resolved its knobs before the backend fell back (e.g.
    a TPU-tuned profile applied after the CPU fallback kicked in)."""
    if stamp is None:
        return []
    prof_b = stamp["profile_backend"]
    meas_b = stamp["measured_backend"]
    if meas_b in (None, "unknown") or prof_b == meas_b:
        return []
    return [{
        "key": "tuned_profile.backend_mismatch",
        "old": prof_b,
        "new": meas_b,
        "why": (f"round measured on {meas_b!r} but its knobs came from a "
                f"profile tuned on {prof_b!r} "
                f"({stamp['path'] or 'unknown path'}) — re-run the sweep "
                "on the backend that serves"),
    }]


def diff_slo(
    old: dict | None, new: dict | None, threshold: float
) -> list[dict]:
    """SLO regression rows (empty = fine).  p99 regresses RELATIVELY
    (new > old * (1 + threshold), past a small absolute floor); budget
    consumption regresses ABSOLUTELY (consumed_frac grew by more than
    ``threshold`` of the budget).  A vanished record regresses; a newly
    appearing one never does."""
    if old is None:
        return []
    if new is None:
        return [{
            "key": "slo.missing",
            "old": "present",
            "new": None,
            "why": "the old round carried an SLO record and the new one "
                   "does not — the round lost its SLO accounting",
        }]
    rows: list[dict] = []
    o_p99, n_p99 = old.get("served_p99_ms"), new.get("served_p99_ms")
    if o_p99 is not None and n_p99 is not None:
        if n_p99 > o_p99 * (1.0 + threshold) and n_p99 - o_p99 > SLO_MIN_DELTA_MS:
            rows.append({
                "key": "slo.served_p99_ms",
                "old": o_p99,
                "new": n_p99,
                "why": f"served p99 grew {n_p99 / max(o_p99, 1e-9):.2f}x",
            })
    for name in ("availability", "latency"):
        o_b = (old.get("error_budget") or {}).get(name) or {}
        n_b = (new.get("error_budget") or {}).get(name) or {}
        o_c, n_c = o_b.get("consumed_frac"), n_b.get("consumed_frac")
        if o_c is None or n_c is None:
            continue
        if n_c - o_c > threshold:
            rows.append({
                "key": f"slo.budget.{name}",
                "old": o_c,
                "new": n_c,
                "why": (f"{name} error-budget consumption grew "
                        f"{n_c - o_c:+.3f} (absolute)"),
            })
    for key in ("dropped", "double_served"):
        o_v, n_v = old.get(key), new.get(key)
        if isinstance(o_v, int) and isinstance(n_v, int) and n_v > o_v:
            rows.append({
                "key": f"slo.{key}",
                "old": o_v,
                "new": n_v,
                "why": f"{key} requests appeared — an invariant, not a knob",
            })
    return rows


def diff_breakdowns(
    old: dict[str, float], new: dict[str, float]
) -> list[dict]:
    """Per-phase rows sorted by absolute regression (worst first).  Phases
    present on only one side diff against 0 — a phase appearing or
    disappearing IS an attribution, not an error.  Overlapped ingest
    stages are folded first (``_OVERLAPPED_FOLD``)."""
    old = _fold_overlapped(old)
    new = _fold_overlapped(new)
    rows = []
    for phase in sorted(set(old) | set(new)):
        a, b = old.get(phase, 0.0), new.get(phase, 0.0)
        delta = b - a
        rows.append({
            "phase": phase,
            "old_secs": round(a, 3),
            "new_secs": round(b, 3),
            "delta_secs": round(delta, 3),
            # relative to the OLD total phase time; None for new phases
            "delta_frac": round(delta / a, 4) if a > 0 else None,
        })
    rows.sort(key=lambda r: abs(r["delta_secs"]), reverse=True)
    return rows


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="trace_diff", description=__doc__)
    ap.add_argument("old", help="baseline BENCH_*.json or *.trace.jsonl")
    ap.add_argument("new", help="candidate BENCH_*.json or *.trace.jsonl")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="per-phase relative regression that fails the "
                         "diff (default 0.10 = +10%% on that phase)")
    ap.add_argument("--min-secs", type=float, default=0.05,
                    help="ignore phases below this absolute delta "
                         "(default 0.05s: jitter, not regressions)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    try:
        old_bd, old_wall, old_kind = load_breakdown(args.old)
        new_bd, new_wall, new_kind = load_breakdown(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"trace_diff: {exc}", file=sys.stderr)
        return 2

    rows = diff_breakdowns(old_bd, new_bd)
    regressions = [
        r for r in rows
        if r["delta_secs"] > args.min_secs
        and (r["delta_frac"] is None or r["delta_frac"] > args.threshold)
    ]
    # No try/except here: load_slo already returns None for an artifact
    # without a record, and a trace unreadable at this point would have
    # failed load_breakdown above — a surviving error is a real bug that
    # must not silently pass the SLO gate.
    slo_rows = diff_slo(load_slo(args.old), load_slo(args.new),
                        args.threshold)
    served_rows = diff_served(load_served_p99(args.old),
                              load_served_p99(args.new), args.threshold)
    comm_rows = diff_comm(load_comm_bytes(args.old),
                          load_comm_bytes(args.new), args.threshold)
    fabric_rows = diff_fabric(load_fabric(args.old),
                              load_fabric(args.new), args.threshold)
    autoscale_rows = diff_autoscale(load_autoscale(args.old),
                                    load_autoscale(args.new), args.threshold)
    tuned_rows = check_tuned_backend(load_tuned_stamp(args.new))
    all_regressions = (
        [r["phase"] for r in regressions]
        + [r["key"] for r in slo_rows]
        + [r["key"] for r in served_rows]
        + [r["key"] for r in comm_rows]
        + [r["key"] for r in fabric_rows]
        + [r["key"] for r in autoscale_rows]
        + [r["key"] for r in tuned_rows]
    )
    result = {
        "old": {"path": args.old, "kind": old_kind, "wall_secs": old_wall},
        "new": {"path": args.new, "kind": new_kind, "wall_secs": new_wall},
        "phases": rows,
        "slo": slo_rows,
        "served": served_rows,
        "comm": comm_rows,
        "fabric": fabric_rows,
        "autoscale": autoscale_rows,
        "tuned_profile": tuned_rows,
        "regressions": all_regressions,
        "worst_regression": all_regressions[0] if all_regressions else None,
    }

    if args.json:
        print(json.dumps(result, indent=2))
    else:
        wall = ""
        if old_wall is not None and new_wall is not None:
            wall = f"  (wall {old_wall:.3f}s -> {new_wall:.3f}s)"
        print(f"trace_diff: {args.old} -> {args.new}{wall}")
        print(f"{'phase':28s} {'old':>9s} {'new':>9s} {'delta':>9s}  rel")
        for r in rows:
            rel = ("   new" if r["old_secs"] == 0
                   else "  gone" if r["new_secs"] == 0
                   else f"{r['delta_frac']:+.1%}")
            mark = " <-- REGRESSED" if r["phase"] in result["regressions"] else ""
            print(f"{r['phase']:28s} {r['old_secs']:9.3f} {r['new_secs']:9.3f} "
                  f"{r['delta_secs']:+9.3f}  {rel}{mark}")
        for r in (slo_rows + served_rows + comm_rows + fabric_rows
                  + autoscale_rows + tuned_rows):
            print(f"{r['key']:28s} {r['old']!s:>9s} {r['new']!s:>9s}  "
                  f"{r['why']} <-- REGRESSED")
        if all_regressions:
            print(f"trace_diff: {len(all_regressions)} regression(s) past "
                  f"+{args.threshold:.0%}; worst: {result['worst_regression']}")
        else:
            print("trace_diff: no phase regressed past the threshold "
                  "(SLO clean)")
    return 1 if all_regressions else 0


if __name__ == "__main__":
    sys.exit(main())
