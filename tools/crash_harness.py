#!/usr/bin/env python3
"""Crash-consistency harness: SIGKILL a committing process at every write
boundary and prove reload always serves a consistent generation (ISSUE 14).

This is the *dynamic* half of graftlint tier 5: the static analyzer
(``analysis/persistence.py``) enumerates the write boundaries of each
commit sequence (``--crash-points`` on the lint CLI — renames and
deletions, the reader-visible filesystem mutations); this harness replays
the real segment commit protocols with a SIGKILL delivered at each such
boundary and asserts the crash-window contract:

- the segmented index **reloads** after every kill (no torn manifest, no
  dangling pointer);
- the reloaded set serves **byte-identically** to either the pre-kill
  generation or the committed post-kill generation — never a mix, never
  a torn set (checked as a content hash over everything serving reads:
  per-segment postings, re-weighted tables, doc ranges, global DF);
- a post-recovery ``serving.segments.gc_orphans`` pass deletes every
  orphan the kill left behind (tmp files, half-staged dirs, sealed-but-
  unnamed segments, unflipped manifests) and a second pass finds zero.

Scenarios replay the three commit protocols over synthetic segments:

- ``append``   — seal a delta segment + ``commit_append`` (the streaming
                 ingest commit path)
- ``replace``  — ``commit_replace`` of a pre-sealed merged segment,
                 including the generation-deferred GC deletes
- ``merge``    — a full ``SegmentMerger.merge_once`` tick (merge + seal +
                 commit_replace)
- ``floor``    — the serving fabric's generation-floor commit
                 (``serving.fabric.commit_floor``: the rolling-restart
                 barrier no replica may serve below).  A single-rename
                 protocol BY DESIGN — one staged tmp + ``durable_replace``
                 — so its probe is allowed exactly one boundary: the
                 harness proves a kill at that boundary leaves the OLD
                 floor serving (a restarted replica keeps refusing
                 pre-floor artifacts), never a torn floor file.

The kill mechanism patches ``os.replace`` / ``os.unlink`` /
``shutil.rmtree`` in the child to deliver ``SIGKILL`` *before* the N-th
mutation executes, so every inter-syscall crash window is visited; a
probe run first counts the boundaries, which must match what the static
enumeration predicts for the protocol functions involved
(tests/test_persistence_lint.py pins that correspondence).

Usage::

    python tools/crash_harness.py                       # all scenarios
    python tools/crash_harness.py --scenarios append --max-kills 3
    python tools/crash_harness.py --json

Exit 0: every kill point survived.  Exit 1: a torn state, a reload
failure, or a leftover orphan.  The parent is stdlib-only; workers import
the package (CPU backend forced).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

_SCENARIOS = ("append", "replace", "merge", "floor")

# Write-boundary floor per scenario probe: every manifest commit protocol
# spans multiple reader-visible mutations, but the generation-floor
# commit is one atomic rename by design — that atomicity is the property
# under test, not a shrunken protocol.
_MIN_BOUNDARIES = {"floor": 1}


# ===========================================================================
# worker side (runs in a child process; imports the package)
# ===========================================================================


def _worker_env_guard() -> None:
    # determinism: no chaos plan, no tracing, CPU backend; the script
    # lives in tools/ so the repo root must join sys.path for the package
    os.environ["JAX_PLATFORMS"] = "cpu"
    for k in ("GRAFT_CHAOS", "GRAFT_TRACE_DIR", "PALLAS_AXON_POOL_IPS"):
        os.environ.pop(k, None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)


def _mk_output(n_docs: int, vocab_bits: int, seed: int, terms_per_doc: int = 3):
    """A tiny synthetic TfidfOutput (unique terms per doc, raw counts +
    doc lengths) — enough for seal/commit/merge/load without dispatching
    any jax program."""
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        TfidfOutput,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.segments import (
        _host_idf,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import IdfMode
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    rng = np.random.default_rng(seed)
    vocab = 1 << vocab_bits
    doc = np.repeat(np.arange(n_docs, dtype=np.int32), terms_per_doc)
    term = np.concatenate([
        np.sort(rng.permutation(vocab)[:terms_per_doc].astype(np.int32))
        for _ in range(n_docs)
    ])
    order = np.lexsort((doc, term))
    doc, term = doc[order], term[order]
    count = rng.integers(1, 5, size=doc.shape[0]).astype(np.float32)
    doc_lengths = np.zeros(n_docs, np.int32)
    np.add.at(doc_lengths, doc, count.astype(np.int32))
    df = np.bincount(term, minlength=vocab).astype(np.float32)
    idf = _host_idf(df, n_docs, IdfMode.SMOOTH, np.dtype(np.float32))
    return TfidfOutput(
        n_docs=n_docs, vocab_bits=vocab_bits, doc=doc, term=term,
        weight=count.copy(), df=df, idf=idf, metrics=MetricsRecorder(),
        count=count, doc_lengths=doc_lengths,
    )


def _cfg():
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        TfidfConfig,
    )

    return TfidfConfig(vocab_bits=6)


def _state_path(base: str) -> str:
    return os.path.join(base, "state.json")


def _idx(base: str) -> str:
    return os.path.join(base, "idx")


def worker_setup(base: str, scenario: str) -> int:
    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        segments as sgm,
    )

    cfg = _cfg()
    d = _idx(base)
    state: dict = {"scenario": scenario, "config_hash": cfg.config_hash()}
    refs = []
    doc_base = 0
    n_segs = 1 if scenario == "append" else 3
    for i in range(n_segs):
        out = _mk_output(4, cfg.vocab_bits, seed=100 + i)
        ref = sgm.seal_segment(d, out, cfg, doc_base=doc_base, bm25=None)
        sgm.commit_append(d, ref, cfg.config_hash())
        refs.append(ref)
        doc_base += out.n_docs
    state["doc_base"] = doc_base
    if scenario == "floor":
        # a replica restarted mid-rolling-swap reads THIS file to decide
        # what it may serve; the op advances it to the next generation
        from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
            fabric as fab,
        )

        fab.commit_floor(d, 1)
    if scenario in ("replace", "merge"):
        # one COMMITTED merge so the op-window commit_replace carries
        # generation-deferred deletes (it GCs what THIS commit replaced)
        ab = sgm.merge_segments(d, (refs[0], refs[1]), cfg)
        sgm.commit_replace(d, (refs[0].name, refs[1].name), ab)
        if scenario == "replace":
            # pre-seal the next merged segment so the op is ONLY the
            # commit_replace protocol
            abc = sgm.merge_segments(d, (ab, refs[2]), cfg)
            state["merged_ref"] = abc.to_json()
            state["old_names"] = [ab.name, refs[2].name]
    with open(_state_path(base), "w") as f:
        json.dump(state, f)
    print(json.dumps({"setup": scenario, "segments": n_segs}))
    return 0


def _arm_kill(kill_at: int) -> dict:
    """Patch the reader-visible mutation syscalls to SIGKILL this process
    right BEFORE the ``kill_at``-th one executes (-1 = never: count only)."""
    counter = {"n": 0}

    def wrap(orig):
        def inner(*args, **kwargs):
            if counter["n"] == kill_at:
                os.kill(os.getpid(), signal.SIGKILL)
            counter["n"] += 1
            return orig(*args, **kwargs)

        return inner

    os.replace = wrap(os.replace)
    os.unlink = wrap(os.unlink)
    shutil.rmtree = wrap(shutil.rmtree)
    return counter


def worker_op(base: str, scenario: str, kill_at: int) -> int:
    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        segments as sgm,
    )

    cfg = _cfg()
    d = _idx(base)
    with open(_state_path(base)) as f:
        state = json.load(f)
    counter = _arm_kill(kill_at)
    if scenario == "append":
        out = _mk_output(4, cfg.vocab_bits, seed=777)
        ref = sgm.seal_segment(d, out, cfg, doc_base=state["doc_base"],
                               bm25=None)
        sgm.commit_append(d, ref, state["config_hash"])
    elif scenario == "replace":
        ref = sgm.SegmentRef.from_json(state["merged_ref"])
        sgm.commit_replace(d, tuple(state["old_names"]), ref)
    elif scenario == "merge":
        merger = sgm.SegmentMerger(d, cfg, max_segments=1)
        if not merger.merge_once():
            print("merge_once found nothing to merge", file=sys.stderr)
            return 1
    elif scenario == "floor":
        from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
            fabric as fab,
        )

        fab.commit_floor(d, 2)
    else:
        print(f"unknown scenario {scenario}", file=sys.stderr)
        return 1
    print(json.dumps({"boundaries": counter["n"]}))
    return 0


def _scan_orphans(d: str) -> list[str]:
    """Independent re-scan (same rules as gc_orphans) — what a clean
    recovery must leave behind: nothing."""
    import re

    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        segments as sgm,
    )

    manifest_re = re.compile(r"^manifest_(\d{6})\.json$")
    cur = sgm.latest_manifest(d)
    keep = set()
    cur_version = 0
    if cur is not None:
        cur_version = cur.version
        keep = {s.name for s in cur.segments}
        keep |= set(sgm._replaced_by(d, cur.version))
    bad = []
    for n in sorted(os.listdir(d)):
        if n.endswith(".tmp"):
            bad.append(n)
        elif (m := manifest_re.match(n)) and int(m.group(1)) > cur_version:
            bad.append(n)
    seg_root = os.path.join(d, sgm.SEGMENTS_SUBDIR)
    if os.path.isdir(seg_root):
        for n in sorted(os.listdir(seg_root)):
            p = os.path.join(seg_root, n)
            if n.endswith(".tmp") or n.startswith("."):
                bad.append(f"segments/{n}")
            elif os.path.isdir(p) and n not in keep:
                bad.append(f"segments/{n}")
    return bad


def worker_verify(base: str) -> int:
    """Reload, hash everything serving reads, GC orphans, assert a second
    sweep finds none.  Prints {"hash", "version", "gc_deleted"}."""
    import hashlib

    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        segments as sgm,
    )

    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        fabric as fab,
    )

    d = _idx(base)
    segset = sgm.load_segment_set(d)  # must ALWAYS load: torn set = crash
    h = hashlib.sha256()
    # the generation floor is part of what serving reads (a replica below
    # it refuses queries): a kill around the floor commit must leave the
    # old floor or the new floor in the hash, never anything else —
    # read_floor maps a missing/unparseable file to 0, so torn JSON would
    # show up as a third hash and fail the pre-or-post check
    h.update(str(fab.read_floor(d)).encode())
    h.update(str(segset.n_docs).encode())
    h.update(np.ascontiguousarray(segset.df_global).tobytes())
    for seg in segset.segments:
        h.update(f"{seg.ref.doc_base}:{seg.ref.n_docs}".encode())
        h.update(np.ascontiguousarray(seg.index.doc).tobytes())
        h.update(np.ascontiguousarray(seg.index.term).tobytes())
        for ranker in sorted(seg.weights):
            h.update(ranker.encode())
            h.update(np.ascontiguousarray(seg.weights[ranker]).tobytes())
        if seg.term_offsets is not None:
            h.update(np.ascontiguousarray(seg.term_offsets).tobytes())
    deleted: list = []
    if os.environ.get("CRASH_HARNESS_VERIFY_GC", "1") != "0":
        # post-kill recovery: GC the crash debris, then prove a second
        # sweep (and an independent re-scan) find nothing left
        # min_age_s=0: post-kill there is no writer left — every orphan
        # is crash debris regardless of how fresh its mtime is
        deleted = sgm.gc_orphans(d, min_age_s=0)
        second = sgm.gc_orphans(d, min_age_s=0)
        leftovers = _scan_orphans(d)
        if second or leftovers:
            print(f"orphans survived recovery GC: {second or leftovers}",
                  file=sys.stderr)
            return 1
        reloaded = sgm.load_segment_set(d)  # GC must not break the live set
        if reloaded.version != segset.version:
            print("gc_orphans changed the committed generation",
                  file=sys.stderr)
            return 1
    print(json.dumps({"hash": h.hexdigest(), "version": segset.version,
                      "gc_deleted": len(deleted)}))
    return 0


# ===========================================================================
# parent side (stdlib-only orchestration)
# ===========================================================================


def _run_worker(mode: str, base: str, scenario: str | None = None,
                kill_at: int | None = None,
                expect_kill: bool = False, gc: bool = True) -> dict | None:
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", mode,
           "--dir", base]
    if scenario is not None:
        cmd += ["--scenario", scenario]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # scrub our own control knobs too: an exported CRASH_HARNESS_VERIFY_GC=0
    # leaking in from the outer shell would silently disable every
    # post-kill orphan-GC assertion while the gates still print green
    for k in ("GRAFT_CHAOS", "GRAFT_TRACE_DIR", "PALLAS_AXON_POOL_IPS",
              "CRASH_HARNESS_KILL_AT", "CRASH_HARNESS_VERIFY_GC"):
        env.pop(k, None)
    if kill_at is not None:
        env["CRASH_HARNESS_KILL_AT"] = str(kill_at)
    if not gc:
        env["CRASH_HARNESS_VERIFY_GC"] = "0"
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=300)
    if expect_kill:
        if proc.returncode != -signal.SIGKILL:
            raise RuntimeError(
                f"worker {mode}/{scenario} kill_at={kill_at} expected "
                f"SIGKILL, exited {proc.returncode}:\n{proc.stderr[-2000:]}"
            )
        return None
    if proc.returncode != 0:
        raise RuntimeError(
            f"worker {mode}/{scenario} failed rc={proc.returncode}:\n"
            f"{proc.stderr[-2000:]}"
        )
    last = proc.stdout.strip().splitlines()[-1]
    return json.loads(last)


def _copy_state(src: str, dst: str) -> None:
    if os.path.exists(dst):
        shutil.rmtree(dst)
    shutil.copytree(src, dst)


def run_scenario(base_dir: str, scenario: str,
                 max_kills: int | None) -> dict:
    pre = os.path.join(base_dir, scenario, "pre")
    os.makedirs(pre, exist_ok=True)
    _run_worker("setup", pre, scenario)
    # hash-only verifies: the pre state may legitimately hold a sealed-
    # but-uncommitted segment the op is about to commit — recovery GC
    # (which would sweep it) belongs to the post-kill verifies only
    pre_hash = _run_worker("verify", pre, gc=False)["hash"]

    probe = os.path.join(base_dir, scenario, "probe")
    _copy_state(pre, probe)
    boundaries = _run_worker("op", probe, scenario, kill_at=-1)["boundaries"]
    post_hash = _run_worker("verify", probe, gc=False)["hash"]
    if pre_hash == post_hash:
        raise RuntimeError(f"{scenario}: op changed nothing — bad scenario")
    if boundaries < _MIN_BOUNDARIES.get(scenario, 2):
        raise RuntimeError(
            f"{scenario}: only {boundaries} boundaries — protocol shrank?")

    ks = list(range(boundaries))
    if max_kills is not None and max_kills < boundaries:
        # spread the budgeted kills across the window, endpoints included
        ks = sorted({
            round(i * (boundaries - 1) / max(max_kills - 1, 1))
            for i in range(max_kills)
        })
    kills = []
    outcomes = {"pre": 0, "post": 0}
    for k in ks:
        work = os.path.join(base_dir, scenario, f"kill{k:02d}")
        _copy_state(pre, work)
        _run_worker("op", work, scenario, kill_at=k, expect_kill=True)
        got = _run_worker("verify", work)
        if got["hash"] == pre_hash:
            outcome = "pre"
        elif got["hash"] == post_hash:
            outcome = "post"
        else:
            raise RuntimeError(
                f"{scenario}: kill at boundary {k} left a TORN state "
                f"(hash {got['hash'][:12]} is neither pre nor post)")
        outcomes[outcome] += 1
        kills.append({"k": k, "outcome": outcome,
                      "gc_deleted": got["gc_deleted"]})
        shutil.rmtree(work, ignore_errors=True)
    if outcomes["pre"] == 0:
        raise RuntimeError(
            f"{scenario}: no kill point preserved the pre generation — "
            "the kill windows are not covering the commit")
    return {"boundaries": boundaries, "kills": kills,
            "served_pre": outcomes["pre"], "served_post": outcomes["post"]}


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenarios", default=",".join(_SCENARIOS),
                    help=f"comma list of {_SCENARIOS} (default: all)")
    ap.add_argument("--max-kills", type=int, default=None,
                    help="bound kill points per scenario (spread across "
                         "the window); default: every boundary")
    ap.add_argument("--dir", default=None,
                    help="work dir (default: a fresh tempdir, removed on "
                         "success)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir")
    # internal worker plumbing
    ap.add_argument("--worker", choices=("setup", "op", "verify"),
                    default=None, help=argparse.SUPPRESS)
    ap.add_argument("--scenario", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.worker is not None:
        _worker_env_guard()
        base = args.dir
        if args.worker == "setup":
            return worker_setup(base, args.scenario)
        if args.worker == "op":
            kill_at = int(os.environ.get("CRASH_HARNESS_KILL_AT", "-1"))
            return worker_op(base, args.scenario, kill_at)
        return worker_verify(base)

    scenarios = [s.strip() for s in args.scenarios.split(",") if s.strip()]
    for s in scenarios:
        if s not in _SCENARIOS:
            print(f"unknown scenario {s!r} (choose from {_SCENARIOS})",
                  file=sys.stderr)
            return 2
    base_dir = args.dir or tempfile.mkdtemp(prefix="crash_harness_")
    os.makedirs(base_dir, exist_ok=True)
    t0 = time.time()
    report: dict = {}
    try:
        for s in scenarios:
            report[s] = run_scenario(base_dir, s, args.max_kills)
    except RuntimeError as exc:
        print(f"crash_harness: FAIL: {exc}", file=sys.stderr)
        print(f"work dir kept for inspection: {base_dir}", file=sys.stderr)
        return 1
    report["wall_secs"] = round(time.time() - t0, 2)
    if not args.keep and args.dir is None:
        shutil.rmtree(base_dir, ignore_errors=True)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for s in scenarios:
            r = report[s]
            print(f"crash_harness: {s}: {len(r['kills'])} kill(s) over "
                  f"{r['boundaries']} boundaries — "
                  f"{r['served_pre']} served pre / {r['served_post']} post, "
                  "0 torn, 0 orphans after recovery GC")
        print(f"crash_harness: OK ({report['wall_secs']}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
