#!/usr/bin/env bash
# graftlint CI gate: fail on any finding not frozen in analysis/baseline.json.
#
# Runs ALL analysis tiers over the tier-1 surface (the package, tools/,
# bench.py): the lexical AST rules (tier 1), the semantic tier that traces
# every registered jit entry point on the CPU backend (tier 2: recompile /
# promotion / transfer-census / sharding gates), the static cost model
# (tier 3: FLOP/byte intensity floors, pad_frac budgets over the partition
# plans, and the buffer-donation verifier — intensity gates are advisory
# while xla_cost_tpu.json is not TPU-measured), the interprocedural
# concurrency & buffer-lifetime analyzer (tier 4: lock-order cycles,
# blocking-under-lock, use-after-donate, chaos-coverage drift,
# thread/lock registry drift — stdlib-only like tier 1), and the
# persistence & crash-consistency analyzer (tier 5: atomic-write drift,
# pointer-flip ordering, generation-deferred GC, ARTIFACT_SCHEMAS
# writer/reader drift, commit-lock drift — stdlib-only; --crash-points
# prints the derived SIGKILL surface tools/crash_harness.py replays),
# and the distributed wire-protocol analyzer (tier 6: endpoint /
# status-code / key drift against WIRE_SCHEMAS, status-class drift
# against the router's retry logic, retry-unsafe effects ahead of the
# request-id dedup guard, floor monotonicity — stdlib-only;
# --wire-probes prints the derived message space
# tools/protocol_harness.py replays).
# Exit 0 = clean under the ratchet; exit 1 = new findings — fix them,
# suppress with a justified "# graftlint: disable=<rule>" comment
# (lexical/concurrency/persistence/protocol) or a registry-level
# suppress entry (semantic/cost), or (outside ops//parallel/) baseline
# them with a justification.  Pass --tier 1|2|3|4|5|6 to run a single
# tier, --changed-only for the fast pre-commit path
# (tools/precommit.sh), --cost-report for the tier-3 per-entry cost
# table, --lock-graph for the tier-4 lock graph as DOT.
#
# PALLAS_AXON_POOL_IPS is stripped and the CPU backend forced so the gate
# can never hang on a wedged TPU tunnel (NOTES.md round-2 rule).
set -euo pipefail
cd "$(dirname "$0")/.."
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m page_rank_and_tfidf_using_apache_spark_tpu.analysis "$@"
