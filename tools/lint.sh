#!/usr/bin/env bash
# graftlint CI gate: fail on any finding not frozen in analysis/baseline.json.
#
# Runs BOTH analysis tiers over the tier-1 surface (the package, tools/,
# bench.py): the lexical AST rules and the semantic tier that traces every
# registered jit entry point on the CPU backend (recompile / promotion /
# transfer-census / sharding gates).  Exit 0 = clean under the ratchet;
# exit 1 = new findings — fix them, suppress with a justified
# "# graftlint: disable=<rule>" comment (lexical) or a registry-level
# suppress entry (semantic), or (outside ops//parallel/) baseline them
# with a justification.  Pass --tier 1|2 to run a single tier,
# --changed-only for the fast pre-commit path (tools/precommit.sh).
#
# PALLAS_AXON_POOL_IPS is stripped and the CPU backend forced so the gate
# can never hang on a wedged TPU tunnel (NOTES.md round-2 rule).
set -euo pipefail
cd "$(dirname "$0")/.."
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m page_rank_and_tfidf_using_apache_spark_tpu.analysis "$@"
