"""Wikipedia-scale streaming-ingest rehearsal (BASELINE.json:11, VERDICT r1
item 9): push >=1M small synthetic docs through the streaming TF-IDF path
with checkpoints enabled, and record wall time, tokens/sec, peak host RSS,
and the serial-vs-pipelined speedup.  Emits ONE JSON object; --out writes it
to a file (e.g. rehearsal_metrics.json at the repo root).

The corpus is generated lazily chunk by chunk (never materialized — the
whole point of streaming ingest), Zipf-distributed over a 50K-word
vocabulary with bigrams enabled to mirror the Wikipedia config's
"bigram vocab".

Usage: python tools/streaming_rehearsal.py [--docs 1000000] [--out FILE]
       (run with: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu when the
       TPU tunnel is down — see .claude/skills/verify/SKILL.md)
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VOCAB_WORDS = 50_000


def synth_chunks(n_docs: int, docs_per_chunk: int, tokens_per_doc: int, seed: int):
    """Lazy synthetic corpus: Zipf unigrams over a 50K-word pool."""
    rng = np.random.default_rng(seed)
    words = np.char.add("w", np.arange(VOCAB_WORDS).astype("U6"))
    emitted = 0
    while emitted < n_docs:
        m = min(docs_per_chunk, n_docs - emitted)
        lens = np.maximum(rng.poisson(tokens_per_doc, m), 3).astype(np.int64)
        ids = rng.zipf(1.4, int(lens.sum())) % VOCAB_WORDS
        toks = words[ids]
        docs, pos = [], 0
        for ln in lens:
            docs.append(" ".join(toks[pos:pos + ln]))
            pos += ln
        yield docs
        emitted += m


def peak_rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def run_once(cfg, n_docs: int, docs_per_chunk: int, tokens_per_doc: int,
             seed: int):
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf_streaming,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    metrics = MetricsRecorder()
    t0 = time.perf_counter()
    out = run_tfidf_streaming(
        synth_chunks(n_docs, docs_per_chunk, tokens_per_doc, seed),
        cfg, metrics=metrics,
    )
    secs = time.perf_counter() - t0
    chunk_recs = [r for r in metrics.records if r.get("event") == "chunk"]
    tokens = sum(r["tokens"] for r in chunk_recs)
    fin = next((r for r in metrics.records if r.get("event") == "finalize"), None)
    timing = {
        "wall_secs": secs,
        # Ingest-only time: the finalize pass is identical at every
        # prefetch depth, so including it in serial-vs-pipelined ratios
        # dilutes the measured overlap toward 1.0 (the round-5 "1.004x"
        # accounting bug) — pipeline comparisons must use this figure.
        "ingest_secs": secs - (float(fin["secs"]) if fin else 0.0),
        "finalize_secs": float(fin["secs"]) if fin else 0.0,
        # Per-chunk drain (device->host sync) and launch time: the
        # RTT-bound component, reported so the sync cost is visible
        # instead of smeared into tokens/sec.
        "chunk_sync_secs": sum(float(r.get("secs", 0.0)) for r in chunk_recs),
        "chunk_dispatch_secs": sum(
            float(r.get("dispatch_secs", 0.0)) for r in chunk_recs),
    }
    return out, timing, tokens, metrics


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=1_000_000)
    ap.add_argument("--docs-per-chunk", type=int, default=8192)
    ap.add_argument("--tokens-per-doc", type=int, default=12)
    ap.add_argument("--vocab-bits", type=int, default=18)
    ap.add_argument("--ngram", type=int, default=2,
                    help="2 = uni+bigram (the Wikipedia config)")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--checkpoint-every", type=int, default=32)
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig

    with tempfile.TemporaryDirectory(prefix="rehearsal_ck_") as ckdir:
        base = dict(
            vocab_bits=args.vocab_bits, ngram=args.ngram,
            tf_mode="freq", idf_mode="smooth", l2_normalize=True,
            chunk_tokens=1 << 19,
        )
        # serial-vs-pipelined comparison at 1/8 scale (same generator seed).
        # The first serial pass is an untimed warm-up: it compiles both the
        # chunk kernel and the nnz-shaped finalize_weights program, so the
        # two timed runs below (identical data, identical shapes) hit the
        # jit cache and the comparison measures scheduling only.
        small = max(args.docs // 8, 1)
        run_once(TfidfConfig(**base, prefetch=0), small, args.docs_per_chunk,
                 args.tokens_per_doc, args.seed)
        _, serial_t, small_tokens, _ = run_once(
            TfidfConfig(**base, prefetch=0), small, args.docs_per_chunk,
            args.tokens_per_doc, args.seed)
        _, pipe_t, _, _ = run_once(
            TfidfConfig(**base, prefetch=2), small, args.docs_per_chunk,
            args.tokens_per_doc, args.seed)

        # the full rehearsal: checkpoints on, pipelined
        cfg = TfidfConfig(**base, prefetch=2,
                          checkpoint_every=args.checkpoint_every,
                          checkpoint_dir=ckdir)
        out, full_t, tokens, metrics = run_once(
            cfg, args.docs, args.docs_per_chunk, args.tokens_per_doc,
            args.seed)
        n_ckpts = sum(1 for r in metrics.records if r.get("event") == "checkpoint")

    secs = full_t["wall_secs"]
    result = {
        "backend": jax.default_backend(),
        "n_docs": out.n_docs,
        "n_tokens": int(tokens),
        "nnz": out.nnz,
        "wall_secs": round(secs, 2),
        "ingest_secs": round(full_t["ingest_secs"], 2),
        "finalize_secs": round(full_t["finalize_secs"], 2),
        "chunk_sync_secs_total": round(full_t["chunk_sync_secs"], 2),
        "chunk_dispatch_secs_total": round(full_t["chunk_dispatch_secs"], 2),
        "tokens_per_sec": round(tokens / secs),
        "tokens_per_sec_ingest": round(tokens / max(full_t["ingest_secs"], 1e-9)),
        "peak_rss_mb": round(peak_rss_mb(), 1),
        "checkpoints_written": n_ckpts,
        # ingest-only ratio — finalize excluded on both sides (see run_once)
        "pipeline_speedup_vs_serial": round(
            serial_t["ingest_secs"] / max(pipe_t["ingest_secs"], 1e-9), 3),
        "serial_ingest_secs_eighth_scale": round(serial_t["ingest_secs"], 2),
        "pipelined_ingest_secs_eighth_scale": round(pipe_t["ingest_secs"], 2),
        "serial_secs_eighth_scale": round(serial_t["wall_secs"], 2),
        "pipelined_secs_eighth_scale": round(pipe_t["wall_secs"], 2),
        "small_scale_tokens": int(small_tokens),
        "finalize": next((r for r in metrics.records
                          if r.get("event") == "finalize"), None),
    }
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
