#!/usr/bin/env bash
# Fast pre-commit gate: lint ONLY the files changed vs a base ref.
#
#   tools/precommit.sh [BASE]     # default BASE = HEAD (worktree diff)
#
# Tier 1 scans just the changed files; tiers 2/3 re-trace only the jit
# entry points whose contracted module changed (all of them when analysis/
# itself changed); tiers 4, 5 and 6 still model the whole surface
# (interprocedural/cross-file facts do not restrict — all three models
# are pure AST, well under a second) but report only findings in the
# changed files.  tools/lint.sh remains the full-repo CI gate — this script is
# the editor-loop companion, typically <2s when nothing jit-adjacent
# moved.
#
# PALLAS_AXON_POOL_IPS is stripped and the CPU backend forced so the gate
# can never hang on a wedged TPU tunnel (NOTES.md round-2 rule).
set -euo pipefail
cd "$(dirname "$0")/.."
BASE="${1:-HEAD}"
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m page_rank_and_tfidf_using_apache_spark_tpu.analysis \
        --changed-only "$BASE"
