#!/usr/bin/env bash
# Full CI pipeline: tier-1 tests, all six graftlint tiers, and the chaos
# gate.
#
# The semantic lint tier (tier 2: CPU-only jaxpr tracing of every
# registered jit entry point) carries a wall-clock budget —
# GRAFT_SEMANTIC_BUDGET_S, default 60s — so trace-time regressions (an
# entry point ballooning, a registry builder doing real work) fail CI
# instead of silently eating the loop.
#
# PALLAS_AXON_POOL_IPS is stripped and the CPU backend forced throughout so
# CI can never hang on a wedged TPU tunnel (NOTES.md round-2 rule).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tier-1 tests =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly

echo "== graftlint tier 1 (lexical) =="
tools/lint.sh --tier 1

echo "== graftlint tier 2 (semantic, budget ${GRAFT_SEMANTIC_BUDGET_S:-60}s) =="
t0=$(date +%s)
tools/lint.sh --tier 2
dt=$(( $(date +%s) - t0 ))
echo "semantic tier: ${dt}s"
if [ "$dt" -gt "${GRAFT_SEMANTIC_BUDGET_S:-60}" ]; then
    echo "FAIL: semantic tier exceeded its ${GRAFT_SEMANTIC_BUDGET_S:-60}s budget (${dt}s)" >&2
    exit 1
fi

echo "== graftlint tier 3 (cost model, budget ${GRAFT_COST_BUDGET_S:-10}s) =="
# Static cost analysis (intensity floors / pad_frac budgets / donation
# verifier) is all trace-time work and must stay interactive-fast: a cost
# run that stops fitting its budget is itself a regression (a registry
# builder started doing real work).
t0=$(date +%s)
tools/lint.sh --tier 3
dt=$(( $(date +%s) - t0 ))
echo "cost tier: ${dt}s"
if [ "$dt" -gt "${GRAFT_COST_BUDGET_S:-10}" ]; then
    echo "FAIL: cost tier exceeded its ${GRAFT_COST_BUDGET_S:-10}s budget (${dt}s)" >&2
    exit 1
fi

echo "== autotune smoke (dry-run prune plan + committed-profile round-trip, budget ${GRAFT_TUNE_BUDGET_S:-60}s) =="
# The cost model that tier 3 audits with also DRIVES the tuner (ISSUE
# 16): the dry-run must show static pruning discarding >=30% of the raw
# knob grid before anything is measured, every group must keep at least
# one survivor (a group pruned to zero would make the real sweep
# unrunnable), and the committed per-backend profile must parse AND
# round-trip through the same utils/config loader the runners resolve
# knobs from — all inside the tuner's own declared budget knob.
t0=$(date +%s)
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python tools/autotune.py --dry-run --json > /tmp/_autotune_plan.json
python - /tmp/_autotune_plan.json <<'EOF'
import json
import os
import sys
import tempfile

with open(sys.argv[1]) as f:
    plan = json.load(f)["plan"]
frac = plan["prune_frac"]
assert frac >= 0.30, (
    f"static pruning discarded only {frac:.1%} of the raw grid — the "
    "cost model stopped doing the tuner's first-pass work")
assert plan["raw_points"] == plan["pruned_points"] + plan["survivor_points"]
for g, gp in plan["groups"].items():
    assert gp["survivors"], f"group {g!r} pruned to zero survivors"

from page_rank_and_tfidf_using_apache_spark_tpu.utils import config

prof = config.load_tuned_profile(backend="cpu")
assert prof is not None, "committed tuned_profile_cpu.json did not load"
assert prof.backend == "cpu" and prof.source == "committed"
assert set(prof.knobs) == set(config.TUNABLE_DEFAULTS), (
    sorted(set(config.TUNABLE_DEFAULTS) ^ set(prof.knobs)))
with tempfile.TemporaryDirectory() as d:
    p = os.path.join(d, "tuned_profile_cpu.json")
    config.write_tuned_profile(p, "cpu", prof.knobs, measured={"smoke": True})
    back = config.load_tuned_profile(path=p)
    assert back.knobs == prof.knobs, "loader round-trip changed the knobs"
print(f"autotune smoke: OK ({plan['pruned_points']}/{plan['raw_points']} "
      f"points pruned statically = {frac:.1%}, committed cpu profile "
      f"round-trips {len(prof.knobs)} knobs)")
EOF
rm -f /tmp/_autotune_plan.json
dt=$(( $(date +%s) - t0 ))
echo "autotune smoke: ${dt}s"
if [ "$dt" -gt "${GRAFT_TUNE_BUDGET_S:-60}" ]; then
    echo "FAIL: autotune smoke exceeded its ${GRAFT_TUNE_BUDGET_S:-60}s budget (${dt}s)" >&2
    exit 1
fi

echo "== graftlint tier 4 (concurrency, budget ${GRAFT_CONC_BUDGET_S:-10}s; incl. lock-graph smoke) =="
# Interprocedural concurrency & buffer-lifetime analysis (lock-order
# cycles, blocking-under-lock, use-after-donate, chaos-coverage drift,
# thread/lock registry drift) is pure AST — stdlib-only like tier 1 —
# and must stay interactive-fast under its own declared budget knob.
# ONE invocation serves both gates: its exit code is the findings gate
# (set -e aborts on failure) and its captured stdout is the --lock-graph
# DOT smoke — the graph must stay emittable for human inspection
# (tools/trace_report.py-style), naming at least the serving drain lock.
t0=$(date +%s)
lock_dot=$(tools/lint.sh --tier 4 --lock-graph)
dt=$(( $(date +%s) - t0 ))
echo "concurrency tier: ${dt}s"
if [ "$dt" -gt "${GRAFT_CONC_BUDGET_S:-10}" ]; then
    echo "FAIL: concurrency tier exceeded its ${GRAFT_CONC_BUDGET_S:-10}s budget (${dt}s)" >&2
    exit 1
fi
case "$lock_dot" in
    *"digraph lock_graph"*"TfidfServer._lock"*) ;;
    *) echo "FAIL: --lock-graph emitted no usable DOT graph" >&2
       printf '%s\n' "$lock_dot" | head -20 >&2
       exit 1 ;;
esac
echo "lock-graph smoke: OK ($(printf '%s\n' "$lock_dot" | grep -c ' -> ') edge(s) emitted)"

echo "== graftlint tier 5 (persistence, budget ${GRAFT_PERSIST_BUDGET_S:-10}s; incl. crash-point smoke) =="
# Persistence & crash-consistency analysis (atomic-write drift,
# pointer-flip ordering, generation-deferred GC, ARTIFACT_SCHEMAS
# writer/reader drift, commit-lock drift) is pure AST — stdlib-only like
# tiers 1/4 — under its own declared budget knob.  ONE invocation serves
# both gates: exit code = findings gate, captured stdout = the
# --crash-points smoke — the derived crash-surface enumeration must stay
# emittable and must still contain the two commit_append rename
# boundaries the crash harness SIGKILLs.
t0=$(date +%s)
crash_json=$(tools/lint.sh --tier 5 --crash-points --json)
dt=$(( $(date +%s) - t0 ))
echo "persistence tier: ${dt}s"
if [ "$dt" -gt "${GRAFT_PERSIST_BUDGET_S:-10}" ]; then
    echo "FAIL: persistence tier exceeded its ${GRAFT_PERSIST_BUDGET_S:-10}s budget (${dt}s)" >&2
    exit 1
fi
crash_tmp=$(mktemp)
printf '%s\n' "$crash_json" > "$crash_tmp"
python - "$crash_tmp" <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    doc = json.load(f)
assert doc["ok"] is True, doc.get("findings")
cps = doc["crash_points"]
# validate the commit_append entry SPECIFICALLY (a null entry or marker
# strings borrowed from commit_replace's chains must not pass)
entry = next((k for k in cps if k.endswith("::commit_append")), None)
assert entry is not None, sorted(cps)
pts = cps[entry]
assert pts, f"{entry} enumeration is empty/null — the harness's kill schedule is gone"
bounds = [p for p in pts if p["boundary"]]
assert [b["op"] for b in bounds] == ["replace", "replace"], bounds
assert "_write_manifest()" in bounds[0]["via"], bounds[0]
assert "_write_pointer()" in bounds[1]["via"], bounds[1]
total = sum(1 for e in cps.values() if e for _p in e)
print(f"crash-point smoke: OK ({len(bounds)} commit_append boundary point(s), "
      f"{total} enumerated op(s) across {len(cps)} commit sequences)")
EOF
rm -f "$crash_tmp"

echo "== crash-harness smoke (SIGKILL at 3 commit_append boundaries) =="
# The dynamic half of tier 5 (ISSUE 14), bounded for CI: replay the real
# seal+commit_append protocol with a SIGKILL at 3 of its enumerated write
# boundaries (spread across the window) and require reload to serve a
# consistent generation — old or new, never torn — with zero orphans
# after the recovery GC pass.  tools/chaos.sh runs the full kill matrix.
python tools/crash_harness.py --scenarios append --max-kills 3

echo "== graftlint tier 6 (wire protocol, budget ${GRAFT_PROTO_BUDGET_S:-10}s; incl. wire-probe smoke) =="
# Distributed wire-protocol analysis (endpoint/status-code/key drift
# against WIRE_SCHEMAS, status-class drift against the router's retry
# logic, retry-unsafe effects ahead of the rid dedup guard, floor
# monotonicity) is pure AST — stdlib-only like tiers 1/4/5 — under its
# own declared budget knob.  ONE invocation serves both gates: exit
# code = findings gate, captured stdout = the --wire-probes smoke — the
# derived message-space enumeration must stay emittable and must still
# contain the duplicate-rid and stale-floor probes the conformance
# harness replays.
t0=$(date +%s)
wire_json=$(tools/lint.sh --tier 6 --wire-probes --json)
dt=$(( $(date +%s) - t0 ))
echo "protocol tier: ${dt}s"
if [ "$dt" -gt "${GRAFT_PROTO_BUDGET_S:-10}" ]; then
    echo "FAIL: protocol tier exceeded its ${GRAFT_PROTO_BUDGET_S:-10}s budget (${dt}s)" >&2
    exit 1
fi
wire_tmp=$(mktemp)
printf '%s\n' "$wire_json" > "$wire_tmp"
python - "$wire_tmp" <<'EOF'
import json
import sys

with open(sys.argv[1]) as f:
    doc = json.load(f)
assert doc["ok"] is True, doc.get("findings")
probes = doc["wire_probes"]
kinds = {p["kind"] for p in probes}
# the two probes the harness's core invariants ride on must be derivable
assert "duplicate-rid" in kinds, sorted(kinds)
assert "stale-floor" in kinds, sorted(kinds)
assert any(p["kind"] == "unknown-path" for p in probes), sorted(kinds)
print(f"wire-probe smoke: OK ({len(probes)} probe(s), "
      f"{len(kinds)} kind(s) enumerated)")
EOF
rm -f "$wire_tmp"

echo "== protocol-harness smoke (declared message space at a live replica) =="
# The dynamic half of tier 6: replay the enumerated malformed /
# out-of-contract / duplicate-rid / stale-floor matrix at a live replica
# and through the router — typed rejection everywhere, zero hangs, zero
# double executions, byte-identical replay.  Shares the protocol tier's
# budget knob: the whole matrix is a bounded smoke, not a soak.
t0=$(date +%s)
python tools/protocol_harness.py
dt=$(( $(date +%s) - t0 ))
echo "protocol harness: ${dt}s"
if [ "$dt" -gt "${GRAFT_PROTO_BUDGET_S:-10}" ]; then
    echo "FAIL: protocol harness exceeded its ${GRAFT_PROTO_BUDGET_S:-10}s budget (${dt}s)" >&2
    exit 1
fi

echo "== drain kill-matrix smoke (SIGKILL at 3 handoff points, budget ${GRAFT_DRAIN_BUDGET_S:-40}s) =="
# The drain handoff's kill-point discipline, exercised for real: a
# 1-replica fleet rolls via SO_REUSEPORT socket handoff while SIGKILL
# lands (a) on the predecessor pre-drain (mid-successor-spawn), (b) on
# the predecessor mid-drain (right after the swap), (c) on the healthy
# successor post-roll.  After every point exactly ONE process serves the
# pinned port — repeated /status polls see a single pid — and the
# closed-loop audit stays dropped=0 / double_served=0.
t0=$(date +%s)
if env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python - > /tmp/_drain_matrix.log 2>&1 <<'EOF'
import json
import os
import signal
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path.cwd()))
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.obs.export import (
    reuse_port_supported,
)
from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

if not reuse_port_supported():
    print("drain kill-matrix: SKIP (platform lacks SO_REUSEPORT)")
    sys.exit(0)

scfg = TfidfConfig(vocab_bits=10)
docs = ["node edge graph rank walk", "graph node directed edge weight",
        "rank walk teleport damping node", "edge list sparse matrix graph"]
tmp = tempfile.mkdtemp(prefix="drain-matrix-")
out = run_tfidf(docs, scfg)
ref = sgm.seal_segment(tmp, out, scfg, doc_base=0,
                       ranks=np.ones(out.n_docs, np.float32),
                       bm25=Bm25Config())
sgm.commit_append(tmp, ref, scfg.config_hash())

fab = fabric.ServingFabric(tmp, fabric.FabricConfig(
    replicas=1, poll_s=0.1, health_period_s=0.2, retry_limit=200,
    retry_pause_s=0.1, grace_s=10.0, federation=False,
))


def kill(pid):
    try:
        os.kill(pid, signal.SIGKILL)
        return True
    except ProcessLookupError:
        return False  # already exited — the point degenerates upward


def settle(expect_new_vs=None, timeout=30.0):
    """Wait until exactly one live serving process, return its pid."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        h = fab._handles.get(0)
        if h is not None and h.alive() and \
                (expect_new_vs is None or h.pid != expect_new_vs):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{fab._ports[0]}/status",
                        timeout=2.0) as resp:
                    st = json.loads(resp.read())
                if st["ready"]:
                    return h.pid
            except OSError:
                pass
        time.sleep(0.1)
    raise AssertionError("no healthy replica settled in time")


def poll_pids(n=15):
    pids = set()
    for _ in range(n):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fab._ports[0]}/status",
                timeout=2.0) as resp:
            pids.add(json.loads(resp.read())["pid"])
        time.sleep(0.02)
    return pids


def roll_with_kill(trigger):
    """Roll in a thread; `trigger(old_pid)` decides when to SIGKILL."""
    old_pid = fab._handles[0].pid
    errs = []

    def run():
        try:
            fab.rolling_restart(timeout=60.0)
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errs.append(exc)

    t = threading.Thread(target=run)
    t.start()
    trigger(old_pid)
    t.join(90.0)
    assert not t.is_alive(), "roll wedged"
    return old_pid, errs


with fab:
    stop = threading.Event()
    failures = []

    def load():
        while not stop.is_set():
            try:
                fab.query(["node", "graph"])
            except Exception as exc:  # noqa: BLE001 — audited below
                failures.append(exc)

    loader = threading.Thread(target=load, daemon=True)
    loader.start()
    try:
        # (a) pre-drain: predecessor dies while the successor is still
        # spawning — the handoff swap must replace it, not race a
        # supervisor respawn onto the same port
        old, errs = roll_with_kill(lambda pid: kill(pid))
        assert not errs, errs
        pid_a = settle(expect_new_vs=old)
        assert poll_pids() == {pid_a}, "more than one listener serving"

        # (b) mid-drain: SIGKILL the predecessor right after the swap
        # (its drain is cut short; in-flight requests retry typed)
        def mid_drain(pid):
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                h = fab._handles.get(0)
                if h is not None and h.pid != pid:
                    break
                time.sleep(0.01)
            kill(pid)

        old, errs = roll_with_kill(mid_drain)
        assert not errs, errs
        pid_b = settle(expect_new_vs=old)
        assert poll_pids() == {pid_b}, "more than one listener serving"

        # (c) post-successor-healthy: the freshly rolled replica dies —
        # ordinary unplanned failure, the supervisor path takes it
        old, errs = roll_with_kill(lambda pid: None)
        assert not errs, errs
        pid_c = settle(expect_new_vs=old)
        kill(pid_c)
        pid_d = settle(expect_new_vs=pid_c)
        assert poll_pids() == {pid_d}, "more than one listener serving"
    finally:
        stop.set()
        loader.join(10.0)
    audit = fab.audit()

assert not failures, failures[:3]
assert audit["dropped"] == 0, audit
assert audit["double_served"] == 0, audit
assert audit["rolled"] == 3, audit
print("drain kill-matrix: OK — SIGKILL pre-drain / mid-drain / "
      "post-successor left exactly one listener each time "
      f"({audit['requests']} closed-loop requests, dropped=0 "
      "double_served=0)")
EOF
then
    tail -1 /tmp/_drain_matrix.log
else
    echo "FAIL: drain kill-matrix smoke; its output:" >&2
    cat /tmp/_drain_matrix.log >&2
    exit 1
fi
dt=$(( $(date +%s) - t0 ))
echo "drain kill-matrix: ${dt}s"
if [ "$dt" -gt "${GRAFT_DRAIN_BUDGET_S:-40}" ]; then
    echo "FAIL: drain kill-matrix exceeded its ${GRAFT_DRAIN_BUDGET_S:-40}s budget (${dt}s)" >&2
    exit 1
fi

echo "== trace-diff gate (per-phase regression across committed rounds) =="
# Compare the two newest committed BENCH rounds: a per-phase wall-time
# regression past GRAFT_TRACE_DIFF_THRESHOLD (default 35%) in the
# committed trajectory fails CI — the round that paid it must explain
# itself before the next one lands on top.  ENFORCING since ISSUE 8: the
# two newest committed rounds (r06+) carry extra.breakdown, so rc=2 — a
# round missing its breakdown — is itself a regression (the bench lost
# its accounting), not a soft skip.  Since ISSUE 10 trace_diff folds the
# overlapped staged-ingest phases (ingest.h2d + ingest.compute) before
# comparing, so wall time moving from compute into overlapped H2D — the
# double-buffering landing — can never read as a false regression.
# `|| true`: zero matching rounds must take the skip branch below, not
# kill the script via set -e/pipefail; sort -V keeps r100 after r99
rounds=$(ls BENCH_r*.json 2>/dev/null | sort -V | tail -2 || true)
if [ "$(echo "$rounds" | grep -c .)" -eq 2 ]; then
    prev=$(echo "$rounds" | head -1)
    cur=$(echo "$rounds" | tail -1)
    set +e
    python tools/trace_diff.py "$prev" "$cur" \
        --threshold "${GRAFT_TRACE_DIFF_THRESHOLD:-0.35}"
    diff_rc=$?
    set -e
    if [ "$diff_rc" -eq 1 ]; then
        echo "FAIL: $cur regressed a phase past ${GRAFT_TRACE_DIFF_THRESHOLD:-0.35} vs $prev" >&2
        exit 1
    elif [ "$diff_rc" -eq 2 ]; then
        echo "FAIL: $prev/$cur are not comparable (missing extra.breakdown)" >&2
        echo "      — committed rounds must carry their per-phase accounting" >&2
        exit 1
    fi
else
    echo "trace-diff gate: skipped (fewer than two committed rounds)"
fi

echo "== traced-run smoke (obs + trace_report) =="
# A tiny streaming TF-IDF run under GRAFT_TRACE_DIR must leave a JSONL
# trace + manifest that tools/trace_report.py turns into a per-phase
# breakdown with a completed chunk timeline — the artifact path bench.py's
# accounting depends on.
smoke_dir=$(mktemp -d)
trap 'rm -rf "$smoke_dir"' EXIT
printf 'alpha beta gamma\nbeta gamma delta\nepsilon zeta alpha\ngamma gamma beta\nalpha delta epsilon\nzeta zeta beta\n' \
    > "$smoke_dir/corpus.txt"
# GRAFT_TUNED_PROFILE=off: the committed profile's pack_target_tokens
# would re-pack this 6-doc corpus into one chunk; this smoke pins the
# 3-chunk timeline, so it runs on dataclass defaults.
if ! env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu GRAFT_TRACE_DIR="$smoke_dir" \
    GRAFT_TUNED_PROFILE=off \
    python -m page_rank_and_tfidf_using_apache_spark_tpu.cli.tfidf \
        "$smoke_dir/corpus.txt" --lines --streaming --chunk-docs 2 \
        --vocab-bits 8 --prefetch 0 > "$smoke_dir/cli.log" 2>&1; then
    echo "FAIL: traced tfidf CLI run; its output:" >&2
    cat "$smoke_dir/cli.log" >&2
    exit 1
fi
trace_file=$(ls "$smoke_dir"/tfidf.*.trace.jsonl)
python tools/trace_report.py "$trace_file" --json > "$smoke_dir/report.json"
python - "$smoke_dir/report.json" <<'EOF'
import json, sys
rep = json.load(open(sys.argv[1]))
assert rep["complete"], f"traced run did not finish: {rep}"
assert "tfidf.stream" in rep["breakdown"], rep["breakdown"]
assert len(rep["chunks"]) == 3 and all(c["complete"] for c in rep["chunks"]), rep["chunks"]
assert rep["manifest"] and rep["manifest"]["status"] == "ok", rep["manifest"]
# the staged ingest pipeline (ISSUE 10) must leave its per-stage
# accounting in the artifact: one ingest_overlap record per run with the
# tokenize/h2d/compute split and the h2d_overlap_frac gauge
assert rep.get("ingest"), rep.get("ingest")
assert all("h2d_overlap_frac" in r for r in rep["ingest"]), rep["ingest"]
print("traced-run smoke: OK "
      f"({rep['events']} events, {len(rep['chunks'])} chunks, "
      f"wall {rep['wall_secs']:.3f}s, "
      f"h2d_overlap {rep['ingest'][-1]['h2d_overlap_frac']})")
EOF

echo "== soak smoke (bounded SLO gate: ~${GRAFT_SOAK_DURATION_S:-20}s CPU soak under *:fail@%5 chaos) =="
# A bounded production soak (ISSUE 11): continuous streaming ingest +
# index rebuild/hot-swap + mixed tfidf/bm25/@prior closed-loop traffic +
# ONE injected device loss, all under *:fail@%5 transient chaos, must
# produce a parseable SLO record with a non-null served p99 and a
# measured time-to-recover, and the zero-dropped / zero-double-served
# invariants must hold.  This is the "heavy traffic" claim as a CI gate.
if ! env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    GRAFT_CHAOS="*:fail@%5" \
    GRAFT_SOAK_DURATION_S="${GRAFT_SOAK_DURATION_S:-20}" \
    GRAFT_SOAK_QPS="${GRAFT_SOAK_QPS:-15}" \
    python bench.py --soak > "$smoke_dir/soak.json" 2> "$smoke_dir/soak.log"; then
    echo "FAIL: soak child; its stderr tail:" >&2
    tail -30 "$smoke_dir/soak.log" >&2
    exit 1
fi
python - "$smoke_dir/soak.json" <<'EOF'
import json, sys
rec = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert rec.get("served_p99_ms") is not None, f"null p99: {rec}"
recov = rec.get("recovery") or {}
assert recov.get("losses_injected", 0) >= 1, f"no loss injected: {recov}"
assert recov.get("time_to_recover_s") is not None, f"no recovery time: {recov}"
assert rec.get("dropped") == 0, f"dropped requests: {rec['dropped']}"
assert rec.get("double_served") == 0, f"double-served: {rec['double_served']}"
assert (rec.get("ingest") or {}).get("chunks", 0) > 0, "no ingest ran"
print("soak smoke: OK "
      f"({rec['requests']} req at {rec['qps']} qps, "
      f"p99 {rec['served_p99_ms']}ms, "
      f"recovered in {recov['time_to_recover_s']}s, "
      f"{rec['ingest']['rebuilds']} rebuild(s))")
EOF

echo "== fabric smoke (N=${GRAFT_FABRIC_REPLICAS:-2} replica fleet: SIGKILL mid-traffic + respawn, budget ${GRAFT_FABRIC_BUDGET_S:-25}s) =="
# The ISSUE 17 serving fabric as a bounded CI gate: an N-replica fleet
# of real child processes mmap-loads the same sealed segments, one
# replica is hard-SIGKILLed mid-traffic, and the router's sibling retry
# + supervisor respawn must deliver every request exactly once
# (dropped=0, double_served=0) — then the run's trace must parse into
# tools/trace_report.py's fabric section (replicas/kills/respawns/totals).
t0=$(date +%s)
if ! env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    GRAFT_FABRIC_REPLICAS="${GRAFT_FABRIC_REPLICAS:-2}" \
    FABRIC_SMOKE_DIR="$smoke_dir" \
    python - > "$smoke_dir/fabric.log" 2>&1 <<'EOF'
import importlib.util
import os
import time

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

d = os.path.join(os.environ["FABRIC_SMOKE_DIR"], "fabidx")
scfg = TfidfConfig(vocab_bits=9)
docs = [f"alpha beta doc{i} shared word graph node" for i in range(8)]
out = run_tfidf(docs, scfg)
ref = sgm.seal_segment(d, out, scfg, doc_base=0,
                       ranks=np.ones(out.n_docs, np.float32),
                       bm25=Bm25Config())
sgm.commit_append(d, ref, scfg.config_hash())
n = int(os.environ.get("GRAFT_FABRIC_REPLICAS", "2"))
trace_dir = os.path.join(os.environ["FABRIC_SMOKE_DIR"], "fabtrace")
with obs.run("fabric_smoke", trace_dir=trace_dir) as r:
    cfg = fabric.FabricConfig(replicas=n, poll_s=0.1, health_period_s=0.2,
                              retry_limit=100, retry_pause_s=0.1,
                              grace_s=10.0)
    with fabric.ServingFabric(d, cfg) as fab:
        for _ in range(5):
            fab.query(["alpha", "beta"])
        fab.kill_replica(0)  # hard SIGKILL mid-traffic
        for _ in range(10):
            fab.query(["shared", "word"])
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if (fab.audit()["respawns"] >= 1
                    and all(s is not None and s.get("ready")
                            for s in fab.statuses())):
                break
            time.sleep(0.2)
        audit = fab.audit()
assert audit["respawns"] >= 1, audit
assert audit["dropped"] == 0 and audit["double_served"] == 0, audit
spec = importlib.util.spec_from_file_location("tr", "tools/trace_report.py")
tr = importlib.util.module_from_spec(spec)
spec.loader.exec_module(tr)
rep = tr.report(r.trace_path)
fb = rep["fabric"]
assert fb is not None and fb["replicas"] == n, fb
assert fb["kills"] >= 1 and len(fb["respawns"]) >= 1, fb
assert fb["totals"]["dropped"] == 0, fb
assert fb["totals"]["double_served"] == 0, fb
print(f"fabric smoke: OK — N={n} fleet survived a SIGKILL "
      f"({audit['requests']} req, {audit['retries']} sibling retries, "
      f"{len(fb['respawns'])} respawn(s), dropped=0, double_served=0)")
EOF
then
    echo "FAIL: fabric smoke; its output:" >&2
    cat "$smoke_dir/fabric.log" >&2
    exit 1
fi
tail -1 "$smoke_dir/fabric.log"
dt=$(( $(date +%s) - t0 ))
echo "fabric smoke: ${dt}s"
if [ "$dt" -gt "${GRAFT_FABRIC_BUDGET_S:-25}" ]; then
    echo "FAIL: fabric smoke exceeded its ${GRAFT_FABRIC_BUDGET_S:-25}s budget (${dt}s) — replica spawn/respawn stopped being interactive" >&2
    exit 1
fi

echo "== federation smoke (fleet scrape → merged board → forced scale-up, budget ${GRAFT_FED_BUDGET_S:-25}s) =="
# The ISSUE 19 observability plane as a bounded CI gate: a 1-replica
# fleet with the router-side FleetHub, one real scrape sweep, the
# router's OWN /snapshot.json must serve a parseable merged fleet board
# (replica rows + counters folded exactly), then one forced scale-up
# through the autoscaler's own spawn path — and the run's trace must
# render tools/trace_report.py's autoscale timeline.
t0=$(date +%s)
if ! env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    FED_SMOKE_DIR="$smoke_dir" \
    python - > "$smoke_dir/federation.log" 2>&1 <<'EOF'
import importlib.util
import json
import os
import urllib.request

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

d = os.path.join(os.environ["FED_SMOKE_DIR"], "fedidx")
scfg = TfidfConfig(vocab_bits=9)
docs = [f"alpha beta doc{i} shared word graph node" for i in range(8)]
out = run_tfidf(docs, scfg)
ref = sgm.seal_segment(d, out, scfg, doc_base=0,
                       ranks=np.ones(out.n_docs, np.float32),
                       bm25=Bm25Config())
sgm.commit_append(d, ref, scfg.config_hash())
trace_dir = os.path.join(os.environ["FED_SMOKE_DIR"], "fedtrace")
with obs.run("fed_smoke", trace_dir=trace_dir) as r:
    cfg = fabric.FabricConfig(replicas=1, poll_s=0.1, health_period_s=0.2,
                              retry_limit=100, retry_pause_s=0.1,
                              grace_s=10.0, latency_slo_s=0.5,
                              availability_target=0.999)
    with fabric.ServingFabric(d, cfg) as fab:
        for _ in range(8):
            fab.query(["alpha", "beta"])
        fab.fleet.scrape_once()
        # the router's OWN exporter serves the merged fleet board
        with urllib.request.urlopen(fab.fleet_url + "/snapshot.json",
                                    timeout=5) as resp:
            snap = json.loads(resp.read())
        assert snap["fleet"]["replicas"], snap["fleet"]
        total = snap["counters"]["serve.requests"]["total"]
        assert total >= 8, snap["counters"]
        # one forced scale-up through the autoscaler's own spawn path
        scaler = fabric.Autoscaler(fab, fabric.AutoscaleConfig(
            min_replicas=1, max_replicas=2, cooldown_s=0.0))
        action = scaler.tick(
            {"budgets": {"availability": {"burn_rate": 10.0}}})
        assert action == "up", action
        assert len(fab.replica_ids()) == 2, fab.replica_ids()
        for _ in range(4):
            fab.query(["shared", "word"])
        audit = fab.audit()
assert audit["dropped"] == 0 and audit["double_served"] == 0, audit
assert audit["scale_ups"] >= 1, audit
spec = importlib.util.spec_from_file_location("tr", "tools/trace_report.py")
tr = importlib.util.module_from_spec(spec)
spec.loader.exec_module(tr)
rep = tr.report(r.trace_path)
a = rep["autoscale"]
assert a is not None and a["ups"] >= 1 and a["actions"] >= 1, a
spec = importlib.util.spec_from_file_location("sw", "tools/slo_watch.py")
sw = importlib.util.module_from_spec(spec)
spec.loader.exec_module(sw)
board = sw.render_fleet(snap)
assert "fleet:" in board, board
print(f"federation smoke: OK — scraped {len(snap['fleet']['replicas'])} "
      f"replica(s), merged {int(total)} requests exactly, forced "
      f"scale-up to {audit['scale_ups'] + 1} replicas, autoscale "
      f"timeline rendered ({a['actions']} action(s))")
EOF
then
    echo "FAIL: federation smoke; its output:" >&2
    cat "$smoke_dir/federation.log" >&2
    exit 1
fi
tail -1 "$smoke_dir/federation.log"
dt=$(( $(date +%s) - t0 ))
echo "federation smoke: ${dt}s"
if [ "$dt" -gt "${GRAFT_FED_BUDGET_S:-25}" ]; then
    echo "FAIL: federation smoke exceeded its ${GRAFT_FED_BUDGET_S:-25}s budget (${dt}s) — the fleet scrape/scale path stopped being interactive" >&2
    exit 1
fi

echo "== segment smoke (seal → serve → post-start commit → merge under *:fail@%5, budget ${GRAFT_SEG_BUDGET_S:-15}s) =="
# The ISSUE 13 ingest→servable path as a bounded CI gate: seal a delta
# segment, serve it via impacted-list scoring, commit a SECOND segment
# AFTER server start and hot-swap it live (no restart — the acceptance
# bar), then background-merge the set — all under transient chaos.  The
# whole lifecycle must fit GRAFT_SEG_BUDGET_S ("servable in seconds").
t0=$(date +%s)
if ! env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    GRAFT_CHAOS='*:fail@%5' GRAFT_RETRY_MAX=4 GRAFT_BACKOFF_BASE_S=0.01 \
    SEG_SMOKE_DIR="$smoke_dir" \
    python - > "$smoke_dir/segments.log" 2>&1 <<'EOF'
import os
import numpy as np
from page_rank_and_tfidf_using_apache_spark_tpu import serving
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig

d = os.path.join(os.environ["SEG_SMOKE_DIR"], "segidx")
scfg = TfidfConfig(vocab_bits=8, prefetch=0, pipeline_depth=0)
chunks = [[f"tok{i} tok{i % 5} shared word" for i in range(j * 3, j * 3 + 3)]
          for j in range(4)]
out = run_tfidf_streaming(iter(chunks), scfg)
ref = sgm.seal_segment(d, out, scfg, doc_base=0)
sgm.commit_append(d, ref, scfg.config_hash())
srv = serving.TfidfServer(
    sgm.load_segment_set(d),
    serving.ServeConfig(top_k=3, scoring="impacted"),
).start()
s, _ = srv.query(["tok3"])
assert float(s[0]) > 0
# a segment committed AFTER server start, hot-swapped without restart
out2 = run_tfidf_streaming(iter([["freshterm post start doc"]]), scfg)
ref2 = sgm.seal_segment(d, out2, scfg, doc_base=out.n_docs)
sgm.commit_append(d, ref2, scfg.config_hash())
srv.refresh_segments(sgm.load_segment_set(d))
s2, i2 = srv.query(["freshterm"])
assert float(s2[0]) > 0 and int(i2[0]) == out.n_docs, (s2, i2)
# background compaction down to one segment, still serving the same doc
merger = sgm.SegmentMerger(d, scfg, max_segments=1)
while merger.merge_once():
    pass
assert len(sgm.latest_manifest(d).segments) == 1
srv.refresh_segments(sgm.load_segment_set(d))
s3, i3 = srv.query(["freshterm"])
assert int(i3[0]) == int(i2[0])
srv.stop()
print("segment smoke: OK — post-start commit served from segment "
      f"{ref2.name} (global doc {int(i2[0])}), merged to 1 segment")
EOF
then
    echo "FAIL: segment smoke; its output:" >&2
    cat "$smoke_dir/segments.log" >&2
    exit 1
fi
tail -1 "$smoke_dir/segments.log"
dt=$(( $(date +%s) - t0 ))
echo "segment smoke: ${dt}s"
if [ "$dt" -gt "${GRAFT_SEG_BUDGET_S:-15}" ]; then
    echo "FAIL: segment smoke exceeded its ${GRAFT_SEG_BUDGET_S:-15}s budget (${dt}s) — the ingest→servable path stopped being 'seconds'" >&2
    exit 1
fi

echo "== owned-strategy smoke (Zipf fixpoint under *:fail@%5, budget ${GRAFT_OWNED_BUDGET_S:-30}s) =="
# ISSUE 15: the owned-slices + sparse-boundary-exchange strategy as a
# bounded CI gate — a seeded Zipf graph runs a fixed-length fixpoint on
# a 4-device mesh under transient chaos, must match the single-chip
# ranks at 1e-9 (f64; fixed iterations, since the owned convergence
# gauge lags one step and a tolerance race would legitimately stop a
# different iteration), and the partition must publish a nonzero
# per-step comm footprint (the gauge the trace_diff comm gate
# regresses).
t0=$(date +%s)
if ! env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    GRAFT_CHAOS='*:fail@%5' GRAFT_RETRY_MAX=4 GRAFT_BACKOFF_BASE_S=0.01 \
    python - > "$smoke_dir/owned.log" 2>&1 <<'EOF'
import numpy as np
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import synthetic_zipf
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
    run_pagerank_sharded,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder

g = synthetic_zipf(3000, 24000, seed=5)
cfg = PageRankConfig(iterations=40, dangling="redistribute",
                     init="uniform", dtype="float64")
base = run_pagerank(g, cfg)
m = MetricsRecorder()
res = run_pagerank_sharded(g, cfg, n_devices=4, strategy="owned", metrics=m)
assert np.abs(res.ranks - base.ranks).sum() <= 1e-9
assert res.iterations == 40
part = next(r for r in m.records if r.get("event") == "partition")
assert part["comm_bytes_per_step"] > 0, part
print("owned smoke: OK — 40-iteration fixpoint matched single-chip at "
      f"1e-9 under chaos, {part['comm_bytes_per_step']} comm B/step "
      "on 4 devices")
EOF
then
    echo "FAIL: owned-strategy smoke; its output:" >&2
    cat "$smoke_dir/owned.log" >&2
    exit 1
fi
tail -1 "$smoke_dir/owned.log"
dt=$(( $(date +%s) - t0 ))
echo "owned smoke: ${dt}s"
if [ "$dt" -gt "${GRAFT_OWNED_BUDGET_S:-30}" ]; then
    echo "FAIL: owned smoke exceeded its ${GRAFT_OWNED_BUDGET_S:-30}s budget (${dt}s)" >&2
    exit 1
fi

echo "== chaos gate (tier-1 under *:fail@%5 + device_lost mesh-shrink scenario) =="
# chaos.sh's second half runs the device_lost sharded scenario under
# XLA_FLAGS=--xla_force_host_platform_device_count=2: both sharded runners
# must survive losing logical device 1 via the elastic mesh-shrink rung.
tools/chaos.sh

echo "CI: all gates green"
