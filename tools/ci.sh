#!/usr/bin/env bash
# Full CI pipeline: tier-1 tests, both graftlint tiers, and the chaos gate.
#
# The semantic lint tier (tier 2: CPU-only jaxpr tracing of every
# registered jit entry point) carries a wall-clock budget —
# GRAFT_SEMANTIC_BUDGET_S, default 60s — so trace-time regressions (an
# entry point ballooning, a registry builder doing real work) fail CI
# instead of silently eating the loop.
#
# PALLAS_AXON_POOL_IPS is stripped and the CPU backend forced throughout so
# CI can never hang on a wedged TPU tunnel (NOTES.md round-2 rule).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tier-1 tests =="
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly

echo "== graftlint tier 1 (lexical) =="
tools/lint.sh --tier 1

echo "== graftlint tier 2 (semantic, budget ${GRAFT_SEMANTIC_BUDGET_S:-60}s) =="
t0=$(date +%s)
tools/lint.sh --tier 2
dt=$(( $(date +%s) - t0 ))
echo "semantic tier: ${dt}s"
if [ "$dt" -gt "${GRAFT_SEMANTIC_BUDGET_S:-60}" ]; then
    echo "FAIL: semantic tier exceeded its ${GRAFT_SEMANTIC_BUDGET_S:-60}s budget (${dt}s)" >&2
    exit 1
fi

echo "== chaos gate =="
tools/chaos.sh

echo "CI: all gates green"
