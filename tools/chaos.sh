#!/usr/bin/env bash
# Chaos gate: run the tier-1 suite under an aggressive fault-injection
# profile — every 5th guarded call (dispatch or host sync) at EVERY site
# raises a transient device error, and a generous sync deadline arms the
# watchdog thread on each guarded call.  The suite must pass unchanged:
# the resilience executor's retries make injected transients invisible to
# callers, which is exactly the property this gate pins.
#
# Tests that install their own chaos plan (resilience.chaos.inject) are
# unaffected: an explicit plan overrides the GRAFT_CHAOS env plan.
#
# PALLAS_AXON_POOL_IPS is stripped and the CPU backend forced so the gate
# can never hang on a wedged TPU tunnel (NOTES.md round-2 rule).
set -euo pipefail
cd "$(dirname "$0")/.."
exec env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    GRAFT_CHAOS='*:fail@%5' \
    GRAFT_RETRY_MAX=4 \
    GRAFT_BACKOFF_BASE_S=0.01 \
    GRAFT_SYNC_DEADLINE_S=60 \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"
