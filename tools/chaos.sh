#!/usr/bin/env bash
# Chaos gate: run the tier-1 suite under an aggressive fault-injection
# profile — every 5th guarded call (dispatch or host sync) at EVERY site
# raises a transient device error, and a generous sync deadline arms the
# watchdog thread on each guarded call.  The suite must pass unchanged:
# the resilience executor's retries make injected transients invisible to
# callers, which is exactly the property this gate pins.
#
# Tests that install their own chaos plan (resilience.chaos.inject) are
# unaffected: an explicit plan overrides the GRAFT_CHAOS env plan.
#
# A second scenario then kills logical device 1 of a forced 2-device CPU
# mesh (GRAFT_CHAOS="*:device_lost@dev:1") and requires both sharded
# runners to finish via the elastic mesh-shrink rung with outputs matching
# an uninterrupted run — the ISSUE 5 acceptance bar.
#
# PALLAS_AXON_POOL_IPS is stripped and the CPU backend forced so the gate
# can never hang on a wedged TPU tunnel (NOTES.md round-2 rule).
set -euo pipefail
cd "$(dirname "$0")/.."
env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    GRAFT_CHAOS='*:fail@%5' \
    GRAFT_RETRY_MAX=4 \
    GRAFT_BACKOFF_BASE_S=0.01 \
    GRAFT_SYNC_DEADLINE_S=60 \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"

# ---------------------------------------------------------------------------
# device_lost sharded scenario (ISSUE 5 acceptance): on a forced 2-device
# CPU mesh with logical device 1 chaos-killed, BOTH sharded runners must
# finish via the elastic mesh-shrink rung (no ResilienceExhausted), match
# the uninterrupted outputs to atol 1e-6 f32, and leave a trace artifact
# holding exactly ONE mesh.shrink span with devices 2->1.
echo "== chaos: device_lost sharded scenario (2-device mesh, dev 1 dies) =="
scenario_dir=$(mktemp -d)
trap 'rm -rf "$scenario_dir"' EXIT
env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    GRAFT_TRACE_DIR="$scenario_dir" \
    SCENARIO_DIR="$scenario_dir" \
    python - <<'EOF'
import glob
import os
import sys

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.io import synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
    run_pagerank_sharded,
    run_tfidf_sharded,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    PageRankConfig,
    TfidfConfig,
)

sys.path.insert(0, "tools")  # chaos.sh runs from the repo root
import trace_report

kw = dict(dangling="redistribute", init="uniform", dtype="float32")
g = synthetic_powerlaw(800, 3200, seed=5)
chunks = [[f"tok{i} tok{i % 5} shared word extra{i % 3}"
           for i in range(j * 2, (j + 1) * 2)] for j in range(12)]

# uninterrupted references, BEFORE the chaos plan is installed
base_pr = run_pagerank(g, PageRankConfig(iterations=10, **kw))
base_tf = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                            n_devices=2)

os.environ["GRAFT_CHAOS"] = "*:device_lost@dev:1"

run = obs.start_run("chaos_device_lost", os.environ["SCENARIO_DIR"])
res = run_pagerank_sharded(g, PageRankConfig(iterations=10, **kw),
                           n_devices=2)
np.testing.assert_allclose(res.ranks, base_pr.ranks, atol=1e-6)

elastic.reset_health()  # fresh loss for the second runner
tf = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10), n_devices=2)
np.testing.assert_allclose(tf.to_dense(), base_tf.to_dense(), atol=1e-6)

# the owned strategy (ISSUE 15): the shrink rung must re-own the rank
# slices and rebuild the boundary sets for the surviving mesh
elastic.reset_health()
res_o = run_pagerank_sharded(g, PageRankConfig(iterations=10, **kw),
                             n_devices=2, strategy="owned")
np.testing.assert_allclose(res_o.ranks, base_pr.ranks, atol=1e-6)
obs.end_run()

rep = trace_report.report(glob.glob(
    os.path.join(os.environ["SCENARIO_DIR"], "chaos_device_lost.*.trace.jsonl")
)[0])
shrinks = rep["mesh_shrinks"]
assert len(shrinks) == 3, shrinks  # one per runner (pagerank/tfidf/owned)
for s in shrinks:
    assert (s["devices_old"], s["devices_new"]) == (2, 1), s
assert not rep["exhausted"], rep["exhausted"]
print("device_lost scenario: OK — all three sharded runners survived via "
      f"mesh-shrink ({[s['site'] for s in shrinks]})")
EOF

# ---------------------------------------------------------------------------
# dataflow-core fixpoint scenario (ISSUE 9): the fixpoint primitive that
# every workload now runs over (dataflow.fixpoint.iterate inside the jit,
# dataflow.fixpoint.run_segments + the elastic ladder on the host side) is
# exercised AS a tolerance (while-loop) fixpoint on a 2-device mesh with
# logical device 1 chaos-killed mid-run: the run must finish via the
# mesh-shrink rung with ranks matching the uninterrupted fixpoint, and a
# batched personalized-PageRank fixpoint must survive a single-chip
# device loss at its delta-sync site through the same shared wiring.
echo "== chaos: dataflow fixpoint under device_lost (2-device mesh) =="
dflow_dir=$(mktemp -d)
trap 'rm -rf "$scenario_dir" "$dflow_dir"' EXIT
env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    GRAFT_TRACE_DIR="$dflow_dir" \
    SCENARIO_DIR="$dflow_dir" \
    python - <<'EOF'
import glob
import os
import sys

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.ppr import run_ppr_batch
from page_rank_and_tfidf_using_apache_spark_tpu.io import synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import run_pagerank_sharded
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

sys.path.insert(0, "tools")  # chaos.sh runs from the repo root
import trace_report

kw = dict(dangling="redistribute", init="uniform", dtype="float32")
g = synthetic_powerlaw(800, 3200, seed=9)
# tolerance run: the while-loop branch of dataflow.fixpoint.iterate
cfg = PageRankConfig(iterations=200, tol=1e-8, **kw)
base = run_pagerank_sharded(g, cfg, n_devices=2)
queries = [[int(g.node_ids[0])], [int(g.node_ids[10])]]
base_ppr = run_ppr_batch(g, PageRankConfig(iterations=30, **kw), queries)

os.environ["GRAFT_CHAOS"] = "*:device_lost@dev:1"
run = obs.start_run("chaos_dataflow_fixpoint", os.environ["SCENARIO_DIR"])
res = run_pagerank_sharded(g, cfg, n_devices=2)
np.testing.assert_allclose(res.ranks, base.ranks, atol=1e-6)

# single-chip dataflow fixpoint: device 0 dies at the PPR delta sync ->
# the checkpoint-salvage rung re-runs on the CPU backend
elastic.reset_health()
os.environ["GRAFT_CHAOS"] = "ppr_delta_sync:device_lost@dev:0"
ppr = run_ppr_batch(g, PageRankConfig(iterations=30, **kw), queries)
np.testing.assert_allclose(ppr.ranks, base_ppr.ranks, atol=1e-6)
obs.end_run()

rep = trace_report.report(glob.glob(os.path.join(
    os.environ["SCENARIO_DIR"], "chaos_dataflow_fixpoint.*.trace.jsonl"
))[0])
shrinks = rep["mesh_shrinks"]
assert len(shrinks) == 1 and (
    shrinks[0]["devices_old"], shrinks[0]["devices_new"]) == (2, 1), shrinks
# the INNER guarded delta fetch exhausts by design (its own ladder has no
# rungs — the outer segment ladder owns recovery); anything else
# exhausting means the salvage rung failed
assert set(rep["exhausted"]) <= {"ppr_delta_sync"}, rep["exhausted"]
assert any(d == "ppr_step" for d in rep["degraded"]), rep["degraded"]
print("dataflow fixpoint scenario: OK — sharded tol-fixpoint shrank 2->1 "
      "and the batched-PPR fixpoint salvaged through the shared ladder")
EOF

# ---------------------------------------------------------------------------
# staged-ingest H2D scenario (ISSUE 10): device_lost injected at the new
# ingest_h2d_put staging site — a fault on an IN-FLIGHT staged chunk —
# must walk the elastic rung on both ingest paths: the single-chip
# streaming pipeline rolls back to its last commit and replays the
# retained host chunks on the CPU rung; the 2-device sharded pipeline
# shrinks its mesh and re-slices the in-flight staged groups over the
# survivor.  Outputs must match uninterrupted runs; the trace must carry
# the per-stage ingest accounting (h2d_overlap_frac) for both.
echo "== chaos: device_lost at ingest_h2d_put (staged ingest, both paths) =="
ingest_dir=$(mktemp -d)
trap 'rm -rf "$scenario_dir" "$dflow_dir" "$ingest_dir"' EXIT
env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    GRAFT_TRACE_DIR="$ingest_dir" \
    SCENARIO_DIR="$ingest_dir" \
    python - <<'EOF'
import glob
import os
import sys

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import run_tfidf_sharded
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig

sys.path.insert(0, "tools")  # chaos.sh runs from the repo root
import trace_report

chunks = [[f"tok{i} tok{i % 5} shared word extra{i % 3}"
           for i in range(j * 2, (j + 1) * 2)] for j in range(12)]

# uninterrupted references, BEFORE the chaos plan is installed
cfg = TfidfConfig(vocab_bits=10, prefetch=2, pipeline_depth=2)
base_stream = run_tfidf_streaming(iter(chunks), cfg)
base_shard = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10),
                               n_devices=2)

run = obs.start_run("chaos_ingest_h2d", os.environ["SCENARIO_DIR"])

# single-chip: device 0 dies at the H2D put -> CPU rung, rollback+replay
os.environ["GRAFT_CHAOS"] = "ingest_h2d_put:device_lost@dev:0"
res = run_tfidf_streaming(iter(chunks), cfg)
assert res.to_dense().tobytes() == base_stream.to_dense().tobytes()

# 2-device sharded: device 1 dies at the sharded put -> mesh shrink 2->1,
# in-flight staged groups re-sliced from retained host corpora
elastic.reset_health()
os.environ["GRAFT_CHAOS"] = "ingest_h2d_put:device_lost@dev:1"
tf = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=10), n_devices=2)
np.testing.assert_allclose(tf.to_dense(), base_shard.to_dense(), atol=1e-6)
obs.end_run()

rep = trace_report.report(glob.glob(os.path.join(
    os.environ["SCENARIO_DIR"], "chaos_ingest_h2d.*.trace.jsonl"))[0])
shrinks = rep["mesh_shrinks"]
assert len(shrinks) == 1 and (
    shrinks[0]["devices_old"], shrinks[0]["devices_new"]) == (2, 1), shrinks
assert shrinks[0]["site"] == "ingest_h2d_put", shrinks
assert rep["degraded"].get("ingest_h2d_put", 0) >= 2, rep["degraded"]
assert not rep["exhausted"], rep["exhausted"]
assert rep["ingest"] and all("h2d_overlap_frac" in r for r in rep["ingest"])
print("staged-ingest scenario: OK — single-chip rolled back+replayed on "
      "the cpu rung, sharded shrank 2->1 re-slicing staged groups "
      f"(ingest runs traced: {len(rep['ingest'])})")
EOF

# ---------------------------------------------------------------------------
# segment hot-swap scenario (ISSUE 13): live traffic against a segmented
# server while delta segments commit and the background merge compacts —
# under transient dispatch chaos AND a transient merge fault.  Every
# logical request must be served exactly once (zero dropped, zero
# double-served via the abandoned-future audit), the post-start segment
# must answer with its global doc id, and the injected merge fault must
# be retried by the resilience executor (not surface, not skip the merge).
echo "== chaos: segment hot-swap under dispatch chaos + merge fault =="
seg_dir=$(mktemp -d)
trap 'rm -rf "$scenario_dir" "$dflow_dir" "$ingest_dir" "$seg_dir"' EXIT
env -u PALLAS_AXON_POOL_IPS \
    JAX_PLATFORMS=cpu \
    GRAFT_RETRY_MAX=4 \
    GRAFT_BACKOFF_BASE_S=0.01 \
    SEG_DIR="$seg_dir" \
    python - <<'EOF'
import os
import threading
import time

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import serving
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig

d = os.path.join(os.environ["SEG_DIR"], "idx")
scfg = TfidfConfig(vocab_bits=10)
docs = [f"doc{i} shared word tok{i % 7}" for i in range(12)]
out = run_tfidf(docs, scfg)
ref = sgm.seal_segment(d, out, scfg, doc_base=0)
sgm.commit_append(d, ref, scfg.config_hash())
srv = serving.TfidfServer(
    sgm.load_segment_set(d),
    serving.ServeConfig(top_k=3, max_batch=4, scoring="impacted"),
).start()

stop = threading.Event()
records = []

def client(idx):
    rng = np.random.default_rng(idx)
    while not stop.is_set():
        rec = {"ok": False, "abandoned": []}
        records.append(rec)
        for _ in range(50):
            fut = None
            try:
                fut = srv.submit([f"tok{int(rng.integers(0, 7))}", "shared"])
                fut.result(5.0)
                rec["ok"] = True
                break
            except Exception:
                if fut is not None and not fut.done:
                    rec["abandoned"].append(fut)
                time.sleep(0.01)
        time.sleep(0.005)

with chaos.inject("serve_dispatch:fail@%5;segment_merge:fail@1") as plan:
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    base = out.n_docs
    for i in range(3):
        o = run_tfidf([f"swap{i} fresh shared"], scfg)
        r = sgm.seal_segment(d, o, scfg, doc_base=base)
        sgm.commit_append(d, r, scfg.config_hash())
        base += o.n_docs
        srv.refresh_segments(sgm.load_segment_set(d))
        time.sleep(0.1)
    s, i2 = srv.query(["swap2"])
    assert float(s[0]) > 0 and int(i2[0]) == base - 1, (s, i2)
    merger = sgm.SegmentMerger(d, scfg, max_segments=1)
    while merger.merge_once():
        pass
    srv.refresh_segments(sgm.load_segment_set(d))
    s, i3 = srv.query(["swap2"])
    assert int(i3[0]) == int(i2[0])
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    assert plan.call_count("segment_merge") >= 2  # injected fail + retry
time.sleep(0.2)
srv.stop()
finished = [r for r in records if r["ok"] or len(r["abandoned"]) >= 1]
dropped = double = 0
for r in finished:
    served = int(r["ok"]) + sum(
        1 for f in r["abandoned"] if f.done and f.error is None)
    dropped += served == 0
    double += max(served - 1, 0)
assert dropped == 0 and double == 0, (dropped, double)
assert len(sgm.latest_manifest(d).segments) == 1
print("segment hot-swap scenario: OK — "
      f"{len(finished)} requests audited across 4 hot swaps + merge, "
      "dropped=0 double_served=0, merge fault retried")
EOF

# ---------------------------------------------------------------------------
# crash-recovery scenario (ISSUE 14): SIGKILL a committing ingest child at
# EVERY enumerated write boundary of the seal+commit_append protocol (the
# streaming delta-segment commit path) via tools/crash_harness.py.  After
# each kill the reloaded segment set must serve byte-identically to the
# pre-kill generation (a kill anywhere before the final LATEST flip) or
# the committed one — never a torn set — and a post-recovery
# serving.segments.gc_orphans pass must leave zero orphan tmp/unnamed
# dirs (a second sweep and an independent re-scan both find nothing).
echo "== chaos: SIGKILL mid-commit_append at every write boundary (crash harness) =="
python - <<'EOF'
import json
import subprocess
import sys

proc = subprocess.run(
    [sys.executable, "tools/crash_harness.py", "--scenarios", "append",
     "--json"],
    capture_output=True, text=True, timeout=300,
)
if proc.returncode != 0:
    sys.stderr.write(proc.stderr[-3000:])
    raise SystemExit("crash harness failed")
rep = json.loads(proc.stdout)["append"]
assert rep["boundaries"] >= 4, rep  # seal (2 renames) + commit (2 renames)
assert len(rep["kills"]) == rep["boundaries"], rep
# every pre-flip kill must serve the PRE-kill generation byte-identically
assert rep["served_pre"] >= 1 and rep["served_pre"] + rep["served_post"] \
    == rep["boundaries"], rep
print("crash-recovery scenario: OK — "
      f"{rep['boundaries']} SIGKILL point(s) through commit_append, "
      f"{rep['served_pre']} served the pre-kill generation / "
      f"{rep['served_post']} the committed one, 0 torn, 0 orphans "
      "after recovery GC")
EOF

# ---------------------------------------------------------------------------
# malformed-message fabric scenario (ISSUE 18): the wire-protocol harness
# guards the router<->replica message surface; this scenario re-runs its
# malformed / duplicate-rid / stale-floor matrix and then replays
# malformed messages at a LIVE faulted fleet — fabric_route:net_partition@2
# faults the router->replica link mid-retry, replica_query:proc_kill@3
# SIGKILLs a real replica mid-query, and replica_swap:proc_kill@1 kills a
# process at its hot-swap seam — asserting typed 400s (never a 500, never
# a hang) and a clean dropped=0 / double_served=0 audit throughout.
echo "== chaos: malformed messages at a faulted fleet (fabric_route / replica_query / replica_swap) =="
python tools/protocol_harness.py
python - <<'EOF'
import json
import subprocess
import sys
import tempfile
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path.cwd()))
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

scfg = TfidfConfig(vocab_bits=10)
docs = ["node edge graph rank walk", "graph node directed edge weight",
        "rank walk teleport damping node", "edge list sparse matrix graph"]
tmp = tempfile.mkdtemp(prefix="chaos-proto-")
out = run_tfidf(docs, scfg)
ref = sgm.seal_segment(tmp, out, scfg, doc_base=0,
                       ranks=np.ones(out.n_docs, np.float32),
                       bm25=Bm25Config())
sgm.commit_append(tmp, ref, scfg.config_hash())

MALFORMED = [b"{not json", b"[]", b"null", b'{"terms": ["node"]}']


def post_raw(port, body):
    """None = the port is dead (a SIGKILLed replica mid-respawn: that IS
    the chaos, not a protocol violation).  A live port must answer a
    typed status within the timeout — never hang, never crash."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/query", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=5.0) as r:
            return r.status
    except urllib.error.HTTPError as exc:
        return exc.code
    except urllib.error.URLError:
        return None


# a real 2-replica fleet: replica 1 SIGKILLs itself mid-query
# (replica_query:proc_kill@3); the router link is partitioned every 2nd
# hop (fabric_route:net_partition@2) while malformed bodies land at the
# live replica ports between valid routed queries
fab = fabric.ServingFabric(tmp, fabric.FabricConfig(
    replicas=2, poll_s=0.1, health_period_s=0.2, retry_limit=100,
    retry_pause_s=0.1, request_timeout_s=10.0, grace_s=10.0,
    replica_chaos=((1, "replica_query:proc_kill@3"),),
))
typed_rejections = 0
with fab:
    with chaos.inject("fabric_route:net_partition@2"):
        for i in range(10):
            scores, _ = fab.query(["node"])
            assert len(scores) > 0
            port = fab._ports[i % len(fab._ports)]
            code = post_raw(port, MALFORMED[i % len(MALFORMED)])
            assert code in (400, None), (
                f"malformed message answered {code}, want typed 400")
            if code == 400:
                typed_rejections += 1
    assert typed_rejections >= 4, typed_rejections
    audit = fab.audit()
    assert audit["dropped"] == 0, audit
    assert audit["double_served"] == 0, audit

# the hot-swap kill seam: replica_swap:proc_kill@1 must SIGKILL the
# process at its FIRST swap call — a malformed-timing fault the
# supervisor absorbs in the fleet scenario above
probe = subprocess.run(
    [sys.executable, "-c",
     "from page_rank_and_tfidf_using_apache_spark_tpu.resilience import "
     "chaos\n"
     "ctx = chaos.inject('replica_swap:proc_kill@1'); ctx.__enter__()\n"
     "chaos.on_call('replica_swap')\n"],
    timeout=60,
)
assert probe.returncode == -9, probe.returncode

print("malformed-message fabric scenario: OK — typed 400s under "
      "fabric_route:net_partition@2 + replica_query:proc_kill@3, "
      "replica_swap:proc_kill@1 kill seam verified, "
      "dropped=0 double_served=0")
EOF

# ---------------------------------------------------------------------------
# scrape-chaos scenario (ISSUE 19): the fleet observability plane must
# degrade to STALENESS, never to routing impact.  fed_scrape:net_partition
# severs every scrape mid-traffic — queries keep routing, the audit stays
# dropped=0 / double_served=0, the partitioned replicas are LABELED stale
# (never dropped from the board, last-known state kept in the aggregate)
# and recover to fresh once the partition lifts; fed_scrape:net_hang then
# stalls scrapes on the scraper thread while the query path stays live.
echo "== chaos: fleet scrape partition/hang (fed_scrape) =="
python - <<'EOF'
import os
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path.cwd()))
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

# fast scrape cadence so staleness (3 missed scrapes) is observable in
# a bounded scenario: stale after 0.6s
os.environ["GRAFT_FED_SCRAPE_S"] = "0.2"

scfg = TfidfConfig(vocab_bits=10)
docs = ["node edge graph rank walk", "graph node directed edge weight",
        "rank walk teleport damping node", "edge list sparse matrix graph"]
tmp = tempfile.mkdtemp(prefix="chaos-scrape-")
out = run_tfidf(docs, scfg)
ref = sgm.seal_segment(tmp, out, scfg, doc_base=0,
                       ranks=np.ones(out.n_docs, np.float32),
                       bm25=Bm25Config())
sgm.commit_append(tmp, ref, scfg.config_hash())

fab = fabric.ServingFabric(tmp, fabric.FabricConfig(
    replicas=2, poll_s=0.1, health_period_s=0.2, retry_limit=100,
    retry_pause_s=0.1, grace_s=10.0,
))
with fab:
    for _ in range(6):
        scores, _ = fab.query(["node"])
        assert len(scores) > 0
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        snap = fab.fleet.snapshot()
        if (snap["counters"].get("serve.requests", {}).get("total", 0) >= 1
                and not snap["fleet"]["stale"]):
            break
        time.sleep(0.2)
    assert len(snap["fleet"]["replicas"]) == 2, snap["fleet"]
    assert not snap["fleet"]["stale"], snap["fleet"]
    base_total = snap["counters"]["serve.requests"]["total"]
    assert base_total >= 1, snap["counters"]

    # every scrape severed: routing must not notice, the board must
    # label (never drop) the unreachable replicas and keep their
    # last-known contribution in the aggregate
    with chaos.inject("fed_scrape:net_partition@1+"):
        for _ in range(10):
            scores, _ = fab.query(["graph"])
            assert len(scores) > 0
        time.sleep(1.0)  # > stale_after_s (0.6): three missed scrapes
        snap2 = fab.fleet.snapshot()
        assert snap2["fleet"]["replicas"] == snap["fleet"]["replicas"], \
            snap2["fleet"]  # partitioned replicas never dropped
        assert len(snap2["fleet"]["stale"]) == 2, snap2["fleet"]
        assert snap2["fleet"]["per_replica"]["0"]["stale"], snap2["fleet"]
        kept = snap2["counters"]["serve.requests"]["total"]
        assert kept >= base_total, (kept, base_total)  # last-known kept
    assert snap2["fleet"]["scrape_errors"] >= 2, snap2["fleet"]

    # partition lifted: the scraper recovers the fleet to fresh
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if not fab.fleet.snapshot()["fleet"]["stale"]:
            break
        time.sleep(0.2)
    assert not fab.fleet.snapshot()["fleet"]["stale"]

    # hung scrapes stall the scraper thread, not the query path
    with chaos.inject("fed_scrape:net_hang@1+:400"):
        for _ in range(10):
            scores, _ = fab.query(["rank"])
            assert len(scores) > 0
    audit = fab.audit()

assert audit["dropped"] == 0, audit
assert audit["double_served"] == 0, audit
assert audit["requests"] == 26 and audit["delivered"] == 26, audit

print("scrape-chaos scenario: OK — 26/26 delivered under "
      "fed_scrape:net_partition@1+ + net_hang@1+:400, both replicas "
      "labeled stale (never dropped), aggregate kept last-known state, "
      "fleet recovered to fresh, dropped=0 double_served=0")
EOF


# cache-partition scenario (ISSUE 20): the sharded result cache must
# degrade to LOCAL COMPUTE, never to blocking or wrong bytes.  Replica 1
# boots with cache_peek:net_partition@1 + net_hang@2:2000 +
# cache_fill:net_partition@1+ in ITS environment (replica_chaos): its
# first peek at the owner partitions, the consecutive fill failure trips
# the per-peer breaker within GRAFT_CACHE_BREAKER_TRIP=2, later queries
# fail fast (no peer I/O), the half-open probe eats the 2s hang bounded
# by the 0.4s peek deadline, and the NEXT probe recloses the breaker
# with a real peer hit — byte-identical to the owner's answer.  Routed
# traffic never notices: audit dropped=0 / double_served=0.
echo "== chaos: sharded-cache peer partition/hang (cache_peek / cache_fill) =="
python - <<'EOF'
import json
import os
import sys
import tempfile
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path.cwd()))
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.serving import fabric
from page_rank_and_tfidf_using_apache_spark_tpu.serving import segments as sgm
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    TfidfConfig,
)

os.environ["GRAFT_CACHE_BREAKER_TRIP"] = "2"
os.environ["GRAFT_CACHE_BREAKER_PROBE_S"] = "1.0"
os.environ["GRAFT_CACHE_PEEK_DEADLINE_S"] = "0.4"

scfg = TfidfConfig(vocab_bits=10)
docs = ["node edge graph rank walk", "graph node directed edge weight",
        "rank walk teleport damping node", "edge list sparse matrix graph"]
tmp = tempfile.mkdtemp(prefix="chaos-cache-")
out = run_tfidf(docs, scfg)
ref = sgm.seal_segment(tmp, out, scfg, doc_base=0,
                       ranks=np.ones(out.n_docs, np.float32),
                       bm25=Bm25Config())
sgm.commit_append(tmp, ref, scfg.config_hash())

SPEC = ("cache_peek:net_partition@1;cache_peek:net_hang@2:2000;"
        "cache_fill:net_partition@1+")
fab = fabric.ServingFabric(tmp, fabric.FabricConfig(
    replicas=2, poll_s=0.1, health_period_s=0.2, retry_limit=100,
    retry_pause_s=0.1, grace_s=10.0, federation=False,
    replica_chaos=((1, SPEC),),
))

def post(port, path, doc, timeout=5.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())

def status(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=5.0) as resp:
        return json.loads(resp.read())

# single-word keys the cache ring routes to replica 0 (the owner):
# driving them at replica 1 directly exercises the non-owner peek path
ring = fabric._Ring([0, 1], 64)
owned = [[w] for w in (f"k{i}" for i in range(200))
         if ring.route(fabric.affinity_key([w], "tfidf"))[0] == 0]
assert len(owned) >= 4, len(owned)
k_hot, k_open, k_hang, k_heal = owned[0], owned[1], owned[2], owned[3]

with fab:
    p1 = fab._ports[1]
    # warm the owner through the router (affinity routes k_hot to 0)
    ref_scores, ref_docs = fab.query(k_hot)

    # peek#1 partitions, the consecutive fill failure trips the breaker
    t0 = time.perf_counter()
    r1 = post(p1, "/query", {"rid": "cc-1", "terms": k_hot,
                             "ranker": "tfidf"})
    assert time.perf_counter() - t0 < 2.0  # bounded: deadline + compute
    assert r1["scores"] == [float(s) for s in ref_scores], r1
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and status(p1)["breaker_open"] == 0:
        time.sleep(0.05)  # the tripping fill is asynchronous
    st = status(p1)
    assert st["breaker_open"] == 1, st
    assert st["peek_timeouts"] >= 1, st

    # breaker open: no peer I/O at all — fast local compute, and the
    # routed path keeps serving correct bytes mid-partition
    t0 = time.perf_counter()
    post(p1, "/query", {"rid": "cc-2", "terms": k_open, "ranker": "tfidf"})
    assert time.perf_counter() - t0 < 1.0
    for _ in range(5):
        scores, _ = fab.query(k_hot)
        assert [float(s) for s in scores] == [float(s) for s in ref_scores]

    # half-open probe #1 eats the 2s hang but blocks only for the 0.4s
    # peek deadline before falling back to local compute (re-opens)
    time.sleep(1.2)
    t0 = time.perf_counter()
    post(p1, "/query", {"rid": "cc-3", "terms": k_hang, "ranker": "tfidf"})
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.5, elapsed  # NOT the 2s hang
    st = status(p1)
    assert st["breaker_open"] == 1, st
    assert st["peek_timeouts"] >= 2, st

    # half-open probe #2 is clean: warms through the owner, recloses
    time.sleep(1.2)
    fab.query(k_heal)  # router warms the owner first
    r4 = post(p1, "/query", {"rid": "cc-4", "terms": k_heal,
                             "ranker": "tfidf"})
    st = status(p1)
    assert st["breaker_open"] == 0, st
    assert st["peer_hits"] >= 1, st
    audit = fab.audit()

assert audit["dropped"] == 0, audit
assert audit["double_served"] == 0, audit
assert audit["failed"] == 0, audit

print("cache-partition scenario: OK — non-owner served correct bytes "
      "under cache_peek:net_partition/net_hang + cache_fill:net_partition, "
      "blocking bounded by the 0.4s peek deadline (2s hang absorbed), "
      "breaker tripped at 2 consecutive failures, half-open probe "
      "reclosed it with a real peer hit, dropped=0 double_served=0")
EOF
