"""Cost-model-driven autotuner: graftlint tier 3 goes active (ISSUE 16).

The repo already carries a static cost model (analysis/cost.py budgets
declared per entry point in ``analysis/registry.ENTRY_POINTS``: pad_frac
ceilings over the real padding policies, arithmetic-intensity floors) and
a knob registry (``registry.TUNED_KNOBS``: every tunable, its candidate
domain, and the entry points it shapes).  This tool closes the loop —
the Spark counterpart is sizing ``spark.conf`` from the stage metrics
page, except here the cost model runs BEFORE anything is measured:

1. **Enumerate**: the full cartesian grid per knob *group* (knobs that
   interact are swept together; independent groups multiply nothing).
2. **Prune**: every grid point is evaluated against the SAME static
   surfaces tier 3 budgets — ``plan_partition``/``stream_pad_plan``/
   ``serve_pad_plan``/``impacted_pad_plan`` pad fractions vs the entry's
   declared ``pad_frac_ceiling``, and a bucket-padding intensity model vs
   its ``intensity_floor``.  A point that violates a budget is discarded
   **unmeasured** — the wall-clock sweep never pays for a configuration
   the lint gate would reject anyway.
3. **Measure**: survivors run the existing microbenches (the streaming
   ingest, the hybrid/sort_shuffle PageRank steps, the warm serving
   batch path) under the ``GRAFT_TUNE_BUDGET_S`` wall-clock budget.
   When the budget expires, unmeasured survivors fall back to the
   lowest-static-cost point and are flagged in the profile's
   ``measured`` evidence.
4. **Commit**: ``utils/config.write_tuned_profile`` publishes
   ``tuned_profile_<backend>.json`` — backend-provenance-stamped
   (``check_overwrite``: a CPU sweep may not clobber a TPU profile),
   staged + ``durable_replace``'d (tier-5 crash-consistency monitored),
   schema-declared in ``ARTIFACT_SCHEMAS``.  Runners resolve it through
   ``utils/config.load_tuned_profile`` / ``tuned_config`` (flag > env >
   profile > TUNABLE_DEFAULTS), and the tier-3 ``profile-drift`` check
   audits the committed artifact against the registry every lint run.

Usage::

    python tools/autotune.py --dry-run          # prune plan only, no jax
    python tools/autotune.py                    # sweep + commit profile
    python tools/autotune.py --json --out /tmp/p.json --budget-s 30
"""

from __future__ import annotations

import argparse
import itertools
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Sharded-plan probes (the owned/hybrid groups) cost pad fractions at this
# mesh width; the measured sweep forces the same host-device count so the
# pruned plan and the measured plan are the same plan.
MESH_DEVICES = 4

# Knob groups: knobs inside one group interact (their product is swept);
# groups are independent (their winners compose).  Every TUNED_KNOBS name
# must appear in exactly one group — enumerate_grid() enforces it, so a
# registry knob added without a tuning story fails loudly here instead of
# silently never being tuned.
GROUPS: tuple = (
    # measured in this order under the wall-clock budget: the two groups
    # that map straight onto bench keys (streaming tokens/s, warm serving
    # QPS) go first so a tight budget still measures what the A/B gate
    # scores; the PageRank shape knobs follow
    ("ingest", ("pack_target_tokens", "prefetch", "pipeline_depth")),
    ("serve", ("max_batch", "impact_bucket_width", "impact_warm_buckets")),
    ("hybrid", ("head_coverage", "head_row_width")),
    ("shuffle", ("shuffle_bucket_width",)),
    ("owned", ("owned_max_head",)),
)

# Calibration anchor for the sort_shuffle intensity model: the static
# model in analysis/cost.py measures 0.072 FLOP/byte at the default
# bucket width (registry comment on pagerank_step_sort_shuffle).  Other
# widths scale by dispatched-slot ratio: intensity ∝ useful/dispatched.
SHUFFLE_BASE_INTENSITY = 0.072
SHUFFLE_BASE_WIDTH = 8


def _entry_budgets():
    """pad_frac ceilings + intensity floors, straight from the registry —
    the tuner prunes against the SAME numbers tier 3 gates on, never a
    private copy."""
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
        ENTRY_POINTS,
    )

    return {
        e.name: {"pad_frac_ceiling": e.pad_frac_ceiling,
                 "intensity_floor": e.intensity_floor}
        for e in ENTRY_POINTS
    }


def _knob_domains():
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
        TUNED_KNOBS,
    )

    return {name: tuple(domain) for name, domain, _ in TUNED_KNOBS}


def enumerate_grid(domains: dict) -> dict:
    """Full cartesian candidate grid, grouped: {group: [point dict, ...]}.
    Raises if the GROUPS partition and the registry knob set drift."""
    grouped = {name for _, knobs in GROUPS for name in knobs}
    missing = set(domains) - grouped
    extra = grouped - set(domains)
    if missing or extra:
        raise ValueError(
            f"GROUPS/TUNED_KNOBS drift: unswept knobs {sorted(missing)}, "
            f"unknown knobs {sorted(extra)}"
        )
    grid = {}
    for group, knobs in GROUPS:
        points = []
        for values in itertools.product(*(domains[k] for k in knobs)):
            points.append(dict(zip(knobs, values)))
        grid[group] = points
    return grid


# ---------------------------------------------------------------------------
# Probe workloads — deterministic stand-ins for the bench's real traffic,
# shaped like it (power-law graph, ragged log-normal documents, Zipf-ish
# serving batches and posting runs).  The static cost surfaces run over
# these; seeds are fixed so a prune decision is reproducible in tests.
# ---------------------------------------------------------------------------


def build_probes() -> dict:
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        synthetic_powerlaw,
    )

    rng = np.random.default_rng(0)
    graph = synthetic_powerlaw(20_000, 160_000, seed=0)
    # ragged documents: mostly short, a heavy tail — the mix that makes
    # unpacked fixed-doc-count chunks pay for their widest member
    doc_tokens = np.clip(
        rng.lognormal(5.0, 1.1, size=2048), 16, 6000
    ).astype(int)
    # serving arrivals: bursty micro-batches (1..max), hot small head
    batch_sizes = [int(b) for b in
                   np.clip(rng.zipf(1.4, size=192), 1, 16)]
    # impacted posting-run matrix, latency mode: 4-query micro-batches of
    # 4 terms each, posting runs of 20 docs — the interactive traffic the
    # impacted path exists for.  Deliberately CONSTANT: the carried pow2
    # bucket cap makes cap*width nearly width-invariant on mixed traffic
    # (buckets trade count against width), so the static width signal
    # lives exactly where a fixed matrix exposes it — intra-bucket
    # padding vs the 2**IMPACT_MIN_BUCKET_BITS floor.
    run_lengths = [[20] * 16 for _ in range(64)]
    return {
        "graph": graph,
        "doc_tokens": [int(t) for t in doc_tokens],
        "chunk_docs": 48,
        "batch_sizes": batch_sizes,
        "run_lengths": run_lengths,
    }


def pack_counts(doc_tokens, target: int, chunk_docs: int) -> list:
    """Raw per-chunk token counts the streaming ingest would dispatch:
    ``target == 0`` keeps the caller's fixed-doc-count chunking (each
    chunk pays for the sum of its docs); ``target > 0`` greedily re-packs
    whole documents to ~target tokens per chunk — the host-side mirror of
    ``dataflow.ingest.pack_doc_chunks`` (documents never split)."""
    if target <= 0:
        return [sum(doc_tokens[i:i + chunk_docs])
                for i in range(0, len(doc_tokens), chunk_docs)]
    counts, acc = [], 0
    for t in doc_tokens:
        if acc and acc + t > target:
            counts.append(acc)
            acc = 0
        acc += t
    if acc:
        counts.append(acc)
    return counts


def shuffle_padded_slots(indegrees, width: int) -> int:
    """Dispatched slots of the sort_shuffle bucket layout at this width:
    every destination row's edges padded up to a multiple of the bucket."""
    return int(sum(((int(d) + width - 1) // width) * width
                   for d in indegrees if d))


def impacted_static_pad(run_lengths, width: int, min_bits: int = 6) -> float:
    """Whole-workload pad fraction of the impacted path at bucket width
    ``width``: intra-bucket padding (runs padded to the width) plus the
    carried pow2 bucket-cap padding (``serving.server.impacted_pad_plan``'s
    policy, floor ``2**min_bits``), as a fraction of dispatched slots."""
    cap = 0
    total_raw = 0
    total_slots = 0
    for runs in run_lengths:
        n_buckets = sum((r + width - 1) // width for r in runs)
        need = max(n_buckets, 1 << min_bits)
        cap = max(cap, 1 << math.ceil(math.log2(need)))
        total_raw += sum(runs)
        total_slots += cap * width
    return (total_slots - total_raw) / max(total_slots, 1)


# ---------------------------------------------------------------------------
# Static pruning — one evaluator per group.  Each returns a list of
# violation records [{"entry", "metric", "value", "budget"}]; an empty
# list means the point survives to measurement.
# ---------------------------------------------------------------------------


def _viol(entry, metric, value, budget):
    return {"entry": entry, "metric": metric,
            "value": round(float(value), 4), "budget": budget}


def static_violations(group: str, point: dict, probes: dict,
                      budgets: dict) -> list:
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        stream_pad_plan,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
        plan_partition,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
        serve_pad_plan,
    )

    out = []
    if group == "hybrid":
        entry = "pagerank_sharded_hybrid"
        ceiling = budgets[entry]["pad_frac_ceiling"]
        plan = plan_partition(
            probes["graph"], MESH_DEVICES, strategy="hybrid",
            head_coverage=point["head_coverage"],
            head_row_width=point["head_row_width"],
        )
        if ceiling is not None and plan.pad_frac > ceiling:
            out.append(_viol(entry, "pad_frac", plan.pad_frac, ceiling))
    elif group == "owned":
        entry = "pagerank_sharded_owned"
        ceiling = budgets[entry]["pad_frac_ceiling"]
        plan = plan_partition(
            probes["graph"], MESH_DEVICES, strategy="owned",
            owned_max_head=point["owned_max_head"],
        )
        if ceiling is not None and plan.pad_frac > ceiling:
            out.append(_viol(entry, "pad_frac", plan.pad_frac, ceiling))
    elif group == "shuffle":
        entry = "pagerank_step_sort_shuffle"
        floor = budgets[entry]["intensity_floor"]
        indeg = np.diff(probes["graph"].csr_indptr())
        base = shuffle_padded_slots(indeg, SHUFFLE_BASE_WIDTH)
        slots = shuffle_padded_slots(indeg, point["shuffle_bucket_width"])
        intensity = SHUFFLE_BASE_INTENSITY * base / max(slots, 1)
        if floor is not None and intensity < floor:
            out.append(_viol(entry, "intensity", intensity, floor))
    elif group == "ingest":
        entry = "tfidf_chunk_ingest_carry"
        ceiling = budgets[entry]["pad_frac_ceiling"]
        counts = pack_counts(probes["doc_tokens"],
                             point["pack_target_tokens"],
                             probes["chunk_docs"])
        (_, pad_frac), = stream_pad_plan(counts)
        if ceiling is not None and pad_frac > ceiling:
            out.append(_viol(entry, "pad_frac", pad_frac, ceiling))
    elif group == "serve":
        entry = "tfidf_score_query_batch"
        ceiling = budgets[entry]["pad_frac_ceiling"]
        (_, pad_frac), = serve_pad_plan(probes["batch_sizes"],
                                        point["max_batch"])
        if ceiling is not None and pad_frac > ceiling:
            out.append(_viol(entry, "pad_frac", pad_frac, ceiling))
        entry = "tfidf_score_impacted_batch"
        ceiling = budgets[entry]["pad_frac_ceiling"]
        pad = impacted_static_pad(probes["run_lengths"],
                                  point["impact_bucket_width"])
        if ceiling is not None and pad > ceiling:
            out.append(_viol(entry, "pad_frac", pad, ceiling))
    else:  # pragma: no cover - enumerate_grid guards group names
        raise ValueError(f"unknown tuning group {group!r}")
    return out


def prune(grid: dict, probes: dict, budgets: dict) -> dict:
    """Run the static cost model over the whole grid.  Returns the plan:
    {group: {"survivors": [point], "pruned": [{"point", "violations"}]}}
    plus top-level raw/pruned/survivor counts and the prune fraction."""
    plan: dict = {"groups": {}}
    raw = pruned_n = 0
    for group, points in grid.items():
        survivors, pruned = [], []
        for point in points:
            violations = static_violations(group, point, probes, budgets)
            if violations:
                pruned.append({"point": point, "violations": violations})
            else:
                survivors.append(point)
        plan["groups"][group] = {"survivors": survivors, "pruned": pruned}
        raw += len(points)
        pruned_n += len(pruned)
    plan["raw_points"] = raw
    plan["pruned_points"] = pruned_n
    plan["survivor_points"] = raw - pruned_n
    plan["prune_frac"] = pruned_n / max(raw, 1)
    return plan


def _static_rank(group: str, point: dict, probes: dict) -> float:
    """Tie-break / budget-exhausted fallback ordering: the point's worst
    static pad fraction (lower = cheaper to dispatch).  Never used to
    *reject* — only to order survivors and pick an unmeasured fallback."""
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        stream_pad_plan,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
        plan_partition,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
        serve_pad_plan,
    )

    if group == "hybrid":
        return plan_partition(probes["graph"], MESH_DEVICES,
                              strategy="hybrid",
                              head_coverage=point["head_coverage"],
                              head_row_width=point["head_row_width"]).pad_frac
    if group == "owned":
        return plan_partition(probes["graph"], MESH_DEVICES,
                              strategy="owned",
                              owned_max_head=point["owned_max_head"]).pad_frac
    if group == "shuffle":
        indeg = np.diff(probes["graph"].csr_indptr())
        slots = shuffle_padded_slots(indeg, point["shuffle_bucket_width"])
        return slots / max(probes["graph"].n_edges, 1)
    if group == "ingest":
        counts = pack_counts(probes["doc_tokens"],
                             point["pack_target_tokens"],
                             probes["chunk_docs"])
        return stream_pad_plan(counts)[0][1]
    if group == "serve":
        (_, qpad), = serve_pad_plan(probes["batch_sizes"],
                                    point["max_batch"])
        return max(qpad, impacted_static_pad(
            probes["run_lengths"], point["impact_bucket_width"]))
    raise ValueError(f"unknown tuning group {group!r}")


# ---------------------------------------------------------------------------
# Measured sweep — the existing microbench shapes, miniaturized: each
# survivor runs the real production path (run_pagerank / streaming ingest
# / the warm TfidfServer batch loop) on a probe workload, wall-clocked.
# Lower seconds = better; metric values land in the profile's evidence.
# ---------------------------------------------------------------------------


def _bench_corpus(n_docs: int = 768, seed: int = 0) -> list:
    import numpy as np

    rng = np.random.default_rng(seed)
    docs = []
    for _ in range(n_docs):
        n = int(np.clip(rng.lognormal(4.6, 0.9), 8, 1200))
        docs.append(" ".join(f"w{rng.zipf(1.3) % 20_000}" for _ in range(n)))
    return docs


def _measure_pagerank(point: dict, impl: str, graph) -> float:
    from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import (
        run_pagerank,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        PageRankConfig, tuned_config,
    )

    cfg = tuned_config(PageRankConfig, None, iterations=4, spmv_impl=impl,
                       **point)
    run_pagerank(graph, cfg)  # warm: pay the compile outside the clock
    t0 = time.perf_counter()
    run_pagerank(graph, cfg)
    return time.perf_counter() - t0


def _measure_owned(point: dict, graph) -> float:
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        pagerank_sharded,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        PageRankConfig, tuned_config,
    )

    cfg = tuned_config(PageRankConfig, None, iterations=4, **point)
    pagerank_sharded.run_pagerank_sharded(
        graph, cfg, n_devices=MESH_DEVICES, strategy="owned")
    t0 = time.perf_counter()
    pagerank_sharded.run_pagerank_sharded(
        graph, cfg, n_devices=MESH_DEVICES, strategy="owned")
    return time.perf_counter() - t0


def _measure_ingest(point: dict, docs: list) -> float:
    from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
        iter_corpus_chunks,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf_streaming,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        TfidfConfig, tuned_config,
    )

    cfg = tuned_config(TfidfConfig, None, vocab_bits=14, **point)

    def once():
        t0 = time.perf_counter()
        run_tfidf_streaming(iter_corpus_chunks(iter(docs), 48), cfg)
        return time.perf_counter() - t0

    once()  # warm
    return once()


def _measure_serve(point: dict, index, queries: list) -> float:
    from page_rank_and_tfidf_using_apache_spark_tpu import serving

    scfg = serving.ServeConfig(
        top_k=10, scoring="impacted",
        queue_depth=max(64, 2 * point["max_batch"]), **point)
    with serving.TfidfServer(index, scfg) as srv:
        warm = [srv.submit([f"warmonly{i}"]) for i in range(2 * scfg.max_batch)]
        for p in warm:
            p.result(120.0)
        t0 = time.perf_counter()
        pend = [srv.submit(q) for q in queries]
        for p in pend:
            p.result(120.0)
        return time.perf_counter() - t0


def _build_serve_probe(tmp_dir: str):
    from page_rank_and_tfidf_using_apache_spark_tpu import serving
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        TfidfConfig,
    )
    import numpy as np

    docs = _bench_corpus(n_docs=384, seed=1)
    out = run_tfidf(docs, TfidfConfig(vocab_bits=13))
    serving.save_index(tmp_dir, out, TfidfConfig(vocab_bits=13))
    index = serving.load_index(tmp_dir)
    rng = np.random.default_rng(2)
    queries = [[f"w{rng.zipf(1.3) % 20_000}"
                for _ in range(int(rng.integers(2, 5)))]
               for _ in range(128)]
    return index, queries


def _measure_signature(group: str, point: dict) -> tuple:
    """Points that dispatch identical work share one measurement.  On the
    probe index the impacted warmup's carried cap never approaches the
    smallest ``impact_warm_buckets`` candidate, so warm-bucket variants
    are shape-identical at this scale — collapse them instead of paying
    the serve bench three times per (batch, width) pair."""
    if group == "serve":
        return (point["max_batch"], point["impact_bucket_width"],
                min(point["impact_warm_buckets"], 1024))
    return tuple(sorted(point.items()))


def measure_survivors(plan: dict, probes: dict, budget_s: float,
                      log=print) -> tuple:
    """Wall-clock the survivors group by group, best point wins its
    group's knobs.  Returns (knobs, evidence): every declared knob gets a
    value (measured winner, or lowest-static-cost fallback when the
    budget expired first) — the committed profile must carry the FULL
    registry knob set or tier 3's profile-drift check fires."""
    import shutil
    import tempfile

    deadline = time.monotonic() + budget_s
    serve_probe = None
    serve_dir = None
    ingest_docs = None
    knobs: dict = {}
    evidence: dict = {"budget_s": budget_s, "groups": {}}

    def expired():
        return time.monotonic() >= deadline

    try:
        for group, _ in GROUPS:
            entry = plan["groups"][group]
            survivors = sorted(
                entry["survivors"],
                key=lambda p: _static_rank(group, p, probes))
            gev = {"measured": [], "fallback": False}
            best = None
            best_secs = None
            sig_cache: dict = {}
            for point in survivors:
                if expired():
                    break
                sig = _measure_signature(group, point)
                if sig in sig_cache:
                    gev["measured"].append({"point": point,
                                            "secs": round(sig_cache[sig], 4),
                                            "shared": True})
                    continue
                try:
                    if group in ("hybrid", "shuffle", "owned"):
                        # measure on the SAME graph the static prune
                        # costed — a winner picked at one scale need not
                        # hold at another (degree-head coverage shifts
                        # with the power-law tail)
                        bench_graph = probes["graph"]
                        if group == "owned":
                            secs = _measure_owned(point, bench_graph)
                        else:
                            impl = ("hybrid" if group == "hybrid"
                                    else "sort_shuffle")
                            secs = _measure_pagerank(point, impl,
                                                     bench_graph)
                    elif group == "ingest":
                        if ingest_docs is None:
                            ingest_docs = _bench_corpus()
                        secs = _measure_ingest(point, ingest_docs)
                    elif group == "serve":
                        if serve_probe is None:
                            serve_dir = tempfile.mkdtemp(
                                prefix="autotune_idx_")
                            serve_probe = _build_serve_probe(serve_dir)
                        secs = _measure_serve(point, *serve_probe)
                    else:  # pragma: no cover
                        raise ValueError(group)
                except Exception as exc:  # noqa: BLE001 - one bad point
                    # must not kill the sweep; record it and move on
                    gev["measured"].append(
                        {"point": point, "error": f"{type(exc).__name__}: {exc}"})
                    continue
                sig_cache[sig] = secs
                gev["measured"].append({"point": point,
                                        "secs": round(secs, 4)})
                if best_secs is None or secs < best_secs:
                    best, best_secs = point, secs
                log(f"[autotune] {group} {point} -> {secs:.3f}s")
            if best is not None:
                # shape-identical variants shared the winning measurement:
                # among them, prefer the point closest to the hand-picked
                # defaults — a knob only moves off its default when the
                # sweep actually distinguished it
                from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (  # noqa: E501
                    TUNABLE_DEFAULTS,
                )
                best_sig = _measure_signature(group, best)
                ties = [m["point"] for m in gev["measured"]
                        if "secs" in m
                        and _measure_signature(group, m["point"]) == best_sig]
                best = min(ties or [best], key=lambda p: sum(
                    1 for k, v in p.items() if TUNABLE_DEFAULTS.get(k) != v))
            if best is None:
                # budget expired (or every measurement failed) before this
                # group produced a number: commit the lowest-static-cost
                # survivor, flagged so the evidence says "not measured"
                best = survivors[0] if survivors else None
                gev["fallback"] = True
            if best is None:  # pragma: no cover - empty survivor set
                raise RuntimeError(
                    f"group {group!r}: every grid point was pruned — the "
                    "probe workload and the registry budgets disagree")
            gev["winner"] = best
            gev["winner_secs"] = best_secs
            knobs.update(best)
            evidence["groups"][group] = gev
    finally:
        if serve_dir is not None:
            shutil.rmtree(serve_dir, ignore_errors=True)
    return knobs, evidence


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Cost-model-pruned knob sweep; commits "
                    "tuned_profile_<backend>.json")
    ap.add_argument("--dry-run", action="store_true",
                    help="enumerate + prune only: print the plan (raw/"
                         "pruned/survivor counts per group), measure "
                         "nothing, write nothing")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output on stdout")
    ap.add_argument("--backend", default=None,
                    help="stamp/write for this backend (default: the "
                         "live jax backend, or utils.config."
                         "default_backend() under --dry-run)")
    ap.add_argument("--out", default=None,
                    help="profile path (default: the committed "
                         "tuned_profile_<backend>.json at the repo root)")
    ap.add_argument("--budget-s", type=float, default=None,
                    help="measured-sweep wall-clock budget in seconds "
                         "(default: $GRAFT_TUNE_BUDGET_S, then 60)")
    ap.add_argument("--force", action="store_true",
                    help="allow overwriting a TPU-stamped profile from a "
                         "non-TPU sweep (utils/artifacts.py guard)")
    args = ap.parse_args(argv)

    budget_s = args.budget_s
    if budget_s is None:
        budget_s = float(os.environ.get("GRAFT_TUNE_BUDGET_S", "60") or 60)

    # The owned group's sharded microbench needs a real multi-device mesh;
    # on CPU that is the host-platform device-count flag, which only works
    # if it is set before jax initializes — so set it before ANY package
    # import that might pull jax in.
    if not args.dry_run and "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count"
                f"={MESH_DEVICES}").strip()

    from page_rank_and_tfidf_using_apache_spark_tpu.utils import (
        artifacts, config,
    )

    def log(msg):
        print(msg, file=sys.stderr, flush=True)

    domains = _knob_domains()
    budgets = _entry_budgets()
    probes = build_probes()
    grid = enumerate_grid(domains)
    plan = prune(grid, probes, budgets)
    log(f"[autotune] grid: {plan['raw_points']} raw points, "
        f"{plan['pruned_points']} pruned by the static cost model "
        f"({plan['prune_frac']:.0%}), {plan['survivor_points']} to measure")

    if args.dry_run:
        backend = args.backend or config.default_backend()
        doc = {"backend": backend, "plan": plan, "dry_run": True}
        print(json.dumps(doc, indent=None if args.json else 2,
                         sort_keys=True))
        return 0

    import jax

    backend = args.backend or jax.default_backend()
    out_path = args.out or config.profile_path(backend)
    try:
        # fail FAST, before the sweep spends its budget, if the commit
        # would downgrade a TPU-stamped profile
        artifacts.check_overwrite(out_path, backend, force=args.force)
    except artifacts.ProvenanceError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    t0 = time.monotonic()
    knobs, evidence = measure_survivors(plan, probes, budget_s, log=log)
    evidence["sweep_secs"] = round(time.monotonic() - t0, 2)
    evidence["prune"] = {
        "raw_points": plan["raw_points"],
        "pruned_points": plan["pruned_points"],
        "prune_frac": round(plan["prune_frac"], 4),
    }

    missing = set(domains) - set(knobs)
    if missing:  # pragma: no cover - GROUPS partition guard upstream
        raise RuntimeError(f"sweep left knobs untuned: {sorted(missing)}")

    record = config.write_tuned_profile(
        out_path, backend, knobs, measured=evidence, force=args.force)
    log(f"[autotune] committed {out_path} (backend={backend})")
    if args.json:
        print(json.dumps({"path": out_path, "record": record, "plan": {
            "raw_points": plan["raw_points"],
            "pruned_points": plan["pruned_points"],
            "prune_frac": plan["prune_frac"],
        }}, sort_keys=True))
    else:
        print(json.dumps(record["knobs"], indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
