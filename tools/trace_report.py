#!/usr/bin/env python3
"""Reconstruct a run's accounting from an obs trace file.

Reads the crash-safe JSONL trace the ``obs`` subsystem writes
(``<name>.<pid>.trace.jsonl``) and answers "where did the time go" — the
Spark-web-UI question — even for a run that was SIGKILLed mid-stream:

- per-phase wall-time **breakdown** (top-level spans on the main thread,
  grouped by name; incomplete spans are credited with their elapsed time
  up to the last event on record and flagged),
- the per-chunk **timeline** (``tfidf.chunk`` spans → chunk index, wall
  seconds, start offset),
- **retry / chaos / watchdog / degraded / exhausted tallies per site**
  (the resilience executor's event stream),
- the **last incomplete span** — the phase the process died inside,
- the run manifest (sibling ``.manifest.json``) and run-end summary when
  present.

Deliberately stdlib-only with no package imports: the bench parent (which
must never import jax) imports this module to turn child trace artifacts
into the BENCH record's ``extra.breakdown`` — no stderr scraping.

Usage::

    python tools/trace_report.py RUN.trace.jsonl [--json]
    python tools/trace_report.py TRACE_DIR [--json]   # stitch: group every
        # child run under its GRAFT_TRACE_PARENT id into one round tree
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any


def load_events(path: str) -> tuple[list[dict[str, Any]], int]:
    """Parse a JSONL trace; returns (events, bad_line_count).  A SIGKILL
    mid-write truncates at most the final line — skip unparseable lines
    rather than failing the whole post-mortem."""
    events: list[dict[str, Any]] = []
    bad = 0
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                evt = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(evt, dict) and "kind" in evt:
                events.append(evt)
            else:
                bad += 1
    return events, bad


def pair_spans(
    events: list[dict[str, Any]], last_t: float
) -> tuple[list[dict[str, Any]], list[dict[str, Any]]]:
    """Match span_begin/span_end into span records.

    Returns (complete, incomplete).  Incomplete spans (begin with no end —
    the process died inside them) get ``secs`` = elapsed up to the last
    event on record and ``complete: False``.
    """
    open_spans: dict[int, dict[str, Any]] = {}
    complete: list[dict[str, Any]] = []
    for evt in events:
        if evt["kind"] == "span_begin":
            open_spans[evt["span"]] = {
                "span": evt["span"],
                "parent": evt.get("parent"),
                "name": evt.get("name", "?"),
                "attrs": evt.get("attrs") or {},
                "thread": evt.get("thread"),
                "t0": evt["t"],
                "complete": True,
            }
        elif evt["kind"] == "span_end":
            rec = open_spans.pop(evt["span"], None)
            if rec is None:  # end without begin: trace started mid-run
                rec = {
                    "span": evt["span"],
                    "parent": evt.get("parent"),
                    "name": evt.get("name", "?"),
                    "attrs": evt.get("attrs") or {},
                    "thread": evt.get("thread"),
                    "t0": evt["t"] - evt.get("secs", 0.0),
                    "complete": True,
                }
            rec["secs"] = evt.get("secs", 0.0)
            rec["status"] = evt.get("status", "ok")
            complete.append(rec)
    incomplete = []
    for rec in open_spans.values():
        rec["complete"] = False
        rec["secs"] = max(last_t - rec["t0"], 0.0)
        rec["status"] = "incomplete"
        incomplete.append(rec)
    return complete, incomplete


def _tally(events: list[dict[str, Any]], kind: str) -> dict[str, int]:
    out: dict[str, int] = {}
    for evt in events:
        if evt["kind"] == kind:
            site = str(evt.get("site", evt.get("name", "?")))
            out[site] = out.get(site, 0) + 1
    return out


def _pct(sorted_xs: list[float], p: float) -> float | None:
    """Nearest-rank percentile over an ascending list (None when empty)."""
    if not sorted_xs:
        return None
    i = min(len(sorted_xs) - 1, max(0, -(-int(p * 100) * len(sorted_xs) // 100) - 1))
    return sorted_xs[i]


def report(path: str) -> dict[str, Any]:
    """Full accounting for one trace file, as a JSON-ready dict."""
    events, bad = load_events(path)
    if not events:
        return {"trace": path, "events": 0, "bad_lines": bad, "empty": True}
    t_first = events[0]["t"]
    t_last = max(e["t"] for e in events)
    run_start = next((e for e in events if e["kind"] == "run_start"), None)
    run_end = next((e for e in events if e["kind"] == "run_end"), None)
    # A sink_detached tombstone means the trace was truncated by a sink
    # write error, NOT by the process dying — keep the two separable.
    sink_lost = any(e["kind"] == "sink_detached" for e in events)
    t0 = run_start["t"] if run_start else t_first
    wall = (run_end["t"] if run_end else t_last) - t0

    spans, incomplete = pair_spans(events, t_last)
    all_spans = spans + incomplete

    # Breakdown: top-level (parentless) spans on the thread that owns the
    # run — concurrent worker-thread spans (the streaming tokenizer)
    # overlap the main timeline and would double-count wall time.
    main_thread = (run_start or events[0]).get("thread")
    breakdown: dict[str, float] = {}
    incomplete_phases: list[str] = []
    for rec in all_spans:
        if rec["parent"] is not None or rec.get("thread") != main_thread:
            continue
        breakdown[rec["name"]] = breakdown.get(rec["name"], 0.0) + rec["secs"]
        if not rec["complete"]:
            incomplete_phases.append(rec["name"])

    # Per-span-name aggregates (all threads, all depths).
    span_stats: dict[str, dict[str, float]] = {}
    for rec in all_spans:
        s = span_stats.setdefault(rec["name"], {"count": 0, "secs": 0.0})
        s["count"] += 1
        s["secs"] += rec["secs"]

    # Per-device timings (ROADMAP hardening (d)): the sharded ingest
    # publishes one ``device_timing`` event per super-chunk with each
    # device's shard-ready time, keyed by step — joined into the chunk
    # timeline below so a straggling device is visible per chunk.
    device_timings = {
        e.get("step"): e
        for e in events
        if e["kind"] == "device_timing" and e.get("step") is not None
    }

    chunks = sorted(
        (
            {
                "chunk": rec["attrs"].get("chunk"),
                "secs": rec["secs"],
                "t_rel": rec["t0"] - t0,
                "complete": rec["complete"],
                **(
                    {
                        "devices": device_timings[rec["attrs"]["step"]].get("devices"),
                        "per_device_secs": device_timings[rec["attrs"]["step"]].get("secs"),
                    }
                    if rec["name"] == "tfidf.super_chunk"
                    and rec["attrs"].get("step") in device_timings
                    else {}
                ),
            }
            for rec in all_spans
            if rec["name"] in ("tfidf.chunk", "tfidf.super_chunk")
            and "chunk" in rec["attrs"]
        ),
        key=lambda c: c["t_rel"],
    )

    # Elastic mesh-shrink transitions (resilience/elastic.py): one span per
    # degradation step, carrying old/new device counts and the ladder rung
    # taken — what makes a degraded bench round attributable from the
    # artifact alone ("why did throughput halve at +312s?" -> "8->4 shrink").
    mesh_shrinks = sorted(
        (
            {
                "site": rec["attrs"].get("site"),
                "ladder": rec["attrs"].get("ladder"),
                "devices_old": rec["attrs"].get("devices_old"),
                "devices_new": rec["attrs"].get("devices_new"),
                "t_rel": rec["t0"] - t0,
                "secs": rec["secs"],
                "complete": rec["complete"],
            }
            for rec in all_spans
            if rec["name"] == "mesh.shrink"
        ),
        key=lambda s: s["t_rel"],
    )
    shrink_sites: dict[str, int] = {}
    for s in mesh_shrinks:
        site = str(s["site"] or "?")
        shrink_sites[site] = shrink_sites.get(site, 0) + 1

    # Strategy decisions (ISSUE 9 satellite): auto_select_strategy and
    # plan_partition publish WHAT was chosen and the measured inputs that
    # drove the choice — "why did this run pick hybrid" is answerable
    # from the artifact alone.
    strategy = {
        "decisions": [
            {k: v for k, v in e.items() if k not in ("kind", "t", "thread")}
            for e in events
            if e["kind"] in ("strategy_decision", "auto_strategy")
        ],
        "plans": [
            {k: v for k, v in e.items() if k not in ("kind", "t", "thread")}
            for e in events
            if e["kind"] == "partition_plan"
        ],
    }
    if not strategy["decisions"] and not strategy["plans"]:
        strategy = None

    last_incomplete = None
    if incomplete:
        deepest = max(incomplete, key=lambda r: r["t0"])
        last_incomplete = {
            "name": deepest["name"],
            "span": deepest["span"],
            "attrs": deepest["attrs"],
            "elapsed_secs": deepest["secs"],
            "thread": deepest.get("thread"),
        }

    # Staged-ingest pipeline accounting (ISSUE 10): chunked_ingest
    # publishes one ``ingest_overlap`` event per run with the per-stage
    # wall seconds (tokenize / H2D staging / compute) and the
    # h2d_overlap_frac gauge — the fraction of H2D staging time spent
    # while chunk compute was in flight.  A traced process may hold
    # several ingest runs (the bench child runs serial + pipelined
    # passes); each is reported, in order.
    ingest_runs = [
        {k: v for k, v in e.items() if k not in ("kind", "t", "thread")}
        for e in events
        if e["kind"] == "ingest_overlap"
    ]

    # SLO record (ISSUE 11): the soak harness publishes ONE ``slo`` event
    # at scoring time — served p50/p99 under ingest load, error-budget
    # burn, time-to-recover, dropped/double-served.  The last one wins (a
    # trace normally holds exactly one).
    slo_events = [
        {k: v for k, v in e.items()
         if k not in ("kind", "t", "wall", "thread", "seq")}
        for e in events
        if e["kind"] == "slo"
    ]
    slo = slo_events[-1] if slo_events else None

    # Serving-path accounting (ISSUE 8): per-request ``serve_request``
    # events carry queue-wait and total latency; the serve.pad/dispatch/
    # pull spans give the phase split.  Present only for serve runs.
    serve_reqs = [e for e in events if e["kind"] == "serve_request"]
    serving = None
    if serve_reqs:
        lat = sorted(e.get("total_s", 0.0) for e in serve_reqs)
        qw = sorted(e.get("queue_wait_s", 0.0) for e in serve_reqs)
        serving = {
            "requests": len(serve_reqs),
            "cache_hits": sum(e.get("cache") == "hit" for e in serve_reqs),
            "errors": sum(1 for e in serve_reqs if e.get("error")),
            "latency_p50_s": _pct(lat, 0.50),
            "latency_p99_s": _pct(lat, 0.99),
            "queue_wait_p50_s": _pct(qw, 0.50),
            "queue_wait_p99_s": _pct(qw, 0.99),
            "phases": {
                name.split(".", 1)[1]: round(span_stats[name]["secs"], 4)
                for name in ("serve.pad", "serve.dispatch", "serve.pull")
                if name in span_stats
            },
        }

    # Serving-fabric accounting (ISSUE 17): the router process publishes
    # the fleet's lifecycle — spawns, health transitions, supervisor
    # respawns (with measured recovery), the committed generation-floor
    # timeline, rolling restarts, and a periodic per-replica stats fold
    # (the replicas' own numbers, read over /status).  Rendered as the
    # "fabric" section; tools/trace_diff.py regresses the fleet SLO
    # record between rounds.
    fab_events = [e for e in events
                  if str(e.get("kind", "")).startswith("fabric_")]
    fabric = None
    if fab_events:
        start = next((e for e in fab_events
                      if e["kind"] == "fabric_start"), None)
        stop_evt = next((e for e in reversed(fab_events)
                         if e["kind"] == "fabric_stop"), None)
        replica_stats: dict[Any, dict[str, Any]] = {}
        for e in fab_events:
            if e["kind"] == "fabric_replica_stats":
                replica_stats[e.get("replica")] = {
                    k: e.get(k)
                    for k in ("requests", "executions", "replays",
                              "p50_ms", "p99_ms", "generation", "floor")
                }
        for rid, st in replica_stats.items():
            st["qps"] = (round(st["requests"] / wall, 3)
                         if st.get("requests") and wall > 0 else None)
        fabric = {
            "replicas": start.get("replicas") if start else None,
            "spawns": sum(e["kind"] == "fabric_spawn" for e in fab_events),
            "kills": sum(e["kind"] == "fabric_kill" for e in fab_events),
            "suspects": sum(e["kind"] == "fabric_suspect"
                            for e in fab_events),
            "respawns": [
                {"replica": e.get("replica"), "pid": e.get("pid"),
                 "recovery_s": e.get("recovery_s"),
                 "t_rel": round(e["t"] - t0, 3)}
                for e in fab_events if e["kind"] == "fabric_respawn"
            ],
            "floor_timeline": [
                {"floor": e.get("floor"), "t_rel": round(e["t"] - t0, 3)}
                for e in fab_events if e["kind"] == "fabric_floor"
            ],
            "rolls": sum(e["kind"] == "fabric_rolled" for e in fab_events),
            # Drain-handoff forensics (ISSUE 20): rolls split by
            # mechanism (socket handoff vs the retry-carried fallback)
            # and the per-replica handoff phase timeline — spawn →
            # successor_ready → drain on the router side, the replica's
            # own drain_begin/drain_done interleaved when its trace is
            # folded in.  `totals.roll_retries` (from fabric_stop) is
            # the handoff acceptance gate: 0 when every roll handed off.
            "handoff_rolls": sum(
                e["kind"] == "fabric_rolled" and bool(e.get("handoff"))
                for e in fab_events),
            "retry_rolls": sum(
                e["kind"] == "fabric_rolled" and not e.get("handoff")
                for e in fab_events),
            "drain_timeline": sorted(
                [{"replica": e.get("replica"), "phase": e.get("phase"),
                  "pid": e.get("pid"), "t_rel": round(e["t"] - t0, 3)}
                 for e in fab_events if e["kind"] == "fabric_handoff"]
                + [{"replica": e.get("replica"), "phase": "drain_begin",
                    "pid": e.get("pid"), "t_rel": round(e["t"] - t0, 3)}
                   for e in fab_events
                   if e["kind"] == "fabric_drain_begin"]
                + [{"replica": e.get("replica"), "phase": "drain_done",
                    "drain_s": e.get("drain_s"),
                    "t_rel": round(e["t"] - t0, 3)}
                   for e in fab_events
                   if e["kind"] == "fabric_drain_done"],
                key=lambda row: row["t_rel"]),
            "replica_stats": replica_stats,
            "totals": (
                {k: v for k, v in stop_evt.items()
                 if k not in ("kind", "t", "wall", "thread", "seq")}
                if stop_evt else None
            ),
        }

    # Sharded-cache accounting (ISSUE 20): per-replica local/peer hit
    # rates folded from the router's periodic /status scrape, the
    # breaker transition timeline (cache_breaker events), and the peek
    # latency histogram from the run-end summary.  peer_hit_rate is
    # peer_hits over peek ATTEMPTS (hits + misses + timeouts) — skipped
    # open-breaker peeks never reached the wire and are not attempts.
    cache = None
    breaker_events = [e for e in events if e.get("kind") == "cache_breaker"]
    cache_stats: dict[Any, dict[str, Any]] = {}
    for e in events:
        if e.get("kind") == "fabric_replica_stats" and \
                e.get("peer_hits") is not None:
            hits = int(e.get("cache_hits") or 0)
            ph = int(e.get("peer_hits") or 0)
            pm = int(e.get("peer_misses") or 0)
            pt = int(e.get("peek_timeouts") or 0)
            reqs = int(e.get("requests") or 0)
            cache_stats[e.get("replica")] = {
                "requests": reqs,
                "local_hits": hits,
                "local_hit_rate": round(hits / reqs, 4) if reqs else None,
                "peer_hits": ph,
                "peer_misses": pm,
                "peek_timeouts": pt,
                "peer_hit_rate": (round(ph / (ph + pm + pt), 4)
                                  if ph + pm + pt else None),
                "fills": int(e.get("fills") or 0),
                "peer_stores": int(e.get("peer_stores") or 0),
                "breaker_open": e.get("breaker_open"),
            }
    if cache_stats or breaker_events:
        summary_h = ((run_end or {}).get("summary") or {}).get(
            "histograms") or {}
        cache = {
            "replica_stats": cache_stats,
            "peek_latency": summary_h.get("cache_peek_s"),
            "breaker_transitions": [
                {"replica": e.get("replica"), "peer": e.get("peer"),
                 "old": e.get("old"), "new": e.get("new"),
                 "t_rel": round(e["t"] - t0, 3)}
                for e in breaker_events
            ],
        }

    # Autoscaling timeline (ISSUE 19): the burn-rate autoscaler publishes
    # one ``autoscale`` event per ACTION (holds are silent) carrying the
    # measured inputs that drove it — burn rates, queue p99, offered
    # rate, fleet size before/after.  Flaps (direction reversals between
    # consecutive actions) are recomputed from the timeline so the
    # trace_diff gate never trusts a counter the process could misreport;
    # fed_scrape_error tallies ride along (scrape chaos forensics).
    as_events = [e for e in events if e["kind"] == "autoscale"]
    autoscale = None
    if as_events or any(e["kind"] == "autoscale_start" for e in events):
        timeline = []
        for e in as_events:
            row = {k: v for k, v in e.items()
                   if k not in ("kind", "t", "wall", "thread", "seq")}
            row["t_rel"] = round(e["t"] - t0, 3)
            timeline.append(row)
        autoscale = {
            "actions": len(as_events),
            "ups": sum(e.get("action") == "up" for e in as_events),
            "downs": sum(e.get("action") == "down" for e in as_events),
            "flaps": sum(
                1 for prev, cur in zip(as_events, as_events[1:])
                if prev.get("action") != cur.get("action")
            ),
            "errors": sum(e["kind"] == "autoscale_error" for e in events),
            "scrape_errors": sum(
                e["kind"] == "fed_scrape_error" for e in events
            ),
            "timeline": timeline,
        }

    manifest = None
    mpath = path.replace(".trace.jsonl", ".manifest.json")
    if mpath != path and os.path.exists(mpath):
        try:
            with open(mpath, "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            manifest = None

    return {
        "trace": path,
        "manifest": manifest,
        "trace_parent": (
            (run_start or {}).get("trace_parent")
            or (manifest or {}).get("trace_parent")
        ),
        "serving": serving,
        "slo": slo,
        "fabric": fabric,
        "cache": cache,
        "autoscale": autoscale,
        "events": len(events),
        "bad_lines": bad,
        "complete": run_end is not None,
        "status": (
            run_end.get("status")
            if run_end
            else ("trace-lost" if sink_lost else "killed")
        ),
        "wall_secs": wall,
        "breakdown": breakdown,
        "ingest": ingest_runs or None,
        "incomplete_phases": incomplete_phases,
        "spans": span_stats,
        "chunks": chunks,
        "retries": _tally(events, "retry"),
        "backoffs": _tally(events, "backoff"),
        "chaos": _tally(events, "chaos"),
        "watchdog": _tally(events, "watchdog"),
        "degraded": _tally(events, "degraded"),
        "exhausted": _tally(events, "exhausted"),
        "mesh_shrinks": mesh_shrinks,
        "shrinks": shrink_sites,
        "strategy": strategy,
        "checkpoints": sum(e["kind"] == "checkpoint_save" for e in events),
        "last_incomplete": last_incomplete,
        "summary": run_end.get("summary") if run_end else None,
    }


# Span names that wrap exactly one guarded host sync (a device->host pull
# or fence).  Their durations are the empirical distribution of healthy
# sync times — what the adaptive GRAFT_SYNC_DEADLINE_S knob (bench.py) is
# calibrated against.
SYNC_SPAN_NAMES = frozenset(
    {
        "tfidf.chunk",
        "tfidf.super_chunk",
        "tfidf.finalize",
        "pagerank.ckpt_pull",
        "pagerank.result_pull",
    }
)


def sync_p99(path: str, span_names: frozenset = SYNC_SPAN_NAMES) -> float | None:
    """p99 duration (seconds) over the completed sync-flavored spans in a
    trace, or None when the trace holds none.  bench.py feeds a PRIOR
    round's value into the next round's child sync deadline
    (``max(knob, 3 * p99)``), so the watchdog tracks the tunnel's actually
    observed behavior instead of a guess."""
    events, _ = load_events(path)
    secs = sorted(
        e.get("secs", 0.0)
        for e in events
        if e["kind"] == "span_end" and e.get("name") in span_names
    )
    return _pct(secs, 0.99)


def stitch(root: str) -> dict[str, Any]:
    """Reassemble one trace TREE from a directory of per-process artifacts
    (ROADMAP hardening (c)): every ``*.trace.jsonl`` under ``root``
    (recursively) whose run adopted a ``GRAFT_TRACE_PARENT`` id is grouped
    under that id; runs without one group under ``"(unparented)"``.  The
    result is the whole-round accounting the bench parent could never see
    from any single child: per-child wall/status/breakdown plus the round
    totals, keyed by the id the parent exported."""
    import glob

    paths = sorted(
        glob.glob(os.path.join(root, "**", "*.trace.jsonl"), recursive=True),
        key=os.path.getmtime,
    )
    trees: dict[str, dict[str, Any]] = {}
    for p in paths:
        try:
            rep = report(p)
        except OSError:
            continue
        if rep.get("empty"):
            continue
        parent = rep.get("trace_parent") or "(unparented)"
        tree = trees.setdefault(
            parent, {"trace_parent": parent, "children": [],
                     "wall_secs": 0.0, "retries": 0, "checkpoints": 0}
        )
        man = rep.get("manifest") or {}
        tree["children"].append({
            "name": man.get("name") or os.path.basename(p).split(".")[0],
            "pid": man.get("pid"),
            "trace": p,
            "status": rep["status"],
            "wall_secs": round(rep["wall_secs"], 3),
            "breakdown": {k: round(v, 3) for k, v in rep["breakdown"].items()},
            "serving": rep.get("serving"),
            "slo": rep.get("slo"),
            "fabric": rep.get("fabric"),
            "cache": rep.get("cache"),
        })
        tree["wall_secs"] = round(tree["wall_secs"] + rep["wall_secs"], 3)
        tree["retries"] += sum(rep["retries"].values())
        tree["checkpoints"] += rep["checkpoints"]
    return {"root": root, "trees": sorted(
        trees.values(), key=lambda t: -len(t["children"])
    )}


def render_stitched(doc: dict[str, Any]) -> str:
    lines = [f"stitched trace root: {doc['root']}"]
    if not doc["trees"]:
        lines.append("  (no trace artifacts found)")
    for tree in doc["trees"]:
        lines.append(
            f"trace {tree['trace_parent']}: {len(tree['children'])} child "
            f"run(s), {tree['wall_secs']:.3f}s total wall, "
            f"{tree['retries']} retries, {tree['checkpoints']} checkpoints"
        )
        for ch in tree["children"]:
            top = sorted(ch["breakdown"].items(), key=lambda kv: -kv[1])[:3]
            phases = ", ".join(f"{k} {v:.2f}s" for k, v in top)
            lines.append(
                f"  {ch['name']:16s} pid={ch['pid']} {ch['status']:10s} "
                f"{ch['wall_secs']:9.3f}s  {phases}"
            )
            if ch.get("serving"):
                sv = ch["serving"]
                lines.append(
                    f"  {'':16s} serving: {sv['requests']} req, "
                    f"{sv['cache_hits']} hits, p50 "
                    f"{(sv['latency_p50_s'] or 0) * 1e3:.1f}ms p99 "
                    f"{(sv['latency_p99_s'] or 0) * 1e3:.1f}ms"
                )
            if ch.get("fabric"):
                fb = ch["fabric"]
                lines.append(
                    f"  {'':16s} fabric: {fb.get('replicas')} replica(s), "
                    f"{len(fb.get('respawns') or [])} respawn(s), "
                    f"{fb.get('rolls')} rolled"
                )
    return "\n".join(lines)


def render_human(rep: dict[str, Any]) -> str:
    if rep.get("empty"):
        return f"{rep['trace']}: empty trace ({rep['bad_lines']} bad line(s))"
    lines = [f"trace: {rep['trace']}"]
    man = rep.get("manifest")
    if man:
        lines.append(
            f"run: {man.get('name')} pid={man.get('pid')} "
            f"backend={man.get('backend')} git={man.get('git_sha')} "
            f"status={man.get('status')}"
        )
    lines.append(
        f"events: {rep['events']} ({rep['bad_lines']} bad), "
        f"wall {rep['wall_secs']:.3f}s, "
        + ("run completed" if rep["complete"] else "RUN DID NOT END (killed?)")
    )
    if rep["breakdown"]:
        lines.append("phase breakdown (top-level, main thread):")
        total = sum(rep["breakdown"].values())
        for name, secs in sorted(rep["breakdown"].items(), key=lambda kv: -kv[1]):
            mark = "  [incomplete]" if name in rep["incomplete_phases"] else ""
            pct = 100.0 * secs / rep["wall_secs"] if rep["wall_secs"] > 0 else 0.0
            lines.append(f"  {name:32s} {secs:10.3f}s {pct:5.1f}%{mark}")
        lines.append(f"  {'(phases total)':32s} {total:10.3f}s")
    if rep.get("ingest"):
        lines.append("ingest pipeline (staged: tokenize | h2d | compute):")
        for run in rep["ingest"]:
            lines.append(
                f"  {run.get('chunks', '?'):>4} chunk(s)  "
                f"tokenize {run.get('tokenize_secs', 0.0):8.3f}s  "
                f"h2d {run.get('h2d_secs', 0.0):8.3f}s  "
                f"compute {run.get('compute_secs', 0.0):8.3f}s  "
                f"h2d_overlap {100.0 * run.get('h2d_overlap_frac', 0.0):5.1f}%"
                f"  (prefetch={run.get('depth')}, "
                f"pipeline_depth={run.get('pipeline_depth')})"
            )
    if rep["chunks"]:
        done = [c for c in rep["chunks"] if c["complete"]]
        lines.append(
            f"chunks: {len(done)} complete of {len(rep['chunks'])} started"
        )
        worst = sorted(done, key=lambda c: -c["secs"])[:5]
        for c in worst:
            dev = ""
            if c.get("per_device_secs"):
                dev = "  devices [" + ", ".join(
                    f"{s:.4f}s" for s in c["per_device_secs"]
                ) + "]"
            lines.append(
                f"  chunk {c['chunk']}: {c['secs']:.4f}s (at +{c['t_rel']:.2f}s)"
                f"{dev}"
            )
    if rep.get("serving"):
        sv = rep["serving"]
        lines.append(
            f"serving: {sv['requests']} requests ({sv['cache_hits']} cache "
            f"hits, {sv['errors']} errors), latency p50 "
            f"{(sv['latency_p50_s'] or 0) * 1e3:.2f}ms / p99 "
            f"{(sv['latency_p99_s'] or 0) * 1e3:.2f}ms, queue-wait p50 "
            f"{(sv['queue_wait_p50_s'] or 0) * 1e3:.2f}ms"
        )
        if sv["phases"]:
            lines.append("  " + ", ".join(
                f"{k} {v:.3f}s" for k, v in sv["phases"].items()
            ))
    if rep.get("slo"):
        slo = rep["slo"]
        rec = slo.get("recovery") or {}
        budgets = slo.get("error_budget") or {}
        avail = budgets.get("availability") or {}
        lines.append(
            f"slo: {slo.get('requests')} requests at "
            f"{slo.get('qps')} qps over {slo.get('duration_s')}s — "
            f"served p50 {slo.get('served_p50_ms')}ms / "
            f"p99 {slo.get('served_p99_ms')}ms "
            f"(target {((slo.get('slo_targets') or {}).get('p99_ms'))}ms)"
        )
        lines.append(
            f"  error budget: {avail.get('bad', 0)} bad of "
            f"{avail.get('total', 0)} (consumed "
            f"{avail.get('consumed_frac')}x allowed, burn "
            f"{avail.get('burn_rate')}); dropped "
            f"{slo.get('dropped')}, double-served "
            f"{slo.get('double_served')}"
        )
        lines.append(
            f"  losses: {rec.get('losses_injected', 0)} injected, "
            f"time-to-recover "
            f"{rec.get('time_to_recover_s')}s; ingest "
            f"{((slo.get('ingest') or {}).get('chunks'))} chunks / "
            f"{((slo.get('ingest') or {}).get('rebuilds'))} rebuilds"
        )
    if rep.get("fabric"):
        fb = rep["fabric"]
        lines.append(
            f"fabric: {fb.get('replicas')} replica(s), {fb['spawns']} "
            f"spawn(s), {fb['kills']} kill(s), "
            f"{len(fb['respawns'])} respawn(s), {fb['rolls']} rolled, "
            f"{fb['suspects']} suspect transition(s)"
        )
        for rid in sorted(fb["replica_stats"], key=str):
            st = fb["replica_stats"][rid]
            lines.append(
                f"  replica {rid}: {st.get('requests')} req "
                f"({st.get('qps')} qps), p50 {st.get('p50_ms')}ms / "
                f"p99 {st.get('p99_ms')}ms, {st.get('replays')} replay(s), "
                f"gen {st.get('generation')} (floor {st.get('floor')})"
            )
        for r in fb["respawns"]:
            lines.append(
                f"  respawn: replica {r['replica']} at +{r['t_rel']}s, "
                f"recovered in {r['recovery_s']}s"
            )
        if fb["floor_timeline"]:
            lines.append("  floor timeline: " + " -> ".join(
                f"{f['floor']}@+{f['t_rel']}s" for f in fb["floor_timeline"]
            ))
        if fb.get("drain_timeline"):
            lines.append(
                f"  drain: {fb.get('handoff_rolls', 0)} handoff roll(s) / "
                f"{fb.get('retry_rolls', 0)} retry roll(s); timeline: "
                + " -> ".join(
                    f"r{d.get('replica')}:{d.get('phase')}@+{d['t_rel']}s"
                    for d in fb["drain_timeline"]
                )
            )
        if fb.get("totals"):
            t = fb["totals"]
            lines.append(
                f"  totals: {t.get('requests')} routed, "
                f"{t.get('delivered')} delivered, "
                f"{t.get('retries', 0)} retried "
                f"({t.get('roll_retries', 0)} during rolls), "
                f"{t.get('failed', 0)} dropped, "
                f"{t.get('double_served', 0)} double-served"
            )
    if rep.get("cache"):
        ca = rep["cache"]
        lines.append(
            f"cache: {len(ca['replica_stats'])} replica(s) reporting, "
            f"{len(ca['breaker_transitions'])} breaker transition(s)"
        )
        for rid in sorted(ca["replica_stats"], key=str):
            st = ca["replica_stats"][rid]
            lines.append(
                f"  replica {rid}: local hit rate "
                f"{st.get('local_hit_rate')}, peer hit rate "
                f"{st.get('peer_hit_rate')} ({st.get('peer_hits')} hit / "
                f"{st.get('peer_misses')} miss / "
                f"{st.get('peek_timeouts')} timeout), "
                f"{st.get('fills')} fill(s) out, "
                f"{st.get('peer_stores')} store(s) in, "
                f"{st.get('breaker_open')} breaker(s) open"
            )
        if ca.get("peek_latency"):
            lines.append(f"  peek latency: {ca['peek_latency']}")
        for b in ca["breaker_transitions"]:
            lines.append(
                f"  breaker: replica {b.get('replica')} -> peer "
                f"{b.get('peer')}: {b.get('old')} -> {b.get('new')} "
                f"at +{b['t_rel']}s"
            )
    if rep.get("autoscale"):
        a = rep["autoscale"]
        lines.append(
            f"autoscale: {a['actions']} action(s) ({a['ups']} up / "
            f"{a['downs']} down), {a['flaps']} flap(s), "
            f"{a['errors']} error(s), {a['scrape_errors']} scrape error(s)"
        )
        for d in a["timeline"]:
            inputs = ", ".join(
                f"{k}={d[k]}"
                for k in ("burn_availability", "burn_latency",
                          "queue_p99_ms", "rate_per_s")
                if d.get(k) is not None
            )
            lines.append(
                f"  {d.get('action')} at +{d.get('t_rel')}s "
                f"[{d.get('reason')}]: {d.get('replicas_before')}->"
                f"{d.get('replicas_after')} replica(s)"
                + (f" ({inputs})" if inputs else "")
            )
    for key in ("retries", "chaos", "watchdog", "degraded", "exhausted",
                "shrinks"):
        if rep.get(key):
            tally = ", ".join(f"{s}={n}" for s, n in sorted(rep[key].items()))
            lines.append(f"{key}: {tally}")
    for s in rep.get("mesh_shrinks", []):
        mark = "" if s["complete"] else "  [incomplete]"
        lines.append(
            f"mesh shrink: {s['devices_old']}->{s['devices_new']} "
            f"({s['ladder']}) at +{s['t_rel']:.2f}s, {s['secs']:.3f}s "
            f"rebuild [{s['site']}]{mark}"
        )
    if rep.get("strategy"):
        st = rep["strategy"]
        for d in st["decisions"]:
            chosen = d.get("chosen", "?")
            reason = d.get("reason", "")
            inputs = ", ".join(
                f"{k}={d[k]}"
                for k in ("devices", "nodes", "edges",
                          "replicated_state_bytes", "node_state_bytes",
                          "head_edge_frac")
                if k in d
            )
            lines.append(
                f"strategy: chose {chosen!r}"
                + (f" — {reason}" if reason else "")
                + (f" ({inputs})" if inputs else "")
            )
        for p in st["plans"]:
            lines.append(
                f"partition plan: {p.get('strategy')} d={p.get('devices')} "
                f"pad_frac={p.get('pad_frac')} block={p.get('block')} "
                f"e_dev={p.get('e_dev')}"
            )
    if rep["checkpoints"]:
        lines.append(f"checkpoints saved: {rep['checkpoints']}")
    if rep["last_incomplete"]:
        li = rep["last_incomplete"]
        lines.append(
            f"last incomplete span: {li['name']} {li['attrs'] or ''} "
            f"({li['elapsed_secs']:.3f}s elapsed, thread {li['thread']})"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="trace_report", description=__doc__)
    ap.add_argument("trace", help="a <name>.<pid>.trace.jsonl file, or a "
                                  "directory to stitch (all children of one "
                                  "GRAFT_TRACE_PARENT id become one tree)")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)
    if os.path.isdir(args.trace):
        doc = stitch(args.trace)
        print(json.dumps(doc, indent=2, default=str) if args.json
              else render_stitched(doc))
        return 0
    if not os.path.exists(args.trace):
        print(f"trace_report: no such file: {args.trace}", file=sys.stderr)
        return 2
    rep = report(args.trace)
    if args.json:
        print(json.dumps(rep, indent=2, default=str))
    else:
        print(render_human(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
