"""Honest per-component SpMV timing (VERDICT r1 item 2).

Times each stage of the PageRank SpMV pipeline at web-Google scale and emits
ONE JSON object mapping component -> ms/op, naming the dominant stage.  This
table decides where kernel-engineering effort goes (NOTES.md perf ideas).

Method (the only protocol that yields truthful numbers on the axon tunnel,
where ``block_until_ready()`` does not sync):

- run each variant R times inside ONE jit via ``lax.fori_loop``, with a value
  dependency chaining iterations (prevents DCE and cross-rep overlap);
- fence by fetching a scalar to host;
- per-op time = (T(fn_R) - T(fn_0)) / R, which subtracts compile-cache lookup,
  dispatch, and host<->device RTT.

Usage: python tools/spmv_breakdown.py [--nodes N] [--edges E] [--reps R]
                                      [--out breakdown.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=875_000)
    ap.add_argument("--edges", type=int, default=5_100_000)
    ap.add_argument("--reps", type=int, default=50)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--out", type=str, default=None,
                    help="also write the JSON table to this path")
    ap.add_argument("--force", action="store_true",
                    help="allow overwriting a TPU-measured --out artifact "
                         "with a non-TPU run (utils/artifacts.py guard)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import synthetic_powerlaw
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import DanglingMode

    from page_rank_and_tfidf_using_apache_spark_tpu.utils import artifacts

    backend = jax.default_backend()
    try:
        # fail FAST, before minutes of measurement, if the write would
        # downgrade a TPU-stamped artifact
        artifacts.check_overwrite(args.out, backend, force=args.force)
    except artifacts.ProvenanceError as exc:
        print(f"REFUSED: {exc}", file=sys.stderr)
        return 3
    reps = args.reps
    g = synthetic_powerlaw(args.nodes, args.edges, seed=args.seed)
    n, n_edges = g.n_nodes, g.n_edges
    dg = ops.put_graph(g, "float32")
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.random(n).astype(np.float32))
    pe = jnp.asarray(rng.random(n_edges).astype(np.float32))
    print(f"backend={backend} n={n} E={n_edges} reps={reps}",
          file=sys.stderr, flush=True)

    def timed(name, make_body, *arrays):
        """make_body(x, *rest) -> array; first arg is the chained carry."""

        def run_n(r):
            @jax.jit
            def f(x0, *rest):
                def body(i, x):
                    out = make_body(x, *rest)
                    # min(|out|) >= 0 always, so minimum(., 0) is exactly 0
                    # and the carry never drifts — but the reduction touches
                    # every element, so the rep chain depends on the WHOLE
                    # result and XLA cannot DCE the measured work.  (The old
                    # out.ravel()[0] consumed one element — XLA sliced the
                    # rest away, the "cumsum_blocked_E: 0.0" artifact — and
                    # went negative on monotone_diff's signed data, drifting
                    # the carry.)
                    keep = jnp.minimum(jnp.abs(out).min(), 0.0)
                    return x + keep.astype(x.dtype)

                return lax.fori_loop(0, r, body, x0)

            return f

        f0, fr = run_n(0), run_n(reps)
        for f in (f0, fr):
            float(f(*arrays).ravel()[0])  # compile both programs
        t0 = time.perf_counter()
        float(f0(*arrays).ravel()[0])
        base = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(fr(*arrays).ravel()[0])
        full = time.perf_counter() - t0
        ms = max((full - base) / reps * 1e3, 0.0)
        print(f"{name:32s} {ms:8.3f} ms  (rtt {base * 1e3:.0f} ms)",
              file=sys.stderr, flush=True)
        return ms

    table: dict[str, float] = {}
    src_sorted = jnp.asarray(np.sort(np.asarray(dg.src)))

    table["gather_w_src"] = timed(
        "gather w[src] [E]", lambda x, s: x[s], w, dg.src)
    table["gather_w_src_sorted"] = timed(
        "gather w[sorted(src)] [E]", lambda x, s: x[s], w, src_sorted)
    table["cumsum_E"] = timed("cumsum [E]", lambda x: jnp.cumsum(x), pe)
    table["cumsum_blocked_E"] = timed(
        "cumsum_blocked [E] (MXU)", lambda x: ops.cumsum_blocked(x), pe)
    table["segment_sum_E_to_N"] = timed(
        "segment_sum [E->N]",
        lambda x, d: jax.ops.segment_sum(
            x, d, num_segments=n, indices_are_sorted=True),
        pe, dg.dst)
    # the real diff stage gathers from the (E+1)-length cumsum output with
    # indptr values up to E — shape must match or the access pattern lies
    ce = jnp.asarray(rng.random(n_edges + 1).astype(np.float32))
    table["monotone_diff_N"] = timed(
        "diff c[indptr] [N]",
        lambda c, ip: c[ip[1:]] - c[ip[:-1]], ce, dg.indptr)
    table["spmv_cumsum"] = timed(
        "spmv cumsum", lambda x: ops.spmv_cumsum(dg, x, n), w)
    table["spmv_cumsum_mxu"] = timed(
        "spmv cumsum_mxu", lambda x: ops.spmv_cumsum_mxu(dg, x, n), w)
    table["spmv_segment"] = timed(
        "spmv segment", lambda x: ops.spmv_segment(dg, x, n), w)
    # degree-aware hybrid + sort-based static shuffle (ISSUE 7): the
    # static layouts build once on host (amortized; bench.py records the
    # cost as spmv_preprocess_secs), the per-iteration kernels race here
    dg_h = ops.put_graph(g, "float32", layout="hybrid")
    dg_s = ops.put_graph(g, "float32", layout="sort_shuffle")
    hl = dg_h.hybrid
    if hl.head_ids.shape[0]:
        table["hybrid_head_rowsum"] = timed(
            "hybrid head gather+rowsum [R,W]",
            lambda x: ops.hybrid_rowsum(
                jnp.concatenate([x, jnp.zeros(1, x.dtype)])[hl.head_src]
            ),
            w)
    table["spmv_hybrid"] = timed(
        "spmv hybrid (dense head + tail)",
        lambda x: ops.spmv_hybrid(dg_h, x, n), w)
    table["spmv_sort_shuffle"] = timed(
        "spmv sort_shuffle (bucket reduce)",
        lambda x: ops.spmv_sort_shuffle(dg_s, x, n), w)
    table["full_step_cumsum"] = timed(
        "full step (cumsum)",
        lambda x: ops.pagerank_step(
            x, dg, jnp.full(n, 1.0 / n, jnp.float32), n=n, damping=0.85,
            dangling=DanglingMode.REDISTRIBUTE, total_mass=1.0, impl="cumsum"),
        w)

    # Stage tables are per-path: the deployed cumsum impl runs gather ->
    # cumsum -> monotone diff; the segment impl runs gather -> segment_sum.
    # The old table maxed over the union, so the named "dominant" stage
    # could come from a path the winning impl never executes (VERDICT r5).
    cumsum_path = ("gather_w_src", "cumsum_E", "monotone_diff_N")
    segment_path = ("gather_w_src", "segment_sum_E_to_N")
    payload = {
        "n_nodes": n,
        "n_edges": n_edges,
        "reps": reps,
        "ms_per_op": {k: round(v, 4) for k, v in table.items()},
        # dominant stage of the deployed (cumsum) path, plus the
        # alternative path's, so kernel effort aims at the right stage
        "dominant_component": max(cumsum_path, key=lambda k: table[k]),
        "dominant_component_segment_path": max(
            segment_path, key=lambda k: table[k]),
    }
    print(json.dumps({"backend": backend, **payload}))  # stdout regardless
    try:
        artifacts.write_artifact(args.out, payload, backend=backend,
                                 force=args.force)
    except artifacts.ProvenanceError as exc:  # raced stamp change
        print(f"REFUSED: {exc}", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
