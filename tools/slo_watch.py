#!/usr/bin/env python3
"""Terminal SLO watcher over a running process's live-metrics endpoint.

Points at the stdlib HTTP exporter :mod:`obs.export` serves on
``GRAFT_METRICS_PORT`` (``/snapshot.json``) and renders the
rolling-window SLO board — served p50/p95/p99, request/error rates,
error-budget consumption and burn — refreshing in place.  The live-view
counterpart of the Spark web UI: a soak or ``cli.serve`` process is
inspectable *while it runs*, no SIGKILL post-mortem required.

Deliberately stdlib-only (same rule as trace_report.py/trace_diff.py: it
must run from any jax-free shell).

``--fleet`` renders the federation board (ISSUE 19) when pointed at a
router exporting a :class:`obs.federation.FleetHub`: the exact merged
aggregate first, then one row per replica (requests/errors/quantiles)
with its scrape staleness — stale replicas are labeled ``STALE``, never
dropped, mirroring the fleet snapshot's contract.

Usage::

    python tools/slo_watch.py --port 9109            # loop, 2s refresh
    python tools/slo_watch.py --port 9109 --once     # one snapshot
    python tools/slo_watch.py --url http://host:9109 --json
    python tools/slo_watch.py --port 9109 --fleet    # federation board
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any


def fetch(url: str, timeout: float = 5.0) -> dict[str, Any]:
    with urllib.request.urlopen(url.rstrip("/") + "/snapshot.json",
                                timeout=timeout) as resp:
        return json.loads(resp.read())


def _ms(v: Any) -> str:
    return "      -" if v is None else f"{v * 1e3:7.2f}"


def render(snap: dict[str, Any]) -> str:
    """One snapshot as a fixed-width terminal board (pure function — unit
    tested without a server)."""
    lines: list[str] = []
    win = snap.get("latency_s", {}).get("window", {}) or {}
    tot = snap.get("latency_s", {}).get("total", {}) or {}
    qw = snap.get("queue_wait_s", {}) or {}
    lines.append(
        f"serve latency ms  (rolling {snap.get('window_s', '?')}s window, "
        f"{win.get('count', 0)} requests in window)"
    )
    lines.append(
        f"  p50 {_ms(win.get('p50'))}   p90 {_ms(win.get('p90'))}   "
        f"p95 {_ms(win.get('p95'))}   p99 {_ms(win.get('p99'))}"
    )
    lines.append(
        f"  cumulative: {tot.get('count', 0)} served, "
        f"mean {_ms(tot.get('mean'))}ms, p99 {_ms(tot.get('p99'))}ms; "
        f"queue-wait p99 {_ms(qw.get('p99'))}ms"
    )
    budgets = snap.get("budgets", {}) or {}
    for name, b in sorted(budgets.items()):
        lines.append(
            f"budget[{name}]: target {b.get('target')}  bad "
            f"{b.get('bad')}/{b.get('total')}  consumed "
            f"{b.get('consumed_frac')}x allowed  burn {b.get('burn_rate')}x"
        )
    counters = snap.get("counters", {}) or {}
    if counters:
        lines.append("counters (total | /s over window):")
        for name, c in sorted(counters.items()):
            lines.append(
                f"  {name:24s} {c.get('total', 0):12.0f} | "
                f"{c.get('rate_per_s', 0.0):8.2f}/s"
            )
    gauges = snap.get("gauges", {}) or {}
    for name, v in sorted(gauges.items()):
        lines.append(f"gauge {name} = {v}")
    return "\n".join(lines)


def render_fleet(snap: dict[str, Any]) -> str:
    """The federation board: merged aggregate + per-replica rows (pure
    function — unit tested without a fleet).  Falls back to the plain
    board when the snapshot carries no ``fleet`` section."""
    fleet = snap.get("fleet")
    if not isinstance(fleet, dict):
        return render(snap) + "\n(no fleet section: not a FleetHub endpoint)"
    lines = [render(snap)]
    n = len(fleet.get("replicas") or [])
    stale = fleet.get("stale") or []
    lines.append(
        f"fleet: {n} replica(s), {len(stale)} stale "
        f"(scrape every {fleet.get('scrape_s')}s, stale after "
        f"{fleet.get('stale_after_s')}s; {fleet.get('scrapes', 0)} scrapes, "
        f"{fleet.get('scrape_errors', 0)} errors)"
    )
    per = fleet.get("per_replica") or {}
    if per:
        lines.append(
            f"  {'replica':10s} {'requests':>9s} {'errors':>7s} "
            f"{'p50 ms':>8s} {'p99 ms':>8s} {'age s':>7s}"
        )
        for r, row in sorted(per.items()):
            lines.append(
                f"  {r:10s} {row.get('requests', 0):9.0f} "
                f"{row.get('errors', 0):7.0f} {_ms(row.get('p50_s'))} "
                f"{_ms(row.get('p99_s'))} {row.get('staleness_s', 0.0):7.2f}"
                f"{'  STALE' if row.get('stale') else ''}"
            )
    merge_errors = fleet.get("merge_errors") or {}
    for r, err in sorted(merge_errors.items()):
        lines.append(f"  merge error [{r}]: {err}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="slo_watch", description=__doc__)
    ap.add_argument("--url", default=None,
                    help="endpoint base url (overrides --host/--port)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9109,
                    help="the process's GRAFT_METRICS_PORT")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    ap.add_argument("--json", action="store_true",
                    help="raw snapshot JSON instead of the board")
    ap.add_argument("--fleet", action="store_true",
                    help="federation board: aggregate + per-replica rows "
                         "with staleness (point at a router's FleetHub "
                         "exporter)")
    args = ap.parse_args(argv)
    url = args.url or f"http://{args.host}:{args.port}"

    while True:
        try:
            snap = fetch(url)
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as exc:
            print(f"slo_watch: {url}: {exc}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(snap, indent=2))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear, home
            print(f"slo_watch {url}  "
                  f"@ {time.strftime('%H:%M:%S')}")
            print(render_fleet(snap) if args.fleet else render(snap))
            sys.stdout.flush()
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
