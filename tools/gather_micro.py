"""Mosaic dynamic lane/sublane-gather throughput probe (decides the SpMV
kernel design).

The SpMV breakdown (tools/spmv_breakdown.py, breakdown_tpu.json) shows the
whole PageRank step is dominated by XLA's gather/scatter (~150M gathers/s,
<1% of v5e HBM bandwidth).  Mosaic's only dynamic gathers are
``take_along_axis(x, idx, axis)`` with ``idx.shape == x.shape`` lowering to
``tpu.dynamic_gather`` on lanes (axis=1) or sublanes (axis=0).  Findings
this probe encodes (TPU v5e, jax 0.9.0):

- (1, W) single-row shapes do not lower at all (gather canonicalizes to an
  unsupported pattern);
- (8, W) shapes lower for any W via jax.export, but the Mosaic BACKEND
  compiler crashes ("please report a bug", apply-vector-layout) for W
  beyond a modest tile count — jax.export is NOT a sufficient proxy; the
  real width ceiling must be probed on-chip, which this script does by
  compiling each width before timing it;
- the usable-width ceiling and the ns/gather curve decide the SpMV design
  (table-chunk bucketing vs in-kernel local reductions).

Timing follows the NOTES.md protocol: reps chained inside one jit via
``lax.fori_loop`` (value dependency defeats DCE/overlap), scalar fetch as
the only reliable fence on the axon tunnel, 0-rep baseline subtracted.

Usage: python tools/gather_micro.py [--reps 8] [--out gather_micro.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=8)
    ap.add_argument("--target-gathers", type=int, default=4_400_000,
                    help="~gathers per rep (web-Google edge count scale)")
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--interpret", action="store_true")
    ap.add_argument("--force", action="store_true",
                    help="allow overwriting a TPU-measured --out artifact "
                         "with a non-TPU run (utils/artifacts.py guard)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from page_rank_and_tfidf_using_apache_spark_tpu.utils import artifacts

    reps = args.reps
    rng = np.random.default_rng(0)
    backend = jax.default_backend()
    print(f"backend={backend} reps={reps}", file=sys.stderr, flush=True)
    try:
        # fail FAST, before minutes of measurement, if the write would
        # downgrade a TPU-stamped artifact
        artifacts.check_overwrite(args.out, backend, force=args.force)
    except artifacts.ProvenanceError as exc:
        print(f"REFUSED: {exc}", file=sys.stderr)
        return 3

    def make_runner(width, steps, axis, broadcast):
        rows = 8
        x_rows = 1 if broadcast else rows

        def kernel(x_ref, idx_ref, o_ref):
            x = x_ref[:]
            if broadcast:
                x = jnp.broadcast_to(x, (rows, width))
            o_ref[:] = jnp.take_along_axis(x, idx_ref[:], axis=axis)

        io_spec = pl.BlockSpec((rows, width), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)

        def call(x, idx):
            return pl.pallas_call(
                kernel,
                grid=(steps,),
                in_specs=[
                    pl.BlockSpec((x_rows, width), lambda i: (0, 0),
                                 memory_space=pltpu.VMEM),
                    io_spec,
                ],
                out_specs=io_spec,
                out_shape=jax.ShapeDtypeStruct((rows * steps, width), x.dtype),
                interpret=args.interpret,
            )(x, idx)

        return call

    def timed(name, width, steps, axis=1, broadcast=False):
        """Effective ns/gather via the chained fori_loop protocol; returns a
        record with {'compile_ok': False} if Mosaic rejects the shape."""
        rows = 8
        x_rows = 1 if broadcast else rows
        hi = rows if axis == 0 else width
        x = jnp.asarray(rng.random((x_rows, width)).astype(np.float32))
        idx = jnp.asarray(
            rng.integers(0, hi, (rows * steps, width)).astype(np.int32))
        call = make_runner(width, steps, axis, broadcast)

        def run_n(r):
            @jax.jit
            def f(x0, ix):
                def body(i, acc):
                    out = call(acc, ix)
                    # Reduce over the WHOLE kernel output: min(|out|) is
                    # >= 0 so the minimum with 0 keeps the carry unchanged,
                    # while the value dependency covers every gathered
                    # element — XLA cannot DCE the pallas_call.  (The old
                    # out[0, 0] consumption produced the physically
                    # impossible 0.0 ns/gather "bcast_w128" artifact.)
                    return acc + jnp.minimum(jnp.abs(out).min(), 0.0)

                return lax.fori_loop(0, r, body, x0)

            return f

        f0, fr = run_n(0), run_n(reps)
        try:
            for f in (f0, fr):
                float(f(x, idx)[0, 0])  # compile
        except Exception as exc:  # Mosaic backend rejection — record it
            msg = str(exc).splitlines()[0][:120] if str(exc) else repr(exc)[:120]
            print(f"{name:34s} COMPILE FAIL: {msg}", file=sys.stderr,
                  flush=True)
            return {"compile_ok": False, "error": msg}
        t0 = time.perf_counter()
        float(f0(x, idx)[0, 0])
        base = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(fr(x, idx)[0, 0])
        full = time.perf_counter() - t0
        per_rep = max((full - base) / reps, 1e-9)
        n_g = rows * steps * width
        ns = per_rep / n_g * 1e9
        print(f"{name:34s} {per_rep * 1e3:9.3f} ms/rep  {n_g / 1e6:6.2f} Mg "
              f"-> {ns:8.3f} ns/gather  ({n_g / per_rep / 1e9:.2f} Gg/s)",
              file=sys.stderr, flush=True)
        return {"compile_ok": True, "ms_per_rep": round(per_rep * 1e3, 4),
                "gathers": n_g, "ns_per_gather": round(ns, 4)}

    t: dict[str, dict] = {}
    tg = args.target_gathers
    for w in (128, 256, 512, 1024, 2048, 4096, 8192, 32768, 109184):
        steps = max(tg // (8 * w), 1)
        t[f"lane_w{w}"] = timed(f"lane (8,{w})", w, steps)
        if not t[f"lane_w{w}"]["compile_ok"]:
            break  # wider will fail too; don't risk more backend crashes
    # sublane gather (axis=0): 8-deep tables per lane column — the routing
    # primitive for cross-sublane reads
    t["sublane_w1024"] = timed("sublane (8,1024) ax0", 1024,
                               max(tg // (8 * 1024), 1), axis=0)
    # broadcast-row variant at the widest working lane width
    widest_ok = max((int(k.split("w")[1]) for k, v in t.items()
                     if k.startswith("lane_") and v.get("compile_ok")),
                    default=0)
    if widest_ok:
        t[f"bcast_w{widest_ok}"] = timed(
            f"bcast (8,{widest_ok})", widest_ok,
            max(tg // (8 * widest_ok), 1), broadcast=True)

    ok = {k: v for k, v in t.items() if v.get("compile_ok")}
    best = min(ok, key=lambda k: ok[k]["ns_per_gather"]) if ok else None
    payload = {"reps": reps, "modes": t, "best_mode": best,
               "widest_lane_ok": widest_ok}
    print(json.dumps({"backend": backend, **payload}))  # stdout regardless
    try:
        artifacts.write_artifact(args.out, payload, backend=backend,
                                 force=args.force)
    except artifacts.ProvenanceError as exc:  # raced stamp change
        print(f"REFUSED: {exc}", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
