"""XLA-op cost model probe for the SpMV shuffle design (round 2).

tools/gather_micro.py pinned Mosaic's primitives: lane gather 0.153 ns/elem
(128-wide tiles only), sublane gather 0.082 ns (8-deep).  This script fills
in the XLA-side costs that decide how a static permutation / shuffle routing
network should be built around them:

- row-gather from a (T, 128) table (the tile pre-fetch primitive)
- gather from tiny tables (does XLA specialize small operands?)
- same-shape take_along_axis along lanes (does plain XLA hit dynamic_gather?)
- scatter-add of N values into an E array (telescoping-diff build)
- segment_sum into few segments (hot-bin accumulate)
- sort of E pairs (sort-as-shuffle baseline)
- (R, 128) <-> (128, R) transpose (stage glue for routing networks)

Protocol: NOTES.md fencing (fori_loop chaining, scalar fetch, 0-rep base).

Usage: python tools/xla_cost_micro.py [--out xla_cost_tpu.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=3_563_796)
    ap.add_argument("--nodes", type=int, default=872_511)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--force", action="store_true",
                    help="allow overwriting a TPU-measured --out artifact "
                         "with a non-TPU run (utils/artifacts.py guard)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from page_rank_and_tfidf_using_apache_spark_tpu.utils import artifacts

    E, N, reps = args.edges, args.nodes, args.reps
    rng = np.random.default_rng(0)
    backend = jax.default_backend()
    print(f"backend={backend} E={E} N={N} reps={reps}",
          file=sys.stderr, flush=True)
    try:
        # fail FAST, before minutes of measurement, if the write would
        # downgrade a TPU-stamped artifact
        artifacts.check_overwrite(args.out, backend, force=args.force)
    except artifacts.ProvenanceError as exc:
        print(f"REFUSED: {exc}", file=sys.stderr)
        return 3

    def timed(name, make_body, *arrays, elems=None):
        def run_n(r):
            @jax.jit
            def f(x0, *rest):
                def body(i, x):
                    out = make_body(x, *rest)
                    # Depend on EVERY element: min(|out|) >= 0, so the
                    # minimum with 0 is exactly 0 and the carry never
                    # drifts, but XLA cannot DCE any of the measured work.
                    # (The old out.ravel()[0] chain consumed one element,
                    # letting XLA slice away the rest — the round-5
                    # poisoned-cost-model artifact.)
                    keep = jnp.abs(out).min().astype(x.dtype)
                    return x + jnp.minimum(keep, jnp.zeros((), x.dtype))

                return lax.fori_loop(0, r, body, x0)

            return f

        f0, fr = run_n(0), run_n(reps)
        for f in (f0, fr):
            float(f(*arrays).ravel()[0])
        t0 = time.perf_counter()
        float(f0(*arrays).ravel()[0])
        base = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(fr(*arrays).ravel()[0])
        full = time.perf_counter() - t0
        ms = max((full - base) / reps * 1e3, 0.0)
        per = f"  ({ms * 1e6 / elems:8.3f} ns/elem)" if elems else ""
        print(f"{name:40s} {ms:9.3f} ms{per}", file=sys.stderr, flush=True)
        rec = {"ms": round(ms, 4)}
        if elems:
            rec["ns_per_elem"] = round(ms * 1e6 / elems, 4)
        return rec

    t: dict[str, dict] = {}
    tiles = -(-N // 128)
    w2 = jnp.asarray(rng.random((tiles, 128)).astype(np.float32))
    n_rows = -(-E // 128)
    row_ids = jnp.asarray(
        rng.integers(0, tiles, n_rows).astype(np.int32))
    t["row_gather_T128"] = timed(
        f"row-gather ({tiles},128)[{n_rows}]",
        lambda x, ids: x[ids], w2, row_ids, elems=n_rows * 128)

    small = jnp.asarray(rng.random(1024).astype(np.float32))
    sidx = jnp.asarray(rng.integers(0, 1024, E).astype(np.int32))
    t["gather_small_1k"] = timed(
        "gather [E] from 1024-table", lambda x, s: x[s], small, sidx, elems=E)

    med = jnp.asarray(rng.random(65536).astype(np.float32))
    midx = jnp.asarray(rng.integers(0, 65536, E).astype(np.int32))
    t["gather_med_64k"] = timed(
        "gather [E] from 64K-table", lambda x, s: x[s], med, midx, elems=E)

    xr = jnp.asarray(rng.random((n_rows, 128)).astype(np.float32))
    lidx = jnp.asarray(rng.integers(0, 128, (n_rows, 128)).astype(np.int32))
    t["xla_take_along_lanes"] = timed(
        "XLA take_along_axis (R,128) ax1",
        lambda x, ix: jnp.take_along_axis(x, ix, axis=1), xr, lidx,
        elems=n_rows * 128)

    e_arr = jnp.asarray(rng.random(E).astype(np.float32))
    npos = jnp.asarray(np.sort(rng.integers(0, E, N)).astype(np.int32))
    nvals = jnp.asarray(rng.random(N).astype(np.float32))
    t["scatter_add_N_into_E"] = timed(
        "scatter-add N into [E] (sorted pos)",
        lambda x, p, v: x.at[p].add(v), e_arr, npos, nvals, elems=N)

    hot_seg = jnp.asarray(rng.integers(0, 1024, E).astype(np.int32))
    t["segment_sum_E_to_1k"] = timed(
        "segment_sum [E] -> 1024 bins",
        lambda x, s: jax.ops.segment_sum(x, s, num_segments=1024),
        e_arr, hot_seg, elems=E)

    skey = jnp.asarray(rng.integers(0, E, E).astype(np.int32))
    t["sort_E_pairs"] = timed(
        "sort [E] (i32 key, f32 val)",
        lambda x, k: lax.sort((k, x), num_keys=1)[1], e_arr, skey, elems=E)

    t["transpose_R128"] = timed(
        "transpose (R,128)->(128,R)",
        lambda x: x.T.reshape(n_rows, 128), xr, elems=n_rows * 128)

    payload = {"E": E, "N": N, "reps": reps, "ops": t}
    print(json.dumps({"backend": backend, **payload}))  # stdout regardless
    try:
        artifacts.write_artifact(args.out, payload, backend=backend,
                                 force=args.force)
    except artifacts.ProvenanceError as exc:  # raced stamp change
        print(f"REFUSED: {exc}", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
