"""Benchmark harness — prints ONE JSON line for the driver.

North-star metric (BASELINE.json:2): PageRank iterations/sec at web-Google
scale (875K nodes / 5.1M edges, 20 iterations, damping 0.85 — config 1).
The SNAP datasets are not mounted in this environment (SURVEY.md §6), so a
synthetic power-law graph of identical scale stands in.

``vs_baseline``: the reference publishes no numbers and pyspark is not
installed (BASELINE.md), so the interim baseline anchor is the scipy CSR
power iteration on this host's CPU — the strongest single-process CPU
implementation available — per BASELINE.md's "interim CPU reference point".
The BASELINE.json target (≥20× vs 8-core Spark-local) is strictly *weaker*
than beating scipy CSR, which does the same FLOPs without JVM/shuffle
overhead: Spark local[8] runs this workload orders of magnitude slower than
scipy (per-record iterator chains vs vectorized kernels).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    n_nodes = 875_000
    n_edges = 5_100_000
    iters = 20

    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import synthetic_powerlaw
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    t0 = time.perf_counter()
    graph = synthetic_powerlaw(n_nodes, n_edges, seed=7)
    log(f"graph: {graph.n_nodes} nodes, {graph.n_edges} edges "
        f"({time.perf_counter() - t0:.1f}s gen)")

    # --- CPU anchor: scipy CSR power iteration (same math, float32) ---
    import scipy.sparse as sp

    a = sp.csr_matrix(
        (np.ones(graph.n_edges, np.float32), (graph.dst, graph.src)),
        shape=(graph.n_nodes, graph.n_nodes),
    )
    inv = np.where(graph.out_degree > 0, 1.0 / np.maximum(graph.out_degree, 1), 0.0).astype(np.float32)
    e = np.full(graph.n_nodes, 1.0 / graph.n_nodes, np.float32)
    dang = (graph.out_degree == 0).astype(np.float32)
    r = np.full(graph.n_nodes, 1.0 / graph.n_nodes, np.float32)
    anchor_iters = 5
    t0 = time.perf_counter()
    for _ in range(anchor_iters):
        w = r * inv
        contribs = a @ w
        contribs += float(np.dot(r, dang)) * e
        r = 0.15 * e + 0.85 * contribs
    cpu_secs_per_iter = (time.perf_counter() - t0) / anchor_iters
    cpu_ips = 1.0 / cpu_secs_per_iter
    log(f"cpu anchor (scipy CSR): {cpu_ips:.2f} iters/sec")

    # --- TPU run ---
    import jax
    import jax.numpy as jnp

    # cumsum SpMV: the dst-sorted prefix-sum formulation, ~1.5x over
    # segment_sum on v5e where XLA's scatter path dominates (ops/pagerank.py
    # spmv_cumsum docstring has the accuracy analysis).
    cfg = PageRankConfig(iterations=iters, dangling="redistribute", init="uniform",
                         dtype="float32", spmv_impl="cumsum")
    n = graph.n_nodes
    dg = ops.put_graph(graph, cfg.dtype)
    e_dev = jax.device_put(ops.restart_vector(n, cfg))
    ranks0 = jax.device_put(ops.init_ranks(n, cfg))
    runner = ops.make_pagerank_runner(n, cfg)

    # NOTE: on the axon tunnel block_until_ready() does NOT sync; the only
    # reliable fence is fetching a scalar to host.  Also subtract the
    # measured host<->device round-trip so the number reflects device time.
    def run_once():
        t0 = time.perf_counter()
        ranks, it, delta = runner(dg, ranks0, e_dev)
        checksum = float(jnp.sum(ranks))
        return time.perf_counter() - t0, checksum, float(delta)

    secs, checksum, delta = run_once()
    log(f"tpu first call (compile+{iters} iters): {secs:.2f}s")
    rtt_probe = jax.jit(lambda x: x.sum())
    float(rtt_probe(e_dev))
    t0 = time.perf_counter()
    float(rtt_probe(e_dev))
    rtt = time.perf_counter() - t0
    warm = min(run_once()[0] for _ in range(3))
    device_secs = max(warm - rtt, 1e-9)
    tpu_ips = iters / device_secs
    log(f"tpu warm: {warm:.3f}s wall ({rtt * 1e3:.0f}ms rtt) for {iters} iters "
        f"-> {tpu_ips:.1f} iters/sec, checksum={checksum:.4f}, delta={delta:.3e}")

    print(json.dumps({
        "metric": "pagerank_iters_per_sec_webgoogle_scale",
        "value": round(tpu_ips, 2),
        "unit": "iters/sec (875K nodes, 5.1M edges, f32, 1 chip)",
        "vs_baseline": round(tpu_ips / cpu_ips, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
