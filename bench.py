"""Benchmark harness — prints ONE JSON line for the driver.

North-star metric (BASELINE.json:2): PageRank iterations/sec at web-Google
scale (875K nodes / 5.1M edges, 20 iterations, damping 0.85 — config 1).
The SNAP datasets are not mounted in this environment (SURVEY.md §6), so a
synthetic power-law graph of identical scale stands in.

``vs_baseline``: the reference publishes no numbers and pyspark is not
installed (BASELINE.md), so the interim baseline anchor is the scipy CSR
power iteration on this host's CPU — the strongest single-process CPU
implementation available — per BASELINE.md's "interim CPU reference point".
The BASELINE.json target (≥20× vs 8-core Spark-local) is strictly *weaker*
than beating scipy CSR, which does the same FLOPs without JVM/shuffle
overhead: Spark local[8] runs this workload orders of magnitude slower than
scipy (per-record iterator chains vs vectorized kernels).

Self-tuning: which SpMV formulation wins depends on how XLA/Mosaic lower
gather, scatter and prefix sums on the present chip generation, so the
harness races the candidate impls and reports the winner.  Each candidate
runs in a subprocess with a timeout — a candidate that fails to compile or
wedges the compile service costs its time budget, not the whole bench.
Override the list with BENCH_IMPLS=a,b,c; scale with BENCH_NODES/EDGES/ITERS.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

N_NODES = int(os.environ.get("BENCH_NODES", 875_000))
N_EDGES = int(os.environ.get("BENCH_EDGES", 5_100_000))
ITERS = int(os.environ.get("BENCH_ITERS", 20))
SEED = 7
CANDIDATE_TIMEOUT_S = int(os.environ.get("BENCH_IMPL_TIMEOUT_S", 420))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _build_graph():
    """Generate the bench graph — or reload the parent's copy, so candidate
    subprocesses don't spend their timeout budget on regeneration."""
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        Graph,
        synthetic_powerlaw,
    )

    t0 = time.perf_counter()
    cache = os.environ.get("BENCH_GRAPH_NPZ")
    if cache and os.path.exists(cache):
        z = np.load(cache)
        graph = Graph(int(z["n_nodes"]), z["src"], z["dst"],
                      z["out_degree"], z["node_ids"])
        verb = "load"
    else:
        graph = synthetic_powerlaw(N_NODES, N_EDGES, seed=SEED)
        verb = "gen"
    log(f"graph: {graph.n_nodes} nodes, {graph.n_edges} edges "
        f"({time.perf_counter() - t0:.1f}s {verb})")
    return graph


def _save_graph(graph, path: str) -> None:
    np.savez(path, n_nodes=graph.n_nodes, src=graph.src, dst=graph.dst,
             out_degree=graph.out_degree, node_ids=graph.node_ids)


def measure_impl(impl: str) -> dict:
    """Run one SpMV impl on the accelerator; returns {'ips':, 'checksum':}."""
    import jax
    import jax.numpy as jnp

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    graph = _build_graph()
    n = graph.n_nodes
    dg = ops.put_graph(graph, "float32")
    cfg = PageRankConfig(iterations=ITERS, dangling="redistribute",
                         init="uniform", dtype="float32", spmv_impl=impl)
    e_dev = jax.device_put(ops.restart_vector(n, cfg))
    ranks0 = jax.device_put(ops.init_ranks(n, cfg))
    runner = ops.make_pagerank_runner(n, cfg)

    # NOTE: on the axon tunnel block_until_ready() does NOT sync; the only
    # reliable fence is fetching a scalar to host.  Subtract the measured
    # host<->device round-trip so numbers reflect device time.
    def run_once():
        t0 = time.perf_counter()
        ranks, it, delta = runner(dg, ranks0, e_dev)
        checksum = float(jnp.sum(ranks))
        return time.perf_counter() - t0, checksum, float(delta)

    secs, checksum, delta = run_once()
    log(f"[{impl}] first call (compile+{ITERS} iters): {secs:.2f}s")
    rtt_probe = jax.jit(lambda x: x.sum())
    float(rtt_probe(e_dev))
    t0 = time.perf_counter()
    float(rtt_probe(e_dev))
    rtt = time.perf_counter() - t0
    warm = min(run_once()[0] for _ in range(3))
    device_secs = max(warm - rtt, 1e-9)
    ips = ITERS / device_secs
    log(f"[{impl}] warm: {warm:.3f}s wall ({rtt * 1e3:.0f}ms rtt) for "
        f"{ITERS} iters -> {ips:.1f} iters/sec, checksum={checksum:.4f}, "
        f"delta={delta:.3e}")
    return {"ips": ips, "checksum": checksum}


def main() -> int:
    graph = _build_graph()

    # --- CPU anchor: scipy CSR power iteration (same math, float32) ---
    import scipy.sparse as sp

    a = sp.csr_matrix(
        (np.ones(graph.n_edges, np.float32), (graph.dst, graph.src)),
        shape=(graph.n_nodes, graph.n_nodes),
    )
    inv = np.where(graph.out_degree > 0,
                   1.0 / np.maximum(graph.out_degree, 1), 0.0).astype(np.float32)
    e = np.full(graph.n_nodes, 1.0 / graph.n_nodes, np.float32)
    dang = (graph.out_degree == 0).astype(np.float32)
    r = np.full(graph.n_nodes, 1.0 / graph.n_nodes, np.float32)
    anchor_iters = 5
    t0 = time.perf_counter()
    for _ in range(anchor_iters):
        w = r * inv
        contribs = a @ w
        contribs += float(np.dot(r, dang)) * e
        r = 0.15 * e + 0.85 * contribs
    cpu_ips = anchor_iters / (time.perf_counter() - t0)
    log(f"cpu anchor (scipy CSR): {cpu_ips:.2f} iters/sec")

    # --- accelerator: race candidates, each isolated in a subprocess ---
    # Ordered safe-first: cumsum/segment are known to compile on-chip; the
    # Pallas candidate runs LAST so a wedged Mosaic compile (killed at the
    # timeout) can never block the measurements that already succeeded.
    candidates = os.environ.get("BENCH_IMPLS", "cumsum,segment,pallas").split(",")
    import atexit
    import tempfile

    fd, graph_cache = tempfile.mkstemp(prefix="bench_graph_", suffix=".npz")
    os.close(fd)
    atexit.register(lambda: os.path.exists(graph_cache) and os.unlink(graph_cache))
    _save_graph(graph, graph_cache)
    child_env = dict(os.environ, BENCH_GRAPH_NPZ=graph_cache)
    results: dict[str, float] = {}
    for impl in candidates:
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--impl", impl],
                capture_output=True, text=True, timeout=CANDIDATE_TIMEOUT_S,
                cwd=os.path.dirname(os.path.abspath(__file__)), env=child_env,
            )
        except subprocess.TimeoutExpired as exc:
            for stream in (exc.stderr, exc.stdout):
                if stream:
                    sys.stderr.write(stream if isinstance(stream, str)
                                     else stream.decode(errors="replace"))
            log(f"[{impl}] TIMEOUT after {CANDIDATE_TIMEOUT_S}s; skipping")
            continue
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            log(f"[{impl}] subprocess failed rc={proc.returncode}: "
                f"{proc.stdout.strip()[-400:]}")
            continue
        try:
            out = json.loads(proc.stdout.strip().splitlines()[-1])
            checksum, ips = out["checksum"], out["ips"]
        except (json.JSONDecodeError, IndexError, KeyError, TypeError):
            log(f"[{impl}] unparseable output: {proc.stdout[-400:]!r}")
            continue
        if not (0.99 < checksum < 1.01):  # mass must be conserved
            log(f"[{impl}] BAD CHECKSUM {checksum}; discarding")
            continue
        results[impl] = ips
        log(f"[{impl}] done in {time.perf_counter() - t0:.0f}s wall")
    if not results:
        log("no SpMV impl produced a valid result")
        return 1
    best = max(results, key=results.get)
    tpu_ips = results[best]

    print(json.dumps({
        "metric": "pagerank_iters_per_sec_webgoogle_scale",
        "value": round(tpu_ips, 2),
        "unit": (f"iters/sec ({graph.n_nodes} nodes, {graph.n_edges} edges, "
                 f"f32, 1 chip, spmv={best})"),
        "vs_baseline": round(tpu_ips / cpu_ips, 2),
    }))
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--impl":
        print(json.dumps(measure_impl(sys.argv[2])))
        sys.exit(0)
    sys.exit(main())
