"""Benchmark harness — prints ONE JSON line for the driver.

North-star metric (BASELINE.json:2): PageRank iterations/sec at web-Google
scale (875K nodes / 5.1M edges, 20 iterations, damping 0.85 — config 1).
Also reports TF-IDF throughput at 20-Newsgroups scale (config 2: batch) and
through the streaming ingest path (config 5's mechanism) in ``extra``.
The SNAP datasets are not mounted in this environment (SURVEY.md §6), so
synthetic data of identical scale stands in.

``vs_baseline``: the reference publishes no numbers and pyspark is not
installed (BASELINE.md), so the interim baseline anchor is the scipy CSR
power iteration on this host's CPU — the strongest single-process CPU
implementation available — per BASELINE.md's "interim CPU reference point".
The BASELINE.json target (≥20× vs 8-core Spark-local) is strictly *weaker*
than beating scipy CSR, which does the same FLOPs without JVM/shuffle
overhead.

Dead-tunnel proofing (round-1 failure: 3×420 s timeouts, no JSON at all):
the TPU here is reached through a relay tunnel that can be down.  Before
any measurement the harness probes backend liveness in a ≤90 s subprocess;
if the probe fails every measurement falls back to the JAX CPU backend and
the output carries ``"tpu_unreachable": true`` — a valid, parseable record
in either tunnel state.  The parent process NEVER imports jax: a process
wedged on the dead tunnel blocks jax imports machine-wide (observed), so
all jax work lives in subprocesses that the parent can time out and kill.

Self-tuning: which SpMV formulation wins depends on how XLA/Mosaic lower
gather, scatter and prefix sums on the present chip generation, so the
harness races the candidate impls and reports the winner, each isolated in
a subprocess with a timeout.  Override with BENCH_IMPLS=a,b,c; scale with
BENCH_NODES/EDGES/ITERS; skip sections with BENCH_SKIP_TFIDF=1.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

N_NODES = int(os.environ.get("BENCH_NODES", 875_000))
N_EDGES = int(os.environ.get("BENCH_EDGES", 5_100_000))
ITERS = int(os.environ.get("BENCH_ITERS", 20))
TFIDF_DOCS = int(os.environ.get("BENCH_TFIDF_DOCS", 19_000))
TFIDF_TOKENS_PER_DOC = int(os.environ.get("BENCH_TFIDF_TOKENS_PER_DOC", 180))
SEED = 7
CANDIDATE_TIMEOUT_S = int(os.environ.get("BENCH_IMPL_TIMEOUT_S", 420))
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", 90))
TFIDF_TIMEOUT_S = int(os.environ.get("BENCH_TFIDF_TIMEOUT_S", 420))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# data generation (parent generates once, children reload via cache files)
# --------------------------------------------------------------------------

def _build_graph():
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        Graph,
        synthetic_powerlaw,
    )

    t0 = time.perf_counter()
    cache = os.environ.get("BENCH_GRAPH_NPZ")
    if cache and os.path.exists(cache) and os.path.getsize(cache) > 0:
        z = np.load(cache)
        graph = Graph(int(z["n_nodes"]), z["src"], z["dst"],
                      z["out_degree"], z["node_ids"])
        verb = "load"
    else:
        graph = synthetic_powerlaw(N_NODES, N_EDGES, seed=SEED)
        verb = "gen"
    log(f"graph: {graph.n_nodes} nodes, {graph.n_edges} edges "
        f"({time.perf_counter() - t0:.1f}s {verb})")
    return graph


def _save_graph(graph, path: str) -> None:
    np.savez(path, n_nodes=graph.n_nodes, src=graph.src, dst=graph.dst,
             out_degree=graph.out_degree, node_ids=graph.node_ids)


def _synth_corpus_lines(n_docs: int, tokens_per_doc: int, seed: int) -> list[str]:
    """Zipf-distributed synthetic corpus at 20-Newsgroups scale: ~19K docs,
    Zipf unigrams over a ~50K-word vocabulary (BASELINE.json:8)."""
    rng = np.random.default_rng(seed)
    lens = np.maximum(
        rng.poisson(tokens_per_doc, n_docs), 8).astype(np.int64)
    total = int(lens.sum())
    ids = rng.zipf(1.3, total) % 50_000
    words = np.char.add("w", ids.astype("U6"))
    docs, pos = [], 0
    for ln in lens:
        docs.append(" ".join(words[pos:pos + ln]))
        pos += ln
    return docs


def _corpus(path_env: str = "BENCH_CORPUS_TXT") -> list[str]:
    cache = os.environ.get(path_env)
    t0 = time.perf_counter()
    if cache and os.path.exists(cache):
        with open(cache) as f:
            docs = f.read().splitlines()
        verb = "load"
    else:
        docs = _synth_corpus_lines(TFIDF_DOCS, TFIDF_TOKENS_PER_DOC, SEED)
        verb = "gen"
    log(f"corpus: {len(docs)} docs ({time.perf_counter() - t0:.1f}s {verb})")
    return docs


# --------------------------------------------------------------------------
# child modes (each runs in its own process; may touch jax)
# --------------------------------------------------------------------------

def gen_graph() -> dict:
    """Child mode: generate the bench graph and save it to BENCH_GRAPH_NPZ.
    Runs sanitized (no axon registration) so the parent stays jax-free."""
    graph = _build_graph()
    _save_graph(graph, os.environ["BENCH_GRAPH_NPZ"])
    return {"n_nodes": graph.n_nodes, "n_edges": graph.n_edges}


def probe() -> dict:
    """Tiny end-to-end backend check: devices + one jit + scalar fetch."""
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    y = float(jax.jit(lambda v: (v * 2).sum())(jnp.arange(8.0)))
    assert y == 56.0
    return {"ok": True, "backend": jax.default_backend(),
            "devices": [str(d) for d in devs]}


def measure_impl(impl: str) -> dict:
    """Run one SpMV impl on the default backend; {'ips':, 'checksum':}."""
    from page_rank_and_tfidf_using_apache_spark_tpu import obs

    with obs.run(f"impl_{impl}"):
        return _measure_impl_traced(impl, obs)


def _measure_impl_traced(impl: str, obs) -> dict:
    import jax
    import jax.numpy as jnp

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    with obs.span("bench.graph"):
        graph = _build_graph()
        n = graph.n_nodes
        cfg = PageRankConfig(iterations=ITERS, dangling="redistribute",
                             init="uniform", dtype="float32", spmv_impl=impl)
        # the one-time static-layout build (degree sort / head split /
        # bucket padding for hybrid and sort_shuffle) is timed separately:
        # the BENCH record must show it amortizes over the run
        t0 = time.perf_counter()
        layout = ops.layout_for_impl(impl)
        dg = ops.put_graph(
            graph, "float32", layout=layout,
            head_coverage=cfg.head_coverage,
            head_row_width=cfg.head_row_width,
            bucket_width=cfg.shuffle_bucket_width,
            keep_edge_arrays=layout is None,
        )
        preprocess_secs = time.perf_counter() - t0
        e_dev = jax.device_put(ops.restart_vector(n, cfg))
        ranks0_host = ops.init_ranks(n, cfg)
        runner = ops.make_pagerank_runner(n, cfg)
    log(f"[{impl}] layout+put: {preprocess_secs:.2f}s")

    # NOTE: on the axon tunnel block_until_ready() does NOT sync; the only
    # reliable fence is fetching a scalar to host.  Subtract the measured
    # host<->device round-trip so numbers reflect device time.
    def run_once():
        # the runner donates its rank carry (in-place update on device), so
        # every rep puts a fresh one — fenced (scalar fetch: the only
        # reliable sync on the tunnel) BEFORE t0 so the H2D transfer stays
        # outside the timed region
        ranks0 = jax.device_put(ranks0_host)
        float(ranks0[0])
        t0 = time.perf_counter()
        ranks, it, delta = runner(dg, ranks0, e_dev)
        checksum = float(jnp.sum(ranks))
        return time.perf_counter() - t0, checksum, float(delta)

    with obs.span("bench.compile"):
        secs, checksum, delta = run_once()
    log(f"[{impl}] first call (compile+{ITERS} iters): {secs:.2f}s")
    with obs.span("bench.rtt"):
        rtt_probe = jax.jit(lambda x: x.sum())
        float(rtt_probe(e_dev))
        t0 = time.perf_counter()
        float(rtt_probe(e_dev))
        rtt = time.perf_counter() - t0
    with obs.span("bench.warm"):
        warm = min(run_once()[0] for _ in range(3))
    device_secs = max(warm - rtt, 1e-9)
    ips = ITERS / device_secs
    log(f"[{impl}] warm: {warm:.3f}s wall ({rtt * 1e3:.0f}ms rtt) for "
        f"{ITERS} iters -> {ips:.1f} iters/sec, checksum={checksum:.4f}, "
        f"delta={delta:.3e}")
    return {"ips": ips, "checksum": checksum,
            "preprocess_secs": preprocess_secs,
            "backend": jax.default_backend()}


def _ingest_overlap_frac(metrics) -> float | None:
    """The h2d_overlap_frac of the LAST staged-ingest run a metrics
    recorder saw (dataflow.ingest publishes one ``ingest_overlap`` record
    per chunked_ingest run), or None when no run completed."""
    for r in reversed(metrics.records):
        if r.get("event") == "ingest_overlap":
            return float(r["h2d_overlap_frac"])
    return None


def measure_tfidf() -> dict:
    """TF-IDF throughput: batch pipeline (config 2) and streaming ingest
    (config 5's mechanism), tokens/sec with the same fencing rules.

    When the parent provides BENCH_TFIDF_CKPT_DIR the streaming passes
    checkpoint per chunk, and BENCH_TFIDF_RESUME=1 switches to resume-only
    mode: continue the interrupted ingest from the first unprocessed chunk
    (the BENCH_r05 fix — a 420s timeout used to discard all completed
    chunks) and report the partial-but-real cumulative throughput.

    The whole measurement runs as a traced obs run (the parent passes
    GRAFT_TRACE_DIR): every section is a ``bench.*`` phase span flushed to
    the JSONL trace, so even a child the parent kills at the timeout
    leaves a full per-phase, per-chunk accounting behind — the parent
    reads the artifact instead of scraping this process's stderr."""
    from page_rank_and_tfidf_using_apache_spark_tpu import obs

    with obs.run("tfidf"):
        return _measure_tfidf_traced(obs)


def _measure_tfidf_traced(obs) -> dict:
    from page_rank_and_tfidf_using_apache_spark_tpu.io.text import tokenize_corpus
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf,
        run_tfidf_streaming,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig

    with obs.span("bench.corpus"):
        docs = _corpus()
    cfg = TfidfConfig(vocab_bits=18)
    ck_dir = os.environ.get("BENCH_TFIDF_CKPT_DIR")
    # Stride 8: frequent checkpoints would perturb the timed passes (each
    # snapshot compacts ALL accumulated parts + writes an .npz), breaking
    # trajectory comparability with rounds <= r05.  Tests that need chunk-
    # granular resume set BENCH_TFIDF_CKPT_EVERY=1 explicitly.
    ck: dict = (
        {"checkpoint_every": int(os.environ.get("BENCH_TFIDF_CKPT_EVERY", "8")),
         "checkpoint_dir": ck_dir}
        if ck_dir else {}
    )
    chunk_docs = int(os.environ.get("BENCH_TFIDF_CHUNK_DOCS", "512"))
    chunks = [docs[i:i + chunk_docs] for i in range(0, len(docs), chunk_docs)]

    # Staged-ingest knobs shared by every streaming pass (ISSUE 10): the
    # chunk kernel compiles at the 2^18 cap, so chunks are RE-PACKED to
    # fill it (pack_target_tokens — padding, not scheduling, was most of
    # the r07 streaming-vs-batch gap), and the H2D transfer of chunk N+1
    # runs on the pipeline's transfer thread under chunk N's compute
    # (pipeline_depth).  The resume pass MUST re-pack with the same
    # target: checkpoint chunk indices count packed chunks.
    # BENCH_TFIDF_PACK_TOKENS=0 keeps the source chunking (tests that
    # need many small resumable chunks pin it off).
    pack = int(os.environ.get("BENCH_TFIDF_PACK_TOKENS", 1 << 18))
    stream_kw: dict = {"vocab_bits": 18, "chunk_tokens": 1 << 18,
                       "pack_target_tokens": pack}

    if ck_dir and os.environ.get("BENCH_TFIDF_RESUME") == "1":
        scfg = TfidfConfig(prefetch=2, **stream_kw, **ck)
        t0 = time.perf_counter()
        with obs.span("bench.stream_resume"):
            sout = run_tfidf_streaming(chunks, scfg, resume=True)
        secs = max(time.perf_counter() - t0, 1e-9)
        toks = int(sum(r["tokens"] for r in sout.metrics.records
                       if r.get("event") == "chunk"))
        if toks:
            tps = toks / secs
        else:
            # Zero chunks left: the interrupted child had already finished
            # ingest (it died between the last checkpoint and its JSON
            # line).  A 0 tokens/s "success" would be worse than the old
            # bare TIMEOUT — report the checkpoint's cumulative totals.
            from page_rank_and_tfidf_using_apache_spark_tpu.utils import (
                checkpoint as ckpt,
            )

            latest = ckpt.latest_checkpoint(ck_dir)
            ext = ckpt.peek_meta(latest)["extra"] if latest else {}
            toks = int(ext.get("n_tokens", 0))
            csecs = float(ext.get("ingest_secs", 0.0))
            tps = toks / csecs if csecs > 0 else 0.0
        log(f"[tfidf-resume] completed remaining chunks: {toks} tokens, "
            f"{tps / 1e6:.2f} M tokens/s")
        return {"batch_tokens_per_sec": 0.0,
                "stream_tokens_per_sec": tps,
                "stream_overlap_speedup": 1.0,
                "h2d_overlap_frac": _ingest_overlap_frac(sout.metrics),
                "streaming_vs_batch_ratio": None,  # no batch pass here
                "resumed": True, "chunks": len(chunks),
                "n_tokens": toks, "nnz": sout.nnz}

    with obs.span("bench.warmup"):
        n_tokens = tokenize_corpus(docs[:64], vocab_bits=18).n_tokens  # warm cheap
        del n_tokens

    # batch: run once to compile, once warm
    t0 = time.perf_counter()
    with obs.span("bench.batch_cold"):
        out = run_tfidf(docs, cfg)
    cold = time.perf_counter() - t0
    tok_total = int(sum(r["tokens"] for r in out.metrics.records
                        if r.get("event") == "tokenize"))
    t0 = time.perf_counter()
    with obs.span("bench.batch_warm"):
        out = run_tfidf(docs, cfg)
    warm = time.perf_counter() - t0
    batch_tps = tok_total / warm
    log(f"[tfidf-batch] {len(docs)} docs, {tok_total} tokens: cold {cold:.2f}s "
        f"warm {warm:.2f}s -> {batch_tps / 1e6:.2f} M tokens/s, nnz={out.nnz}")

    # streaming: fixed-size chunks through the once-compiled chunk kernel;
    # measure the serial (prefetch=0) and double-buffered (prefetch=2)
    # schedules separately — on TPU the pipelined one overlaps host
    # tokenization with device compute (SURVEY.md §5.7), on the CPU backend
    # they tie (all stages share the same saturated cores).  With a parent-
    # provided checkpoint dir every pass snapshots per chunk, so a timeout
    # kill leaves a resumable (and accountable) partial run behind.
    scfg0 = TfidfConfig(prefetch=0, pipeline_depth=0, **stream_kw, **ck)
    with obs.span("bench.stream_warmup"):
        sout = run_tfidf_streaming(iter(chunks), scfg0)  # compile + first pass
    t0 = time.perf_counter()
    with obs.span("bench.stream_serial"):
        sout = run_tfidf_streaming(iter(chunks), scfg0)
    s_serial = time.perf_counter() - t0
    scfg2 = TfidfConfig(prefetch=2, pipeline_depth=2, **stream_kw, **ck)
    t0 = time.perf_counter()
    with obs.span("bench.stream_pipelined"):
        sout = run_tfidf_streaming(iter(chunks), scfg2)
    s_pipe = time.perf_counter() - t0
    stream_tps = tok_total / min(s_serial, s_pipe)
    overlap = _ingest_overlap_frac(sout.metrics)
    ratio = stream_tps / batch_tps if batch_tps > 0 else None
    log(f"[tfidf-stream] {len(chunks)} chunks: serial {s_serial:.2f}s, "
        f"pipelined {s_pipe:.2f}s -> {stream_tps / 1e6:.2f} M tokens/s, "
        f"overlap speedup {s_serial / s_pipe:.2f}x, "
        f"h2d_overlap {overlap}, "
        f"{f'{ratio:.2f}' if ratio is not None else 'n/a'}x batch, "
        f"nnz={sout.nnz}")
    return {"batch_tokens_per_sec": batch_tps,
            "stream_tokens_per_sec": stream_tps,
            "stream_overlap_speedup": s_serial / s_pipe,
            "h2d_overlap_frac": overlap,
            "streaming_vs_batch_ratio": ratio,
            "resumed": False, "chunks": len(chunks),
            "n_tokens": tok_total, "nnz": out.nnz}


def measure_serve() -> dict:
    """Served-QPS bench (ISSUE 8): build a servable index from the bench
    corpus, then race the warm batched serving path against the naive
    per-request (batch=1, cold) loop — the status-quo cost of scoring
    without a long-lived server, where every query pays a fresh compile.

    Reports p50/p99 latency and QPS at ≥2 fixed micro-batch sizes, cache
    hit counts, and the warm/naive speedup.  Runs traced: every request is
    a ``serve_request`` event, every batch a ``serve.batch`` span, so
    ``trace_report`` shows queue-wait vs pad vs dispatch vs pull."""
    from page_rank_and_tfidf_using_apache_spark_tpu import obs

    with obs.run("serve"):
        return _measure_serve_traced(obs)


def _measure_serve_traced(obs) -> dict:
    import tempfile as tf

    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig

    with obs.span("bench.corpus"):
        docs = _corpus()
    cfg = TfidfConfig(vocab_bits=18)
    idx_dir = tf.mkdtemp(prefix="bench_serve_idx_")
    try:
        return _measure_serve_on_index(obs, docs, cfg, idx_dir)
    finally:
        import shutil

        shutil.rmtree(idx_dir, ignore_errors=True)


def _measure_serve_on_index(obs, docs, cfg, idx_dir: str) -> dict:
    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu import serving
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as tops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import percentile

    with obs.span("bench.index_build"):
        out = run_tfidf(docs, cfg)
        serving.save_index(idx_dir, out, cfg)
        index = serving.load_index(idx_dir)
    log(f"[serve] index v{index.version}: {index.n_docs} docs, "
        f"{index.nnz} nnz")

    # Query stream at the bench's Zipf vocabulary: mostly unique, a hot
    # head repeated so the LRU has something to do (production query logs
    # are Zipf too).
    rng = np.random.default_rng(SEED)
    n_queries = int(os.environ.get("BENCH_SERVE_QUERIES", 256))
    hot = [[f"w{rng.zipf(1.3) % 50_000}" for _ in range(3)] for _ in range(8)]
    queries = []
    for _ in range(n_queries):
        if rng.random() < 0.25:
            queries.append(hot[int(rng.integers(len(hot)))])
        else:
            queries.append([f"w{rng.zipf(1.3) % 50_000}"
                            for _ in range(int(rng.integers(2, 5)))])

    # --- naive per-request (batch=1, cold) loop: every request pays its
    # own compile, exactly what scoring costs without a warm server ---
    import jax.numpy as jnp

    res_dev = tops.TfidfResult(
        doc=jnp.asarray(np.ascontiguousarray(index.doc)),
        term=jnp.asarray(np.ascontiguousarray(index.term)),
        weight=jnp.asarray(np.ascontiguousarray(index.weight)),
        n_pairs=jnp.asarray(index.nnz),
        valid=jnp.ones(index.nnz, index.weight.dtype),
        idf=jnp.asarray(np.ascontiguousarray(index.idf)),
        df=jnp.asarray(np.ascontiguousarray(index.df)),
    )
    k = 10
    n_naive = int(os.environ.get("BENCH_SERVE_NAIVE", 8))
    helper = serving.TfidfServer(index, serving.ServeConfig(top_k=k))
    t0 = time.perf_counter()
    with obs.span("bench.serve_naive", requests=n_naive):
        for terms in queries[:n_naive]:
            qt, qw = helper.make_query(terms)
            qvec = np.zeros(index.vocab_size, index.weight.dtype)
            np.add.at(qvec, qt, qw)
            # a FRESH jit wrapper per request defeats the executable
            # cache: this is the per-request cold cost a process-per-query
            # (or CLI-per-query) deployment pays
            cold = jax.jit(
                lambda r, q: tops.score_query(r, q, n_docs=index.n_docs, k=k)
            )
            scores, idxs = cold(res_dev, jnp.asarray(qvec))
            # the per-request round-trip IS the thing being measured here:
            # this loop exists to price the no-server status quo
            np.asarray(scores), np.asarray(idxs)  # graftlint: disable=host-sync-in-loop
    naive_secs = max(time.perf_counter() - t0, 1e-9)
    naive_qps = n_naive / naive_secs
    log(f"[serve] naive cold loop: {n_naive} req in {naive_secs:.2f}s "
        f"-> {naive_qps:.2f} qps")

    # --- warm batched path at fixed micro-batch sizes, both scoring
    # modes: "coo" (the full-postings scatter/gather, comparable to prior
    # rounds) and "impacted" (ISSUE 13's CSC-by-term run slicing) ---
    def _timed_pass(scoring: str, max_batch: int) -> dict:
        scfg = serving.ServeConfig(top_k=k, max_batch=max_batch,
                                   queue_depth=max(64, 2 * max_batch),
                                   scoring=scoring)
        with serving.TfidfServer(index, scfg) as srv:
            with obs.span("bench.serve_warm", batch=max_batch,
                          scoring=scoring):
                # warm with THROWAWAY queries disjoint from the measured
                # stream: the timed pass must earn its cache hits from
                # genuine repeats, not from a warmup that pre-scored its
                # own prefix
                pendings = [srv.submit([f"warmonly{i}"])
                            for i in range(2 * max_batch)]
                for p in pendings:
                    p.result(60.0)  # warm pass: absorb any residual lazies
                t0 = time.perf_counter()
                pendings = [srv.submit(q) for q in queries]
                lats = []
                for p in pendings:
                    p.result(120.0)
                    lats.append(p.latency_s or 0.0)
                secs = max(time.perf_counter() - t0, 1e-9)
            stats = srv.stats()
        lats.sort()
        return {
            "qps": round(n_queries / secs, 2),
            "p50_ms": round(percentile(lats, 0.50) * 1e3, 3),
            "p99_ms": round(percentile(lats, 0.99) * 1e3, 3),
            "cache_hits": stats["cache_hits"],
            "batches": stats["batches"],
        }

    served: dict = {}
    served_impacted: dict = {}
    for max_batch in (4, 8, 16):
        served[f"b{max_batch}"] = _timed_pass("coo", max_batch)
        log(f"[serve] b{max_batch}: {served[f'b{max_batch}']}")
    for max_batch in (8, 16):
        served_impacted[f"b{max_batch}"] = _timed_pass("impacted", max_batch)
        log(f"[serve] impacted b{max_batch}: "
            f"{served_impacted[f'b{max_batch}']}")
    best_qps = max(v["qps"] for v in served.values())
    return {
        "served_qps": served,
        "served_impacted_qps": served_impacted,
        # flat per-batch latency maps — the trace_diff served-latency
        # regression gate reads these (keys always present on a healthy
        # child; the parent nulls them when the child fails)
        "served_p50_ms": {b: v["p50_ms"] for b, v in served.items()},
        "served_p99_ms": {b: v["p99_ms"] for b, v in served.items()},
        "naive_qps": round(naive_qps, 3),
        "naive_requests": n_naive,
        "requests": n_queries,
        "speedup_vs_naive": round(best_qps / naive_qps, 2),
        "index_nnz": index.nnz,
        "backend": jax.default_backend(),
    }


def measure_serve_scale() -> dict:
    """The ISSUE 13 acceptance measurement: full-COO vs impacted-list
    serving on a ≥1M-doc synthetic Zipf corpus (CPU backend).  The corpus
    is synthesized directly as a postings COO (tokenizing 1M documents is
    ingest-bench territory, not serving-bench) over a Zipf(1.3) word
    distribution whose term ids come from the REAL query-side hash
    pipeline, so served queries hit the same vocabulary.

    Queries sample the Zipf tail past a small stopword head (real query
    pipelines strip stopwords; an impacted list for a term that appears
    in most documents IS the corpus).  Reported: QPS + p50/p99 per path
    at one fixed batch size, and the QPS ratio at no-worse p99 — the
    ">=10x served QPS at fixed p99" acceptance bar."""
    from page_rank_and_tfidf_using_apache_spark_tpu import obs

    with obs.run("serve_scale"):
        return _measure_serve_scale_traced(obs)


def _measure_serve_scale_traced(obs) -> dict:
    import shutil
    import tempfile as tf

    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu import serving
    from page_rank_and_tfidf_using_apache_spark_tpu.io import text as tio
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        TfidfOutput,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        TfidfConfig,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
        percentile,
    )

    n_docs = int(os.environ.get("BENCH_SCALE_DOCS", str(1 << 20)))
    words = 50_000
    terms_per_doc = 18
    stop_head = 16  # query-side stopword strip (the corpus keeps them)
    vocab_bits = 18
    cfg = TfidfConfig(vocab_bits=vocab_bits)
    rng = np.random.default_rng(SEED)

    with obs.span("bench.scale_corpus", n_docs=n_docs):
        # word -> hashed term id through the REAL query hash pipeline
        word_tid = tio.hash_to_vocab(
            tio.fnv1a_64([f"w{i}" for i in range(words)]), vocab_bits
        ).astype(np.int64)
        wid = (rng.zipf(1.3, n_docs * terms_per_doc) - 1) % words
        doc = np.repeat(np.arange(n_docs, dtype=np.int64), terms_per_doc)
        term = word_tid[wid]
        key = term * n_docs + doc
        uniq, count = np.unique(key, return_counts=True)
        term_u = (uniq // n_docs).astype(np.int32)
        doc_u = (uniq % n_docs).astype(np.int32)
        count = count.astype(np.float32)
        df = np.bincount(term_u, minlength=1 << vocab_bits).astype(
            np.float32)
        idf = np.where(df > 0, np.log(n_docs / np.maximum(df, 1.0)),
                       0.0).astype(np.float32)
        weight = count * idf[term_u]
        out = TfidfOutput(
            n_docs=n_docs, vocab_bits=vocab_bits, doc=doc_u, term=term_u,
            weight=weight, df=df, idf=idf, metrics=MetricsRecorder(),
            count=count,
            doc_lengths=np.full(n_docs, terms_per_doc, np.int32),
        )
    idx_dir = tf.mkdtemp(prefix="bench_scale_idx_")
    try:
        with obs.span("bench.scale_index", nnz=int(out.nnz)):
            serving.save_index(idx_dir, out, cfg)
            index = serving.load_index(idx_dir)
        log(f"[serve-scale] {index.n_docs} docs, {index.nnz} nnz")

        def gen_queries(n: int) -> list[list[str]]:
            qs = []
            for _ in range(n):
                t = int(rng.integers(2, 5))
                qs.append([
                    f"w{stop_head + (int(rng.zipf(1.3)) - 1) % (words - stop_head)}"
                    for _ in range(t)
                ])
            return qs

        k = 10
        batch = 8
        results: dict = {}
        for scoring, n_q in (("coo", int(os.environ.get(
                "BENCH_SCALE_COO_QUERIES", "48"))),
                ("impacted", int(os.environ.get(
                    "BENCH_SCALE_IMPACTED_QUERIES", "512")))):
            queries = gen_queries(n_q)
            scfg = serving.ServeConfig(
                top_k=k, max_batch=batch, queue_depth=4 * batch,
                cache_size=0,  # raw path cost: no LRU flattery
                scoring=scoring,
                impact_warm_buckets=1 << 15,
            )
            with serving.TfidfServer(index, scfg) as srv:
                with obs.span("bench.scale_serve", scoring=scoring,
                              requests=n_q):
                    warm = [srv.submit(q) for q in gen_queries(2 * batch)]
                    for p in warm:
                        p.result(600.0)
                    t0 = time.perf_counter()
                    pend = [srv.submit(q) for q in queries]
                    lats = []
                    for p in pend:
                        p.result(600.0)
                        lats.append(p.latency_s or 0.0)
                    secs = max(time.perf_counter() - t0, 1e-9)
            lats.sort()
            results[scoring] = {
                "qps": round(n_q / secs, 2),
                "p50_ms": round(percentile(lats, 0.50) * 1e3, 3),
                "p99_ms": round(percentile(lats, 0.99) * 1e3, 3),
                "requests": n_q,
            }
            log(f"[serve-scale] {scoring}: {results[scoring]}")
        coo, imp = results["coo"], results["impacted"]
        return {
            "n_docs": n_docs,
            "nnz": index.nnz,
            "batch": batch,
            "coo": coo,
            "impacted": imp,
            "qps_speedup": round(imp["qps"] / max(coo["qps"], 1e-9), 2),
            # ">=10x at fixed p99": the QPS ratio counts only while the
            # impacted path's p99 is no worse than the COO path's
            "p99_no_worse": imp["p99_ms"] <= coo["p99_ms"],
            "backend": jax.default_backend(),
        }
    finally:
        shutil.rmtree(idx_dir, ignore_errors=True)


def measure_workloads() -> dict:
    """Dataflow-workloads bench (ISSUE 9): trajectory numbers for the
    three workloads the dataflow core opened —

    - ``ppr_batch_queries_per_sec``: a B-query batch of personalized
      PageRank runs as ONE vmapped fixpoint over the shared bench graph;
      queries/sec = B / warm wall for ``BENCH_PPR_ITERS`` iterations.
    - ``cc_iters_per_sec``: min-label-propagation rounds/sec on the same
      graph (capped rounds — a throughput gauge, not a convergence race).
    - ``bm25_vs_tfidf_served_qps``: the serving A/B — the same corpus
      index served under each ranker through the warm batched path.
    """
    from page_rank_and_tfidf_using_apache_spark_tpu import obs

    with obs.run("workloads"):
        return _measure_workloads_traced(obs)


def _measure_workloads_traced(obs) -> dict:
    import jax
    import jax.numpy as jnp

    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.components import (
        make_components_runner,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.ppr import (
        make_ppr_batch_runner,
        restart_batch,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        ComponentsConfig,
        PageRankConfig,
    )

    out: dict = {"backend": jax.default_backend()}
    with obs.span("bench.graph"):
        graph = _build_graph()
        n = graph.n_nodes

    # --- batched personalized PageRank ---
    b = int(os.environ.get("BENCH_PPR_BATCH", 8))
    ppr_iters = int(os.environ.get("BENCH_PPR_ITERS", 10))
    cfg = PageRankConfig(iterations=ppr_iters, dangling="redistribute",
                         init="uniform", spmv_impl="cumsum")
    rng = np.random.default_rng(SEED)
    queries = [[int(graph.node_ids[i])]
               for i in rng.integers(0, n, size=b)]
    with obs.span("bench.ppr_setup"):
        dg = ops.put_graph(graph, "float32")
        e_b = jax.device_put(restart_batch(graph, cfg, queries))
        runner = make_ppr_batch_runner(n, cfg)
        ranks0_host = np.broadcast_to(
            ops.init_ranks(n, cfg), (b, n)
        ).copy()

    def ppr_once():
        r0 = jax.device_put(ranks0_host)
        float(r0[0, 0])  # fence the H2D put outside the timed region
        t0 = time.perf_counter()
        ranks, it, delta = runner(dg, r0, e_b)
        checksum = float(jnp.sum(ranks))
        return time.perf_counter() - t0, checksum

    with obs.span("bench.ppr_compile"):
        ppr_once()
    with obs.span("bench.ppr"):
        secs, checksum = min(ppr_once() for _ in range(2))
    out["ppr_batch_queries_per_sec"] = round(b / secs, 3)
    out["ppr_batch"] = b
    out["ppr_iters"] = ppr_iters
    log(f"[workloads] ppr: {b} queries x {ppr_iters} iters in {secs:.2f}s "
        f"-> {out['ppr_batch_queries_per_sec']} q/s (checksum {checksum:.3f})")

    # --- connected components (label propagation) ---
    cc_rounds = int(os.environ.get("BENCH_CC_ROUNDS", 20))
    ccfg = ComponentsConfig(iterations=cc_rounds, tol=0.0)  # fixed rounds
    with obs.span("bench.cc_setup"):
        cc_runner = make_components_runner(n, ccfg)
        labels_host = np.arange(n, dtype=np.int32)

    def cc_once():
        l0 = jax.device_put(labels_host)
        int(l0[0])
        t0 = time.perf_counter()
        labels, it, changed = cc_runner(dg, l0)
        k = int(labels[0])  # scalar fence
        return time.perf_counter() - t0, k

    with obs.span("bench.cc_compile"):
        cc_once()
    with obs.span("bench.cc"):
        secs, _ = min(cc_once() for _ in range(2))
    out["cc_iters_per_sec"] = round(cc_rounds / secs, 3)
    out["cc_rounds"] = cc_rounds
    log(f"[workloads] cc: {cc_rounds} rounds in {secs:.2f}s -> "
        f"{out['cc_iters_per_sec']} iters/s")

    # --- BM25 vs TF-IDF served QPS (the serving A/B) ---
    import shutil
    import tempfile as tf

    from page_rank_and_tfidf_using_apache_spark_tpu import serving
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import run_tfidf
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        Bm25Config,
        TfidfConfig,
    )

    with obs.span("bench.corpus"):
        docs = _corpus()
    idx_dir = tf.mkdtemp(prefix="bench_workloads_idx_")
    try:
        with obs.span("bench.index_build"):
            tout = run_tfidf(docs, TfidfConfig(vocab_bits=18))
            serving.save_index(idx_dir, tout, TfidfConfig(vocab_bits=18),
                               bm25=Bm25Config())
            index = serving.load_index(idx_dir)
        n_q = int(os.environ.get("BENCH_AB_QUERIES", 128))
        queries = [[f"w{rng.zipf(1.3) % 50_000}"
                    for _ in range(int(rng.integers(2, 5)))]
                   for _ in range(n_q)]
        ab: dict = {}
        for ranker in ("tfidf", "bm25"):
            scfg = serving.ServeConfig(top_k=10, max_batch=8, cache_size=0)
            with serving.TfidfServer(index, scfg) as srv:
                with obs.span("bench.serve_ab", ranker=ranker):
                    warm = [srv.submit([f"warmonly{i}"], ranker=ranker)
                            for i in range(16)]
                    for p in warm:
                        p.result(60.0)
                    t0 = time.perf_counter()
                    pend = [srv.submit(q, ranker=ranker) for q in queries]
                    for p in pend:
                        p.result(120.0)
                    secs = max(time.perf_counter() - t0, 1e-9)
            ab[ranker] = round(n_q / secs, 2)
            log(f"[workloads] serve {ranker}: {ab[ranker]} qps")
        ab["bm25_over_tfidf"] = round(ab["bm25"] / max(ab["tfidf"], 1e-9), 3)
        out["bm25_vs_tfidf_served_qps"] = ab
    finally:
        shutil.rmtree(idx_dir, ignore_errors=True)
    return out


def measure_owned_scale() -> dict:
    """Owned-strategy scale sweep (ISSUE 15 acceptance): seeded Zipf
    graphs (power-law BOTH degree axes — the web-graph shape) at
    ``BENCH_OWNED_SCALES`` multiples of web-Google's node count run
    end-to-end under ``strategy='owned'`` on the host mesh, recording the
    per-step comm bytes each partition publishes.  The fitted
    log-log exponent of comm bytes vs node count must come out < 1 (the
    sublinearity claim), and the TOP scale is asserted un-runnable
    replicated: its node state exceeds the declared per-device budget
    (``BENCH_OWNED_HBM_BYTES``) and ``auto_select_strategy`` under that
    budget picks ``owned`` — "fits because every chip holds everything"
    vs "scales because no chip has to", as a measured record."""
    from page_rank_and_tfidf_using_apache_spark_tpu import obs

    with obs.run("owned_scale"):
        return _measure_owned_scale_traced(obs)


def _measure_owned_scale_traced(obs) -> dict:
    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        synthetic_zipf,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
        auto_select_strategy,
        run_pagerank_sharded,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        PageRankConfig,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    out: dict = {"backend": jax.default_backend(), "scales": {}}
    scales = [
        float(s)
        for s in os.environ.get("BENCH_OWNED_SCALES", "1,4,10").split(",")
        if s.strip()
    ]
    if not scales:  # BENCH_OWNED_SCALES="" = the documented skip spelling
        out["skipped"] = True
        return out
    base_n = int(os.environ.get("BENCH_OWNED_BASE_NODES", N_NODES))
    avg_deg = float(os.environ.get("BENCH_OWNED_AVG_DEG",
                                   N_EDGES / N_NODES))
    budget = int(os.environ.get("BENCH_OWNED_HBM_BYTES", 256 << 20))
    iters = int(os.environ.get("BENCH_OWNED_ITERS", "2"))
    d = min(8, len(jax.devices()))
    out["devices"] = d
    pts: list[tuple[int, int]] = []
    top = None
    for s in sorted(scales):
        n, e = int(base_n * s), int(base_n * s * avg_deg)
        with obs.span("owned_scale.graph", scale=s):
            graph = synthetic_zipf(n, e, seed=SEED, src_exponent=1.5)
        m = MetricsRecorder()
        cfg = PageRankConfig(iterations=iters, dangling="redistribute",
                             init="uniform", dtype="float32")
        with obs.span("owned_scale.run", scale=s):
            t0 = time.perf_counter()
            res = run_pagerank_sharded(graph, cfg, n_devices=d,
                                       strategy="owned", metrics=m)
            secs = time.perf_counter() - t0
        part = next(r for r in m.records if r.get("event") == "partition")
        checksum = float(res.ranks.sum())
        assert 0.99 < checksum < 1.01, checksum  # mass conserved
        label = f"{s:g}x"
        out["scales"][label] = {
            "nodes": n, "edges": e,
            "comm_bytes_per_step": int(part["comm_bytes_per_step"]),
            "pad_frac": part["pad_frac"],
            "iters_per_sec": round(res.iterations / max(secs, 1e-9), 3),
            "checksum": round(checksum, 6),
        }
        obs.gauge(f"owned_scale.comm_bytes.{label}",
                  part["comm_bytes_per_step"])
        log(f"[owned-scale] {label}: n={n} e={e} "
            f"comm={part['comm_bytes_per_step']} B/step "
            f"({res.iterations} iters in {secs:.1f}s)")
        pts.append((n, int(part["comm_bytes_per_step"])))
        top = graph
    if len(pts) >= 2:
        ln = np.log([float(p[0]) for p in pts])
        lc = np.log([float(max(p[1], 1)) for p in pts])
        out["comm_scaling_exponent"] = round(float(np.polyfit(ln, lc, 1)[0]), 3)
        # the sublinear bar — enforced when the sweep spans enough range
        # for the fit to outrun the pow2 boundary-buffer quantization
        # (adjacent pow2 caps alias the exponent at tiny test scales)
        if pts[-1][0] >= 4 * pts[0][0]:
            assert out["comm_scaling_exponent"] < 1.0, out
    # the replicated wall, asserted at the TOP scale, through the SAME
    # footprint model auto_select_strategy gates on
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
        replicated_state_bytes,
    )

    top_n, top_e = pts[-1][0], int(pts[-1][0] * avg_deg)
    replicated = replicated_state_bytes(top_n, top_e, d)
    does_not_fit = replicated > budget / 2
    choice = auto_select_strategy(top, d, hbm_bytes=budget)
    out["replicated_wall"] = {
        "per_device_budget_bytes": budget,
        "replicated_state_bytes": replicated,
        "does_not_fit": bool(does_not_fit),
        "auto_select": choice,
    }
    if len(scales) > 1:  # the full sweep must actually hit the wall
        assert does_not_fit and choice == "owned", out["replicated_wall"]
    return out


def measure_soak() -> dict:
    """Production-soak child (ISSUE 11): continuous streaming ingest +
    index rebuild/hot-swap + mixed tfidf/bm25/@prior closed-loop traffic
    + background PageRank-prior refresh + deterministic chaos (>=1
    injected device loss), scored on SLOs — served p50/p99 under ingest
    load, error-budget burn, time-to-recover, and the zero-dropped /
    zero-double-served invariants.  Shaped by the GRAFT_SOAK_* env knobs
    (duration/QPS/SLO targets); emits ONE ``slo`` record the parent
    copies into ``extra.slo`` and trace_diff regresses across rounds."""
    from page_rank_and_tfidf_using_apache_spark_tpu import obs
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.soak import (
        SoakConfig,
        run_soak,
    )

    with obs.run("soak"):
        return run_soak(SoakConfig.from_env())


def measure_serve_fabric() -> dict:
    """Multi-process serving fabric child (ISSUE 17): saturated fleet
    QPS at N=1 vs N=GRAFT_FABRIC_REPLICAS replica processes mmap-loading
    the SAME sealed segment artifacts, plus a SIGKILL-recovery probe —
    one replica is hard-killed mid-traffic and the supervisor-measured
    respawn time and the cross-process dropped / double-served audit are
    recorded.  Honesty note: on a single-core host every replica process
    contends for the same CPU, so n4/n1 lands near 1x (plus router/IPC
    overhead) — the fleet buys fault isolation there, not throughput;
    the >=3x scaling claim needs cores (recorded via ``cpus``)."""
    import shutil
    import threading
    import urllib.request

    from page_rank_and_tfidf_using_apache_spark_tpu import obs
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        fabric as fb,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        segments as sgm,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        Bm25Config,
        TfidfConfig,
    )

    rng = np.random.default_rng(17)
    vocab = [f"term{i:03d}" for i in range(160)]
    docs = [" ".join(rng.choice(vocab, size=30).tolist())
            for _ in range(48)]
    scfg = TfidfConfig(vocab_bits=10)
    n = max(2, int(os.environ.get("GRAFT_FABRIC_REPLICAS", "4")))
    window_s = float(os.environ.get("BENCH_FABRIC_WINDOW_S", "8"))
    queries = [[vocab[i], vocab[(i * 7 + 3) % len(vocab)]]
               for i in range(32)]

    def _arm(index_dir: str, replicas: int, kill: bool) -> dict:
        cfg = fb.FabricConfig(
            replicas=replicas, poll_s=0.2, health_period_s=0.3,
            retry_limit=120, retry_pause_s=0.1, grace_s=10.0,
        )
        served = 0
        recovery_s = None
        with fb.ServingFabric(index_dir, cfg) as fab:
            for q in queries[: 2 * replicas]:  # warm every replica
                fab.query(q)
            t0 = time.perf_counter()
            kill_at = t0 + window_s / 3.0
            k0 = None
            while time.perf_counter() - t0 < window_s:
                if kill and k0 is None and time.perf_counter() >= kill_at:
                    fab.kill_replica(0)
                    k0 = time.perf_counter()
                fab.query(queries[served % len(queries)])
                served += 1
            if k0 is not None:
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    if (fab.audit()["respawns"] >= 1
                            and all(s is not None and s.get("ready")
                                    for s in fab.statuses())):
                        recovery_s = round(time.perf_counter() - k0, 2)
                        break
                    time.sleep(0.2)
            audit = fab.audit()
        return {"qps": round(served / window_s, 1),
                "recovery_s": recovery_s,
                "dropped": int(audit["dropped"]),
                "double_served": int(audit["double_served"])}

    def _fed_arm(index_dir: str) -> tuple:
        """Federation + autoscale probe (ISSUE 19): a 1-replica fleet
        with the router-side FleetHub scraping, one real scrape sweep
        into the exact merged board, then a forced control-loop exercise
        — a synthetic-burn tick scales 1->2 and an idle tick drains back
        — so every round records a real spawn AND drain through the
        autoscaler's own path, deterministically (no load-timing
        dependence)."""
        cfg = fb.FabricConfig(
            replicas=1, poll_s=0.2, health_period_s=0.3,
            retry_limit=120, retry_pause_s=0.1, grace_s=10.0,
            latency_slo_s=0.5, availability_target=0.999,
        )
        with fb.ServingFabric(index_dir, cfg) as fab:
            for q in queries[:16]:
                fab.query(q)
            fab.fleet.scrape_once()
            snap = fab.fleet.snapshot()
            scaler = fb.Autoscaler(fab, fb.AutoscaleConfig(
                min_replicas=1, max_replicas=2, cooldown_s=0.0,
                idle_hold_s=0.0))
            scaler.tick({"budgets": {"availability": {"burn_rate": 10.0}}})
            scaler.tick({})
            stats = scaler.stats()
            audit = fab.audit()
        win = (snap.get("latency_s") or {}).get("window") or {}
        flt = snap.get("fleet") or {}
        p99 = win.get("p99")
        fed = {
            "replicas": len(flt.get("replicas") or []),
            "stale": len(flt.get("stale") or []),
            "staleness_s_max": (snap.get("gauges") or {}).get(
                "fed_staleness_s_max"),
            "scrapes": flt.get("scrapes"),
            "scrape_errors": flt.get("scrape_errors"),
            "p99_ms": None if p99 is None else round(p99 * 1e3, 3),
        }
        stats["scale_ups"] = int(audit.get("scale_ups", 0))
        stats["scale_downs"] = int(audit.get("scale_downs", 0))
        return fed, stats

    def _roll_arm(index_dir: str) -> dict:
        """Drain-handoff probe (ISSUE 20): a rolling restart under a
        closed-loop load thread.  With the socket handoff carrying the
        roll, retries attributed to the roll window must be ZERO — the
        number trace_diff gates as an invariant."""
        cfg = fb.FabricConfig(
            replicas=2, poll_s=0.2, health_period_s=0.3,
            retry_limit=120, retry_pause_s=0.1, grace_s=10.0,
        )
        with fb.ServingFabric(index_dir, cfg) as fab:
            for q in queries[:4]:
                fab.query(q)
            stop_evt = threading.Event()

            def load():
                i = 0
                while not stop_evt.is_set():
                    fab.query(queries[i % len(queries)])
                    i += 1

            t = threading.Thread(target=load, daemon=True,
                                 name="bench-roll-load")
            t.start()
            try:
                fab.rolling_restart(timeout=60.0)
            finally:
                stop_evt.set()
                t.join(10.0)
            audit = fab.audit()
        return {"roll_retries": int(audit["roll_retries"]),
                "rolled": int(audit["rolled"]),
                "dropped": int(audit["dropped"]),
                "double_served": int(audit["double_served"])}

    def _cache_arm(index_dir: str) -> dict:
        """Sharded-cache A/B (ISSUE 20): the SAME Zipf-skewed stream
        driven round-robin DIRECTLY at the replica /query endpoints
        (every replica sees every hot key — the worst case for isolated
        per-replica LRUs), with LRUs sized well below the key set.  Arm
        A is the PR-17 fleet (peer_cache off), arm B the sharded cache;
        the fleet-wide execution count measures duplicate computes and
        every response is checked byte-equal across paths."""
        stream_rng = np.random.default_rng(20)
        ranks = np.arange(1, len(queries) + 1, dtype=np.float64)  # graftlint: disable=dtype-drift (host-only Zipf weight math for rng.choice; never dispatched)
        weights = 1.0 / ranks ** 1.1
        weights /= weights.sum()
        stream = stream_rng.choice(len(queries), size=240, p=weights)

        def drive(peer_cache: bool) -> dict:
            cfg = fb.FabricConfig(
                replicas=n, poll_s=0.2, health_period_s=0.3,
                retry_limit=120, retry_pause_s=0.1, grace_s=10.0,
                peer_cache=peer_cache, cache_size=8,
            )
            served: dict[int, list] = {}
            with fb.ServingFabric(index_dir, cfg) as fab:
                ports = [fab._ports[i] for i in sorted(fab._ports)]
                for j, qi in enumerate(stream):
                    doc = json.dumps({
                        "rid": f"cache-{int(peer_cache)}-{j}",
                        "terms": queries[qi], "ranker": "tfidf",
                    }).encode()
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{ports[j % len(ports)]}/query",
                        data=doc, method="POST",
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=10.0) as r:
                        out = json.loads(r.read())
                    key = int(qi)
                    pair = [out["scores"], out["docs"]]
                    # byte-equality across every serve path (local
                    # compute, local LRU, peer peek, filled owner)
                    if served.setdefault(key, pair) != pair:
                        raise AssertionError(
                            f"divergent bytes for query {key}")
                sts = [s for s in fab.statuses() if s is not None]
                # computes, not serves: "executions" counts every
                # first-time rid INCLUDING peer-hit serves (which never
                # touch the dispatch queue), so the A/B signal lives in
                # the server-level requests − cache_hits — submits that
                # actually reached a dispatch
                computes = sum(int(s.get("requests") or 0)
                               - int(s.get("cache_hits") or 0)
                               for s in sts)
                hits = sum(int(s.get("peer_hits") or 0) for s in sts)
                misses = sum(int(s.get("peer_misses") or 0) for s in sts)
                tos = sum(int(s.get("peek_timeouts") or 0) for s in sts)
            attempts = hits + misses + tos
            return {"computes": computes, "peer_hits": hits,
                    "peer_hit_rate": (round(hits / attempts, 4)
                                      if attempts else None)}

        a = drive(False)
        b = drive(True)
        return {
            "computes_local_only": a["computes"],
            "computes_sharded": b["computes"],
            "peer_hit_rate": b["peer_hit_rate"],
            # duplicate-compute reduction, the number the sharded cache
            # exists to buy: >1 means fewer fleet-wide computes for
            # the SAME skewed stream and byte-identical answers
            "speedup": (round(a["computes"] / b["computes"], 3)
                        if b["computes"] else None),
        }

    tmp = tempfile.mkdtemp(prefix="bench_fabric_")
    try:
        out = run_tfidf(docs, scfg)
        ref = sgm.seal_segment(tmp, out, scfg, doc_base=0,
                               ranks=np.ones(out.n_docs, np.float32),
                               bm25=Bm25Config())
        sgm.commit_append(tmp, ref, scfg.config_hash())
        with obs.run("serve_fabric"):
            one = _arm(tmp, 1, kill=False)
            fleet = _arm(tmp, n, kill=True)
            try:
                fed, scale = _fed_arm(tmp)
            except Exception:  # noqa: BLE001 — federation probe is additive:
                fed, scale = None, None  # null keys, fabric numbers survive
            try:
                roll = _roll_arm(tmp)
            except Exception:  # noqa: BLE001 — additive probe, null keys
                roll = None
            try:
                cache = _cache_arm(tmp)
            except Exception:  # noqa: BLE001 — additive probe, null keys
                cache = None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis.protocol import (
        wire_fingerprint,
    )

    cpus = os.cpu_count()
    return {
        "fabric_qps": {"n1": one["qps"], f"n{n}": fleet["qps"]},
        "fabric_replicas": n,
        "fabric_recovery_s": fleet["recovery_s"],
        "fabric_dropped": one["dropped"] + fleet["dropped"],
        "fabric_double_served": (one["double_served"]
                                 + fleet["double_served"]),
        "fabric_cpus": cpus,
        # WIRE_SCHEMAS generation these numbers were measured against:
        # trace_diff arms fresh (no regression compare) across rounds
        # whose fingerprints differ — the wire contract changed.
        "fabric_proto_fingerprint": wire_fingerprint(),
        # cpus < replicas: the fleet arms contended for the same cores,
        # so the nN/n1 ratio is context, not a gated scaling claim.
        "fabric_scaling_nongating": bool(cpus is not None and cpus < n),
        # ISSUE 19: the fleet-federation board (replicas scraped, stale
        # count, max staleness, fleet-aggregate p99) and the autoscaler's
        # decision tallies from the forced scale exercise — null when the
        # federation probe failed (the fabric numbers above survive).
        "fleet_federation": fed,
        "autoscale": scale,
        # ISSUE 20: retries attributed to a handoff-carried rolling
        # restart under closed-loop load (the zero-retry claim), and
        # the sharded-cache A/B under the Zipf-skewed stream — the
        # cross-replica hit rate and the duplicate-compute reduction
        # vs the isolated-LRU fleet.  Null = the probe failed.
        "fabric_roll_retries": (None if roll is None
                                else roll["roll_retries"]),
        "fabric_roll": roll,
        "cache_peer_hit_rate": (None if cache is None
                                else cache["peer_hit_rate"]),
        "cache_speedup_skewed": (None if cache is None
                                 else cache["speedup"]),
        "cache_ab": cache,
    }


def measure_tfidf_sharded() -> dict:
    """Sharded (multi-device) ingest throughput — the ROADMAP's
    ``tfidf_sharded_tokens_per_sec``, null in every round before this
    landed.  Runs the data-parallel super-chunk ingest over a real mesh
    (simulated CPU devices when no TPU pod is attached: the parent arms
    ``xla_force_host_platform_device_count`` for this child)."""
    from page_rank_and_tfidf_using_apache_spark_tpu import obs

    with obs.run("tfidf_sharded"):
        return _measure_tfidf_sharded_traced(obs)


def _measure_tfidf_sharded_traced(obs) -> dict:
    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
        DATA_AXIS,
        make_mesh,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.tfidf_sharded import (
        run_tfidf_sharded,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig

    with obs.span("bench.corpus"):
        docs = _corpus()
    d = min(int(os.environ.get("BENCH_TFIDF_SHARDED_DEVICES", "4")),
            len(jax.devices()))
    mesh = make_mesh(d, DATA_AXIS)
    chunk_docs = int(os.environ.get("BENCH_TFIDF_CHUNK_DOCS", "512"))
    chunks = [docs[i:i + chunk_docs] for i in range(0, len(docs), chunk_docs)]
    # pack to the compiled cap + stage the sharded puts of super-chunk
    # N+1 under super-chunk N's compute (same staged pipeline as the
    # single-chip streaming child, ISSUE 10)
    cfg = TfidfConfig(vocab_bits=18, chunk_tokens=1 << 17,
                      pack_target_tokens=1 << 17,
                      prefetch=2, pipeline_depth=2)

    def tokens(out) -> int:
        return int(sum(r["tokens"] for r in out.metrics.records
                       if r.get("event") == "super_chunk"))

    with obs.span("bench.sharded_warmup"):
        out = run_tfidf_sharded(iter(chunks), cfg, mesh=mesh)  # compile pass
    t0 = time.perf_counter()
    with obs.span("bench.sharded"):
        out = run_tfidf_sharded(iter(chunks), cfg, mesh=mesh)
    secs = max(time.perf_counter() - t0, 1e-9)
    toks = tokens(out)
    tps = toks / secs
    overlap = _ingest_overlap_frac(out.metrics)
    log(f"[tfidf-sharded] {len(chunks)} chunks over {d} devices: "
        f"{secs:.2f}s -> {tps / 1e6:.2f} M tokens/s, "
        f"h2d_overlap {overlap}, nnz={out.nnz}")
    return {"sharded_tokens_per_sec": tps, "devices": d,
            "h2d_overlap_frac": overlap,
            "n_tokens": toks, "nnz": out.nnz,
            "backend": jax.default_backend()}


def measure_autotuned_ab() -> dict:
    """Autotuned-vs-default A/B arm (ISSUE 16).  The parent runs this
    child TWICE — once with ``GRAFT_TUNED_PROFILE`` pointing at the
    committed profile, once with it ``off`` — and divides the arms into
    the ``autotuned_vs_default`` speedup keys.  The child itself only
    resolves knobs through the production ladder
    (``load_tuned_profile``/``tuned_config``): whatever the profile says
    is what gets measured, exactly as a real runner would see it."""
    from page_rank_and_tfidf_using_apache_spark_tpu import obs

    with obs.run("autotuned_ab"):
        return _measure_autotuned_ab_traced(obs)


def _measure_autotuned_ab_traced(obs) -> dict:
    import shutil

    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu import serving
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        synthetic_powerlaw,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
        iter_corpus_chunks,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import (
        run_pagerank,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        run_tfidf,
        run_tfidf_streaming,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        PageRankConfig,
        TfidfConfig,
        load_tuned_profile,
        tuned_config,
    )

    profile = load_tuned_profile()  # env-resolved: the arm under test
    out: dict = {
        "profile_loaded": profile is not None,
        "profile_path": profile.path if profile else None,
        "backend": jax.default_backend(),
        "stream_tokens_per_sec": None,
        "hybrid_iters_per_sec": None,
        "served_qps": None,
    }

    # ragged corpus: the chunk-packing knob only matters when fixed
    # doc-count chunks arrive half-full, so doc sizes are log-normal like
    # real corpora (a constant-size corpus would hide the pack win)
    rng = np.random.default_rng(SEED)
    docs = []
    for _ in range(1536):
        n = int(np.clip(rng.lognormal(4.6, 0.9), 8, 1200))
        docs.append(" ".join(f"w{rng.zipf(1.3) % 50_000}"
                             for _ in range(n)))
    n_tokens = sum(len(d.split()) for d in docs)

    with obs.span("bench.ab_stream"):
        cfg = tuned_config(TfidfConfig, profile, vocab_bits=16)
        run_tfidf_streaming(iter_corpus_chunks(iter(docs), 96), cfg)  # warm
        best = math.inf
        for _ in range(2):
            t0 = time.perf_counter()
            run_tfidf_streaming(iter_corpus_chunks(iter(docs), 96), cfg)
            best = min(best, time.perf_counter() - t0)
        out["stream_tokens_per_sec"] = round(n_tokens / best, 1)

    with obs.span("bench.ab_hybrid"):
        graph = synthetic_powerlaw(20_000, 160_000, seed=SEED)
        pcfg = tuned_config(PageRankConfig, profile, iterations=8,
                            spmv_impl="hybrid")
        run_pagerank(graph, pcfg)  # warm
        best = math.inf
        for _ in range(3):
            t0 = time.perf_counter()
            run_pagerank(graph, pcfg)
            best = min(best, time.perf_counter() - t0)
        out["hybrid_iters_per_sec"] = round(pcfg.iterations / best, 2)

    with obs.span("bench.ab_serve"):
        idx_dir = tempfile.mkdtemp(prefix="bench_ab_idx_")
        try:
            tcfg = TfidfConfig(vocab_bits=14)
            res = run_tfidf(docs[:512], tcfg)
            serving.save_index(idx_dir, res, tcfg)
            index = serving.load_index(idx_dir)
            scfg = tuned_config(serving.ServeConfig, profile,
                                top_k=10, scoring="impacted")
            queries = [[f"w{rng.zipf(1.3) % 50_000}"
                        for _ in range(int(rng.integers(2, 5)))]
                       for _ in range(192)]
            with serving.TfidfServer(index, scfg) as srv:
                warm = [srv.submit([f"warmonly{i}"])
                        for i in range(2 * scfg.max_batch)]
                for p in warm:
                    p.result(120.0)
                best = math.inf
                for _ in range(2):
                    t0 = time.perf_counter()
                    pend = [srv.submit(q) for q in queries]
                    for p in pend:
                        p.result(120.0)
                    best = min(best, time.perf_counter() - t0)
            out["served_qps"] = round(len(queries) / best, 2)
        finally:
            shutil.rmtree(idx_dir, ignore_errors=True)

    log(f"[autotuned-ab] profile={'on' if profile else 'off'} "
        f"stream={out['stream_tokens_per_sec']} tok/s "
        f"hybrid={out['hybrid_iters_per_sec']} it/s "
        f"served={out['served_qps']} qps")
    return out


# --------------------------------------------------------------------------
# parent orchestration (NO jax imports in this section)
# --------------------------------------------------------------------------

def _trace_report_module():
    """Load tools/trace_report.py (stdlib-only, NO package/jax imports —
    safe in the parent) for turning child trace artifacts into the BENCH
    record's per-phase breakdown."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "trace_report.py")
    spec = importlib.util.spec_from_file_location("bench_trace_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _prior_sync_p99(base: str | None) -> float | None:
    """p99 healthy-sync duration from the most recent PRIOR bench round's
    tfidf trace artifact under the persistent trace root (BENCH_TRACE_DIR).
    None without a persistent root or a readable prior artifact — rounds
    with an ephemeral tmpdir root can never see a prior round."""
    if not base:
        return None
    import glob

    me = os.path.join(base, f"run_{os.getpid()}")
    paths = [
        p
        for p in glob.glob(os.path.join(base, "run_*", "tfidf.*.trace.jsonl"))
        if not p.startswith(me + os.sep)
    ]
    if not paths:
        return None
    latest = max(paths, key=os.path.getmtime)
    try:
        p99 = _trace_report_module().sync_p99(latest)
    except Exception as exc:  # a broken artifact must not block the bench
        log(f"[deadline] unreadable prior trace {latest}: {exc}")
        return None
    if p99 is not None:
        log(f"[deadline] prior-round sync p99 {p99:.3f}s ({latest})")
    return p99


def _effective_sync_deadline(knob_s: float, prior_p99_s: float | None) -> float:
    """PR-3 armed a fixed 120 s child sync deadline; this re-validates it
    against observed behavior: when a prior round's trace artifact exists,
    the deadline is max(knob, 3 x that round's p99 sync span) — generous
    enough that a tunnel merely being slow never trips the watchdog, tight
    enough that a wedged sync dies in seconds-to-minutes, not at the
    parent's 420 s kill.  knob 0 keeps the watchdog disabled."""
    if knob_s <= 0 or prior_p99_s is None:
        return knob_s
    return max(knob_s, 3.0 * prior_p99_s)


def _tfidf_trace_accounting(trace_dir: str) -> dict | None:
    """Per-phase accounting of the (latest) tfidf child from its trace
    artifact — works for healthy, resumed and timeout-killed children
    alike, because the JSONL sink flushes per event.  Reads the artifact,
    never the child's stderr."""
    import glob

    paths = sorted(glob.glob(os.path.join(trace_dir, "tfidf.*.trace.jsonl")),
                   key=os.path.getmtime)
    if not paths:
        return None
    try:
        rep = _trace_report_module().report(paths[-1])
    except Exception as exc:  # a broken trace must not kill the bench
        log(f"[trace] unreadable tfidf trace: {type(exc).__name__}: {exc}")
        return None
    return None if rep.get("empty") else rep


def _read_ckpt_meta(ck_dir: str) -> dict | None:
    """Read the latest chunk-checkpoint's metadata without importing the
    package (whose import chain reaches jax — forbidden in the parent).
    Mirrors utils/checkpoint.py's LATEST-pointer + embedded-meta format."""
    try:
        with open(os.path.join(ck_dir, "LATEST")) as f:
            name = f.read().strip()
        with np.load(os.path.join(ck_dir, name)) as z:
            return json.loads(bytes(z["__ckpt_meta__"]).decode())
    except Exception:
        return None


def _lint_clean() -> bool | None:
    """Run the graftlint gate (all six tiers — lexical, semantic, cost,
    concurrency, persistence, protocol — in a CPU-only subprocess) and
    report its verdict, so every BENCH_*.json records whether the measured tree
    passed static analysis.  None = the gate itself could not run (never
    blocks the bench)."""
    lint_sh = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tools", "lint.sh")
    try:
        proc = subprocess.run(
            [lint_sh], capture_output=True, text=True, timeout=180,
        )
    except (OSError, subprocess.TimeoutExpired) as exc:
        log(f"[lint] gate unavailable: {exc}")
        return None
    clean = proc.returncode == 0
    log(f"[lint] {'clean' if clean else 'FINDINGS'} (rc={proc.returncode})")
    if not clean:
        sys.stderr.write(proc.stdout[-2000:])
    return clean


def _tuned_profile_snapshot(path: str) -> dict | None:
    """Stdlib-only read of the committed tuned profile for the BENCH
    record: provenance (backend stamp, git sha) plus the knob values the
    children resolved through ``load_tuned_profile``.  None = no profile
    committed; an unreadable one records its error instead of raising."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        return {"path": path, "error": f"{type(exc).__name__}: {exc}"}
    return {
        "path": path,
        "backend": rec.get("backend"),
        "git_sha": rec.get("git_sha"),
        "created_wall": rec.get("created_wall"),
        "knobs": rec.get("knobs"),
    }


def _ab_speedup(tuned: dict | None, default: dict | None,
                key: str) -> float | None:
    """tuned/default ratio for one A/B key; None unless both arms
    produced a positive number (> 1.0 = the tuned profile wins)."""
    if not tuned or not default:
        return None
    t, d = tuned.get(key), default.get(key)
    if not t or not d or d <= 0:
        return None
    return round(float(t) / float(d), 3)


def _run_child(mode: str, timeout_s: int, env: dict) -> dict | None:
    """Run ``bench.py --<mode>`` in a subprocess; parse its last JSON line."""
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), f"--{mode}"],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )
    except subprocess.TimeoutExpired as exc:
        for stream in (exc.stderr, exc.stdout):
            if stream:
                sys.stderr.write(stream if isinstance(stream, str)
                                 else stream.decode(errors="replace"))
        log(f"[{mode}] TIMEOUT after {timeout_s}s")
        return None
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        log(f"[{mode}] subprocess failed rc={proc.returncode}: "
            f"{proc.stdout.strip()[-400:]}")
        return None
    try:
        out = json.loads(proc.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        log(f"[{mode}] unparseable output: {proc.stdout[-400:]!r}")
        return None
    log(f"[{mode}] done in {time.perf_counter() - t0:.0f}s wall")
    return out


def _emit(value: float, unit: str, vs_baseline: float, extra: dict) -> None:
    print(json.dumps({
        "metric": "pagerank_iters_per_sec_webgoogle_scale",
        "value": value, "unit": unit, "vs_baseline": vs_baseline,
        "extra": extra,
    }))


def main() -> int:
    """Always emits exactly one parseable JSON record and exits 0 — the
    round's scored artifact must exist in every failure mode (round-1
    lesson: rc=1 after three timeouts scored as 'no number')."""
    fd, graph_cache = tempfile.mkstemp(prefix="bench_graph_", suffix=".npz")
    os.close(fd)
    try:
        return _main(graph_cache)
    except Exception as exc:  # emit the self-describing record regardless
        import traceback

        traceback.print_exc(file=sys.stderr)
        _emit(0.0, f"iters/sec (bench harness error: {type(exc).__name__})",
              0.0, {"harness_error": repr(exc)[:300]})
        return 0
    finally:
        if os.path.exists(graph_cache):
            os.unlink(graph_cache)


def _main(graph_cache: str) -> int:
    # The parent must not import jax, even transitively: the package
    # __init__ chain reaches ``import jax``, and with a wedged process
    # around, jax-registering interpreter startups block machine-wide
    # (observed).  So even graph generation runs in a sanitized child;
    # the parent only ever np.load()s the result.
    safe_env = dict(os.environ)
    safe_env.pop("PALLAS_AXON_POOL_IPS", None)
    safe_env["JAX_PLATFORMS"] = "cpu"
    gen_out = _run_child("gen-graph", 600,
                         dict(safe_env, BENCH_GRAPH_NPZ=graph_cache))
    if gen_out is None or os.path.getsize(graph_cache) == 0:
        _emit(0.0, "iters/sec (graph generation failed)", 0.0,
              {"graph_gen_failed": True})
        return 0
    z = np.load(graph_cache)
    graph_n_nodes, graph_n_edges = int(z["n_nodes"]), int(z["src"].shape[0])
    graph_src, graph_dst, graph_outdeg = z["src"], z["dst"], z["out_degree"]
    log(f"graph: {graph_n_nodes} nodes, {graph_n_edges} edges (from child)")

    # --- TPU liveness probe, isolated + killable (round-1 lesson) ---
    probe_out = _run_child("probe", PROBE_TIMEOUT_S, dict(os.environ))
    tpu_alive = bool(probe_out and probe_out.get("ok")
                     and probe_out.get("backend") not in ("cpu",))
    if probe_out and not tpu_alive and probe_out.get("backend") == "cpu":
        # jax resolved to CPU on its own — no TPU plugin present
        log("backend resolved to cpu (no TPU plugin)")
    child_env = dict(os.environ)
    sync_deadline_s: float | None = None
    sync_deadline_source = "off"
    if tpu_alive:
        # Arm the resilience watchdog in every TPU child (ROADMAP PR-2
        # leftover): a hung host sync on the relay tunnel then surfaces as
        # a retryable SyncDeadlineExceeded inside the child instead of
        # wedging it until the parent's 420 s kill.  The deadline is
        # ADAPTIVE: with a prior round's trace artifact under
        # BENCH_TRACE_DIR, it becomes max(knob, 3 x that round's p99 sync
        # span) — calibrated to the tunnel's observed behavior instead of
        # a guess.  Override with BENCH_SYNC_DEADLINE_S (0 disables); an
        # explicit GRAFT_SYNC_DEADLINE_S in the parent env wins outright.
        if "GRAFT_SYNC_DEADLINE_S" in os.environ:
            sync_deadline_s = float(os.environ["GRAFT_SYNC_DEADLINE_S"])
            sync_deadline_source = "env"
        else:
            knob = float(os.environ.get("BENCH_SYNC_DEADLINE_S", "120"))
            p99 = _prior_sync_p99(os.environ.get("BENCH_TRACE_DIR"))
            sync_deadline_s = _effective_sync_deadline(knob, p99)
            sync_deadline_source = (
                "trace-p99" if sync_deadline_s > knob else "knob"
            )
            child_env["GRAFT_SYNC_DEADLINE_S"] = str(sync_deadline_s)
        log(f"[deadline] child sync deadline {sync_deadline_s}s "
            f"({sync_deadline_source})")
    else:
        log(f"TPU UNREACHABLE (probe={probe_out}); falling back to JAX-CPU "
            "for all measurements")
        # Stripping PALLAS_AXON_POOL_IPS makes the axon sitecustomize skip
        # plugin registration entirely; while any process is wedged on the
        # dead tunnel, interpreters that register the plugin at startup
        # block machine-wide (observed), so CPU children must never touch it.
        child_env.pop("PALLAS_AXON_POOL_IPS", None)
        child_env["JAX_PLATFORMS"] = "cpu"

    # Every measurement child writes its obs run telemetry here (crash-safe
    # JSONL trace + manifest).  The directory intentionally OUTLIVES the
    # bench: it is the post-mortem artifact the BENCH record points at
    # (``extra.trace_path``), so a timed-out child leaves a full accounting
    # instead of a scraped stderr tail.  Under BENCH_TRACE_DIR each bench
    # run gets its own pid-scoped subdirectory, so a persistent artifact
    # root can never attribute a PREVIOUS round's trace to this record.
    base = os.environ.get("BENCH_TRACE_DIR")
    if base:
        trace_dir = os.path.join(base, f"run_{os.getpid()}")
        os.makedirs(trace_dir, exist_ok=True)
    else:
        trace_dir = tempfile.mkdtemp(prefix="bench_trace_")
    child_env["GRAFT_TRACE_DIR"] = trace_dir
    # Cross-process span propagation (ROADMAP hardening (c)): the parent
    # exports ONE trace id for the whole round; every child run adopts it
    # in its run_start event + manifest, so
    # `tools/trace_report.py <trace_dir>` stitches the round back into a
    # single tree without pid archaeology.
    trace_parent = f"bench-{os.getpid()}-{int(time.time())}"
    child_env["GRAFT_TRACE_PARENT"] = trace_parent
    log(f"trace artifacts: {trace_dir} (trace parent {trace_parent})")

    # --- CPU anchor: scipy CSR power iteration (same math, float32) ---
    import scipy.sparse as sp

    a = sp.csr_matrix(
        (np.ones(graph_n_edges, np.float32), (graph_dst, graph_src)),
        shape=(graph_n_nodes, graph_n_nodes),
    )
    inv = np.where(graph_outdeg > 0,
                   1.0 / np.maximum(graph_outdeg, 1), 0.0).astype(np.float32)
    e = np.full(graph_n_nodes, 1.0 / graph_n_nodes, np.float32)
    dang = (graph_outdeg == 0).astype(np.float32)
    r = np.full(graph_n_nodes, 1.0 / graph_n_nodes, np.float32)
    anchor_iters = 5
    t0 = time.perf_counter()
    for _ in range(anchor_iters):
        w = r * inv
        contribs = a @ w
        contribs += float(np.dot(r, dang)) * e
        r = 0.15 * e + 0.85 * contribs
    cpu_ips = anchor_iters / (time.perf_counter() - t0)
    log(f"cpu anchor (scipy CSR): {cpu_ips:.2f} iters/sec")

    # --- share the generated graph with measurement children ---
    child_env["BENCH_GRAPH_NPZ"] = graph_cache

    # --- accelerator: race candidates, each isolated in a subprocess ---
    # Ordered safe-first: cumsum/segment are known to compile on-chip; the
    # degree-aware hybrid and the sort-based static shuffle race next
    # (pure XLA off-chip, Pallas rowsum on a real TPU); the Pallas cumsum
    # candidate runs LAST so a wedged Mosaic compile (killed at the
    # timeout) can never block the measurements that already succeeded.
    candidates = os.environ.get(
        "BENCH_IMPLS",
        "cumsum,cumsum_mxu,segment,hybrid,sort_shuffle,pallas").split(",")
    if (not tpu_alive and "pallas" in candidates
            and "BENCH_IMPLS" not in os.environ):
        candidates.remove("pallas")  # interpret mode at 5M edges: pointless
    results: dict[str, float] = {}
    preprocess: dict[str, float] = {}
    backend_used = "unknown"
    for impl in candidates:
        out = _run_child(f"impl={impl}", CANDIDATE_TIMEOUT_S, child_env)
        if out is None:
            continue
        checksum, ips = out.get("checksum"), out.get("ips")
        if checksum is None or ips is None:
            log(f"[{impl}] missing fields in {out}")
            continue
        if not (0.99 < checksum < 1.01):  # mass must be conserved
            log(f"[{impl}] BAD CHECKSUM {checksum}; discarding")
            continue
        results[impl] = ips
        if out.get("preprocess_secs") is not None:
            preprocess[impl] = round(out["preprocess_secs"], 3)
        backend_used = out.get("backend", backend_used)

    # --- TF-IDF throughput (configs 2 and 5) ---
    tfidf_out = None
    sharded_out = None
    serve_out = None
    scale_out = None
    workloads_out = None
    soak_out = None
    fabric_out = None
    tfidf_record: dict = {}
    if not os.environ.get("BENCH_SKIP_TFIDF"):
        import shutil

        fd, corpus_cache = tempfile.mkstemp(prefix="bench_corpus_",
                                            suffix=".txt")
        os.close(fd)
        with open(corpus_cache, "w") as f:
            f.write("\n".join(_corpus()))
        child_env["BENCH_CORPUS_TXT"] = corpus_cache
        # Per-chunk checkpoints make a timed-out child resumable AND
        # accountable: the BENCH_r05 failure ("[tfidf] TIMEOUT after 420s"
        # at chunk 24) discarded all 24 completed chunks because nothing
        # between the subprocess timeout and the ingest loop could resume.
        ck_dir = tempfile.mkdtemp(prefix="bench_tfidf_ck_")
        child_env["BENCH_TFIDF_CKPT_DIR"] = ck_dir
        try:
            tfidf_out = _run_child("tfidf", TFIDF_TIMEOUT_S, child_env)
            for _ in range(int(os.environ.get("BENCH_TFIDF_RETRIES", "1"))):
                if tfidf_out is not None:
                    break
                log("[tfidf] relaunching in resume mode from the chunk "
                    "checkpoint")
                tfidf_out = _run_child(
                    "tfidf", TFIDF_TIMEOUT_S,
                    dict(child_env, BENCH_TFIDF_RESUME="1"),
                )
            if tfidf_out is None:
                # Still no complete run: emit the self-describing partial
                # record from the surviving chunk checkpoint so this
                # round's BENCH_*.json stays comparable with healthy ones.
                meta = _read_ckpt_meta(ck_dir)
                if meta:
                    ext = meta.get("extra", {})
                    secs = float(ext.get("ingest_secs", 0.0))
                    toks = int(ext.get("n_tokens", 0))
                    tfidf_record = {
                        "partial": True,
                        "chunks_completed": int(meta.get("step", 0)),
                        "docs_completed": int(ext.get("n_docs", 0)),
                        "tokens_completed": toks,
                        "stream_tokens_per_sec_so_far": (
                            round(toks / secs, 1) if secs > 0 else 0.0
                        ),
                    }
                    log(f"[tfidf] partial record from checkpoint: {tfidf_record}")
            # Sharded ingest throughput (ROADMAP leftover: the
            # tfidf_sharded_tokens_per_sec field was null in every round).
            # On the CPU fallback the child gets simulated devices; on a
            # live TPU it uses the real pod mesh.
            sh_env = dict(child_env)
            if not tpu_alive:
                flags = sh_env.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    sh_env["XLA_FLAGS"] = (
                        flags + " --xla_force_host_platform_device_count=4"
                    ).strip()
            sharded_out = _run_child("tfidf-sharded", TFIDF_TIMEOUT_S, sh_env)
            # Served-QPS (ISSUE 8): warm batched query path vs the naive
            # per-request cold loop, p50/p99 at fixed batch sizes.
            serve_out = _run_child("serve", TFIDF_TIMEOUT_S, child_env)
            # Impacted-vs-COO at 1M-doc scale (ISSUE 13 acceptance):
            # synthetic Zipf postings, one fixed batch size, both paths.
            if not os.environ.get("BENCH_SKIP_SCALE"):
                scale_out = _run_child("serve-scale", TFIDF_TIMEOUT_S,
                                       child_env)
            # Dataflow workloads (ISSUE 9): batched PPR, label-prop CC,
            # and the BM25-vs-TFIDF serving A/B.
            workloads_out = _run_child("workloads", TFIDF_TIMEOUT_S,
                                       child_env)
        finally:
            os.unlink(corpus_cache)
            shutil.rmtree(ck_dir, ignore_errors=True)

    # Production soak (ISSUE 11): the SLO-scored long-running composition
    # (continuous ingest + live mixed traffic + chaos).  Independent of
    # the corpus caches above — it streams its own growing corpus.
    # Timeout = soak duration + generous setup margin; skip with
    # BENCH_SKIP_SOAK=1.
    if not os.environ.get("BENCH_SKIP_SOAK"):
        soak_s = float(os.environ.get("GRAFT_SOAK_DURATION_S", "60"))
        soak_timeout = int(os.environ.get(
            "BENCH_SOAK_TIMEOUT_S", str(int(3 * soak_s + 240))))
        soak_out = _run_child("soak", soak_timeout, child_env)

    # Multi-process serving fabric (ISSUE 17): N=1 vs N=GRAFT_FABRIC_REPLICAS
    # replica processes over the same mmap'd segments, one SIGKILL-recovery
    # probe, and the cross-process delivery audit.  The fabric is stdlib
    # router + HTTP replicas — cheap next to the jax children.  Skip with
    # BENCH_SKIP_FABRIC=1.
    if not os.environ.get("BENCH_SKIP_FABRIC"):
        fabric_out = _run_child(
            "serve-fabric",
            int(os.environ.get("BENCH_FABRIC_TIMEOUT_S", "420")), child_env,
        )

    # Owned-strategy scale sweep (ISSUE 15): comm bytes/step at 1x/4x/10x
    # web-Google node counts under strategy='owned', fitted sublinearity
    # exponent, and the asserted replicated wall at the top scale.
    # Independent of the corpus caches; needs a multi-device mesh, so the
    # CPU fallback gets simulated devices.  Skip with BENCH_SKIP_OWNED=1.
    owned_out = None
    if not os.environ.get("BENCH_SKIP_OWNED"):
        ow_env = dict(child_env)
        if not tpu_alive:
            flags = ow_env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                ow_env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
        owned_out = _run_child(
            "owned-scale",
            int(os.environ.get("BENCH_OWNED_TIMEOUT_S", "900")), ow_env,
        )

    # Autotuned-vs-default A/B (ISSUE 16): the same child twice, once
    # resolving knobs through the committed tuned profile and once with
    # the profile forced off — the ratio of the arms IS the measured
    # value of the autotuner's output.  Runs only when a committed
    # profile exists for the backend the candidates actually used; skip
    # with BENCH_SKIP_AB=1.
    ab_tuned_out = None
    ab_default_out = None
    ab_profile_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"tuned_profile_{backend_used if backend_used != 'unknown' else 'cpu'}.json",
    )
    if not os.environ.get("BENCH_SKIP_AB") and os.path.exists(ab_profile_path):
        ab_timeout = int(os.environ.get("BENCH_AB_TIMEOUT_S", "600"))
        # the arms run chaos-free: an A/B under injected faults measures
        # the chaos plan, not the knobs (and a hang plan aimed at the
        # resilience child would wedge both arms identically)
        ab_env = dict(child_env)
        ab_env.pop("GRAFT_CHAOS", None)
        ab_tuned_out = _run_child(
            "autotuned-ab", ab_timeout,
            dict(ab_env, GRAFT_TUNED_PROFILE=ab_profile_path))
        ab_default_out = _run_child(
            "autotuned-ab", ab_timeout,
            dict(ab_env, GRAFT_TUNED_PROFILE="off"))

    # --- sklearn anchor for TF-IDF (same corpus would be ideal but costs
    # parent time; a fixed-rate anchor is recorded by tools/ when needed) ---
    extra: dict = {"tpu_unreachable": not tpu_alive, "backend": backend_used,
                   "cpu_anchor_ips": round(cpu_ips, 2),
                   "lint_clean": _lint_clean(),
                   # the sync deadline the children actually ran under
                   # (None = watchdog not armed, CPU-fallback round) and
                   # where it came from: "knob" (static default),
                   # "trace-p99" (adapted from a prior round's artifact),
                   # or "env" (explicit GRAFT_SYNC_DEADLINE_S)
                   "sync_deadline_s": sync_deadline_s,
                   "sync_deadline_source": sync_deadline_source}
    extra["trace_parent"] = trace_parent
    # Which tuned profile shaped this round (ISSUE 16): the committed
    # per-backend artifact, read stdlib-only (the parent never imports
    # the package).  Always present; null = no committed profile for the
    # measured backend.  trace_diff flags a round whose profile backend
    # stamp disagrees with the backend the candidates ran on.
    extra["tuned_profile"] = _tuned_profile_snapshot(ab_profile_path)
    # Autotuned-vs-default speedups (tuned arm / default arm, > 1 means
    # the committed profile wins).  Keys are ALWAYS present so rounds
    # stay comparable; null = that arm (or both) failed this round.
    extra["autotuned_vs_default"] = {
        key: _ab_speedup(ab_tuned_out, ab_default_out, key)
        for key in ("stream_tokens_per_sec", "hybrid_iters_per_sec",
                    "served_qps")
    }
    # Always present so rounds are comparable: null = the serve child did
    # not produce a number this round.
    extra["served_qps"] = None
    # Per-batch served latency maps + the impacted-path A/B (ISSUE 13):
    # always present so rounds stay comparable; null = the serve child
    # failed this round.  trace_diff's served-latency gate regresses
    # served_p99_ms between committed rounds exactly like the SLO p99.
    extra["served_p50_ms"] = None
    extra["served_p99_ms"] = None
    extra["served_impacted_qps"] = None
    if serve_out and serve_out.get("served_qps"):
        extra["served_qps"] = serve_out["served_qps"]
        extra["serve_naive_qps"] = serve_out.get("naive_qps")
        extra["serve_speedup_vs_naive"] = serve_out.get("speedup_vs_naive")
        extra["served_p50_ms"] = serve_out.get("served_p50_ms")
        extra["served_p99_ms"] = serve_out.get("served_p99_ms")
        extra["served_impacted_qps"] = serve_out.get("served_impacted_qps")
    # The 1M-doc impacted-vs-COO acceptance block (null = child failed
    # or BENCH_SKIP_SCALE): {n_docs, nnz, coo, impacted, qps_speedup}.
    extra["serve_scale"] = None
    if scale_out and scale_out.get("qps_speedup") is not None:
        extra["serve_scale"] = scale_out
    # Always present so rounds are comparable (null = the workloads child
    # produced no number this round): the ISSUE 9 dataflow-workload
    # trajectory keys.
    extra["ppr_batch_queries_per_sec"] = None
    extra["cc_iters_per_sec"] = None
    extra["bm25_vs_tfidf_served_qps"] = None
    if workloads_out:
        for key in ("ppr_batch_queries_per_sec", "cc_iters_per_sec",
                    "bm25_vs_tfidf_served_qps"):
            if workloads_out.get(key) is not None:
                extra[key] = workloads_out[key]
    # Always present so rounds are comparable (null = the soak child did
    # not produce a record this round): the ISSUE 11 SLO record — served
    # p50/p99 under ingest load, error-budget burn, time-to-recover,
    # dropped/double-served counts.  tools/trace_diff.py regresses this
    # block between committed rounds.
    # Owned scale sweep + the per-point comm-bytes map trace_diff's comm
    # gate regresses across rounds (keys always present; null on a failed
    # or skipped child).
    extra["owned_scale"] = None
    extra["comm_bytes_per_step"] = None
    extra["owned_comm_scaling_exponent"] = None
    if owned_out is not None:
        extra["owned_scale"] = owned_out
        extra["comm_bytes_per_step"] = {
            f"owned-{k}": v["comm_bytes_per_step"]
            for k, v in (owned_out.get("scales") or {}).items()
        } or None
        extra["owned_comm_scaling_exponent"] = owned_out.get(
            "comm_scaling_exponent"
        )

    extra["slo"] = None
    if soak_out:
        extra["slo"] = soak_out
    # Always present so rounds are comparable (null = the fabric child
    # failed or BENCH_SKIP_FABRIC): the ISSUE 17 replica-fleet keys —
    # per-fleet-size saturated QPS, SIGKILL->respawned recovery, and the
    # cross-process dropped/double-served audit (invariants: trace_diff
    # flags ANY increase).  fabric_cpus records the honesty context: on
    # a 1-core host the fleet arms contend for the same CPU and nN/n1
    # lands near 1x — fault isolation, not throughput;
    # fabric_scaling_nongating makes that machine-readable (ISSUE 18)
    # so trace_diff gates only the n1 point there.
    # fabric_proto_fingerprint stamps the WIRE_SCHEMAS generation the
    # numbers were measured against; rounds with different fingerprints
    # arm fresh instead of comparing.
    extra["fabric_qps"] = None
    extra["fabric_recovery_s"] = None
    extra["fabric_dropped"] = None
    extra["fabric_double_served"] = None
    if fabric_out and fabric_out.get("fabric_qps"):
        extra["fabric_qps"] = fabric_out["fabric_qps"]
        extra["fabric_replicas"] = fabric_out.get("fabric_replicas")
        extra["fabric_recovery_s"] = fabric_out.get("fabric_recovery_s")
        extra["fabric_dropped"] = fabric_out.get("fabric_dropped")
        extra["fabric_double_served"] = fabric_out.get(
            "fabric_double_served")
        extra["fabric_cpus"] = fabric_out.get("fabric_cpus")
        extra["fabric_proto_fingerprint"] = fabric_out.get(
            "fabric_proto_fingerprint")
        extra["fabric_scaling_nongating"] = fabric_out.get(
            "fabric_scaling_nongating")
    # Always present (ISSUE 19 gate keys): the federation board and the
    # autoscaler decision tallies — null = the fabric child (or its
    # federation probe) failed this round; trace_diff's flap-count and
    # fleet-p99 gates skip nulls but flag a round that LOST the keys.
    extra["fleet_federation"] = None
    extra["autoscale"] = None
    # Always present (ISSUE 20 gate keys): roll-attributed retries (0
    # when the drain handoff carried every roll), the cross-replica
    # cache hit rate, and the skewed-stream duplicate-compute reduction
    # — null = the fabric child (or that probe) failed this round.
    extra["fabric_roll_retries"] = None
    extra["cache_peer_hit_rate"] = None
    extra["cache_speedup_skewed"] = None
    if fabric_out:
        extra["fleet_federation"] = fabric_out.get("fleet_federation")
        extra["autoscale"] = fabric_out.get("autoscale")
        extra["fabric_roll_retries"] = fabric_out.get("fabric_roll_retries")
        extra["fabric_roll"] = fabric_out.get("fabric_roll")
        extra["cache_peer_hit_rate"] = fabric_out.get("cache_peer_hit_rate")
        extra["cache_speedup_skewed"] = fabric_out.get(
            "cache_speedup_skewed")
        extra["cache_ab"] = fabric_out.get("cache_ab")
    # Always present so rounds are comparable: null = the sharded child
    # did not produce a number this round.
    extra["tfidf_sharded_tokens_per_sec"] = None
    extra["tfidf_sharded_h2d_overlap_frac"] = None
    if sharded_out and sharded_out.get("sharded_tokens_per_sec"):
        extra["tfidf_sharded_tokens_per_sec"] = round(
            sharded_out["sharded_tokens_per_sec"])
        extra["tfidf_sharded_devices"] = int(sharded_out.get("devices", 0))
        extra["tfidf_sharded_h2d_overlap_frac"] = sharded_out.get(
            "h2d_overlap_frac")
    # Always present (ISSUE 10 ratchet keys): null = the tfidf child did
    # not produce them this round.  h2d_overlap_frac proves the staged
    # pipeline overlapped H2D with compute; streaming_vs_batch_ratio is
    # the ROADMAP "within 2x" gap tracked directly (target >= 0.5).
    extra["h2d_overlap_frac"] = None
    extra["streaming_vs_batch_ratio"] = None
    if tfidf_out:
        extra["h2d_overlap_frac"] = tfidf_out.get("h2d_overlap_frac")
        if tfidf_out.get("streaming_vs_batch_ratio") is not None:
            extra["streaming_vs_batch_ratio"] = round(
                tfidf_out["streaming_vs_batch_ratio"], 3)
    if tfidf_out:
        extra["tfidf_batch_tokens_per_sec"] = round(
            tfidf_out.get("batch_tokens_per_sec", 0.0))
        extra["tfidf_stream_tokens_per_sec"] = round(
            tfidf_out.get("stream_tokens_per_sec", 0.0))
        extra["tfidf_stream_overlap_speedup"] = round(
            tfidf_out.get("stream_overlap_speedup", 1.0), 3)
        tfidf_record = {
            "partial": False,
            "chunks_completed": int(tfidf_out.get("chunks", 0)),
            "resumed": bool(tfidf_out.get("resumed", False)),
        }

    # Per-phase accounting from the tfidf child's trace ARTIFACT (present
    # for healthy, resumed and timeout-killed children alike) — the BENCH
    # record's time-breakdown no longer depends on scraping child stderr.
    extra["trace_path"] = trace_dir
    if not os.environ.get("BENCH_SKIP_TFIDF"):
        rep = _tfidf_trace_accounting(trace_dir)
        if rep:
            extra["breakdown"] = {
                k: round(v, 3) for k, v in rep["breakdown"].items()
            }
            extra["breakdown_wall_secs"] = round(rep["wall_secs"], 3)
            # the staged-ingest stage split straight from the ARTIFACT
            # (one record per chunked_ingest run in the tfidf child), so
            # the committed round proves where the H2D overlap landed
            # independent of the child's returned numbers
            if rep.get("ingest"):
                extra["trace_ingest"] = rep["ingest"]
            if rep["retries"]:
                extra["trace_retries"] = rep["retries"]
            if not rep["complete"]:
                tfidf_record.setdefault("partial", True)
                if rep.get("last_incomplete"):
                    tfidf_record["last_incomplete_span"] = (
                        rep["last_incomplete"]["name"]
                    )
    if tfidf_record:
        extra["tfidf"] = tfidf_record

    if not results:
        _emit(0.0, "iters/sec (no SpMV impl produced a valid result)", 0.0,
              extra)
        return 0
    best = max(results, key=results.get)
    ips = results[best]
    extra["all_impls"] = {k: round(v, 2) for k, v in results.items()}
    # one-time static-layout build cost per impl (hybrid head split /
    # shuffle bucket padding): must stay amortizable vs the run itself
    extra["spmv_preprocess_secs"] = preprocess
    _emit(round(ips, 2),
          (f"iters/sec ({graph_n_nodes} nodes, {graph_n_edges} edges, "
           f"f32, backend={backend_used}, spmv={best})"),
          round(ips / cpu_ips, 2), extra)
    return 0


if __name__ == "__main__":
    if len(sys.argv) == 2 and sys.argv[1] == "--gen-graph":
        print(json.dumps(gen_graph()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--probe":
        print(json.dumps(probe()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--tfidf":
        print(json.dumps(measure_tfidf()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--tfidf-sharded":
        print(json.dumps(measure_tfidf_sharded()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--serve":
        print(json.dumps(measure_serve()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--serve-scale":
        print(json.dumps(measure_serve_scale()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--owned-scale":
        print(json.dumps(measure_owned_scale()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--soak":
        print(json.dumps(measure_soak()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--serve-fabric":
        print(json.dumps(measure_serve_fabric()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--workloads":
        print(json.dumps(measure_workloads()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1] == "--autotuned-ab":
        print(json.dumps(measure_autotuned_ab()))
        sys.exit(0)
    if len(sys.argv) == 2 and sys.argv[1].startswith("--impl="):
        print(json.dumps(measure_impl(sys.argv[1].split("=", 1)[1])))
        sys.exit(0)
    if len(sys.argv) == 3 and sys.argv[1] == "--impl":  # legacy spelling
        print(json.dumps(measure_impl(sys.argv[2])))
        sys.exit(0)
    sys.exit(main())
