"""End-to-end PageRank via the library API (no CLI).

Mirrors the reference driver's flow (SURVEY.md §3.1): build the graph,
iterate, inspect ranks — plus the personalized variant (BASELINE.json:10).

Run from the repo root:  python examples/pagerank_example.py [edges.txt]
Without an input file a synthetic power-law graph stands in.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from page_rank_and_tfidf_using_apache_spark_tpu.api import pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
    load_snap,
    synthetic_powerlaw,
)

graph = (
    load_snap(sys.argv[1]) if len(sys.argv) > 1
    else synthetic_powerlaw(10_000, 80_000, seed=0)
)
print(f"graph: {graph.n_nodes} nodes, {graph.n_edges} edges")

# Textbook semantics (networkx parity): mass-preserving, 1/N init.
res = pagerank(graph, iterations=50, tol=1e-9, dangling="redistribute",
               init="uniform")
top = res.ranks.argsort()[::-1][:5]
print(f"converged after {res.iterations} iters (l1_delta={res.l1_delta:.2e})")
for i in top:
    print(f"  node {graph.node_ids[i]}: {res.ranks[i]:.6f}")

# Personalized: restart onto a source set (original node ids, as they
# appear in the edge file) instead of the uniform vector.
seed_nodes = (int(graph.node_ids[top[0]]),)
ppr = pagerank(graph, iterations=50, tol=1e-9, dangling="redistribute",
               init="uniform", personalize=seed_nodes)
print(f"personalized on {seed_nodes}: top neighbor "
      f"{graph.node_ids[ppr.ranks.argsort()[::-1][1]]}")
