"""Multi-chip PageRank + TF-IDF via the library API (SURVEY.md §2.2 R1–R3).

Demonstrates every shard strategy over a device mesh — on real chips when a
TPU pod is attached, or on simulated devices anywhere:

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/multichip_example.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import synthetic_powerlaw
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
    auto_select_strategy,
    make_mesh,
    run_pagerank_sharded,
    run_tfidf_sharded,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    PageRankConfig,
    TfidfConfig,
)

mesh = make_mesh()  # all visible devices
d = int(mesh.devices.size)
graph = synthetic_powerlaw(20_000, 120_000, seed=3)
cfg = PageRankConfig(iterations=30, dangling="redistribute", init="uniform",
                     dtype="float64")
single = run_pagerank(graph, cfg).ranks

print(f"mesh: {d} devices; auto strategy -> "
      f"{auto_select_strategy(graph, d)!r}")
for strategy in ("edges", "nodes", "nodes_balanced", "src", "src_ring",
                 "hybrid"):
    res = run_pagerank_sharded(graph, cfg, mesh=mesh, strategy=strategy)
    l1 = np.abs(res.ranks - single).sum()
    print(f"pagerank[{strategy:14s}] on {d} devices: L1 vs single-chip {l1:.2e}")

docs = [f"alpha w{i % 17} w{i % 5} beta{i % 3}" for i in range(512)]
chunks = [docs[i:i + 64] for i in range(0, len(docs), 64)]
out = run_tfidf_sharded(iter(chunks), TfidfConfig(vocab_bits=14), mesh=mesh)
print(f"tfidf sharded: {out.n_docs} docs, nnz={out.nnz} "
      f"(DF psum over {d} devices, replicated IDF broadcast)")
