"""End-to-end TF-IDF + query scoring via the library API.

Mirrors the reference's TF-IDF chain (SURVEY.md §3.2) and the top-k query
capability (SURVEY.md A11).

Run from the repo root:  python examples/tfidf_example.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.api import tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
    fnv1a_64,
    hash_to_vocab,
    tokenize,
)

names = ["spark.txt", "tpu.txt", "pagerank.txt", "tfidf.txt"]
docs = [
    "apache spark is a cluster computing framework",
    "a tpu accelerates dense linear algebra with a systolic array",
    "pagerank scores pages by the structure of the web graph",
    "tf idf weighs terms by frequency and inverse document frequency",
]

out = tfidf(docs, vocab_bits=12, idf_mode="smooth", l2_normalize=True)
print(f"{out.n_docs} docs, {out.nnz} nonzero (term, doc) weights")

# Score documents for a query by summed TF-IDF (the reference's likely
# takeOrdered capability, SURVEY.md A11).
query = "spark framework"
qids = hash_to_vocab(fnv1a_64(tokenize(query)), 12)
scores = np.zeros(out.n_docs)
for qid in np.unique(qids):
    hit = out.term == qid
    np.add.at(scores, out.doc[hit], out.weight[hit])
for rank, d in enumerate(scores.argsort()[::-1][:3], 1):
    print(f"  {rank}. {names[d]}  score={scores[d]:.4f}")
