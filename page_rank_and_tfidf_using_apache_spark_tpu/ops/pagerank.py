"""PageRank numeric core: one XLA program per iteration loop.

Reference counterpart (SURVEY.md §3.1, BASELINE.json:5): the per-iteration
Spark chain ``links.join(ranks).flatMap(computeContribs).reduceByKey(add)
.mapValues(0.15 + 0.85*r)`` — two shuffle stages per iteration, scheduled by
the DAGScheduler, executed as per-record iterator chains.

TPU-native design: the whole iteration is one sparse matvec plus an axpy —
``contribs = Aᵀ · (ranks / outdeg)``; ``ranks' = base + d·(contribs [+
dangling])`` — expressed as a gather + ``segment_sum`` over destination-
sorted edges (the `reduceByKey` becomes a contiguous segmented reduction the
MXU/VPU pipeline, not a shuffle), and the *entire loop* lives inside one
``jit``-compiled ``lax.scan`` / ``lax.while_loop``: zero host round-trips
between iterations, XLA fuses the damping/axpy/delta into the reduction's
epilogue.

Semantics flags (SURVEY.md §3.1 dangling-node caveat):
- ``dangling=drop``        mass at out-degree-0 nodes vanishes (canonical
                           Spark example behavior).
- ``dangling=redistribute`` dangling mass re-spread over the restart
                           distribution (textbook/networkx behavior; keeps
                           ``sum(ranks)`` invariant).
- ``spark_exact``          additionally reproduces the example's shrinking
                           key-set: nodes that receive no contribution drop
                           out of the rank table entirely (rank 0, and they
                           stop contributing even if they have out-links).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    DanglingMode,
    PageRankConfig,
    RankInit,
)


class DeviceGraph(NamedTuple):
    """Device-resident graph state (the reference's ``links.cache()`` —
    SURVEY.md A3: built once, reused across all iterations)."""

    src: jax.Array  # int32 [E], edge sources, dst-sorted order
    dst: jax.Array  # int32 [E], non-decreasing
    inv_outdeg: jax.Array  # f[N], 1/out_degree (0 at dangling nodes)
    dangling: jax.Array  # f[N], 1.0 where out_degree == 0
    has_outlinks: jax.Array  # f[N], 1.0 where out_degree > 0
    indptr: jax.Array | None = None  # int32 [N+1], CSR row pointers into dst


def put_graph(graph: Graph, dtype: str = "float32") -> DeviceGraph:
    """Host Graph → device arrays (one host→device transfer per run)."""
    outdeg = graph.out_degree.astype(dtype)
    with np.errstate(divide="ignore"):
        inv = np.where(outdeg > 0, 1.0 / np.maximum(outdeg, 1), 0.0).astype(dtype)
    indptr = graph.csr_indptr().astype(np.int32)
    return DeviceGraph(
        src=jnp.asarray(graph.src),
        dst=jnp.asarray(graph.dst),
        inv_outdeg=jnp.asarray(inv),
        dangling=jnp.asarray((graph.out_degree == 0).astype(dtype)),
        has_outlinks=jnp.asarray((graph.out_degree > 0).astype(dtype)),
        indptr=jnp.asarray(indptr),
    )


def restart_vector(n: int, cfg: PageRankConfig) -> np.ndarray:
    """The teleport distribution e: uniform for standard PageRank, an
    indicator over the source set for personalized PageRank
    (BASELINE.json:10; SURVEY.md §3.4)."""
    dtype = cfg.dtype
    if cfg.personalize is None:
        return np.full(n, 1.0 / n, dtype=dtype)
    e = np.zeros(n, dtype=dtype)
    idx = np.asarray(cfg.personalize, dtype=np.int64)
    if idx.size == 0:
        raise ValueError("personalize must name at least one node")
    if (idx < 0).any() or (idx >= n).any():
        raise ValueError(f"personalize node ids out of range [0, {n})")
    # np.add.at so duplicate ids accumulate — e must always sum to 1.
    np.add.at(e, idx, 1.0 / idx.size)
    return e


def init_ranks(n: int, cfg: PageRankConfig) -> np.ndarray:
    if cfg.init is RankInit.ONE:
        return np.ones(n, dtype=cfg.dtype)
    return np.full(n, 1.0 / n, dtype=cfg.dtype)


def spmv_segment(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """contribs[v] = Σ_{(u,v)∈E} weighted_ranks[u] via sorted segment_sum —
    the `reduceByKey(add)` of BASELINE.json:5 as one segmented reduction."""
    per_edge = weighted_ranks[dg.src]
    return jax.ops.segment_sum(
        per_edge, dg.dst, num_segments=n, indices_are_sorted=True
    )


def spmv_bcoo(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """Same contraction through jax.experimental.sparse.BCOO (the
    BASELINE.json:5 prescription) — kept as a benchmarked alternative."""
    from jax.experimental import sparse

    ones = jnp.ones_like(weighted_ranks, shape=dg.src.shape)
    mat = sparse.BCOO(
        (ones, jnp.stack([dg.dst, dg.src], axis=1)),
        shape=(n, n),
        indices_sorted=True,
        unique_indices=True,
    )
    return mat @ weighted_ranks


def cumsum_diff_spmv(per_edge, indptr, cumsum_fn=jnp.cumsum) -> jax.Array:
    """Shared prefix-sum segmented-reduction skeleton: ``out[v] =
    cumsum(per_edge)[indptr[v+1]] - cumsum(per_edge)[indptr[v]]``, exploiting
    a sorted-segment invariant to replace the scatter-add with a cumsum
    plus two *monotone* gathers.  ``cumsum_fn`` is the prefix-sum primitive
    (``jnp.cumsum`` for the XLA variant, the Pallas carry kernel for
    spmv_impl='pallas'); accuracy analysis on :func:`spmv_cumsum`."""
    c0 = jnp.concatenate([jnp.zeros(1, per_edge.dtype), cumsum_fn(per_edge)])
    return c0[indptr[1:]] - c0[indptr[:-1]]


def cumsum_blocked(x: jax.Array, block: int = 128) -> jax.Array:
    """Inclusive prefix sum as MXU work instead of XLA's reduce-window.

    ``jnp.cumsum`` over millions of elements lowers to an O(E·log E)
    reduce-window chain on TPU; here the E-length scan becomes one
    ``[M, B] @ [B, B]`` upper-triangular matmul on the systolic array
    (row-wise inclusive cumsum of an ``[M, B]`` reshape) plus a B×-smaller
    recursive carry — ~2 HBM passes and trivial MXU FLOPs (E·B).  Error is
    the blocked-summation order, no worse than the sequential scan's.
    """
    n = x.shape[0]
    if n <= 4 * block:
        return jnp.cumsum(x)
    m = -(-n // block)
    xp = jnp.concatenate([x, jnp.zeros(m * block - n, x.dtype)]).reshape(m, block)
    # T[k, j] = 1 for k <= j: row-cumsum via one MXU matmul.  HIGHEST
    # precision keeps f32 inputs f32 on TPU (default would round through
    # bf16, breaking the "same accuracy class as the sequential scan"
    # contract); the FLOPs are trivial either way.
    tri = jnp.triu(jnp.ones((block, block), x.dtype))
    rows = jnp.matmul(xp, tri, precision=jax.lax.Precision.HIGHEST)
    row_tot = rows[:, -1]
    carry = cumsum_blocked(row_tot, block) - row_tot  # exclusive row carry
    return (rows + carry[:, None]).reshape(-1)[:n]


def spmv_cumsum(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """Prefix-sum SpMV through ``jnp.cumsum`` — measured 1.5x faster per
    PageRank iteration than ``segment_sum`` at web-Google scale on TPU v5e,
    where XLA's scatter path is the bottleneck.  Accuracy cost in float32:
    the prefix sum accumulates to the full vector mass before differencing,
    so per-SpMV L1 error is ~2e-4 relative (vs ~1e-5 for segment_sum);
    parity tests run it in float64 where both are exact to 1e-12.
    """
    if dg.indptr is None:
        raise ValueError("spmv_impl='cumsum' needs DeviceGraph.indptr (use put_graph)")
    return cumsum_diff_spmv(weighted_ranks[dg.src], dg.indptr)


def spmv_cumsum_mxu(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """The prefix-sum SpMV with the MXU-blocked cumsum (:func:`cumsum_blocked`)
    as the scan primitive — same accuracy class as spmv_cumsum."""
    if dg.indptr is None:
        raise ValueError("spmv_impl='cumsum_mxu' needs DeviceGraph.indptr (use put_graph)")
    return cumsum_diff_spmv(weighted_ranks[dg.src], dg.indptr,
                            cumsum_fn=cumsum_blocked)


def _spmv(dg: DeviceGraph, weighted: jax.Array, n: int, impl: str) -> jax.Array:
    if impl == "segment":
        return spmv_segment(dg, weighted, n)
    if impl == "bcoo":
        return spmv_bcoo(dg, weighted, n)
    if impl == "cumsum":
        return spmv_cumsum(dg, weighted, n)
    if impl == "cumsum_mxu":
        return spmv_cumsum_mxu(dg, weighted, n)
    if impl == "pallas":
        from page_rank_and_tfidf_using_apache_spark_tpu.ops import pallas_kernels as pk

        if dg.indptr is None:
            raise ValueError("spmv_impl='pallas' needs DeviceGraph.indptr (use put_graph)")
        # Mosaic only compiles on real TPUs; everywhere else (CPU tests,
        # simulated meshes) run the same kernel under the interpreter.
        interpret = jax.default_backend() not in ("tpu", "axon")
        return pk.spmv_pallas(dg.src, dg.indptr, weighted, n=n, interpret=interpret)
    raise ValueError(f"unknown spmv impl {impl!r}")


def pagerank_step(
    ranks: jax.Array,
    dg: DeviceGraph,
    e: jax.Array,
    *,
    n: int,
    damping: float,
    dangling: DanglingMode,
    total_mass: float,
    impl: str = "segment",
) -> jax.Array:
    """One power-iteration step.

    ``total_mass`` is the invariant rank-vector sum: ``n`` under the Spark
    init=ONE convention (uniform restart term is then the familiar constant
    0.15), ``1.0`` under the textbook init=UNIFORM convention (restart term
    (1-d)/n).  The restart distribution ``e`` always sums to 1; both the
    restart and the redistributed dangling mass are spread according to it,
    so under dangling=redistribute ``sum(ranks) == total_mass`` is exactly
    preserved every step.
    """
    weighted = ranks * dg.inv_outdeg
    contribs = _spmv(dg, weighted, n, impl)
    if dangling is DanglingMode.REDISTRIBUTE:
        # lost mass re-enters through the restart distribution e; on a
        # sharded mesh this sum is the lax.psum of BASELINE.json:5.
        dangling_mass = jnp.sum(ranks * dg.dangling)
        contribs = contribs + dangling_mass * e
    base = (1.0 - damping) * total_mass * e
    return base + damping * contribs


class SparkExactState(NamedTuple):
    """Carry for exact canonical-Spark-example emulation: the rank table's
    key set shrinks to nodes that received contributions (SURVEY.md §3.1)."""

    ranks: jax.Array  # f[N]; value only meaningful where present == 1
    present: jax.Array  # f[N]; 1.0 if node currently in the rank table


def spark_exact_step(
    state: SparkExactState, dg: DeviceGraph, *, n: int, damping: float, impl: str = "segment"
) -> SparkExactState:
    weighted = state.ranks * state.present * dg.inv_outdeg
    contribs = _spmv(dg, weighted, n, impl)
    # A node re-enters the table iff some present source with out-links
    # points at it (join emits ≥1 record for it).
    received = _spmv(dg, state.present * dg.has_outlinks, n, impl)
    present = (received > 0).astype(state.ranks.dtype)
    ranks = present * ((1.0 - damping) + damping * contribs)
    return SparkExactState(ranks=ranks, present=present)


def make_pagerank_runner(n: int, cfg: PageRankConfig):
    """Compile the full iteration loop into one XLA program.

    Returns ``run(dg, ranks0, e) -> (ranks, iters_done, final_delta)``.
    Fixed-iteration runs use ``lax.scan`` (XLA unrolls the loop body once and
    reuses it); tolerance runs use ``lax.while_loop`` carrying the L1 delta.
    The Python-side driver loop of the reference (SURVEY.md §3.1 🔥 outer
    loop) disappears entirely — there are no host round-trips between
    iterations.

    ``ranks0`` is **donated** (``donate_argnums=(1,)``): the carry is dead
    the moment the loop starts, so XLA reuses its buffer for the output
    ranks instead of holding two node-sized vectors live across the whole
    loop.  The input array is consumed — callers that re-invoke a runner
    must re-``device_put`` a fresh carry (the segment driver threads each
    segment's output into the next, so it never reuses one; bench.py re-puts
    per timing rep).  The tier-3 donation verifier (analysis/cost.py) holds
    this contract against the lowered computation's input/output aliasing.
    """
    damping = cfg.damping
    impl = cfg.spmv_impl
    dangling = cfg.dangling
    total_mass = float(n) if cfg.init is RankInit.ONE else 1.0

    def step_fn(ranks: jax.Array, dg: DeviceGraph, e: jax.Array) -> jax.Array:
        return pagerank_step(
            ranks, dg, e,
            n=n, damping=damping, dangling=dangling,
            total_mass=total_mass, impl=impl,
        )

    if cfg.tol > 0.0:

        @functools.partial(jax.jit, donate_argnums=(1,))
        def run(dg: DeviceGraph, ranks0: jax.Array, e: jax.Array):
            def cond(carry):
                _, delta, it = carry
                return jnp.logical_and(delta > cfg.tol, it < cfg.iterations)

            def body(carry):
                ranks, _, it = carry
                new = step_fn(ranks, dg, e)
                return new, jnp.sum(jnp.abs(new - ranks)), it + 1

            init = (ranks0, jnp.array(jnp.inf, ranks0.dtype), jnp.array(0, jnp.int32))
            ranks, delta, it = jax.lax.while_loop(cond, body, init)
            return ranks, it, delta

        return run

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(dg: DeviceGraph, ranks0: jax.Array, e: jax.Array):
        def body(ranks, _):
            new = step_fn(ranks, dg, e)
            return new, jnp.sum(jnp.abs(new - ranks))

        ranks, deltas = jax.lax.scan(body, ranks0, None, length=cfg.iterations)
        last = deltas[-1] if cfg.iterations > 0 else jnp.array(jnp.inf, ranks0.dtype)
        return ranks, jnp.array(cfg.iterations, jnp.int32), last

    return run


def make_spark_exact_runner(n: int, cfg: PageRankConfig):
    """Runner for spark_exact mode (always fixed iterations, like the
    reference's ``for i in range(iters)`` driver loop).  ``ranks0`` is
    donated, same contract as :func:`make_pagerank_runner`."""

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(dg: DeviceGraph, ranks0: jax.Array, e: jax.Array):
        del e  # spark_exact is never personalized
        state0 = SparkExactState(ranks=ranks0, present=dg.has_outlinks)

        def body(state, _):
            new = spark_exact_step(state, dg, n=n, damping=cfg.damping, impl=cfg.spmv_impl)
            delta = jnp.sum(jnp.abs(new.ranks - state.ranks))
            return new, delta

        state, deltas = jax.lax.scan(body, state0, None, length=cfg.iterations)
        last = deltas[-1] if cfg.iterations > 0 else jnp.array(jnp.inf, ranks0.dtype)
        return state.ranks, jnp.array(cfg.iterations, jnp.int32), last

    return run
