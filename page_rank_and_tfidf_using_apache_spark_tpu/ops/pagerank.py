"""PageRank numeric core: one XLA program per iteration loop.

Reference counterpart (SURVEY.md §3.1, BASELINE.json:5): the per-iteration
Spark chain ``links.join(ranks).flatMap(computeContribs).reduceByKey(add)
.mapValues(0.15 + 0.85*r)`` — two shuffle stages per iteration, scheduled by
the DAGScheduler, executed as per-record iterator chains.

TPU-native design: the whole iteration is one sparse matvec plus an axpy —
``contribs = Aᵀ · (ranks / outdeg)``; ``ranks' = base + d·(contribs [+
dangling])`` — expressed as a gather + ``segment_sum`` over destination-
sorted edges (the `reduceByKey` becomes a contiguous segmented reduction the
MXU/VPU pipeline, not a shuffle), and the *entire loop* lives inside one
``jit``-compiled ``lax.scan`` / ``lax.while_loop``: zero host round-trips
between iterations, XLA fuses the damping/axpy/delta into the reduction's
epilogue.

Semantics flags (SURVEY.md §3.1 dangling-node caveat):
- ``dangling=drop``        mass at out-degree-0 nodes vanishes (canonical
                           Spark example behavior).
- ``dangling=redistribute`` dangling mass re-spread over the restart
                           distribution (textbook/networkx behavior; keeps
                           ``sum(ranks)`` invariant).
- ``spark_exact``          additionally reproduces the example's shrinking
                           key-set: nodes that receive no contribution drop
                           out of the rank table entirely (rank 0, and they
                           stop contributing even if they have out-links).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.fixpoint import iterate
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    TUNABLE_DEFAULTS,
    DanglingMode,
    PageRankConfig,
    RankInit,
)


class HybridLayout(NamedTuple):
    """Static degree-aware head/tail split of the dst-sorted edge array
    (*Sparse Allreduce*'s dense-head/sparse-tail decomposition of a
    power-law degree distribution, blocked for the MXU per *RankMap*).

    The **head** is the top-k in-degree destinations covering roughly
    ``coverage`` of all edges (every one with in-degree >= the row width,
    so a dense row is never mostly padding): each head node's in-edges are
    chunked into fixed-width rows of ``head_src``, whose per-iteration
    reduction is a single ``[R, W] @ [W]`` matvec on the MXU — the hot,
    scatter-heavy rows of the power-law distribution stop touching the
    scatter path entirely.  The **tail** keeps the sorted-segment layout.
    Sentinel source id ``n`` points at the zero slot of the extended
    weight vector, so padding needs no mask."""

    head_ids: jax.Array  # int32 [H] head node ids (in-degree descending)
    head_src: jax.Array  # int32 [R, W] per-row edge sources (sentinel n)
    head_row_node: jax.Array  # int32 [R] row -> head slot, non-decreasing
    tail_src: jax.Array  # int32 [Et]
    tail_dst: jax.Array  # int32 [Et], non-decreasing
    tail_indptr: jax.Array  # int32 [N+1] CSR pointers over the tail edges
    head_w: jax.Array | None = None  # f [R, W] edge weights (0 at sentinels)
    tail_w: jax.Array | None = None  # f [Et] edge weights


class ShuffleLayout(NamedTuple):
    """Sort-based static-shuffle layout: the dst-sorted edge array padded
    so every destination's run occupies whole fixed-width buckets.  The
    per-iteration reduction is then a pure ``reshape -> reduce`` over the
    bucket matrix plus a bucket-granular (B× smaller) sorted segment-sum —
    no edge-granular scatter or prefix scan survives on the contribution
    side.  Sentinel source id ``n`` reads the zero slot of the extended
    weight vector."""

    bucket_src: jax.Array  # int32 [NB, B] per-bucket edge sources
    bucket_node: jax.Array  # int32 [NB] bucket -> dst node, non-decreasing
    bucket_w: jax.Array | None = None  # f [NB, B] edge weights (0 at pads)


class DeviceGraph(NamedTuple):
    """Device-resident graph state (the reference's ``links.cache()`` —
    SURVEY.md A3: built once, reused across all iterations)."""

    src: jax.Array  # int32 [E], edge sources, dst-sorted order
    dst: jax.Array  # int32 [E], non-decreasing
    inv_outdeg: jax.Array  # f[N], 1/out_degree — 1/out_STRENGTH on a
    # weighted graph — (0 at dangling nodes)
    dangling: jax.Array  # f[N], 1.0 where out_degree == 0
    has_outlinks: jax.Array  # f[N], 1.0 where out_degree > 0
    indptr: jax.Array | None = None  # int32 [N+1], CSR row pointers into dst
    hybrid: HybridLayout | None = None  # spmv_impl='hybrid' static layout
    shuffle: ShuffleLayout | None = None  # spmv_impl='sort_shuffle' layout
    # Per-edge weights in dst-sorted order (weighted PageRank, ISSUE 15):
    # the SpMV contribution becomes ``w(u,v) * rank[u] / strength[u]`` —
    # networkx ``pagerank(weight=)`` semantics.  None = unweighted.
    edge_weight: jax.Array | None = None


def _pow2_floor(x: int) -> int:
    return 1 << max(int(x).bit_length() - 1, 0)


def plan_hybrid_head(
    in_degree: np.ndarray,
    n_edges: int,
    *,
    coverage: float = 0.5,
    row_width: int = 128,
) -> tuple[np.ndarray, int]:
    """Head-membership policy shared by the single-chip layout builder and
    the sharded partition *planner* (parallel/pagerank_sharded.py) — the
    two must agree or the linted plan is not the materialized one.

    Returns ``(head_order, W)``: node ids in in-degree-descending order
    truncated to the head, and the effective row width.  The head is the
    smallest top-k covering ``coverage`` of all edges, where every member
    has in-degree >= W (a lower-degree node would make its dense row
    mostly padding — those stay on the tail path).  W adapts downward to
    the largest power of two <= the max in-degree so small graphs still
    exercise the dense path."""
    if n_edges == 0 or in_degree.size == 0:
        return np.zeros(0, np.int64), max(8, row_width)
    w = max(8, min(row_width, _pow2_floor(int(in_degree.max()))))
    order = np.argsort(-in_degree, kind="stable")
    deg_sorted = in_degree[order]
    k_deg = int(np.searchsorted(-deg_sorted, -w, side="right"))
    if k_deg == 0:
        return np.zeros(0, np.int64), w
    cum = np.cumsum(deg_sorted[:k_deg], dtype=np.int64)
    k_cov = int(np.searchsorted(cum, coverage * n_edges, side="left")) + 1
    k = min(k_deg, k_cov)
    return order[:k].astype(np.int64), w


class HybridHostLayout(NamedTuple):
    """Numpy form of :class:`HybridLayout` plus its padding accounting —
    built once on host at ``put_graph`` time (the amortized
    ``spmv_preprocess_secs`` bench.py records)."""

    head_ids: np.ndarray
    head_src: np.ndarray
    head_row_node: np.ndarray
    tail_src: np.ndarray
    tail_dst: np.ndarray
    tail_indptr: np.ndarray
    head_edges: int
    pad_slots: int  # sentinel slots in the dense rows
    head_w: np.ndarray | None = None  # [R, W] weights (0 at sentinels)
    tail_w: np.ndarray | None = None  # [Et] weights


def build_hybrid_layout(
    graph: Graph, *, coverage: float = 0.5, row_width: int = 128
) -> HybridHostLayout:
    """One-time host pass: degree sort -> head/tail split -> dense row
    blocking.  O(E) after the cached csr_indptr; fully vectorized."""
    n = graph.n_nodes
    ip = graph.csr_indptr()
    indeg = np.diff(ip)
    head_ids, w = plan_hybrid_head(
        indeg, graph.n_edges, coverage=coverage, row_width=row_width
    )
    in_head = np.zeros(n + 1, bool)
    in_head[head_ids] = True

    # dense head rows: each head node's in-edge run chunked into whole
    # rows of width w, the last row padded with the sentinel id n.
    # Vectorized like build_shuffle_layout: per-edge (row, col) from
    # repeat/offset arithmetic, one fancy-index store for all head edges.
    deg = indeg[head_ids] if head_ids.size else np.zeros(0, np.int64)
    rows_per = -(-deg // w)
    r = int(rows_per.sum())
    head_src = np.full((r, w), n, np.int32)
    weighted = graph.weight is not None
    head_w = np.zeros((r, w), np.float64) if weighted else None  # graftlint: disable=dtype-drift (host staging; cast to the run dtype at put_graph)
    head_row_node = np.repeat(
        np.arange(head_ids.size, dtype=np.int64), rows_per
    ).astype(np.int32)
    if head_ids.size:
        row_start = np.concatenate([[0], np.cumsum(rows_per)])
        run_start = np.concatenate([[0], np.cumsum(deg)])
        offs = np.arange(int(deg.sum()), dtype=np.int64) - np.repeat(
            run_start[:-1], deg
        )
        e_idx = np.repeat(ip[head_ids], deg) + offs
        rows = np.repeat(row_start[:-1], deg) + offs // w
        head_src[rows, offs % w] = graph.src[e_idx]
        if weighted:
            head_w[rows, offs % w] = graph.weight[e_idx]

    keep = ~in_head[graph.dst]
    tail_src = graph.src[keep].astype(np.int32)
    tail_dst = graph.dst[keep].astype(np.int32)
    tail_indptr = np.searchsorted(tail_dst, np.arange(n + 1)).astype(np.int32)
    head_edges = int(graph.n_edges - tail_src.size)
    return HybridHostLayout(
        head_ids=head_ids.astype(np.int32),
        head_src=head_src,
        head_row_node=head_row_node,
        tail_src=tail_src,
        tail_dst=tail_dst,
        tail_indptr=tail_indptr,
        head_edges=head_edges,
        pad_slots=r * w - head_edges,
        head_w=head_w,
        tail_w=graph.weight[keep] if weighted else None,
    )


def build_shuffle_layout(
    graph: Graph, *,
    bucket_width: int = TUNABLE_DEFAULTS["shuffle_bucket_width"],
) -> tuple[
    np.ndarray, np.ndarray, np.ndarray | None
]:
    """One-time host pass for the sort-based static shuffle: pad every
    destination's (already dst-sorted) edge run to whole buckets of width
    ``bucket_width``.  Returns ``(bucket_src [NB, B], bucket_node [NB],
    bucket_w [NB, B] | None)`` — fully vectorized, no per-node python
    loop; ``bucket_w`` carries per-edge weights (0 at pad slots) for a
    weighted graph."""
    n, e, b = graph.n_nodes, graph.n_edges, bucket_width
    ip = graph.csr_indptr()
    indeg = np.diff(ip)
    buckets_per = -(-indeg // b)
    nb = int(buckets_per.sum())
    bucket_src = np.full((nb, b), n, np.int32)
    bucket_w = (
        np.zeros((nb, b), np.float64)  # graftlint: disable=dtype-drift (host staging; cast to the run dtype at put_graph)
        if graph.weight is not None else None
    )
    bucket_node = np.repeat(
        np.arange(n, dtype=np.int64), buckets_per
    ).astype(np.int32)
    if e:
        # per-edge (row, col) inside its node's bucket block
        offs = np.arange(e, dtype=np.int64) - np.repeat(ip[:-1], indeg)
        bucket_start = np.concatenate([[0], np.cumsum(buckets_per)])
        row = np.repeat(bucket_start[:-1], indeg) + offs // b
        bucket_src[row, offs % b] = graph.src
        if bucket_w is not None:
            bucket_w[row, offs % b] = graph.weight
    return bucket_src, bucket_node, bucket_w


def put_graph(
    graph: Graph,
    dtype: str = "float32",
    *,
    layout: str | None = None,
    head_coverage: float = TUNABLE_DEFAULTS["head_coverage"],
    head_row_width: int = TUNABLE_DEFAULTS["head_row_width"],
    bucket_width: int = TUNABLE_DEFAULTS["shuffle_bucket_width"],
    keep_edge_arrays: bool = True,
) -> DeviceGraph:
    """Host Graph → device arrays (one host→device transfer per run).

    ``layout`` additionally builds the static SpMV layout an impl needs:
    ``"hybrid"`` (degree-aware dense head + segment tail) or
    ``"sort_shuffle"`` (fixed-width dst buckets).  See
    :func:`layout_for_impl` for the impl -> layout mapping.

    ``keep_edge_arrays=False`` skips the raw ``src``/``dst``/``indptr``
    device upload (zero-length placeholders instead): the layout impls
    never read them, and at bench scale they are ~3E dead int32 on HBM
    plus transfer time — only valid when the caller commits to a
    layout-backed impl (models.pagerank.put_graph_for does)."""
    # Weighted graphs normalize by out-STRENGTH (Σ outgoing weights —
    # networkx stochastic_graph semantics); unweighted by out-degree.
    # Dangling is out_degree == 0 under both (weights are positive).
    inv = graph.inv_out_strength(dtype)
    if not keep_edge_arrays and layout is None:
        raise ValueError("keep_edge_arrays=False requires a static layout")
    src_h = graph.src if keep_edge_arrays else np.zeros(0, np.int32)
    dst_h = graph.dst if keep_edge_arrays else np.zeros(0, np.int32)
    indptr = (
        graph.csr_indptr().astype(np.int32)
        if keep_edge_arrays else np.zeros(0, np.int32)
    )
    weighted = graph.weight is not None
    edge_weight = (
        jnp.asarray(graph.weight.astype(dtype))
        if weighted and keep_edge_arrays else None
    )
    hybrid = None
    shuffle = None
    if layout == "hybrid":
        hl = build_hybrid_layout(
            graph, coverage=head_coverage, row_width=head_row_width
        )
        hybrid = HybridLayout(
            head_ids=jnp.asarray(hl.head_ids),
            head_src=jnp.asarray(hl.head_src),
            head_row_node=jnp.asarray(hl.head_row_node),
            tail_src=jnp.asarray(hl.tail_src),
            tail_dst=jnp.asarray(hl.tail_dst),
            tail_indptr=jnp.asarray(hl.tail_indptr),
            head_w=(jnp.asarray(hl.head_w.astype(dtype))
                    if hl.head_w is not None else None),
            tail_w=(jnp.asarray(hl.tail_w.astype(dtype))
                    if hl.tail_w is not None else None),
        )
    elif layout == "sort_shuffle":
        bucket_src, bucket_node, bucket_w = build_shuffle_layout(
            graph, bucket_width=bucket_width
        )
        shuffle = ShuffleLayout(
            bucket_src=jnp.asarray(bucket_src),
            bucket_node=jnp.asarray(bucket_node),
            bucket_w=(jnp.asarray(bucket_w.astype(dtype))
                      if bucket_w is not None else None),
        )
    elif layout is not None:
        raise ValueError(f"unknown graph layout {layout!r}")
    return DeviceGraph(
        src=jnp.asarray(src_h),
        dst=jnp.asarray(dst_h),
        inv_outdeg=jnp.asarray(inv),
        dangling=jnp.asarray((graph.out_degree == 0).astype(dtype)),
        has_outlinks=jnp.asarray((graph.out_degree > 0).astype(dtype)),
        indptr=jnp.asarray(indptr),
        hybrid=hybrid,
        shuffle=shuffle,
        edge_weight=edge_weight,
    )


def layout_for_impl(impl: str) -> str | None:
    """Which static layout ``put_graph`` must build for an spmv impl."""
    return {"hybrid": "hybrid", "sort_shuffle": "sort_shuffle"}.get(impl)


def restart_vector(n: int, cfg: PageRankConfig) -> np.ndarray:
    """The teleport distribution e: uniform for standard PageRank, an
    indicator over the source set for personalized PageRank
    (BASELINE.json:10; SURVEY.md §3.4)."""
    dtype = cfg.dtype
    if cfg.personalize is None:
        return np.full(n, 1.0 / n, dtype=dtype)
    e = np.zeros(n, dtype=dtype)
    idx = np.asarray(cfg.personalize, dtype=np.int64)
    if idx.size == 0:
        raise ValueError("personalize must name at least one node")
    if (idx < 0).any() or (idx >= n).any():
        raise ValueError(f"personalize node ids out of range [0, {n})")
    # np.add.at so duplicate ids accumulate — e must always sum to 1.
    np.add.at(e, idx, 1.0 / idx.size)
    return e


def init_ranks(n: int, cfg: PageRankConfig) -> np.ndarray:
    if cfg.init is RankInit.ONE:
        return np.ones(n, dtype=cfg.dtype)
    return np.full(n, 1.0 / n, dtype=cfg.dtype)


def _edge_values(dg: DeviceGraph, weighted_ranks: jax.Array) -> jax.Array:
    """Per-edge contribution ``weighted_ranks[src] (* w(src, dst))`` — the
    one place the optional edge-weight multiply lives for the raw-edge
    impls (segment/cumsum/cumsum_mxu/pallas share it)."""
    per_edge = weighted_ranks[dg.src]
    if dg.edge_weight is not None:
        per_edge = per_edge * dg.edge_weight
    return per_edge


def spmv_segment(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """contribs[v] = Σ_{(u,v)∈E} w(u,v)·weighted_ranks[u] via sorted
    segment_sum — the `reduceByKey(add)` of BASELINE.json:5 as one
    segmented reduction (w ≡ 1 unweighted)."""
    return jax.ops.segment_sum(
        _edge_values(dg, weighted_ranks), dg.dst,
        num_segments=n, indices_are_sorted=True,
    )


def spmv_bcoo(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """Same contraction through jax.experimental.sparse.BCOO (the
    BASELINE.json:5 prescription) — kept as a benchmarked alternative."""
    from jax.experimental import sparse

    data = (
        dg.edge_weight if dg.edge_weight is not None
        else jnp.ones_like(weighted_ranks, shape=dg.src.shape)
    )
    mat = sparse.BCOO(
        (data, jnp.stack([dg.dst, dg.src], axis=1)),
        shape=(n, n),
        indices_sorted=True,
        unique_indices=True,
    )
    return mat @ weighted_ranks


def cumsum_diff_spmv(per_edge, indptr, cumsum_fn=jnp.cumsum) -> jax.Array:
    """Shared prefix-sum segmented-reduction skeleton: ``out[v] =
    cumsum(per_edge)[indptr[v+1]] - cumsum(per_edge)[indptr[v]]``, exploiting
    a sorted-segment invariant to replace the scatter-add with a cumsum
    plus two *monotone* gathers.  ``cumsum_fn`` is the prefix-sum primitive
    (``jnp.cumsum`` for the XLA variant, the Pallas carry kernel for
    spmv_impl='pallas'); accuracy analysis on :func:`spmv_cumsum`."""
    c0 = jnp.concatenate([jnp.zeros(1, per_edge.dtype), cumsum_fn(per_edge)])
    return c0[indptr[1:]] - c0[indptr[:-1]]


def cumsum_blocked(x: jax.Array, block: int = 128) -> jax.Array:
    """Inclusive prefix sum as MXU work instead of XLA's reduce-window.

    ``jnp.cumsum`` over millions of elements lowers to an O(E·log E)
    reduce-window chain on TPU; here the E-length scan becomes one
    ``[M, B] @ [B, B]`` upper-triangular matmul on the systolic array
    (row-wise inclusive cumsum of an ``[M, B]`` reshape) plus a B×-smaller
    recursive carry — ~2 HBM passes and trivial MXU FLOPs (E·B).  Error is
    the blocked-summation order, no worse than the sequential scan's.
    """
    n = x.shape[0]
    if n <= 4 * block:
        return jnp.cumsum(x)
    m = -(-n // block)
    xp = jnp.concatenate([x, jnp.zeros(m * block - n, x.dtype)]).reshape(m, block)
    # T[k, j] = 1 for k <= j: row-cumsum via one MXU matmul.  HIGHEST
    # precision keeps f32 inputs f32 on TPU (default would round through
    # bf16, breaking the "same accuracy class as the sequential scan"
    # contract); the FLOPs are trivial either way.
    tri = jnp.triu(jnp.ones((block, block), x.dtype))
    rows = jnp.matmul(xp, tri, precision=jax.lax.Precision.HIGHEST)
    row_tot = rows[:, -1]
    carry = cumsum_blocked(row_tot, block) - row_tot  # exclusive row carry
    return (rows + carry[:, None]).reshape(-1)[:n]


def spmv_cumsum(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """Prefix-sum SpMV through ``jnp.cumsum`` — measured 1.5x faster per
    PageRank iteration than ``segment_sum`` at web-Google scale on TPU v5e,
    where XLA's scatter path is the bottleneck.  Accuracy cost in float32:
    the prefix sum accumulates to the full vector mass before differencing,
    so per-SpMV L1 error is ~2e-4 relative (vs ~1e-5 for segment_sum);
    parity tests run it in float64 where both are exact to 1e-12.
    """
    if dg.indptr is None:
        raise ValueError("spmv_impl='cumsum' needs DeviceGraph.indptr (use put_graph)")
    return cumsum_diff_spmv(_edge_values(dg, weighted_ranks), dg.indptr)


def spmv_cumsum_mxu(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """The prefix-sum SpMV with the MXU-blocked cumsum (:func:`cumsum_blocked`)
    as the scan primitive — same accuracy class as spmv_cumsum."""
    if dg.indptr is None:
        raise ValueError("spmv_impl='cumsum_mxu' needs DeviceGraph.indptr (use put_graph)")
    return cumsum_diff_spmv(_edge_values(dg, weighted_ranks), dg.indptr,
                            cumsum_fn=cumsum_blocked)


def hybrid_rowsum(rows: jax.Array) -> jax.Array:
    """Dense-head row reduction: ``[R, W] -> [R]`` as one MXU matvec
    against a ones vector (the RankMap-style blocked contraction).  On a
    real TPU the Pallas kernel streams the row matrix through VMEM in one
    HBM pass; elsewhere the plain dot is what XLA lowers best (the
    interpreter at bench scale would be pointless)."""
    if jax.default_backend() in ("tpu", "axon"):
        from page_rank_and_tfidf_using_apache_spark_tpu.ops import (
            pallas_kernels as pk,
        )

        return pk.rowsum_pallas(rows)
    ones = jnp.ones((rows.shape[1],), rows.dtype)
    return jnp.matmul(rows, ones, precision=jax.lax.Precision.HIGHEST)


def spmv_hybrid(dg: DeviceGraph, weighted_ranks: jax.Array, n: int) -> jax.Array:
    """Degree-aware hybrid SpMV: the high-in-degree head as a dense
    ``[R, W]`` gather + MXU row reduction (zero scatter traffic for the
    power-law hot rows), the long tail through the scatter-free
    prefix-sum/monotone-diff path over its own CSR pointers, combined
    with one scatter-add of H head totals.

    Accuracy class: the head rows sum in fixed blocked order (segment
    class — each node accumulates within its own rows only); the tail
    inherits the prefix-sum class of :func:`spmv_cumsum`, but over only
    the tail's mass — roughly half the accumulated error of the full
    cumsum impl at the default 0.5 head coverage."""
    hl = dg.hybrid
    if hl is None:
        raise ValueError("spmv_impl='hybrid' needs put_graph(layout='hybrid')")
    if hl.tail_src.shape[0]:
        per_tail = weighted_ranks[hl.tail_src]
        if hl.tail_w is not None:
            per_tail = per_tail * hl.tail_w
        contribs = cumsum_diff_spmv(per_tail, hl.tail_indptr)
    else:
        contribs = jnp.zeros(n, weighted_ranks.dtype)
    h = hl.head_ids.shape[0]
    if h:
        w_ext = jnp.concatenate(
            [weighted_ranks, jnp.zeros(1, weighted_ranks.dtype)]
        )
        rows = w_ext[hl.head_src]
        if hl.head_w is not None:
            rows = rows * hl.head_w  # sentinel slots carry weight 0
        row_sums = hybrid_rowsum(rows)
        head = jax.ops.segment_sum(
            row_sums, hl.head_row_node, num_segments=h, indices_are_sorted=True
        )
        contribs = contribs.at[hl.head_ids].add(head)
    return contribs


def spmv_sort_shuffle(
    dg: DeviceGraph, weighted_ranks: jax.Array, n: int
) -> jax.Array:
    """Sort-based static-shuffle SpMV: with every destination's edge run
    padded to whole fixed-width buckets at ``put_graph`` time, the
    per-iteration contribution side is a pure ``reshape -> reduce`` over
    the bucket matrix plus a bucket-granular sorted segment-sum — the
    edge-granular scatter/prefix machinery shrinks by the bucket width."""
    sl = dg.shuffle
    if sl is None:
        raise ValueError(
            "spmv_impl='sort_shuffle' needs put_graph(layout='sort_shuffle')"
        )
    if sl.bucket_src.shape[0] == 0:
        return jnp.zeros(n, weighted_ranks.dtype)
    w_ext = jnp.concatenate(
        [weighted_ranks, jnp.zeros(1, weighted_ranks.dtype)]
    )
    vals = w_ext[sl.bucket_src]
    if sl.bucket_w is not None:
        vals = vals * sl.bucket_w  # pad slots carry weight 0
    bucket_sums = vals.sum(axis=1)
    return jax.ops.segment_sum(
        bucket_sums, sl.bucket_node, num_segments=n, indices_are_sorted=True
    )


def spmv(dg: DeviceGraph, weighted: jax.Array, n: int, impl: str) -> jax.Array:
    """The one SpMV dispatch point: route a weighted gather+combine
    through the impl the graph's static layout was built for.  This is
    the ``dataflow.graph_combine`` shuffle backend — every fixpoint
    workload (PageRank, personalized PageRank, HITS) shares these tuned
    impls instead of owning scatter strategy privately."""
    if impl == "segment":
        return spmv_segment(dg, weighted, n)
    if impl == "bcoo":
        return spmv_bcoo(dg, weighted, n)
    if impl == "cumsum":
        return spmv_cumsum(dg, weighted, n)
    if impl == "cumsum_mxu":
        return spmv_cumsum_mxu(dg, weighted, n)
    if impl == "hybrid":
        return spmv_hybrid(dg, weighted, n)
    if impl == "sort_shuffle":
        return spmv_sort_shuffle(dg, weighted, n)
    if impl == "pallas":
        from page_rank_and_tfidf_using_apache_spark_tpu.ops import pallas_kernels as pk

        if dg.indptr is None:
            raise ValueError("spmv_impl='pallas' needs DeviceGraph.indptr (use put_graph)")
        # Mosaic only compiles on real TPUs; everywhere else (CPU tests,
        # simulated meshes) run the same kernel under the interpreter.
        interpret = jax.default_backend() not in ("tpu", "axon")
        return pk.spmv_pallas(dg.src, dg.indptr, weighted, n=n,
                              edge_weight=dg.edge_weight, interpret=interpret)
    raise ValueError(f"unknown spmv impl {impl!r}")


def pagerank_step(
    ranks: jax.Array,
    dg: DeviceGraph,
    e: jax.Array,
    *,
    n: int,
    damping: float,
    dangling: DanglingMode,
    total_mass: float,
    impl: str = "segment",
) -> jax.Array:
    """One power-iteration step.

    ``total_mass`` is the invariant rank-vector sum: ``n`` under the Spark
    init=ONE convention (uniform restart term is then the familiar constant
    0.15), ``1.0`` under the textbook init=UNIFORM convention (restart term
    (1-d)/n).  The restart distribution ``e`` always sums to 1; both the
    restart and the redistributed dangling mass are spread according to it,
    so under dangling=redistribute ``sum(ranks) == total_mass`` is exactly
    preserved every step.
    """
    weighted = ranks * dg.inv_outdeg
    contribs = spmv(dg, weighted, n, impl)
    if dangling is DanglingMode.REDISTRIBUTE:
        # lost mass re-enters through the restart distribution e; on a
        # sharded mesh this sum is the lax.psum of BASELINE.json:5.
        dangling_mass = jnp.sum(ranks * dg.dangling)
        contribs = contribs + dangling_mass * e
    base = (1.0 - damping) * total_mass * e
    return base + damping * contribs


class SparkExactState(NamedTuple):
    """Carry for exact canonical-Spark-example emulation: the rank table's
    key set shrinks to nodes that received contributions (SURVEY.md §3.1)."""

    ranks: jax.Array  # f[N]; value only meaningful where present == 1
    present: jax.Array  # f[N]; 1.0 if node currently in the rank table


def spark_exact_step(
    state: SparkExactState, dg: DeviceGraph, *, n: int, damping: float, impl: str = "segment"
) -> SparkExactState:
    weighted = state.ranks * state.present * dg.inv_outdeg
    contribs = spmv(dg, weighted, n, impl)
    # A node re-enters the table iff some present source with out-links
    # points at it (join emits ≥1 record for it).
    received = spmv(dg, state.present * dg.has_outlinks, n, impl)
    present = (received > 0).astype(state.ranks.dtype)
    ranks = present * ((1.0 - damping) + damping * contribs)
    return SparkExactState(ranks=ranks, present=present)


def make_pagerank_runner(n: int, cfg: PageRankConfig):
    """Compile the full iteration loop into one XLA program.

    Returns ``run(dg, ranks0, e) -> (ranks, iters_done, final_delta)``.
    Fixed-iteration runs use ``lax.scan`` (XLA unrolls the loop body once and
    reuses it); tolerance runs use ``lax.while_loop`` carrying the L1 delta.
    The Python-side driver loop of the reference (SURVEY.md §3.1 🔥 outer
    loop) disappears entirely — there are no host round-trips between
    iterations.

    ``ranks0`` is **donated** (``donate_argnums=(1,)``): the carry is dead
    the moment the loop starts, so XLA reuses its buffer for the output
    ranks instead of holding two node-sized vectors live across the whole
    loop.  The input array is consumed — callers that re-invoke a runner
    must re-``device_put`` a fresh carry (the segment driver threads each
    segment's output into the next, so it never reuses one; bench.py re-puts
    per timing rep).  The tier-3 donation verifier (analysis/cost.py) holds
    this contract against the lowered computation's input/output aliasing.

    The loop skeleton is the dataflow core's :func:`dataflow.fixpoint
    .iterate` combinator — one scan/while implementation shared with the
    sharded runner and every new fixpoint workload.
    """
    damping = cfg.damping
    impl = cfg.spmv_impl
    dangling = cfg.dangling
    total_mass = float(n) if cfg.init is RankInit.ONE else 1.0

    def step_fn(ranks: jax.Array, dg: DeviceGraph, e: jax.Array) -> jax.Array:
        return pagerank_step(
            ranks, dg, e,
            n=n, damping=damping, dangling=dangling,
            total_mass=total_mass, impl=impl,
        )

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(dg: DeviceGraph, ranks0: jax.Array, e: jax.Array):
        return iterate(
            lambda ranks: step_fn(ranks, dg, e), ranks0,
            iterations=cfg.iterations, tol=cfg.tol,
        )

    return run


def make_spark_exact_runner(n: int, cfg: PageRankConfig):
    """Runner for spark_exact mode (always fixed iterations, like the
    reference's ``for i in range(iters)`` driver loop).  ``ranks0`` is
    donated, same contract as :func:`make_pagerank_runner`."""

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(dg: DeviceGraph, ranks0: jax.Array, e: jax.Array):
        del e  # spark_exact is never personalized
        state0 = SparkExactState(ranks=ranks0, present=dg.has_outlinks)
        state, iters, last = iterate(
            lambda s: spark_exact_step(
                s, dg, n=n, damping=cfg.damping, impl=cfg.spmv_impl
            ),
            state0,
            iterations=cfg.iterations,
            delta_fn=lambda new, old: jnp.sum(jnp.abs(new.ranks - old.ranks)),
        )
        return state.ranks, iters, last

    return run
