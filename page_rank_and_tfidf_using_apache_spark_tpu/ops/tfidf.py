"""TF-IDF numeric core: hashed-vocabulary TF / DF / weight passes on device.

Reference counterpart (SURVEY.md §3.2, BASELINE.json:5): Spark's
``flatMap(tokenize) → reduceByKey`` term-count pass, the ``distinct →
reduceByKey`` document-frequency pass, and the ``tf.join(idf)`` weight join
— three shuffles over ((term, doc), count) records.

TPU-native design: tokens arrive as flat hashed ``(doc_id, term_id)`` int32
arrays (io/text.py).  Both `reduceByKey` passes become **one sort + one
run-length encoding**: sort tokens by the composite key ``term·D + doc``;
each maximal run of equal keys is one (term, doc) pair, so

- TF  = run lengths                       (``segment_sum`` of ones over runs)
- DF  = number of runs per term           (``segment_sum`` of run-starts)
- the tf·idf "join" = a gather of ``idf[term]`` into each run

All shapes are static (outputs padded to ``n_tokens`` with a validity mask),
so the whole pipeline is one ``jit``-compiled XLA program per (n_tokens,
vocab) shape — the streaming ingest path (models/tfidf.py) feeds fixed-size
chunks precisely so this compiles once (SURVEY.md §7 "fixed shapes under
jit").
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import IdfMode, TfMode


class SparseCounts(NamedTuple):
    """Padded COO of per-(doc, term) counts — the materialized result of the
    reference's TF `reduceByKey`.  Rows ``[0, n_pairs)`` are valid, sorted by
    (term, doc); the padding tail repeats harmless zeros."""

    doc: jax.Array  # int32 [cap]
    term: jax.Array  # int32 [cap]
    count: jax.Array  # f[cap]
    n_pairs: jax.Array  # int32 scalar — number of valid rows
    valid: jax.Array  # f[cap] — 1.0 for valid rows


class TfidfResult(NamedTuple):
    """Sparse per-(doc, term) TF-IDF weights + the dense IDF vector (the
    reference's joined A10 output plus the broadcast IDF table R3).

    ``count`` carries the raw per-pair term counts alongside the
    finalized weights: the BM25 ranker (dataflow/bm25.py) re-weights the
    SAME postings from counts, so the pipeline exports them instead of
    forcing a second corpus pass.  Optional (None) for legacy callers
    that build a result by hand."""

    doc: jax.Array  # int32 [cap]
    term: jax.Array  # int32 [cap]
    weight: jax.Array  # f[cap]
    n_pairs: jax.Array  # int32 scalar
    valid: jax.Array  # f[cap]
    idf: jax.Array  # f[vocab]
    df: jax.Array  # f[vocab]
    count: jax.Array | None = None  # f[cap] raw per-pair counts


def count_pairs(
    doc_ids: jax.Array,
    term_ids: jax.Array,
    *,
    token_valid: jax.Array | None = None,
) -> SparseCounts:
    """The TF pass: ((term, doc), 1) → reduceByKey(add), as sort + RLE.

    ``token_valid`` masks padding tokens (streaming chunks); masked tokens
    sort to a sentinel key past every real pair and are excluded.
    """
    cap = doc_ids.shape[0]
    dtype = jnp.float32
    if cap == 0:  # empty corpus/chunk: keep every downstream shape valid
        zf = jnp.zeros(0, dtype)
        zi = jnp.zeros(0, jnp.int32)
        return SparseCounts(doc=zi, term=zi, count=zf, n_pairs=jnp.array(0, jnp.int32), valid=zf)
    # Lexicographic (valid-first, term-major, doc-minor) sort — avoids a
    # composite int key, which would overflow int32 at vocab 2^18 × many docs.
    # Multi-operand lax.sort instead of jnp.lexsort: the sorted doc/term/
    # validity arrays come out directly (no int64 permutation vector, no
    # post-sort gathers), so every aval in the trace stays at the declared
    # 32-bit widths — the tier-2 implicit-promotion gate traces this under
    # x64 and fails on any 64-bit leak.
    if token_valid is not None:
        _, term_s, doc_s, tok_valid_s = jax.lax.sort(
            (~token_valid, term_ids, doc_ids, token_valid),
            num_keys=3,
            is_stable=True,
        )
    else:
        term_s, doc_s = jax.lax.sort((term_ids, doc_ids), num_keys=2, is_stable=True)
        tok_valid_s = jnp.ones(cap, dtype=bool)

    changed = jnp.logical_or(term_s[1:] != term_s[:-1], doc_s[1:] != doc_s[:-1])
    run_start = jnp.concatenate([jnp.ones(1, bool), changed])
    run_start = jnp.logical_and(run_start, tok_valid_s)
    run_idx = jnp.cumsum(run_start.astype(jnp.int32)) - 1  # run id per token
    n_pairs = run_idx[-1] + 1
    # All tokens of a run share doc/term, so duplicate scatters write the
    # same value — order doesn't matter.
    safe_run = jnp.where(tok_valid_s, run_idx, cap - 1)
    doc_o = jnp.zeros(cap, doc_ids.dtype).at[safe_run].set(doc_s)
    term_o = jnp.zeros(cap, term_ids.dtype).at[safe_run].set(term_s)
    count_o = jax.ops.segment_sum(
        tok_valid_s.astype(dtype), safe_run, num_segments=cap
    )
    valid = (jnp.arange(cap, dtype=jnp.int32) < n_pairs).astype(dtype)
    return SparseCounts(
        doc=doc_o, term=term_o, count=count_o * valid, n_pairs=n_pairs, valid=valid
    )


def document_frequency(counts: SparseCounts, vocab: int) -> jax.Array:
    """The DF pass: distinct (term, doc) → (term, 1) → reduceByKey(add).
    Each valid COO row *is* one distinct pair, so DF is a segment_sum of the
    validity mask over terms."""
    return jax.ops.segment_sum(counts.valid, counts.term, num_segments=vocab)


def idf_vector(df: jax.Array, n_docs: jax.Array | float, mode: IdfMode) -> jax.Array:
    """IDF formula variants (SURVEY.md §4 — the reference's exact smoothing
    is unverifiable, so every common variant is pinned behind the flag).
    Terms with df == 0 get idf 0 (they never appear, weight is 0 anyway) —
    avoids inf under CLASSIC."""
    n = jnp.asarray(n_docs, df.dtype)
    safe_df = jnp.maximum(df, 1.0)
    if mode is IdfMode.CLASSIC:
        idf = jnp.log(n / safe_df)
    elif mode is IdfMode.MLLIB:
        idf = jnp.log((n + 1.0) / (df + 1.0))
    elif mode is IdfMode.SMOOTH:
        idf = jnp.log((1.0 + n) / (1.0 + df)) + 1.0
    else:
        raise ValueError(f"unknown idf mode {mode}")
    return jnp.where(df > 0, idf, 0.0)


def tf_values(
    counts: SparseCounts, doc_lengths: jax.Array, mode: TfMode
) -> jax.Array:
    """TF variants over the raw per-pair counts."""
    if mode is TfMode.RAW:
        return counts.count
    if mode is TfMode.FREQ:
        dl = jnp.maximum(doc_lengths[counts.doc].astype(counts.count.dtype), 1.0)
        return counts.count / dl
    if mode is TfMode.LOGNORM:
        return jnp.where(counts.count > 0, 1.0 + jnp.log(counts.count), 0.0) * counts.valid
    raise ValueError(f"unknown tf mode {mode}")


@functools.partial(
    jax.jit,
    static_argnames=("n_docs", "vocab", "tf_mode", "idf_mode", "l2_normalize"),
)
def tfidf_pipeline(
    doc_ids: jax.Array,
    term_ids: jax.Array,
    doc_lengths: jax.Array,
    *,
    n_docs: int,
    vocab: int,
    tf_mode: TfMode = TfMode.RAW,
    idf_mode: IdfMode = IdfMode.CLASSIC,
    l2_normalize: bool = False,
) -> TfidfResult:
    """The full batch pipeline as one XLA program: TF pass → DF pass → IDF
    vector → weight join (→ optional per-doc L2 norm, sklearn-style)."""
    counts = count_pairs(doc_ids, term_ids)
    df = document_frequency(counts, vocab)
    idf = idf_vector(df, float(n_docs), idf_mode)
    tf = tf_values(counts, doc_lengths, tf_mode)
    w = tf * idf[counts.term] * counts.valid
    if l2_normalize:
        sq = jax.ops.segment_sum(w * w, counts.doc, num_segments=n_docs)
        norm = jnp.sqrt(jnp.maximum(sq, 1e-30))
        w = w / norm[counts.doc]
    return TfidfResult(
        doc=counts.doc, term=counts.term, weight=w,
        n_pairs=counts.n_pairs, valid=counts.valid, df=df, idf=idf,
        count=counts.count,
    )


@functools.partial(
    jax.jit, static_argnames=("n_docs", "tf_mode", "l2_normalize"))
def finalize_weights(
    doc: jax.Array,  # int32 [nnz]
    count: jax.Array,  # f[nnz]
    doc_lengths: jax.Array,  # int32 [n_docs]
    idf_per_pair: jax.Array,  # f[nnz] — idf[term] pre-gathered on host
    *,
    n_docs: int,
    tf_mode: TfMode,
    l2_normalize: bool,
) -> jax.Array:
    """Device-side second pass of the streaming ingest (SURVEY.md §5.7):
    TF weighting + idf join + optional per-doc L2 norm over the accumulated
    COO.  One compile at the final nnz; the elementwise math and the two
    doc-segment reductions are where the numpy finalize spent its time at
    Wikipedia scale."""
    if tf_mode is TfMode.RAW:
        tf = count
    elif tf_mode is TfMode.FREQ:
        tf = count / jnp.maximum(doc_lengths[doc].astype(count.dtype), 1.0)
    elif tf_mode is TfMode.LOGNORM:
        tf = jnp.where(count > 0, 1.0 + jnp.log(jnp.maximum(count, 1.0)), 0.0)
    else:
        raise ValueError(f"unknown tf mode {tf_mode}")
    w = tf * idf_per_pair
    if l2_normalize:
        sq = jax.ops.segment_sum(w * w, doc, num_segments=n_docs)
        w = w / jnp.sqrt(jnp.maximum(sq, 1e-30))[doc]
    return w


@functools.partial(jax.jit, static_argnames=("vocab",))
def chunk_counts(
    doc_ids: jax.Array,
    term_ids: jax.Array,
    token_valid: jax.Array,
    *,
    vocab: int,
) -> tuple[SparseCounts, jax.Array]:
    """Streaming-ingest kernel: one fixed-shape chunk → (per-pair counts,
    per-term DF increment).  Compiles once for the chunk shape; every chunk
    reuses the executable (SURVEY.md §5.7)."""
    counts = count_pairs(doc_ids, term_ids, token_valid=token_valid)
    df = document_frequency(counts, vocab)
    return counts, df


@functools.partial(
    jax.jit, static_argnames=("vocab",), donate_argnums=(3,))
def chunk_counts_carry(
    doc_ids: jax.Array,
    term_ids: jax.Array,
    token_valid: jax.Array,
    df_carry: jax.Array,
    *,
    vocab: int,
) -> tuple[SparseCounts, jax.Array]:
    """The production streaming-ingest kernel: one fixed-shape chunk →
    (per-pair counts, **updated device-resident DF accumulator**).

    Unlike :func:`chunk_counts` (which returns a per-chunk DF *increment*
    for the host to add up), the DF vector lives on device across the whole
    stream and ``df_carry`` is **donated**: XLA writes the accumulated DF
    back into the same buffer every chunk instead of allocating a fresh
    vocab-sized vector, and the host never pulls DF per chunk — only at
    checkpoint commit points and finalize (models/tfidf.py).  At vocab 2^18
    that removes a ~1 MB device→host transfer per chunk from the streaming
    hot loop.  The tier-3 donation verifier (analysis/cost.py) holds the
    donation against the lowered computation's input/output aliasing.
    """
    counts = count_pairs(doc_ids, term_ids, token_valid=token_valid)
    df = document_frequency(counts, vocab)
    return counts, df_carry + df


@functools.partial(jax.jit, static_argnames=("n_docs", "k"))
def score_query(
    result: TfidfResult,
    query_weights: jax.Array,  # f[vocab] — query's weight per term
    *,
    n_docs: int,
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """A11 top-k query scoring: score(doc) = Σ_t w[doc,t]·q[t], then top-k.
    The sparse dot rides the same segment_sum machinery as everything else."""
    per_pair = result.weight * query_weights[result.term] * result.valid
    scores = jax.ops.segment_sum(per_pair, result.doc, num_segments=n_docs)
    return jax.lax.top_k(scores, k)


@functools.partial(
    jax.jit, static_argnames=("n_docs", "vocab", "k", "use_prior"))
def score_query_batch(
    doc: jax.Array,  # int32 [nnz] postings (device-resident across calls)
    term: jax.Array,  # int32 [nnz]
    weight: jax.Array,  # f[nnz]
    valid: jax.Array,  # f[nnz]
    q_term: jax.Array,  # int32 [B, Q] hashed query term ids (padded)
    q_weight: jax.Array,  # f[B, Q] per-term query weights
    q_valid: jax.Array,  # f[B, Q] 1.0 for real query slots
    doc_prior: jax.Array,  # f[n_docs] additive prior (e.g. scaled PageRank)
    *,
    n_docs: int,
    vocab: int,
    k: int,
    use_prior: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """The warm serving path's batched A11 scorer (ISSUE 8): one compiled
    program scores a padded micro-batch of sparse queries against the
    device-resident postings and returns per-query top-k — the full
    ``[B, n_docs]`` score matrix never crosses device→host.

    Queries arrive *sparse* ([B, Q] term ids + weights, Q fixed) so the
    per-request H2D transfer is bytes, not a vocab-sized vector; the dense
    per-query lookup table is scattered on device.  Padding slots carry
    ``q_valid`` 0 and term id 0, scattering nothing.  Per query the math is
    exactly :func:`score_query`'s (same multiply order, same segment_sum),
    so a served result is bit-equal to the one-shot path — pinned by
    tests/test_serving.py.  ``use_prior`` (static) fuses an additive
    per-document prior — the PageRank ranks riding in the serving artifact
    — into the score before top-k.
    """
    b = q_term.shape[0]
    qdense = jnp.zeros((b, vocab), weight.dtype)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    qdense = qdense.at[rows, q_term].add(q_weight * q_valid)

    def one(qrow):
        per_pair = weight * qrow[term] * valid
        scores = jax.ops.segment_sum(per_pair, doc, num_segments=n_docs)
        if use_prior:
            scores = scores + doc_prior
        return scores

    scores = jax.vmap(one)(qdense)
    return jax.lax.top_k(scores, k)


@functools.partial(
    jax.jit,
    static_argnames=("n_docs", "batch", "bucket_width", "k", "use_prior"))
def score_impacted_batch(
    doc,  # int32 [nnz] CSC-by-term postings: doc ids, term-major order
    weight,  # f[nnz] ranker weight table over the SAME rows
    bucket_start,  # int32 [C] postings offset of each bucket's first row
    bucket_len,  # int32 [C] live rows in the bucket (0 for pad buckets)
    bucket_row,  # int32 [C] padded query row the bucket scores into
    bucket_qw,  # f[C] query weight of the bucket's term (0 for pads)
    doc_prior,  # f[n_docs] additive prior (e.g. scaled PageRank)
    *,
    n_docs: int,
    batch: int,
    bucket_width: int,
    k: int,
    use_prior: bool = False,
):
    """The latency-shaped serving scorer (ISSUE 13): score a padded query
    micro-batch against ONLY the batch's query terms' posting runs.

    :func:`score_query_batch` is throughput-shaped — every dispatch pays a
    ``[B, vocab]`` scatter plus a ``[B, nnz]`` gather over the WHOLE
    postings table, so p50 grows with corpus nnz whatever the query asks.
    Here the host (serving/server.py) slices each query term's posting run
    out of the CSC-by-term layout (``term_offsets`` in the index artifact)
    and pads the runs into fixed-width buckets — ``sort_shuffle``'s
    fixed-bucket trick applied to postings — so the device program is pure
    reshape → gather → scatter-add over ``C·W`` postings rows, where
    ``C·W ≈ Σ df(query terms)``, independent of corpus nnz.

    Byte-equality with the full-COO path is load-bearing (the serving A/B
    is pinned, not hoped): per (row, doc) the contributions arrive in the
    same order the COO path adds them — query terms ascending (the host
    planner walks the canonical term-sorted query), docs ascending within
    a run (the artifact is (term, doc)-sorted) — and every pad slot
    contributes an exact ``±0.0``, which IEEE addition absorbs.  The same
    multiply association ``(weight · q) · mask`` is kept so rounding is
    identical.

    Pad buckets carry ``len 0, row 0, qw 0``; dead lanes of a partial
    bucket are masked the same way.  ``batch``/``bucket_width`` are static
    (the compile signature is one (batch cap, bucket cap) point of the
    serving shape matrix); the outputs are per-query top-k over the
    LOCAL doc-id space — the segment merge (:func:`topk_merge`)
    globalizes ids.
    """
    lane = jnp.arange(bucket_width, dtype=jnp.int32)[None, :]  # [1, W]
    idx = bucket_start[:, None] + lane  # [C, W]
    live = lane < bucket_len[:, None]  # bool [C, W]
    safe = jnp.where(live, idx, 0)
    mask = live.astype(weight.dtype)
    contrib = weight[safe] * bucket_qw[:, None] * mask
    rows = jnp.broadcast_to(bucket_row[:, None], safe.shape)
    cols = jnp.where(live, doc[safe], 0)
    scores = jnp.zeros((batch, n_docs), weight.dtype).at[rows, cols].add(
        contrib
    )
    if use_prior:
        scores = scores + doc_prior
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def topk_merge(seg_scores, seg_ids, seg_bases, *, k: int):
    """Device-side merge of per-segment top-k candidates (ISSUE 13):
    ``seg_scores``/``seg_ids`` are tuples of per-segment ``[B, k_i]``
    arrays (local doc ids), ``seg_bases`` the per-segment global doc-id
    bases.  Candidates are globalized and re-ranked in ONE fused program,
    so only ``[B, k]`` ever crosses device→host however many live
    segments a query fans out over.  Ties keep the earlier (older,
    lower-base) segment — ``lax.top_k`` is stable in input position."""
    scores = jnp.concatenate(list(seg_scores), axis=1)
    ids = jnp.concatenate(
        [i + jnp.asarray(b, i.dtype) for i, b in zip(seg_ids, seg_bases)],
        axis=1,
    )
    top, pos = jax.lax.top_k(scores, k)
    return top, jnp.take_along_axis(ids, pos, axis=1)
