"""Pallas TPU kernels for the PageRank SpMV hot loop.

The per-iteration contraction ``contribs[v] = Σ_{(u,v)∈E} w[u]`` (the
reference's ``flatMap(computeContribs).reduceByKey(add)`` chain,
SURVEY.md §3.1) is a gather + segmented reduction over dst-sorted edges.
``spmv_pallas`` fuses the two memory-bound passes XLA emits for the cumsum
formulation (gather → HBM → cumsum) into one kernel: the rank table stays
resident in VMEM (~3.4 MB at web-Google scale, well under the v5e budget),
edge-source indices stream through in chunks, and each chunk is gathered
and prefix-summed on-chip with a scalar carry across the sequential grid.
The host-side wrapper then takes the O(N) monotone difference at the CSR
row pointers, exactly like ``ops.pagerank.spmv_cumsum``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Edges streamed per grid step. 64K edges = 256 KB of int32 indices plus a
# 256 KB f32 value block in VMEM — small next to the resident rank table.
_CHUNK = 64 * 1024
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _gather_cumsum_kernel(src_ref, w_ref, out_ref, carry_ref):
    """One edge chunk: gather w[src], inclusive prefix sum + running carry."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        carry_ref[0, 0] = jnp.zeros((), carry_ref.dtype)

    rows = _CHUNK // _LANES
    vals = jnp.take(w_ref[:], src_ref[:].reshape(-1), axis=0)
    vals = vals.reshape(rows, _LANES)
    # 2-D prefix sum in row-major edge order: lane-wise cumsum, then add the
    # exclusive cumsum of the row totals.
    lane_cum = jnp.cumsum(vals, axis=1)
    row_tot = lane_cum[:, -1:]
    row_base = jnp.cumsum(row_tot, axis=0) - row_tot
    carry = carry_ref[0, 0]
    out_ref[:] = (lane_cum + row_base + carry).reshape(1, _CHUNK)
    carry_ref[0, 0] = carry + jnp.sum(row_tot)


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def spmv_pallas(
    src: jax.Array,
    indptr: jax.Array,
    w: jax.Array,
    *,
    n: int,
    interpret: bool = False,
) -> jax.Array:
    """``contribs[v] = Σ_{e: dst-sorted, dst[e]=v} w[src[e]]``.

    Args:
      src: int32 [E] edge sources in dst-sorted order.
      indptr: int32 [N+1] CSR row pointers into the dst-sorted edge list.
      w: f32 [N] per-node values (already divided by out-degree).
      n: number of nodes (static).
    """
    e = src.shape[0]
    if e == 0:
        return jnp.zeros(n, w.dtype)
    dtype = w.dtype
    e_pad = _round_up(e, _CHUNK)
    # Pad w by ≥1 slot of zeros and point padded edges at it: they then add
    # nothing to the prefix sum past position E.
    n_pad = _round_up(n + 1, _LANES * 8)
    w_pad = jnp.zeros(n_pad, dtype).at[:n].set(w)
    src_pad = jnp.full(e_pad, n, jnp.int32).at[:e].set(src.astype(jnp.int32))

    grid = e_pad // _CHUNK
    c1 = pl.pallas_call(
        _gather_cumsum_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),  # whole w table resident
        ],
        out_specs=pl.BlockSpec((1, _CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, e_pad), dtype),
        scratch_shapes=[pltpu.SMEM((1, 1), dtype)],
        interpret=interpret,
    )(src_pad.reshape(1, e_pad), w_pad)

    c = jnp.concatenate([jnp.zeros(1, dtype), c1.reshape(e_pad)[:e]])
    return c[indptr[1:]] - c[indptr[:-1]]
