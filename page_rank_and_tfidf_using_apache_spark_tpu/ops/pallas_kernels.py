"""Pallas TPU kernels for the PageRank SpMV hot loop.

The per-iteration contraction ``contribs[v] = Σ_{(u,v)∈E} w[u]`` (the
reference's ``flatMap(computeContribs).reduceByKey(add)`` chain,
SURVEY.md §3.1) is a gather + segmented reduction over dst-sorted edges.
``spmv_pallas`` fuses the two memory-bound passes XLA emits for the cumsum
formulation (gather → HBM → cumsum) into one kernel: the rank table stays
resident in VMEM (~3.4 MB at web-Google scale, well under the v5e budget),
edge-source indices stream through in chunks, and each chunk is gathered
and prefix-summed on-chip with a scalar carry across the sequential grid.
The host-side wrapper then takes the O(N) monotone difference at the CSR
row pointers, exactly like ``ops.pagerank.spmv_cumsum``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Edges streamed per grid step. 64K edges = 256 KB of int32 indices plus a
# 256 KB f32 value block in VMEM — small next to the resident rank table.
_CHUNK = 64 * 1024
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _gather_cumsum_kernel(src_ref, w_ref, out_ref, carry_ref):
    """One edge chunk: gather w[src], inclusive prefix sum + running carry."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        carry_ref[0, 0] = jnp.zeros((), carry_ref.dtype)

    rows = _CHUNK // _LANES
    vals = jnp.take(w_ref[:], src_ref[:].reshape(-1), axis=0)
    vals = vals.reshape(rows, _LANES)
    # 2-D prefix sum in row-major edge order: lane-wise cumsum, then add the
    # exclusive cumsum of the row totals.
    lane_cum = jnp.cumsum(vals, axis=1)
    row_tot = lane_cum[:, -1:]
    row_base = jnp.cumsum(row_tot, axis=0) - row_tot
    carry = carry_ref[0, 0]
    out_ref[:] = (lane_cum + row_base + carry).reshape(1, _CHUNK)
    carry_ref[0, 0] = carry + jnp.sum(row_tot)


def _gather_cumsum(src, w, n, e, interpret):
    """Inclusive prefix sum over ``w[src]`` (padded to a chunk multiple)."""
    dtype = w.dtype
    e_pad = _round_up(e, _CHUNK)
    # Pad w by ≥1 slot of zeros and point padded edges at it: they then add
    # nothing to the prefix sum past position E.
    n_pad = _round_up(n + 1, _LANES * 8)
    w_pad = jnp.zeros(n_pad, dtype).at[:n].set(w)
    src_pad = jnp.full(e_pad, n, jnp.int32).at[:e].set(src.astype(jnp.int32))

    grid = e_pad // _CHUNK
    c1 = pl.pallas_call(
        _gather_cumsum_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),  # whole w table resident
        ],
        out_specs=pl.BlockSpec((1, _CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, e_pad), dtype),
        scratch_shapes=[pltpu.SMEM((1, 1), dtype)],
        interpret=interpret,
    )(src_pad.reshape(1, e_pad), w_pad)
    return c1.reshape(e_pad)


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def spmv_pallas(
    src: jax.Array,
    indptr: jax.Array,
    w: jax.Array,
    *,
    n: int,
    interpret: bool = False,
) -> jax.Array:
    """``contribs[v] = Σ_{e: dst-sorted, dst[e]=v} w[src[e]]``.

    Args:
      src: int32 [E] edge sources in dst-sorted order.
      indptr: int32 [N+1] CSR row pointers into the dst-sorted edge list.
      w: f32 [N] per-node values (already divided by out-degree).
      n: number of nodes (static).
    """
    e = src.shape[0]
    if e == 0:
        return jnp.zeros(n, w.dtype)
    dtype = w.dtype
    c1 = _gather_cumsum(src, w, n, e, interpret)
    c = jnp.concatenate([jnp.zeros(1, dtype), c1[:e]])
    return c[indptr[1:]] - c[indptr[:-1]]


# ---------------------------------------------------------------------------
# Full-Pallas variant: the CSR-row difference also runs on-chip.
# ---------------------------------------------------------------------------

# Nodes per diff-kernel grid step.
_NODE_CHUNK = 8 * 1024


def _window_diff_kernel(starts_ref, lo_ref, hi_ref, c_hbm, out_ref, scratch, sem):
    """One node chunk: DMA the contiguous cumsum window this chunk's CSR
    rows span, then take per-row differences with chunk-local indices."""
    i = pl.program_id(0)
    start = starts_ref[i]
    cap = scratch.shape[-1]
    dma = pltpu.make_async_copy(
        c_hbm.at[0, pl.ds(start, cap)], scratch.at[0], sem
    )
    dma.start()
    dma.wait()
    lo = lo_ref[:] - start
    hi = hi_ref[:] - start
    win = scratch[0]
    out_ref[:] = (
        jnp.take(win, hi.reshape(-1), axis=0) - jnp.take(win, lo.reshape(-1), axis=0)
    ).reshape(out_ref.shape)


@functools.partial(jax.jit, static_argnames=("n", "cap", "interpret"))
def _window_diff(c, lo, hi, starts, *, n, cap, interpret):
    n_pad = lo.shape[0]
    grid = n_pad // _NODE_CHUNK
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, _NODE_CHUNK), lambda i, s: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _NODE_CHUNK), lambda i, s: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # cumsum stays in HBM
        ],
        out_specs=pl.BlockSpec(
            (1, _NODE_CHUNK), lambda i, s: (0, i), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((1, cap), c.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        _window_diff_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, n_pad), c.dtype),
        interpret=interpret,
    )(starts, lo.reshape(1, n_pad), hi.reshape(1, n_pad), c.reshape(1, -1))
    return out.reshape(n_pad)[:n]


def spmv_pallas_full(
    src: jax.Array,
    indptr: jax.Array,
    w: jax.Array,
    *,
    n: int,
    window_starts: jax.Array,
    window_cap: int,
    interpret: bool = False,
) -> jax.Array:
    """Like :func:`spmv_pallas` but the CSR-row difference is a second Pallas
    kernel (per-node-chunk windowed DMA + on-chip take) instead of two XLA
    gathers.  Needs host-precomputed window metadata from
    :func:`diff_window_meta` (static per graph)."""
    e = src.shape[0]
    if e == 0:
        return jnp.zeros(n, w.dtype)
    c1 = _gather_cumsum(src, w, n, e, interpret)
    # exclusive prefix c[j] = sum of first j per-edge values, padded so every
    # window [start, start+cap) is in bounds
    e_pad1 = _round_up(e + 1 + window_cap, _LANES)
    c = jnp.zeros(e_pad1, w.dtype).at[1 : e + 1].set(c1[:e])
    c = jnp.where(  # positions past e hold the total (diffs there are 0)
        jnp.arange(e_pad1) > e, c1[e - 1] if e > 0 else 0.0, c
    )
    n_pad = _round_up(n, _NODE_CHUNK)
    lo = jnp.full(n_pad, e, jnp.int32).at[:n].set(indptr[:-1].astype(jnp.int32))
    hi = jnp.full(n_pad, e, jnp.int32).at[:n].set(indptr[1:].astype(jnp.int32))
    return _window_diff(c, lo, hi, window_starts, n=n, cap=window_cap,
                        interpret=interpret)


def diff_window_meta(indptr: np.ndarray, n_edges: int) -> tuple[np.ndarray, int]:
    """Per-node-chunk cumsum-window starts and the uniform window size.

    Chunk i's CSR rows reference cumsum positions
    ``[indptr[i*NC], indptr[min((i+1)*NC, n)]]`` — contiguous because the
    edge array is dst-sorted.  Returns (starts int32 [grid], cap) with cap
    the max span rounded up to lanes (the VMEM scratch size; caller should
    fall back to the XLA diff when cap is too large for VMEM).
    """
    n = indptr.shape[0] - 1
    n_pad = _round_up(n, _NODE_CHUNK)
    grid = n_pad // _NODE_CHUNK
    bounds = np.minimum(np.arange(grid + 1) * _NODE_CHUNK, n)
    lo = indptr[bounds[:-1]]
    hi = indptr[bounds[1:]]
    span = int((hi + 1 - lo).max()) if grid > 0 else 1
    cap = _round_up(max(span, _LANES), _LANES)
    return lo.astype(np.int32), cap
