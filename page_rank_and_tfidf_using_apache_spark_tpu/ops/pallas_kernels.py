"""Pallas TPU kernels for the PageRank SpMV hot loop.

The per-iteration contraction ``contribs[v] = Σ_{(u,v)∈E} w[u]`` (the
reference's ``flatMap(computeContribs).reduceByKey(add)`` chain,
SURVEY.md §3.1) is, over dst-sorted edges, a gather + prefix sum + CSR-row
difference.  The gather and the monotone row-pointer difference stay in XLA
(Mosaic's vector gather only supports same-shape lane gathers, so a global
table gather cannot beat XLA's own lowering on-chip).  What Pallas *can*
win is the prefix sum: XLA lowers a multi-million-element 1-D cumsum as
O(log E) shifted-add passes — each a full HBM sweep — while a sequential
grid with a scalar carry does it in exactly one read and one write of the
edge array.  ``cumsum_pallas`` is that kernel; ``spmv_pallas`` composes it
with the XLA gather/diff into the ``spmv_impl='pallas'`` variant raced by
bench.py.  ``rowsum_pallas`` is the hybrid impl's dense-head reduction
(``ops/pagerank.py spmv_hybrid``): the gathered ``[R, W]`` per-edge weight
matrix of the top-in-degree nodes streamed through VMEM in one HBM pass,
each block reduced by a single MXU matvec.

Lowering is validated without a chip via ``jax.export`` cross-platform
lowering (tests/test_tpu_lowering.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Elements per grid step. 256K f32 = 1 MB in / 1 MB out per step in VMEM.
_CHUNK = 256 * 1024
_LANES = 128


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _scan_axis(x, axis):
    """Inclusive Hillis–Steele prefix sum along ``axis`` of a 2-D block,
    built from Mosaic-supported primitives only (roll + iota mask + add;
    ``jnp.cumsum`` has no Pallas TPU lowering)."""
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, axis)
    size = x.shape[axis]
    shift = 1
    while shift < size:
        rolled = pltpu.roll(x, shift=np.int32(shift), axis=axis)
        x = x + jnp.where(idx >= shift, rolled, jnp.zeros((), x.dtype))
        shift *= 2
    return x


def _cumsum_carry_kernel(x_ref, out_ref, carry_ref):
    """One chunk of a running prefix sum: 2-D local scan + scalar carry."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        carry_ref[0, 0] = jnp.zeros((), carry_ref.dtype)

    rows = _CHUNK // _LANES
    vals = x_ref[:].reshape(rows, _LANES)
    # Row-major 2-D prefix sum: lane-wise scan, then add the exclusive
    # scan of the row totals (computed lane-broadcast so both scans use the
    # same (rows, 128) layout).
    lane_cum = _scan_axis(vals, 1)
    row_tot = jnp.broadcast_to(lane_cum[:, _LANES - 1 :], vals.shape)
    row_cum = _scan_axis(row_tot, 0)
    carry = carry_ref[0, 0]
    out_ref[:] = (lane_cum + (row_cum - row_tot) + carry).reshape(1, _CHUNK)
    carry_ref[0, 0] = carry + row_cum[rows - 1, _LANES - 1]


@functools.partial(jax.jit, static_argnames=("interpret",))
def cumsum_pallas(x: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Inclusive 1-D prefix sum in one HBM read + one write.

    The grid is sequential on TPU, so a scalar SMEM carry threads the
    running total across chunks.
    """
    (e,) = x.shape
    if e == 0:
        return x
    dtype = x.dtype
    e_pad = _round_up(e, _CHUNK)
    x_pad = jnp.zeros(e_pad, dtype).at[:e].set(x)

    out = pl.pallas_call(
        _cumsum_carry_kernel,
        grid=(e_pad // _CHUNK,),
        in_specs=[pl.BlockSpec((1, _CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, _CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, e_pad), dtype),
        scratch_shapes=[pltpu.SMEM((1, 1), dtype)],
        interpret=interpret,
    )(x_pad.reshape(1, e_pad))
    return out.reshape(e_pad)[:e]


# Rows per grid step of the dense-head row reduction.  1024 x 128 f32 is
# 512 KB of VMEM in, 4 KB out per step.
_ROW_BLOCK = 1024


def _rowsum_kernel(x_ref, o_ref):
    """One block of dense-head rows: a single MXU matvec against a ones
    vector reduces the lane dimension ([RB, W] @ [W, 1] -> [RB])."""
    ones = jnp.ones((x_ref.shape[1], 1), x_ref.dtype)
    o_ref[:] = jax.lax.dot(
        x_ref[:], ones, precision=jax.lax.Precision.HIGHEST
    ).reshape(1, -1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def rowsum_pallas(mat: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Row sums of the hybrid SpMV's dense head matrix in ONE HBM read.

    The gathered ``[R, W]`` per-edge weight matrix streams through VMEM
    block by block; each block's reduction is one systolic-array matvec —
    the contraction shape RankMap's platform-aware blocking prescribes for
    mapping a dense decomposition onto the MXU."""
    r, w = mat.shape
    if r == 0:
        return jnp.zeros((0,), mat.dtype)
    rb = min(_ROW_BLOCK, _round_up(r, 8))
    r_pad = _round_up(r, rb)
    mat_pad = jnp.zeros((r_pad, w), mat.dtype).at[:r].set(mat)
    out = pl.pallas_call(
        _rowsum_kernel,
        grid=(r_pad // rb,),
        in_specs=[
            pl.BlockSpec((rb, w), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((1, rb), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, r_pad), mat.dtype),
        interpret=interpret,
    )(mat_pad)
    return out.reshape(r_pad)[:r]


def spmv_pallas(
    src: jax.Array,
    indptr: jax.Array,
    w: jax.Array,
    *,
    n: int,
    edge_weight: jax.Array | None = None,
    interpret: bool = False,
) -> jax.Array:
    """``contribs[v] = Σ_{e: dst-sorted, dst[e]=v} w[src[e]]`` with the
    prefix sum fused into :func:`cumsum_pallas` (gather and CSR-row
    difference in XLA).

    Args:
      src: int32 [E] edge sources in dst-sorted order.
      indptr: int32 [N+1] CSR row pointers into the dst-sorted edge list.
      w: f[N] per-node values (already divided by out-degree).
      n: number of nodes (static).
    """
    from page_rank_and_tfidf_using_apache_spark_tpu.ops.pagerank import (
        cumsum_diff_spmv,
    )

    e = src.shape[0]
    if e == 0:
        return jnp.zeros(n, w.dtype)
    per_edge = w[src]
    if edge_weight is not None:  # weighted PageRank: w(u,v)·rank[u]/s[u]
        per_edge = per_edge * edge_weight
    return cumsum_diff_spmv(
        per_edge, indptr, functools.partial(cumsum_pallas, interpret=interpret)
    )
