"""Owned-partition boundary machinery (ISSUE 15): the host planner and
device-side gather/scatter helpers under the ``owned`` sharded strategy.

The replicated-state wall: every pre-ISSUE-15 sharded PageRank either
replicates the full rank vector (``edges``/``hybrid`` — O(n) state per
chip, one O(n)-byte dense ``psum``) or gathers it per step (``nodes*`` —
O(n)-byte ``all_gather``).  Both stop fitting/paying at 10-100x web-Google
node counts (ROADMAP).  *Sparse Allreduce* (PAPERS.md) observes that on a
power-law graph the partition cut is dominated by a small hub set: peel
the hubs into a tiny replicated mini-state and the remaining cut-crossing
("boundary") entries are a sublinear fraction of n — so exchanging ONLY
those, over fixed-width padded buffers, makes per-step comm bytes
sublinear in node count.  DrJAX motivates expressing that exchange as
native JAX collectives (the ``ppermute`` butterfly in
``parallel.collectives.butterfly_all_gather``) rather than host-side
shuffles.

Layout (one :class:`OwnedPlan`, materialized as one :class:`OwnedShard`):

- **head** — top-k nodes by combined (in+out) degree covering
  ``coverage`` of all edge endpoints, capped at ``max_head``.  Hubs are
  touched by almost every shard, so their rank state is REPLICATED
  ([H_pad] mini-vector) and their in-edge contributions are combined by
  ONE small dense ``psum`` — cheaper than exchanging them.  Head in-edges
  are dealt across devices at edge granularity, which also removes the
  node-granularity load floor ``nodes_balanced`` hits on hubs.
- **tail** — every other node, partitioned into d contiguous owned
  blocks at equal tail-in-edge splits (min-max optimal, node count per
  device capped at 2x the even block).  Each shard holds ONLY its
  [block] rank slice; a tail node's in-edges live with its owner.
- **boundary** — per owner j, the sorted set S_j of tail nodes owned by
  j that some OTHER shard reads as an edge source.  Each step, every
  shard packs its outgoing boundary values into a fixed-width [B_pad]
  buffer and a log2(d)-round ``ppermute`` butterfly all-gathers the d
  buffers; a host-precomputed per-edge index then gathers every edge's
  source value from the concatenation ``[local slice | boundary table |
  replicated head | 0]`` — shapes static across iterations, bytes per
  step = (d-1)*B_pad + O(H_pad), both sublinear in n on power-law graphs.

Everything here is host-side numpy except the two trivial jit-side
helpers at the bottom; the compiled step lives in
``parallel/pagerank_sharded.py`` (and ``parallel/workloads_sharded.py``
for the owned HITS/CC variants).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph


def _pow2_ceil(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length() if x > 1 else 1


def comm_entries_per_step(d: int, b_pad: int, h_pad: int) -> int:
    """Array entries each device sends per owned iteration: the butterfly
    ((d-1)·B_pad — round k carries 2^k·B_pad) plus the ring-allreduce
    cost of the one [H_pad+2] head psum (~2 passes).  THE one formula —
    the plan event (:meth:`OwnedPlan.comm_entries_per_step`) and the
    materialized gauge (`_ShardedExec`) both read it, so they cannot
    drift."""
    if d <= 1:
        return 0
    return int((d - 1) * b_pad + 2 * (h_pad + 2) * (d - 1) // d)


# A head member must concentrate at least this many edge endpoints —
# below it, replicating the node costs more state than its boundary
# entries would cost exchange (same role as plan_hybrid_head's row-width
# floor).
OWNED_HEAD_MIN_DEGREE = 8


def plan_owned_head(
    in_degree: np.ndarray,
    out_degree: np.ndarray,
    n_edges: int,
    *,
    coverage: float = 0.5,
    max_head: int = 4096,
) -> np.ndarray:
    """Head-membership policy of the ``owned`` strategy: the smallest
    top-k set by COMBINED (in + out) degree covering ``coverage`` of all
    2E edge endpoints, every member with combined degree >=
    ``OWNED_HEAD_MIN_DEGREE``, capped at ``max_head`` (the replicated
    mini-state and the per-step psum are O(head)).  Both degree axes
    matter: high IN-degree hubs receive from every shard (their combine
    is the psum), high OUT-degree hubs are read by every shard (their
    replication empties the boundary sets).  Returns ASCENDING node ids —
    head slot order is id order, which keeps every per-device head edge
    chunk sorted for the segment reduction."""
    if n_edges == 0 or in_degree.size == 0 or max_head <= 0:
        return np.zeros(0, np.int64)
    combined = in_degree.astype(np.int64) + out_degree.astype(np.int64)
    order = np.argsort(-combined, kind="stable")
    deg_sorted = combined[order]
    k_deg = int(np.searchsorted(-deg_sorted, -OWNED_HEAD_MIN_DEGREE,
                                side="right"))
    if k_deg == 0:
        return np.zeros(0, np.int64)
    cum = np.cumsum(deg_sorted[:k_deg], dtype=np.int64)
    k_cov = int(np.searchsorted(cum, coverage * 2 * n_edges, side="left")) + 1
    k = min(k_deg, k_cov, max_head)
    return np.sort(order[:k].astype(np.int64))


class OwnedPlan(NamedTuple):
    """Pure planning output of the ``owned`` strategy: boundaries, padded
    widths, boundary-set sizes and the padding/comm accounting — no
    per-device array materialized (``build_owned_shard`` materializes
    exactly this plan; the tier-3 pad gauge budgets these numbers)."""

    n: int
    d: int
    head_ids: np.ndarray  # int64 [H] ascending global ids (replicated)
    bounds: np.ndarray  # int64 [d+1] tail-RANK block boundaries
    block: int  # tail nodes per device (padded)
    n_pad: int  # d * block
    h: int  # real head size
    h_pad: int  # pow2 padded head width
    e_dev: int  # tail edge slots per device
    he_dev: int  # head edge slots per device
    b_pad: int  # boundary buffer width (pow2 over max |S_j|)
    boundary_counts: np.ndarray  # int64 [d] real |S_j|
    boundary_keys: np.ndarray  # int64 [Σ|S_j|] sorted owner*n+src keys
    # (a plan artifact build_owned_shard reuses — O(cut), not O(E))
    pad_frac: float  # padded edge-slot fraction (same gauge as others)
    boundary_pad_frac: float  # padded fraction of the d*b_pad exchange

    def comm_entries_per_step(self) -> int:
        """Array entries each device sends per iteration — see the
        module-level :func:`comm_entries_per_step`."""
        return comm_entries_per_step(self.d, self.b_pad, self.h_pad)


def _minmax_tail_split(tail_ip: np.ndarray, nt: int, d: int) -> np.ndarray:
    """Optimal min-max contiguous split of the tail nodes at equal
    tail-in-edge widths (binary search + greedy max-fill — the
    ``nodes_balanced`` planner's algorithm over tail-rank space), node
    count per device capped at 2x the even block."""
    bounds = np.zeros(d + 1, np.int64)
    if nt == 0:
        return bounds
    cap = 2 * max(1, math.ceil(nt / d))
    e_tail = int(tail_ip[-1])

    def fill(width: int) -> np.ndarray | None:
        b = 0
        out = np.zeros(d + 1, np.int64)
        for i in range(d):
            hi = int(np.searchsorted(
                tail_ip, tail_ip[b] + width, side="right")) - 1
            hi = min(max(hi, b), b + cap, nt)
            out[i + 1] = hi
            b = hi
        return out if b >= nt else None

    lo_w = max(1, math.ceil(e_tail / d))
    hi_w = max(e_tail, 1)
    bounds = fill(hi_w)
    assert bounds is not None  # d * cap >= 2 * nt always covers nt
    while lo_w < hi_w:
        mid = (lo_w + hi_w) // 2
        bm = fill(mid)
        if bm is None:
            lo_w = mid + 1
        else:
            hi_w, bounds = mid, bm
    return bounds


def plan_owned(
    graph: Graph,
    n_devices: int,
    *,
    coverage: float = 0.5,
    max_head: int = 4096,
    head_ids: np.ndarray | None = None,
    bounds: np.ndarray | None = None,
) -> OwnedPlan:
    """Plan the owned partition: head set, tail block boundaries, padded
    widths, and the per-owner boundary sets (cut-crossing sources).  One
    O(E) vectorized host pass; no per-device arrays.

    ``head_ids``/``bounds`` override the head policy / the min-max split
    with a FIXED node partition: a workload that pulls along both edge
    directions (owned HITS/CC in parallel/workloads_sharded.py) plans its
    reverse-direction exchange over the transposed graph under the SAME
    ownership, so both directions read one consistent rank slice."""
    d = n_devices
    if d < 1 or d & (d - 1):
        # the boundary butterfly is recursive doubling: partners are
        # i XOR 2^k, which only pairs up on power-of-two meshes (the same
        # shapes the elastic shrink chain rebuilds at) — reject early
        # instead of failing deep inside shard_map tracing
        raise ValueError(
            f"the owned strategy needs a power-of-two device count, got {d}"
        )
    n = graph.n_nodes
    e = graph.n_edges
    ip = graph.csr_indptr()
    indeg = np.diff(ip)

    if head_ids is None:
        head_ids = plan_owned_head(indeg, graph.out_degree, e,
                                   coverage=coverage, max_head=max_head)
    else:
        head_ids = np.sort(np.asarray(head_ids, np.int64))
    h = int(head_ids.size)
    h_pad = _pow2_ceil(max(h, 1))
    in_head = np.zeros(n, bool)
    in_head[head_ids] = True

    tail_ids = np.flatnonzero(~in_head)
    nt = int(tail_ids.size)
    tail_rank = np.full(n, -1, np.int64)
    tail_rank[tail_ids] = np.arange(nt, dtype=np.int64)

    mask_t = ~in_head[graph.dst]
    t_dst_rank = tail_rank[graph.dst[mask_t]]  # non-decreasing
    tail_ip = np.searchsorted(t_dst_rank, np.arange(nt + 1)).astype(np.int64)

    if bounds is None:
        bounds = _minmax_tail_split(tail_ip, nt, d)
    else:
        bounds = np.asarray(bounds, np.int64)
        assert bounds.shape == (d + 1,) and bounds[-1] == nt
    block = max(1, int(np.diff(bounds).max())) if nt else 1
    n_pad = d * block

    per_dev_tail = tail_ip[bounds[1:]] - tail_ip[bounds[:-1]]
    e_dev = max(1, int(per_dev_tail.max())) if nt else 1
    he = int(e - t_dst_rank.size)
    he_dev = max(1, math.ceil(he / d)) if he else 1

    # ---- boundary sets: remote (owner, src) pairs over BOTH edge classes
    def owner_of(rank: np.ndarray) -> np.ndarray:
        return np.searchsorted(bounds, rank, side="right") - 1

    te_src = graph.src[mask_t]
    reader_t = owner_of(t_dst_rank)
    he_src = graph.src[~mask_t]
    reader_h = np.arange(he, dtype=np.int64) // he_dev

    keys_parts = []
    for srcs, readers in ((te_src, reader_t), (he_src, reader_h)):
        is_tail_src = ~in_head[srcs]
        src_owner = owner_of(tail_rank[srcs])
        remote = is_tail_src & (src_owner != readers)
        keys_parts.append(src_owner[remote] * np.int64(n) + srcs[remote])
    boundary_keys = np.unique(np.concatenate(keys_parts)) if keys_parts else \
        np.zeros(0, np.int64)
    boundary_counts = np.bincount(
        (boundary_keys // n).astype(np.int64), minlength=d
    ).astype(np.int64)
    b_pad = _pow2_ceil(max(int(boundary_counts.max(initial=0)), 1))

    slots = d * (e_dev + he_dev)
    pad_frac = (slots - e) / max(slots, 1)
    boundary_pad_frac = (
        (d * b_pad - int(boundary_counts.sum())) / max(d * b_pad, 1)
    )
    return OwnedPlan(
        n=n, d=d, head_ids=head_ids, bounds=bounds, block=block,
        n_pad=n_pad, h=h, h_pad=h_pad, e_dev=e_dev, he_dev=he_dev,
        b_pad=b_pad, boundary_counts=boundary_counts,
        boundary_keys=boundary_keys, pad_frac=pad_frac,
        boundary_pad_frac=boundary_pad_frac,
    )


class OwnedShard(NamedTuple):
    """Materialized owned layout, ready for ``device_put``.  Every
    ``*_src_idx`` entry indexes the step's per-device LOOKUP vector
    ``[local slice (block) | boundary table (d*b_pad) | head (h_pad) |
    zero slot]`` — padding slots point at the zero slot and carry
    coefficient 0, so no mask survives into the step."""

    n: int
    d: int
    block: int
    n_pad: int
    h: int
    h_pad: int
    b_pad: int
    e_dev: int
    he_dev: int
    head_ids: np.ndarray  # int64 [H] ascending
    tail_map: np.ndarray  # int64 [n]: global id -> padded tail slot; -1 head
    tail_src_idx: np.ndarray  # int32 [d, e_dev] lookup indices
    tail_dst: np.ndarray  # int32 [d, e_dev] block-local dst, non-decreasing
    tail_w: np.ndarray  # f [d, e_dev] edge coefficient (weight / 1; 0 pad)
    head_src_idx: np.ndarray  # int32 [d, he_dev] lookup indices
    head_slot: np.ndarray  # int32 [d, he_dev] psum-buffer slot (pad: h_pad+1)
    head_w: np.ndarray  # f [d, he_dev]
    out_idx: np.ndarray  # int32 [d, b_pad] local tail slots to pack (0 pad)
    boundary_counts: np.ndarray  # int64 [d]
    inv_tail: np.ndarray  # f [n_pad] 1/out-strength in owned layout
    dang_tail: np.ndarray  # f [n_pad]
    inv_head: np.ndarray  # f [h_pad]
    dang_head: np.ndarray  # f [h_pad]

    @property
    def zero_slot(self) -> int:
        return self.block + self.d * self.b_pad + self.h_pad


def build_owned_shard(graph: Graph, plan: OwnedPlan, dtype: str) -> OwnedShard:
    """Materialize exactly ``plan``: per-device edge arrays with
    host-precomputed lookup indices, outgoing boundary pack indices, and
    the owned/replicated node-state vectors."""
    d, n, e = plan.d, plan.n, graph.n_edges
    block, b_pad, h_pad, he_dev, e_dev = (
        plan.block, plan.b_pad, plan.h_pad, plan.he_dev, plan.e_dev
    )
    bounds = plan.bounds
    head_ids = plan.head_ids
    zero_slot = block + d * b_pad + h_pad

    in_head = np.zeros(n, bool)
    in_head[head_ids] = True
    head_slot_of = np.full(n, -1, np.int64)
    head_slot_of[head_ids] = np.arange(plan.h, dtype=np.int64)

    tail_ids = np.flatnonzero(~in_head)
    nt = int(tail_ids.size)
    tail_rank = np.full(n, -1, np.int64)
    tail_rank[tail_ids] = np.arange(nt, dtype=np.int64)

    def owner_of(rank: np.ndarray) -> np.ndarray:
        return np.searchsorted(bounds, rank, side="right") - 1

    # global id -> padded tail slot (device o's nodes at [o*block, ...))
    rank_all = tail_rank[tail_ids]
    owner_all = owner_of(rank_all)
    tail_map = np.full(n, -1, np.int64)
    tail_map[tail_ids] = owner_all * block + (rank_all - bounds[owner_all])

    starts = np.concatenate([[0], np.cumsum(plan.boundary_counts)])

    def lookup_idx(srcs: np.ndarray, readers: np.ndarray) -> np.ndarray:
        """Per-edge index into the reader's lookup vector."""
        src_rank = tail_rank[srcs]
        src_owner = owner_of(src_rank)
        local = src_rank - bounds[np.clip(src_owner, 0, d - 1)]
        keys = src_owner * np.int64(n) + srcs
        pos = np.searchsorted(plan.boundary_keys, keys) - starts[
            np.clip(src_owner, 0, d - 1)
        ]
        remote_idx = block + src_owner * b_pad + pos
        idx = np.where(
            in_head[srcs],
            block + d * b_pad + head_slot_of[srcs],
            np.where(src_owner == readers, local, remote_idx),
        )
        return idx.astype(np.int64)

    weights = (graph.weight if graph.weight is not None
               else np.ones(e, np.float64))  # graftlint: disable=dtype-drift (host staging; cast into the dtype'd coefficient arrays below)

    # ---- tail edges: contiguous per-owner runs of the tail edge array
    mask_t = ~in_head[graph.dst]
    te_src = graph.src[mask_t]
    te_w = weights[mask_t]
    t_dst_rank = tail_rank[graph.dst[mask_t]]
    tail_ip = np.searchsorted(t_dst_rank, np.arange(nt + 1)).astype(np.int64)
    reader_t = owner_of(t_dst_rank)
    te_idx = lookup_idx(te_src, reader_t)

    tail_src_idx = np.full((d, e_dev), zero_slot, np.int32)
    tail_dst = np.full((d, e_dev), max(block - 1, 0), np.int32)
    tail_w = np.zeros((d, e_dev), dtype)
    for i in range(d):
        lo = int(tail_ip[bounds[i]]) if nt else 0
        hi = int(tail_ip[bounds[i + 1]]) if nt else 0
        k = hi - lo
        tail_src_idx[i, :k] = te_idx[lo:hi]
        tail_dst[i, :k] = (t_dst_rank[lo:hi] - bounds[i])
        tail_w[i, :k] = te_w[lo:hi]

    # ---- head edges: dealt in d contiguous chunks of the (slot-sorted)
    # head edge array; padding scatters +0.0 into the delta slot (h_pad+1),
    # keeping each device's slot sequence non-decreasing
    mask_h = ~mask_t
    he_src = graph.src[mask_h]
    he_w = weights[mask_h]
    he_slot = head_slot_of[graph.dst[mask_h]]
    he = int(he_src.size)
    reader_h = np.arange(he, dtype=np.int64) // he_dev
    he_idx = lookup_idx(he_src, reader_h) if he else np.zeros(0, np.int64)

    head_src_idx = np.full((d, he_dev), zero_slot, np.int32)
    head_slot = np.full((d, he_dev), h_pad + 1, np.int32)
    head_w = np.zeros((d, he_dev), dtype)
    for i in range(d):
        lo, hi = min(i * he_dev, he), min((i + 1) * he_dev, he)
        k = hi - lo
        head_src_idx[i, :k] = he_idx[lo:hi]
        head_slot[i, :k] = he_slot[lo:hi]
        head_w[i, :k] = he_w[lo:hi]

    # ---- outgoing boundary pack indices: owner j's S_j as local slots
    out_idx = np.zeros((d, b_pad), np.int32)
    for j in range(d):
        seg = plan.boundary_keys[starts[j]:starts[j + 1]]
        srcs = (seg - j * np.int64(n)).astype(np.int64)
        out_idx[j, : srcs.size] = (tail_rank[srcs] - bounds[j])

    # ---- node-state vectors (the shared float64-divide-then-cast
    # normalizer — parity with every other strategy's inv computation)
    inv_g = graph.inv_out_strength(np.float64)  # graftlint: disable=dtype-drift (host staging; scattered into the dtype'd vectors below)
    dang_g = (graph.out_degree == 0).astype(np.float64)  # graftlint: disable=dtype-drift (host staging; cast to the run dtype two lines down)

    inv_tail = np.zeros(plan.n_pad, dtype)
    dang_tail = np.zeros(plan.n_pad, dtype)
    inv_tail[tail_map[tail_ids]] = inv_g[tail_ids]
    dang_tail[tail_map[tail_ids]] = dang_g[tail_ids]
    inv_head = np.zeros(h_pad, dtype)
    dang_head = np.zeros(h_pad, dtype)
    inv_head[: plan.h] = inv_g[head_ids]
    dang_head[: plan.h] = dang_g[head_ids]

    return OwnedShard(
        n=n, d=d, block=block, n_pad=plan.n_pad, h=plan.h, h_pad=h_pad,
        b_pad=b_pad, e_dev=e_dev, he_dev=he_dev,
        head_ids=head_ids, tail_map=tail_map,
        tail_src_idx=tail_src_idx, tail_dst=tail_dst, tail_w=tail_w,
        head_src_idx=head_src_idx, head_slot=head_slot, head_w=head_w,
        out_idx=out_idx, boundary_counts=plan.boundary_counts,
        inv_tail=inv_tail, dang_tail=dang_tail,
        inv_head=inv_head, dang_head=dang_head,
    )


def split_global(shard, global_vec: np.ndarray,
                 dtype: str) -> tuple[np.ndarray, np.ndarray]:
    """Logical [n] host array -> (tail [n_pad], head [h_pad]) in the
    owned layout (padding zeros).  ``shard`` is duck-typed on the layout
    fields (n_pad/h/h_pad/tail_map/head_ids): an :class:`OwnedShard` or
    the dataflow layer's ``OwnedArray`` view."""
    tail = np.zeros(shard.n_pad, dtype)
    head = np.zeros(shard.h_pad, dtype)
    mask = shard.tail_map >= 0
    tail[shard.tail_map[mask]] = global_vec[mask]
    head[: shard.h] = global_vec[shard.head_ids]
    return tail, head


def merge_global(shard, tail: np.ndarray,
                 head: np.ndarray) -> np.ndarray:
    """(tail [n_pad], head [h_pad]) -> logical [n] host array (same
    duck-typed ``shard`` as :func:`split_global`)."""
    out = np.empty(shard.n, tail.dtype)
    mask = shard.tail_map >= 0
    out[mask] = tail[shard.tail_map[mask]]
    out[shard.head_ids] = head[: shard.h]
    return out


# ------------------------------------------------------- jit-side helpers


def pack_boundary(wt_local, out_idx):
    """Gather this shard's outgoing boundary values into its fixed-width
    exchange buffer: ``[block] -> [b_pad]`` (padding rows re-read slot 0;
    no receiver ever indexes them)."""
    return wt_local[out_idx]


def boundary_lookup(wt_local, btable, wh, fill=0):
    """The step's per-device source-value lookup vector:
    ``[local slice | exchanged boundary table | replicated head | fill]``
    — every host-precomputed ``*_src_idx`` indexes this concatenation.
    ``fill`` is the padding slot's value: 0 for additive combines, the
    dtype max for min-combines (owned connected components)."""
    import jax.numpy as jnp

    return jnp.concatenate(
        [wt_local, btable, wh, jnp.full(1, fill, wt_local.dtype)]
    )
