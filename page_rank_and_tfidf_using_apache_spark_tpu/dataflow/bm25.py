"""BM25 — the second ranker over the SAME postings arrays (ISSUE 9
workload 4; ROADMAP "BM25 scoring beside TF-IDF ... the serving layer
gets an A/B-able second ranker").

Okapi BM25 with the Lucene idf variant (non-negative for every df)::

    idf(t)    = ln(1 + (N - df + 0.5) / (df + 0.5))
    w(d, t)   = idf(t) * c * (k1 + 1) / (c + k1 * (1 - b + b * |d|/avgdl))

where ``c`` is the raw (doc, term) count the TF-IDF pipeline already
materializes (``TfidfOutput.count`` — no second corpus pass), ``|d|``
the document length and ``avgdl`` the corpus mean.  The weights land in
the SAME (term, doc)-sorted COO slots as the TF-IDF weights, so the
serving artifact stores them as one extra array and
``ops.score_query_batch`` serves either ranker from the same compiled
program — the weight table is a traced argument, so per-request ranker
selection costs zero recompiles (serving/server.py ``submit(...,
ranker="bm25")``).
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import Bm25Config


@functools.partial(jax.jit, static_argnames=("n_docs", "k1", "b"))
def bm25_weights(
    doc,  # int32 [nnz]
    term,  # int32 [nnz]
    count,  # f[nnz] raw per-pair counts
    doc_lengths,  # int32 [n_docs]
    df,  # f[vocab]
    *,
    n_docs: int,
    k1: float,
    b: float,
):
    """Per-(doc, term) BM25 weights over the postings COO: one gather of
    the per-doc length, one gather of the per-term df (the broadcast
    join), pure elementwise math — compiles once per nnz shape."""
    import jax.numpy as jnp

    dl = doc_lengths[doc].astype(count.dtype)
    avgdl = jnp.maximum(
        jnp.sum(doc_lengths.astype(count.dtype)) / n_docs, 1.0
    )
    n = jnp.asarray(float(n_docs), count.dtype)
    df_pair = df[term]
    idf = jnp.log1p((n - df_pair + 0.5) / (df_pair + 0.5))
    tf = count * (k1 + 1.0) / (count + k1 * (1.0 - b + b * dl / avgdl))
    return idf * tf


def bm25_from_tfidf(output, cfg: Bm25Config = Bm25Config()) -> np.ndarray:
    """BM25 weight array aligned with a :class:`~..models.tfidf
    .TfidfOutput`'s postings rows.  Needs the raw counts/doc lengths the
    pipeline now exports; an output predating that field fails loudly
    rather than inverting finalized weights (lossy where idf is 0)."""
    if output.count is None or output.doc_lengths is None:
        raise ValueError(
            "TfidfOutput carries no raw counts/doc lengths — rebuild the "
            "index with this version (BM25 re-weights counts, not tf-idf "
            "weights)"
        )
    import jax.numpy as jnp

    from page_rank_and_tfidf_using_apache_spark_tpu.resilience import (
        executor as rx,
    )

    w = bm25_weights(
        jnp.asarray(output.doc), jnp.asarray(output.term),
        jnp.asarray(output.count.astype(output.weight.dtype)),
        jnp.asarray(output.doc_lengths.astype(np.int32)),
        jnp.asarray(output.df),
        n_docs=max(int(output.n_docs), 1), k1=float(cfg.k1), b=float(cfg.b),
    )
    with obs.span("bm25.weights", nnz=int(output.nnz)):
        return rx.device_get(w, site="bm25_weights_pull")
