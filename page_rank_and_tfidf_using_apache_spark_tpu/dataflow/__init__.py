"""dataflow/ — the RDD-analog core under every workload (ISSUE 9).

What made the reference a *framework* rather than two scripts was Spark's
RDD layer: one partitioned-collection abstraction with shuffle,
broadcast-join and iteration, which PageRank and TF-IDF are both thin
programs over.  This package is that layer's TPU-native analog — a small
set of JAX-native primitives with the resilience/elastic/obs machinery
attached ONCE, underneath:

=====================  ====================================================
Spark RDD operation    dataflow primitive
=====================  ====================================================
``partitionBy``        :class:`partition.PartitionedArray` (+ the static
                       plans in ``parallel.pagerank_sharded.plan_partition``
                       and the ``ingest.grow_chunk_cap`` padding policy)
``reduceByKey(op)``    :func:`combine.segment_combine` (add/min/max) and
                       :func:`combine.graph_combine` (the degree-aware
                       SpMV shuffle impls)
``broadcast`` + join   :func:`combine.broadcast_join`
driver ``for`` loop    :func:`fixpoint.iterate` (in-jit scan/while) +
                       :func:`fixpoint.run_segments` (host segments with
                       checkpoints + the elastic degradation ladder)
``textFile`` ingest    :func:`ingest.chunked_ingest` (bounded source →
                       padded device chunks, donated carry, commit points)
=====================  ====================================================

PageRank (single-chip + sharded) and streaming TF-IDF are ported to run
over these primitives with pinned equivalence to the pre-port paths; the
marginal-cost claim is demonstrated by the four workloads that open on
top: batched personalized PageRank (:mod:`ppr`), HITS (:mod:`hits`),
connected components / label propagation (:mod:`components`) and BM25
(:mod:`bm25`, served as an A/B-able second ranker beside TF-IDF).  Every
jit entry point here is registered in ``analysis/registry.py`` so the
tier-2/3 lint gates cover the subsystem from day one.
"""

from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.fixpoint import (
    ElasticResult,
    iterate,
    run_segments,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.ingest import (
    chunked_ingest,
    grow_chunk_cap,
    prefetched,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.combine import (
    broadcast_join,
    graph_combine,
    segment_combine,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.partition import (
    PartitionedArray,
)

__all__ = [
    "ElasticResult",
    "PartitionedArray",
    "broadcast_join",
    "chunked_ingest",
    "graph_combine",
    "grow_chunk_cap",
    "iterate",
    "prefetched",
    "run_segments",
    "segment_combine",
]
