"""Connected components via min-label propagation (ISSUE 9 workload 3).

The SAME SpMV skeleton as PageRank — gather along edges, combine by
destination — with the combine swapped from ``add`` to ``min``
(``dataflow.segment_combine(op="min")``): every node starts labeled with
its own id, each step every node takes the minimum label over itself and
its neighbors along BOTH edge directions (a directed edge list describes
an undirected connectivity question), and the fixpoint is reached when
no label changes.  The converged label of a node is the smallest node id
in its weakly-connected component, so components are exactly the label
classes — pinned against ``networkx.connected_components`` by the oracle
test.

Convergence is data-dependent (≈ the component diameter), so the loop
runs as a tolerance fixpoint: the delta gauge is the COUNT of changed
labels (cast to float for the shared ``iterate`` carry) and ``tol=0.5``
means "stop when nothing moved".  ``bench.py --workloads`` records
``cc_iters_per_sec`` over this runner.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import combine
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import fixpoint as dflow
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import ComponentsConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


def label_step(labels, dg: ops.DeviceGraph, n: int):
    """One min-propagation round over both edge directions.  Empty
    segments come back as the dtype max from ``segment_min``; the outer
    ``minimum`` against the current labels clamps them away."""
    import jax.numpy as jnp

    incoming = combine.segment_combine(
        combine.broadcast_join(labels, dg.src), dg.dst, n,
        op="min", indices_are_sorted=True,
    )
    outgoing = combine.segment_combine(
        combine.broadcast_join(labels, dg.dst), dg.src, n,
        op="min", indices_are_sorted=False,
    )
    return jnp.minimum(labels, jnp.minimum(incoming, outgoing))


def make_components_runner(n: int, cfg: ComponentsConfig):
    """Compile the label-propagation fixpoint: ``run(dg, labels0 [n]
    int32) -> (labels, iters, changed)``, labels donated (argnum 1)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(dg: ops.DeviceGraph, labels0: jax.Array):
        return dflow.iterate(
            lambda lab: label_step(lab, dg, n), labels0,
            iterations=cfg.iterations, tol=cfg.tol,
            delta_fn=lambda new, old: jnp.sum(
                (new != old).astype(jnp.float32)
            ),
        )

    return run


@dataclasses.dataclass(frozen=True)
class ComponentsResult:
    labels: np.ndarray  # int32 [n]: smallest node id in the component
    n_components: int
    iterations: int
    metrics: MetricsRecorder
    # False when the iteration cap ended the run with labels still
    # changing: the component split is then an OVER-segmentation (a long
    # chain needs ~diameter rounds) — callers must not trust
    # n_components without checking this.
    converged: bool = True

    def groups(self) -> list[set[int]]:
        """Components as sets of compacted node indices (oracle-test
        shape, mirroring networkx.connected_components)."""
        out: dict[int, set[int]] = {}
        for i, lab in enumerate(self.labels):
            out.setdefault(int(lab), set()).add(i)
        return list(out.values())


def run_components(
    graph: Graph,
    cfg: ComponentsConfig = ComponentsConfig(),
    *,
    metrics: MetricsRecorder | None = None,
) -> ComponentsResult:
    """Weakly-connected components of the edge list, to fixpoint."""
    metrics = metrics or MetricsRecorder()
    n = graph.n_nodes
    if n == 0:
        return ComponentsResult(np.zeros(0, np.int32), 0, 0, metrics)

    labels, done, last_changed = dflow.run_single_chip_fixpoint(
        cfg, metrics, site_prefix="cc",
        init_state=lambda: np.arange(n, dtype=np.int32),
        make_runner=lambda seg_cfg: make_components_runner(n, seg_cfg),
        build_operands=lambda: (ops.put_graph(graph, "float32"),),
        call=lambda runner, ops_t, ld: runner(ops_t[0], ld),
    )
    # last_changed is the final round's changed-label COUNT: nonzero past
    # the iteration cap means labels were still propagating and the
    # grouping below over-segments long components — surface it loudly.
    converged = last_changed <= cfg.tol
    if not converged:
        metrics.record(event="cc_not_converged", iterations=done,
                       still_changing=int(last_changed))
    n_components = int(np.unique(labels).shape[0])
    metrics.scalar("n_components", n_components)
    return ComponentsResult(labels=labels, n_components=n_components,
                            iterations=done, metrics=metrics,
                            converged=converged)
