"""``segment_combine`` / ``broadcast_join`` — the dataflow shuffle and
broadcast primitives.

Spark correspondence (SURVEY.md L3, BASELINE.json:5):

- ``reduceByKey(op)`` → :func:`segment_combine` — a segmented reduction
  over a keyed flat array.  On sorted keys it is one contiguous
  ``segment_*`` pass (the contract every dst-sorted edge layout in this
  repo maintains); unsorted keys take the scatter path.  ``op`` extends
  past Spark's common ``add`` to ``min``/``max`` — the combine of the
  connected-components / label-propagation workload.
- the per-iteration SpMV shuffle → :func:`graph_combine` — routes one
  degree-weighted gather + combine through the *existing* SpMV impls
  (segment / cumsum / cumsum_mxu / hybrid / sort_shuffle / pallas) and
  their static degree-aware layouts, so every fixpoint workload shares
  one tuned shuffle implementation instead of re-owning scatter
  strategy.
- ``broadcast(table)`` + map-side join → :func:`broadcast_join` — a
  device-resident gather of a replicated table (Spark's torrent
  broadcast is a sharding annotation here; the join is the gather).
"""

from __future__ import annotations

import jax


def segment_combine(
    values: jax.Array,
    keys: jax.Array,
    num_segments: int,
    *,
    op: str = "add",
    indices_are_sorted: bool = False,
) -> jax.Array:
    """``reduceByKey``: combine ``values`` by ``keys`` into
    ``num_segments`` slots.  Empty segments yield the op's identity for
    ``add`` (0) and the dtype's extreme for ``min``/``max`` (callers that
    need a different fill combine against their own initial state — see
    ``dataflow.components``)."""
    fns = {
        "add": jax.ops.segment_sum,
        "min": jax.ops.segment_min,
        "max": jax.ops.segment_max,
    }
    if op not in fns:
        raise ValueError(f"unknown combine op {op!r} (want add/min/max)")
    return fns[op](
        values, keys, num_segments=num_segments,
        indices_are_sorted=indices_are_sorted,
    )


def graph_combine(dg, weighted: jax.Array, n: int, impl: str = "segment") -> jax.Array:
    """The graph-shuffle form of :func:`segment_combine`:
    ``out[v] = Σ_{(u,v)∈E} weighted[u]`` through whichever SpMV impl (and
    static layout) the :class:`~..ops.pagerank.DeviceGraph` was built for.
    This is the hot per-iteration ``join → flatMap → reduceByKey`` chain
    of BASELINE.json:5 behind ONE dispatch point — PageRank, personalized
    PageRank and HITS's authority pass all route here."""
    # ops.pagerank owns the impl table (and imports dataflow.fixpoint);
    # resolve lazily to keep the package import DAG acyclic.
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops

    return ops.spmv(dg, weighted, n, impl)


def broadcast_join(table: jax.Array, keys: jax.Array) -> jax.Array:
    """Map-side join against a broadcast table: ``out[i] =
    table[keys[i]]``.  The reference's ``tf.join(idf)`` (a shuffle in
    Spark) and the per-edge rank lookup ``ranks[src]`` are both this one
    gather; on a mesh the table rides replicated, which IS the broadcast."""
    return table[keys]
