"""HITS (hubs & authorities) — two interleaved SpMV fixpoints with norm
steps (ISSUE 9 workload 2; Kleinberg's algorithm, networkx-parity
semantics).

Per iteration, mirroring ``networkx.hits`` exactly so the oracle test
can pin values, not just ordering:

1. ``auth[v] = Σ_{(u,v)∈E} hub[u]`` — the forward SpMV, the SAME
   dst-sorted segment combine PageRank's contribution pass uses;
2. ``auth /= max(auth)``;
3. ``hub[u] = Σ_{(u,v)∈E} auth[v]`` — the *reverse* SpMV, a
   ``dataflow.segment_combine`` over the src axis (unsorted scatter-add:
   the edge array is dst-sorted, and HITS is the first workload that
   reduces along the other axis);
4. ``hub /= max(hub)``;
5. converge on the L1 delta of the hub vector; final sum-normalization
   of both vectors.

Both vectors ride one ``[2, n]`` carry through a single
:func:`dataflow.fixpoint.iterate` loop (donated, same contract as the
PageRank runners), and the host side is the shared segment driver —
checkpoints, retry and CPU degradation included, zero new wiring.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import combine
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import fixpoint as dflow
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.utils import config
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import HitsConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


def hits_step(ha, dg: ops.DeviceGraph, n: int):
    """One networkx-parity HITS iteration over the ``[2, n]`` carry
    (row 0 = hubs, row 1 = authorities).  Edge weights (when the graph
    carries them — networkx weighted-HITS semantics) scale each edge's
    contribution in BOTH directions; the same dst-sorted weight array
    serves both, since each combine walks the same edge set."""
    import jax.numpy as jnp

    hub = ha[0]
    per_fwd = combine.broadcast_join(hub, dg.src)
    if dg.edge_weight is not None:
        per_fwd = per_fwd * dg.edge_weight
    auth = combine.segment_combine(
        per_fwd, dg.dst, n, op="add", indices_are_sorted=True,
    )
    auth = auth / jnp.maximum(jnp.max(auth), 1e-30)
    per_rev = combine.broadcast_join(auth, dg.dst)
    if dg.edge_weight is not None:
        per_rev = per_rev * dg.edge_weight
    new_hub = combine.segment_combine(
        per_rev, dg.src, n, op="add", indices_are_sorted=False,
    )
    new_hub = new_hub / jnp.maximum(jnp.max(new_hub), 1e-30)
    return jnp.stack([new_hub, auth])


def make_hits_runner(n: int, cfg: HitsConfig):
    """Compile the HITS fixpoint: ``run(dg, ha0 [2, n]) -> (ha, iters,
    delta)`` with the carry donated (argnum 1) and convergence on the hub
    vector's L1 delta (networkx's ``err`` gauge)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(dg: ops.DeviceGraph, ha0: jax.Array):
        return dflow.iterate(
            lambda ha: hits_step(ha, dg, n), ha0,
            iterations=cfg.iterations, tol=cfg.tol,
            delta_fn=lambda new, old: jnp.sum(jnp.abs(new[0] - old[0])),
        )

    return run


@dataclasses.dataclass(frozen=True)
class HitsResult:
    hubs: np.ndarray  # f[n], sum-normalized
    authorities: np.ndarray  # f[n], sum-normalized
    iterations: int
    l1_delta: float
    metrics: MetricsRecorder


def run_hits(
    graph: Graph,
    cfg: HitsConfig = HitsConfig(),
    *,
    metrics: MetricsRecorder | None = None,
) -> HitsResult:
    """Run HITS to convergence on the default device.  All host-loop
    machinery (segments, checkpoints of the [2, n] carry, retry + CPU
    rung) comes from the shared dataflow fixpoint driver."""
    config.ensure_dtype_support(cfg.dtype)
    metrics = metrics or MetricsRecorder()
    n = graph.n_nodes
    if n == 0:
        z = np.zeros(0, cfg.dtype)
        return HitsResult(z, z, 0, 0.0, metrics)

    ha, done, last_delta = dflow.run_single_chip_fixpoint(
        cfg, metrics, site_prefix="hits",
        init_state=lambda: np.full((2, n), 1.0 / n, cfg.dtype),
        make_runner=lambda seg_cfg: make_hits_runner(n, seg_cfg),
        build_operands=lambda: (ops.put_graph(graph, cfg.dtype),),
        call=lambda runner, ops_t, hd: runner(ops_t[0], hd),
    )
    hubs, auths = ha[0], ha[1]
    hs, as_ = float(hubs.sum()), float(auths.sum())
    hubs = hubs / hs if hs > 0 else hubs
    auths = auths / as_ if as_ > 0 else auths
    return HitsResult(hubs=hubs, authorities=auths, iterations=done,
                      l1_delta=last_delta, metrics=metrics)
